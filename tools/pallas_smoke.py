#!/usr/bin/env python
"""The `make smoke` pallas-interpret leg: kernel-vs-lax bitwise identity.

Runs one raft config — the canonical bug config, plus an
overflow-mid-batch variant — through the lax step path and the fused
Pallas step kernel (``EngineConfig(pallas=True)``, interpret mode on
CPU) and demands bit-identical final state on EVERY leaf. This is the
executable form of the kernel's one contract (docs/perf.md "Roofline
round 2"): the kernel body *is* the step function, so any divergence
means the Pallas plumbing (const hoisting, aliasing, block specs)
corrupted state. Nonzero exit on any mismatch.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def main() -> int:
    import dataclasses

    import jax
    import numpy as np

    from madsim_tpu.engine import (DeviceEngine, EngineConfig, RaftActor,
                                   RaftDeviceConfig)

    configs = [
        ("raft_bug", RaftDeviceConfig(n=3, buggy_double_vote=True),
         EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                      t_limit_us=1_000_000, stop_on_bug=False)),
        ("raft_overflow", RaftDeviceConfig(n=3, n_proposals=2),
         EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=8,
                      t_limit_us=1_000_000, stop_on_bug=False)),
    ]
    seeds = np.arange(8)
    failures = 0
    for name, rcfg, cfg in configs:
        lax_eng = DeviceEngine(RaftActor(rcfg), cfg)
        pls_eng = DeviceEngine(RaftActor(rcfg),
                               dataclasses.replace(cfg, pallas=True))
        s_lax = lax_eng.run(lax_eng.init(seeds), max_steps=1_500)
        s_pls = pls_eng.run(pls_eng.init(seeds), max_steps=1_500)
        paths = [jax.tree_util.keystr(p) for p, _
                 in jax.tree_util.tree_flatten_with_path(s_lax)[0]]
        mismatched = [
            pth for pth, a, b in zip(paths, jax.tree.leaves(s_lax),
                                     jax.tree.leaves(s_pls))
            if not np.array_equal(np.asarray(a), np.asarray(b))]
        obs = lax_eng.observe(s_lax)
        extra = ""
        if name == "raft_overflow" and not obs["overflow"].any():
            mismatched.append("<config failed to overflow — the "
                              "overflow path went unexercised>")
        if mismatched:
            failures += 1
            print(f"pallas_smoke: {name} DIVERGED on {mismatched}",
                  file=sys.stderr)
        else:
            interest = ("bug" if name == "raft_bug" else "overflow")
            extra = f", {interest}={int(obs[interest].sum())}/{len(seeds)}"
            print(f"pallas_smoke: {name} bitwise identical "
                  f"(kernel == lax, {len(paths)} leaves{extra})")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
