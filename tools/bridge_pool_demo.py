#!/usr/bin/env python
"""Bridge worker-pool demo: the jobs=J bitwise contract, end to end.

The `make bridge-pool-demo` CI gate (docs/bridge.md "Parallel task
bodies"; ROADMAP item 4):

1. Sweep a mixed-outcome suite (values, raised errors, deadlocks, a
   time limit, lossy RPC send accounting) through the bridge THREE
   ways — serial in-process, pooled jobs=1, pooled jobs=2 (uneven
   W % J split) — and assert per-seed poll traces, outcomes, and error
   attribution are BITWISE identical, with and without batch recycling.
2. Crash leg: SIGKILL one worker mid-round and assert the parent raises
   a pointed BridgePoolError naming the worker/slot-range/round, exits
   cleanly (no hang), and unlinks every shared-memory segment.

Nonzero exit on any miss.
"""
import glob
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu as ms  # noqa: E402
from madsim_tpu import time as vtime  # noqa: E402
from madsim_tpu.bridge import sweep_traced  # noqa: E402
from madsim_tpu.bridge.pool import BridgePoolError, sweep_pooled  # noqa: E402
from madsim_tpu.core.task import Deadlock  # noqa: E402
from madsim_tpu.net import Endpoint, NetSim, rpc  # noqa: E402

SEEDS = list(range(10))


class Ping:
    __slots__ = ("n",)

    def __init__(self, n):
        self.n = n


async def _await(f):
    return await f


async def world(seed):
    """Mixed-outcome world: seeds 0/5 deadlock-adjacent sleeps, 3/7
    raise, the rest run a lossy RPC exchange and return (sum, sends)."""
    if seed % 5 == 0:
        await vtime.sleep(0.2)
        await _await(ms.sync.SimFuture())  # deadlock: nothing resolves it
    if seed % 4 == 3:
        await vtime.sleep(0.1 * (seed % 3 + 1))
        raise ValueError(f"boom {seed}")
    h = ms.Handle.current()

    async def server_init():
        ep = await Endpoint.bind("10.0.0.1:9000")

        async def handle(req):
            return req.n * 2

        rpc.add_rpc_handler(ep, Ping, handle)
        await vtime.sleep(1e6)

    h.create_node(name="server", ip="10.0.0.1", init=server_init)
    client = h.create_node(name="client", ip="10.0.0.2")
    done = ms.sync.SimFuture()

    async def client_body():
        ep = await Endpoint.bind("10.0.0.2:0")
        got = 0
        for i in range(4):
            while True:
                try:
                    got += await rpc.call(ep, "10.0.0.1:9000", Ping(i),
                                          timeout=0.3)
                    break
                except TimeoutError:
                    pass
        done.set_result(got)

    client.spawn(client_body())
    got = await vtime.timeout(600, _await(done))
    return got, ms.simulator(NetSim).network.stat.msg_count


def lossy():
    c = ms.Config()
    c.net.packet_loss_rate = 0.12
    return c


def key(outs):
    return [(o.seed, o.value, type(o.error).__name__ if o.error else None,
             str(o.error) if o.error else None) for o in outs]


def main() -> int:
    print("== bridge pool demo: jobs=J bitwise == jobs=1 == serial ==")
    serial, tr_serial = sweep_traced(world, SEEDS, config=lossy())
    n_deadlocks = sum(isinstance(o.error, Deadlock) for o in serial)
    n_raises = sum(isinstance(o.error, ValueError) for o in serial)
    assert n_deadlocks and n_raises and any(o.value for o in serial), \
        "suite is not mixed-outcome — demo would prove nothing"
    for batch in (None, 3):
        for jobs in (1, 2):
            outs, trs = sweep_pooled(world, SEEDS, jobs=jobs, trace=True,
                                     config=lossy(), batch=batch)
            assert trs == tr_serial, \
                f"traces diverged at jobs={jobs} batch={batch}"
            assert key(outs) == key(serial), \
                f"outcomes diverged at jobs={jobs} batch={batch}"
            print(f"  jobs={jobs} batch={batch}: {len(SEEDS)} seeds "
                  f"bitwise ok ({n_deadlocks} deadlocks, {n_raises} raises)")

    print("== crash leg: SIGKILL a worker mid-round ==")
    parent = os.getpid()

    async def crasher(seed):
        await vtime.sleep(0.1)
        if seed == 7 and os.getpid() != parent:
            os.kill(os.getpid(), signal.SIGKILL)
        return seed

    try:
        sweep_pooled(crasher, SEEDS, jobs=2)
        print("FAIL: worker crash did not raise BridgePoolError")
        return 1
    except BridgePoolError as exc:
        assert exc.worker == 1 and exc.slots == (5, 10), exc
        assert exc.round_no is not None, exc
        assert "worker 1" in str(exc) and "slots 5..9" in str(exc), exc
        print(f"  pointed error ok: {exc}")
    if os.path.isdir("/dev/shm"):
        leftover = glob.glob("/dev/shm/msbp-*")
        assert not leftover, f"orphaned shared-memory segments: {leftover}"
        print("  no orphaned shared-memory segments")
    print("BRIDGE POOL DEMO OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
