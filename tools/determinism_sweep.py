"""Determinism-gate workload for `make determinism` (CI harness).

Runs the canonical 2-node RPC ping-pong under chaos (restart + partition +
packet loss) across a seed sweep with the determinism checker on: each seed
executes twice with RNG-access log/replay and fails on the first divergent
access (`madsim/src/sim/runtime/mod.rs:164-189` analog). Driven by the same
MADSIM_TEST_* env vars as the reference (builder.rs:55-107); the Makefile
sets MADSIM_TEST_NUM/SEED/CHECK_DETERMINISM.
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, NetSim, rpc
from madsim_tpu import time as simtime


@dataclasses.dataclass
class Ping:
    x: int


# Defaults chosen for CI speed; env vars override (Builder.from_env wins
# for anything the decorator doesn't pin).
os.environ.setdefault("MADSIM_TEST_NUM", "8")
os.environ.setdefault("MADSIM_TEST_SEED", "0")
os.environ.setdefault("MADSIM_TEST_CHECK_DETERMINISM", "1")


_CFG = ms.Config()
_CFG.net.packet_loss_rate = 0.05  # the chaos must include the loss RNG path


@ms.test(time_limit=120.0, config=_CFG)
async def chaos_pingpong():
    cfg_h = ms.Handle.current()

    async def server_init():
        ep = await Endpoint.bind("10.0.0.1:700")

        async def ping(req):
            return Ping(req.x + 1)

        rpc.add_rpc_handler(ep, Ping, ping)
        await simtime.sleep(3600)

    srv = cfg_h.create_node(name="srv", ip="10.0.0.1", init=server_init)
    cli = cfg_h.create_node(name="cli", ip="10.0.0.2")
    done = ms.sync.SimFuture()

    async def client():
        ep = await Endpoint.bind("10.0.0.2:0")
        got = 0
        for i in range(30):
            try:
                r = await rpc.call(ep, "10.0.0.1:700", Ping(i), timeout=1.0)
                assert r.x == i + 1
                got += 1
            except TimeoutError:
                pass
        done.set_result(got)

    cli.spawn(client())
    sim = ms.simulator(NetSim)
    await simtime.sleep(0.8)
    sim.disconnect(srv.id)
    await simtime.sleep(0.5)
    sim.connect(srv.id)
    await simtime.sleep(0.3)
    cfg_h.restart(srv.id)
    got = await done
    assert got > 0, "no progress under chaos"
    return got


if __name__ == "__main__":
    got = chaos_pingpong()
    n = os.environ["MADSIM_TEST_NUM"]
    print(f"determinism sweep OK: {n} seeds x2 runs, last got={got}")
