#!/usr/bin/env python
"""Regenerate (or verify) the tracelint cost-budget ledger.

Usage::

    python tools/update_budgets.py --reason "why the budgets moved"
    python tools/update_budgets.py --check          # the CI/make gate
    python tools/update_budgets.py --check --json

Regeneration re-measures every budget-tracked hot-path program (fresh
compiles — the persistent cache strips cost/alias statistics) and
rewrites ``madsim_tpu/analysis/budgets.json``. Budgets RATCHET: an
existing ceiling survives while the fresh measurement still fits under
it; raising one requires the ``--reason`` line, which is recorded in the
ledger so every budget bump carries its justification in-tree.

``--check`` runs the full tracelint gate instead (trace rules + ledger
diff) — exactly what ``make tracelint`` executes — and exits nonzero on
any finding. CI uses this mode.

Regeneration REFUSES to run while the target ledger has uncommitted
modifications in git: regeneration rewrites the whole file, so a
concurrent hand edit (another branch's budget bump mid-review, a
``--reason`` line being drafted) would be silently clobbered. Commit or
stash the ledger first, or pass ``--force`` to overwrite deliberately.
"""
import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def ledger_dirty(path: str) -> bool:
    """True iff ``path`` is a git-tracked file with uncommitted
    modifications (staged or not). Untracked files and non-repo paths
    return False: there is no committed baseline to clobber there."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain", "--", os.path.abspath(path)],
            cwd=directory, capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return False
    if out.returncode != 0:
        return False
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    return any(not ln.startswith("??") for ln in lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="verify instead of regenerate: run the full "
                         "tracelint gate (rules + ledger diff)")
    ap.add_argument("--reason", default=None,
                    help="justification recorded in the ledger "
                         "(required to regenerate)")
    ap.add_argument("--budgets", default=None, metavar="PATH",
                    help="ledger path (default: the in-package "
                         "analysis/budgets.json)")
    ap.add_argument("--json", action="store_true",
                    help="with --check: machine-readable findings")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text")
    ap.add_argument("--force", action="store_true",
                    help="regenerate even over uncommitted ledger edits "
                         "(they WILL be overwritten)")
    args = ap.parse_args(argv)

    if args.check:
        from madsim_tpu.analysis.cli import main_trace

        trace_args = []
        if args.budgets:
            trace_args += ["--budgets", args.budgets]
        if args.json:
            trace_args += ["--json"]
        elif args.format != "text":
            trace_args += ["--format", args.format]
        return main_trace(trace_args)

    if not args.reason:
        print("update_budgets: regenerating the ledger requires "
              "--reason '...' (recorded as the justification line); "
              "use --check to verify instead", file=sys.stderr)
        return 2

    from madsim_tpu.analysis import budgets as B

    # Refuse to clobber uncommitted ledger edits — BEFORE any (slow)
    # measurement, so the refusal is instant and nothing is half-done.
    guard_path = args.budgets or B.DEFAULT_LEDGER
    if not args.force and ledger_dirty(guard_path):
        print(f"update_budgets: {guard_path} has uncommitted "
              "modifications; regeneration rewrites the whole file and "
              "would silently clobber them. Commit/stash the ledger "
              "first, or pass --force to overwrite.", file=sys.stderr)
        return 2

    from madsim_tpu.analysis.tracelint import (measure_program, registry)

    path = guard_path
    try:
        prev = B.load_ledger(path).get("programs", {})
    except (FileNotFoundError, ValueError):
        prev = {}

    entries = {}
    for name, prog in sorted(registry().items()):
        if not prog.budget:
            continue
        print(f"measuring {name} (fresh compile)...", file=sys.stderr)
        m = measure_program(name, prog)
        entries[name] = B.make_entry(m, prog.title, prev.get(name))
        for metric in B.CEILING_METRICS:
            if metric in entries[name]:
                e = entries[name][metric]
                moved = (prev.get(name, {}).get(metric, {}).get("budget")
                         != e["budget"])
                print(f"  {metric:18s} measured {e['measured']:>14} "
                      f"budget {e['budget']:>14}"
                      f"{'  (budget moved)' if moved else ''}",
                      file=sys.stderr)
        af = entries[name]["alias_fraction"]
        print(f"  {'alias_fraction':18s} measured {af['measured']:>14} "
              f"min {af['min']:>14}", file=sys.stderr)
    out = B.write_ledger(entries, args.reason, path)
    print(f"update_budgets: wrote {len(entries)} program entries to {out}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
