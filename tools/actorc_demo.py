#!/usr/bin/env python
"""End-to-end actor-compiler demo: spec → compile → host-twin
crosscheck → guided Paxos hunt → triage → CLI replay.

The `make actorc-demo` target (docs/actorc.md) — the acceptance gate of
ROADMAP item 3. Exits nonzero on any miss.

1. COMPILE: the multi-decree Paxos spec (actorc/families/paxos.py), the
   first DSL-only family — packed lanes, widen/narrow boundaries and
   the single-outbox assembly all placed by the compiler.
2. CROSSCHECK: the generated plain-Python host twin must agree with the
   compiled device actor on every per-event state lane, outbox row and
   bug decision over real (faulted) trajectories — the conformance
   oracle (actorc/conformance.py).
3. HUNT: `sweep(recycle=True, search=...)` over the forgetful-acceptor
   consistency violation (one flipped `durable` annotation): guided
   must reach the bug in strictly fewer seeds than the matched
   random-mutation baseline.
4. TRIAGE: the find pipes unchanged through `triage.triage` to a
   verified 1-minimal repro bundle, which must replay through
   `python -m madsim_tpu.obs replay` in a fresh process.
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET = 512

# Pinned hunt numbers (the PR 11 retune-and-re-pin rule, see
# tools/fuzz_demo.py): bitwise-deterministic, so drift WITHOUT a
# deliberate mutation/spec change means search or compiler semantics
# regressed silently.
PIN_PAXOS_GUIDED = 191   # guided seeds-to-bug
PIN_PAXOS_RANDOM = None  # random: not found inside the budget


def main() -> int:
    import numpy as np

    from madsim_tpu.actorc import crosscheck
    from madsim_tpu.actorc.families.paxos import (PaxosConfig,
                                                  engine_config,
                                                  paxos_spec)
    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.engine.core import FAULT_RESTART
    from madsim_tpu.parallel.sweep import sweep
    from madsim_tpu.search.hunts import paxos_hunt
    from madsim_tpu.triage import triage

    # -- 1+2: compile + host-twin conformance --------------------------
    bcfg = PaxosConfig(buggy_forgetful_acceptor=True, contend_all=True)
    # A schedule that exercises the interesting paths: an in-window
    # restart (amnesia + possible violation) and a late benign one.
    faults = np.array([[80_000, FAULT_RESTART, 2, 0],
                       [600_000, FAULT_RESTART, 0, 0]], np.int32)
    rep = crosscheck(paxos_spec(bcfg), engine_config(bcfg),
                     seeds=[0, 1, 2, 5], faults=faults, max_steps=350)
    print(f"actorc-demo: host twin agreed with the compiled actor on "
          f"{rep['steps_checked']} steps "
          f"({rep['events_delivered']} delivered events, "
          f"{rep['restarts']} restarts) across {rep['n_seeds']} seeds",
          file=sys.stderr)

    # -- 3: the guided hunt --------------------------------------------
    hunt = paxos_hunt()
    eng = DeviceEngine(hunt.actor, hunt.cfg)

    def run(guided):
        return sweep(None, hunt.cfg, np.arange(BUDGET), engine=eng,
                     faults=hunt.template, stop_on_first_bug=True,
                     search=hunt.search(guided), **hunt.sweep_kw)

    g = run(True)
    r = run(False)
    g_seeds = (g.failing_seeds[0] + 1) if g.failing_seeds else None
    r_seeds = (r.failing_seeds[0] + 1) if r.failing_seeds else None
    print(f"actorc-demo: paxos forgetful-acceptor @ {BUDGET} seeds: "
          f"guided found the consistency violation at seed {g_seeds}, "
          f"random at {r_seeds if r_seeds else f'>{BUDGET} (not found)'}",
          file=sys.stderr)
    if g_seeds is None:
        print("actorc-demo: guided search missed the Paxos bug in budget",
              file=sys.stderr)
        return 1
    if r_seeds is not None and g_seeds >= r_seeds:
        print(f"actorc-demo: guided ({g_seeds}) did not beat random "
              f"({r_seeds})", file=sys.stderr)
        return 1
    if (g_seeds, r_seeds) != (PIN_PAXOS_GUIDED, PIN_PAXOS_RANDOM):
        print(f"actorc-demo: paxos seeds-to-bug drifted off the pinned "
              f"numbers: got guided={g_seeds} random={r_seeds}, pinned "
              f"{PIN_PAXOS_GUIDED}/{PIN_PAXOS_RANDOM}. If mutation, "
              f"spec, or compiler code changed deliberately, retune and "
              f"re-pin; otherwise semantics regressed.", file=sys.stderr)
        return 1

    # -- 4: triage to a 1-minimal replayable bundle --------------------
    with tempfile.TemporaryDirectory() as td:
        report = triage(g, out_dir=td, chunk_steps=32, max_steps=20_000)
        print(report.summary(), file=sys.stderr)
        if len(report.classes) != 1:
            print(f"actorc-demo: expected ONE failure class, got "
                  f"{len(report.classes)}", file=sys.stderr)
            return 1
        key = report.classes[0].key
        mr = report.minimized[key]
        if not mr.one_minimal:
            print(f"actorc-demo: minimizer did not reach a verified "
                  f"1-minimal fixpoint: {mr.summary()}", file=sys.stderr)
            return 1
        bundle_path = report.bundles[key]
        with open(bundle_path, encoding="utf-8") as f:
            bundle = json.load(f)
        if bundle.get("actor") != "paxos":
            print(f"actorc-demo: bundle names actor "
                  f"{bundle.get('actor')!r}, want 'paxos' (registry "
                  "entry missing?)", file=sys.stderr)
            return 1
        lin = bundle.get("lineage") or {}
        if lin.get("schema") != "madsim.search.lineage/1" or \
                not lin.get("operators_applied"):
            print(f"actorc-demo: bundle lineage block missing/"
                  f"incomplete: {lin.keys()}", file=sys.stderr)
            return 1
        trace_path = os.path.join(td, "trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "madsim_tpu.obs", "replay",
             "--bundle", bundle_path, "--out", trace_path],
            env={**os.environ}, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(f"actorc-demo: CLI replay of the minimized bundle "
                  f"failed rc={proc.returncode}", file=sys.stderr)
            return 1
        block = bundle.get("minimization") or {}
        print(f"actorc-demo: guided find minimized "
              f"{block.get('original_rows')} -> "
              f"{block.get('final_rows')} rows in "
              f"{block.get('rounds')} rounds and replayed",
              file=sys.stderr)

    print(f"actorc-demo ok: compiled Paxos crosschecked against its "
          f"generated host twin; guided found the consistency violation "
          f"at seed {g_seeds} vs "
          f"{r_seeds if r_seeds else f'>{BUDGET}'} random; 1-minimal "
          f"bundle replayed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
