#!/usr/bin/env python
"""detlint entry point: nondeterminism-escape + sim/real-parity linter.

Equivalent to ``python -m madsim_tpu.analysis``; this wrapper works from
any cwd by anchoring --root at the repo it lives in.
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from madsim_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    # The `trace` subcommand (pass 3) has no --root; hand it through.
    if argv[:1] != ["trace"] and "--root" not in argv:
        argv = ["--root", _REPO] + argv
    sys.exit(main(argv))
