#!/usr/bin/env python
"""End-to-end repro-bundle demo: sweep → bundle → CLI replay → timeline.

The `make replay-demo` target (docs/observability.md "The repro-bundle
workflow"). Exercises the whole failure-observability loop on a known
buggy config:

1. sweep the double-vote Raft bug over a small seed batch
   (metrics-on, flight recorder aboard — the per-seed frames and the
   failing world's decoded black-box ring are printed);
2. write a device-sweep repro bundle for the first failing seed
   carrying the ``madsim.blackbox/1`` block (obs/bundle.py,
   obs/blackbox.py);
3. replay it with ``python -m madsim_tpu.obs replay --bundle
   --crosscheck`` in a fresh process (the CLI contract, not the
   in-process library) — the crosscheck verifies the recorded ring is
   bitwise the suffix of the replayed trace;
4. validate the exported Chrome trace-event JSON: parseable, non-empty,
   and its final event is the invariant raise;
5. tamper with one recorded ring event and assert the crosscheck now
   exits nonzero — divergence must be loud, not a warning.

Exits nonzero on any failed expectation.
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BLACKBOX_K = 16


def main() -> int:
    import numpy as np

    from madsim_tpu.engine import (DeviceEngine, EngineConfig, RaftActor,
                                   RaftDeviceConfig)
    from madsim_tpu.obs.blackbox import blackbox_block
    from madsim_tpu.obs.bundle import write_sweep_bundle
    from madsim_tpu.parallel.sweep import sweep

    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000, metrics=True,
                       blackbox=BLACKBOX_K)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    res = sweep(None, cfg, np.arange(256), engine=eng, chunk_steps=64,
                max_steps=4_000)
    if not res.failing_seeds:
        print("replay-demo: the buggy config found no failing seed in "
              "256 worlds — the injected bug is gone?", file=sys.stderr)
        return 1
    seed = res.failing_seeds[0]
    print(res.repro_banner(), file=sys.stderr)
    frames = res.metrics["per_seed"]
    row = int(np.argmax(np.asarray(res.seeds) == seed))
    print(f"replay-demo: failing seed {seed} metrics: "
          + ", ".join(f"{k}={int(np.asarray(v)[row])}"
                      for k, v in sorted(frames.items())
                      if np.asarray(v).ndim == 1), file=sys.stderr)
    ring = res.blackbox(seed)
    print(f"replay-demo: failing seed {seed} black box "
          f"(last {len(ring)} events):", file=sys.stderr)
    for e in ring:
        print(f"  step {e['step']:>4}  t={e['t_us']:>8} µs  {e['kind']}"
              + (" *** RAISE ***" if e.get("bug_raised") else ""),
              file=sys.stderr)
    if not ring or not ring[-1].get("bug_raised"):
        print("replay-demo: the failing world's ring does not end at the "
              "invariant raise", file=sys.stderr)
        return 1

    with tempfile.TemporaryDirectory() as td:
        block = blackbox_block(
            ring, seed=seed, k=BLACKBOX_K,
            pos=int(np.asarray(res.observations["bb_pos"])[row]),
            steps=int(np.asarray(res.observations["steps"])[row]),
            faults=None)
        bundle_path = write_sweep_bundle(
            td, seed=seed, actor="raft", actor_config=rcfg,
            engine_config=cfg, max_steps=4_000,
            error="RaftInvariantViolation: double vote",
            extra={"blackbox": block})
        trace_path = os.path.join(td, "trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "madsim_tpu.obs", "replay",
             "--bundle", bundle_path, "--crosscheck", "--out", trace_path],
            env={**os.environ}, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(f"replay-demo: CLI replay failed rc={proc.returncode}",
                  file=sys.stderr)
            return 1
        with open(trace_path) as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        assert events, "empty trace"
        assert doc["otherData"]["clock"] == "virtual_us", doc["otherData"]
        final = events[-1]
        if final["name"] != "invariant:raise":
            print(f"replay-demo: final trace event is {final!r}, expected "
                  "the invariant raise", file=sys.stderr)
            return 1
        print(f"replay-demo ok: seed {seed} replayed, {len(events)} trace "
              f"events, invariant raise at t={final['ts']:.0f} µs, ring "
              "crosschecked bitwise")

        # Divergence leg: corrupt one recorded event, re-run the
        # crosscheck, demand a loud nonzero exit.
        with open(bundle_path) as f:
            bundle = json.load(f)
        bundle["extra"]["blackbox"]["events"][-1]["t_us"] += 1
        with open(bundle_path, "w") as f:
            json.dump(bundle, f)
        proc = subprocess.run(
            [sys.executable, "-m", "madsim_tpu.obs", "replay",
             "--bundle", bundle_path, "--crosscheck",
             "--out", os.path.join(td, "trace2.json")],
            env={**os.environ}, capture_output=True, text=True)
        if proc.returncode != 1:
            print(f"replay-demo: tampered ring crosscheck exited "
                  f"rc={proc.returncode}, expected 1 (divergence must be "
                  "loud)", file=sys.stderr)
            sys.stderr.write(proc.stderr)
            return 1
        print("replay-demo ok: tampered ring detected "
              "(crosscheck exit 1)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
