#!/usr/bin/env python
"""bench_diff — regression table between two bench rounds.

Compares the metrics that gate this repo's performance story — headline
seeds/s, per-config seeds/s and world utilization, the XLA cost model
(flops/bytes per world-step, peak-over-state), the sweep loop's stall
profile (host share of loop wall, superstep fan-in), bridge throughput,
and behavior coverage — between two bench artifacts, and prints an
aligned table with per-metric deltas and regression markers.

Accepted inputs (auto-detected per file):

- ``bench_results.json`` — the raw result ``bench.py`` writes;
- ``BENCH_r*.json`` — the driver wrapper ``{n, cmd, rc, tail, parsed}``
  (``parsed`` may be null when the run's stdout was truncated; the last
  JSON line of ``tail`` is tried as a fallback).

Usage::

    python tools/bench_diff.py OLD.json NEW.json [--fail-on-regress PCT]
    python tools/bench_diff.py --auto     # newest round vs bench_results

``--fail-on-regress PCT`` exits 1 when any tracked metric moves against
its better-direction by more than PCT percent — the CI hook (`make
bench-diff` runs after `make smoke` whenever a previous round artifact
exists). Without it the tool always exits 0 on a successful comparison:
the table is the product.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (label, dotted path into the result dict, higher_is_better).
# Paths resolve leniently: a missing leg renders "-" instead of failing,
# so old rounds without newer fields still diff cleanly.
METRICS: List[Tuple[str, str, bool]] = [
    ("headline seeds/s", "value", True),
    ("headline vs_baseline", "vs_baseline", True),
    ("5node seeds/s", "configs.madraft_5node.seeds_per_sec", True),
    ("5node utilization", "configs.madraft_5node.world_utilization", True),
    ("5node flops/world-step",
     "configs.madraft_5node.xla_cost.flops_per_world_step", False),
    ("5node bytes/step",
     "configs.madraft_5node.xla_cost.bytes_accessed_per_step", False),
    ("5node state bytes/world",
     "configs.madraft_5node.xla_cost.state_bytes_per_world", False),
    ("5node peak/state",
     "configs.madraft_5node.xla_cost.peak_over_state", False),
    ("5node chunks/dispatch",
     "configs.madraft_5node.sweep_loop.chunks_per_dispatch", True),
    ("5node host stall s",
     "configs.madraft_5node.sweep_loop.host_decision_s", False),
    ("5node loop wall s",
     "configs.madraft_5node.sweep_loop.loop_wall_s", False),
    ("5node distinct behaviors",
     "configs.madraft_5node.coverage.distinct_behaviors", True),
    ("ttfb device seeds/s",
     "configs.time_to_first_bug.device_seeds_per_sec", True),
    ("ttfb flops/world-step",
     "configs.time_to_first_bug.xla_cost.flops_per_world_step", False),
    ("ttfb state bytes/world",
     "configs.time_to_first_bug.xla_cost.state_bytes_per_world", False),
    ("ttfb peak/state",
     "configs.time_to_first_bug.xla_cost.peak_over_state", False),
    ("ttfb hunt utilization",
     "configs.time_to_first_bug.recycled_hunt.world_utilization", True),
    ("ttfb chunks/dispatch",
     "configs.time_to_first_bug.sweep_loop.chunks_per_dispatch", True),
    # Whole-hunt residency (docs/perf.md): dispatch economics of the
    # pinned recycled hunt, pipelined vs fused — the fused row must hold
    # the >=4x seeds-per-dispatch advantage, and epochs_on_device counts
    # the refill epochs the host no longer orchestrates.
    ("ttfb seeds/dispatch",
     "configs.time_to_first_bug.sweep_loop.seeds_per_dispatch", True),
    ("ttfb fused seeds/dispatch",
     "configs.time_to_first_bug.sweep_loop_fused.seeds_per_dispatch",
     True),
    ("ttfb fused epochs on device",
     "configs.time_to_first_bug.sweep_loop_fused.epochs_on_device",
     True),
    ("ttfb fused dispatch reduction",
     "configs.time_to_first_bug.recycled_hunt.fused_dispatch_reduction",
     True),
    # Flight-recorder pricing (docs/observability.md "The flight
    # recorder"): the K=64 ring's on-vs-off deltas — state bytes added
    # per world, ring-write flops, and the seeds/s tax (ratio, higher is
    # cheaper). The off legs stay the exact pre-blackbox program.
    ("ttfb blackbox state B/world +",
     "configs.time_to_first_bug.blackbox.state_bytes_per_world_delta",
     False),
    ("ttfb blackbox flops/world-step +",
     "configs.time_to_first_bug.blackbox.flops_per_world_step_delta",
     False),
    ("ttfb blackbox seeds/s ratio",
     "configs.time_to_first_bug.blackbox.seeds_per_sec_ratio", True),
    ("5node seeds/dispatch",
     "configs.madraft_5node.sweep_loop.seeds_per_dispatch", True),
    ("ttfb distinct behaviors",
     "configs.time_to_first_bug.coverage.distinct_behaviors", True),
    ("bridge seeds/s", "configs.bridge_sweep.bridge_seeds_per_sec", True),
    ("bridge vs host", "configs.bridge_sweep.bridge_vs_host", True),
    # Forked worker pool behind the shared kernel (bridge/pool.py,
    # ROADMAP item 4): throughput vs host at J=2, protocol overhead vs
    # the serial loop on the same seeds (the 1-core gate), and the
    # parent's own per-round Python work, which must stay ~O(1) in W
    # (the pack loop left the parent).
    ("bridge pool j2 vs host",
     "configs.bridge_sweep.pool.j2_w64.bridge_vs_host", True),
    ("bridge pool j2 overhead frac",
     "configs.bridge_sweep.pool.j2_w64.pool_overhead_frac", False),
    ("bridge pool j2 parent ms/round",
     "configs.bridge_sweep.pool.j2_w64.parent_ms_per_round", False),
    ("host engine seeds/s", "configs.host_engine.seeds_per_sec", True),
    # Fleet fabric overhead (docs/fleet.md; bench_fleet_sweep): the
    # 2-worker local fabric's rate vs the single-host sweep on the same
    # seeds, tracked so lease/heartbeat/merge costs can't creep.
    ("fleet seeds/s", "configs.fleet_sweep.fleet_seeds_per_sec", True),
    ("fleet overhead frac",
     "configs.fleet_sweep.fabric_overhead_frac", False),
    # Fabric cost model breakdown (ISSUE 17; docs/fleet.md "Fabric
    # cost model"): per-lease phase timings and the coalesced control
    # plane's counted discipline — tracked so the O(1) lease turnaround
    # can't silently regress toward O(fresh sweep).
    ("fleet acquire ms/lease", "configs.fleet_sweep.acquire_ms", False),
    ("fleet sweep ms/lease", "configs.fleet_sweep.sweep_ms", False),
    ("fleet merge ms", "configs.fleet_sweep.merge_ms", False),
    ("fleet rpcs/lease", "configs.fleet_sweep.rpcs_per_lease", False),
    ("fleet session reuse hits",
     "configs.fleet_sweep.session_reuse_hits", True),
    # Failure-triage economy (docs/triage.md; bench_minimize_bug): how
    # cheaply a hunt's failure turns into a 1-minimal repro — rounds ==
    # candidate sweeps, so both the search's round count and its wall
    # time are tracked against creep.
    ("minimize rounds", "configs.minimize_bug.rounds", False),
    ("minimize candidates",
     "configs.minimize_bug.candidates_evaluated", False),
    ("minimize wall s", "configs.minimize_bug.wall_s", False),
    ("minimize final rows", "configs.minimize_bug.final_rows", False),
    # Guided-search hunting power (docs/search.md; bench_guided_hunt):
    # seeds-to-bug on the pair family (lower = the staircase is
    # working), the lower-bound speedup vs the matched random baseline,
    # and bugs-at-budget on the seeded raft double-vote.
    ("guided pair seeds-to-bug",
     "configs.guided_hunt.pair.guided_seeds_to_bug", False),
    ("guided pair seeds/dispatch",
     "configs.guided_hunt.pair.sweep_loop.seeds_per_dispatch", True),
    ("guided pair speedup>=",
     "configs.guided_hunt.pair.speedup_lower_bound", True),
    ("guided raft bugs",
     "configs.guided_hunt.raft.guided_bugs_found", True),
    ("random raft bugs",
     "configs.guided_hunt.raft.random_bugs_found", False),
    ("guided raft novelty area",
     "configs.guided_hunt.raft.guided_novelty_area", True),
    # The actorc-compiled Paxos leg (docs/actorc.md): seeds-to-bug on
    # the forgetful-acceptor consistency violation — the first DSL-only
    # family the guided search hunts — plus its staircase depth.
    ("guided paxos seeds-to-bug",
     "configs.guided_hunt.paxos.guided_seeds_to_bug", False),
    ("guided paxos speedup>=",
     "configs.guided_hunt.paxos.speedup_lower_bound", True),
    ("guided paxos lineage depth",
     "configs.guided_hunt.paxos.guided_lineage_depth", True),
    # Evolution observatory (obs/lineage.py, PR 13): ancestry depth of
    # the guided pair hunt and the corpus-survival credit of the
    # node-rotation operator (the one the pair bug NEEDS) — the
    # operator-credit signals a future adaptive scheduler will feed on.
    ("guided pair lineage depth",
     "configs.guided_hunt.pair.guided_lineage_depth", True),
    ("guided pair node_rotate survived",
     "configs.guided_hunt.pair.guided_operator_stats.node_rotate.survived",
     True),
    ("guided fleet lineage depth",
     "configs.guided_fleet.lineage_depth", True),
    # Cross-range corpus exchange (docs/fleet.md "Corpus exchange";
    # bench_guided_fleet): the fleet-level staircase — an exchanged
    # fleet must keep reaching the pair bug on ranges too small to
    # climb alone — plus the exchange's wall-time overhead and merge
    # traffic.
    ("exchanged fleet seeds-to-bug",
     "configs.guided_fleet.exchanged_seeds_to_bug", False),
    ("exchanged fleet bugs",
     "configs.guided_fleet.exchanged_bugs_found", True),
    ("exchange overhead frac",
     "configs.guided_fleet.exchange_overhead_frac", False),
    ("exchange merge inserts",
     "configs.guided_fleet.merge_inserts", True),
]


def load_round(path: str) -> dict:
    """A bench result dict from either artifact shape (see module doc)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if "metric" in doc and "configs" in doc:
        return doc  # bench_results.json shape
    if "parsed" in doc:  # BENCH_r wrapper
        if isinstance(doc.get("parsed"), dict):
            return doc["parsed"]
        # Truncated-stdout rounds: the tail's last JSON-looking line.
        for line in reversed((doc.get("tail") or "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
        raise ValueError(f"{path}: wrapper has no parsable result "
                         "(parsed is null and no JSON line in tail)")
    raise ValueError(f"{path}: not a bench artifact (neither "
                     "bench_results.json nor a BENCH_r wrapper)")


def dig(doc: Any, path: str) -> Optional[float]:
    cur = doc
    for leg in path.split("."):
        if not isinstance(cur, dict) or leg not in cur:
            return None
        cur = cur[leg]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def _auto_pair() -> Tuple[str, str]:
    """--auto: newest *parsable* BENCH_r*.json round vs
    bench_results.json (if it exists), else the two newest parsable
    rounds. Rounds whose stdout was truncated past recovery (no
    ``parsed``, no JSON tail line) are skipped with a note — exactly the
    failure mode that motivated the durable bench_results.json."""
    rounds = sorted(
        glob.glob(os.path.join(REPO, "BENCH_r*.json")),
        key=lambda p: int(re.search(r"BENCH_r(\d+)", p).group(1)))
    parsable = []
    for p in reversed(rounds):
        try:
            load_round(p)
            parsable.append(p)
        except (ValueError, OSError) as exc:
            print(f"bench_diff: skipping {os.path.basename(p)}: {exc}",
                  file=sys.stderr)
        if len(parsable) >= 2:
            break
    current = os.path.join(REPO, "bench_results.json")
    if parsable and os.path.exists(current):
        return parsable[0], current
    if len(parsable) >= 2:
        return parsable[1], parsable[0]
    raise SystemExit(
        "bench_diff --auto: need a parsable BENCH_r*.json plus "
        "bench_results.json (or two parsable rounds) — run `make smoke` "
        "first")


def diff_table(old: dict, new: dict, old_name: str, new_name: str,
               fail_pct: Optional[float] = None) -> Tuple[str, List[str]]:
    w_label = max(len(m[0]) for m in METRICS)
    header = (f"{'metric':<{w_label}}  {old_name:>14}  {new_name:>14}  "
              f"{'Δ%':>8}  ")
    lines = [header, "-" * len(header)]
    regressions: List[str] = []
    for label, path, higher_better in METRICS:
        a, b = dig(old, path), dig(new, path)
        if a is None and b is None:
            continue

        def fmt(v):
            if v is None:
                return "-"
            return f"{v:,.4g}" if abs(v) < 1000 else f"{v:,.0f}"

        if a is None or b is None or a == 0:
            delta_s, mark = "-", "  (new)" if a is None else "  (gone)"
        else:
            pct = (b - a) / abs(a) * 100.0
            improved = pct >= 0 if higher_better else pct <= 0
            delta_s = f"{pct:+.1f}%"
            mark = "" if abs(pct) < 0.05 else ("  ok" if improved
                                               else "  REGRESSED")
            if not improved and fail_pct is not None \
                    and abs(pct) > fail_pct:
                regressions.append(f"{label}: {fmt(a)} -> {fmt(b)} "
                                   f"({delta_s})")
        lines.append(f"{label:<{w_label}}  {fmt(a):>14}  {fmt(b):>14}  "
                     f"{delta_s:>8}{mark}")
    return "\n".join(lines), regressions


def ledger_rows(round_doc: dict, round_name: str) -> List[str]:
    """Rows comparing the tracelint budget ledger (analysis/budgets.json
    — what `make lint` enforces) against a bench round's recorded
    `xla_cost` (what that round actually measured). The two are the same
    program at possibly different shapes, so the per-world / ratio
    figures are the comparable ones; a gap means the ledger is stale
    relative to what benches run (regenerate via tools/update_budgets.py).
    """
    ledger_path = os.path.join(
        REPO, "madsim_tpu", "analysis", "budgets.json")
    if not os.path.exists(ledger_path):
        return []
    try:
        with open(ledger_path, encoding="utf-8") as f:
            ledger = json.load(f)
    except ValueError:
        return []
    rows: List[str] = []
    pairs = [
        ("engine.run flops/world-step", "engine.run", "flops_per_world",
         "configs.time_to_first_bug.xla_cost.flops_per_world_step"),
        ("engine.run state bytes/world", "engine.run",
         "state_bytes_per_world",
         "configs.time_to_first_bug.xla_cost.state_bytes_per_world"),
        ("engine.run peak/state", "engine.run", "peak_over_arg",
         "configs.time_to_first_bug.xla_cost.peak_over_state"),
    ]
    for label, prog, metric, round_path in pairs:
        entry = ledger.get("programs", {}).get(prog, {}).get(metric)
        if not isinstance(entry, dict):
            continue
        measured, budget = entry.get("measured"), entry.get("budget")
        round_v = dig(round_doc, round_path)
        gap = ""
        if round_v is not None and measured:
            pct = (round_v - measured) / abs(measured) * 100.0
            gap = f"  round {round_v:,.4g} ({pct:+.1f}% vs ledger)"
        rows.append(f"  {label:<28} ledger {measured:,.4g} "
                    f"budget {budget:,.4g}{gap}")
    if rows:
        rows.insert(0, f"budget ledger (analysis/budgets.json) vs "
                       f"{round_name}:")
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="regression table between two bench rounds")
    ap.add_argument("old", nargs="?", help="older artifact "
                                           "(BENCH_r*.json or "
                                           "bench_results.json)")
    ap.add_argument("new", nargs="?", help="newer artifact")
    ap.add_argument("--auto", action="store_true",
                    help="newest BENCH round vs bench_results.json")
    ap.add_argument("--fail-on-regress", type=float, default=None,
                    metavar="PCT",
                    help="exit 1 if any metric regresses more than PCT%%")
    args = ap.parse_args(argv)

    if args.auto:
        old_path, new_path = _auto_pair()
    elif args.old and args.new:
        old_path, new_path = args.old, args.new
    else:
        ap.error("give OLD and NEW artifacts, or --auto")
    old = load_round(old_path)
    new = load_round(new_path)
    table, regressions = diff_table(
        old, new, os.path.basename(old_path)[:14],
        os.path.basename(new_path)[:14],
        fail_pct=args.fail_on_regress)
    print(f"bench_diff: {old_path} -> {new_path}")
    print(table)
    for row in ledger_rows(old, os.path.basename(old_path)):
        print(row)
    if regressions:
        print(f"\n{len(regressions)} metric(s) regressed past "
              f"{args.fail_on_regress}%:", file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
