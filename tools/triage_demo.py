#!/usr/bin/env python
"""End-to-end failure-triage demo: inject → hunt → minimize → replay.

The `make triage-demo` target (docs/triage.md "The triage workflow").
Exercises the whole batched-minimization loop on the known-minimal
synthetic bug (triage/synthetic.py):

1. INJECT: per-world 32-row restart schedules where only two rows (the
   pair restarting nodes 1 and 2) are load-bearing — plus clean decoy
   worlds whose schedules lack one of the pair;
2. HUNT: one metrics-on pipelined sweep over the seed batch finds the
   failing worlds;
3. TRIAGE: `triage.triage(result)` dedupes the failures into classes
   (behavior signature + invariant id), runs the batched ddmin
   minimizer on one representative per class — asserting it converges
   to EXACTLY the two load-bearing rows — and writes one repro bundle
   per class with the `minimization` provenance block;
4. REPLAY: each minimized bundle replays through
   ``python -m madsim_tpu.obs replay`` in a fresh process; nonzero exit
   unless the recorded failure reproduces from the minimized schedule.

Exits nonzero on any failed expectation.
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.parallel.sweep import sweep
    from madsim_tpu.triage import (PairRestartActor, PairRestartConfig,
                                   pair_schedule, triage)
    from madsim_tpu.triage.synthetic import engine_config

    acfg = PairRestartConfig()
    cfg = engine_config(acfg, metrics=True)
    eng = DeviceEngine(PairRestartActor(acfg), cfg)

    # INJECT: 32 worlds; even seeds carry the full 32-row schedule with
    # the load-bearing pair at rows {5, 20}; odd seeds get a decoy
    # schedule missing the node-2 restart (they must NOT fail).
    n, n_rows = 32, 32
    full = pair_schedule(n_rows=n_rows, need=(5, 20), acfg=acfg)
    decoy = full.copy()
    decoy[20, 2] = 0  # row 20 restarts the filler node instead of node_b
    faults = np.stack([full if w % 2 == 0 else decoy for w in range(n)])

    # HUNT: one pipelined metrics-on sweep.
    res = sweep(None, cfg, np.arange(n), faults=faults, engine=eng,
                chunk_steps=32, max_steps=4_000)
    failing = res.failing_seeds
    print(f"triage-demo: hunt over {n} seeds: {len(failing)} failing",
          file=sys.stderr)
    if sorted(failing) != list(range(0, n, 2)):
        print(f"triage-demo: expected exactly the even seeds to fail, "
              f"got {failing}", file=sys.stderr)
        return 1

    # TRIAGE: dedupe + minimize one representative per class + bundles.
    with tempfile.TemporaryDirectory() as td:
        report = triage(res, out_dir=td, chunk_steps=32, max_steps=4_000)
        print(report.summary(), file=sys.stderr)
        if len(report.classes) != 1:
            print(f"triage-demo: expected ONE failure class, got "
                  f"{len(report.classes)}", file=sys.stderr)
            return 1
        key = report.classes[0].key
        mr = report.minimized[key]
        want = full[[5, 20]]
        if mr.final_rows != 2 or not (mr.schedule == want).all():
            print(f"triage-demo: minimizer returned\n{mr.schedule}\n"
                  f"expected exactly rows {{5, 20}}:\n{want}",
                  file=sys.stderr)
            return 1
        if not mr.one_minimal:
            print("triage-demo: 1-minimality verification failed",
                  file=sys.stderr)
            return 1

        # REPLAY the minimized bundle in a fresh process via the CLI —
        # rc 1 there means the recorded failure did NOT reproduce.
        bundle_path = report.bundles[key]
        with open(bundle_path, encoding="utf-8") as f:
            bundle = json.load(f)
        block = bundle.get("minimization") or {}
        if (block.get("original_rows"), block.get("final_rows")) != (32, 2):
            print(f"triage-demo: bundle minimization block is off: "
                  f"{block}", file=sys.stderr)
            return 1
        trace_path = os.path.join(td, "trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "madsim_tpu.obs", "replay",
             "--bundle", bundle_path, "--out", trace_path],
            env={**os.environ}, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(f"triage-demo: CLI replay of the minimized bundle "
                  f"failed rc={proc.returncode}", file=sys.stderr)
            return 1
        with open(trace_path, encoding="utf-8") as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        if not events or events[-1]["name"] != "invariant:raise":
            print("triage-demo: replayed trace does not end at the "
                  "invariant raise", file=sys.stderr)
            return 1
        print(f"triage-demo ok: {len(failing)} failures -> 1 class, "
              f"schedule {block['original_rows']} -> "
              f"{block['final_rows']} rows in {block['rounds']} rounds "
              f"({block['candidates_evaluated']} candidates), minimized "
              f"bundle replayed to the invariant raise")
    return 0


if __name__ == "__main__":
    sys.exit(main())
