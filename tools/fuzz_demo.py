#!/usr/bin/env python
"""End-to-end closed-fuzzer-loop demo: inject → guided hunt → triage.

The `make fuzz-demo` target (docs/search.md "The guided workflow") — the
acceptance gate of ROADMAP item 2. Exits nonzero on any miss.

1. INJECT: the pair-restart family (search/family.py) — the invariant
   needs two specific node restarts; the template restarts only filler
   nodes, so NO fixed-schedule sweep can ever reach the bug: only the
   search's mutation operators can.
2. HUNT: coverage-guided `sweep(recycle=True, search=...)` vs the
   MATCHED random-mutation baseline (same operators, rates and budget,
   no feedback) — guided must reach the bug in strictly fewer seeds.
3. TRIAGE: the find pipes unchanged through `triage.triage` — the
   materialized child schedule ddmins to a verified 1-minimal bundle
   (exactly the two target restarts), which must replay through
   `python -m madsim_tpu.obs replay` in a fresh process.
4. RAFT: the seeded double-vote hunt (search/hunts.py raft_hunt):
   guided must find strictly more failing seeds than random at the
   same budget (first-bug ties are expected — generation-1 children
   are shared by construction).
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET = 512

# The pinned search numbers (ROADMAP item 2; docs/search.md): the
# guided hunts are bitwise-deterministic, so these exact values hold
# until MUTATION/GENERATION code changes — at which point the PR 11
# retune-and-re-pin rule applies: retune search/family.py + hunts.py,
# re-measure with `bench.py --only guided`, and re-pin here AND in the
# ROADMAP recap. A drift WITHOUT a mutation-code change means search
# semantics regressed silently — that is what this gate exists to catch
# (PR 12 satellite: exchange/fleet work must not move these).
PIN_PAIR_GUIDED = 73    # guided seeds-to-bug, pair family
PIN_PAIR_RANDOM = 409   # random seeds-to-bug, pair family
PIN_RAFT_GUIDED = 6     # guided failing seeds at budget, seeded raft
PIN_RAFT_RANDOM = 3     # random failing seeds at budget, seeded raft


def main() -> int:
    import numpy as np

    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.parallel.sweep import sweep
    from madsim_tpu.search.family import GuidedPairConfig, HUNT_NODES
    from madsim_tpu.search.hunts import pair_hunt, raft_hunt
    from madsim_tpu.triage import triage

    def run(hunt, guided, stop):
        eng = engines.setdefault(hunt.name,
                                 DeviceEngine(hunt.actor, hunt.cfg))
        return sweep(None, hunt.cfg, np.arange(BUDGET), engine=eng,
                     faults=hunt.template, stop_on_first_bug=stop,
                     search=hunt.search(guided), **hunt.sweep_kw)

    engines = {}

    # -- 1+2: the pair family, guided vs random ------------------------
    pair = pair_hunt()
    g = run(pair, guided=True, stop=True)
    r = run(pair, guided=False, stop=True)
    g_seeds = (g.failing_seeds[0] + 1) if g.failing_seeds else None
    r_seeds = (r.failing_seeds[0] + 1) if r.failing_seeds else None
    print(f"fuzz-demo: pair family @ {BUDGET} seeds: guided found the "
          f"bug at seed {g_seeds}, random at "
          f"{r_seeds if r_seeds else f'>{BUDGET} (not found)'}",
          file=sys.stderr)
    if g_seeds is None:
        print("fuzz-demo: guided search missed the pair bug in budget",
              file=sys.stderr)
        return 1
    if r_seeds is not None and g_seeds >= r_seeds:
        print(f"fuzz-demo: guided ({g_seeds}) did not beat random "
              f"({r_seeds}) on the pair family", file=sys.stderr)
        return 1
    if (g_seeds, r_seeds) != (PIN_PAIR_GUIDED, PIN_PAIR_RANDOM):
        print(f"fuzz-demo: pair seeds-to-bug drifted off the pinned "
              f"numbers: got guided={g_seeds} random={r_seeds}, pinned "
              f"{PIN_PAIR_GUIDED}/{PIN_PAIR_RANDOM}. If mutation/"
              f"generation code changed deliberately, retune and re-pin "
              f"(see the constants above); otherwise search semantics "
              f"regressed.", file=sys.stderr)
        return 1

    # -- 2b: the find's lineage (obs/lineage.py, docs/search.md
    # "Reading the lineage"): the ancestry chain must reach a
    # generation-0 template parent and name at least one mutation
    # operator — the pair bug is UNREACHABLE without mutation, so an
    # operator-free chain means provenance accounting broke.
    from madsim_tpu.obs.lineage import render_operator_table, render_tree

    chain = g.search.ancestry(g.failing_seeds[0], seeds=g.seeds)
    print("fuzz-demo: find derivation:\n"
          + render_tree(chain), file=sys.stderr)
    print(render_operator_table(g.search.operator_stats), file=sys.stderr)
    if chain[-1].get("kind") != "template":
        print(f"fuzz-demo: ancestry chain does not terminate at the "
              f"generation-0 template: {chain[-1]}", file=sys.stderr)
        return 1
    chain_ops = {op for node in chain for op in node.get("ops", [])}
    if not chain_ops:
        print("fuzz-demo: the find's ancestry names NO mutation "
              "operators — the pair bug cannot be reached without "
              "mutation, so the lineage lanes are broken",
              file=sys.stderr)
        return 1
    bug_ops = {name for name, row in g.search.operator_stats.items()
               if row["bug"] > 0}
    if not bug_ops:
        print("fuzz-demo: operator outcome table credits no operator "
              "with the find (bug row all zero)", file=sys.stderr)
        return 1

    # -- 3: triage the guided find to a 1-minimal replayable bundle ----
    with tempfile.TemporaryDirectory() as td:
        report = triage(g, out_dir=td, chunk_steps=32, max_steps=20_000)
        print(report.summary(), file=sys.stderr)
        if len(report.classes) != 1:
            print(f"fuzz-demo: expected ONE failure class, got "
                  f"{len(report.classes)}", file=sys.stderr)
            return 1
        key = report.classes[0].key
        mr = report.minimized[key]
        acfg = GuidedPairConfig(n=HUNT_NODES)
        targets = sorted(int(x) for x in mr.schedule[:, 2])
        if mr.final_rows != 2 or not mr.one_minimal or \
                targets != [acfg.node_a, acfg.node_b]:
            print(f"fuzz-demo: minimizer returned {mr.final_rows} rows "
                  f"targeting {targets} (want 2 rows, targets "
                  f"{[acfg.node_a, acfg.node_b]}, 1-minimal); "
                  f"{mr.summary()}", file=sys.stderr)
            return 1
        bundle_path = report.bundles[key]
        with open(bundle_path, encoding="utf-8") as f:
            bundle = json.load(f)
        block = bundle.get("minimization") or {}
        if block.get("final_rows") != 2:
            print(f"fuzz-demo: bundle minimization block off: {block}",
                  file=sys.stderr)
            return 1
        lin_block = bundle.get("lineage") or {}
        if lin_block.get("schema") != "madsim.search.lineage/1" or \
                not lin_block.get("operators_applied") or \
                (lin_block.get("chain") or [{}])[-1].get("kind") \
                != "template":
            print(f"fuzz-demo: bundle lineage block missing/incomplete: "
                  f"{ {k: lin_block.get(k) for k in ('schema', 'operators_applied')} }",
                  file=sys.stderr)
            return 1
        trace_path = os.path.join(td, "trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "madsim_tpu.obs", "replay",
             "--bundle", bundle_path, "--out", trace_path],
            env={**os.environ}, capture_output=True, text=True)
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(f"fuzz-demo: CLI replay of the minimized bundle "
                  f"failed rc={proc.returncode}", file=sys.stderr)
            return 1
        print(f"fuzz-demo: guided find minimized "
              f"{block['original_rows']} -> {block['final_rows']} rows "
              f"in {block['rounds']} rounds and replayed", file=sys.stderr)

    # -- 4: the seeded raft double-vote, bugs-at-budget ----------------
    raft = raft_hunt()
    gr = run(raft, guided=True, stop=False)
    rr = run(raft, guided=False, stop=False)
    g_bugs, r_bugs = len(gr.failing_seeds), len(rr.failing_seeds)
    print(f"fuzz-demo: seeded raft double-vote @ {BUDGET} seeds: "
          f"guided found {g_bugs} failing seeds, random {r_bugs}",
          file=sys.stderr)
    if g_bugs <= r_bugs:
        print("fuzz-demo: guided search did not out-hunt random on the "
              "seeded raft bug", file=sys.stderr)
        return 1
    if (g_bugs, r_bugs) != (PIN_RAFT_GUIDED, PIN_RAFT_RANDOM):
        print(f"fuzz-demo: raft bugs-at-budget drifted off the pinned "
              f"numbers: got guided={g_bugs} random={r_bugs}, pinned "
              f"{PIN_RAFT_GUIDED}/{PIN_RAFT_RANDOM} — retune and re-pin "
              f"if mutation code changed, else investigate the "
              f"regression.", file=sys.stderr)
        return 1

    print(f"fuzz-demo ok: pair bug at seed {g_seeds} guided vs "
          f"{r_seeds if r_seeds else f'>{BUDGET}'} random "
          f"(>= {((r_seeds or BUDGET + 1) / g_seeds):.1f}x fewer seeds), "
          f"1-minimal bundle replayed; raft {g_bugs} vs {r_bugs} "
          f"failing seeds at the same budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())
