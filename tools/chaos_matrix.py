"""Chaos matrix runner: `make chaos` (docs/fleet.md).

Drives the fleet fabric's worker-crash / duplicate-completion matrix on
the virtual CPU mesh for all three actor families and asserts the
crash-identical contract end to end:

    single-host sweep()  ==  crash-free fleet  ==  chaotic fleet

on seed ids, bug flags, per-seed observations, and (raft, metrics on)
the coverage ledger — while verifying the chaos actually happened
(kills, lease expiries + re-issues, duplicated completions, SIGTERM
preemptions, torn checkpoints, RPC retries all nonzero). Prints one
JSON summary line per family and exits nonzero on any violation.

`--process` additionally runs the multiprocess leg (real worker
processes, pipes, SIGKILL mid-lease) — slower: each worker pays a JAX
import + compile. CI runs the default matrix after smoke; the same
assertions also live in tier-1 (tests/test_fleet.py) so `make test`
covers them too.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _families():
    from madsim_tpu.engine import (
        DeviceEngine,
        EngineConfig,
        PBActor,
        PBDeviceConfig,
        RaftActor,
        RaftDeviceConfig,
        TPCActor,
        TPCDeviceConfig,
    )

    raft_cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                            t_limit_us=1_500_000, stop_on_bug=True,
                            metrics=True)
    yield "raft", DeviceEngine(
        RaftActor(RaftDeviceConfig(n=3, buggy_double_vote=True)), raft_cfg)
    yield "pb", DeviceEngine(
        PBActor(PBDeviceConfig(n=3, n_writes=4)),
        EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.05))
    yield "tpc", DeviceEngine(
        TPCActor(TPCDeviceConfig(n=4, n_txns=4,
                                 buggy_presumed_commit=True)),
        EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.1))


def _contract_equal(a, b) -> list:
    from madsim_tpu.fleet import contract_mismatches

    return contract_mismatches(a, b)


def run_matrix(n_seeds: int = 64) -> int:
    from madsim_tpu.fleet import ChaosConfig, fleet_sweep
    from madsim_tpu.parallel.sweep import sweep

    chaos = ChaosConfig(seed=11, kill_at=(("w0", 2),),
                        preempt_at=(("w1", 5),),
                        duplicate_all_completions=True,
                        drop_rpc_rate=0.25, drop_heartbeat_rate=0.1,
                        tear_checkpoint_on_kill=True, restart_after=2)
    kw = dict(chunk_steps=64, max_steps=20_000)
    failures = 0
    for name, eng in _families():
        seeds = np.arange(n_seeds)
        single = sweep(None, eng.cfg, seeds, engine=eng, **kw)
        clean = fleet_sweep(None, eng.cfg, seeds, engine=eng,
                            n_workers=2, range_size=n_seeds // 4, **kw)
        with tempfile.TemporaryDirectory() as ckdir:
            chaotic = fleet_sweep(None, eng.cfg, seeds, engine=eng,
                                  n_workers=2, range_size=n_seeds // 4,
                                  chaos=chaos, checkpoint_dir=ckdir, **kw)
        bad = _contract_equal(single, clean) + _contract_equal(single,
                                                               chaotic)
        stats = chaotic.loop_stats["fleet"]
        injected = {k: stats[k] for k in
                    ("kills", "preemptions", "rpc_retries",
                     "checkpoints_discarded")}
        injected["leases_expired"] = stats["leases_expired"]
        injected["leases_reissued"] = stats["leases_reissued"]
        injected["duplicates_crosschecked"] = \
            stats["duplicates_crosschecked"]
        missing = [k for k in ("kills", "leases_expired", "leases_reissued",
                               "duplicates_crosschecked")
                   if not injected.get(k)]
        ok = not bad and not missing
        failures += 0 if ok else 1
        print(json.dumps({
            "family": name, "ok": ok, "n_seeds": n_seeds,
            "failing_seeds": len(single.failing_seeds),
            "contract_mismatches": bad,
            "chaos_not_exercised": missing,
            "injected": injected,
        }))
    return failures


def run_guided_leg(n_seeds: int = 96) -> int:
    """Guided-refill chaos leg (docs/search.md): a chaotic guided fleet
    must equal a crash-free guided fleet BITWISE — per-seed
    observations, bug flags, and the materialized schedules' effects.
    (No single-host comparison here: each leased range evolves its own
    corpus, so guided fleet results are deterministic per (seeds, range
    partitioning, SearchConfig), not partition-invariant.)"""
    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.fleet import ChaosConfig, fleet_sweep
    from madsim_tpu.search.hunts import pair_hunt

    hunt = pair_hunt()
    eng = DeviceEngine(hunt.actor, hunt.cfg)
    seeds = np.arange(n_seeds)
    kw = dict(engine=eng, faults=hunt.template,
              search=hunt.search(True), **hunt.sweep_kw)
    clean = fleet_sweep(None, hunt.cfg, seeds, n_workers=2,
                        range_size=n_seeds // 2, **kw)
    chaotic = fleet_sweep(None, hunt.cfg, seeds, n_workers=2,
                          range_size=n_seeds // 2,
                          chaos=ChaosConfig(seed=7, kill_at=(("w1", 2),),
                                            drop_rpc_rate=0.2,
                                            restart_after=2), **kw)
    bad = _contract_equal(clean, chaotic)
    stats = chaotic.loop_stats["fleet"]
    ok = not bad and stats["kills"] > 0
    print(json.dumps({
        "family": "guided_pair(guided refill)", "ok": ok,
        "n_seeds": n_seeds,
        "contract_mismatches": bad,
        "injected": {k: stats[k] for k in ("kills", "leases_reissued",
                                           "rpc_retries")},
    }))
    return 0 if ok else 1


def run_exchange_leg(n_seeds: int = 320) -> int:
    """Guided-EXCHANGE chaos leg (docs/fleet.md "Corpus exchange"):
    a chaotic exchanged fleet — worker kills mid-epoch (kill→re-lease
    re-seeds from the last merged epoch), duplicated completions, torn
    corpus publishes, dropped RPCs — must equal a crash-free exchanged
    fleet BITWISE on the contract fields INCLUDING the materialized
    per-seed schedules and the final merged corpus; and the exchange
    must actually bite: the exchanged fleet reaches the pair bug on
    64-seed ranges an independent fleet can never climb alone."""
    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.fleet import ChaosConfig, ExchangeConfig, fleet_sweep
    from madsim_tpu.search.hunts import pair_hunt

    hunt = pair_hunt()
    eng = DeviceEngine(hunt.actor, hunt.cfg)
    seeds = np.arange(n_seeds)
    kw = dict(engine=eng, faults=hunt.template, search=hunt.search(True),
              stop_on_first_bug=True, range_size=64, n_workers=2,
              exchange=ExchangeConfig(every=1), **hunt.sweep_kw)
    clean = fleet_sweep(None, hunt.cfg, seeds, **kw)
    chaotic = fleet_sweep(
        None, hunt.cfg, seeds,
        chaos=ChaosConfig(seed=13, kill_at=(("w1", 2),),
                          duplicate_all_completions=True,
                          tear_publish_at=(("w0", 1),),
                          drop_rpc_rate=0.2, restart_after=2), **kw)
    bad = _contract_equal(clean, chaotic)
    stats = chaotic.loop_stats["fleet"]
    injected = {k: stats[k] for k in
                ("kills", "leases_reissued", "publishes_torn",
                 "duplicates_crosschecked", "rpc_retries")}
    # Torn-publish-under-coalescing (ISSUE 17): the publish rides the
    # batched publish+complete turn now, so a torn first attempt must
    # surface through the batch response and re-send solo.
    injected["corpus_resent"] = sum(
        w["corpus_resent"] for w in stats["workers"].values())
    missing = [k for k in injected if not injected[k]]
    found = bool(clean.failing_seeds)
    ok = not bad and not missing and found
    print(json.dumps({
        "family": "guided_pair(corpus exchange)", "ok": ok,
        "n_seeds": n_seeds,
        "contract_mismatches": bad,
        "chaos_not_exercised": missing,
        "exchange_found_bug": found,
        "epochs_merged": stats["epochs_merged"],
        "injected": injected,
    }))
    return 0 if ok else 1


def run_session_prefetch_leg(n_seeds: int = 64) -> int:
    """Fabric cost-model disciplines under chaos (ISSUE 17): grouped
    persistent-session quanta + default lease prefetch, with NO
    checkpoint dir so the grouped path is live — w0 is preempted at its
    FIRST heartbeat (mid-prefetch: its prefetched leases are still
    held and must all release cleanly), w1 is killed mid-group (held
    leases recover via TTL expiry), completions are duplicated and RPCs
    dropped. Gate: chaotic == clean == single-host bitwise, with the
    disciplines demonstrably active (prefetched + grouped leases,
    session reuse) and the chaos demonstrably landed on them."""
    from madsim_tpu.engine import (
        DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig,
    )
    from madsim_tpu.fleet import ChaosConfig, fleet_sweep
    from madsim_tpu.parallel.sweep import sweep

    eng = DeviceEngine(
        RaftActor(RaftDeviceConfig(n=3, buggy_double_vote=True)),
        EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                     t_limit_us=1_500_000, stop_on_bug=True,
                     metrics=True))
    seeds = np.arange(n_seeds)
    kw = dict(chunk_steps=64, max_steps=20_000)
    single = sweep(None, eng.cfg, seeds, engine=eng, **kw)
    clean = fleet_sweep(None, eng.cfg, seeds, engine=eng, n_workers=2,
                        range_size=n_seeds // 8, **kw)
    chaotic = fleet_sweep(
        None, eng.cfg, seeds, engine=eng, n_workers=2,
        range_size=n_seeds // 8,
        chaos=ChaosConfig(seed=23, preempt_at=(("w0", 1),),
                          kill_at=(("w1", 3),),
                          duplicate_all_completions=True,
                          drop_rpc_rate=0.2, restart_after=2), **kw)
    bad = (_contract_equal(single, clean)
           + _contract_equal(single, chaotic))
    cstats = clean.loop_stats["fleet"]
    stats = chaotic.loop_stats["fleet"]
    active = {k: (cstats[k], stats[k]) for k in
              ("leases_prefetched", "grouped_leases",
               "session_reuse_hits")}
    injected = {k: stats[k] for k in
                ("preemptions", "kills", "leases_expired",
                 "leases_reissued", "duplicates_crosschecked")}
    missing = ([k for k, v in active.items() if not v[0]]
               + [k for k, v in injected.items() if not v])
    ok = not bad and not missing
    print(json.dumps({
        "family": "raft(session+prefetch)", "ok": ok,
        "n_seeds": n_seeds,
        "contract_mismatches": bad,
        "disciplines_inactive_or_chaos_missed": missing,
        "disciplines": active,
        "injected": injected,
    }))
    return 0 if ok else 1


def run_process_leg(n_seeds: int = 32) -> int:
    from madsim_tpu.engine import (
        DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig,
    )
    from madsim_tpu.fleet import fleet_sweep
    from madsim_tpu.parallel.sweep import sweep

    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000, stop_on_bug=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(n_seeds)
    kw = dict(chunk_steps=64, max_steps=20_000)
    single = sweep(None, cfg, seeds, engine=eng, **kw)
    with tempfile.TemporaryDirectory() as ckdir:
        fleet = fleet_sweep(RaftActor(rcfg), cfg, seeds, n_workers=2,
                            range_size=n_seeds // 4, spawn="process",
                            lease_ttl=5.0, checkpoint_dir=ckdir,
                            kill_after_heartbeats={"w0": 1},
                            serve_timeout_s=300.0, **kw)
    bad = _contract_equal(single, fleet)
    print(json.dumps({"family": "raft(process)", "ok": not bad,
                      "contract_mismatches": bad,
                      "fleet": {k: v for k, v in
                                fleet.loop_stats["fleet"].items()
                                if not isinstance(v, dict)}}))
    return 1 if bad else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=64)
    ap.add_argument("--process", action="store_true",
                    help="also run the multiprocess (spawn) leg")
    args = ap.parse_args()
    failures = run_matrix(args.seeds)
    failures += run_session_prefetch_leg()
    failures += run_guided_leg()
    failures += run_exchange_leg()
    if args.process:
        failures += run_process_leg()
    if failures:
        print(f"chaos matrix: {failures} FAMILY FAILURES", file=sys.stderr)
        return 1
    print("chaos matrix: all families crash-identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
