// Native host-engine core for madsim_tpu.
//
// The reference's performance-critical host components are native Rust
// (SURVEY §2 ⚙): the Threefry-equivalent seeded RNG (madsim/src/sim/rand.rs),
// the timer wheel (time/mod.rs via naive_timer), and the scheduler's random
// ready-pick (utils/mpsc.rs:73-83). This file provides the same kernels,
// exposed two ways from one translation unit:
//
//   1. a plain C ABI (the ms_* functions) for non-Python consumers/tests;
//   2. a CPython extension module (`_core`) — the hot path. The C API is
//      used rather than ctypes because per-call marshalling overhead of
//      ctypes (~µs) exceeds the kernels' own cost and made the "native"
//      path slower than pure Python.
//
// Pure-Python fallbacks exist for every function here
// (madsim_tpu/native/__init__.py chooses at import); bit-exactness contract:
// threefry2x32 must match ops/threefry.py's numpy and jax implementations
// word-for-word (tested in tests/test_native.py), since host and device
// engines share RNG streams.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -I<python-include> \
//            -o _core.so madsim_core.cpp

#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_set>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Threefry-2x32, 20 rounds (Random123) — must match ops/threefry.py.
// ---------------------------------------------------------------------------

static const unsigned ROT[8] = {13, 15, 26, 6, 17, 29, 16, 24};

static inline uint32_t rotl32(uint32_t x, unsigned r) {
  return (x << r) | (x >> (32 - r));
}

static inline void threefry2x32(uint32_t k0, uint32_t k1, uint32_t c0,
                                uint32_t c1, uint32_t* out0, uint32_t* out1) {
  uint32_t x0 = c0 + k0;
  uint32_t x1 = c1 + k1;
  uint32_t ks[3] = {k0, k1, k0 ^ k1 ^ 0x1BD11BDAu};
  for (unsigned i = 0; i < 5; ++i) {
    for (unsigned r = 0; r < 4; ++r) {
      x0 += x1;
      x1 = rotl32(x1, ROT[4 * (i % 2) + r]);
      x1 ^= x0;
    }
    x0 += ks[(i + 1) % 3];
    x1 += ks[(i + 2) % 3] + (uint32_t)(i + 1);
  }
  *out0 = x0;
  *out1 = x1;
}

// Single draw of counter block `counter` → (x1 << 32) | x0, like draw_np.
uint64_t ms_threefry_draw(uint32_t k0, uint32_t k1, uint64_t counter) {
  uint32_t x0, x1;
  threefry2x32(k0, k1, (uint32_t)(counter & 0xFFFFFFFFu),
               (uint32_t)(counter >> 32), &x0, &x1);
  return ((uint64_t)x1 << 32) | (uint64_t)x0;
}

// Derive a stream key (derive_stream_np): encrypt the stream id.
uint64_t ms_derive_stream(uint32_t k0, uint32_t k1, uint64_t stream) {
  return ms_threefry_draw(k0, k1, stream);
}

// Batch draw for bulk consumers (fault-schedule generation etc.).
void ms_threefry_batch(uint32_t k0, uint32_t k1, uint64_t start_counter,
                       uint64_t n, uint64_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    out[i] = ms_threefry_draw(k0, k1, start_counter + i);
}

// ---------------------------------------------------------------------------
// Timer wheel: binary min-heap of (deadline_ns, seq) with lazy cancellation.
// Mirrors core/timewheel.py TimeRuntime semantics exactly.
// ---------------------------------------------------------------------------

struct TimerEntry {
  int64_t deadline_ns;
  uint64_t seq;
  bool operator>(const TimerEntry& o) const {
    if (deadline_ns != o.deadline_ns) return deadline_ns > o.deadline_ns;
    return seq > o.seq;
  }
};

struct TimerHeap {
  std::priority_queue<TimerEntry, std::vector<TimerEntry>,
                      std::greater<TimerEntry>>
      heap;
  std::unordered_set<uint64_t> cancelled;
};

void* ms_timerheap_new() { return new TimerHeap(); }

void ms_timerheap_free(void* h) { delete (TimerHeap*)h; }

// All accessors tolerate a null handle (the Python wrapper passes None after
// free during teardown races) by treating it as an empty heap.
void ms_timerheap_push(void* h, int64_t deadline_ns, uint64_t seq) {
  if (h) ((TimerHeap*)h)->heap.push(TimerEntry{deadline_ns, seq});
}

void ms_timerheap_cancel(void* h, uint64_t seq) {
  if (h) ((TimerHeap*)h)->cancelled.insert(seq);
}

// Earliest live deadline → 1 and *deadline set; 0 if empty.
int ms_timerheap_peek(void* h, int64_t* deadline_ns) {
  auto* th = (TimerHeap*)h;
  if (!th) return 0;
  while (!th->heap.empty()) {
    const TimerEntry& top = th->heap.top();
    auto it = th->cancelled.find(top.seq);
    if (it != th->cancelled.end()) {
      th->cancelled.erase(it);
      th->heap.pop();
      continue;
    }
    *deadline_ns = top.deadline_ns;
    return 1;
  }
  return 0;
}

// Pop the earliest live entry if deadline <= now → 1 and *seq set; else 0.
int ms_timerheap_pop_due(void* h, int64_t now_ns, uint64_t* seq) {
  auto* th = (TimerHeap*)h;
  if (!th) return 0;
  int64_t deadline;
  while (ms_timerheap_peek(h, &deadline)) {
    if (deadline > now_ns) return 0;
    *seq = th->heap.top().seq;
    th->heap.pop();
    return 1;
  }
  return 0;
}

uint64_t ms_timerheap_len(void* h) {
  return h ? ((TimerHeap*)h)->heap.size() : 0;
}

// ---------------------------------------------------------------------------
// Seeded random ready-pick (utils/mpsc.rs:73-83 analog): uniform index from
// one RNG draw, matching GlobalRng.gen_range's modulo method so Python and
// native scheduling decisions are interchangeable.
// ---------------------------------------------------------------------------

uint64_t ms_pick_index(uint32_t k0, uint32_t k1, uint64_t counter,
                       uint64_t len) {
  return ms_threefry_draw(k0, k1, counter) % len;
}

// ---------------------------------------------------------------------------
// Stateful RNG cursor (GlobalRng's hot path in one native object): key,
// draw counter, and the 32-bit half-block buffer live here, so a scheduler
// decision (gen_range) is ONE native call instead of four Python frames.
// Semantics are bit-identical to madsim_tpu/core/rng.py GlobalRng:
//   next_u64: fresh block, clears the u32 buffer
//   next_u32: buffered half first, else low half of a fresh block
//   gen_range(lo,hi): lo + next_u64() % (hi-lo)
//   random(): (next_u64() >> 11) * 2^-53
// ---------------------------------------------------------------------------

struct RngState {
  uint32_t k0, k1;
  uint64_t counter;
  uint32_t buf;
  int has_buf;
};

void* ms_rng_new(uint32_t k0, uint32_t k1, uint64_t counter) {
  auto* st = new RngState{k0, k1, counter, 0, 0};
  return st;
}

void ms_rng_free(void* p) { delete (RngState*)p; }

uint64_t ms_rng_next_u64(void* p) {
  auto* st = (RngState*)p;
  st->has_buf = 0;
  return ms_threefry_draw(st->k0, st->k1, st->counter++);
}

uint32_t ms_rng_next_u32(void* p) {
  auto* st = (RngState*)p;
  if (st->has_buf) {
    st->has_buf = 0;
    return st->buf;
  }
  uint64_t block = ms_threefry_draw(st->k0, st->k1, st->counter++);
  st->buf = (uint32_t)(block >> 32);
  st->has_buf = 1;
  return (uint32_t)(block & 0xFFFFFFFFu);
}

}  // extern "C"

// ===========================================================================
// CPython extension module bindings (the fast path).
// ===========================================================================

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static void heap_capsule_destructor(PyObject* capsule) {
  void* h = PyCapsule_GetPointer(capsule, "madsim.TimerHeap");
  if (h) ms_timerheap_free(h);
}

static TimerHeap* heap_from(PyObject* capsule) {
  return (TimerHeap*)PyCapsule_GetPointer(capsule, "madsim.TimerHeap");
}

static PyObject* py_threefry_draw(PyObject*, PyObject* args) {
  unsigned int k0, k1;
  unsigned long long counter;
  if (!PyArg_ParseTuple(args, "IIK", &k0, &k1, &counter)) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_threefry_draw(k0, k1, counter));
}

static PyObject* py_derive_stream(PyObject*, PyObject* args) {
  unsigned int k0, k1;
  unsigned long long stream;
  if (!PyArg_ParseTuple(args, "IIK", &k0, &k1, &stream)) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_derive_stream(k0, k1, stream));
}

static PyObject* py_heap_new(PyObject*, PyObject*) {
  return PyCapsule_New(ms_timerheap_new(), "madsim.TimerHeap",
                       heap_capsule_destructor);
}

static PyObject* py_heap_push(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long deadline;
  unsigned long long seq;
  if (!PyArg_ParseTuple(args, "OLK", &capsule, &deadline, &seq)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  ms_timerheap_push(h, deadline, seq);
  Py_RETURN_NONE;
}

static PyObject* py_heap_cancel(PyObject*, PyObject* args) {
  PyObject* capsule;
  unsigned long long seq;
  if (!PyArg_ParseTuple(args, "OK", &capsule, &seq)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  ms_timerheap_cancel(h, seq);
  Py_RETURN_NONE;
}

static PyObject* py_heap_peek(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  int64_t deadline;
  if (!ms_timerheap_peek(h, &deadline)) Py_RETURN_NONE;
  return PyLong_FromLongLong(deadline);
}

static PyObject* py_heap_pop_due(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long now;
  if (!PyArg_ParseTuple(args, "OL", &capsule, &now)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  uint64_t seq;
  if (!ms_timerheap_pop_due(h, now, &seq)) Py_RETURN_NONE;
  return PyLong_FromUnsignedLongLong(seq);
}

static PyObject* py_heap_len(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_timerheap_len(h));
}

// -- RngState bindings ------------------------------------------------------

static void rng_capsule_destructor(PyObject* capsule) {
  void* p = PyCapsule_GetPointer(capsule, "madsim.RngState");
  if (p) ms_rng_free(p);
}

static RngState* rng_from(PyObject* capsule) {
  return (RngState*)PyCapsule_GetPointer(capsule, "madsim.RngState");
}

static PyObject* py_rng_new(PyObject*, PyObject* args) {
  unsigned int k0, k1;
  unsigned long long counter;
  if (!PyArg_ParseTuple(args, "IIK", &k0, &k1, &counter)) return nullptr;
  return PyCapsule_New(ms_rng_new(k0, k1, counter), "madsim.RngState",
                       rng_capsule_destructor);
}

static PyObject* py_rng_next_u64(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_rng_next_u64(st));
}

static PyObject* py_rng_next_u32(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  return PyLong_FromUnsignedLong(ms_rng_next_u32(st));
}

static PyObject* py_rng_gen_range(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long lo, hi;
  if (!PyArg_ParseTuple(args, "OLL", &capsule, &lo, &hi)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  long long width = hi - lo;
  if (width <= 0) {
    PyErr_Format(PyExc_ValueError, "empty range [%lld, %lld)", lo, hi);
    return nullptr;
  }
  uint64_t v = ms_rng_next_u64(st);
  return PyLong_FromLongLong(lo + (long long)(v % (uint64_t)width));
}

static PyObject* py_rng_random(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  uint64_t v = ms_rng_next_u64(st);
  return PyFloat_FromDouble((double)(v >> 11) * 1.1102230246251565e-16);
}

static PyObject* py_rng_get_state(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  if (st->has_buf)
    return Py_BuildValue("(KI)", (unsigned long long)st->counter,
                         (unsigned int)st->buf);
  return Py_BuildValue("(KO)", (unsigned long long)st->counter, Py_None);
}

// ---------------------------------------------------------------------------
// Native poll loop: Executor.run_all_ready in C (task.rs:121-180 hot loop).
//
// Bit-exactness contract with the Python loop (core/task.py run_all_ready +
// _poll): same RNG draws in the same order (ready pick, per-poll jitter),
// same enqueue order (TaskWaker objects are appended to the SAME
// SimFuture._callbacks list, at the same position, as the Python closure
// would be), same exception routing. The Python loop remains the fallback
// for trace mode, determinism log/check mode, and builds without the
// native core — cross-checked in tests/test_native.py.
// ---------------------------------------------------------------------------

// Interned attribute names (created in PyInit__core).
static PyObject *s_queue, *s_yields, *s_uncaught, *s_scheduled, *s_finished,
    *s_cancelled, *s_node, *s_killed, *s_paused, *s_paused_tasks, *s_task,
    *s_pending_exc, *s_coro, *s_send, *s_throw, *s_drop, *s_set_result,
    *s_set_exception, *s_wake_epoch, *s_result, *s_exception, *s_callbacks,
    *s_join_future, *s_tasks, *s_elapsed_ns, *s_poll_count, *s_time,
    *s_foreign_yield, *s_value, *s_yield_now, *s_noop_waiting,
    *s_after_noop;

// TaskWaker: the C twin of the per-await closure
//   lambda _fut, t=task, e=epoch: self._wake(t) if t.wake_epoch == e else None
// Appended to SimFuture._callbacks so callback ORDER (part of the enqueue
// order, and therefore of the seeded trajectory) matches the Python loop.
typedef struct {
  PyObject_HEAD
  PyObject* executor;
  PyObject* task;
  long long epoch;
} TaskWakerObject;

static int enqueue_task(PyObject* executor, PyObject* task);

static PyObject* TaskWaker_call(PyObject* self_obj, PyObject* args,
                                PyObject* kwargs) {
  TaskWakerObject* self = (TaskWakerObject*)self_obj;
  PyObject* epoch_obj = PyObject_GetAttr(self->task, s_wake_epoch);
  if (!epoch_obj) return nullptr;
  long long epoch = PyLong_AsLongLong(epoch_obj);
  Py_DECREF(epoch_obj);
  if (epoch == -1 && PyErr_Occurred()) return nullptr;
  if (epoch == self->epoch) {
    if (enqueue_task(self->executor, self->task) < 0) return nullptr;
  }
  Py_RETURN_NONE;
}

// GC support is mandatory here: every pending await forms a cycle
// (task.coro frame → future → _callbacks → waker → task), exactly like
// the Python closure it replaces — which is GC-tracked. Without
// traverse/clear a discarded Runtime with suspended tasks would leak its
// whole executor graph.
static int TaskWaker_traverse(PyObject* self_obj, visitproc visit,
                              void* arg) {
  TaskWakerObject* self = (TaskWakerObject*)self_obj;
  Py_VISIT(self->executor);
  Py_VISIT(self->task);
  return 0;
}

static int TaskWaker_clear(PyObject* self_obj) {
  TaskWakerObject* self = (TaskWakerObject*)self_obj;
  Py_CLEAR(self->executor);
  Py_CLEAR(self->task);
  return 0;
}

static void TaskWaker_dealloc(PyObject* self_obj) {
  PyObject_GC_UnTrack(self_obj);
  TaskWaker_clear(self_obj);
  PyObject_GC_Del(self_obj);
}

static PyTypeObject TaskWakerType = {
    PyVarObject_HEAD_INIT(nullptr, 0) "madsim_tpu.native.TaskWaker",
    sizeof(TaskWakerObject),
    0,                 // tp_itemsize
    TaskWaker_dealloc, // tp_dealloc
};

// _enqueue parity: if task._scheduled or task._finished: return;
// task._scheduled = True; queue.append(task). Returns -1 on error.
static int enqueue_task(PyObject* executor, PyObject* task) {
  PyObject* flag = PyObject_GetAttr(task, s_scheduled);
  if (!flag) return -1;
  int truthy = PyObject_IsTrue(flag);
  Py_DECREF(flag);
  if (truthy) return truthy < 0 ? -1 : 0;
  flag = PyObject_GetAttr(task, s_finished);
  if (!flag) return -1;
  truthy = PyObject_IsTrue(flag);
  Py_DECREF(flag);
  if (truthy) return truthy < 0 ? -1 : 0;
  if (PyObject_SetAttr(task, s_scheduled, Py_True) < 0) return -1;
  PyObject* queue = PyObject_GetAttr(executor, s_queue);
  if (!queue) return -1;
  int rc = PyList_Append(queue, task);
  Py_DECREF(queue);
  return rc;
}

// Truthiness of an attribute; -1 on error.
static int attr_true(PyObject* obj, PyObject* name) {
  PyObject* v = PyObject_GetAttr(obj, name);
  if (!v) return -1;
  int t = PyObject_IsTrue(v);
  Py_DECREF(v);
  return t;
}

// task._finished = True; task.node.tasks.pop(task, None);
// then join_future.set_result(value) / set_exception(exc).
static int finish_task(PyObject* task, PyObject* method, PyObject* payload) {
  if (PyObject_SetAttr(task, s_finished, Py_True) < 0) return -1;
  PyObject* node = PyObject_GetAttr(task, s_node);
  if (!node) return -1;
  PyObject* tasks = PyObject_GetAttr(node, s_tasks);
  Py_DECREF(node);
  if (!tasks) return -1;
  if (PyDict_Contains(tasks, task) > 0 && PyDict_DelItem(tasks, task) < 0) {
    Py_DECREF(tasks);
    return -1;
  }
  Py_DECREF(tasks);
  PyObject* fut = PyObject_GetAttr(task, s_join_future);
  if (!fut) return -1;
  PyObject* r = PyObject_CallMethodObjArgs(fut, method, payload, nullptr);
  Py_DECREF(fut);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

// run_ready(executor, tls, SimFuture, Cancelled, PENDING, rng_capsule)
static PyObject* py_run_ready(PyObject*, PyObject* args) {
  PyObject *ex, *tls, *simfut_t, *cancelled_t, *pending, *rng_capsule;
  if (!PyArg_ParseTuple(args, "OOOOOO", &ex, &tls, &simfut_t, &cancelled_t,
                        &pending, &rng_capsule))
    return nullptr;
  RngState* st = rng_from(rng_capsule);
  if (!st) return nullptr;
  PyObject* queue = PyObject_GetAttr(ex, s_queue);  // one list for the run
  if (!queue) return nullptr;
  PyObject* time_obj = PyObject_GetAttr(ex, s_time);
  if (!time_obj) {
    Py_DECREF(queue);
    return nullptr;
  }
  long long polls = 0;
  int failed = 0;

  for (;;) {
    PyObject* unc = PyObject_GetAttr(ex, s_uncaught);
    if (!unc) { failed = 1; break; }
    int has_unc = unc != Py_None;
    Py_DECREF(unc);
    if (has_unc) break;

    Py_ssize_t n = PyList_GET_SIZE(queue);
    if (n == 0) {
      // Resolve parked yields once the ready batch drains (yield_now
      // keeps the timer path's ordering — see the Python loop).
      PyObject* ylist = PyObject_GetAttr(ex, s_yields);
      if (!ylist) { failed = 1; break; }
      if (PyList_GET_SIZE(ylist) == 0) { Py_DECREF(ylist); break; }
      PyObject* fresh = PyList_New(0);
      if (!fresh || PyObject_SetAttr(ex, s_yields, fresh) < 0) {
        Py_XDECREF(fresh); Py_DECREF(ylist); failed = 1; break;
      }
      Py_DECREF(fresh);
      Py_ssize_t yn = PyList_GET_SIZE(ylist);
      for (Py_ssize_t i = 0; i < yn && !failed; ++i) {
        PyObject* fut = PyList_GET_ITEM(ylist, i);  // borrowed
        PyObject* r =
            PyObject_CallMethodObjArgs(fut, s_set_result, Py_None, nullptr);
        if (!r) failed = 1; else Py_DECREF(r);
      }
      Py_DECREF(ylist);
      if (!failed) {
        int noop = attr_true(ex, s_noop_waiting);
        if (noop < 0) failed = 1;
        else if (noop) {
          PyObject* r =
              PyObject_CallMethodObjArgs(ex, s_after_noop, nullptr);
          if (!r) failed = 1; else Py_DECREF(r);
        }
      }
      if (failed) break;
      continue;
    }

    // Seeded uniform pick + swap-remove (gen_range parity: u64 % width).
    Py_ssize_t idx = (Py_ssize_t)(ms_rng_next_u64(st) % (uint64_t)n);
    PyObject* task = PyList_GET_ITEM(queue, idx);  // borrowed
    Py_INCREF(task);                               // our working ref
    if (idx != n - 1) {
      PyObject* last = PyList_GET_ITEM(queue, n - 1);
      Py_INCREF(last);
      // PyList_SetItem steals the new ref AND decrefs the displaced item.
      PyList_SetItem(queue, idx, last);
      Py_INCREF(task);
      PyList_SetItem(queue, n - 1, task);
    }
    if (PyList_SetSlice(queue, n - 1, n, nullptr) < 0) {
      Py_DECREF(task); failed = 1; break;
    }
    if (PyObject_SetAttr(task, s_scheduled, Py_False) < 0) {
      Py_DECREF(task); failed = 1; break;
    }
    PyObject* info = PyObject_GetAttr(task, s_node);
    if (!info) { Py_DECREF(task); failed = 1; break; }
    int killed = attr_true(info, s_killed);
    int cancelled = killed > 0 ? 0 : attr_true(task, s_cancelled);
    int finished =
        (killed > 0 || cancelled > 0) ? 0 : attr_true(task, s_finished);
    if (killed < 0 || cancelled < 0 || finished < 0) {
      Py_DECREF(info); Py_DECREF(task); failed = 1; break;
    }
    if (killed || cancelled || finished) {
      Py_DECREF(info);
      PyObject* r = PyObject_CallMethodObjArgs(task, s_drop, nullptr);
      Py_DECREF(task);
      if (!r) { failed = 1; break; }
      Py_DECREF(r);
      continue;
    }
    int paused = attr_true(info, s_paused);
    if (paused < 0) { Py_DECREF(info); Py_DECREF(task); failed = 1; break; }
    if (paused) {
      PyObject* parked = PyObject_GetAttr(info, s_paused_tasks);
      Py_DECREF(info);
      if (!parked) { Py_DECREF(task); failed = 1; break; }
      int rc = PyList_Append(parked, task);
      Py_DECREF(parked);
      Py_DECREF(task);
      if (rc < 0) { failed = 1; break; }
      continue;
    }
    Py_DECREF(info);

    // tls.task push (getattr default None, like the Python loop).
    PyObject* prev = PyObject_GetAttr(tls, s_task);
    if (!prev) {
      if (!PyErr_ExceptionMatches(PyExc_AttributeError)) {
        Py_DECREF(task); failed = 1; break;
      }
      PyErr_Clear();
      prev = Py_None;
      Py_INCREF(prev);
    }
    if (PyObject_SetAttr(tls, s_task, task) < 0) {
      Py_DECREF(prev); Py_DECREF(task); failed = 1; break;
    }
    polls += 1;

    // ---- inlined _poll --------------------------------------------------
    PyObject* coro = PyObject_GetAttr(task, s_coro);
    PyObject* yielded = nullptr;
    if (coro) {
      PyObject* pend = PyObject_GetAttr(task, s_pending_exc);
      if (pend && pend != Py_None) {
        if (PyObject_SetAttr(task, s_pending_exc, Py_None) == 0)
          yielded = PyObject_CallMethodObjArgs(coro, s_throw, pend, nullptr);
        Py_DECREF(pend);
      } else if (pend) {
        Py_DECREF(pend);
        yielded = PyObject_CallMethodObjArgs(coro, s_send, Py_None, nullptr);
      }
      Py_DECREF(coro);
    }

    if (yielded == Py_None) {
      // Stdlib Task semantics: a bare None yield = "resume next loop
      // iteration" (aiohttp's helpers.noop and friends). Swap in the
      // executor's yield_now future and fall through to the normal
      // SimFuture attach below.
      Py_DECREF(yielded);
      yielded = PyObject_CallMethodObjArgs(ex, s_yield_now, nullptr);
    }

    if (!yielded) {
      if (PyErr_ExceptionMatches(PyExc_StopIteration)) {
        PyObject *etype, *evalue, *etb;
        PyErr_Fetch(&etype, &evalue, &etb);
        PyErr_NormalizeException(&etype, &evalue, &etb);
        PyObject* value = evalue ? PyObject_GetAttr(evalue, s_value) : nullptr;
        Py_XDECREF(etype); Py_XDECREF(evalue); Py_XDECREF(etb);
        if (!value) failed = 1;
        else {
          if (finish_task(task, s_set_result, value) < 0) failed = 1;
          Py_DECREF(value);
        }
      } else if (PyErr_ExceptionMatches(cancelled_t)) {
        PyErr_Clear();
        PyObject* r = PyObject_CallMethodObjArgs(task, s_drop, nullptr);
        if (!r) failed = 1; else Py_DECREF(r);
      } else if (PyErr_Occurred()) {
        PyObject *etype, *evalue, *etb;
        PyErr_Fetch(&etype, &evalue, &etb);
        PyErr_NormalizeException(&etype, &evalue, &etb);
        if (etb) PyException_SetTraceback(evalue, etb);
        if (finish_task(task, s_set_exception, evalue) < 0 ||
            PyObject_SetAttr(ex, s_uncaught, evalue) < 0)
          failed = 1;
        Py_XDECREF(etype); Py_XDECREF(evalue); Py_XDECREF(etb);
      } else {
        failed = 1;  // coro attr missing
      }
    } else if (PyObject_IsInstance(yielded, simfut_t) > 0) {
      PyObject* epoch_obj = PyObject_GetAttr(task, s_wake_epoch);
      long long epoch = epoch_obj ? PyLong_AsLongLong(epoch_obj) : -1;
      Py_XDECREF(epoch_obj);
      PyObject* res = PyObject_GetAttr(yielded, s_result);
      PyObject* exc = res ? PyObject_GetAttr(yielded, s_exception) : nullptr;
      if (!res || !exc) {
        Py_XDECREF(res); Py_XDECREF(exc); failed = 1;
      } else {
        int done = (res != pending) || (exc != Py_None);
        Py_DECREF(res); Py_DECREF(exc);
        if (done) {
          // add_done_callback on a done future fires synchronously; the
          // epoch just captured always matches.
          if (enqueue_task(ex, task) < 0) failed = 1;
        } else {
          TaskWakerObject* waker =
              PyObject_GC_New(TaskWakerObject, &TaskWakerType);
          if (!waker) failed = 1;
          else {
            Py_INCREF(ex); waker->executor = ex;
            Py_INCREF(task); waker->task = task;
            waker->epoch = epoch;
            PyObject_GC_Track((PyObject*)waker);
            PyObject* cbs = PyObject_GetAttr(yielded, s_callbacks);
            if (!cbs || PyList_Append(cbs, (PyObject*)waker) < 0) failed = 1;
            Py_XDECREF(cbs);
            Py_DECREF(waker);
          }
        }
      }
      Py_DECREF(yielded);
    } else if (PyErr_Occurred()) {
      Py_DECREF(yielded);
      failed = 1;  // IsInstance error
    } else {
      // Foreign awaitable: shared Python diagnostic path.
      PyObject* r = PyObject_CallMethodObjArgs(ex, s_foreign_yield, task,
                                               yielded, nullptr);
      Py_DECREF(yielded);
      if (!r) failed = 1; else Py_DECREF(r);
    }

    // tls.task pop (the Python loop's `finally`) — preserve any pending
    // exception across the restore, exactly like a finally block.
    {
      PyObject *etype = nullptr, *evalue = nullptr, *etb = nullptr;
      if (PyErr_Occurred()) PyErr_Fetch(&etype, &evalue, &etb);
      if (PyObject_SetAttr(tls, s_task, prev) < 0) {
        if (etype) PyErr_Clear();  // the original error wins
        failed = 1;
      }
      if (etype) PyErr_Restore(etype, evalue, etb);
    }
    Py_DECREF(prev);
    Py_DECREF(task);
    if (failed) break;

    // Per-poll 50-100 ns jitter (task.rs:176-178), same draw as gen_range.
    long long delta = 50 + (long long)(ms_rng_next_u64(st) % 50);
    PyObject* t_ns = PyObject_GetAttr(time_obj, s_elapsed_ns);
    if (!t_ns) { failed = 1; break; }
    PyObject* delta_obj = PyLong_FromLongLong(delta);
    PyObject* new_t = delta_obj ? PyNumber_Add(t_ns, delta_obj) : nullptr;
    Py_DECREF(t_ns);
    Py_XDECREF(delta_obj);
    if (!new_t || PyObject_SetAttr(time_obj, s_elapsed_ns, new_t) < 0)
      failed = 1;
    Py_XDECREF(new_t);
    if (failed) break;
  }

  Py_DECREF(time_obj);
  Py_DECREF(queue);
  // Flush the poll counter even on the error path.
  PyObject* pc = PyObject_GetAttr(ex, s_poll_count);
  if (pc) {
    PyObject* add = PyLong_FromLongLong(polls);
    PyObject* total = add ? PyNumber_Add(pc, add) : nullptr;
    Py_DECREF(pc);
    Py_XDECREF(add);
    if (total) {
      PyObject_SetAttr(ex, s_poll_count, total);
      Py_DECREF(total);
    }
  } else if (!failed) {
    failed = 1;
  }
  if (failed) return nullptr;
  Py_RETURN_NONE;
}

static PyMethodDef core_methods[] = {
    {"run_ready", py_run_ready, METH_VARARGS,
     "run_ready(executor, tls, SimFuture, Cancelled, PENDING, rng) — "
     "Executor.run_all_ready in C, bit-identical to the Python loop"},
    {"rng_new", py_rng_new, METH_VARARGS,
     "rng_new(k0, k1, counter) -> RngState capsule"},
    {"rng_next_u64", py_rng_next_u64, METH_VARARGS, "fresh u64 block"},
    {"rng_next_u32", py_rng_next_u32, METH_VARARGS, "buffered u32 draw"},
    {"rng_gen_range", py_rng_gen_range, METH_VARARGS,
     "gen_range(rng, lo, hi) -> lo + u64 % (hi-lo)"},
    {"rng_random", py_rng_random, METH_VARARGS, "uniform [0,1), 53-bit"},
    {"rng_get_state", py_rng_get_state, METH_VARARGS,
     "(counter, buf|None) — parity checks / introspection"},
    {"threefry_draw", py_threefry_draw, METH_VARARGS,
     "threefry_draw(k0, k1, counter) -> u64 block (x1<<32|x0)"},
    {"derive_stream", py_derive_stream, METH_VARARGS,
     "derive_stream(k0, k1, stream) -> u64 derived key"},
    {"heap_new", py_heap_new, METH_NOARGS, "new timer heap capsule"},
    {"heap_push", py_heap_push, METH_VARARGS, "push(heap, deadline_ns, seq)"},
    {"heap_cancel", py_heap_cancel, METH_VARARGS, "cancel(heap, seq)"},
    {"heap_peek", py_heap_peek, METH_VARARGS,
     "peek(heap) -> earliest live deadline_ns | None"},
    {"heap_pop_due", py_heap_pop_due, METH_VARARGS,
     "pop_due(heap, now_ns) -> seq | None"},
    {"heap_len", py_heap_len, METH_VARARGS, "len(heap)"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef core_module = {PyModuleDef_HEAD_INIT, "_core",
                                         "madsim_tpu native host core",
                                         -1, core_methods};

PyMODINIT_FUNC PyInit__core(void) {
  struct {
    PyObject** slot;
    const char* name;
  } names[] = {
      {&s_queue, "queue"}, {&s_yields, "_yields"},
      {&s_uncaught, "_uncaught"}, {&s_scheduled, "_scheduled"},
      {&s_finished, "_finished"}, {&s_cancelled, "cancelled"},
      {&s_node, "node"}, {&s_killed, "killed"}, {&s_paused, "paused"},
      {&s_paused_tasks, "paused_tasks"}, {&s_task, "task"},
      {&s_pending_exc, "_pending_exc"}, {&s_coro, "coro"},
      {&s_send, "send"}, {&s_throw, "throw"}, {&s_drop, "drop"},
      {&s_set_result, "set_result"}, {&s_set_exception, "set_exception"},
      {&s_wake_epoch, "wake_epoch"}, {&s_result, "_result"},
      {&s_exception, "_exception"}, {&s_callbacks, "_callbacks"},
      {&s_join_future, "join_future"}, {&s_tasks, "tasks"},
      {&s_elapsed_ns, "elapsed_ns"}, {&s_poll_count, "poll_count"},
      {&s_time, "time"}, {&s_foreign_yield, "_foreign_yield"},
      {&s_value, "value"}, {&s_yield_now, "noop_yield"},
      {&s_noop_waiting, "_noop_waiting"}, {&s_after_noop, "_after_noop_drain"},
  };
  for (auto& e : names) {
    *e.slot = PyUnicode_InternFromString(e.name);
    if (!*e.slot) return nullptr;
  }
  TaskWakerType.tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC;
  TaskWakerType.tp_call = TaskWaker_call;
  TaskWakerType.tp_traverse = TaskWaker_traverse;
  TaskWakerType.tp_clear = TaskWaker_clear;
  if (PyType_Ready(&TaskWakerType) < 0) return nullptr;
  return PyModule_Create(&core_module);
}
