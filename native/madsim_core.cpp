// Native host-engine core for madsim_tpu.
//
// The reference's performance-critical host components are native Rust
// (SURVEY §2 ⚙): the Threefry-equivalent seeded RNG (madsim/src/sim/rand.rs),
// the timer wheel (time/mod.rs via naive_timer), and the scheduler's random
// ready-pick (utils/mpsc.rs:73-83). This file provides the same kernels,
// exposed two ways from one translation unit:
//
//   1. a plain C ABI (the ms_* functions) for non-Python consumers/tests;
//   2. a CPython extension module (`_core`) — the hot path. The C API is
//      used rather than ctypes because per-call marshalling overhead of
//      ctypes (~µs) exceeds the kernels' own cost and made the "native"
//      path slower than pure Python.
//
// Pure-Python fallbacks exist for every function here
// (madsim_tpu/native/__init__.py chooses at import); bit-exactness contract:
// threefry2x32 must match ops/threefry.py's numpy and jax implementations
// word-for-word (tested in tests/test_native.py), since host and device
// engines share RNG streams.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -I<python-include> \
//            -o _core.so madsim_core.cpp

#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_set>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Threefry-2x32, 20 rounds (Random123) — must match ops/threefry.py.
// ---------------------------------------------------------------------------

static const unsigned ROT[8] = {13, 15, 26, 6, 17, 29, 16, 24};

static inline uint32_t rotl32(uint32_t x, unsigned r) {
  return (x << r) | (x >> (32 - r));
}

static inline void threefry2x32(uint32_t k0, uint32_t k1, uint32_t c0,
                                uint32_t c1, uint32_t* out0, uint32_t* out1) {
  uint32_t x0 = c0 + k0;
  uint32_t x1 = c1 + k1;
  uint32_t ks[3] = {k0, k1, k0 ^ k1 ^ 0x1BD11BDAu};
  for (unsigned i = 0; i < 5; ++i) {
    for (unsigned r = 0; r < 4; ++r) {
      x0 += x1;
      x1 = rotl32(x1, ROT[4 * (i % 2) + r]);
      x1 ^= x0;
    }
    x0 += ks[(i + 1) % 3];
    x1 += ks[(i + 2) % 3] + (uint32_t)(i + 1);
  }
  *out0 = x0;
  *out1 = x1;
}

// Single draw of counter block `counter` → (x1 << 32) | x0, like draw_np.
uint64_t ms_threefry_draw(uint32_t k0, uint32_t k1, uint64_t counter) {
  uint32_t x0, x1;
  threefry2x32(k0, k1, (uint32_t)(counter & 0xFFFFFFFFu),
               (uint32_t)(counter >> 32), &x0, &x1);
  return ((uint64_t)x1 << 32) | (uint64_t)x0;
}

// Derive a stream key (derive_stream_np): encrypt the stream id.
uint64_t ms_derive_stream(uint32_t k0, uint32_t k1, uint64_t stream) {
  return ms_threefry_draw(k0, k1, stream);
}

// Batch draw for bulk consumers (fault-schedule generation etc.).
void ms_threefry_batch(uint32_t k0, uint32_t k1, uint64_t start_counter,
                       uint64_t n, uint64_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    out[i] = ms_threefry_draw(k0, k1, start_counter + i);
}

// ---------------------------------------------------------------------------
// Timer wheel: binary min-heap of (deadline_ns, seq) with lazy cancellation.
// Mirrors core/timewheel.py TimeRuntime semantics exactly.
// ---------------------------------------------------------------------------

struct TimerEntry {
  int64_t deadline_ns;
  uint64_t seq;
  bool operator>(const TimerEntry& o) const {
    if (deadline_ns != o.deadline_ns) return deadline_ns > o.deadline_ns;
    return seq > o.seq;
  }
};

struct TimerHeap {
  std::priority_queue<TimerEntry, std::vector<TimerEntry>,
                      std::greater<TimerEntry>>
      heap;
  std::unordered_set<uint64_t> cancelled;
};

void* ms_timerheap_new() { return new TimerHeap(); }

void ms_timerheap_free(void* h) { delete (TimerHeap*)h; }

// All accessors tolerate a null handle (the Python wrapper passes None after
// free during teardown races) by treating it as an empty heap.
void ms_timerheap_push(void* h, int64_t deadline_ns, uint64_t seq) {
  if (h) ((TimerHeap*)h)->heap.push(TimerEntry{deadline_ns, seq});
}

void ms_timerheap_cancel(void* h, uint64_t seq) {
  if (h) ((TimerHeap*)h)->cancelled.insert(seq);
}

// Earliest live deadline → 1 and *deadline set; 0 if empty.
int ms_timerheap_peek(void* h, int64_t* deadline_ns) {
  auto* th = (TimerHeap*)h;
  if (!th) return 0;
  while (!th->heap.empty()) {
    const TimerEntry& top = th->heap.top();
    auto it = th->cancelled.find(top.seq);
    if (it != th->cancelled.end()) {
      th->cancelled.erase(it);
      th->heap.pop();
      continue;
    }
    *deadline_ns = top.deadline_ns;
    return 1;
  }
  return 0;
}

// Pop the earliest live entry if deadline <= now → 1 and *seq set; else 0.
int ms_timerheap_pop_due(void* h, int64_t now_ns, uint64_t* seq) {
  auto* th = (TimerHeap*)h;
  if (!th) return 0;
  int64_t deadline;
  while (ms_timerheap_peek(h, &deadline)) {
    if (deadline > now_ns) return 0;
    *seq = th->heap.top().seq;
    th->heap.pop();
    return 1;
  }
  return 0;
}

uint64_t ms_timerheap_len(void* h) {
  return h ? ((TimerHeap*)h)->heap.size() : 0;
}

// ---------------------------------------------------------------------------
// Seeded random ready-pick (utils/mpsc.rs:73-83 analog): uniform index from
// one RNG draw, matching GlobalRng.gen_range's modulo method so Python and
// native scheduling decisions are interchangeable.
// ---------------------------------------------------------------------------

uint64_t ms_pick_index(uint32_t k0, uint32_t k1, uint64_t counter,
                       uint64_t len) {
  return ms_threefry_draw(k0, k1, counter) % len;
}

// ---------------------------------------------------------------------------
// Stateful RNG cursor (GlobalRng's hot path in one native object): key,
// draw counter, and the 32-bit half-block buffer live here, so a scheduler
// decision (gen_range) is ONE native call instead of four Python frames.
// Semantics are bit-identical to madsim_tpu/core/rng.py GlobalRng:
//   next_u64: fresh block, clears the u32 buffer
//   next_u32: buffered half first, else low half of a fresh block
//   gen_range(lo,hi): lo + next_u64() % (hi-lo)
//   random(): (next_u64() >> 11) * 2^-53
// ---------------------------------------------------------------------------

struct RngState {
  uint32_t k0, k1;
  uint64_t counter;
  uint32_t buf;
  int has_buf;
};

void* ms_rng_new(uint32_t k0, uint32_t k1, uint64_t counter) {
  auto* st = new RngState{k0, k1, counter, 0, 0};
  return st;
}

void ms_rng_free(void* p) { delete (RngState*)p; }

uint64_t ms_rng_next_u64(void* p) {
  auto* st = (RngState*)p;
  st->has_buf = 0;
  return ms_threefry_draw(st->k0, st->k1, st->counter++);
}

uint32_t ms_rng_next_u32(void* p) {
  auto* st = (RngState*)p;
  if (st->has_buf) {
    st->has_buf = 0;
    return st->buf;
  }
  uint64_t block = ms_threefry_draw(st->k0, st->k1, st->counter++);
  st->buf = (uint32_t)(block >> 32);
  st->has_buf = 1;
  return (uint32_t)(block & 0xFFFFFFFFu);
}

}  // extern "C"

// ===========================================================================
// CPython extension module bindings (the fast path).
// ===========================================================================

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static void heap_capsule_destructor(PyObject* capsule) {
  void* h = PyCapsule_GetPointer(capsule, "madsim.TimerHeap");
  if (h) ms_timerheap_free(h);
}

static TimerHeap* heap_from(PyObject* capsule) {
  return (TimerHeap*)PyCapsule_GetPointer(capsule, "madsim.TimerHeap");
}

static PyObject* py_threefry_draw(PyObject*, PyObject* args) {
  unsigned int k0, k1;
  unsigned long long counter;
  if (!PyArg_ParseTuple(args, "IIK", &k0, &k1, &counter)) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_threefry_draw(k0, k1, counter));
}

static PyObject* py_derive_stream(PyObject*, PyObject* args) {
  unsigned int k0, k1;
  unsigned long long stream;
  if (!PyArg_ParseTuple(args, "IIK", &k0, &k1, &stream)) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_derive_stream(k0, k1, stream));
}

static PyObject* py_heap_new(PyObject*, PyObject*) {
  return PyCapsule_New(ms_timerheap_new(), "madsim.TimerHeap",
                       heap_capsule_destructor);
}

static PyObject* py_heap_push(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long deadline;
  unsigned long long seq;
  if (!PyArg_ParseTuple(args, "OLK", &capsule, &deadline, &seq)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  ms_timerheap_push(h, deadline, seq);
  Py_RETURN_NONE;
}

static PyObject* py_heap_cancel(PyObject*, PyObject* args) {
  PyObject* capsule;
  unsigned long long seq;
  if (!PyArg_ParseTuple(args, "OK", &capsule, &seq)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  ms_timerheap_cancel(h, seq);
  Py_RETURN_NONE;
}

static PyObject* py_heap_peek(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  int64_t deadline;
  if (!ms_timerheap_peek(h, &deadline)) Py_RETURN_NONE;
  return PyLong_FromLongLong(deadline);
}

static PyObject* py_heap_pop_due(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long now;
  if (!PyArg_ParseTuple(args, "OL", &capsule, &now)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  uint64_t seq;
  if (!ms_timerheap_pop_due(h, now, &seq)) Py_RETURN_NONE;
  return PyLong_FromUnsignedLongLong(seq);
}

static PyObject* py_heap_len(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  TimerHeap* h = heap_from(capsule);
  if (!h) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_timerheap_len(h));
}

// -- RngState bindings ------------------------------------------------------

static void rng_capsule_destructor(PyObject* capsule) {
  void* p = PyCapsule_GetPointer(capsule, "madsim.RngState");
  if (p) ms_rng_free(p);
}

static RngState* rng_from(PyObject* capsule) {
  return (RngState*)PyCapsule_GetPointer(capsule, "madsim.RngState");
}

static PyObject* py_rng_new(PyObject*, PyObject* args) {
  unsigned int k0, k1;
  unsigned long long counter;
  if (!PyArg_ParseTuple(args, "IIK", &k0, &k1, &counter)) return nullptr;
  return PyCapsule_New(ms_rng_new(k0, k1, counter), "madsim.RngState",
                       rng_capsule_destructor);
}

static PyObject* py_rng_next_u64(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  return PyLong_FromUnsignedLongLong(ms_rng_next_u64(st));
}

static PyObject* py_rng_next_u32(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  return PyLong_FromUnsignedLong(ms_rng_next_u32(st));
}

static PyObject* py_rng_gen_range(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long lo, hi;
  if (!PyArg_ParseTuple(args, "OLL", &capsule, &lo, &hi)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  long long width = hi - lo;
  if (width <= 0) {
    PyErr_Format(PyExc_ValueError, "empty range [%lld, %lld)", lo, hi);
    return nullptr;
  }
  uint64_t v = ms_rng_next_u64(st);
  return PyLong_FromLongLong(lo + (long long)(v % (uint64_t)width));
}

static PyObject* py_rng_random(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  uint64_t v = ms_rng_next_u64(st);
  return PyFloat_FromDouble((double)(v >> 11) * 1.1102230246251565e-16);
}

static PyObject* py_rng_get_state(PyObject*, PyObject* args) {
  PyObject* capsule;
  if (!PyArg_ParseTuple(args, "O", &capsule)) return nullptr;
  RngState* st = rng_from(capsule);
  if (!st) return nullptr;
  if (st->has_buf)
    return Py_BuildValue("(KI)", (unsigned long long)st->counter,
                         (unsigned int)st->buf);
  return Py_BuildValue("(KO)", (unsigned long long)st->counter, Py_None);
}

static PyMethodDef core_methods[] = {
    {"rng_new", py_rng_new, METH_VARARGS,
     "rng_new(k0, k1, counter) -> RngState capsule"},
    {"rng_next_u64", py_rng_next_u64, METH_VARARGS, "fresh u64 block"},
    {"rng_next_u32", py_rng_next_u32, METH_VARARGS, "buffered u32 draw"},
    {"rng_gen_range", py_rng_gen_range, METH_VARARGS,
     "gen_range(rng, lo, hi) -> lo + u64 % (hi-lo)"},
    {"rng_random", py_rng_random, METH_VARARGS, "uniform [0,1), 53-bit"},
    {"rng_get_state", py_rng_get_state, METH_VARARGS,
     "(counter, buf|None) — parity checks / introspection"},
    {"threefry_draw", py_threefry_draw, METH_VARARGS,
     "threefry_draw(k0, k1, counter) -> u64 block (x1<<32|x0)"},
    {"derive_stream", py_derive_stream, METH_VARARGS,
     "derive_stream(k0, k1, stream) -> u64 derived key"},
    {"heap_new", py_heap_new, METH_NOARGS, "new timer heap capsule"},
    {"heap_push", py_heap_push, METH_VARARGS, "push(heap, deadline_ns, seq)"},
    {"heap_cancel", py_heap_cancel, METH_VARARGS, "cancel(heap, seq)"},
    {"heap_peek", py_heap_peek, METH_VARARGS,
     "peek(heap) -> earliest live deadline_ns | None"},
    {"heap_pop_due", py_heap_pop_due, METH_VARARGS,
     "pop_due(heap, now_ns) -> seq | None"},
    {"heap_len", py_heap_len, METH_VARARGS, "len(heap)"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef core_module = {PyModuleDef_HEAD_INIT, "_core",
                                         "madsim_tpu native host core",
                                         -1, core_methods};

PyMODINIT_FUNC PyInit__core(void) { return PyModule_Create(&core_module); }
