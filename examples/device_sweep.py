"""Device-engine sweep: the TPU-native seed-sweep workflow in one file.

The reference explores interleavings with one OS thread per seed
(`MADSIM_TEST_JOBS`); here thousands of seeded worlds advance per XLA
dispatch. This example runs the MadRaft-equivalent actor with an injected
double-vote bug under a kill/restart fault schedule, finds the failing
seeds, prints the repro banner, and replays the first failing seed as an
ordered event trace — the whole find→repro→inspect loop.

Run it::

    python examples/device_sweep.py             # default 4096 worlds
    python examples/device_sweep.py 65536       # bigger sweep
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from madsim_tpu.engine import (
    DeviceEngine, EngineConfig, FAULT_KILL, FAULT_RESTART,
    RaftActor, RaftDeviceConfig,
)
from madsim_tpu.parallel.sweep import sweep


def main(n_worlds: int = 4096) -> None:
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    # metrics=True: the device-resident flight recorder (docs/
    # observability.md) — per-world counters ride the sweep at zero
    # trajectory impact (metrics-on is bit-identical to metrics-off).
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=48,
                       t_limit_us=2_000_000, metrics=True)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    faults = np.array([[600_000, FAULT_KILL, 1, 0],
                       [1_200_000, FAULT_RESTART, 1, 0]], np.int32)

    # observe=: the live telemetry stream (docs/observability.md "The
    # sweep observatory") — one JSONL record per superstep read, tailable
    # while the sweep runs: python -m madsim_tpu.obs watch <file> --follow
    res = sweep(None, cfg, np.arange(n_worlds), faults=faults, engine=eng,
                chunk_steps=512, max_steps=8_000,
                checkpoint_path="/tmp/device_sweep.npz",
                checkpoint_every_chunks=4,
                observe="/tmp/device_sweep_telemetry.jsonl")
    # The one-paragraph operator rendering (seeds, bugs, utilization,
    # coverage, top drop causes) — no dataclass-repr grepping.
    print(res.summary())
    st = res.loop_stats
    print(f"orchestration: {st['chunks']} chunks in {st['dispatches']} host "
          f"dispatches ({st['chunks_per_dispatch']}x superstep fan-in); "
          f"host decision stall {st['host_decision_s']:.3f}s + device wait "
          f"{st['device_wait_s']:.3f}s of {st['loop_wall_s']:.3f}s loop wall")
    agg = res.metrics["aggregate"]
    print(f"fleet metrics: {agg['msgs_sent']} msgs sent, "
          f"{agg['msgs_delivered']} delivered, {agg['timer_fires']} timer "
          f"fires, {agg['drop_loss']} lost, "
          f"{sum(agg['fault_hist'])} faults injected")
    cov = res.coverage
    curve = cov.novelty_curve
    print(f"coverage: {cov.distinct_behaviors} distinct behaviors in "
          f"{cov.n_buckets} buckets (novelty "
          f"{int(curve[0]) if curve.size else 0}->"
          f"{int(curve[-1]) if curve.size else 0}; a still-rising curve "
          f"means the hunt had not saturated)"
          f"\ntelemetry: /tmp/device_sweep_telemetry.jsonl "
          f"(python -m madsim_tpu.obs watch ...)")
    if not res.failing_seeds:
        print("no failing seeds in this sweep — try more worlds")
        return
    print(res.repro_banner())

    seed = res.failing_seeds[0]
    print(f"\nreplaying seed {seed}:")
    trace = eng.trace(seed, max_steps=8_000, faults=faults)
    bug_step = next((i for i, e in enumerate(trace) if e.get("bug_raised")),
                    len(trace) - 1)
    for e in trace[max(0, bug_step - 5):bug_step + 1]:
        mark = "  <-- BUG" if e.get("bug_raised") else ""
        drop = " (dropped)" if e.get("dropped") else ""
        print(f"  t={e['t_us']:>9}us {e['kind']:<14} "
              f"{e['src']}->{e['dst']}{drop}{mark}")

    # Durable artifacts: a Perfetto-loadable timeline and a one-file
    # repro bundle the obs CLI replays verbatim (docs/observability.md).
    from madsim_tpu.obs import trace_to_chrome
    from madsim_tpu.obs.bundle import write_sweep_bundle
    from madsim_tpu.obs.timeline import dump_chrome

    dump_chrome(trace_to_chrome(trace, seed=seed), "/tmp/device_sweep_trace.json")
    bundle = write_sweep_bundle(
        "/tmp", seed=seed, actor="raft", actor_config=rcfg,
        engine_config=cfg, faults=faults, max_steps=8_000,
        error="RaftInvariantViolation: election safety",
        trace_path="/tmp/device_sweep_trace.json")
    print(f"\ntimeline: /tmp/device_sweep_trace.json (chrome://tracing)"
          f"\nrepro bundle: {bundle}"
          f"\n  replay: python -m madsim_tpu.obs replay --bundle {bundle}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4096)
