"""Greeter: the canonical example app (reference `madsim/examples/rpc.rs` +
`tonic-example/src/server.rs` analog).

Demonstrates the service-layer ergonomics in one file:

- ``@service`` / ``@rpc_method`` — handlers registered from method
  annotations, no hand-wired add_rpc_handler (`#[madsim::service]`);
- structured tracing spans — run with ``MADSIM_LOG=INFO`` to see every
  line stamped ``[t=<vtime> node=<id>/<name> task=<id>]``;
- the ``@main`` seed-sweep driver and fault injection: one client node is
  restarted mid-run and recovers via its init closure.

Run it::

    MADSIM_LOG=INFO python examples/greeter.py            # one seed
    MADSIM_TEST_NUM=10 python examples/greeter.py         # seed sweep
    MADSIM_TEST_CHECK_DETERMINISM=1 python examples/greeter.py
"""
import dataclasses
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu as ms
from madsim_tpu import time as vtime
from madsim_tpu.net import Endpoint, rpc, rpc_method, service

log = logging.getLogger("greeter")


# -- protocol ---------------------------------------------------------------

@dataclasses.dataclass
class HelloRequest:
    name: str


@dataclasses.dataclass
class HelloReply:
    message: str


@dataclasses.dataclass
class StatsRequest:
    pass


# -- server -----------------------------------------------------------------

@service
class Greeter:
    """Request types route from the @rpc_method annotations."""

    def __init__(self):
        self.greeted = 0

    @rpc_method
    async def say_hello(self, req: HelloRequest) -> HelloReply:
        self.greeted += 1
        log.info("greeting %s (#%d)", req.name, self.greeted)
        return HelloReply(message=f"Hello, {req.name}!")

    @rpc_method
    async def stats(self, req: StatsRequest) -> int:
        return self.greeted


# -- world ------------------------------------------------------------------

SERVER_ADDR = "10.0.0.1:50051"


async def run_client(name: str, n_greetings: int) -> int:
    ep = await Endpoint.bind("0.0.0.0:0")
    done = 0
    while done < n_greetings:
        try:
            reply = await rpc.call(ep, SERVER_ADDR,
                                   HelloRequest(name=f"{name}-{done}"),
                                   timeout=1.0)
            assert reply.message == f"Hello, {name}-{done}!"
            done += 1
        except TimeoutError:
            log.info("%s: timeout, retrying", name)
            await vtime.sleep(0.1)
    return done


@ms.main
async def main():
    h = ms.Handle.current()
    greeter = Greeter()

    async def server_init():
        await greeter.serve(SERVER_ADDR)
        log.info("greeter serving on %s", SERVER_ADDR)
        await vtime.sleep(3600)

    h.create_node(name="server", ip="10.0.0.1", init=server_init)

    results = ms.sync.Queue()

    def client_init(name: str, n: int):
        async def body():
            results.put_nowait((name, await run_client(name, n)))

        return body

    clients = [
        h.create_node(name=f"cli{i}", ip=f"10.0.0.{i + 2}",
                      init=client_init(f"cli{i}", 5))
        for i in range(3)
    ]

    # Chaos: restart one client mid-run; its init closure restarts the
    # workload from scratch (`tonic-example/src/server.rs:281-332` pattern).
    await vtime.sleep(ms.rand.thread_rng().gen_range_f64(0.05, 0.25))
    victim = ms.rand.thread_rng().choice(clients)
    log.info("restarting %s", victim.name)
    h.restart(victim)

    finished = set()
    while len(finished) < 3:
        name, count = await results.get()
        assert count == 5
        finished.add(name)

    # The supervisor (main node) has no network identity — audits run on a
    # node like everything else.
    auditor = h.create_node(name="auditor", ip="10.0.0.99")

    async def audit() -> int:
        ep = await Endpoint.bind("0.0.0.0:0")
        return await rpc.call(ep, SERVER_ADDR, StatsRequest(), timeout=1.0)

    total = await auditor.spawn(audit())
    print(f"world done at t={vtime.monotonic():.3f}s: "
          f"{total} greetings served (>= 15; restarts re-greet)")
    assert total >= 15
    return total


if __name__ == "__main__":
    main()
