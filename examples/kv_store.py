"""Durable KV store: crash-recovery + the missing-fsync bug hunt.

The FsSim consumer example (alongside examples/greeter.py for RPC and
examples/device_sweep.py for the batched engine): a write-ahead-logged
key-value server whose node is killed and restarted mid-run. Node reset
power-fails the simulated disk — unsynced writes are LOST, synced ones
survive (`madsim_tpu/fs.py`; the semantics the reference stubs as TODO at
`madsim/src/sim/fs.rs:38-53`) — and the init closure recovers the table
from the WAL like a restarted process.

The subject under test is the store's durability contract: *an
acknowledged put must survive a crash*.

- default mode: the server fsyncs the WAL BEFORE acking — sweeps stay
  clean no matter when the crash lands;
- ``--buggy``: the server acks without ever syncing, so any crash after
  an ack can lose the acknowledged write; the seed sweep finds one and
  prints the failing seed to reproduce.

Run it::

    python examples/kv_store.py                    # clean: all seeds pass
    python examples/kv_store.py --buggy            # durability bug found
    MADSIM_TEST_SEED=7 python examples/kv_store.py --buggy   # repro one seed
"""
import dataclasses
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu as ms
from madsim_tpu import fs
from madsim_tpu import time as vtime
from madsim_tpu.net import Endpoint, rpc, rpc_method, service

log = logging.getLogger("kv")

SERVER_ADDR = "10.0.0.1:4000"
N_KEYS = 8


class DurabilityViolation(AssertionError):
    pass


# -- protocol ---------------------------------------------------------------

@dataclasses.dataclass
class Put:
    key: str
    value: str


@dataclasses.dataclass
class Get:
    key: str


# -- server -----------------------------------------------------------------

@service
class KvServer:
    """WAL-backed table. A fresh instance per node incarnation (the init
    closure constructs one), so recovery is a real read-the-log path."""

    def __init__(self, sync_before_ack: bool):
        self.sync_before_ack = sync_before_ack
        self.table = {}
        self.wal = None
        self.off = 0

    async def recover(self) -> None:
        self.wal = await fs.File.open_or_create("wal")
        data = await self.wal.read_all()
        self.off = len(data)
        for line in data.decode().splitlines():
            key, _, value = line.partition("=")
            self.table[key] = value
        log.info("recovered %d keys (%d WAL bytes)", len(self.table), self.off)

    @rpc_method
    async def put(self, req: Put) -> bool:
        record = f"{req.key}={req.value}\n".encode()
        await self.wal.write_all_at(record, self.off)
        self.off += len(record)
        if self.sync_before_ack:
            await self.wal.sync_all()  # durable BEFORE the ack
        self.table[req.key] = req.value
        return True  # the ack: this write is now promised to survive

    @rpc_method
    async def get(self, req: Get):
        return self.table.get(req.key)


# -- world ------------------------------------------------------------------

async def world(buggy: bool):
    h = ms.Handle.current()

    async def server_init():
        srv = KvServer(sync_before_ack=not buggy)
        await srv.recover()
        await srv.serve(SERVER_ADDR)
        await vtime.sleep(3600)

    server = h.create_node(name="kv", ip="10.0.0.1", init=server_init)
    done = ms.sync.SimFuture()

    async def client_init():
        ep = await Endpoint.bind("0.0.0.0:0")
        acked = []
        for i in range(N_KEYS):
            while True:  # retry across crashes; puts are idempotent
                try:
                    ok = await rpc.call(ep, SERVER_ADDR,
                                        Put(f"k{i}", f"v{i}"), timeout=0.5)
                    assert ok
                    acked.append(i)
                    break
                except TimeoutError:
                    await vtime.sleep(0.05)
            await vtime.sleep(0.02)
        # Audit: every acknowledged write must still be readable.
        for i in acked:
            while True:
                try:
                    got = await rpc.call(ep, SERVER_ADDR, Get(f"k{i}"),
                                         timeout=0.5)
                    break
                except TimeoutError:
                    await vtime.sleep(0.05)
            if got != f"v{i}":
                done.set_exception(DurabilityViolation(
                    f"acked put k{i}=v{i} lost after crash (got {got!r})"))
                return
        done.set_result(len(acked))

    h.create_node(name="client", ip="10.0.0.2", init=client_init)

    # Chaos: crash-restart the server a few times inside the put window.
    # Kill power-fails the disk (unsynced WAL bytes vanish); restart runs
    # server_init, which recovers from what the WAL durably holds.
    rng = ms.rand.thread_rng()
    for _ in range(3):
        await vtime.sleep(rng.gen_range_f64(0.02, 0.2))
        log.info("supervisor: restarting kv node at t=%.3f", vtime.monotonic())
        h.restart(server)

    return await vtime.timeout(60, done)


def main():
    logging.basicConfig(level=os.environ.get("MADSIM_LOG", "WARNING"))
    buggy = "--buggy" in sys.argv
    seed = int(os.environ.get("MADSIM_TEST_SEED", "0"))
    count = int(os.environ.get("MADSIM_TEST_NUM", "20"))
    found = None
    for s in range(seed, seed + count):
        try:
            acked = ms.run(world(buggy), seed=s, time_limit=120)
            print(f"seed {s}: clean ({acked} acked writes survived)")
        except DurabilityViolation as exc:
            print(f"seed {s}: DURABILITY BUG — {exc}")
            print(f"note: run with MADSIM_TEST_SEED={s} to reproduce")
            found = s
            break
    if buggy and found is None:
        print("no violation in this sweep; widen MADSIM_TEST_NUM")
        return 1
    if not buggy and found is not None:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
