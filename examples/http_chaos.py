"""Unmodified pip HTTP stacks under chaos: the event-loop drop-in demo.

The deepest interception layer in action (the reference's flagship proof
is upstream tokio-postgres running over sim sockets,
`madsim-tokio-postgres/src/socket.rs:6-13`; here it is pip **aiohttp** —
server AND client — with not one line changed): under ``aio.patched()``,
``loop.create_connection`` / ``create_server`` / ``sock_*`` land on the
simulated network, so ~40 kLoC of third-party HTTP machinery runs on
virtual time with seeded chaos.

The system under test is a tiny "inventory" web service with a
read-modify-write race: ``/take?n=`` reads the stock level, "thinks"
(awaits) for a moment, then writes the decrement. Two clients hammer it
concurrently while the network partitions and heals.

- default mode: the handler holds a lock across the read-think-write —
  stock never goes negative, every seed passes;
- ``--buggy``: no lock. Most interleavings still pass; the seeded
  scheduler sweep finds one where two requests interleave mid-think and
  oversell the stock, then prints the seed so you can replay the exact
  trajectory.

Run it::

    python examples/http_chaos.py                 # clean: all seeds pass
    python examples/http_chaos.py --buggy         # oversell found + seed
    MADSIM_TEST_SEED=<s> python examples/http_chaos.py --buggy  # replay
"""
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu as ms
from madsim_tpu import task as mtask
from madsim_tpu import time as vtime
from madsim_tpu.net import NetSim
from madsim_tpu.shims import aio

STOCK = 5


class OversellViolation(AssertionError):
    pass


def build_world(buggy: bool):
    from aiohttp import ClientError, ClientSession, ClientTimeout, web

    async def world():
        h = ms.Handle.current()

        async def server_init():
            state = {"stock": STOCK}
            lock = asyncio.Lock()

            async def take(request):
                async def read_think_write():
                    level = state["stock"]
                    await vtime.sleep(0.002)  # the "think": races live here
                    if level <= 0:
                        return web.json_response({"ok": False, "left": 0})
                    state["stock"] = level - 1
                    return web.json_response({"ok": True,
                                              "left": state["stock"]})

                if buggy:
                    return await read_think_write()
                async with lock:
                    return await read_think_write()

            app = web.Application()
            app.router.add_post("/take", take)
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "10.0.0.1", 80).start()
            await vtime.sleep(1e6)

        srv = h.create_node(name="shop", ip="10.0.0.1", init=server_init)
        buyers = [h.create_node(name=f"buyer{i}", ip=f"10.0.0.{2 + i}")
                  for i in range(2)]

        async def chaos():
            sim = ms.simulator(NetSim)
            for _ in range(3):
                await vtime.sleep(0.7)
                sim.disconnect2(srv.id, buyers[0].id)
                await vtime.sleep(0.4)
                sim.connect2(srv.id, buyers[0].id)

        mtask.spawn(chaos())

        async def buyer():
            bought = 0
            async with ClientSession(
                    timeout=ClientTimeout(total=0.5)) as sess:
                for _ in range(STOCK):
                    # Jittered shopping cadence: whether two buyers'
                    # think-windows overlap depends on the seed — most
                    # interleavings are innocent, some oversell.
                    await vtime.sleep(ms.rand.random() * 0.2)
                    while True:
                        try:
                            async with sess.post(
                                    "http://10.0.0.1/take") as resp:
                                body = await resp.json()
                            break
                        except (ClientError, TimeoutError,
                                ConnectionError, asyncio.TimeoutError):
                            await vtime.sleep(0.15)
                    if not body["ok"]:
                        return bought
                    bought += 1
            return bought

        handles = [b.spawn(buyer()) for b in buyers]
        total = sum([await t for t in handles])
        if total > STOCK:
            raise OversellViolation(
                f"sold {total} units of a stock of {STOCK}")
        return total

    return world


def main() -> int:
    buggy = "--buggy" in sys.argv
    seed = int(os.environ.get("MADSIM_TEST_SEED", "0"))
    count = int(os.environ.get("MADSIM_TEST_NUM", "40"))
    world = build_world(buggy)

    with aio.patched():
        for s in range(seed, seed + count):
            rt = ms.Runtime(seed=s)
            rt.set_time_limit(120.0)
            try:
                total = rt.block_on(world())
            except OversellViolation as exc:
                print(f"seed {s}: OVERSELL — {exc}")
                print(f"note: run with MADSIM_TEST_SEED={s} "
                      "MADSIM_TEST_NUM=1 to replay this trajectory")
                return 1
            print(f"seed {s}: sold {total}/{STOCK} — ok")
    print(f"{count} seeds clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
