"""Benchmark suite: all five BASELINE.json configs + backend crosscheck.

The headline (BASELINE.json metric): MadRaft 3-node seeds/sec on the batched
device engine, and its speedup over single-seed host (CPU) execution — the
reference's one-thread-per-seed model (`madsim/src/sim/runtime/builder.rs:
118-136`). The reference publishes no numbers (BASELINE.md); the other
configs mirror its harness definitions:

  1. rpc_pingpong       2-node RPC ping-pong, single seed, host engine
                        (`madsim/benches/rpc.rs:11-26`)
  1b. rpc_real          the same ping-pong on the production backend over
                        real loopback TCP — the transport the reference's
                        criterion bench actually measures
  2. madraft_3node      3-node leader election, W seeds vmapped (headline)
  3. grpc_chaos         gRPC echo under partition chaos
                        (`tonic-example/src/server.rs:281-332`)
  4. postgres_skew      postgres client<->server with clock-skew injection
  5. madraft_5node      5-node log replication x failure-schedule sweep
                        (device engine, per-world fault schedules)

Plus two cross-engine validations VERDICT r1 required:
  - crosscheck          TPU vs CPU bit-exact trajectory equality
  - time_to_first_bug   host vs device finding the same injected Raft bug
                        (buggy_double_vote), wall-clock to first detection

Prints ONE JSON line (driver contract): the headline metric with the other
config results embedded under "configs". Details go to stderr.
"""
import argparse
import json
import subprocess
import sys
import time as walltime

import numpy as np

SIM_SECONDS = 1.0  # virtual seconds of Raft per seed (headline config)
# Payload sweep mirroring `benches/rpc.rs:28-54`, shared by the sim and
# production RPC configs so their curves stay directly comparable.
PAYLOAD_SIZES = (16, 256, 4096, 65536, 1 << 20)


class BenchPing:
    """RPC request type for the ping-pong configs. Module-level because the
    real backend pickles payloads onto the wire (std-mode bincode analog)."""

    __slots__ = ("n",)

    def __init__(self, n):
        self.n = n


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def xla_cost_record(eng, state, max_steps: int) -> dict:
    """XLA's own per-step cost model for the compiled (donated) run path.

    Lowers ``eng._run`` at the given state's shapes (no execution — safe
    on a donated state) and records ``cost_analysis()`` flops/bytes and
    ``memory_analysis()`` sizes into the bench result, so per-iteration
    performance accounting is a tracked artifact per round (PRISM-style)
    instead of a one-off measurement. ``make smoke`` asserts the keys
    exist; the tier-1 op-budget test (tests/test_queue_insert.py) gates
    flops per world-step against a recorded budget. Never raises: on any
    analysis failure the keys are present with null values plus an
    ``error`` string, keeping the bench record intact.
    """
    import numpy as _np

    out = {"n_worlds": None, "max_steps": max_steps,
           "packed": bool(getattr(eng.cfg, "packed", False)),
           "flops_per_step": None, "flops_per_world_step": None,
           "bytes_accessed_per_step": None,
           "argument_size_bytes": None, "output_size_bytes": None,
           "temp_size_bytes": None, "aliased_bytes": None,
           "state_bytes_per_world": None,
           "peak_bytes_est": None, "peak_over_state": None}
    try:
        w = int(_np.asarray(state.now).shape[0])
        out["n_worlds"] = w
        comp = eng._run.lower(state, max_steps).compile()
        ca = comp.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else {}
        flops = ca.get("flops")
        if flops is not None:
            out["flops_per_step"] = float(flops)
            out["flops_per_world_step"] = round(float(flops) / w, 2)
        ba = ca.get("bytes accessed")
        if ba is not None:
            out["bytes_accessed_per_step"] = float(ba)
        ma = comp.memory_analysis()
        arg = int(ma.argument_size_in_bytes)
        out.update({
            "argument_size_bytes": arg,
            "output_size_bytes": int(ma.output_size_in_bytes),
            "temp_size_bytes": int(ma.temp_size_in_bytes),
            "aliased_bytes": int(ma.alias_size_in_bytes),
        })
        peak = (arg + int(ma.output_size_in_bytes)
                + int(ma.temp_size_in_bytes) - int(ma.alias_size_in_bytes))
        out["peak_bytes_est"] = peak
        if arg:
            out["peak_over_state"] = round(peak / arg, 4)
        # The packed-lane regression surface (tracked by bench_diff and
        # gated by the budget ledger's state_bytes_per_world entry).
        out["state_bytes_per_world"] = round(arg / w, 2)
    except Exception as exc:  # noqa: BLE001 — observability must not fail the bench
        out["error"] = f"{type(exc).__name__}: {exc}"
    return out


# ---------------------------------------------------------------------------
# Config 1: RPC ping-pong, 2 nodes, single seed, host engine
# ---------------------------------------------------------------------------

def bench_rpc_pingpong(n_rounds: int) -> dict:
    """Round-trips/sec of the built-in RPC over the simulated network, plus
    payload-throughput points mirroring `benches/rpc.rs:28-54` sizes."""
    import madsim_tpu as ms
    from madsim_tpu.net import Endpoint, rpc
    from madsim_tpu import time as simtime

    Ping = BenchPing

    def world(payload: bytes, rounds: int):
        rt = ms.Runtime(seed=1)

        async def main():
            h = ms.Handle.current()

            async def server_init():
                ep = await Endpoint.bind("10.0.0.1:9000")

                # The reference's criterion handler consumes the data and
                # returns an empty sidecar (`benches/rpc.rs:35-38`); echoing
                # it back would double the measured wire traffic.
                async def handle(req, data):
                    return Ping(req.n + 1), b""

                rpc.add_rpc_handler_with_data(ep, Ping, handle)
                await simtime.sleep(1e6)

            h.create_node(name="server", ip="10.0.0.1", init=server_init)
            client = h.create_node(name="client", ip="10.0.0.2")
            done = ms.sync.SimFuture()

            async def client_body():
                ep = await Endpoint.bind("10.0.0.2:0")
                # Datagram sends are not retransmitted: the very first call
                # can race the server's bind, so retry it until the server
                # is up (the reference's tests use the same retry idiom).
                while True:
                    try:
                        await rpc.call_with_data(
                            ep, "10.0.0.1:9000", Ping(0), payload, timeout=0.2)
                        break
                    except TimeoutError:
                        pass
                # Virtual latency measured over the counted rounds only
                # (startup + retry traffic excluded).
                t_start = simtime.monotonic()
                for i in range(rounds):
                    await rpc.call_with_data(
                        ep, "10.0.0.1:9000", Ping(i), payload, timeout=5.0)
                done.set_result(simtime.monotonic() - t_start)

            client.spawn(client_body())
            return await done

        return rt.block_on(main())

    t0 = walltime.perf_counter()
    virt = world(b"", n_rounds)
    dt = walltime.perf_counter() - t0
    out = {"empty_rpc_roundtrips_per_sec": round(n_rounds / dt, 2),
           "virtual_latency_ms": round(virt / n_rounds * 1e3, 3)}

    data_rounds = max(16, n_rounds // 8)
    rates = {}
    for size in PAYLOAD_SIZES:
        payload = b"\xab" * size
        t0 = walltime.perf_counter()
        world(payload, data_rounds)
        dt = walltime.perf_counter() - t0
        rates[f"{size}B"] = round(data_rounds * size / dt / 1e6, 2)
    out["payload_mb_per_sec"] = rates
    log(f"rpc_pingpong: {out}")
    return out


# ---------------------------------------------------------------------------
# Config 1b: the same RPC ping-pong on the PRODUCTION backend — direct
# parity with the reference's criterion bench, which measures the std TCP
# transport over loopback (`madsim/benches/rpc.rs:11-56`).
# ---------------------------------------------------------------------------

def bench_rpc_real(n_rounds: int) -> dict:
    import os

    prior_backend = os.environ.get("MADSIM_BACKEND")
    prior_transport = os.environ.get("MADSIM_REAL_TRANSPORT")
    os.environ["MADSIM_BACKEND"] = "real"
    # Pin the first leg to TCP explicitly so a pre-set uds env can't turn
    # the tcp-vs-uds comparison into uds-vs-uds with a wrong label.
    os.environ["MADSIM_REAL_TRANSPORT"] = "tcp"
    try:
        import madsim_tpu as ms
        from madsim_tpu.net import Endpoint, rpc

        async def world(payload: bytes, rounds: int) -> float:
            server = await Endpoint.bind("127.0.0.1:0")

            # Reference handler shape: consume data, empty response sidecar
            # (`benches/rpc.rs:35-38`).
            async def handle(req, data):
                return BenchPing(req.n + 1), b""

            rpc.add_rpc_handler_with_data(server, BenchPing, handle)
            client = await Endpoint.bind("127.0.0.1:0")
            addr = server.local_addr()
            t0 = walltime.perf_counter()
            for i in range(rounds):
                await rpc.call_with_data(client, addr, BenchPing(i),
                                         payload, timeout=10.0)
            dt = walltime.perf_counter() - t0
            client.close()
            server.close()
            return dt

        dt = ms.run(world(b"", n_rounds))
        out = {"empty_rpc_roundtrips_per_sec": round(n_rounds / dt, 2),
               "empty_rpc_latency_us": round(dt / n_rounds * 1e6, 1)}
        rates = {}
        data_rounds = max(16, n_rounds // 8)
        for size in PAYLOAD_SIZES:
            dt = ms.run(world(b"\xab" * size, data_rounds))
            rates[f"{size}B"] = round(data_rounds * size / dt / 1e6, 2)
        out["payload_mb_per_sec"] = rates
        # The alternative wire transports on the same world: kernel UDS
        # instead of loopback TCP, and the shm bulk leg (UDS control +
        # shared-memory rings for >=32 KiB payloads — docs/transports.md).
        os.environ["MADSIM_REAL_TRANSPORT"] = "uds"
        dt = ms.run(world(b"", n_rounds))
        out["uds_empty_rpc_roundtrips_per_sec"] = round(n_rounds / dt, 2)
        out["uds_empty_rpc_latency_us"] = round(dt / n_rounds * 1e6, 1)
        os.environ["MADSIM_REAL_TRANSPORT"] = "shm"
        dt = ms.run(world(b"", n_rounds))
        out["shm_empty_rpc_latency_us"] = round(dt / n_rounds * 1e6, 1)
        shm_rates = {}
        for size in PAYLOAD_SIZES:
            dt = ms.run(world(b"\xab" * size, data_rounds))
            shm_rates[f"{size}B"] = round(data_rounds * size / dt / 1e6, 2)
        out["shm_payload_mb_per_sec"] = shm_rates
        log(f"rpc_real (production backend, tcp + uds + shm): {out}")
        return out
    finally:
        if prior_backend is None:
            os.environ.pop("MADSIM_BACKEND", None)
        else:
            os.environ["MADSIM_BACKEND"] = prior_backend
        if prior_transport is None:
            os.environ.pop("MADSIM_REAL_TRANSPORT", None)
        else:
            os.environ["MADSIM_REAL_TRANSPORT"] = prior_transport


# ---------------------------------------------------------------------------
# Config 2 (headline): MadRaft 3-node, device engine vs host single-seed
# ---------------------------------------------------------------------------

def host_seed_rate(n_seeds: int) -> dict:
    """Single-seed host engine baseline with an explicit per-event cost
    model (VERDICT r2 item 7): seeds/s, scheduler polls ("events")/s, and
    µs/poll, so the vs_baseline denominator is a measured quantity."""
    import madsim_tpu as ms
    from madsim_tpu.models.raft import RaftCluster, RaftOptions

    async def world():
        from madsim_tpu import time as simtime

        cluster = RaftCluster(3, RaftOptions(persist=False))
        try:
            await cluster.wait_for_leader(timeout=SIM_SECONDS)
        except TimeoutError:
            pass
        now = simtime.monotonic()
        if now < SIM_SECONDS:
            await simtime.sleep(SIM_SECONDS - now)
        return cluster.leader()

    t0 = walltime.perf_counter()
    elected = 0
    polls = 0
    for seed in range(n_seeds):
        rt = ms.Runtime(seed=seed)
        if rt.block_on(world()) is not None:
            elected += 1
        polls += rt.handle.task.poll_count
    dt = walltime.perf_counter() - t0
    out = {
        "seeds_per_sec": round(n_seeds / dt, 2),
        "events_per_sec": round(polls / dt, 1),
        "us_per_event": round(dt / polls * 1e6, 3),
        "events_per_seed": round(polls / n_seeds, 1),
        "elected": elected,
        "n_seeds": n_seeds,
    }
    log(f"host: {n_seeds} seeds in {dt:.2f}s ({out['seeds_per_sec']} seeds/s, "
        f"{out['events_per_sec']:.0f} events/s, {out['us_per_event']} us/event, "
        f"{elected}/{n_seeds} elected)")
    return out


def device_seed_rate(n_worlds: int, max_steps: int = 2_000) -> float:
    import jax

    from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig

    # State footprint sizes HBM traffic (queue + logs are rewritten every
    # step) and is the single biggest throughput knob. Measured over 262k
    # seeds (observe() reports qmax): queue high-water mark is 18 slots,
    # so queue_cap=28 carries 10 slots of headroom at ~1.9x the rate of
    # 64; the election-only headline never appends log entries, so
    # log_cap=4 replaces the default 16. The run still asserts overflow==0.
    rcfg = RaftDeviceConfig(n=3, log_cap=4)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=28,
                       t_limit_us=int(SIM_SECONDS * 1e6))
    eng = DeviceEngine(RaftActor(rcfg), cfg)

    # Warmup: compile init + run on the same shapes.
    warm = eng.run(eng.init(np.arange(n_worlds)), max_steps=max_steps)
    jax.block_until_ready(warm)

    # Best of 3 timed runs: the chip is reached through a shared tunnel and
    # single-run numbers wobble ±10%; the best run is the least-contended
    # measurement of the same fixed computation.
    dt = float("inf")
    for _ in range(3):
        t0 = walltime.perf_counter()
        state = eng.init(np.arange(1_000_000, 1_000_000 + n_worlds))
        state = eng.run(state, max_steps=max_steps)
        jax.block_until_ready(state)
        dt = min(dt, walltime.perf_counter() - t0)

    obs = eng.observe(state)
    assert not obs["active"].any(), "worlds did not finish; raise max_steps"
    assert not obs["bug"].any(), "clean config must not flag bugs"
    assert not obs["overflow"].any(), \
        f"queue overflow (qmax={int(obs['qmax'].max())}): raise queue_cap"
    elected = int(obs["leader_elected"].sum())
    log(f"device[{jax.default_backend()}]: {n_worlds} seeds in {dt:.2f}s "
        f"({n_worlds / dt:.0f} seeds/s, {elected}/{n_worlds} elected, "
        f"mean {obs['steps'].mean():.0f} steps/world)")
    return n_worlds / dt


# ---------------------------------------------------------------------------
# Config 3: gRPC echo under partition chaos
# ---------------------------------------------------------------------------

def bench_grpc_chaos(n_clients: int, sim_seconds: float) -> dict:
    """Echoes/sec completed while a supervisor partitions and heals the
    network and restarts client nodes (`tonic-example/src/server.rs:281-332`
    semantics: progress must continue across chaos)."""
    import madsim_tpu as ms
    from madsim_tpu.net import NetSim
    from madsim_tpu.shims import grpc_sim
    from madsim_tpu import time as simtime

    class Echo:
        SERVICE_NAME = "bench.Echo"

        @grpc_sim.unary
        async def Say(self, request, context):
            return request

        @grpc_sim.bidi
        async def Stream(self, requests, context):
            async for r in requests:
                yield r

    completed = [0]

    def world():
        rt = ms.Runtime(seed=7)
        rt.set_time_limit(sim_seconds * 10 + 60)

        async def main():
            h = ms.Handle.current()
            server = grpc_sim.Server().add_service(Echo())

            async def serve():
                await server.serve(("10.0.0.1", 50051))

            srv = h.create_node(name="server", ip="10.0.0.1", init=serve)

            def client_init(i):
                async def body():
                    while True:
                        try:
                            ch = await grpc_sim.Channel.connect(("10.0.0.1", 50051))
                            while True:
                                rsp = await simtime.timeout(
                                    1.0, ch.unary("/bench.Echo/Say", completed[0]))
                                assert rsp is not None
                                completed[0] += 1
                        except (OSError, TimeoutError, grpc_sim.Status):
                            await simtime.sleep(0.05)

                return body

            clients = [h.create_node(name=f"cli{i}", ip=f"10.0.0.{i + 2}",
                                     init=client_init(i))
                       for i in range(n_clients)]

            sim = ms.simulator(NetSim)
            from madsim_tpu import rand
            rng = rand.thread_rng()
            t_end = sim_seconds
            while simtime.monotonic() < t_end:
                await simtime.sleep(rng.gen_range_f64(0.1, 0.3))
                act = rng.gen_range(0, 3)
                victim = clients[rng.gen_range(0, n_clients)]
                if act == 0:
                    sim.disconnect2(srv.id, victim.id)
                    await simtime.sleep(rng.gen_range_f64(0.05, 0.2))
                    sim.connect2(srv.id, victim.id)
                elif act == 1:
                    ms.Handle.current().restart(victim)
                else:
                    sim.disconnect(victim.id)   # clog the whole node
                    await simtime.sleep(rng.gen_range_f64(0.05, 0.2))
                    sim.connect(victim.id)

        rt.block_on(main())

    t0 = walltime.perf_counter()
    world()
    dt = walltime.perf_counter() - t0
    assert completed[0] > 0, "no gRPC progress under chaos"
    out = {"echoes_completed": completed[0],
           "echoes_per_wall_sec": round(completed[0] / dt, 2),
           "sim_seconds": sim_seconds, "n_clients": n_clients}
    log(f"grpc_chaos: {out}")
    return out


# ---------------------------------------------------------------------------
# Config 4: postgres client<->server with clock-skew injection
# ---------------------------------------------------------------------------

def bench_postgres_skew(n_queries: int) -> dict:
    """Queries/sec against the in-sim postgres server while the client and
    server wall clocks are skewed apart (and re-skewed mid-run). Asserts the
    client observes the skew via the server's now() and that queries keep
    succeeding — wall-clock skew must not affect protocol correctness."""
    import madsim_tpu as ms
    from madsim_tpu.shims import postgres
    from madsim_tpu import time as simtime

    stats = {}

    def world():
        rt = ms.Runtime(seed=3)
        rt.set_time_limit(600)

        async def main():
            h = ms.Handle.current()
            server = postgres.SimPostgresServer()

            async def serve():
                await server.serve(("10.0.0.1", 5432))

            srv = h.create_node(name="pg", ip="10.0.0.1", init=serve)
            app = h.create_node(name="app", ip="10.0.0.2")
            # Inject: server clock 30 s ahead, client 5 s behind.
            h.set_clock_skew(srv, +30.0)
            h.set_clock_skew(app, -5.0)
            done = ms.sync.SimFuture()

            async def body():
                while True:  # server bind race: retry the initial connect
                    try:
                        conn = await postgres.connect("10.0.0.1", user="bench")
                        break
                    except OSError:
                        await simtime.sleep(0.05)
                await conn.execute("CREATE TABLE kv (k, v)")
                # Extended-query protocol: all inserts/reads go through
                # Parse/Bind/Execute prepared statements, each pair inside
                # a transaction (VERDICT r2 item 5 done-criteria).
                ins = await conn.prepare("INSERT INTO kv VALUES ($1, $2)")
                sel = await conn.prepare("SELECT v FROM kv WHERE k = $1")
                for i in range(n_queries):
                    async with conn.transaction():
                        await conn.execute_prepared(ins, [str(i), f"v{i}"])
                    rows = await conn.query_prepared(sel, [str(i)])
                    assert rows[0].get("v") == f"v{i}"
                    if i == n_queries // 2:
                        # Hot re-skew mid-connection, plus a transaction
                        # rollback: its write must not survive.
                        ms.Handle.current().set_clock_skew(srv, -45.0)
                        try:
                            async with conn.transaction():
                                await conn.execute_prepared(
                                    ins, ["doomed", "x"])
                                raise RuntimeError("force rollback")
                        except RuntimeError:
                            pass
                        assert await conn.query_prepared(sel, ["doomed"]) == []
                srv_now = await conn.query("SELECT now()")
                await conn.close()
                done.set_result((srv_now[0][0], simtime.system_time()))

            app.spawn(body())
            srv_now, app_now = await done
            stats["server_now"] = srv_now
            stats["client_observed_skew_s"] = round(
                float(srv_now) - app_now, 1) if _floatable(srv_now) else None

        rt.block_on(main())

    t0 = walltime.perf_counter()
    world()
    dt = walltime.perf_counter() - t0
    out = {"queries_per_wall_sec": round(2 * n_queries / dt, 2),
           "n_queries": 2 * n_queries,
           "client_observed_skew_s": stats.get("client_observed_skew_s")}
    log(f"postgres_skew: {out}")
    return out


def _floatable(v) -> bool:
    try:
        float(v)
        return True
    except (TypeError, ValueError):
        return False


# ---------------------------------------------------------------------------
# Config 5: MadRaft 5-node log replication x failure-schedule sweep (device)
# ---------------------------------------------------------------------------

def make_fault_schedules(n_worlds: int, n_nodes: int, t_limit_us: int,
                         seed: int = 0) -> np.ndarray:
    """Per-world fault rows [time_us, op, a, b]: one kill+restart pair and
    one link clog+unclog window per world, at schedule-swept times."""
    from madsim_tpu.engine.core import (
        FAULT_KILL, FAULT_RESTART, FAULT_CLOG_LINK, FAULT_UNCLOG_LINK)

    rng = np.random.default_rng(seed)
    t_kill = rng.integers(t_limit_us // 10, t_limit_us // 2, n_worlds)
    t_restart = t_kill + rng.integers(50_000, t_limit_us // 4, n_worlds)
    victim = rng.integers(0, n_nodes, n_worlds)
    t_clog = rng.integers(t_limit_us // 10, t_limit_us // 2, n_worlds)
    t_unclog = t_clog + rng.integers(50_000, t_limit_us // 4, n_worlds)
    a = rng.integers(0, n_nodes, n_worlds)
    b = (a + 1 + rng.integers(0, n_nodes - 1, n_worlds)) % n_nodes
    rows = np.stack([
        np.stack([t_kill, np.full(n_worlds, FAULT_KILL), victim,
                  np.zeros(n_worlds)], axis=1),
        np.stack([t_restart, np.full(n_worlds, FAULT_RESTART), victim,
                  np.zeros(n_worlds)], axis=1),
        np.stack([t_clog, np.full(n_worlds, FAULT_CLOG_LINK), a, b], axis=1),
        np.stack([t_unclog, np.full(n_worlds, FAULT_UNCLOG_LINK), a, b], axis=1),
    ], axis=1).astype(np.int32)
    return rows


def bench_madraft_5node(n_worlds: int) -> dict:
    """5-node Raft with client proposals + per-world failure schedules,
    swept on the device engine (BASELINE config 5; the reference's analog is
    MADSIM_TEST_NUM=100000 with chaos, one thread per seed)."""
    import jax

    from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig
    from madsim_tpu.parallel.sweep import sweep

    t_limit_us = 3_000_000
    rcfg = RaftDeviceConfig(n=5, n_proposals=4, log_cap=16,
                            propose_start_us=1_000_000,
                            propose_interval_us=200_000)
    # Measured high-water mark: 58 slots over 100k fault-scheduled seeds;
    # 64 runs ~13% faster than 80 and the overflow assert below guards the
    # headroom. chunk_steps: 512 used to beat 128 because each chunk cost
    # a host sync; with superstepped dispatch (r8) the host pays one
    # dispatch per ~K chunks, so fine chunks now WIN — 16 measured ~15%
    # faster than 512 (utilization 0.94 vs 0.77: stragglers waste <16
    # masked steps instead of <512) at a 5.9x chunk-per-dispatch fold.
    cfg = EngineConfig(n_nodes=5, outbox_cap=6, queue_cap=64,
                       t_limit_us=t_limit_us)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    faults = make_fault_schedules(n_worlds, 5, t_limit_us)

    # Cost-model record for this engine config (capped batch: the model
    # is per-shape, flops_per_world_step is the tracked quantity; the
    # probe state dies before the timed sweep allocates).
    rec_w = min(n_worlds, 4_096)
    xla_cost = xla_cost_record(
        eng, eng.init(np.arange(rec_w), faults=faults[:rec_w]), 2_000)

    # Observability record (docs/observability.md): the same config swept
    # metrics-on at the capped batch. metrics is a STATIC engine knob, so
    # this uses its own engine and the timed sweep below stays the exact
    # metrics-off program; trajectories are bit-identical either way
    # (tier-1, tests/test_obs.py).
    import dataclasses as _dc

    eng_m = DeviceEngine(RaftActor(rcfg), _dc.replace(cfg, metrics=True))
    res_m = sweep(None, eng_m.cfg, np.arange(rec_w), faults=faults[:rec_w],
                  engine=eng_m, chunk_steps=16, max_steps=20_000)
    sim_metrics = {"n_worlds": rec_w, **res_m.metrics["aggregate"]}
    # Behavior-coverage rollup of the same probe (docs/observability.md
    # "reading the novelty curve"; `make smoke` asserts
    # distinct_behaviors > 1).
    coverage = res_m.coverage.to_json()
    del eng_m, res_m

    # Warmup compile on the SAME batch shape as the timed run (jit
    # specializes on shapes; a smaller warmup batch would leave the real
    # compile inside the timed window).
    res = sweep(None, cfg, np.arange(n_worlds), faults=faults, engine=eng,
                chunk_steps=16, max_steps=20_000)

    t0 = walltime.perf_counter()
    res = sweep(None, cfg, np.arange(n_worlds), faults=faults, engine=eng,
                chunk_steps=16, max_steps=20_000)
    dt = walltime.perf_counter() - t0

    obs = res.observations
    n_bug = int(obs["bug"].sum())
    assert n_bug == 0, f"clean 5-node config flagged {n_bug} bugs"
    assert not obs["overflow"].any(), \
        f"queue overflow (qmax={int(obs['qmax'].max())}): raise queue_cap"
    committed = obs["max_commit"]
    hist = res.n_active_history
    out = {"seeds_per_sec": round(n_worlds / dt, 2),
           "n_worlds": n_worlds,
           "mean_committed": round(float(committed.mean()), 2),
           "worlds_with_commits": int((committed > 0).sum()),
           "elected_frac": round(float(obs["leader_elected"].mean()), 4),
           # Occupancy telemetry (docs/perf.md "world recycling"): measured
           # per-chunk, not inferred from a one-off steps histogram.
           "world_utilization": round(res.world_utilization, 4),
           "n_chunks": int(hist.size),
           "n_active_history": [int(x) for x in hist],
           # Orchestration breakdown of the timed sweep (docs/perf.md
           # "Pipelined orchestration"): dispatch counts, superstep
           # fan-in, and the host/device wall split of the chunk loop.
           "sweep_loop": res.loop_stats,
           "xla_cost": xla_cost,
           # Fleet-aggregate simulation metrics of the metrics-on probe
           # sweep (docs/observability.md; asserted by `make smoke`).
           "sim_metrics": sim_metrics,
           # Behavior-coverage ledger rollup of the same probe sweep.
           "coverage": coverage}
    log(f"madraft_5node[{jax.default_backend()}]: {dt:.2f}s  {out}")
    return out


def bench_fleet_sweep(n_worlds: int) -> dict:
    """2-worker local fleet fabric vs single-host sweep on the same
    seeds (docs/fleet.md): measures the fabric's orchestration overhead
    — lease RPCs, heartbeats, per-range dispatch — so bench_diff tracks
    it round over round. The bitwise contract (fleet == single-host on
    ids/bugs/observations) is asserted inline; this bench exists for
    the RATE delta, the tier-1 chaos matrix owns the contract."""
    import jax

    from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig
    from madsim_tpu.fleet import fleet_sweep
    from madsim_tpu.parallel.sweep import sweep

    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_500_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    seeds = np.arange(n_worlds)
    kw = dict(chunk_steps=64, max_steps=100_000)
    n_ranges = 8

    # Warmup compiles both paths on the real shapes.
    single = sweep(None, cfg, seeds, engine=eng, **kw)
    fleet = fleet_sweep(None, cfg, seeds, engine=eng, n_workers=2,
                        range_size=-(-n_worlds // n_ranges), **kw)
    assert np.array_equal(single.bug, fleet.bug), \
        "fleet result diverged from single-host (bitwise contract)"

    t0 = walltime.perf_counter()
    single = sweep(None, cfg, seeds, engine=eng, **kw)
    dt_single = walltime.perf_counter() - t0
    t0 = walltime.perf_counter()
    fleet = fleet_sweep(None, cfg, seeds, engine=eng, n_workers=2,
                        range_size=-(-n_worlds // n_ranges), **kw)
    dt_fleet = walltime.perf_counter() - t0

    stats = fleet.loop_stats["fleet"]
    leases = max(1, stats["leases_issued"])
    out = {"n_worlds": n_worlds,
           "n_workers": 2,
           "n_ranges": stats["ranges"],
           "single_seeds_per_sec": round(n_worlds / dt_single, 2),
           "fleet_seeds_per_sec": round(n_worlds / dt_fleet, 2),
           # >0 = the fabric costs throughput vs one big batch (smaller
           # per-range batches + lease bookkeeping); the tracked number.
           # ISSUE 17 gate: <= 0.15 on this config (sessions + prefetch
           # + coalesced control plane; docs/fleet.md "Fabric cost
           # model").
           "fabric_overhead_frac": round(1 - dt_single / dt_fleet, 4),
           "leases_issued": stats["leases_issued"],
           "heartbeats": stats["heartbeats"],
           "fabric_ticks": stats["fabric_ticks"],
           # Per-phase breakdown of the fleet wall (docs/fleet.md
           # "Fabric cost model"): where each lease's time went, and
           # the counted control-plane discipline per lease.
           "acquire_ms": round(1000.0 * stats["acquire_s"] / leases, 3),
           "sweep_ms": round(1000.0 * stats["sweep_s"] / leases, 3),
           "merge_ms": round(1000.0 * stats.get("merge_s", 0.0), 3),
           "rpcs_per_lease": stats["rpcs_per_lease"],
           "control_rpcs_per_lease": stats["control_rpcs_per_lease"],
           "session_reuse_hits": stats["session_reuse_hits"],
           "leases_prefetched": stats["leases_prefetched"],
           "grouped_leases": stats["grouped_leases"]}
    log(f"fleet_sweep[{jax.default_backend()}]: single {dt_single:.2f}s "
        f"fleet {dt_fleet:.2f}s  {out}")
    return out


def bench_guided_hunt(budget: int) -> dict:
    """Coverage-guided schedule search vs the matched random-mutation
    baseline (docs/search.md; search/hunts.py), on the two canonical
    hunts the ROADMAP item-2 gate names:

    - pair family: seeds-to-bug under ``stop_on_first_bug`` (the bug is
      reachable ONLY through mutation; guided ~73 vs random ~409);
    - seeded raft double-vote: failing seeds found at the full budget
      (first-bug ties are expected — generation-1 children are shared
      by construction — so the hunting-power metric is bugs-at-budget).

    Both legs also record the novelty-curve area (sum of the per-chunk
    cumulative distinct-behavior counts — a bigger area = coverage grew
    earlier), tracked round over round by tools/bench_diff.py. The
    pair-leg ordering (guided strictly first) is asserted inline; the
    raft margin is gated end-to-end by `make fuzz-demo`.
    """
    import jax

    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.parallel.sweep import sweep
    from madsim_tpu.search.hunts import pair_hunt, paxos_hunt, raft_hunt

    def leg(hunt, stop_first: bool) -> dict:
        eng = DeviceEngine(hunt.actor, hunt.cfg)
        out = {"budget": budget}
        for mode, guided in (("guided", True), ("random", False)):
            t0 = walltime.perf_counter()
            res = sweep(None, hunt.cfg, np.arange(budget), engine=eng,
                        faults=hunt.template, stop_on_first_bug=stop_first,
                        search=hunt.search(guided), **hunt.sweep_kw)
            dt = walltime.perf_counter() - t0
            f = res.failing_seeds
            out[f"{mode}_seeds_to_bug"] = (int(f[0]) + 1) if f else None
            out[f"{mode}_bugs_found"] = len(f)
            out[f"{mode}_novelty_area"] = int(
                res.coverage.novelty_curve.sum())
            out[f"{mode}_generations"] = int(res.search.generations)
            out[f"{mode}_corpus_size"] = int(res.search.corpus_size)
            # Evolution-observatory accounting (obs/lineage.py): the
            # deepest ancestry chain materialized and the per-operator
            # outcome table — tracked round over round by
            # tools/bench_diff.py as the operator-credit signal.
            out[f"{mode}_lineage_depth"] = int(res.search.lineage_depth())
            out[f"{mode}_operator_stats"] = res.search.operator_stats
            out[f"{mode}_wall_s"] = round(dt, 3)
            if guided:
                # Dispatch economics of the guided leg (docs/perf.md
                # "Whole-hunt residency"; make smoke asserts the
                # seeds_per_dispatch / epochs_on_device keys).
                out["sweep_loop"] = res.loop_stats
        g, r = out["guided_seeds_to_bug"], out["random_seeds_to_bug"]
        # seeds-to-bug ratio; an un-found random leg counts as budget+1
        # (a lower bound on the true gap).
        if g is not None:
            out["speedup_lower_bound"] = round(
                (r if r is not None else budget + 1) / g, 2)
        return out

    pair = leg(pair_hunt(), stop_first=True)
    assert pair["guided_seeds_to_bug"] is not None, \
        "guided search missed the pair-family bug inside the budget"
    r = pair["random_seeds_to_bug"]
    assert r is None or pair["guided_seeds_to_bug"] < r, \
        f"guided ({pair['guided_seeds_to_bug']}) did not beat random " \
        f"({r}) on the pair family"
    raft = leg(raft_hunt(), stop_first=False)
    # The actorc-compiled DSL-only family (docs/actorc.md): multi-decree
    # Paxos, forgetful-acceptor consistency violation. Same gate shape
    # as the pair leg — guided must reach the bug strictly first
    # (measured: guided ~191, random not found in 512).
    paxos = leg(paxos_hunt(), stop_first=True)
    assert paxos["guided_seeds_to_bug"] is not None, \
        "guided search missed the Paxos forgetful-acceptor bug inside " \
        "the budget"
    rp = paxos["random_seeds_to_bug"]
    assert rp is None or paxos["guided_seeds_to_bug"] < rp, \
        f"guided ({paxos['guided_seeds_to_bug']}) did not beat random " \
        f"({rp}) on the Paxos family"
    out = {"n_seed_budget": budget, "pair": pair, "raft": raft,
           "paxos": paxos}
    log(f"guided_hunt[{jax.default_backend()}]: {out}")
    return out


def bench_guided_fleet(budget: int) -> dict:
    """Cross-range corpus exchange vs independent-corpus fleet
    (docs/fleet.md "Corpus exchange"), on the pair family at a range
    size DELIBERATELY too small to climb the staircase alone: 64-seed
    ranges under a ~73-seed bug mean an independent fleet can never
    reach it — partition-dependence made visible — while the exchanged
    fleet chains corpus progress across epochs and finds it. Records
    seeds-to-bug both ways (the acceptance gate: exchanged reaches the
    bug in no more seeds than the best independent range, asserted
    inline), bugs at budget, merge/publish traffic, and the exchange
    overhead fraction tools/bench_diff.py tracks round over round."""
    import jax

    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.fleet import ExchangeConfig, fleet_sweep
    from madsim_tpu.fleet.lease import split_ranges
    from madsim_tpu.search.hunts import pair_hunt

    hunt = pair_hunt()
    eng = DeviceEngine(hunt.actor, hunt.cfg)
    seeds = np.arange(budget)
    range_size = 64
    kw = dict(engine=eng, faults=hunt.template, search=hunt.search(True),
              stop_on_first_bug=True, **hunt.sweep_kw)

    def best_seeds_to_bug(res):
        """Fewest seeds INTO any one range before its first find (the
        per-range analog of guided_hunt's seeds-to-bug; None = no range
        found the bug)."""
        fails = sorted(int(s) for s in res.failing_seeds)
        per = [s - r.lo + 1 for r in split_ranges(budget, range_size)
               for s in fails if r.lo <= s < r.hi]
        return min(per) if per else None

    # Warmup compiles the engine + search programs on the real shapes so
    # the timed runs measure orchestration, not XLA.
    fleet_sweep(None, hunt.cfg, seeds[:range_size], n_workers=1,
                range_size=range_size, **kw)
    t0 = walltime.perf_counter()
    independent = fleet_sweep(None, hunt.cfg, seeds, n_workers=2,
                              range_size=range_size, **kw)
    dt_ind = walltime.perf_counter() - t0
    t0 = walltime.perf_counter()
    exchanged = fleet_sweep(None, hunt.cfg, seeds, n_workers=2,
                            range_size=range_size,
                            exchange=ExchangeConfig(every=1), **kw)
    dt_exc = walltime.perf_counter() - t0

    st = exchanged.loop_stats["fleet"]
    ind_best = best_seeds_to_bug(independent)
    exc_best = best_seeds_to_bug(exchanged)
    out = {
        "budget": budget, "range_size": range_size, "exchange_every": 1,
        "independent_seeds_to_bug": ind_best,
        "exchanged_seeds_to_bug": exc_best,
        "independent_bugs_found": len(independent.failing_seeds),
        "exchanged_bugs_found": len(exchanged.failing_seeds),
        "exchanged_first_global_seed": (
            int(exchanged.failing_seeds[0]) + 1
            if exchanged.failing_seeds else None),
        "epochs_merged": st["epochs_merged"],
        "merge_inserts": st["merge_inserts"],
        "publishes": st["publishes"],
        "publish_bytes": st["publish_bytes"],
        "broadcast_bytes": st["broadcast_bytes"],
        "merged_corpus_size": int(exchanged.search.corpus_size),
        # Fleet-level evolution observatory (obs/lineage.py): ancestry
        # depth across the exchanged epochs and the merged per-operator
        # outcome table (each range's table summed).
        "lineage_depth": int(exchanged.search.lineage_depth()),
        "operator_stats": exchanged.search.operator_stats,
        "independent_wall_s": round(dt_ind, 3),
        "exchanged_wall_s": round(dt_exc, 3),
        # >0 = the exchange costs wall time vs the independent fleet
        # (epoch barriers serialize rounds + merge/broadcast work).
        "exchange_overhead_frac": round(1 - dt_ind / dt_exc, 4),
    }
    # The acceptance gate: the exchanged fleet reaches the bug in no
    # more seeds-into-a-range than the best independent range (an
    # un-found independent leg counts as range_size+1, a lower bound).
    assert exc_best is not None, \
        "exchanged fleet missed the pair bug — exchange is not chaining " \
        "corpus progress across epochs (retune fleet/exchange.py)"
    assert exc_best <= (ind_best if ind_best is not None
                        else range_size + 1), \
        f"exchanged fleet needed {exc_best} seeds vs best independent " \
        f"range's {ind_best}"
    assert len(exchanged.failing_seeds) >= len(independent.failing_seeds)
    log(f"guided_fleet[{jax.default_backend()}]: {out}")
    return out


def bench_minimize_bug(n_rows: int) -> dict:
    """Batched ddmin schedule minimization on the known-minimal
    synthetic bug (docs/triage.md; triage/synthetic.py): an ``n_rows``
    restart schedule whose failure needs exactly two rows. Tracks the
    minimizer's round/candidate economy and wall time round over round
    (tools/bench_diff.py) — the metric is how cheaply a hunt's failure
    turns into a 1-minimal repro, not seeds/s."""
    import jax

    from madsim_tpu.engine import DeviceEngine
    from madsim_tpu.triage import (PairRestartActor, PairRestartConfig,
                                   minimize, pair_schedule)
    from madsim_tpu.triage.synthetic import engine_config

    acfg = PairRestartConfig()
    cfg = engine_config(acfg)
    eng = DeviceEngine(PairRestartActor(acfg), cfg)
    need = (n_rows // 6, (2 * n_rows) // 3)
    faults = pair_schedule(n_rows=n_rows, need=need, acfg=acfg)
    kw = dict(engine=eng, chunk_steps=32, max_steps=4_000)

    # Warmup: compiles every candidate-batch bucket the loop will use.
    res = minimize(None, cfg, 7, faults, **kw)
    t0 = walltime.perf_counter()
    res = minimize(None, cfg, 7, faults, **kw)
    dt = walltime.perf_counter() - t0

    assert res.final_rows == 2 and res.one_minimal, res.summary()
    assert (res.schedule == faults[list(need)]).all(), \
        f"minimizer missed the known-minimal rows {need}"
    out = {"n_rows": n_rows,
           "final_rows": res.final_rows,
           "rounds": res.rounds,
           "candidates_evaluated": res.candidates_evaluated,
           "one_minimal": bool(res.one_minimal),
           "wall_s": round(dt, 3),
           "candidates_per_sec": round(res.candidates_evaluated / dt, 1)
           if dt > 0 else None,
           "rounds_per_sec": round(res.rounds / dt, 2) if dt > 0 else None}
    log(f"minimize_bug[{jax.default_backend()}]: {dt:.2f}s  {out}")
    return out


# ---------------------------------------------------------------------------
# Cross-engine validation: TPU<->CPU bit-exactness
# ---------------------------------------------------------------------------

def bench_crosscheck(n_worlds: int) -> dict:
    import jax

    from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig
    from madsim_tpu.engine.crosscheck import crosscheck_backends

    rcfg = RaftDeviceConfig(n=3)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=1_000_000)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    out = crosscheck_backends(eng, np.arange(n_worlds), max_steps=5_000)
    # Also crosscheck under fault schedules (exercises the fault path).
    faults = make_fault_schedules(n_worlds, 3, 1_000_000, seed=1)
    eng2 = DeviceEngine(RaftActor(rcfg), cfg)
    out_f = crosscheck_backends(eng2, np.arange(n_worlds), faults=faults,
                                max_steps=5_000)
    out["bitwise_equal_with_faults"] = out_f["bitwise_equal"]
    # The contract holds for every actor family, not just the flagship:
    # primary-backup and two-phase-commit crosscheck bitwise too (smaller
    # batches — the point is coverage, not throughput).
    from madsim_tpu.engine import (PBActor, PBDeviceConfig, TPCActor,
                                   TPCDeviceConfig)

    pb = DeviceEngine(
        PBActor(PBDeviceConfig(n=3, n_writes=4)),
        EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.05))
    out["bitwise_equal_pb"] = crosscheck_backends(
        pb, np.arange(min(n_worlds, 1024)), max_steps=5_000)["bitwise_equal"]
    tpc = DeviceEngine(
        TPCActor(TPCDeviceConfig(n=4, n_txns=4, buggy_presumed_commit=True)),
        EngineConfig(n_nodes=4, outbox_cap=5, queue_cap=64,
                     t_limit_us=1_500_000, loss_rate=0.1))
    out["bitwise_equal_tpc"] = crosscheck_backends(
        tpc, np.arange(min(n_worlds, 1024)), max_steps=5_000)["bitwise_equal"]
    log(f"crosscheck: {out}")
    return out


# ---------------------------------------------------------------------------
# Cross-engine validation: time to first bug, host vs device
# ---------------------------------------------------------------------------

def bench_time_to_first_bug(host_seeds_n: int, device_worlds: int) -> dict:
    """Both engines hunt the same injected bug (double voting breaking
    election safety, the buggy_double_vote switch present in BOTH
    models/raft.py and engine/raft_actor.py). Host = sequential seeds,
    reference style; device = one vmapped batch.

    Reported as *expected* wall seconds to first detection, derived from
    each engine's measured per-seed bug rate and seeds/sec (a single
    measured first-hit time is one geometric sample — pure luck). Also
    cross-validates that the two engines find the bug at comparable
    per-seed densities (the BASELINE.json second metric)."""
    import jax

    import madsim_tpu as ms
    from madsim_tpu.models.raft import (
        RaftCluster, RaftOptions, RaftInvariantViolation)
    from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig

    # Host: fixed number of seeds; count hits.
    async def world():
        from madsim_tpu import time as simtime

        cluster = RaftCluster(3, RaftOptions(persist=False,
                                             buggy_double_vote=True))
        while simtime.monotonic() < 2.0:
            await simtime.sleep(0.05)

    t0 = walltime.perf_counter()
    host_hits = 0
    for seed in range(host_seeds_n):
        rt = ms.Runtime(seed=seed)
        rt.set_time_limit(60.0)
        try:
            rt.block_on(world())
        except RaftInvariantViolation:
            host_hits += 1
    host_dt = walltime.perf_counter() - t0
    host_rate = host_hits / host_seeds_n
    host_sps = host_seeds_n / host_dt
    host_expected = (1.0 / host_rate) / host_sps if host_hits else None
    log(f"host bug hunt: {host_hits}/{host_seeds_n} seeds hit "
        f"({host_sps:.1f} seeds/s)")

    # Device: one batch of worlds with the same bug switch.
    rcfg = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=2_000_000, stop_on_bug=False)
    eng = DeviceEngine(RaftActor(rcfg), cfg)
    warm = eng.run(eng.init(np.arange(device_worlds)), max_steps=4_000)
    jax.block_until_ready(warm)

    # init and run timed separately (docs/perf.md: init was previously
    # inside the window, hiding where bench-environment variance lives).
    t0 = walltime.perf_counter()
    state = eng.init(np.arange(device_worlds))
    jax.block_until_ready(state)
    init_dt = walltime.perf_counter() - t0
    t0 = walltime.perf_counter()
    state = eng.run(state, max_steps=4_000)
    jax.block_until_ready(state)
    run_dt = walltime.perf_counter() - t0
    obs = eng.observe(state)
    # Cost-model record at the exact shapes the timed run used (lower
    # only — the donated buffers are never re-executed).
    xla_cost = xla_cost_record(eng, state, 4_000)
    dev_dt = init_dt + run_dt
    n_bugs = int(obs["bug"].sum())
    assert n_bugs > 0, "device engine failed to find the injected bug"
    dev_rate = n_bugs / device_worlds
    # Measured world-utilization of the monolithic batch (docs/perf.md
    # "the straggler tail"): mean vs max masked steps across the batch.
    max_steps_run = int(obs["steps"].max())
    batch_util = (float(obs["steps"].mean()) / max_steps_run
                  if max_steps_run else 0.0)

    # World recycling (docs/perf.md): the same hunt streamed through a
    # bounded batch with stop_on_first_bug, refilling retired slots from
    # the seed cursor. Reports the per-chunk occupancy telemetry the
    # monolithic run cannot have.
    from madsim_tpu.parallel.sweep import sweep as device_sweep

    rcfg_s = RaftDeviceConfig(n=3, buggy_double_vote=True)
    cfg_s = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                         t_limit_us=2_000_000, stop_on_bug=True)
    eng_s = DeviceEngine(RaftActor(rcfg_s), cfg_s)
    batch_w = max(256, device_worlds // 8)
    # chunk_steps=64 (was 256): with supersteps the host no longer pays a
    # dispatch+sync per chunk, so fine-grained chunks are affordable and
    # buy 4x finer on-device stop_on_first_bug granularity — the device
    # exits within 64 steps of the first detection instead of 256.
    t0 = walltime.perf_counter()
    res = device_sweep(None, cfg_s, np.arange(device_worlds), engine=eng_s,
                       chunk_steps=64, max_steps=4_000,
                       stop_on_first_bug=True, recycle=True,
                       batch_worlds=batch_w)
    recycled_dt = walltime.perf_counter() - t0
    recycled = {
        "batch_worlds": batch_w,
        "world_utilization": round(res.world_utilization, 4),
        "n_chunks": int(res.n_active_history.size),
        "found_bug": bool(res.bug.any()),
        "wall_s_incl_compile": round(recycled_dt, 3),
    }
    # Whole-hunt residency (docs/perf.md): the SAME pinned hunt with the
    # occupancy loop fused into one device program — refill, compaction,
    # and the seed cursor run in-loop, so the host issues O(1)
    # mega-dispatches instead of one dispatch per epoch. Bitwise
    # equality with the pipelined run is tier-1 (tests/test_fused.py);
    # here the dispatch economics land in bench_results.json so
    # tools/bench_diff.py can hold the >=4x reduction round over round.
    t0 = walltime.perf_counter()
    res_f = device_sweep(None, cfg_s, np.arange(device_worlds),
                         engine=eng_s, chunk_steps=64, max_steps=4_000,
                         stop_on_first_bug=True, recycle=True,
                         batch_worlds=batch_w, fused=True)
    fused_dt = walltime.perf_counter() - t0
    assert res_f.failing_seeds == res.failing_seeds, \
        "fused hunt diverged from the pipelined hunt on the bench config"
    recycled["fused_wall_s_incl_compile"] = round(fused_dt, 3)
    recycled["fused_dispatch_reduction"] = round(
        res.loop_stats["dispatches_per_seed"]
        / max(res_f.loop_stats["dispatches_per_seed"], 1e-9), 2)
    # Observability record (docs/observability.md): the hunt config swept
    # metrics-on at a capped batch, with per-seed frames aggregated over
    # the fleet. Separate engine — metrics is a static knob; every timed
    # run above stays the exact metrics-off program.
    import dataclasses as _dc

    rec_w_m = min(device_worlds, 2_048)
    eng_m = DeviceEngine(RaftActor(rcfg), _dc.replace(cfg, metrics=True))
    res_m = device_sweep(None, eng_m.cfg, np.arange(rec_w_m), engine=eng_m,
                         chunk_steps=64, max_steps=4_000)
    sim_metrics = {"n_worlds": rec_w_m, **res_m.metrics["aggregate"]}
    coverage = res_m.coverage.to_json()
    del eng_m, res_m

    # Flight-recorder pricing (docs/observability.md "The flight
    # recorder"): the SAME monolithic batch with the K=64 per-world
    # event ring aboard (EngineConfig(blackbox=64)), timed against the
    # blackbox-off run above. The off run IS the baseline — bitwise
    # invisibility keeps it the exact pre-blackbox program — so the
    # deltas here price the opt-in: ring state per world, ring-write
    # flops, and the seeds/s tax. tools/bench_diff.py tracks all three
    # round over round; `make smoke` asserts the keys.
    bb_k = 64
    eng_b = DeviceEngine(RaftActor(rcfg), _dc.replace(cfg, blackbox=bb_k))
    warm_b = eng_b.run(eng_b.init(np.arange(device_worlds)),
                       max_steps=4_000)
    jax.block_until_ready(warm_b)
    del warm_b
    state_b = eng_b.init(np.arange(device_worlds))
    jax.block_until_ready(state_b)
    t0 = walltime.perf_counter()
    state_b = eng_b.run(state_b, max_steps=4_000)
    jax.block_until_ready(state_b)
    bb_run_dt = walltime.perf_counter() - t0
    xla_cost_b = xla_cost_record(eng_b, state_b, 4_000)
    obs_b = eng_b.observe(state_b)
    assert bool(np.array_equal(np.asarray(obs_b["bug"]),
                               np.asarray(obs["bug"]))), \
        "blackbox-on run diverged from blackbox-off on the bug vector"

    def _bb_delta(on, off, nd=2):
        return (round(on - off, nd)
                if on is not None and off is not None else None)

    blackbox = {
        "k": bb_k,
        "seeds_per_sec": round(device_worlds / bb_run_dt, 1),
        "seeds_per_sec_off": round(device_worlds / run_dt, 1),
        "seeds_per_sec_ratio": round(run_dt / bb_run_dt, 4),
        "state_bytes_per_world": xla_cost_b["state_bytes_per_world"],
        "state_bytes_per_world_off": xla_cost["state_bytes_per_world"],
        "state_bytes_per_world_delta": _bb_delta(
            xla_cost_b["state_bytes_per_world"],
            xla_cost["state_bytes_per_world"]),
        "flops_per_world_step": xla_cost_b["flops_per_world_step"],
        "flops_per_world_step_off": xla_cost["flops_per_world_step"],
        "flops_per_world_step_delta": _bb_delta(
            xla_cost_b["flops_per_world_step"],
            xla_cost["flops_per_world_step"]),
    }
    del eng_b, state_b, obs_b

    # Expected seeds to first bug = 1/rate; the device explores
    # device_worlds/dev_dt seeds per second.
    dev_expected = (1.0 / dev_rate) / (device_worlds / dev_dt)
    host_ci = _wilson_ci(host_hits, host_seeds_n)
    dev_ci = _wilson_ci(n_bugs, device_worlds)
    ci_overlap = host_ci[0] <= dev_ci[1] and dev_ci[0] <= host_ci[1]
    ratio = host_rate / dev_rate if dev_rate else float("inf")
    out = {
        "host_bug_rate": round(host_rate, 4),
        "host_bug_rate_ci95": [round(x, 4) for x in host_ci],
        "host_seeds_per_sec": round(host_sps, 2),
        "host_expected_s_to_first_bug": (round(host_expected, 3)
                                         if host_expected else None),
        "device_bug_rate": round(dev_rate, 4),
        "device_bug_rate_ci95": [round(x, 4) for x in dev_ci],
        "device_init_s": round(init_dt, 3),
        "device_run_s": round(run_dt, 3),
        "device_seeds_per_sec": round(device_worlds / dev_dt, 1),
        "device_run_seeds_per_sec": round(device_worlds / run_dt, 1),
        "device_expected_s_to_first_bug": round(dev_expected, 4),
        "device_first_failing_seed": int(np.argmax(obs["bug"])),
        "device_world_utilization": round(batch_util, 4),
        # Per-step XLA cost model of this engine config (the op-budget
        # regression axis; docs/perf.md "Single-pass insert + donation").
        "xla_cost": xla_cost,
        # Fleet-aggregate simulation metrics of the metrics-on probe
        # sweep (docs/observability.md; asserted by `make smoke`).
        "sim_metrics": sim_metrics,
        # Behavior-coverage ledger rollup of the same probe sweep
        # (docs/observability.md "reading the novelty curve").
        "coverage": coverage,
        # Flight-recorder on-vs-off pricing at K=64
        # (docs/observability.md "The flight recorder").
        "blackbox": blackbox,
        "recycled_hunt": recycled,
        # Orchestration breakdown of the recycled hunt's chunk loop
        # (docs/perf.md "Pipelined orchestration"): the acceptance axes
        # are host_decision_s vs loop_wall_s (stall fraction) and
        # chunks_per_dispatch (superstep fan-in).
        "sweep_loop": res.loop_stats,
        # The same hunt under whole-hunt residency (docs/perf.md
        # "Whole-hunt residency"): the acceptance axes are
        # seeds_per_dispatch / dispatches_per_seed (>=4x fewer than the
        # pipelined row above) and epochs_on_device (every refill epoch
        # the host no longer orchestrates).
        "sweep_loop_fused": res_f.loop_stats,
        # Statistical gate (docs/perf.md): Wilson-CI overlap, with a
        # bounded model-difference allowance (the two engines share the
        # bug mechanism, not the timing model) — replaces the toothless
        # [0.1, 10] band.
        "rates_comparable": bool(host_rate > 0 and dev_rate > 0
                                 and (ci_overlap or 1 / 3 <= ratio <= 3.0)),
        "rates_ci_overlap": bool(ci_overlap),
        "speedup": (round(host_expected / dev_expected, 1)
                    if host_expected else None),
    }
    log(f"time_to_first_bug: {out}")
    return out


def _wilson_ci(hits: int, n: int, z: float = 1.96):
    """Wilson 95% interval for a binomial rate (docs/perf.md gate)."""
    if n == 0:
        return (0.0, 1.0)
    p = hits / n
    denom = 1 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = z * ((p * (1 - p) / n + z * z / (4 * n * n)) ** 0.5) / denom
    return (max(0.0, center - half), min(1.0, center + half))


# ---------------------------------------------------------------------------
# Config 8: the host<->device BRIDGE — sweep the UNMODIFIED rpc ping-pong
# host workload (config 1's world) across seeds with the device decision
# kernel (bridge/), vs the same seeds run sequentially on the pure host
# engine. Reports the honest speedup and where the time goes; per-seed
# trajectories are bit-identical across the two engines (tests/test_bridge).
# ---------------------------------------------------------------------------

def bench_bridge_sweep(n_host: int, n_bridge: int) -> dict:
    import madsim_tpu as ms
    from madsim_tpu import time as simtime
    from madsim_tpu.bridge import sweep
    from madsim_tpu.net import Endpoint, rpc

    ROUNDS = 20

    async def world():
        h = ms.Handle.current()

        async def server_init():
            ep = await Endpoint.bind("10.0.0.1:9000")

            async def handle(req, data):
                return BenchPing(req.n + 1), b""

            rpc.add_rpc_handler_with_data(ep, BenchPing, handle)
            await simtime.sleep(1e6)

        h.create_node(name="server", ip="10.0.0.1", init=server_init)
        client = h.create_node(name="client", ip="10.0.0.2")
        done = ms.sync.SimFuture()

        async def client_body():
            ep = await Endpoint.bind("10.0.0.2:0")
            for i in range(ROUNDS):
                await rpc.call_with_data(ep, "10.0.0.1:9000", BenchPing(i),
                                         b"x" * 64, timeout=5.0)
            done.set_result(True)

        client.spawn(client_body())

        async def _await(f):
            return await f

        return await simtime.timeout(600, _await(done))

    import os

    jobs = os.cpu_count() or 1
    out = {"world": f"rpc_pingpong x{ROUNDS} (bench config 1)",
           "jobs": jobs}

    t0 = walltime.perf_counter()
    polls = 0
    for seed in range(n_host):
        rt = ms.Runtime(seed=seed)
        assert rt.block_on(world())
        polls += rt.task.poll_count
    host_dt = walltime.perf_counter() - t0
    host_rate = n_host / host_dt
    out.update({
        "host_seeds_per_sec": round(host_rate, 1),
        "host_us_per_poll": round(host_dt / polls * 1e6, 2),
    })

    from madsim_tpu.bridge.runtime import sweep_profiled

    # Warm with the real world at the real W: the jitted step is process-
    # cached per (cap, k_events), so the later sweeps are steady state.
    # The headline rate comes from a PLAIN sweep (no profiling overhead);
    # the breakdown comes from a separate profiled sweep.
    t0 = walltime.perf_counter()
    sweep(world, list(range(n_bridge)))
    cold_dt = walltime.perf_counter() - t0
    t0 = walltime.perf_counter()
    outs = sweep(world, list(range(n_bridge)))
    dt = walltime.perf_counter() - t0
    assert all(o.error is None for o in outs)
    _outs_p, prof = sweep_profiled(world, list(range(n_bridge)))
    rate = n_bridge / dt
    out.update({
        "bridge_w": n_bridge,
        "bridge_seeds_per_sec": round(rate, 1),
        "bridge_cold_seeds_per_sec": round(n_bridge / cold_dt, 1),
        "bridge_vs_host": round(rate / host_rate, 2),
        "bridge_round_breakdown_ms": {
            k[:-2]: round(prof[k] / max(prof["rounds"], 1) * 1e3, 2)
            for k in ("host_s", "pack_s", "dispatch_s", "settle_s")},
        "bridge_rounds": prof["rounds"],
        # The bridge kernel's device-resident observability block,
        # aggregated over the fleet (docs/observability.md), plus the
        # per-slot behavior-coverage sketch over the same counters.
        "sim_metrics": prof.get("sim_metrics"),
        "coverage": prof.get("coverage"),
        "note": ("per-seed trajectories bit-identical to host "
                 "(tests/test_bridge.py); task bodies are serial Python, "
                 "so single-core speedup is Amdahl-bounded by the measured "
                 "~5-15% decision-kernel fraction — breakdown and ceiling "
                 "analysis in docs/bridge.md"),
    })
    # -- the forked worker pool (bridge/pool.py, ROADMAP item 4) ----------
    # J workers run the task bodies behind the SAME shared kernel, each
    # packing its slot slice straight into shared memory. Recorded per
    # (J, W): throughput vs host, the parent-observed per-phase wall
    # windows, and pool_overhead_frac = (pool - serial)/serial wall on
    # the same seeds — on a 1-core box the honest number is overhead,
    # not speedup (docs/bridge.md "Parallel task bodies"); a multi-core
    # runner's bridge_vs_host at J=4 is the scaling headline.
    from madsim_tpu.bridge.pool import sweep_pooled

    smoke = n_bridge <= 64
    pool: dict = {}
    for Wp in ((64,) if smoke else (64, 512)):
        pseeds = list(range(Wp))
        sweep(world, pseeds)  # warm this width's jit shapes off the clock
        t0 = walltime.perf_counter()
        outs = sweep(world, pseeds)
        serial_dt = walltime.perf_counter() - t0
        assert all(o.error is None for o in outs)
        for J in ((1, 2) if smoke else (1, 2, 4)):
            stats: dict = {}
            t0 = walltime.perf_counter()
            outs = sweep_pooled(world, pseeds, jobs=J, stats=stats)[0]
            pdt = walltime.perf_counter() - t0
            assert all(o.error is None for o in outs)
            rounds = max(stats["rounds"], 1)
            pool[f"j{J}_w{Wp}"] = {
                "seeds_per_sec": round(Wp / pdt, 1),
                "bridge_vs_host": round((Wp / pdt) / host_rate, 2),
                "pool_overhead_frac": round((pdt - serial_dt) / serial_dt,
                                            3),
                # Parent-observed phase windows: host = workers running
                # task bodies (+ fork barrier), pack = shared-memory
                # pack barrier, dispatch = the jitted kernel step,
                # settle = worker settle + drain chain.
                "host_ms_per_round": round(
                    stats["host_s"] / rounds * 1e3, 3),
                "pack_ms_per_round": round(
                    stats["pack_s"] / rounds * 1e3, 3),
                "dispatch_ms_per_round": round(
                    stats["dispatch_s"] / rounds * 1e3, 3),
                "settle_ms_per_round": round(
                    stats["settle_s"] / rounds * 1e3, 3),
                # The parent's OWN per-round Python work (reset apply +
                # bucket calc + broadcast bookkeeping, no waiting): the
                # pack loop is gone from the parent profile, so this
                # stays ~O(1) in W — compare across the w64/w512 rows.
                "parent_ms_per_round": round(
                    stats["parent_s"] / rounds * 1e3, 4),
                "rounds": stats["rounds"],
                "drain_rounds": stats["drain_rounds"],
            }
    out["pool"] = pool
    out["pool_note"] = (
        "jobs=J forked pool behind one shared kernel, bitwise == jobs=1 "
        "== serial (tests/test_bridge_pool.py); this box has "
        f"{jobs} core(s), so interpret bridge_vs_host at J>1 "
        "accordingly — on 1 core the gate is pool_overhead_frac, not "
        "speedup")
    log(f"bridge_sweep: {out}")
    return out


# ---------------------------------------------------------------------------
# Main
# ---------------------------------------------------------------------------

# (short name, JSON key, runner). Short names are the --only/--break-config
# vocabulary; runners take the parsed args.
_CONFIGS = [
    ("rpc", "rpc_pingpong",
     lambda a: bench_rpc_pingpong(64 if a.smoke else 1_000)),
    ("rpc_real", "rpc_real",
     lambda a: bench_rpc_real(256 if a.smoke else 2_000)),
    ("grpc", "grpc_chaos",
     lambda a: bench_grpc_chaos(n_clients=2 if a.smoke else 5,
                                sim_seconds=2.0 if a.smoke else 10.0)),
    ("postgres", "postgres_skew",
     lambda a: bench_postgres_skew(16 if a.smoke else 200)),
    ("crosscheck", "crosscheck",
     lambda a: bench_crosscheck(128 if a.smoke else 4_096)),
    ("bug", "time_to_first_bug",
     lambda a: bench_time_to_first_bug(
         host_seeds_n=16 if a.smoke else 128,
         device_worlds=1_024 if a.smoke else 65_536)),
    ("5node", "madraft_5node",
     lambda a: bench_madraft_5node(256 if a.smoke else 100_000)),
    ("fleet", "fleet_sweep",
     lambda a: bench_fleet_sweep(128 if a.smoke else 4_096)),
    ("minimize", "minimize_bug",
     lambda a: bench_minimize_bug(16 if a.smoke else 64)),
    ("guided", "guided_hunt",
     lambda a: bench_guided_hunt(256 if a.smoke else 512)),
    # Budget pinned at 320/512 regardless of --smoke depth: the
    # exchanged fleet's first find lands in epoch 4 (global seed ~294),
    # and per-range evolution is budget-prefix-stable, so 320 covers
    # the gate at smoke cost.
    ("gfleet", "guided_fleet",
     lambda a: bench_guided_fleet(320 if a.smoke else 512)),
    ("bridge", "bridge_sweep",
     lambda a: bench_bridge_sweep(n_host=16 if a.smoke else 64,
                                  n_bridge=64 if a.smoke else 512)),
]


def _child_argv(args, short: str) -> list:
    argv = [sys.executable, __file__, "--run-config", short]
    if args.smoke:
        argv.append("--smoke")
    if short == "3node":
        # Only the headline child consumes the sizing overrides.
        if args.worlds:
            argv += ["--worlds", str(args.worlds)]
        if args.host_seeds:
            argv += ["--host-seeds", str(args.host_seeds)]
    if args.break_config:
        argv += ["--break-config", args.break_config]
    return argv


def _run_config_subprocess(args, short: str, key: str) -> dict:
    """Run one config in a child process (VERDICT r2 item 3, hardened).

    Process isolation covers the crash classes in-process try/except cannot
    — XLA/C++ aborts, SIGSEGV, OOM kills — and, because the parent itself
    never initializes JAX, sequential children can each acquire the
    (single-process-locked) TPU cleanly."""
    import threading

    cmd = _child_argv(args, short)
    limit = 600 if args.smoke else 3600
    # Stream the child's stderr live (progress logs) while also keeping it
    # for the error tail; capture stdout (the one JSON line) separately.
    # Each pipe has exactly one reader thread — communicate() would race
    # the stderr pump for the same fd.
    child = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
    err_lines: list = []
    out_box: list = []

    def pump_err():
        for line in child.stderr:
            sys.stderr.write(line)
            sys.stderr.flush()
            err_lines.append(line)

    def pump_out():
        out_box.append(child.stdout.read())

    threads = [threading.Thread(target=pump_err, daemon=True),
               threading.Thread(target=pump_out, daemon=True)]
    for t in threads:
        t.start()
    try:
        child.wait(timeout=limit)
    except subprocess.TimeoutExpired:
        child.kill()
        child.wait()
        log(f"{key} FAILED: timeout after {limit}s")
        return {"error": f"timeout after {limit}s"}
    finally:
        for t in threads:
            t.join(timeout=5)
    if child.returncode != 0:
        tail = [ln.strip() for ln in err_lines[-3:]]
        log(f"{key} FAILED: rc={child.returncode}")
        return {"error": f"rc={child.returncode}: " + " | ".join(tail)}
    try:
        return json.loads(out_box[0].strip().splitlines()[-1])
    except (ValueError, IndexError) as exc:
        return {"error": f"bad child output: {exc}"}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small fast run (CI/verify)")
    ap.add_argument("--worlds", type=int, default=None)
    ap.add_argument("--host-seeds", type=int, default=None)
    ap.add_argument("--only", type=str, default=None,
                    help="comma list: 3node,rpc,rpc_real,grpc,postgres,"
                         "5node,fleet,minimize,guided,crosscheck,bug,"
                         "bridge (3node = the headline)")
    ap.add_argument("--break-config", type=str, default=None,
                    help="(testing) name of a config to force-fail, proving "
                         "failure isolation keeps the headline alive")
    ap.add_argument("--run-config", type=str, default=None,
                    help="(internal) child mode: run ONE config, print its "
                         "JSON dict, exit nonzero on failure")
    ap.add_argument("--in-process", action="store_true",
                    help="run configs in-process (debugging; loses native-"
                         "crash isolation)")
    args = ap.parse_args()

    shorts = {c[0] for c in _CONFIGS}
    _BREAKABLE = shorts | {"3node_device", "3node_host"}
    if args.break_config is not None and args.break_config not in _BREAKABLE:
        ap.error(f"--break-config must be one of {sorted(_BREAKABLE)}")

    def boom(*_a, **_kw):
        raise RuntimeError("forced failure (--break-config)")

    def pick(name, fn):
        return boom if args.break_config == name else fn

    def headline(args) -> dict:
        """Device + host headline rates; per-half errors go in the dict."""
        smoke = args.smoke
        # 512k worlds is the measured single-chip sweet spot (HBM-resident,
        # past the per-iteration overhead knee; 1M+ starts regressing).
        n_worlds = args.worlds or (256 if smoke else 524_288)
        n_host = args.host_seeds or (8 if smoke else 32)
        out = {}
        try:
            out["dev_rate"] = pick("3node_device", device_seed_rate)(n_worlds)
        except Exception as exc:
            log(f"headline device FAILED: {type(exc).__name__}: {exc}")
            out["dev_error"] = f"{type(exc).__name__}: {exc}"
        try:
            host = pick("3node_host", host_seed_rate)(n_host)
            out["host"] = host
            out["host_rate"] = host["seeds_per_sec"]
        except Exception as exc:
            log(f"headline host baseline FAILED: {type(exc).__name__}: {exc}")
            out["host_error"] = f"{type(exc).__name__}: {exc}"
        return out

    if args.run_config is not None:
        # Child mode: one config, one JSON line, rc=1 on any failure.
        if args.run_config == "3node":
            print(json.dumps(headline(args)), flush=True)
            return
        for short, _key, runner in _CONFIGS:
            if short == args.run_config:
                print(json.dumps(pick(short, runner)(args)), flush=True)
                return
        ap.error(f"--run-config must be one of {sorted(shorts | {'3node'})}")

    only = set(args.only.split(",")) if args.only else None

    def want(name: str) -> bool:
        return only is None or name in only

    # Headline FIRST (its number must survive anything later), then each
    # other config in its own child process, so a native-level crash
    # (SIGSEGV/abort/OOM) in any config cannot take the others down — and
    # the parent stays JAX-free throughout (the TPU is a single-process
    # resource, released as each sequential child exits).
    configs = {}
    dev_rate = host_rate = None
    if want("3node"):
        if args.in_process:
            h = headline(args)
        else:
            h = _run_config_subprocess(args, "3node", "headline")
        dev_rate, host_rate = h.get("dev_rate"), h.get("host_rate")
        if "host" in h:
            # The measured denominator of vs_baseline, with its per-event
            # cost model (events = scheduler polls).
            configs["host_engine"] = h["host"]
        errs = {k: v for k, v in h.items()
                if k in ("error", "dev_error", "host_error")}
        if errs:
            configs["headline_errors"] = errs

    for short, key, runner in _CONFIGS:
        if not want(short):
            continue
        if args.in_process:
            try:
                configs[key] = pick(short, runner)(args)
            except Exception as exc:
                log(f"{key} FAILED: {type(exc).__name__}: {exc}")
                configs[key] = {"error": f"{type(exc).__name__}: {exc}"}
        else:
            configs[key] = _run_config_subprocess(args, short, key)

    result = {
        "metric": "madraft_3node_1s_seeds_per_sec",
        "value": round(dev_rate, 2) if dev_rate else None,
        "unit": "seeds/s",
        "vs_baseline": (round(dev_rate / host_rate, 2)
                        if dev_rate and host_rate else None),
        # vs_baseline denominator caveat (VERDICT r1/r2): the baseline is
        # THIS repo's host engine (Python coroutines over the native C++
        # RNG/timer/scheduler-decision core), not the reference's Rust
        # engine (not runnable here). configs.host_engine carries its
        # measured events/s and us/event so the denominator is a
        # quantified cost model, not a guess; the residual per-event cost
        # is Python coroutine frames (~60% of runtime), which native
        # bookkeeping cannot remove.
        "baseline_note": "host = this repo's engine (Python coroutines + "
                         "native C++ core), single-seed; see "
                         "configs.host_engine for events/s and us/event",
        "configs": configs,
    }
    # The durable record FIRST (VERDICT r5: two rounds lost their headline
    # numbers to truncated stdout tails) — `make smoke` asserts this file
    # parses and carries the headline keys. Written atomically so a killed
    # run can't leave a half-written JSON shadowing the previous record.
    import os
    import tempfile

    out_path = os.environ.get("MADSIM_BENCH_RESULTS", "bench_results.json")
    fd, tmp_path = tempfile.mkstemp(
        dir=os.path.dirname(out_path) or ".", suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    os.replace(tmp_path, out_path)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
