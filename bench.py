"""Benchmark: MadRaft 3-node seeds/sec, batched device engine vs host engine.

The BASELINE.json headline: how many seeded MadRaft simulations per wall
second can the framework explore, and the speedup over single-seed host
(CPU) execution (the reference's one-thread-per-seed model,
`madsim/src/sim/runtime/builder.rs:118-136`).

One *seed* = one full simulation of a 3-node Raft cluster for 1 virtual
second: randomized election timeouts, leader election, then steady-state
heartbeats, over the simulated network (1-10 ms latency). The device engine
runs W of these vmapped on the accelerator; the host baseline runs the
arbitrary-Python MadRaft model (madsim_tpu/models/raft.py) one seed at a
time, exactly like the reference.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "seeds/s", "vs_baseline": N}
vs_baseline = device seeds/s ÷ host single-seed seeds/s (≥100 is the
BASELINE.json north-star bar). Details go to stderr.
"""
import argparse
import json
import sys
import time as walltime

import numpy as np

SIM_SECONDS = 1.0  # virtual seconds of Raft per seed


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Host baseline: single-seed MadRaft, one world at a time
# ---------------------------------------------------------------------------

def host_seed_rate(n_seeds: int) -> float:
    import madsim_tpu as ms
    from madsim_tpu.models.raft import RaftCluster, RaftOptions

    async def world():
        from madsim_tpu import time as simtime

        cluster = RaftCluster(3, RaftOptions(persist=False))
        try:
            await cluster.wait_for_leader(timeout=SIM_SECONDS)
        except TimeoutError:
            pass
        now = simtime.monotonic()
        if now < SIM_SECONDS:
            await simtime.sleep(SIM_SECONDS - now)
        return cluster.leader()

    t0 = walltime.perf_counter()
    elected = 0
    for seed in range(n_seeds):
        rt = ms.Runtime(seed=seed)
        if rt.block_on(world()) is not None:
            elected += 1
    dt = walltime.perf_counter() - t0
    log(f"host: {n_seeds} seeds in {dt:.2f}s "
        f"({n_seeds / dt:.2f} seeds/s, {elected}/{n_seeds} elected)")
    return n_seeds / dt


# ---------------------------------------------------------------------------
# Device engine: W worlds vmapped
# ---------------------------------------------------------------------------

def device_seed_rate(n_worlds: int, max_steps: int = 2_000) -> float:
    import jax

    from madsim_tpu.engine import DeviceEngine, EngineConfig, RaftActor, RaftDeviceConfig

    rcfg = RaftDeviceConfig(n=3)
    cfg = EngineConfig(n_nodes=3, outbox_cap=4, queue_cap=64,
                       t_limit_us=int(SIM_SECONDS * 1e6))
    eng = DeviceEngine(RaftActor(rcfg), cfg)

    # Warmup: compile init + run on the same shapes.
    warm = eng.run(eng.init(np.arange(n_worlds)), max_steps=max_steps)
    jax.block_until_ready(warm)

    t0 = walltime.perf_counter()
    state = eng.init(np.arange(1_000_000, 1_000_000 + n_worlds))
    state = eng.run(state, max_steps=max_steps)
    jax.block_until_ready(state)
    dt = walltime.perf_counter() - t0

    obs = eng.observe(state)
    assert not obs["active"].any(), "worlds did not finish; raise max_steps"
    assert not obs["bug"].any(), "clean config must not flag bugs"
    elected = int(obs["leader_elected"].sum())
    log(f"device[{jax.default_backend()}]: {n_worlds} seeds in {dt:.2f}s "
        f"({n_worlds / dt:.0f} seeds/s, {elected}/{n_worlds} elected, "
        f"mean {obs['steps'].mean():.0f} steps/world)")
    return n_worlds / dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small fast run (CI/verify)")
    ap.add_argument("--worlds", type=int, default=None)
    ap.add_argument("--host-seeds", type=int, default=None)
    args = ap.parse_args()

    # 256k worlds is the measured single-chip sweet spot (HBM-resident, past
    # the per-iteration overhead knee; larger starts spilling).
    n_worlds = args.worlds or (256 if args.smoke else 262_144)
    n_host = args.host_seeds or (2 if args.smoke else 8)

    dev_rate = device_seed_rate(n_worlds)
    host_rate = host_seed_rate(n_host)

    print(json.dumps({
        "metric": "madraft_3node_1s_seeds_per_sec",
        "value": round(dev_rate, 2),
        "unit": "seeds/s",
        "vs_baseline": round(dev_rate / host_rate, 2),
    }), flush=True)


if __name__ == "__main__":
    main()
