# CI harness (reference analog: .github/workflows/ci.yml:66-125 + Makefile).
# `make check` is the snapshot gate: every target must pass before a commit
# that touches runtime behavior ships. Nonzero exit on any failure.

PY ?= python
# Tests and the determinism sweep run on a virtual 8-device CPU mesh so they
# pass anywhere (tests/conftest.py pins this too; exporting here covers the
# non-pytest entry points).
CPU_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
# Persistent XLA compilation cache (madsim_tpu/parallel/compile_cache.py),
# honored by every entry point at package import and inherited by spawned
# fleet workers: each distinct program compiles ONCE across all of
# `make check`'s legs, and CI re-runs start warm. The tracelint budget
# leg is exempt by construction (analysis/budgets.py compiles fresh —
# the cache strips cost/alias stats).
export MADSIM_COMPILE_CACHE ?= $(CURDIR)/.jax_cache

.PHONY: check lint detlint tracelint speclint speclint-demo test smoke \
        dryrun determinism dualmode native clean replay-demo bench-diff \
        chaos chaos-full triage-demo fuzz-demo actorc-demo \
        bridge-pool-demo

check: lint test smoke dryrun determinism
	@echo "ALL CHECKS PASSED"

# The static gate, four passes in three legs (docs/detlint.md):
#  - detlint: AST passes — nondeterminism escapes (DET*), sim/real API
#    parity (PAR*), hot-loop sync discipline (DET008/DET009).
#  - tracelint: program-level pass — jaxpr rules over the compiled
#    hot-path programs (TRC*), donation contracts, and the checked-in
#    cost-budget ledger analysis/budgets.json (BUD*). Budget programs
#    compile FRESH (the persistent cache strips cost/alias stats), so
#    this leg costs real compile time — that is the point: an op-budget
#    regression fails `make lint` before a bench round ever runs.
#  - speclint: protocol-level pass (docs/speclint.md) — the shipped
#    actorc family specs verified BEFORE lowering: reachability,
#    exhaustiveness, timer discipline, lane-capacity proofs, RNG/effect
#    budgets, durability flow (SPC*).
# Zero findings required; intentional sites are covered by
# detlint-allow.txt and inline `detlint: allow[RULE]` pragmas.
lint: detlint tracelint speclint

detlint:
	$(PY) -m madsim_tpu.analysis madsim_tpu tools

tracelint:
	$(CPU_ENV) $(PY) tools/update_budgets.py --check

speclint:
	$(CPU_ENV) $(PY) -m madsim_tpu.analysis spec

# Pass 4's protocol card for the Paxos family — the kinds x handlers
# matrix, timer graph and lane budget table, rendered byte-stably (CI
# runs it twice and diffs: the static profile must not wobble).
speclint-demo:
	$(CPU_ENV) $(PY) -m madsim_tpu.analysis spec --card paxos

test:
	$(PY) -m pytest tests/ -x -q

# The sim/real matrix on its own (also part of `test`): the same worlds
# executed inside a seeded simulation AND over real asyncio + TCP.
dualmode:
	$(PY) -m pytest tests/test_dualmode.py -q

smoke:
	$(PY) bench.py --smoke > /tmp/bench_smoke.json
	@tail -1 /tmp/bench_smoke.json | $(PY) -c "import json,sys; \
	d=json.load(sys.stdin); assert d['value'], d; \
	bad={k: v for k, v in d['configs'].items() if isinstance(v, dict) \
	     and ({'error', 'dev_error', 'host_error'} & set(v))}; \
	assert not bad, f'configs failed: {bad}'; \
	print('smoke ok:', d['value'], d['unit'])"
	@$(PY) -c "import json; d=json.load(open('bench_results.json')); \
	missing={'metric','value','unit','vs_baseline','configs'}-set(d); \
	assert not missing, f'bench_results.json missing {missing}'; \
	xc=[d['configs'][k].get('xla_cost') for k in \
	    ('time_to_first_bug','madraft_5node')]; \
	need={'flops_per_step','flops_per_world_step','peak_bytes_est', \
	      'argument_size_bytes','aliased_bytes', \
	      'state_bytes_per_world','packed'}; \
	assert all(isinstance(x,dict) and need<=set(x) for x in xc), \
	    f'xla_cost records missing/incomplete: {xc}'; \
	sl=[d['configs'][k].get('sweep_loop') for k in \
	    ('time_to_first_bug','madraft_5node')]; \
	sneed={'device_wait_s','host_decision_s','dispatch_depth', \
	       'dispatches_per_seed','seeds_per_dispatch','epochs_on_device', \
	       'chunks','dispatches','chunks_per_dispatch','loop_wall_s'}; \
	assert all(isinstance(x,dict) and sneed<=set(x) for x in sl), \
	    f'sweep_loop records missing/incomplete: {sl}'; \
	sm=[d['configs'][k].get('sim_metrics') for k in \
	    ('time_to_first_bug','madraft_5node')]; \
	mneed={'msgs_sent','msgs_delivered','timer_fires','kind_hist', \
	       'fault_hist','enqueued','vtime_us'}; \
	assert all(isinstance(x,dict) and mneed<=set(x) for x in sm), \
	    f'sim_metrics records missing/incomplete: {sm}'; \
	cv=[d['configs'][k].get('coverage') for k in \
	    ('time_to_first_bug','madraft_5node')]; \
	assert all(isinstance(x,dict) and x.get('distinct_behaviors',0)>1 \
	           for x in cv), f'coverage records missing/flat: {cv}'; \
	bb=d['configs']['time_to_first_bug'].get('blackbox'); \
	bneed={'k','seeds_per_sec','seeds_per_sec_off','seeds_per_sec_ratio', \
	       'state_bytes_per_world','state_bytes_per_world_off', \
	       'state_bytes_per_world_delta','flops_per_world_step', \
	       'flops_per_world_step_off','flops_per_world_step_delta'}; \
	assert isinstance(bb,dict) and bneed<=set(bb), \
	    f'blackbox record missing/incomplete: {bb}'; \
	gh=d['configs'].get('guided_hunt'); \
	assert isinstance(gh,dict) and {'pair','raft'}<=set(gh), \
	    f'guided_hunt record missing/incomplete: {gh}'; \
	p=gh['pair']; \
	assert p.get('guided_seeds_to_bug') and \
	    (p.get('random_seeds_to_bug') is None or \
	     p['guided_seeds_to_bug']<p['random_seeds_to_bug']), \
	    f'guided search did not beat random on the pair family: {p}'; \
	px=gh.get('paxos'); \
	assert isinstance(px,dict) and px.get('guided_seeds_to_bug') and \
	    (px.get('random_seeds_to_bug') is None or \
	     px['guided_seeds_to_bug']<px['random_seeds_to_bug']), \
	    f'guided did not beat random on the actorc Paxos family: {px}'; \
	assert px.get('guided_lineage_depth',0)>=1, \
	    f'paxos find has no ancestry depth: {px.get(\"guided_lineage_depth\")}'; \
	rneed={'guided_bugs_found','random_bugs_found', \
	       'guided_novelty_area','random_novelty_area'}; \
	assert rneed<=set(gh['raft']), f'guided_hunt raft leg: {gh[\"raft\"]}'; \
	bp=d['configs']['bridge_sweep'].get('pool'); \
	bneed={'bridge_vs_host','pool_overhead_frac','seeds_per_sec', \
	       'host_ms_per_round','pack_ms_per_round','dispatch_ms_per_round', \
	       'settle_ms_per_round','parent_ms_per_round'}; \
	assert isinstance(bp,dict) and {'j1_w64','j2_w64'}<=set(bp) and \
	    all(bneed<=set(v) for v in bp.values()), \
	    f'bridge pool record missing/incomplete: {bp}'; \
	dsp={'seeds_per_dispatch','epochs_on_device'}; \
	assert dsp<=set(p.get('sweep_loop',{})), \
	    f'guided_hunt pair sweep_loop missing {dsp}: {p.get(\"sweep_loop\")}'; \
	slf=d['configs']['time_to_first_bug'].get('sweep_loop_fused'); \
	assert isinstance(slf,dict) and slf.get('fused') and \
	    dsp<=set(slf), f'fused sweep_loop record missing/incomplete: {slf}'; \
	ls=p.get('guided_operator_stats'); \
	assert isinstance(ls,dict) and {'splice','node_rotate'}<=set(ls) \
	    and all({'produced','novel','survived','bug'}<=set(v) \
	            for v in ls.values()), \
	    f'guided_hunt operator_stats missing/incomplete: {ls}'; \
	assert p.get('guided_lineage_depth',0)>=1, \
	    f'guided find has no ancestry depth: {p.get(\"guided_lineage_depth\")}'; \
	gf=d['configs'].get('guided_fleet'); \
	fneed={'exchanged_seeds_to_bug','independent_seeds_to_bug', \
	       'exchanged_bugs_found','independent_bugs_found', \
	       'exchange_overhead_frac','epochs_merged','publishes', \
	       'lineage_depth','operator_stats'}; \
	assert isinstance(gf,dict) and fneed<=set(gf), \
	    f'guided_fleet record missing/incomplete: {gf}'; \
	assert gf.get('exchanged_seeds_to_bug') and \
	    gf['exchanged_bugs_found']>=gf['independent_bugs_found'], \
	    f'exchanged fleet did not hold the cross-range gate: {gf}'; \
	fs=d['configs'].get('fleet_sweep'); \
	fsneed={'fabric_overhead_frac','acquire_ms','sweep_ms','merge_ms', \
	        'rpcs_per_lease','control_rpcs_per_lease', \
	        'session_reuse_hits','leases_prefetched','grouped_leases'}; \
	assert isinstance(fs,dict) and fsneed<=set(fs), \
	    f'fleet_sweep cost-model record missing/incomplete: {fs}'; \
	assert fs['session_reuse_hits']>=1 and fs['leases_prefetched']>=1, \
	    f'fleet fabric disciplines inactive: {fs}'; \
	from madsim_tpu.fleet import MAX_CONTROL_RPCS_PER_LEASE as M; \
	assert fs['control_rpcs_per_lease']<=M, \
	    f'control plane over budget ({M}/lease): {fs}'; \
	print('bench_results.json ok:', d['metric'])"
	$(CPU_ENV) $(PY) tools/pallas_smoke.py

# Fleet chaos matrix (docs/fleet.md): worker kills, lease expiries +
# re-issues, duplicated completions, SIGTERM preemptions, torn
# checkpoints — asserting the merged SweepResult stays bitwise identical
# to a crash-free fleet AND a single-host sweep, for raft/pb/tpc on the
# CPU mesh. CI runs this after smoke; `make test` covers the same
# contract via tests/test_fleet.py. chaos-full adds the multiprocess
# leg (real worker processes + SIGKILL; slower — each worker re-imports
# JAX).
chaos:
	$(CPU_ENV) $(PY) tools/chaos_matrix.py

chaos-full:
	$(CPU_ENV) $(PY) tools/chaos_matrix.py --process

# End-to-end failure-triage workflow (docs/triage.md): inject the
# known-minimal synthetic bug, hunt it with one pipelined sweep, dedupe
# the failures into classes, batch-ddmin one representative per class
# (must converge to EXACTLY the two load-bearing schedule rows), and
# replay the minimized bundle through `python -m madsim_tpu.obs replay`
# in a fresh process — nonzero exit unless the recorded failure
# reproduces from the minimized schedule. CI runs this after chaos.
triage-demo:
	$(CPU_ENV) $(PY) tools/triage_demo.py

# The closed fuzzer loop end to end (docs/search.md; ROADMAP item 2):
# inject the pair-restart family (bug reachable ONLY through schedule
# mutation), run the coverage-guided hunt vs the matched random-mutation
# baseline — guided must reach the bug in strictly fewer seeds — then
# triage the find to a verified 1-minimal bundle and replay it in a
# fresh process; plus the seeded raft double-vote leg, where guided must
# out-hunt random (failing seeds at the same budget). Nonzero exit on
# any miss. CI runs this after triage-demo.
fuzz-demo:
	$(CPU_ENV) $(PY) tools/fuzz_demo.py

# The actor compiler end to end (docs/actorc.md; ROADMAP item 3):
# build the multi-decree Paxos spec, compile it, crosscheck the device
# actor against its generated host twin per event (bitwise), run the
# guided hunt over the forgetful-acceptor consistency violation —
# guided must reach the bug in strictly fewer seeds than the matched
# random baseline — then triage the find to a verified 1-minimal
# bundle and replay it through `python -m madsim_tpu.obs replay` in a
# fresh process. Nonzero exit on any miss. CI runs this after
# fuzz-demo.
actorc-demo:
	$(CPU_ENV) $(PY) tools/actorc_demo.py

# The bridge worker pool end to end (docs/bridge.md "Parallel task
# bodies"; ROADMAP item 4): a mixed-outcome suite (values, raises,
# deadlocks, lossy-RPC send accounting) swept serial, pooled jobs=1,
# and pooled jobs=2 (uneven W%J split) must be BITWISE identical on
# traces + outcomes, with and without batch recycling; then SIGKILL a
# worker mid-round and assert the pointed BridgePoolError (worker /
# slot range / round) with every shared-memory segment unlinked.
# Nonzero exit on any miss. CI runs this after actorc-demo.
bridge-pool-demo:
	$(CPU_ENV) $(PY) tools/bridge_pool_demo.py

# Regression table between two bench rounds (tools/bench_diff.py):
# compares seeds/s, utilization, xla_cost flops/bytes, sweep_loop stalls
# and coverage. Default (--auto) diffs the newest BENCH_r*.json round
# against bench_results.json when present, else the two newest rounds.
# CI runs it after smoke whenever a previous round artifact exists.
bench-diff:
	$(PY) tools/bench_diff.py --auto

# End-to-end repro-bundle workflow (docs/observability.md): sweep a known
# buggy config, write a repro bundle for a failing seed, replay it through
# `python -m madsim_tpu.obs replay`, and validate the exported Chrome
# trace ends at the invariant raise.
replay-demo:
	$(CPU_ENV) $(PY) tools/replay_demo.py

dryrun:
	$(PY) -c "from __graft_entry__ import dryrun_multichip, entry; \
	          dryrun_multichip(8); print('dryrun_multichip(8) ok'); \
	          import jax; fn, args = entry(); \
	          jax.jit(fn).lower(*args).compile(); print('entry() compiles')"

determinism:
	$(CPU_ENV) MADSIM_TEST_NUM=8 MADSIM_TEST_SEED=0 \
	MADSIM_TEST_CHECK_DETERMINISM=1 $(PY) tools/determinism_sweep.py

native:
	$(PY) -c "from madsim_tpu import native; \
	          assert native.available(), 'native core failed to build'; \
	          print('native core built:', native._SO)"

clean:
	rm -f madsim_tpu/native/_core.so /tmp/bench_smoke.json
