"""The two canonical guided hunts: shared by bench.py `guided_hunt`,
`make fuzz-demo` (tools/fuzz_demo.py) and the acceptance gates.

Both hunts compare coverage-guided search against the MATCHED random-
mutation baseline (``SearchConfig(guided=False)``: same operators, same
rates, same budget — no feedback), the comparison the ROADMAP item-2
gate asks for:

- **pair** — the synthetic conjunction family (search/family.py): the
  bug needs two specific node restarts the template never performs, and
  partial progress is behaviorally visible. Guided reaches it in ~73
  seeds where random needs ~409 (measured; docs/search.md "when guided
  beats random") — the seeds-to-bug gate.
- **raft** — a seeded double-vote bug (RaftDeviceConfig
  ``buggy_double_vote``) made schedule-gated: a WIDE election window
  plus narrow network latency makes natural candidate collisions rare
  (~0.8%/seed), while overlapping long PAUSEs flush buffered election
  timers simultaneously on resume — synchronized elections, reliable
  collisions (measured 36/512 under a hand-built sync schedule vs
  4/512 fault-free). The template's short, disjoint pauses are benign;
  the search must grow overlap through time jitter and recombination.
  Guided finds ~2x the failing seeds of random at the same budget —
  the bugs-at-budget gate (first-bug ties are expected here: both modes
  share generation-1 children by construction, and the residual
  seed-dependent collision floor is reachable by either).

- **paxos** — the first actorc-compiled DSL-only family
  (docs/actorc.md): multi-decree Paxos with forgetful acceptors
  (``PaxosConfig(buggy_forgetful_acceptor=True)`` flips ONE
  ``durable`` annotation — the textbook stable-storage violation).
  Every decree is contended, so each opens a ~20 ms amnesia window
  between the first proposer's accept-quorum and the rival's
  promise-quorum; the consistency violation needs TWO restarts
  jittered from the benign early template into a window (one
  in-window restart violates ~1%/seed, two up to ~7%), while one
  in-window restart already perturbs rounds visibly — the staircase.
  Measured: guided reaches the conflict at seed ~191 where random
  finds nothing in 512 (``make actorc-demo``).
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

from .config import SearchConfig
from .family import (
    HUNT_NODES,
    HUNT_ROWS,
    GuidedPairActor,
    GuidedPairConfig,
    engine_config,
    family_schedule,
    hunt_search_config,
)


class Hunt(NamedTuple):
    """One bench/demo hunt setup: build engines with
    ``DeviceEngine(actor, cfg)`` and sweep with ``template`` +
    ``search(guided=...)``."""

    name: str
    actor: object
    cfg: object
    template: np.ndarray
    search: object            # callable(guided: bool) -> SearchConfig
    sweep_kw: dict            # canonical sweep knobs (batch, chunks, ...)


def pair_hunt() -> Hunt:
    """The conjunction family at the canonical shape."""
    acfg = GuidedPairConfig(n=HUNT_NODES)
    return Hunt(
        name="pair_restart_family",
        actor=GuidedPairActor(acfg),
        cfg=engine_config(acfg),
        template=family_schedule(HUNT_ROWS, acfg),
        search=hunt_search_config,
        sweep_kw=dict(recycle=True, batch_worlds=32, chunk_steps=32,
                      max_steps=50_000_000),
    )


def raft_hunt() -> Hunt:
    """The seeded raft double-vote bug, schedule-gated (see module
    docstring for why each constant is what it is)."""
    from ..engine import EngineConfig, RaftActor, RaftDeviceConfig
    from ..engine.core import FAULT_PAUSE, FAULT_RESUME

    rcfg = RaftDeviceConfig(n=5, buggy_double_vote=True,
                            elect_min_us=150_000, elect_max_us=1_300_000,
                            heartbeat_us=40_000)
    cfg = EngineConfig(n_nodes=5, outbox_cap=6, queue_cap=64,
                       t_limit_us=1_600_000, latency_min_us=1_000,
                       latency_max_us=3_000, metrics=True)
    # Benign template: three short, disjoint single-node pauses.
    template = np.array([
        [200_000, FAULT_PAUSE, 4, 0],
        [240_000, FAULT_RESUME, 4, 0],
        [500_000, FAULT_PAUSE, 3, 0],
        [540_000, FAULT_RESUME, 3, 0],
        [800_000, FAULT_PAUSE, 4, 0],
        [840_000, FAULT_RESUME, 4, 0]], np.int32)

    def search(guided: bool = True) -> SearchConfig:
        return SearchConfig(corpus=16, guided=guided, splice_pct=20,
                            disable_pct=5, time_pct=40, node_pct=15,
                            op_pct=5, time_jitter_us=400_000)

    return Hunt(
        name="seeded_raft_double_vote",
        actor=RaftActor(rcfg),
        cfg=cfg,
        template=template,
        search=search,
        sweep_kw=dict(recycle=True, batch_worlds=32, chunk_steps=64,
                      max_steps=50_000_000),
    )


def paxos_hunt() -> Hunt:
    """The multi-decree Paxos forgetful-acceptor hunt — the first
    DSL-only family leg (see module docstring for the staircase
    shape; tuning measured in actorc/families/paxos.py)."""
    from ..actorc.families.paxos import (PaxosActor, PaxosConfig,
                                         engine_config, hunt_template)

    xcfg = PaxosConfig(buggy_forgetful_acceptor=True, contend_all=True)

    def search(guided: bool = True) -> SearchConfig:
        return SearchConfig(corpus=32, guided=guided, splice_pct=20,
                            disable_pct=5, time_pct=40, node_pct=15,
                            op_pct=5, time_jitter_us=60_000)

    return Hunt(
        name="paxos_forgetful_acceptor",
        actor=PaxosActor(xcfg),
        cfg=engine_config(xcfg, metrics=True),
        template=hunt_template(xcfg),
        search=search,
        sweep_kw=dict(recycle=True, batch_worlds=32, chunk_steps=32,
                      max_steps=50_000_000),
    )
