"""Synthetic guided-hunt family: a conjunction bug with observable progress.

``GuidedPairActor`` is the pair-restart family the fuzzer-loop gates run
on (ISSUE/ROADMAP item 2): the invariant fires iff BOTH target nodes
have been restarted at least once — like triage's
:class:`~madsim_tpu.triage.synthetic.PairRestartActor`, but with the one
property that makes coverage guidance *matter*: partial progress is
behaviorally visible. The first restart of each target emits a
"progress beacon" message, so a world that restarted one target delivers
a different ``kind_hist`` than a world that restarted none — they land
in different behavior-signature buckets (obs/coverage.py), the guided
corpus keeps the one-target schedule as a parent, and one more node
rotation reaches the conjunction. A random-mutation baseline must hit
both targets in a single mutation pass of the original template — the
classic staircase argument for why coverage-guided search beats random
fuzzing on conjunctive bugs (docs/search.md "when guided beats
random"), here with an exactly measurable seeds-to-bug gap
(``bench.py guided_hunt``, ``make fuzz-demo``).

The template schedule (:func:`family_schedule`) restarts only filler
nodes: the bug is reachable EXCLUSIVELY through the search's node-
rotation operator, never by seed enumeration — a fixed-schedule sweep
can run forever without finding it.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..engine.core import FAULT_RESTART, EngineConfig, Outbox
from ..engine.lanes import take_small, upd
from ..engine.queue import Event


@dataclasses.dataclass(frozen=True)
class GuidedPairConfig:
    """Static parameters of the guided pair-restart family."""

    n: int = 8        # nodes per world (engine n_nodes must match);
                      # more filler nodes = a harder random baseline
    node_a: int = 1   # the invariant fires when BOTH targets have
    node_b: int = 2   # been restarted at least once


class GuidedPairActor:
    """Pair-restart conjunction with progress beacons.

    Event kinds: 0 = the seed message (keeps an empty-schedule world
    alive for one delivered step), 1 = a progress beacon — sent exactly
    once per target node, on its first restart. Beacons are ordinary
    messages (latency-sampled, loss/clog/dead-dst rules apply), so their
    delivery counts land in the MetricsBlock ``kind_hist`` like any
    actor traffic and the behavior signature separates
    zero/one/two-target worlds with no search-specific plumbing.
    """

    num_kinds = 2
    kind_names = ["seed", "progress"]
    invariant_id = "guided_pair_conjunction"

    def __init__(self, acfg: GuidedPairConfig = GuidedPairConfig()):
        self.acfg = acfg

    def init(self, cfg: EngineConfig, rng):
        s = {"restarts": jnp.zeros((cfg.n_nodes,), jnp.int32)}
        evs = [Event.make(time=1, kind=0,
                          payload_words=cfg.payload_words)]
        return s, evs, rng

    def handle(self, cfg, s, ev, now, rng):
        return s, Outbox.empty(cfg), rng, jnp.asarray(False)

    def on_restart(self, cfg, s, node, now, rng):
        prev = take_small(s["restarts"], node)
        restarts = upd(s["restarts"], node, prev + 1)
        a, b = self.acfg.node_a, self.acfg.node_b
        # First restart of a TARGET node beacons once: the observable
        # progress edge the novelty signal keys on.
        beacon = ((node == a) | (node == b)) & (prev == 0)
        ob = Outbox.empty(cfg)
        ob = ob._replace(
            valid=ob.valid.at[0].set(beacon),
            kind=ob.kind.at[0].set(jnp.int32(1)),
            dst=ob.dst.at[0].set(jnp.int32(0)))
        return {"restarts": restarts}, ob, rng

    def invariant(self, cfg, s):
        a, b = self.acfg.node_a, self.acfg.node_b
        return (s["restarts"][..., a] > 0) & (s["restarts"][..., b] > 0)

    def observe(self, cfg, s):
        a, b = self.acfg.node_a, self.acfg.node_b
        return {
            "restarts_a": s["restarts"][..., a],
            "restarts_b": s["restarts"][..., b],
            # dtype-pinned sum: a bare jnp.sum widens to i64 under the
            # x64 flag (tracelint TRC003).
            "restarts_total": jnp.sum(s["restarts"], axis=-1,
                                      dtype=jnp.int32),
        }


def family_schedule(n_rows: int = 8,
                    acfg: GuidedPairConfig = GuidedPairConfig(),
                    t0_us: int = 20_000, dt_us: int = 20_000) -> np.ndarray:
    """The ``(n_rows, 4)`` template: restarts of FILLER nodes only, at
    strictly increasing times. No subset of the template fails — the
    bug is reachable only through the search's mutation operators."""
    fillers = [i for i in range(acfg.n)
               if i not in (acfg.node_a, acfg.node_b)]
    if not fillers:
        raise ValueError("GuidedPairConfig needs at least one filler node")
    rows = np.zeros((n_rows, 4), np.int32)
    rows[:, 0] = t0_us + dt_us * np.arange(n_rows)
    rows[:, 1] = FAULT_RESTART
    rows[:, 2] = [fillers[i % len(fillers)] for i in range(n_rows)]
    return rows


def engine_config(acfg: GuidedPairConfig = GuidedPairConfig()
                  ) -> EngineConfig:
    """The canonical metrics-on engine config for this family (metrics
    are required: the novelty signal hashes the MetricsBlock)."""
    return EngineConfig(n_nodes=acfg.n, outbox_cap=2, queue_cap=64,
                        t_limit_us=2_000_000, metrics=True)


# The canonical guided-hunt shape shared by bench.py `guided_hunt`,
# `make fuzz-demo` and tests/test_search.py: 12 nodes (10 fillers) and a
# 6-row template make a single-pass double-target hit rare — measured
# seeds-to-bug ~73 guided vs ~409 random under HUNT_SEARCH, the
# staircase gap the acceptance gate asserts.
HUNT_NODES = 12
HUNT_ROWS = 6


def hunt_search_config(guided: bool = True, corpus: int = 32):
    """The tuned :class:`~madsim_tpu.search.SearchConfig` of the
    canonical family hunt; ``guided=False`` is the matched
    random-mutation baseline (same operators and rates, no feedback)."""
    from .config import SearchConfig

    return SearchConfig(corpus=corpus, guided=guided, splice_pct=20,
                        disable_pct=5, time_pct=20, node_pct=15,
                        op_pct=5)
