# tracelint: hot-loop
"""The search generator program: harvest + mutate, one jitted dispatch.

This is the device half of the closed fuzzer loop (docs/search.md): at
every refill boundary of a guided ``sweep(recycle=True, search=...)``
the sweep dispatches ONE compiled program that

1. **harvests** — computes the behavior signature
   (obs/coverage.behavior_signature) of every slot retiring in this
   refill, scores each against the device-resident corpus (sketch
   distance, search/corpus.py), and folds the novel survivors' schedules
   in, sequentially and deterministically; then
2. **generates** — emits one child ``(F, 4)`` schedule per slot by
   tournament-selecting parents from the updated corpus and applying the
   splice/mutation operators (search/mutate.py) under per-slot
   splitmix64 lanes keyed by ``(search seed, slot seed id, generation)``
   (search/rng.py).

The program reads the post-compaction world state (the retiring tail's
MetricsBlock is frozen in place until the slots are refilled — the same
world-retirement edge the PR 6 coverage fold observes) and returns the
children, the updated corpus, and two telemetry scalars
``(corpus_filled, corpus_inserted_total)`` that ride the retire pull the
sweep already pays — zero new mid-loop host syncs (the counted-_fetch
contract, tests/test_search.py).

Cached per ``(mesh, batch width, schedule rows, SearchConfig)`` on the
engine, like every other sweep program; it is registered in the
tracelint program registry as ``search.generate`` with ledger budgets
(analysis/budgets.json).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from ..obs.coverage import behavior_signature
from ..obs.lineage import LineageLanes, OperatorTable, credit, ops_bits
from ..parallel.mesh import scalar_spec, world_sharding
from .config import SearchConfig
from .corpus import CorpusState, harvest_fold, retiring_mask
from .mutate import make_children


def generate_body(eng_cfg, scfg: SearchConfig, w: int):
    """The harvest+generate fold as a plain traced callable.

    This is the body :func:`searcher` jits — exposed un-jitted so the
    fused whole-hunt superstep (parallel/sweep.py) can inline the exact
    same fold inside its ``lax.while_loop`` epoch branch: one
    definition, two call sites, bitwise-identical corpus decisions and
    children by construction. Signature matches the ``scfg.lineage=
    False`` searcher: ``(state, sched, idx, corpus, n_act, new_ids) ->
    (children, corpus', (n_filled, n_inserted))``.
    """
    def run(state, sched, idx, corpus: CorpusState, n_act, new_ids):
        if scfg.guided:
            sigs = behavior_signature(state.metrics)          # (W,) u32
            hmask = retiring_mask(w, n_act, idx, state.active)
            corpus, _ = harvest_fold(corpus, sched, sigs, hmask,
                                     scfg.min_novelty)
        gen1 = corpus.gen + jnp.int32(1)
        children = make_children(scfg, eng_cfg, corpus, new_ids, gen1)
        corpus = corpus._replace(gen=gen1)
        n_filled = jnp.sum(corpus.filled, dtype=jnp.int32)
        return children, corpus, (n_filled, corpus.inserted)

    return run


def generate_body_lineage(eng_cfg, scfg: SearchConfig, w: int):
    """:func:`generate_body` with provenance lanes (``scfg.lineage``).

    The un-jitted twin of the lineage-on searcher program, shared with
    the fused superstep's epoch branch. Signature: ``(state, sched,
    idx, corpus, n_act, new_ids, fill_mask, lin, op_tab, lin_base) ->
    (children, child_lin, corpus', op_tab', stats)``.
    """
    def run(state, sched, idx, corpus: CorpusState, n_act, new_ids,
            fill_mask, lin: LineageLanes, op_tab: OperatorTable,
            lin_base):
        n_ins = jnp.int32(0)
        nov_m = jnp.zeros((w,), bool)
        if scfg.guided:
            sigs = behavior_signature(state.metrics)          # (W,) u32
            hmask = retiring_mask(w, n_act, idx, state.active)
            obits = ops_bits(lin.ops)            # (W, N_OPS) bool
            # Lineage entry id of a retiring world: its (base-offset)
            # seed position + 1 — globally unique across fleet ranges
            # by construction (obs/lineage.py).
            entries = jnp.where(idx >= 0, lin_base + idx + jnp.int32(1),
                                jnp.int32(-1))
            corpus, n_ins, nov_m, ins_m = harvest_fold(
                corpus, sched, sigs, hmask, scfg.min_novelty,
                entries=entries, depths=lin.depth, with_masks=True)
            op_tab = op_tab._replace(
                novel=credit(op_tab.novel, obits, nov_m),
                survived=credit(op_tab.survived, obits, ins_m))
        gen1 = corpus.gen + jnp.int32(1)
        children, child_lin = make_children(scfg, eng_cfg, corpus,
                                            new_ids, gen1, lineage=True)
        op_tab = op_tab._replace(
            produced=credit(op_tab.produced, ops_bits(child_lin.ops),
                            fill_mask))
        corpus = corpus._replace(gen=gen1)
        n_filled = jnp.sum(corpus.filled, dtype=jnp.int32)
        stats = (n_filled, corpus.inserted, corpus.gen,
                 jnp.sum(nov_m, dtype=jnp.int32), n_ins)
        return children, child_lin, corpus, op_tab, stats

    return run


def searcher(eng, mesh, scfg: SearchConfig, w: int, f_rows: int):
    """Compile (and cache per engine) the harvest+generate program.

    Signature (``scfg.lineage=False``, the PR 11 shape):
    ``(state, sched, idx, corpus, n_act, new_ids) ->
    (children, corpus', (n_filled, n_inserted))`` where ``state`` is the
    post-compaction batch (active-first), ``sched`` the (W, F, 4)
    per-slot schedule array permuted with it, ``idx`` the slot→seed
    index, ``n_act`` the live count (rows past it are the retiring
    tail), and ``new_ids`` the (W,) seed ids the refilled slots will
    run. With ``scfg.guided=False`` the harvest is compiled out — the
    corpus stays at the seeded template and the children are the
    matched random-mutation baseline.

    With ``scfg.lineage=True`` (default; obs/lineage.py) the program
    widens to ``(state, sched, idx, corpus, n_act, new_ids, fill_mask,
    lin, op_tab, lin_base) -> (children, child_lin, corpus', op_tab',
    stats)``: the retiring tail's provenance lanes ``lin`` feed the
    per-operator outcome credits (novel / survived at the harvest edge;
    the ``bug`` outcome folds HOST-side from the per-seed lanes the
    final fetch carries — see OperatorTable), installed children
    (``fill_mask``) credit ``produced``, inserted entries record their
    lineage entry id
    (``lin_base + seed id + 1``) and depth on the corpus lanes, and
    ``stats`` grows the per-refill scalars the search telemetry stream
    emits — ``(n_filled, inserted_total, gen, refill_novel,
    refill_inserted)``. Everything added is write-only accounting:
    child bytes, corpus decisions, and the simulation are bit-identical
    to ``lineage=False`` (tier-1-gated).
    """
    cache = eng.__dict__.setdefault("_searcher_cache", {})
    key = (mesh, w, f_rows, scfg)
    if key in cache:
        return cache[key]

    rep = NamedSharding(mesh, scalar_spec())
    ws = world_sharding(mesh)
    corpus_sh = CorpusState(sched=rep, sig=rep, score=rep, filled=rep,
                            gen=rep, inserted=rep, entry=rep, depth=rep)

    if not scfg.lineage:
        out_sh = (ws, corpus_sh, (rep, rep))
        fn = jax.jit(generate_body(eng.cfg, scfg, w), out_shardings=out_sh)
        cache[key] = fn
        return fn

    out_sh = (ws, LineageLanes(p1=ws, p2=ws, ops=ws, depth=ws),
              corpus_sh,
              OperatorTable(produced=rep, novel=rep, survived=rep),
              (rep, rep, rep, rep, rep))
    fn = jax.jit(generate_body_lineage(eng.cfg, scfg, w),
                 out_shardings=out_sh)
    cache[key] = fn
    return fn
