# tracelint: hot-loop
"""Device-resident schedule corpus: the parent pool of the guided hunt.

A fixed-capacity ledger of ``K`` surviving high-novelty ``(F, 4)`` fault
schedules, carried as mesh-replicated device arrays exactly like the
PR 6 coverage ledger (obs/coverage.py) — the sweep loop syncs it to the
host only on the cadence it already pays (the retire pulls and the final
fetch), never mid-loop.

Novelty is the sketch distance of a retiring world's u32 behavior
signature (obs/coverage.behavior_signature over its MetricsBlock
histograms) against every corpus entry's recorded signature: the minimum
Hamming distance in signature bits, 33 against an empty corpus. A world
clears the bar (``SearchConfig.min_novelty``) iff its behavior class is
far enough from everything the corpus already holds — the AFL "keep
inputs that light new coverage" rule with the comparison run entirely
on device.

Insertion is SEQUENTIAL over the retiring tail (a ``fori_loop``), so a
batch retiring several novel worlds folds them one at a time against the
corpus as it updates — two worlds with the same fresh signature insert
once, and the fold order (slot order after compaction) is deterministic,
which is half of the guided sweep's bitwise-reproducibility contract
(the other half is the counter-based mutation lanes, search/rng.py).
Replacement is worst-first: a candidate lands in the lowest-score slot
(unfilled slots score -1, so they fill first; ``argmin`` ties resolve to
the lowest index), and only if its novelty strictly beats that score.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Novelty of a signature against an EMPTY corpus: one more than the
# maximum Hamming distance of two u32s, so the first insert always wins.
EMPTY_NOVELTY = 33


class CorpusState(NamedTuple):
    """The device corpus (all leaves mesh-replicated).

    ``gen`` counts refill generations (bumped once per guided refill —
    the generation half of the (seed, generation) child key);
    ``inserted`` counts total corpus inserts, for telemetry.

    ``entry``/``depth`` are the provenance lanes of the evolution
    observatory (obs/lineage.py): the globally-unique entry id each
    slot's schedule was inserted under (``lin_base + seed position +
    1``; the template is entry 0) and its ancestry depth at insert —
    write-only accounting, never read by the insertion rule, so lineage
    on/off cannot move a single corpus decision.
    """

    sched: jnp.ndarray     # (K, F, 4) i32 parent schedules
    sig: jnp.ndarray       # (K,) u32 behavior signature at insert
    score: jnp.ndarray     # (K,) i32 novelty at insert
    filled: jnp.ndarray    # (K,) bool
    gen: jnp.ndarray       # () i32 refill-generation counter
    inserted: jnp.ndarray  # () i32 total inserts
    entry: jnp.ndarray     # (K,) i32 lineage entry id (-1 unfilled)
    depth: jnp.ndarray     # (K,) i32 ancestry depth at insert


def corpus_init(k: int, template: np.ndarray) -> CorpusState:
    """A fresh corpus seeded with the (normalized) template schedule in
    slot 0 — parents always exist, so generation 1 children are
    mutations of the original schedule. The template's signature is
    unknown until a world runs; it is recorded as 0 with score 0, so the
    first real survivor may replace it."""
    template = np.asarray(template, np.int32)
    f = template.shape[0]
    sched = np.zeros((k, f, 4), np.int32)
    sched[:, :, 0] = -1                      # DISABLED_ROW sentinels
    sched[0] = template
    filled = np.zeros((k,), bool)
    filled[0] = True
    entry = np.full((k,), -1, np.int32)
    entry[0] = 0                             # the template is entry 0
    return CorpusState(
        sched=jnp.asarray(sched),
        sig=jnp.zeros((k,), jnp.uint32),
        score=jnp.zeros((k,), jnp.int32),
        filled=jnp.asarray(filled),
        gen=jnp.int32(0),
        inserted=jnp.int32(0),
        entry=jnp.asarray(entry),
        depth=jnp.zeros((k,), jnp.int32),
    )


def popcount32(x: jnp.ndarray) -> jnp.ndarray:
    """Per-element population count of a u32 array (SWAR; exact integer
    math, bit-stable across backends like coverage's _bit_length_u32)."""
    x = x.astype(jnp.uint32)
    x = x - ((x >> jnp.uint32(1)) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> jnp.uint32(2))
                                        & jnp.uint32(0x33333333))
    x = (x + (x >> jnp.uint32(4))) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> jnp.uint32(24)).astype(jnp.int32)


def novelty(sig: jnp.ndarray, corpus: CorpusState) -> jnp.ndarray:
    """Sketch distance of one signature against the corpus: the minimum
    Hamming distance (bits) to any filled entry's signature,
    :data:`EMPTY_NOVELTY` when nothing is filled."""
    d = popcount32(sig ^ corpus.sig)
    d = jnp.where(corpus.filled, d, jnp.int32(EMPTY_NOVELTY))
    return jnp.min(d)


def retiring_mask(w: int, n_act, idx: jnp.ndarray,
                  active: jnp.ndarray) -> jnp.ndarray:
    """The harvest-fold mask over a post-compaction batch: True on the
    retiring tail — rows past the live count ``n_act`` that still map to
    a real seed (``idx >= 0``; dead padding slots from a dry cursor stay
    excluded) and whose world actually finished (``~active``; frozen
    tails a shrink parked are not re-harvested).

    One definition shared by the jitted :func:`~madsim_tpu.search.generate.searcher`
    program and the fused whole-hunt superstep's in-loop epoch branch
    (parallel/sweep.py) — the fold *population* is half the bitwise
    contract between the two paths, so it must not be duplicated.
    """
    rows_r = jnp.arange(w, dtype=jnp.int32)
    return (rows_r >= n_act) & (idx >= 0) & ~active


def harvest_fold(corpus: CorpusState, sched: jnp.ndarray,
                 sigs: jnp.ndarray, fold_mask: jnp.ndarray,
                 min_novelty: int, entries: jnp.ndarray = None,
                 depths: jnp.ndarray = None,
                 with_masks: bool = False):
    """Fold the masked worlds' schedules into the corpus, sequentially.

    ``sched`` is the (W, F, 4) per-slot schedule array, ``sigs`` the
    (W,) u32 behavior signatures, ``fold_mask`` the (W,) bool of worlds
    retiring in this harvest. Returns the updated corpus and the number
    of inserts performed. Runs at the refill boundary — the same world-
    retirement edge the PR 6 coverage fold observes — where a retired
    slot's MetricsBlock is still frozen in place.

    ``entries``/``depths`` (obs/lineage.py): the candidates' lineage
    entry ids and ancestry depths, recorded on the corpus lanes at
    insert. Defaults (-1 / 0) keep lineage-off sweeps and the host
    parity tests total. Pure accounting — the insertion DECISION never
    reads them, so the sched/sig/score/filled outcome is bit-identical
    with or without lanes.

    ``with_masks=True`` additionally returns the per-world ``(novel,
    inserted)`` bool masks the operator outcome table credits from.
    """
    w = sigs.shape[0]
    if entries is None:
        entries = jnp.full((w,), -1, jnp.int32)
    if depths is None:
        depths = jnp.zeros((w,), jnp.int32)

    def body(j, carry):
        c, n_ins, nov_m, ins_m = carry
        nov = novelty(sigs[j], c)
        key = jnp.where(c.filled, c.score, jnp.int32(-1))
        tgt = jnp.argmin(key).astype(jnp.int32)
        novel_ok = fold_mask[j] & (nov >= jnp.int32(min_novelty))
        do = novel_ok & (nov > key[tgt])
        c = CorpusState(
            sched=jnp.where(do, c.sched.at[tgt].set(sched[j]), c.sched),
            sig=jnp.where(do, c.sig.at[tgt].set(sigs[j]), c.sig),
            score=jnp.where(do, c.score.at[tgt].set(nov), c.score),
            filled=jnp.where(do, c.filled.at[tgt].set(True), c.filled),
            gen=c.gen,
            inserted=c.inserted + do.astype(jnp.int32),
            entry=jnp.where(do, c.entry.at[tgt].set(entries[j]), c.entry),
            depth=jnp.where(do, c.depth.at[tgt].set(depths[j]), c.depth),
        )
        return (c, n_ins + do.astype(jnp.int32),
                nov_m.at[j].set(novel_ok), ins_m.at[j].set(do))

    corpus, n_ins, nov_m, ins_m = jax.lax.fori_loop(
        0, w, body, (corpus, jnp.int32(0), jnp.zeros((w,), bool),
                     jnp.zeros((w,), bool)))
    if with_masks:
        return corpus, n_ins, nov_m, ins_m
    return corpus, n_ins


# ---------------------------------------------------------------------------
# The corpus-merge half: a HOST twin of the device insertion fold
# ---------------------------------------------------------------------------
#
# The fleet's cross-range corpus exchange (fleet/exchange.py) merges
# published per-range corpora on the coordinator — a machine with no
# device state. The merge MUST be the same fold the device runs, bit for
# bit, because a re-issued lease seeds its sweep from the merged corpus
# and the chaos contract (chaotic fleet == clean fleet bitwise) rides on
# every worker deriving identical children from identical parents. So
# the insertion rule lives twice, like PR 9's FNV signature twin: once
# as the jitted ``harvest_fold`` above, once as plain numpy below, with
# a tier-1 parity test (tests/test_exchange.py) holding them together.

class HostCorpus(NamedTuple):
    """Host-side corpus snapshot: the exchanged arrays of a
    :class:`CorpusState` (the ``gen``/``inserted`` counters are per-sweep
    telemetry and stay behind). ``entry``/``depth`` are the lineage
    lanes (obs/lineage.py), merged through the exchange verbatim so a
    fleet-merged report can attribute finds across ranges."""

    sched: np.ndarray   # (K, F, 4) i32 parent schedules
    sig: np.ndarray     # (K,) u32 behavior signature at insert
    score: np.ndarray   # (K,) i32 novelty at insert
    filled: np.ndarray  # (K,) bool
    entry: np.ndarray   # (K,) i32 lineage entry id (-1 unfilled)
    depth: np.ndarray   # (K,) i32 ancestry depth at insert


def host_corpus_init(k: int, template: np.ndarray) -> HostCorpus:
    """Host twin of :func:`corpus_init`: the template-seeded corpus every
    epoch-0 range (and every non-exchanged sweep) starts from."""
    template = np.asarray(template, np.int32)
    sched = np.zeros((k, template.shape[0], 4), np.int32)
    sched[:, :, 0] = -1                      # DISABLED_ROW sentinels
    sched[0] = template
    filled = np.zeros((k,), bool)
    filled[0] = True
    entry = np.full((k,), -1, np.int32)
    entry[0] = 0                             # the template is entry 0
    return HostCorpus(sched=sched, sig=np.zeros((k,), np.uint32),
                      score=np.zeros((k,), np.int32), filled=filled,
                      entry=entry, depth=np.zeros((k,), np.int32))


def host_popcount32(x: int) -> int:
    """Population count of one u32 — the scalar twin of
    :func:`popcount32`."""
    return bin(int(x) & 0xFFFFFFFF).count("1")


def host_harvest_fold(corpus: HostCorpus, sched: np.ndarray,
                      sigs: np.ndarray, fold_mask: np.ndarray,
                      min_novelty: int, entries: np.ndarray = None,
                      depths: np.ndarray = None,
                      with_masks: bool = False):
    """Bit-identical host twin of :func:`harvest_fold`.

    Folds the masked candidates sequentially (index order) into the
    corpus under the same rule: novelty = min Hamming distance to any
    filled entry (:data:`EMPTY_NOVELTY` on an empty corpus); the target
    slot is the argmin of ``where(filled, score, -1)`` with ties to the
    lowest index; insert iff masked, ``novelty >= min_novelty`` and
    strictly above the target's key. Returns the updated corpus and the
    insert count (plus the per-candidate ``(novel, inserted)`` masks
    under ``with_masks``, like the device fold). ``entries``/``depths``
    are the candidates' lineage lanes, recorded at insert (defaults
    -1 / 0, matching the device fold's). Parity with the device fold is
    tier-1-gated.
    """
    c_sched = np.array(corpus.sched, np.int32, copy=True)
    c_sig = np.array(corpus.sig, np.uint32, copy=True)
    c_score = np.array(corpus.score, np.int32, copy=True)
    c_filled = np.array(corpus.filled, bool, copy=True)
    c_entry = np.array(corpus.entry, np.int32, copy=True)
    c_depth = np.array(corpus.depth, np.int32, copy=True)
    sched = np.asarray(sched, np.int32)
    sigs = np.asarray(sigs, np.uint32)
    fold_mask = np.asarray(fold_mask, bool)
    w = sigs.shape[0]
    entries = (np.full((w,), -1, np.int32) if entries is None
               else np.asarray(entries, np.int32))
    depths = (np.zeros((w,), np.int32) if depths is None
              else np.asarray(depths, np.int32))
    nov_m = np.zeros((w,), bool)
    ins_m = np.zeros((w,), bool)
    n_ins = 0
    for j in range(w):
        if c_filled.any():
            d = np.array([host_popcount32(int(sigs[j]) ^ int(s))
                          for s in c_sig], np.int32)
            nov = int(np.where(c_filled, d, np.int32(EMPTY_NOVELTY)).min())
        else:
            nov = EMPTY_NOVELTY
        key = np.where(c_filled, c_score, np.int32(-1))
        tgt = int(np.argmin(key))            # first-min ties, like argmin
        nov_m[j] = bool(fold_mask[j]) and nov >= int(min_novelty)
        if nov_m[j] and nov > int(key[tgt]):
            c_sched[tgt] = sched[j]
            c_sig[tgt] = sigs[j]
            c_score[tgt] = nov
            c_filled[tgt] = True
            c_entry[tgt] = entries[j]
            c_depth[tgt] = depths[j]
            ins_m[j] = True
            n_ins += 1
    out = HostCorpus(sched=c_sched, sig=c_sig, score=c_score,
                     filled=c_filled, entry=c_entry, depth=c_depth)
    if with_masks:
        return out, n_ins, nov_m, ins_m
    return out, n_ins


def merge_corpus(acc: HostCorpus, src: HostCorpus,
                 min_novelty: int) -> Tuple[HostCorpus, int]:
    """Fold one published corpus into the accumulating merged corpus.

    The source's filled entries are candidates in slot-index order —
    the same sequential worst-first insertion the device applies to a
    retiring tail, so the merged corpus of an epoch is a pure fold over
    (previous merged corpus, per-range snapshots in range-id order).
    Scores are RE-computed against the accumulator (an entry novel
    within its own range may be redundant fleet-wide); the lineage
    lanes (entry id, depth) travel VERBATIM — an entry keeps its
    origin-range identity, which is what lets the fleet-merged report
    resolve cross-range ancestry (obs/lineage.py).
    """
    return host_harvest_fold(acc, np.asarray(src.sched, np.int32),
                             np.asarray(src.sig, np.uint32),
                             np.asarray(src.filled, bool), min_novelty,
                             entries=np.asarray(src.entry, np.int32),
                             depths=np.asarray(src.depth, np.int32))


def pick_filled(corpus: CorpusState, draws: jnp.ndarray) -> jnp.ndarray:
    """Map u32 draws to filled corpus indices, uniformly over the filled
    entries (corpus_init guarantees at least one). ``draws`` may carry
    any batch shape; the result holds i32 corpus indices."""
    cum = jnp.cumsum(corpus.filled.astype(jnp.int32), dtype=jnp.int32)
    n_f = jnp.maximum(cum[-1], jnp.int32(1))
    j = (draws % n_f.astype(jnp.uint32)).astype(jnp.int32)
    # Index of the (j+1)-th filled slot: first k with cum[k] == j+1.
    return jnp.searchsorted(cum, j + 1, side="left").astype(jnp.int32)
