# tracelint: hot-loop
"""Device splitmix64 lanes: the mutation randomness of the guided hunt.

The schedule generator (search/mutate.py) needs per-slot random draws
INSIDE the jitted refill-boundary program, and they must be counter-based
— a pure function of ``(search seed, slot seed id, generation, draw
index)`` — so every generated child is replayable from the sweep's
inputs alone (the counter-PRNG reproducibility argument of PAPERS.md;
the same property the engine gets from Threefry in engine/rng.py and the
fleet fabric gets from its host splitmix64 in fleet/rpc.py).

This module is the device twin of :func:`madsim_tpu.fleet.rpc.splitmix64`
— bit-identical by construction (tier-1, tests/test_search.py): a u64 is
carried as two u32 limbs because the sweep runs with the x64 flag off,
and the 64-bit adds/multiplies of the splitmix64 finalizer are spelled
out in 32/16-bit partial products. Stream keys are derived through
engine/rng.py's Threefry (the engine's one key-derivation function), so
the search stream can never collide with the simulation streams that
share the same world seed.

Draw layout: slot ``w`` with generation ``g`` gets the 64-bit stream
state ``x0 = threefry2x32(search_seed, seed_id(w), g, STREAM_SEARCH)``
and lane ``i`` is ``splitmix64(x0 + i * GAMMA)`` — the host function
applied at an offset counter, with the low 32 bits used as the draw.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..ops.threefry import threefry2x32_jax

# The splitmix64 increment (golden-ratio gamma) and finalizer constants,
# split into u32 limbs (hi, lo). Values match fleet/rpc.py exactly.
_GAMMA = (0x9E3779B9, 0x7F4A7C15)
_MUL1 = (0xBF58476D, 0x1CE4E5B9)
_MUL2 = (0x94D049BB, 0x133111EB)

# Threefry stream id of the search generator — far outside the engine's
# actor/device stream ids so search draws never alias simulation draws.
STREAM_SEARCH = 0x5EA7C4


def _u32(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.uint32)


def _add64(a: Tuple, b: Tuple) -> Tuple:
    """(hi, lo) + (hi, lo) mod 2^64 in u32 limbs."""
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    return a[0] + b[0] + carry, lo


def _mul32_64(a: jnp.ndarray, b: jnp.ndarray) -> Tuple:
    """Full 32x32 -> 64 product of two u32s, as (hi, lo) u32 limbs
    (16-bit partial products; jax has no u32 mulhi primitive)."""
    a0, a1 = a & _u32(0xFFFF), a >> _u32(16)
    b0, b1 = b & _u32(0xFFFF), b >> _u32(16)
    lo = a0 * b0
    mid = a1 * b0 + a0 * b1          # may wrap u32: the wrap IS the carry
    mid_carry = (mid < a1 * b0).astype(jnp.uint32) << _u32(16)
    hi = a1 * b1 + (mid >> _u32(16)) + mid_carry
    lo2 = lo + ((mid & _u32(0xFFFF)) << _u32(16))
    hi = hi + (lo2 < lo).astype(jnp.uint32)
    return hi, lo2


def _mul64(a: Tuple, b: Tuple) -> Tuple:
    """(hi, lo) * (hi, lo) mod 2^64: full low product + wrapping cross
    terms into the high limb."""
    hi, lo = _mul32_64(a[1], b[1])
    hi = hi + a[1] * b[0] + a[0] * b[1]
    return hi, lo


def _shr64_xor(x: Tuple, s: int) -> Tuple:
    """x ^ (x >> s) for 0 < s < 32, in limbs."""
    hi, lo = x
    sh_lo = (lo >> _u32(s)) | (hi << _u32(32 - s))
    sh_hi = hi >> _u32(s)
    return hi ^ sh_hi, lo ^ sh_lo


def splitmix64_dev(x: Tuple) -> Tuple:
    """One splitmix64 step on a (hi, lo) u32-limb u64 — bit-identical to
    :func:`madsim_tpu.fleet.rpc.splitmix64` (tier-1-tested parity)."""
    x = _add64(x, (_u32(_GAMMA[0]), _u32(_GAMMA[1])))
    x = _shr64_xor(x, 30)
    x = _mul64(x, (_u32(_MUL1[0]), _u32(_MUL1[1])))
    x = _shr64_xor(x, 27)
    x = _mul64(x, (_u32(_MUL2[0]), _u32(_MUL2[1])))
    return _shr64_xor(x, 31)


def stream_key(search_seed: int, seed_ids: jnp.ndarray,
               generation) -> Tuple:
    """Per-slot 64-bit stream state ``x0`` from the search seed, the
    slot's (refill) seed id vector, and the generation counter — derived
    through engine/rng.py's Threefry so the search stream is disjoint
    from every simulation stream of the same world seed."""
    ids = jnp.asarray(seed_ids, jnp.int32).astype(jnp.uint32)
    gen = jnp.asarray(generation, jnp.int32).astype(jnp.uint32)
    k0, k1 = threefry2x32_jax(
        _u32(search_seed & 0xFFFFFFFF) ^ ids,
        _u32((search_seed >> 32) & 0xFFFFFFFF),
        gen, _u32(STREAM_SEARCH))
    return k1, k0  # (hi, lo)


def lanes_u32(x0: Tuple, n_draws: int) -> jnp.ndarray:
    """``n_draws`` u32 lanes per stream: lane ``i`` is the low limb of
    ``splitmix64(x0 + i * GAMMA)`` (counter-based — no carried state).
    ``x0`` limbs may carry leading batch axes; the draw axis is appended
    last, so the result is ``x0.shape + (n_draws,)``."""
    i = jnp.arange(n_draws, dtype=jnp.uint32)
    # i * GAMMA in limbs, broadcast against the stream batch axes.
    g_hi, g_lo = _mul32_64(i, _u32(_GAMMA[1]))
    g_hi = g_hi + i * _u32(_GAMMA[0])
    hi = x0[0][..., None] + jnp.zeros_like(g_hi)
    lo = x0[1][..., None] + jnp.zeros_like(g_lo)
    ctr = _add64((hi, lo), (g_hi, g_lo))
    return splitmix64_dev(ctr)[1]


def pct(draw: jnp.ndarray) -> jnp.ndarray:
    """Map a u32 draw to an int32 percent bucket in [0, 100)."""
    return (draw % _u32(100)).astype(jnp.int32)
