"""SearchConfig: the knobs of the coverage-guided fault-schedule search.

Frozen and hashable — it keys the cached compiled generator program
(search/generate.py) exactly like ``EngineConfig`` keys the engine's
step programs, so two sweeps with the same knobs share one compile.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SearchConfig:
    """Static parameters of the guided-refill schedule generator.

    The mutation percentages select AT MOST one structural mutation per
    schedule row (one draw against their cumulative ranges): disable,
    time jitter, node/param perturbation, or op flip — after the
    two-parent row splice has been applied at ``splice_pct`` per row.
    Rows falling past the cumulative sum are copied unchanged, so a
    child can also be a pure recombination.
    """

    # Corpus capacity: device-resident (K, F, 4) schedules of surviving
    # high-novelty worlds. Small on purpose — the corpus is a parent
    # pool, not an archive (triage/corpus.py owns the failure archive).
    corpus: int = 64
    # The search stream seed (u64). Mutation lanes are a pure function
    # of (seed, slot seed id, generation) — rerunning a hunt with the
    # same SearchConfig reproduces every child bit for bit.
    seed: int = 0x5EED_5EA7_C4
    # Minimum signature sketch distance (bits of the u32 behavior
    # signature, obs/coverage.py) a retiring world must clear against
    # every corpus entry to be inserted. 1 = any unseen signature.
    min_novelty: int = 1
    # Per-row probability (percent) of splicing the row from the second
    # parent before mutation — the two-parent crossover operator.
    splice_pct: int = 25
    # Cumulative per-row mutation distribution (percent of rows drawing
    # each operator; the remainder stays unmutated).
    disable_pct: int = 8
    time_pct: int = 22
    node_pct: int = 25
    op_pct: int = 10
    # Fire-time jitter half-width in virtual µs; 0 derives
    # ``EngineConfig.t_limit_us // 16`` at program-build time.
    time_jitter_us: int = 0
    # False: the corpus never updates past the seeded template — every
    # child is a fresh random mutation of the ORIGINAL schedule. This is
    # the matched random-fuzzing baseline (same operators, same budget,
    # no coverage feedback) that `bench.py guided_hunt` and
    # `make fuzz-demo` compare guided search against.
    guided: bool = True
    # Provenance lanes + per-operator outcome accounting (obs/lineage.py,
    # docs/search.md "Reading the lineage"): every installed child
    # carries its parent corpus-entry ids, applied-operator bitmask and
    # ancestry depth, and the generator accumulates the per-operator
    # produced/novel/survived/bug table — all device-resident,
    # write-only, synced on the cadence the sweep already pays. False
    # compiles every lane out; lineage-on is bitwise identical to
    # lineage-off on trajectories/schedules/corpus (tier-1-gated).
    lineage: bool = True

    def __post_init__(self):
        if self.corpus < 1:
            raise ValueError("SearchConfig.corpus must be >= 1")
        if self.min_novelty < 1:
            raise ValueError("SearchConfig.min_novelty must be >= 1 "
                             "(0 would admit exact duplicates)")
        for name in ("splice_pct", "disable_pct", "time_pct", "node_pct",
                     "op_pct"):
            v = getattr(self, name)
            if not 0 <= v <= 100:
                raise ValueError(f"SearchConfig.{name} must be in [0, 100]")
        total = (self.disable_pct + self.time_pct + self.node_pct
                 + self.op_pct)
        if total > 100:
            raise ValueError(
                f"SearchConfig mutation percentages are a cumulative "
                f"distribution over one draw per row: disable+time+node+op "
                f"= {total} exceeds 100")
        if self.time_jitter_us < 0:
            raise ValueError("SearchConfig.time_jitter_us must be >= 0")
