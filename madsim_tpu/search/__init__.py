"""Coverage-guided fault-schedule search: the closed fuzzer loop.

The generator half of PAPER.md's always-on hunting service (ROADMAP
item 2). PR 6 built the feedback signal (the device-resident behavior-
coverage ledger) and PR 9 the triage back end (batched ddmin + the
deduplicated corpus of minimized repro bundles); this package closes
the loop by *generating new inputs*: retiring worlds' fault schedules
are scored for novelty against a device-resident corpus, novel
survivors become parents, and ``sweep(recycle=True,
search=SearchConfig(...))`` refills retired slots with mutated/crossed-
over children instead of fixed schedules — device-hours in, a
1-minimal deduplicated failure corpus out (every find pipes unchanged
through ``triage.triage`` → ddmin → minimized bundles, because the
sweep materializes each world's actual schedule into its triage
context).

Module map (docs/search.md):

- :mod:`~madsim_tpu.search.config` — ``SearchConfig``, the static knobs.
- :mod:`~madsim_tpu.search.rng` — device splitmix64 lanes (counter-based
  mutation randomness; bit-identical to the fleet's host splitmix64).
- :mod:`~madsim_tpu.search.corpus` — the device-resident parent corpus
  + novelty scoring (signature sketch distance).
- :mod:`~madsim_tpu.search.mutate` — splice/disable/jitter/rotate/flip
  operators, validity-preserving by construction.
- :mod:`~madsim_tpu.search.generate` — the jitted harvest+generate
  program (tracelint registry: ``search.generate``).
- :mod:`~madsim_tpu.search.family` — ``GuidedPairActor``, the
  conjunction-bug family with observable progress that ``bench.py
  guided_hunt`` and ``make fuzz-demo`` gate on.
"""
import dataclasses as _dc
from typing import Dict as _Dict

import numpy as _np

from .config import SearchConfig
from .corpus import EMPTY_NOVELTY, CorpusState, corpus_init
from .family import (
    GuidedPairActor,
    GuidedPairConfig,
    engine_config,
    family_schedule,
)


@_dc.dataclass
class SearchReport:
    """Host-side outcome of one guided sweep (``SweepResult.search``).

    ``schedules`` is the materialized per-seed ``(n, F, 4)`` array of
    the schedule each seed's world ACTUALLY ran (template rows for the
    first batch, generated children after) — the attribution that makes
    a guided find replayable and triageable; it is also installed as
    ``SweepResult.triage_ctx.faults``. The corpus arrays are the final
    device corpus, pulled once at sweep end.
    """

    generations: int             # guided-refill generations run
    inserted: int                # total corpus inserts over the sweep
    corpus_size: int             # filled corpus entries at exit
    corpus_capacity: int
    corpus_sched: _np.ndarray    # (K, F, 4) parent schedules
    corpus_sig: _np.ndarray      # (K,) u32 signatures at insert
    corpus_score: _np.ndarray    # (K,) novelty at insert (-0 unfilled)
    corpus_filled: _np.ndarray   # (K,) bool
    schedules: _np.ndarray       # (n, F, 4) per-seed materialized rows

    def to_json(self) -> _Dict[str, object]:
        """Compact JSON-safe record (bench_results.json ``search``)."""
        return {
            "generations": int(self.generations),
            "inserted": int(self.inserted),
            "corpus_size": int(self.corpus_size),
            "corpus_capacity": int(self.corpus_capacity),
        }


__all__ = [
    "SearchConfig",
    "SearchReport",
    "CorpusState",
    "corpus_init",
    "EMPTY_NOVELTY",
    "GuidedPairActor",
    "GuidedPairConfig",
    "family_schedule",
    "engine_config",
]
