"""Coverage-guided fault-schedule search: the closed fuzzer loop.

The generator half of PAPER.md's always-on hunting service (ROADMAP
item 2). PR 6 built the feedback signal (the device-resident behavior-
coverage ledger) and PR 9 the triage back end (batched ddmin + the
deduplicated corpus of minimized repro bundles); this package closes
the loop by *generating new inputs*: retiring worlds' fault schedules
are scored for novelty against a device-resident corpus, novel
survivors become parents, and ``sweep(recycle=True,
search=SearchConfig(...))`` refills retired slots with mutated/crossed-
over children instead of fixed schedules — device-hours in, a
1-minimal deduplicated failure corpus out (every find pipes unchanged
through ``triage.triage`` → ddmin → minimized bundles, because the
sweep materializes each world's actual schedule into its triage
context).

Module map (docs/search.md):

- :mod:`~madsim_tpu.search.config` — ``SearchConfig``, the static knobs.
- :mod:`~madsim_tpu.search.rng` — device splitmix64 lanes (counter-based
  mutation randomness; bit-identical to the fleet's host splitmix64).
- :mod:`~madsim_tpu.search.corpus` — the device-resident parent corpus
  + novelty scoring (signature sketch distance).
- :mod:`~madsim_tpu.search.mutate` — splice/disable/jitter/rotate/flip
  operators, validity-preserving by construction.
- :mod:`~madsim_tpu.search.generate` — the jitted harvest+generate
  program (tracelint registry: ``search.generate``).
- :mod:`~madsim_tpu.search.family` — ``GuidedPairActor``, the
  conjunction-bug family with observable progress that ``bench.py
  guided_hunt`` and ``make fuzz-demo`` gate on.
"""
import dataclasses as _dc
from typing import Dict as _Dict

import numpy as _np

from .config import SearchConfig
from .corpus import EMPTY_NOVELTY, CorpusState, corpus_init
from .family import (
    GuidedPairActor,
    GuidedPairConfig,
    engine_config,
    family_schedule,
)


@_dc.dataclass
class SearchReport:
    """Host-side outcome of one guided sweep (``SweepResult.search``).

    ``schedules`` is the materialized per-seed ``(n, F, 4)`` array of
    the schedule each seed's world ACTUALLY ran (template rows for the
    first batch, generated children after) — the attribution that makes
    a guided find replayable and triageable; it is also installed as
    ``SweepResult.triage_ctx.faults``. The corpus arrays are the final
    device corpus, pulled once at sweep end.

    ``lineage`` / ``operator_stats`` (obs/lineage.py, present when the
    sweep ran ``SearchConfig(lineage=True)``, the default): the
    per-seed provenance lanes — parent corpus-entry ids, applied-
    operator bitmask, ancestry depth — and the per-operator outcome
    table (children produced / novel / survived-to-corpus /
    bug-finding per operator class). ``corpus_entry``/``corpus_depth``
    are the corpus's own lineage lanes, carried through the fleet's
    corpus exchange verbatim so merged reports attribute finds across
    ranges.
    """

    generations: int             # guided-refill generations run
    inserted: int                # total corpus inserts over the sweep
    corpus_size: int             # filled corpus entries at exit
    corpus_capacity: int
    corpus_sched: _np.ndarray    # (K, F, 4) parent schedules
    corpus_sig: _np.ndarray      # (K,) u32 signatures at insert
    corpus_score: _np.ndarray    # (K,) novelty at insert (-0 unfilled)
    corpus_filled: _np.ndarray   # (K,) bool
    schedules: _np.ndarray       # (n, F, 4) per-seed materialized rows
    corpus_entry: _np.ndarray = None   # (K,) i32 lineage entry ids
    corpus_depth: _np.ndarray = None   # (K,) i32 ancestry depth at insert
    lineage: object = None             # obs/lineage.py SearchLineage
    operator_stats: _Dict[str, _Dict[str, int]] = None

    def ancestry(self, seed: int, seeds: _np.ndarray = None):
        """The ancestry chain of ``seed``'s world (a list of nodes back
        to the generation-0 template, obs/lineage.py ``ancestry``).
        ``seeds`` maps positions to seed values; defaults to positions
        == values (the canonical arange hunts)."""
        from ..obs.lineage import ancestry as _ancestry

        if self.lineage is None:
            raise ValueError(
                "this SearchReport carries no lineage (the sweep ran "
                "SearchConfig(lineage=False)) — re-run with lineage=True "
                "(the default) to record provenance lanes")
        if seeds is not None:
            rows = _np.flatnonzero(_np.asarray(seeds) == seed)
            if rows.size == 0:
                raise ValueError(f"seed {seed} was not part of this sweep")
            pos = int(rows[0])
        else:
            pos = int(seed)
        return _ancestry(self.lineage, pos, seeds=seeds)

    def lineage_depth(self) -> int:
        """Deepest ancestry chain materialized by this sweep (0 when
        lineage was off or nothing evolved)."""
        return self.lineage.max_depth if self.lineage is not None else 0

    def summary(self) -> str:
        """Human rendering of the search outcome: corpus fill, insert
        pressure, and the per-operator effectiveness table the future
        credit-assignment scheduler will feed on (docs/search.md
        "Reading the lineage")."""
        from ..obs.lineage import render_operator_table, top_operator

        lines = [f"guided search: corpus {self.corpus_size}/"
                 f"{self.corpus_capacity} filled, {self.inserted} "
                 f"insert(s) over {self.generations} generation(s)"]
        if self.lineage is not None:
            lines[0] += f", max ancestry depth {self.lineage_depth()}"
        if self.operator_stats:
            top = top_operator(self.operator_stats)
            if top:
                lines[0] += f", top operator {top}"
            lines.append(render_operator_table(self.operator_stats))
        return "\n".join(lines)

    def to_json(self) -> _Dict[str, object]:
        """Compact JSON-safe record (bench_results.json ``search``)."""
        out = {
            "generations": int(self.generations),
            "inserted": int(self.inserted),
            "corpus_size": int(self.corpus_size),
            "corpus_capacity": int(self.corpus_capacity),
        }
        if self.operator_stats is not None:
            out["operator_stats"] = self.operator_stats
        if self.lineage is not None:
            out["lineage"] = self.lineage.to_json()
        return out


__all__ = [
    "SearchConfig",
    "SearchReport",
    "CorpusState",
    "corpus_init",
    "EMPTY_NOVELTY",
    "GuidedPairActor",
    "GuidedPairConfig",
    "family_schedule",
    "engine_config",
]
