# tracelint: hot-loop
"""Schedule mutation/crossover: the child generators of the guided hunt.

Operators over ``(F, 4)`` fault-schedule rows ``[time_us, op, a, b]``,
vectorized over a ``(W, F)`` batch inside the jitted generator program
(search/generate.py). Validity is preserved **by construction**, which
is what lets the sweep's refill path skip host-side value validation
(``DeviceEngine.refill``'s device-schedule contract): given parents
whose enabled rows are valid for the engine config — the seeded template
is validated by ``init()`` at sweep start, children by induction — every
operator below maps valid rows to valid rows:

- **two-parent row splice** (``splice_pct`` per row): take the row from
  the second parent instead of the first — also the only way a disabled
  row revives, which keeps ragged schedules reachable in both
  directions.
- **row disable**: rewrite to the canonical ``DISABLED_ROW``
  (triage/shrink.py's drop-as-disable representation — shapes stay
  static, and triage's dedup sees canonical arrays).
- **time jitter**: fire time moves by up to ±``time_jitter_us``,
  clamped to ``[1, t_limit_us - 1]`` (never disables, never escapes the
  simulated window).
- **node/param perturbation**: node ops rotate their target(s) within
  ``[0, n_nodes)``; ``SET_LOSS`` resamples its ppm in ``[0, 1e6]``;
  ``SET_LATENCY`` resamples a legal window above its min.
- **op flip**: replace the op within its argument-compatible class —
  {KILL, RESTART, PAUSE, RESUME}, {CLOG_NODE, UNCLOG_NODE},
  {CLOG_LINK, UNCLOG_LINK}. Net-config ops never flip (their params
  ride the payload channel with its own width precondition), so a
  template without SET rows can never grow one.

Each row draws ONE structural mutation from the cumulative
``SearchConfig`` distribution (disable | time | node | op | none), after
the splice draw — matching the classic mutation-stacking of
coverage-guided fuzzers while keeping the per-row draw budget static.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..engine.core import (
    FAULT_CLOG_LINK,
    FAULT_CLOG_NODE,
    FAULT_KILL,
    FAULT_PAUSE,
    FAULT_RESTART,
    FAULT_RESUME,
    FAULT_SET_LATENCY,
    FAULT_SET_LOSS,
    FAULT_UNCLOG_LINK,
    FAULT_UNCLOG_NODE,
)
from .config import SearchConfig
from .corpus import CorpusState, pick_filled
from .rng import lanes_u32, pct, stream_key

# Draws consumed per row / per slot (search/rng.py lane layout).
ROW_DRAWS = 5      # splice, select, time, node, op
SLOT_DRAWS = 4     # parent 1 tournament pair, parent 2 tournament pair

# Argument-compatible op-flip classes: liveness ops (a = node, b unused),
# node clogs, link clogs. A flip rotates within the row's class.
_LIVENESS = (FAULT_KILL, FAULT_RESTART, FAULT_PAUSE, FAULT_RESUME)


def _i32(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.int32)


def _flip_op(op: jnp.ndarray, r: jnp.ndarray) -> jnp.ndarray:
    """The op-flip operator: a different member of the row's class
    (uniform over the other members), identity for SET_* rows."""
    lv = jnp.stack([_i32(o) for o in _LIVENESS])
    # Position of op within the liveness class (4 members -> +1..+3).
    is_lv = ((op == FAULT_KILL) | (op == FAULT_RESTART)
             | (op == FAULT_PAUSE) | (op == FAULT_RESUME))
    lv_pos = (_i32(op == FAULT_RESTART) * 1 + _i32(op == FAULT_PAUSE) * 2
              + _i32(op == FAULT_RESUME) * 3)
    lv_new = lv[(lv_pos + 1 + (r % jnp.uint32(3)).astype(jnp.int32)) % 4]
    node_clog = (op == FAULT_CLOG_NODE) | (op == FAULT_UNCLOG_NODE)
    link_clog = (op == FAULT_CLOG_LINK) | (op == FAULT_UNCLOG_LINK)
    out = jnp.where(is_lv, lv_new, op)
    out = jnp.where(node_clog,
                    _i32(FAULT_CLOG_NODE) + _i32(FAULT_UNCLOG_NODE)
                    - op, out)
    out = jnp.where(link_clog,
                    _i32(FAULT_CLOG_LINK) + _i32(FAULT_UNCLOG_LINK)
                    - op, out)
    return out


def make_children(scfg: SearchConfig, ecfg, corpus: CorpusState,
                  seed_ids: jnp.ndarray, generation,
                  lineage: bool = False):
    """Generate one child schedule per slot: ``(W, F, 4)`` i32.

    ``seed_ids`` is the (W,) i32 vector of the seed ids the refilled
    slots will simulate (placeholders for unrefilled slots — their
    children are discarded by the refill select). Every child is a pure
    function of ``(SearchConfig.seed, seed_id, generation)`` plus the
    corpus contents: bitwise reproducible, replayable, and identical
    between the serial and pipelined sweep loops (which call this at
    identical refill points).

    ``lineage=True`` (obs/lineage.py) additionally returns each child's
    :class:`~madsim_tpu.obs.lineage.LineageLanes`: the two tournament
    parents' corpus ENTRY ids, the applied-operator bitmask folded from
    the per-row masks this function already computes (exposed, never
    recomputed — no extra draw, no changed draw order, so child BYTES
    are identical either way), and the ancestry depth ``1 +
    max(parent depths)``.
    """
    f_rows = corpus.sched.shape[1]
    n = int(ecfg.n_nodes)
    jitter = (int(scfg.time_jitter_us) if scfg.time_jitter_us
              else max(int(ecfg.t_limit_us) // 16, 1))
    t_max = int(ecfg.t_limit_us) - 1

    x0 = stream_key(scfg.seed, seed_ids, generation)
    draws = lanes_u32(x0, SLOT_DRAWS + f_rows * ROW_DRAWS)  # (W, D)
    rows_d = draws[:, SLOT_DRAWS:].reshape(
        draws.shape[0], f_rows, ROW_DRAWS)
    r_splice, r_sel, r_t, r_n, r_o = (rows_d[..., k] for k in range(5))

    def tournament(da, db):
        """Binary tournament over the filled entries: of two uniform
        picks, keep the higher insertion-novelty score (first pick on
        ties) — the standard selection-pressure knob of evolutionary
        fuzzers, deterministic given the corpus."""
        ca, cb = pick_filled(corpus, da), pick_filled(corpus, db)
        return jnp.where(corpus.score[cb] > corpus.score[ca], cb, ca)

    p1 = tournament(draws[:, 0], draws[:, 1])
    p2 = tournament(draws[:, 2], draws[:, 3])
    base = corpus.sched[p1]      # (W, F, 4)
    other = corpus.sched[p2]

    # Two-parent splice, per row.
    do_splice = pct(r_splice) < _i32(scfg.splice_pct)
    row = jnp.where(do_splice[..., None], other, base)
    t, op, a, b = (row[..., k] for k in range(4))
    enabled = t >= 0

    # One structural mutation per row, drawn from the cumulative ranges.
    m = pct(r_sel)
    c_dis = _i32(scfg.disable_pct)
    c_time = c_dis + _i32(scfg.time_pct)
    c_node = c_time + _i32(scfg.node_pct)
    c_op = c_node + _i32(scfg.op_pct)
    do_dis = enabled & (m < c_dis)
    do_time = enabled & (m >= c_dis) & (m < c_time)
    do_node = enabled & (m >= c_time) & (m < c_node)
    do_op = enabled & (m >= c_node) & (m < c_op)

    # Time jitter: ±jitter, clamped inside the simulated window.
    delta = (r_t % jnp.uint32(2 * jitter + 1)).astype(jnp.int32) - jitter
    t = jnp.where(do_time, jnp.clip(t + delta, 1, t_max), t)

    # Node/param perturbation.
    is_set_lat = op == FAULT_SET_LATENCY
    is_set_loss = op == FAULT_SET_LOSS
    is_link = (op == FAULT_CLOG_LINK) | (op == FAULT_UNCLOG_LINK)
    is_node_op = ~is_set_lat & ~is_set_loss
    rot_a = (a + 1 + (r_n % jnp.uint32(max(n - 1, 1))).astype(jnp.int32)) \
        % _i32(n)
    rot_b = (b + 1 + ((r_n >> jnp.uint32(8))
                      % jnp.uint32(max(n - 1, 1))).astype(jnp.int32)) \
        % _i32(n)
    new_loss = (r_n % jnp.uint32(1_000_001)).astype(jnp.int32)
    new_lat_hi = a + 1 + (r_n % jnp.uint32(1_000_000)).astype(jnp.int32)
    a = jnp.where(do_node & is_node_op, rot_a,
                  jnp.where(do_node & is_set_loss, new_loss, a))
    b = jnp.where(do_node & is_link, rot_b,
                  jnp.where(do_node & is_set_lat, new_lat_hi, b))

    # Op flip within the argument-compatible class.
    op = jnp.where(do_op, _flip_op(op, r_o), op)

    t = jnp.where(do_dis, _i32(-1), t)
    child = jnp.stack([t, op, a, b], axis=-1)
    # Canonical disabled rows (triage/shrink.py DISABLED_ROW), so
    # schedule identity is bitwise no matter which operator disabled a
    # row.
    disabled = child[..., 0] < 0
    child = jnp.where(disabled[..., None],
                      jnp.asarray([-1, 0, 0, 0], jnp.int32), child)
    if not lineage:
        return child
    # Provenance lanes (obs/lineage.py): the per-row operator masks
    # computed above, OR-folded to one bit per operator class, the two
    # tournament parents' corpus entry ids, and the ancestry depth.
    # Write-only — nothing below feeds back into the child bytes.
    from ..obs.lineage import LineageLanes, pack_ops

    ops = pack_ops([jnp.any(m, axis=-1) for m in
                    (do_splice, do_dis, do_time, do_node, do_op)])
    d1, d2 = corpus.depth[p1], corpus.depth[p2]
    return child, LineageLanes(
        p1=corpus.entry[p1], p2=corpus.entry[p2], ops=ops,
        depth=jnp.int32(1) + jnp.maximum(d1, d2))
