"""Endpoint: tag-matched messaging, the primary user-facing primitive.

Reference: `madsim/src/sim/net/endpoint.rs` — bind/connect (`:14-35`),
``send_to``/``recv_from`` with tag matching plus raw-payload variants
(`:59-163`), connection-oriented ``connect1``/``accept1`` (`:167-229`), and a
``Mailbox`` that tries pending receivers first, else buffers (`:241-306`).
Registered under the UDP protocol but with unbounded buffering.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ..core import context
from ..core.futures import Channel, ChannelClosed, SimFuture
from ..core.timewheel import to_ns as _to_ns
from .addr import Addr, AddrLike, lookup_host, parse_addr
from .netsim import (
    BindGuard,
    BrokenPipe,
    ChannelReceiver,
    ChannelSender,
    ConnectionReset,
    NetSim,
    _netsim,
)
from .network import IpProtocol, NetworkError, Socket


class _Message:
    __slots__ = ("tag", "data", "from_addr")

    def __init__(self, tag: int, data: Any, from_addr: Addr):
        self.tag = tag
        self.data = data
        self.from_addr = from_addr


class _Mailbox:
    """Tag-matched mailbox (`endpoint.rs:274-306`): deliver tries pending
    receivers (skipping abandoned ones), else buffers; recv takes a matching
    buffered message, else registers."""

    __slots__ = ("registered", "msgs")

    def __init__(self):
        self.registered: List[Tuple[int, SimFuture]] = []
        self.msgs: List[_Message] = []

    def deliver(self, msg: _Message) -> None:
        for i, (tag, fut) in enumerate(self.registered):
            if tag == msg.tag and not fut.done():
                del self.registered[i]
                fut.set_result(msg)
                return
        # Drop completed/abandoned registrations opportunistically.
        self.registered = [(t, f) for (t, f) in self.registered if not f.done()]
        self.msgs.append(msg)

    def recv(self, tag: int) -> SimFuture:
        fut = SimFuture()
        for i, msg in enumerate(self.msgs):
            if msg.tag == tag:
                del self.msgs[i]
                fut.set_result(msg)
                return fut
        self.registered.append((tag, fut))
        return fut

    def unregister(self, fut: SimFuture) -> None:
        self.registered = [(t, f) for (t, f) in self.registered if f is not fut]

    def requeue_front(self, msg: _Message) -> None:
        self.msgs.insert(0, msg)

    def close(self) -> None:
        for _, fut in self.registered:
            if not fut.done():
                fut.set_exception(BrokenPipe("network is down"))
        self.registered.clear()


class _EndpointSocket(Socket):
    __slots__ = ("mailbox", "conn_queue")

    def __init__(self):
        self.mailbox = _Mailbox()
        self.conn_queue = Channel()  # (tx, rx, src_addr) incoming connections

    def deliver(self, src: Addr, dst: Addr, msg) -> None:
        tag, data = msg
        self.mailbox.deliver(_Message(tag, data, src))

    def new_connection(self, src: Addr, dst: Addr, tx, rx) -> None:
        try:
            self.conn_queue.send((tx, rx, src))
        except ChannelClosed:
            pass


class Endpoint:
    """Bindable, tag-matching network endpoint."""

    def __init__(self, guard: BindGuard, socket: _EndpointSocket):
        self._guard = guard
        self._socket = socket
        self._peer: Optional[Addr] = None

    # -- constructors ------------------------------------------------------
    @staticmethod
    async def bind(addr: AddrLike) -> "Endpoint":
        from ..core.backend import is_real

        if is_real():
            # Production backend: the same tag-matching API over a real
            # framed transport — TCP by default, Unix sockets with
            # MADSIM_REAL_TRANSPORT=uds (`std/net/tcp.rs:20-324` analog;
            # transport selection mirrors the ucx/erpc feature flags).
            from ..real.net import real_endpoint_class

            return await real_endpoint_class().bind(addr)
        socket = _EndpointSocket()
        guard = await BindGuard.bind(addr, IpProtocol.UDP, socket)
        return Endpoint(guard, socket)

    @staticmethod
    async def connect(addr: AddrLike) -> "Endpoint":
        from ..core.backend import is_real

        if is_real():
            from ..real.net import real_endpoint_class

            return await real_endpoint_class().connect(addr)
        peer = (await lookup_host(addr))[0]
        ep = await Endpoint.bind("0.0.0.0:0")
        ep._peer = peer
        return ep

    # -- introspection -----------------------------------------------------
    def local_addr(self) -> Addr:
        return self._guard.addr

    def peer_addr(self) -> Addr:
        if self._peer is None:
            raise NetworkError("not connected")
        return self._peer

    # -- datagram path (`endpoint.rs:59-163`) ------------------------------
    async def send_to(self, dst: AddrLike, tag: int, data: Any) -> None:
        dst_addr = (await lookup_host(dst))[0]
        await self.send_to_raw(dst_addr, tag, data)

    async def recv_from(self, tag: int) -> Tuple[Any, Addr]:
        """Receive one message with the given tag → (data, from_addr)."""
        return await self.recv_from_raw(tag)

    async def send(self, tag: int, data: Any) -> None:
        await self.send_to(self.peer_addr(), tag, data)

    async def recv(self, tag: int) -> Any:
        peer = self.peer_addr()
        data, from_addr = await self.recv_from(tag)
        if from_addr != peer:
            # A real error, not an assert: must hold under python -O too.
            raise NetworkError(
                f"received a message from {from_addr}, expected connected "
                f"peer {peer}")
        return data

    async def send_to_raw(self, dst: Addr, tag: int, data: Any) -> None:
        net = self._guard.net
        await net.send(self._guard.node, self._guard.addr[1], dst, IpProtocol.UDP, (tag, data))

    async def recv_from_raw(self, tag: int,
                            timeout: Optional[float] = None) -> Tuple[Any, Addr]:
        """Receive one raw message; optional virtual-time deadline.

        The deadline is armed directly on the mailbox future rather than
        through ``time.timeout`` — no wrapper task to spawn/abort, which
        halves the scheduler polls of a timed RPC (rpc.call's hot path)."""
        fut = self._socket.mailbox.recv(tag)
        timer = None
        if timeout is not None:
            timer = self._guard.net.time.add_timer(
                _to_ns(timeout),
                lambda: fut.set_exception(TimeoutError()) if not fut.done() else None)
        try:
            msg = await fut
        except TimeoutError:
            self._socket.mailbox.unregister(fut)
            raise
        except BaseException:
            # A cancelled receiver (e.g. timeout) must give its message back
            # to later receivers (`endpoint.rs:353-387` test): either it was
            # still registered, or it already held an undelivered message.
            if fut.done() and fut._exception is None:
                self._socket.mailbox.requeue_front(fut.result())
            else:
                self._socket.mailbox.unregister(fut)
            raise
        finally:
            if timer is not None:
                timer.cancel()
        try:
            await self._guard.net.rand_delay()
        except BaseException:
            # Cancelled during the post-receive processing delay: the message
            # was already taken out of the mailbox — put it back.
            self._socket.mailbox.requeue_front(msg)
            raise
        return msg.data, msg.from_addr

    # -- connection-oriented path (`endpoint.rs:167-229`) -------------------
    async def connect1(self, addr: AddrLike) -> Tuple[ChannelSender, ChannelReceiver]:
        dst = (await lookup_host(addr))[0]
        tx, rx, _src = await self._guard.net.connect1(
            self._guard.node, self._guard.addr[1], dst, IpProtocol.UDP
        )
        return tx, rx

    async def accept1(self) -> Tuple[ChannelSender, ChannelReceiver, Addr]:
        await self._guard.net.rand_delay()
        try:
            tx, rx, src = await self._socket.conn_queue.recv()
        except ChannelClosed:
            raise ConnectionReset("endpoint closed") from None
        return tx, rx, src

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._guard.close()
        self._socket.conn_queue.close()
        self._socket.mailbox.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
