"""Built-in RPC framework over tag-matched endpoints.

Reference: `madsim/src/sim/net/rpc.rs` — request tag = stable per-type ID
(hash33 of the type path, `rpc.rs:82-92`); the request payload carries a
random u64 response tag echoed back (`rpc.rs:96-131`);
``add_rpc_handler`` spawns a dispatcher loop per request type, each request
handled in a fresh task (`rpc.rs:134-166`). In sim mode payloads cross as
Python objects — no serialization.
"""
from __future__ import annotations

from typing import Any, Awaitable, Callable, Optional, Tuple, Type

from .. import rand as _rand
from .. import task as _task
from .. import time as vtime
from ..core import context  # noqa: F401 — part of the module's public shape
from ..core.futures import ChannelClosed
from .addr import AddrLike, lookup_host  # noqa: F401
from .endpoint import Endpoint
from .network import BrokenPipe, ConnectionReset


def hash_str(s: str) -> int:
    """hash33 (`rpc.rs:82-92`): h = h*33 + byte, over u64."""
    h = 0
    for b in s.encode():
        h = (h * 33 + b) & ((1 << 64) - 1)
    return h


import functools


@functools.lru_cache(maxsize=4096)
def type_tag(req_type: type) -> int:
    """Stable RPC tag for a request type (module path + qualname)."""
    override = getattr(req_type, "__rpc_id__", None)
    if override is not None:
        return int(override)
    return hash_str(f"{req_type.__module__}::{req_type.__qualname__}")


async def call(ep: Endpoint, dst: AddrLike, request: Any, timeout: Optional[float] = None) -> Any:
    """Send an RPC and await its response."""
    resp, _ = await call_with_data(ep, dst, request, b"", timeout=timeout)
    return resp


async def call_with_data(ep: Endpoint, dst: AddrLike, request: Any, data: bytes,
                         timeout: Optional[float] = None) -> Tuple[Any, bytes]:
    """Send an RPC with a raw data sidecar → (response, response_data).

    The deadline is armed inside the endpoint's mailbox (no wrapper task),
    the timed-RPC fast path on both backends."""
    rsp_tag = _rand.thread_rng().next_u64()
    # send_to resolves the address per backend (sim parser vs real DNS).
    await ep.send_to(dst, type_tag(type(request)), (rsp_tag, request, data))
    payload, _from_addr = await ep.recv_from_raw(rsp_tag, timeout=timeout)
    resp, rsp_data = payload
    if isinstance(resp, _RpcFault):
        raise RpcError(resp.message)
    return resp, rsp_data


def add_rpc_handler(ep: Endpoint, req_type: Type,
                    handler: Callable[[Any], Awaitable[Any]]) -> None:
    """Register an async handler ``(request) -> response`` for a request type."""

    async def _with_data(req, _data):
        return await handler(req), b""

    add_rpc_handler_with_data(ep, req_type, _with_data)


def add_rpc_handler_with_data(ep: Endpoint, req_type: Type,
                              handler: Callable[[Any, bytes], Awaitable[Tuple[Any, bytes]]]) -> None:
    """Register an async handler ``(request, data) -> (response, data)``.

    Spawns a dispatcher loop on the current node; each request runs in a
    fresh task so slow handlers don't serialize the endpoint
    (`rpc.rs:134-166`). Works on both backends: spawn routes to the sim
    executor in-sim and to asyncio tasks in real mode.
    """
    tag = type_tag(req_type)

    async def dispatcher():
        while True:
            try:
                payload, from_addr = await ep.recv_from_raw(tag)
            except (BrokenPipe, ConnectionReset, ChannelClosed):
                return  # endpoint closed / node network reset: clean exit
            rsp_tag, request, data = payload

            async def handle_one(rsp_tag=rsp_tag, request=request, data=data, from_addr=from_addr):
                try:
                    resp, rsp_data = await handler(request, data)
                except RpcError as exc:
                    resp, rsp_data = _RpcFault(str(exc)), b""
                try:
                    await ep.send_to_raw(from_addr, rsp_tag, (resp, rsp_data))
                except (BrokenPipe, ConnectionReset, OSError):
                    pass  # caller vanished; response undeliverable

            _task.spawn(handle_one())

    _task.spawn(dispatcher())


class RpcError(Exception):
    """Application-level RPC failure, propagated to the caller."""


class _RpcFault:
    __slots__ = ("message",)

    def __init__(self, message: str):
        self.message = message


# Ergonomic method-style access, mirroring the reference's trait impls on
# Endpoint (`rpc.rs:94-166`). RealEndpoint gets the same methods attached
# from real/net.py when the real backend actually loads — sim-only runs
# never import the real twin.
Endpoint.call = call  # type: ignore[attr-defined]
Endpoint.call_with_data = call_with_data  # type: ignore[attr-defined]
Endpoint.add_rpc_handler = add_rpc_handler  # type: ignore[attr-defined]
Endpoint.add_rpc_handler_with_data = add_rpc_handler_with_data  # type: ignore[attr-defined]
