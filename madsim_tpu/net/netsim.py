"""NetSim: the network simulator plugin + connection fabric.

Reference: `madsim/src/sim/net/mod.rs` — send = random 0-5 µs delay →
``try_send`` → timer-deferred delivery (`mod.rs:173-197`); ``connect1`` builds
a reliable ordered duplex channel out of two unbounded queues + one relay task
per direction that re-checks link health per message with exponential backoff
1 ms → 10 s while partitioned, so **messages queue across partitions and flush
on heal** (`mod.rs:224-260`); relay tasks are aborted on node reset.

Messages cross the simulated network as in-process Python objects — zero
serialization (`mod.rs:86`), mirroring the reference's ``Box<dyn Any>``.
"""
from __future__ import annotations

import logging
from typing import Callable, Optional, Tuple

from .. import time as vtime
from ..core import context
from ..core.futures import Channel, ChannelClosed
from ..core.plugin import Simulator
from ..core.timewheel import to_ns
from .addr import Addr, AddrLike, format_addr, lookup_host, parse_addr
from .network import (
    AddrInUse,  # noqa: F401 (re-export for callers)
    AddrNotAvailable,  # noqa: F401
    BrokenPipe,
    ConnectionRefused,
    ConnectionReset,
    IpProtocol,
    Network,
    Socket,
    Stat,
)

logger = logging.getLogger("madsim_tpu.net")

_BACKOFF_INITIAL_NS = to_ns(0.001)
_BACKOFF_MAX_NS = to_ns(10.0)


class NetSim(Simulator):
    """Per-runtime network simulator. Registered by default
    (`runtime/mod.rs:61-62` analog); fetched via ``plugin.simulator(NetSim)``."""

    def __init__(self, handle):
        super().__init__(handle)
        # All network decisions (per-message delay, loss, latency) draw from
        # the dedicated NET stream: draw k of seed s is threefry block
        # (net_key(s), k) — the addressing the batched device kernel uses to
        # reproduce them (core/rng.py stream map).
        from ..core.rng import STREAM_NET, GlobalRng

        self.rand = GlobalRng(handle.seed, stream=STREAM_NET)
        self.network = Network(self.rand, handle.config.net)
        self.time = handle.time
        # The executor, cached at construction: rand_delay suspends once
        # per message, and the context-TLS lookup chain it replaces was a
        # measurable slice of RPC-heavy worlds.
        self.executor = handle.task

    # -- Simulator hooks ---------------------------------------------------
    def create_node(self, node_id: int) -> None:
        self.network.insert_node(node_id)

    def reset_node(self, node_id: int) -> None:
        self.network.reset_node(node_id)

    # -- supervisor API (`net/mod.rs:120-178`) ------------------------------
    def stat(self) -> Stat:
        return self.network.stat

    def update_config(self, f: Callable) -> None:
        f(self.network.config)

    def set_ip(self, node_id: int, ip: str) -> None:
        self.network.set_ip(node_id, ip)

    def connect(self, node_id: int) -> None:
        self.network.unclog_node(node_id)

    def disconnect(self, node_id: int) -> None:
        self.network.clog_node(node_id)

    def connect2(self, node1: int, node2: int) -> None:
        self.network.unclog_link(node1, node2)
        self.network.unclog_link(node2, node1)

    def disconnect2(self, node1: int, node2: int) -> None:
        self.network.clog_link(node1, node2)
        self.network.clog_link(node2, node1)

    # -- data path ----------------------------------------------------------
    async def rand_delay(self) -> None:
        """Random 0-5 µs processing delay before touching the network
        (`mod.rs:173-178`); keeps send timestamps distinct across seeds.

        Host-engine redesign: the reference registers a real timer here;
        this engine advances the virtual clock synchronously by the drawn
        delay and suspends through the executor's timer-free yield_now().
        Deliberate divergence: concurrent senders' delays accumulate
        serially (each advances the clock in turn) instead of overlapping
        on the timer wheel — the same serialization the reference's own
        per-poll 50-100 ns jitter has (`task.rs:176-178`), at µs scale,
        bounded by 5 µs x messages-per-batch (vs the 1-10 ms link
        latencies that dominate all timing). In exchange the timer-heap
        push/pop/fire cycle — the hottest path in RPC-heavy worlds — is
        gone. The scheduling point and the RNG draw are unchanged."""
        delay_us = self.rand.gen_range(0, 5)
        self.time.advance(delay_us * 1000)
        await self.executor.yield_now()

    async def send(self, node_id: int, port: int, dst: Addr, protocol: IpProtocol, msg) -> None:
        await self.rand_delay()
        res = self.network.try_send(node_id, dst, protocol)
        if res is None:
            return  # dropped (clogged / lost / no dest) — datagram semantics
        src_ip, _dst_node, socket, latency_ns = res
        src = (src_ip, port)
        self.time.add_timer(latency_ns, lambda: socket.deliver(src, dst, msg))

    async def connect1(self, node_id: int, port: int, dst: Addr, protocol: IpProtocol
                       ) -> Tuple["ChannelSender", "ChannelReceiver", Addr]:
        """Open a reliable ordered duplex connection (`mod.rs:201-221`)."""
        await self.rand_delay()
        res = self.network.try_send(node_id, dst, protocol)
        if res is None:
            raise ConnectionRefused(f"connection refused: {format_addr(dst)}")
        src_ip, dst_node, socket, latency_ns = res
        src = (src_ip, port)
        tx1, rx1 = self._channel(node_id, dst, protocol)
        tx2, rx2 = self._channel(dst_node, src, protocol)
        self.time.add_timer(latency_ns, lambda: socket.new_connection(src, dst, tx2, rx1))
        return tx1, rx2, src

    def _channel(self, node_id: int, dst: Addr, protocol: IpProtocol
                 ) -> Tuple["ChannelSender", "ChannelReceiver"]:
        """One direction of a connection: user queue → relay task → peer
        queue. The relay re-samples the link per message and backs off
        exponentially while partitioned (`mod.rs:224-260`)."""
        upstream = Channel()
        downstream = Channel()

        async def relay():
            try:
                while True:
                    try:
                        msg = await upstream.recv()
                    except ChannelClosed:
                        downstream.close()  # sender side closed: EOF at peer
                        return
                    wait_ns = _BACKOFF_INITIAL_NS
                    while True:
                        res = self.network.try_send(node_id, dst, protocol)
                        if res is not None:
                            await vtime.sleep(res[3] / 1e9)
                            break
                        await vtime.sleep(wait_ns / 1e9)
                        wait_ns = min(wait_ns * 2, _BACKOFF_MAX_NS)
                    try:
                        downstream.send(msg)
                    except ChannelClosed:
                        return  # receiver closed: stop relaying
            except GeneratorExit:
                # Relay aborted (node reset): peer sees connection reset.
                downstream.close()
                raise

        handle = self.executor.spawn(relay(), self.executor.main_node.info)

        def on_reset():
            handle.abort()
            upstream.close()
            downstream.close()

        self.network.add_reset_hook(node_id, on_reset)
        return ChannelSender(upstream), ChannelReceiver(downstream)


class ChannelSender:
    """Sending half of a reliable connection (`endpoint.rs:204-221` analog)."""

    __slots__ = ("_ch",)

    def __init__(self, ch: Channel):
        self._ch = ch

    async def send(self, payload) -> None:
        try:
            self._ch.send(payload)
        except ChannelClosed:
            raise ConnectionReset("connection reset") from None

    def close(self) -> None:
        self._ch.close()


class ChannelReceiver:
    """Receiving half of a reliable connection. ``recv`` raises
    :class:`ConnectionReset` when the channel is closed and drained (the
    peer's EOF)."""

    __slots__ = ("_ch",)

    def __init__(self, ch: Channel):
        self._ch = ch

    async def recv(self):
        try:
            return await self._ch.recv()
        except ChannelClosed:
            raise ConnectionReset("connection reset") from None

    async def recv_or_eof(self):
        """Like recv but returns None at EOF (for stream adapters)."""
        try:
            return await self._ch.recv()
        except ChannelClosed:
            return None

    def close(self) -> None:
        self._ch.close()


class BindGuard:
    """Releases the bound port on close (`mod.rs:264-318`). Python has no
    deterministic drop, so owners call ``close()`` (or use ``with``).

    Deliberately NO ``__del__``: releasing the port at garbage-collection
    time would mutate simulation state at a moment determined by the
    process's allocation history (GC cycles), not by the seed — breaking
    same-seed-same-trajectory. An un-closed guard's port stays bound until
    its node resets; close() is token-checked so a stale guard can never
    release a successor's binding.
    """

    __slots__ = ("net", "node", "addr", "protocol", "socket", "_closed")

    def __init__(self, net: NetSim, node: int, addr: Addr, protocol: IpProtocol,
                 socket: Socket):
        self.net = net
        self.node = node
        self.addr = addr
        self.protocol = protocol
        self.socket = socket
        self._closed = False

    @staticmethod
    async def bind(addr: AddrLike, protocol: IpProtocol, socket: Socket) -> "BindGuard":
        net = _netsim()
        node = context.current_node_id()
        last_err: Optional[Exception] = None
        for candidate in await lookup_host(addr):
            await net.rand_delay()
            try:
                bound = net.network.bind(node, candidate, protocol, socket)
                return BindGuard(net, node, bound, protocol, socket)
            except OSError as exc:
                last_err = exc
        raise last_err or AddrNotAvailable("could not resolve to any addresses")

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.net.network.close(self.node, self.addr, self.protocol,
                                   expected=self.socket)


def _netsim() -> NetSim:
    return context.current_handle().sims.get(NetSim)
