"""Service-layer ergonomics: ``@service`` / ``@rpc`` decorators.

The ``#[madsim::service]`` macro analog (`madsim-macros/src/service.rs:
8-111`): the reference rewrites an impl block so every ``#[rpc]`` method is
registered as an RPC handler by a generated ``add_rpc_handler`` —  here a
class decorator attaches ``add_rpc_handler(ep)`` / ``serve(addr)`` /
``serve_on(ep)`` that wire each ``@rpc`` method into the endpoint's
dispatcher, keyed by the method's request type (taken from its parameter
annotation, the typed-request idiom of `service.rs` RpcFn).

Usage::

    @service
    class KvStore:
        @rpc
        async def put(self, req: PutRequest) -> PutReply: ...
        @rpc
        async def get(self, req: GetRequest) -> GetReply: ...

    node_ep = await Endpoint.bind("10.0.0.1:700")
    await KvStore().serve_on(node_ep)           # or .serve(addr) to bind
    # client side: rpc.call(ep, addr, PutRequest(...))
"""
from __future__ import annotations

import inspect
from typing import Any, Callable, Type

from .addr import AddrLike
from .endpoint import Endpoint
from . import rpc as _rpc_mod


def rpc(fn: Callable) -> Callable:
    """Mark an async method as an RPC handler (`#[rpc]` analog)."""
    if not inspect.iscoroutinefunction(fn):
        raise TypeError("@rpc requires an async method")
    fn._madsim_rpc = True
    return fn


def _request_type(cls_name: str, fn: Callable) -> Type:
    """The request type = the annotation of the first non-self parameter."""
    sig = inspect.signature(fn)
    params = [p for p in sig.parameters.values() if p.name != "self"]
    if not params:
        raise TypeError(
            f"@rpc method {cls_name}.{fn.__name__} needs a request parameter")
    ann = params[0].annotation
    if ann is inspect.Parameter.empty:
        raise TypeError(
            f"@rpc method {cls_name}.{fn.__name__}'s request parameter must "
            "be annotated with its request type (the tag the dispatcher "
            "routes on, `service.rs` RpcFn semantics)")
    if isinstance(ann, str):
        # `from __future__ import annotations` stringizes. Evaluate ONLY
        # this annotation (not the whole signature: an unresolvable reply
        # annotation must not break decoration).
        ann = eval(ann, getattr(fn, "__globals__", {}))  # noqa: S307
    return ann


def service(cls: type) -> type:
    """Class decorator: collect ``@rpc`` methods and attach the serving
    surface (`#[madsim::service]` analog)."""
    methods = {}
    seen: dict = {}
    # dir() + getattr_static covers inherited @rpc methods too (a subclass
    # of a service base must serve the base's handlers).
    for name in dir(cls):
        fn = inspect.getattr_static(cls, name, None)
        if callable(fn) and getattr(fn, "_madsim_rpc", False):
            req_type = _request_type(cls.__name__, fn)
            if req_type in seen:
                raise TypeError(
                    f"@service {cls.__name__}: methods {seen[req_type]!r} "
                    f"and {name!r} both take {req_type.__name__} — request "
                    "types route RPCs, so each may have exactly one handler")
            seen[req_type] = name
            methods[name] = req_type
    cls.__rpc_methods__ = methods

    def add_rpc_handler(self, ep: Endpoint) -> None:
        """Register every @rpc method on an endpoint (generated
        `add_rpc_handler`, service.rs:62-111)."""
        for name, req_type in type(self).__rpc_methods__.items():
            bound = getattr(self, name)

            async def handler(req: Any, _fn=bound) -> Any:
                return await _fn(req)

            _rpc_mod.add_rpc_handler(ep, req_type, handler)

    async def serve_on(self, ep: Endpoint) -> Endpoint:
        """Register handlers on an existing endpoint; returns it."""
        self.add_rpc_handler(ep)
        return ep

    async def serve(self, addr: AddrLike) -> Endpoint:
        """Bind an endpoint at ``addr`` and serve this service on it."""
        return await self.serve_on(await Endpoint.bind(addr))

    cls.add_rpc_handler = add_rpc_handler
    cls.serve_on = serve_on
    cls.serve = serve
    return cls
