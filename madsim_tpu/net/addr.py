"""Socket address handling for the simulated network.

Reference: `madsim/src/sim/net/addr.rs` (ToSocketAddrs + lookup_host). Here an
address is a ``(ip: str, port: int)`` tuple with the IP normalized through
:mod:`ipaddress`. Only numeric hosts and ``localhost`` resolve — there is no
real DNS inside a simulation.
"""
from __future__ import annotations

import functools
import ipaddress
from typing import Tuple, Union

Addr = Tuple[str, int]
AddrLike = Union[str, Addr]


class AddrParseError(ValueError):
    pass


# Address parsing sits on the per-message hot path (every send resolves its
# destination); the cache turns repeat parses of the handful of addresses a
# world uses into dict hits.
@functools.lru_cache(maxsize=4096)
def _normalize_ip(ip: str) -> str:
    if ip == "localhost":
        return "127.0.0.1"
    try:
        return str(ipaddress.ip_address(ip))
    except ValueError as exc:
        raise AddrParseError(f"invalid IP address: {ip!r}") from exc


def parse_addr(addr: AddrLike) -> Addr:
    """Parse ``(ip, port)``, ``"ip:port"``, or ``"[v6]:port"``."""
    if isinstance(addr, tuple):
        ip, port = addr
        return _normalize_ip(str(ip)), int(port)
    if not isinstance(addr, str):
        raise AddrParseError(f"cannot parse address from {type(addr).__name__}")
    text = addr.strip()
    if text.startswith("["):  # [v6]:port
        host, _, port = text[1:].partition("]:")
        if not port:
            raise AddrParseError(f"invalid address: {addr!r}")
        return _normalize_ip(host), int(port)
    host, sep, port = text.rpartition(":")
    if not sep:
        raise AddrParseError(f"missing port in address: {addr!r}")
    return _normalize_ip(host), int(port)


async def lookup_host(addr: AddrLike) -> list[Addr]:
    """Resolve to a list of socket addresses (`addr.rs:32-34` analog)."""
    return [parse_addr(addr)]


@functools.lru_cache(maxsize=4096)
def ip_is_loopback(ip: str) -> bool:
    return ipaddress.ip_address(ip).is_loopback


@functools.lru_cache(maxsize=4096)
def ip_is_unspecified(ip: str) -> bool:
    return ipaddress.ip_address(ip).is_unspecified


def unspecified_for(ip: str) -> str:
    return "::" if ipaddress.ip_address(ip).version == 6 else "0.0.0.0"


def format_addr(addr: Addr) -> str:
    ip, port = addr
    if ":" in ip:
        return f"[{ip}]:{port}"
    return f"{ip}:{port}"
