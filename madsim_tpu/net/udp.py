"""UDP facade: thin wrapper over Endpoint with tag 0
(reference `madsim/src/sim/net/udp.rs:21-72`)."""
from __future__ import annotations

from typing import Tuple

from .addr import Addr, AddrLike
from .endpoint import Endpoint


class UdpSocket:
    def __init__(self, ep: Endpoint):
        self._ep = ep

    @staticmethod
    async def bind(addr: AddrLike) -> "UdpSocket":
        return UdpSocket(await Endpoint.bind(addr))

    @staticmethod
    async def connect(addr: AddrLike) -> "UdpSocket":
        return UdpSocket(await Endpoint.connect(addr))

    def local_addr(self) -> Addr:
        return self._ep.local_addr()

    def peer_addr(self) -> Addr:
        return self._ep.peer_addr()

    async def send_to(self, dst: AddrLike, data: bytes) -> int:
        await self._ep.send_to(dst, 0, bytes(data))
        return len(data)

    async def recv_from(self) -> Tuple[bytes, Addr]:
        data, addr = await self._ep.recv_from(0)
        return data, addr

    async def send(self, data: bytes) -> int:
        await self._ep.send(0, bytes(data))
        return len(data)

    async def recv(self) -> bytes:
        return await self._ep.recv(0)

    def close(self) -> None:
        self._ep.close()
