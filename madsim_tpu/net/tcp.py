"""TCP simulation: listener + ordered reliable byte streams.

Reference: `madsim/src/sim/net/tcp/*` — tokio-compatible ``TcpListener``
(`listener.rs:35-70`) / ``TcpStream`` (`stream.rs:49-88`) built on the
``connect1`` duplex channels; reads drain a local byte buffer then await the
channel (EOF on channel close = orderly shutdown, `stream.rs:107-132`); writes
buffer locally and ``flush`` ships one payload (`stream.rs:135-158`). Like the
reference: no backlog limit, no partial-write simulation.
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..core.futures import Channel, ChannelClosed
from .addr import Addr, AddrLike, format_addr
from .netsim import BindGuard, ChannelReceiver, ChannelSender, ConnectionReset, _netsim
from .network import IpProtocol, Socket


class _ListenerSocket(Socket):
    __slots__ = ("conn_queue",)

    def __init__(self):
        self.conn_queue = Channel()

    def new_connection(self, src: Addr, dst: Addr, tx, rx) -> None:
        try:
            self.conn_queue.send((tx, rx, src, dst))
        except ChannelClosed:
            pass


class TcpListener:
    def __init__(self, guard: BindGuard, socket: _ListenerSocket):
        self._guard = guard
        self._socket = socket

    @staticmethod
    async def bind(addr: AddrLike) -> "TcpListener":
        from ..core.backend import is_real

        if is_real():
            from ..real.tcp import RealTcpListener

            return await RealTcpListener.bind(addr)
        socket = _ListenerSocket()
        guard = await BindGuard.bind(addr, IpProtocol.TCP, socket)
        return TcpListener(guard, socket)

    def local_addr(self) -> Addr:
        return self._guard.addr

    async def accept(self) -> Tuple["TcpStream", Addr]:
        await self._guard.net.rand_delay()
        try:
            tx, rx, src, dst = await self._socket.conn_queue.recv()
        except ChannelClosed:
            raise ConnectionReset("listener closed") from None
        # The server-side stream is manufactured here (`listener.rs:77-96`).
        stream = TcpStream(tx, rx, local=dst, peer=src, guard=None)
        return stream, src

    def close(self) -> None:
        self._guard.close()
        self._socket.conn_queue.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class TcpStream:
    def __init__(self, tx: ChannelSender, rx: ChannelReceiver, local: Addr, peer: Addr,
                 guard: Optional[BindGuard]):
        self._tx = tx
        self._rx = rx
        self._local = local
        self._peer = peer
        self._guard = guard  # client side holds its ephemeral port binding
        self._read_buf = b""
        self._write_buf = bytearray()
        self._eof = False

    @staticmethod
    async def connect(addr: AddrLike) -> "TcpStream":
        from ..core.backend import is_real

        if is_real():
            from ..real.tcp import RealTcpStream

            return await RealTcpStream.connect(addr)
        net = _netsim()
        guard = await BindGuard.bind("0.0.0.0:0", IpProtocol.TCP, Socket())
        from .addr import lookup_host

        dst = (await lookup_host(addr))[0]
        tx, rx, src = await net.connect1(guard.node, guard.addr[1], dst, IpProtocol.TCP)
        return TcpStream(tx, rx, local=src, peer=dst, guard=guard)

    def local_addr(self) -> Addr:
        return self._local

    def peer_addr(self) -> Addr:
        return self._peer

    # -- reading -----------------------------------------------------------
    async def read(self, max_bytes: int = 65536) -> bytes:
        """Read up to max_bytes; returns b"" at EOF (orderly shutdown)."""
        if not self._read_buf:
            if self._eof:
                return b""
            chunk = await self._rx.recv_or_eof()
            if chunk is None:
                self._eof = True
                return b""
            self._read_buf = bytes(chunk)
        out, self._read_buf = self._read_buf[:max_bytes], self._read_buf[max_bytes:]
        return out

    async def read_exact(self, n: int) -> bytes:
        parts = []
        remaining = n
        while remaining > 0:
            chunk = await self.read(remaining)
            if not chunk:
                raise ConnectionReset("unexpected EOF")
            parts.append(chunk)
            remaining -= len(chunk)
        return b"".join(parts)

    # -- writing -----------------------------------------------------------
    def write(self, data: bytes) -> None:
        """Buffer data locally (`stream.rs:135-147`); flush to transmit."""
        self._write_buf.extend(data)

    async def flush(self) -> None:
        if self._write_buf:
            payload, self._write_buf = bytes(self._write_buf), bytearray()
            await self._tx.send(payload)

    async def write_all(self, data: bytes) -> None:
        self.write(data)
        await self.flush()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Orderly shutdown: peer reads EOF after draining in-flight data."""
        self._tx.close()
        if self._guard is not None:
            self._guard.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
