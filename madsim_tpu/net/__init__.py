"""Simulated network stack (reference `madsim/src/sim/net/`).

Layers: :class:`Network` graph (links, fault state, address resolution) →
:class:`NetSim` plugin (latency/drop sampling, timer-deferred delivery,
reliable duplex channels) → user primitives (:class:`Endpoint` tag messaging,
:mod:`rpc`, :class:`TcpListener`/:class:`TcpStream`, :class:`UdpSocket`).
"""
from .addr import Addr, AddrLike, format_addr, lookup_host, parse_addr
from .endpoint import Endpoint
from .netsim import (
    BindGuard,
    ChannelReceiver,
    ChannelSender,
    NetSim,
)
from .network import (
    AddrInUse,
    AddrNotAvailable,
    BrokenPipe,
    ConnectionRefused,
    ConnectionReset,
    IpProtocol,
    NetworkError,
    Socket,
    Stat,
)
from .tcp import TcpListener, TcpStream
from .udp import UdpSocket
from . import rpc  # attaches call/add_rpc_handler onto Endpoint
from .service_layer import rpc as rpc_method  # noqa: F401
from .service_layer import service

__all__ = [
    "Addr", "AddrLike", "format_addr", "lookup_host", "parse_addr",
    "Endpoint", "NetSim", "BindGuard", "ChannelSender", "ChannelReceiver",
    "AddrInUse", "AddrNotAvailable", "BrokenPipe", "ConnectionRefused",
    "ConnectionReset", "IpProtocol", "NetworkError", "Socket", "Stat",
    "TcpListener", "TcpStream", "UdpSocket", "rpc", "service", "rpc_method",
]
