"""The network graph: links, address resolution, sockets, fault state.

Reference: `madsim/src/sim/net/network.rs` — nodes with ≤1 IP, an
``addr_to_node`` map, a socket table keyed ``(addr, protocol)``, clogged
node/link sets, and ``test_link`` = clog check → Bernoulli(packet loss) →
uniform latency sample (`network.rs:249-257`). Protocol-agnostic: upper layers
implement the :class:`Socket` interface.
"""
from __future__ import annotations

import enum
import logging
from typing import Dict, List, Optional, Set, Tuple

from ..core.config import NetConfig
from ..core.rng import GlobalRng, loss_threshold
from ..core.timewheel import to_ns
from .addr import (Addr, format_addr, ip_is_loopback,
                   ip_is_unspecified, unspecified_for)

logger = logging.getLogger("madsim_tpu.net")

LOCALHOST_V4 = "127.0.0.1"


class IpProtocol(enum.Enum):
    TCP = "tcp"
    UDP = "udp"


class Socket:
    """Upper-level protocol socket (`network.rs:56-69`)."""

    def deliver(self, src: Addr, dst: Addr, msg) -> None:
        pass

    def new_connection(self, src: Addr, dst: Addr, tx, rx) -> None:
        pass


class Stat:
    """Network statistics (`network.rs:104-110`)."""

    __slots__ = ("msg_count",)

    def __init__(self):
        self.msg_count = 0

    def __repr__(self):
        return f"Stat(msg_count={self.msg_count})"


class NetworkError(OSError):
    pass


class AddrNotAvailable(NetworkError):
    pass


class AddrInUse(NetworkError):
    pass


class ConnectionRefused(NetworkError):
    pass


class ConnectionReset(NetworkError):
    pass


class BrokenPipe(NetworkError):
    pass


class _NetNode:
    __slots__ = ("ip", "sockets", "reset_hooks")

    def __init__(self):
        self.ip: Optional[str] = None
        self.sockets: Dict[Tuple[Addr, IpProtocol], Socket] = {}
        # Closures run on node reset: abort relay tasks / close channels
        # (`network.rs:303-306` + FallibleTask cancel-on-drop).
        self.reset_hooks: List = []


class Network:
    def __init__(self, rand: GlobalRng, config: NetConfig):
        self.rand = rand
        self.config = config
        self.stat = Stat()
        self.nodes: Dict[int, _NetNode] = {}
        self.addr_to_node: Dict[str, int] = {}
        self.clogged_node: Set[int] = set()
        self.clogged_link: Set[Tuple[int, int]] = set()

    # -- topology ----------------------------------------------------------
    def insert_node(self, node_id: int) -> None:
        self.nodes[node_id] = _NetNode()

    def reset_node(self, node_id: int) -> None:
        node = self.nodes[node_id]
        node.sockets.clear()
        hooks, node.reset_hooks = node.reset_hooks, []
        for hook in hooks:
            hook()

    def set_ip(self, node_id: int, ip: str) -> None:
        node = self.nodes[node_id]
        if node.ip is not None:
            self.addr_to_node.pop(node.ip, None)
        node.ip = ip
        old = self.addr_to_node.get(ip)
        if old is not None and old != node_id:
            raise ValueError(f"IP conflict: {ip} already assigned to node {old}")
        self.addr_to_node[ip] = node_id

    # -- fault state (`network.rs:159-190`) --------------------------------
    def clog_node(self, node_id: int) -> None:
        assert node_id in self.nodes
        self.clogged_node.add(node_id)

    def unclog_node(self, node_id: int) -> None:
        assert node_id in self.nodes
        self.clogged_node.discard(node_id)

    def clog_link(self, src: int, dst: int) -> None:
        assert src in self.nodes and dst in self.nodes
        self.clogged_link.add((src, dst))

    def unclog_link(self, src: int, dst: int) -> None:
        assert src in self.nodes and dst in self.nodes
        self.clogged_link.discard((src, dst))

    def link_clogged(self, src: int, dst: int) -> bool:
        return (
            src in self.clogged_node
            or dst in self.clogged_node
            or (src, dst) in self.clogged_link
        )

    # -- sockets -----------------------------------------------------------
    def bind(self, node_id: int, addr: Addr, protocol: IpProtocol, socket: Socket) -> Addr:
        node = self.nodes[node_id]
        ip, port = addr
        if (
            not ip_is_unspecified(ip)
            and not ip_is_loopback(ip)
            and node.ip is not None
            and ip != node.ip
        ):
            raise AddrNotAvailable(f"invalid address: {format_addr(addr)}")
        if port == 0:
            port = self._ephemeral_port(node, ip, protocol)
            addr = (ip, port)
        key = (addr, protocol)
        if key in node.sockets:
            raise AddrInUse(f"address already in use: {format_addr(addr)}")
        node.sockets[key] = socket
        logger.debug("bind node=%s addr=%s proto=%s", node_id, format_addr(addr), protocol.value)
        return addr

    def _ephemeral_port(self, node: _NetNode, ip: str, protocol: IpProtocol) -> int:
        for port in range(1, 0x10000):
            if ((ip, port), protocol) not in node.sockets:
                return port
        raise AddrInUse("no available ephemeral port")

    def close(self, node_id: int, addr: Addr, protocol: IpProtocol,
              expected: Optional[Socket] = None) -> None:
        """Release a binding. With ``expected``, only release if the table
        still holds that socket — a stale guard (its node reset and the port
        rebound since) must not close the successor's binding."""
        node = self.nodes.get(node_id)
        if node is None:
            return
        key = (addr, protocol)
        if expected is None or node.sockets.get(key) is expected:
            node.sockets.pop(key, None)

    # -- sending (`network.rs:249-301`) ------------------------------------
    def test_link(self, src: int, dst: int) -> Optional[int]:
        """Clog check → loss → uniform latency (ns), None = no delivery now.
        The fault-injection point of the whole system.

        Draw discipline (deliberate divergence from the reference's
        short-circuit at `network.rs:249-257`): every call consumes exactly
        TWO u64 blocks from the NET stream — loss then latency — regardless
        of the clog/loss outcome, so each message's draw indices are a pure
        function of send order. That stability is what lets the device
        kernel sample the same decisions from (net_key, counter) without
        knowing fault outcomes in advance. Loss is an integer threshold
        compare (see :func:`core.rng.loss_threshold`), exact on both
        backends."""
        lost = self.rand.next_u64() < loss_threshold(self.config.packet_loss_rate)
        lo, hi = self.config.send_latency
        latency = self.rand.gen_range(to_ns(lo), max(to_ns(hi), to_ns(lo) + 1))
        if self.link_clogged(src, dst) or lost:
            return None
        self.stat.msg_count += 1
        return latency

    def resolve_dest_node(self, node_id: int, dst: Addr, protocol: IpProtocol) -> Optional[int]:
        node = self.nodes[node_id]
        if ip_is_loopback(dst[0]) or (dst, protocol) in node.sockets:
            return node_id
        if node.ip is None:
            logger.warning("ip not set: node %s", node_id)
            return None
        target = self.addr_to_node.get(dst[0])
        if target is None:
            logger.warning("destination not found: %s", format_addr(dst))
        return target

    def try_send(self, node_id: int, dst: Addr, protocol: IpProtocol):
        """Returns (src_ip, dst_node, socket, latency_ns) or None."""
        dst_node = self.resolve_dest_node(node_id, dst, protocol)
        if dst_node is None:
            return None
        latency = self.test_link(node_id, dst_node)
        if latency is None:
            return None
        sockets = self.nodes[dst_node].sockets
        socket = sockets.get((dst, protocol))
        if socket is None:
            socket = sockets.get(((unspecified_for(dst[0]), dst[1]), protocol))
        if socket is None:
            return None
        if ip_is_loopback(dst[0]):
            src_ip = LOCALHOST_V4
        else:
            src_ip = self.nodes[node_id].ip
        return src_ip, dst_node, socket, latency

    def add_reset_hook(self, node_id: int, hook) -> None:
        self.nodes[node_id].reset_hooks.append(hook)
