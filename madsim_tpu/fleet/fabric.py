"""The fleet fabric: deterministic scheduling of coordinator + workers.

``fleet_sweep()`` is the one-call entry point — the fleet counterpart
of ``parallel.sweep.sweep()`` — and :class:`LocalFabric` is the engine
behind its default ``spawn="inline"`` mode: a single-threaded, round-
robin scheduler that runs every worker's quantum in a fixed order on a
virtual tick clock. No threads, no wall clock, no OS scheduler — which
is exactly why the chaos matrix can be tier-1: a fabric execution is a
pure function of (seeds, config, ChaosConfig), replayable like a seed.

The inline fabric is not a toy: workers run REAL pipelined device
sweeps over their leases (sharing one process's mesh — including the
2-D DCN×ICI ``multihost_mesh``), the coordinator runs the REAL lease
protocol, and every failure mode (kill, expiry, re-issue, duplicate,
preemption, torn checkpoint, RPC retry) takes the same code path a
multiprocess fleet takes. ``spawn="process"`` (fleet/process.py) swaps
the scheduler for real OS processes + pipes + signals, changing the
clock and the transport but not one line of protocol.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import observatory as _obsy
from ..parallel.mesh import seed_mesh
from ..parallel.sweep import SweepResult
from .chaos import ChaosConfig, ChaosPolicy
from .coordinator import Coordinator
from .lease import split_ranges
from .rpc import InlineTransport, RetryPolicy, VirtualClock
from .worker import Worker


class FleetStalledError(RuntimeError):
    """The fabric cannot make progress: every worker is permanently dead
    (restarts disabled) or the scheduling round budget ran out with
    ranges still outstanding. The message carries the coordinator's
    per-range stall report — each stuck range with its holding worker,
    lease generation, last accepted heartbeat, and deadline (or the
    exchange-barrier reason a pending range cannot issue) — plus the
    fleet stats, so the post-mortem starts at the sick range."""


class LocalFabric:
    """Deterministic in-process fabric: round-robin worker quanta on a
    shared virtual clock, one tick per scheduling round (plus one per
    heartbeat inside the sweeps)."""

    def __init__(self, coordinator: Coordinator, workers: List[Worker],
                 clock: VirtualClock, chaos: Optional[ChaosPolicy] = None,
                 max_rounds: int = 100_000):
        self.coordinator = coordinator
        self.workers = workers
        self.clock = clock
        self.chaos = chaos
        self.max_rounds = max_rounds

    def run(self) -> SweepResult:
        rounds = 0
        while not self.coordinator.done():
            rounds += 1
            if rounds > self.max_rounds:
                raise FleetStalledError(
                    f"no convergence after {self.max_rounds} scheduling "
                    f"rounds; {self.coordinator.stall_report()}\n"
                    f"stats: {self.coordinator.stats}")
            alive = 0
            for w in self.workers:
                if w.dead:
                    if self.chaos is not None and self.chaos.restart_due(
                            w.died_at, self.clock.now()):
                        w.restart()
                        self.coordinator.emit(
                            "worker_restarted", worker=w.worker_id,
                            after_preemption=w.preempted)
                    continue
                alive += 1
                w.run_once()
            if alive == 0 and not (self.chaos is not None
                                   and self.chaos.restarts_enabled):
                raise FleetStalledError(
                    "all workers dead with restarts disabled; "
                    f"{self.coordinator.stall_report()}")
            # The scheduler's own tick: even an all-idle round moves
            # fabric time, so a dead worker's lease always expires and a
            # downed worker's restart timer always fires.
            self.clock.advance(1)
            self.coordinator.tick()
        stats = self._fleet_stats()
        return self.coordinator.finalize(fleet_stats=stats)

    def _fleet_stats(self) -> Dict[str, Any]:
        agg: Dict[str, Any] = {"n_workers": len(self.workers),
                               "fabric_ticks": int(self.clock.now()),
                               "spawn": "inline"}
        per_worker = {}
        for w in self.workers:
            per_worker[w.worker_id] = dict(w.stats)
        agg["workers"] = per_worker
        for key in ("kills", "preemptions", "rpc_retries",
                    "heartbeats_dropped", "checkpoints_recovered",
                    "checkpoints_discarded", "leases_prefetched",
                    "grouped_leases", "leases_lost"):
            agg[key] = sum(w.stats[key] for w in self.workers)
        for key in ("acquire_s", "sweep_s"):
            agg[key] = round(sum(w.stats[key] for w in self.workers), 6)
        # Session reuse: leases that rode an already-open SweepSession's
        # standing device slots instead of paying a fresh install.
        agg["session_reuse_hits"] = sum(
            w._session.reuse_hits for w in self.workers
            if w._session is not None)
        # Counted discipline (fleet/rpc.py MAX_CONTROL_RPCS_PER_LEASE):
        # transport turns per issued lease, heartbeats split out — the
        # coalesced control plane's "small constant" gate, measured.
        transport = self.workers[0].transport if self.workers else None
        if transport is not None and hasattr(transport, "calls"):
            calls = dict(transport.calls)
            agg["rpc_turns"] = calls
            total = sum(calls.values())
            control = total - calls.get("heartbeat", 0)
            issued = max(1, self.coordinator.stats["leases_issued"])
            agg["rpcs_per_lease"] = round(total / issued, 3)
            agg["control_rpcs_per_lease"] = round(control / issued, 3)
        return agg


def fleet_sweep(actor: Any, cfg, seeds, *,
                n_workers: int = 2,
                range_size: Optional[int] = None,
                faults: Optional[np.ndarray] = None,
                mesh=None,
                engine=None,
                lease_ttl: float = 8.0,
                chaos: Optional[ChaosConfig] = None,
                observe: Any = None,
                checkpoint_dir: Optional[str] = None,
                checkpoint_every_chunks: int = 4,
                retry: Optional[RetryPolicy] = None,
                max_rounds: int = 100_000,
                spawn: str = "inline",
                exchange: Any = None,
                prefetch: Optional[int] = None,
                **sweep_kwargs) -> SweepResult:
    """Distribute a seed sweep over a resilient coordinator/worker fleet.

    The fleet analog of :func:`madsim_tpu.parallel.sweep.sweep`: the
    seed vector splits into contiguous ranges (``range_size``; default
    two ranges per worker), a coordinator leases ranges to ``n_workers``
    workers with expiry ``lease_ttl`` (fabric clock units), each worker
    runs the leased slice through the pipelined ``sweep()`` (all
    ``sweep_kwargs`` — chunk_steps, recycle/batch_worlds, superstep_max
    — pass through uniformly), and completed ranges merge into one
    ``SweepResult``.

    The resilience contract (tier-1, tests/test_fleet.py): with ANY
    ``chaos`` mix of worker kills, lease expiries, duplicated
    completions, preemptions, and torn checkpoints, the merged result's
    seed ids, bug flags, per-seed observations/metrics, and coverage
    ledger are bitwise identical to a crash-free fleet's AND to a
    single-host ``sweep()`` over the same seeds — crashes cost wall
    time, never results. Double-reported ranges are resolved by
    asserting bitwise equality (:mod:`madsim_tpu.fleet.merge`), so
    redundancy doubles as a cross-execution determinism check.

    ``checkpoint_dir`` enables per-lease checkpointing: preempted
    workers (SIGTERM → checkpoint + lease release) and crashed workers
    leave resumable snapshots the range's next holder continues from
    bit-exactly. ``observe`` receives the fleet telemetry stream
    (``madsim.fleet.telemetry/1`` records — lease/heartbeat/retry/
    re-lease/completion events; a path writes JSONL beside the sweep
    observatory's format, docs/fleet.md).

    ``spawn="inline"`` (default): deterministic single-threaded fabric,
    workers sharing this process's mesh — any mesh, including the 2-D
    DCN×ICI ``multihost_mesh``. ``spawn="process"`` runs workers as OS
    processes with pipe transports and real SIGTERM preemption
    (fleet/process.py) — the deployment shape, minus the determinism of
    the scheduler (results are still bitwise identical; schedules are
    not).

    ``exchange``: an :class:`~madsim_tpu.fleet.exchange.ExchangeConfig`
    — cross-range corpus exchange for guided fleets (requires
    ``search=SearchConfig(...)`` in the sweep kwargs; docs/fleet.md
    "Corpus exchange"). Ranges partition into exchange epochs by range
    id (``exchange.every`` per epoch; default one epoch per worker
    round); each epoch's ranges seed their sweeps from the merged
    corpus of the previous epoch, published snapshots dedupe by range
    with bitwise crosscheck, torn publishes are discarded and re-sent,
    and the merged corpus persists at ``exchange.state_path`` (default
    ``<checkpoint_dir>/exchange_state.npz`` when checkpointing) for
    coordinator crash→resume. Results are bitwise deterministic per
    (seeds, partitioning, exchange cadence, SearchConfig) — chaos
    cannot move them — and the merged result's ``search`` carries the
    final fleet corpus plus the per-seed materialized schedules.
    Inline fabric only.

    ``prefetch``: acquire-ahead depth — each worker acquires up to
    ``1 + prefetch`` leases per control turn, overlapping the next
    lease's acquisition with the current sweep. Default (None): each
    worker's fair share of the range count, so a whole fleet costs ONE
    acquire turn per worker. Prefetched plain leases of one schedule
    run grouped through the worker's persistent ``SweepSession`` (one
    standing device batch, split back into bit-identical per-range
    results); checkpointed / exchange / search leases run solo within
    the quantum. ``prefetch=0`` restores one-lease-per-quantum.
    """
    from ..engine.core import DeviceEngine

    seeds = np.asarray(seeds, np.uint64)
    n = int(seeds.shape[0])
    if n == 0:
        raise ValueError("fleet_sweep needs a non-empty seed vector")
    if n_workers < 1:
        raise ValueError("n_workers must be >= 1")
    if range_size is None:
        range_size = max(1, -(-n // (2 * n_workers)))
    if prefetch is None:
        # Acquire-ahead depth: enough for each worker's fair share of
        # ranges in ONE control turn (the lease-prefetch default). 0
        # restores one-lease-per-quantum (the pre-session fabric).
        n_ranges = -(-n // range_size)
        prefetch = max(0, -(-n_ranges // n_workers) - 1)
    prefetch = max(0, int(prefetch))
    if exchange is not None:
        scfg = sweep_kwargs.get("search")
        if scfg is None:
            raise ValueError(
                "exchange= needs search=SearchConfig(...): the corpus "
                "exchange shares guided-search progress across ranges — "
                "a plain fleet sweep has no corpus to exchange")
        if faults is None:
            raise ValueError(
                "exchange= needs the fault-schedule template (faults=): "
                "the merged corpora evolve within its fault vocabulary")
        if spawn != "inline":
            raise ValueError(
                "exchange= currently requires spawn='inline': the "
                "process fabric does not pipe corpus snapshots yet")
    if spawn == "process":
        from .process import process_fleet_sweep

        return process_fleet_sweep(
            actor, cfg, seeds, n_workers=n_workers, range_size=range_size,
            faults=faults, lease_ttl=lease_ttl, observe=observe,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every_chunks=checkpoint_every_chunks,
            retry=retry, **sweep_kwargs)
    if spawn != "inline":
        raise ValueError(f"spawn must be 'inline' or 'process', "
                         f"got {spawn!r}")

    eng = engine if engine is not None else DeviceEngine(actor, cfg)
    mesh = mesh if mesh is not None else seed_mesh()
    clock = VirtualClock()
    emit, close = _obsy.make_observer(observe)
    policy = ChaosPolicy(chaos) if chaos is not None else None
    exch = None
    if exchange is not None:
        from ..triage.shrink import normalize as _normalize_sched
        from .exchange import CorpusExchange

        scfg = sweep_kwargs["search"]
        faults_a = np.asarray(faults, np.int32)
        template = _normalize_sched(
            faults_a[0] if faults_a.ndim == 3 else faults_a)
        state_path = exchange.state_path
        if state_path is None and checkpoint_dir is not None:
            state_path = os.path.join(checkpoint_dir,
                                      "exchange_state.npz")
            os.makedirs(checkpoint_dir, exist_ok=True)
        exch = CorpusExchange(
            ranges=split_ranges(n, range_size),
            every=exchange.every if exchange.every is not None
            else n_workers,
            template=template, corpus_k=int(scfg.corpus),
            min_novelty=int(scfg.min_novelty), emit=emit, clock=clock,
            state_path=state_path)
        if state_path is not None and os.path.exists(state_path):
            # Coordinator crash→resume: reload the accepted snapshots
            # and re-derive the merged-epoch chain bit-exactly (the
            # merge is a deterministic fold of the persisted inputs).
            exch.resume(state_path)
    coordinator = Coordinator(seeds, range_size=range_size,
                              lease_ttl=lease_ttl, clock=clock, emit=emit,
                              n_devices=mesh.devices.size, exchange=exch)
    transport = InlineTransport(coordinator, chaos=policy)
    if checkpoint_dir is not None:
        os.makedirs(checkpoint_dir, exist_ok=True)
    retry = retry or RetryPolicy()
    workers = [
        Worker(f"w{i}", eng, seeds, transport, clock, faults=faults,
               mesh=mesh, retry=retry, chaos=policy, emit=emit,
               checkpoint_dir=checkpoint_dir,
               checkpoint_every_chunks=checkpoint_every_chunks,
               sweep_kwargs=sweep_kwargs, prefetch=prefetch)
        for i in range(n_workers)]
    fabric = LocalFabric(coordinator, workers, clock, chaos=policy,
                         max_rounds=max_rounds)
    try:
        return fabric.run()
    finally:
        if close is not None:
            close()


__all__ = ["LocalFabric", "FleetStalledError", "fleet_sweep",
           "split_ranges"]
