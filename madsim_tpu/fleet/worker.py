"""The fleet worker: acquire leases, sweep them, heartbeat, report.

A worker is a thin loop around PR 4's pipelined ``sweep()``: one lease =
one sweep over the leased seed slice, run to completion with the same
engine, mesh, and sweep knobs every other worker uses (that uniformity
is what the merge layer's bitwise contract rides on). Heartbeats piggy-
back on the sweep's own telemetry cadence — the ``observe=`` callback
fires once per host scalar read, so lease liveness costs ZERO extra
device syncs — and the heartbeat boundary doubles as the fabric's
preemption point: chaos kills, SIGTERM preemption, and lease-lost
aborts all land there, between supersteps, where the sweep's own
exception path already flushes the async checkpoint writer.

Fabric cost model (docs/fleet.md): three disciplines keep the per-lease
fabric tax ~O(1) instead of O(fresh sweep):

- **Persistent sweep session** — the worker holds ONE
  :class:`~madsim_tpu.parallel.sweep.SweepSession` across leases, so
  per-lease device init, host setup, and compile-cache traffic are paid
  once per worker, not once per lease.
- **Lease prefetch** — ``prefetch=k`` acquires up to ``1+k`` leases in
  a single RPC turn (the coordinator's acquire-ahead path, barrier-
  checked at install time). Prefetched plain leases of the same
  schedule run GROUPED through ``SweepSession.run_group`` — one
  standing device batch at the width the engine is efficient at, split
  back into per-range results that are bit-identical to solo sweeps.
  Checkpointed / exchange / search leases always run solo (their
  per-lease machinery is the contract), sequentially within the same
  quantum.
- **Coalesced control plane** — the corpus publish and the completion
  ride one batched RPC turn; grouped completions batch likewise. Chaos
  interposition stays per LOGICAL message (fleet/rpc.py), so kill /
  torn-publish / duplicate-completion schedules are unchanged.

Failure handling per the ISSUE contract:

- **kill** (crash): the sweep aborts mid-flight, nothing is released;
  every held lease expires at the coordinator and re-issues. If the
  dead worker had checkpointed, the re-issued lease carries the path
  and the next holder resumes bit-exactly (crash recovery == resume).
- **SIGTERM preemption**: ``request_preemption()`` (wired to the signal
  by :func:`install_sigterm_handler`) makes the next heartbeat raise;
  the worker releases EVERY held lease — the running one with its
  checkpoint — and exits its quantum cleanly.
- **corrupt checkpoint** (torn file from a crashed writer): the
  hardened loader (engine/checkpoint.py) raises ``CheckpointError``;
  the worker deletes the file and re-runs the range fresh — losing only
  time, never correctness, because re-execution is deterministic.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..engine.checkpoint import CheckpointError
from .chaos import DELAY, DROP, KILL, PREEMPT
from .rpc import RetryExhausted, RetryPolicy, call_with_retry


class WorkerKilled(BaseException):
    """Chaos crash: aborts the in-flight sweep at a heartbeat boundary.
    BaseException so no recovery handler inside the sweep path can
    accidentally swallow the 'crash'. (Python ``finally`` blocks still
    run — so an async checkpoint writer flushes its last COMPLETED
    snapshot, equivalent to dying just after a finished write; the
    torn-file crash is injected separately via
    ``ChaosConfig.tear_checkpoint_on_kill``.)"""


class LeasePreempted(Exception):
    """SIGTERM-style preemption: stop at the next heartbeat, release
    every held lease (the running one with its checkpoint), survive."""


class LeaseLost(Exception):
    """The coordinator declared a lease expired/superseded: abandon
    the range (someone else owns it now; determinism makes any late
    completion of ours a harmless crosschecked duplicate)."""


class Worker:
    """One fleet worker. ``run_once()`` is the scheduling quantum the
    fabric drives: acquire ``1 + prefetch`` leases, sweep them (grouped
    when the session can), report them.

    ``sweep_kwargs`` are the uniform per-lease sweep knobs
    (chunk_steps, superstep_max, recycle/batch_worlds, ...);
    ``checkpoint_dir`` enables per-lease checkpointing (preemption
    survival + crash recovery); ``checkpoint_every_chunks`` its cadence;
    ``prefetch`` the acquire-ahead depth (0 = one lease per quantum,
    the pre-session fabric behavior).
    """

    def __init__(self, worker_id: str, engine, seeds, transport, clock,
                 faults: Optional[np.ndarray] = None, mesh=None,
                 retry: Optional[RetryPolicy] = None,
                 chaos=None, emit=None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every_chunks: int = 4,
                 sweep_kwargs: Optional[Dict[str, Any]] = None,
                 prefetch: int = 0):
        self.worker_id = worker_id
        self.engine = engine
        self.seeds = np.asarray(seeds, np.uint64)
        self.faults = faults
        self.mesh = mesh
        self.transport = transport
        self.clock = clock
        self.retry = retry or RetryPolicy()
        self.chaos = chaos
        self._emit = emit
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_chunks = checkpoint_every_chunks
        self.sweep_kwargs = dict(sweep_kwargs or {})
        self.prefetch = max(0, int(prefetch))
        self.dead = False
        self.died_at: float = 0.0
        self.preempted = False
        self._preempt_requested = False
        self._lease: Optional[Dict[str, Any]] = None
        self._held: List[Dict[str, Any]] = []
        self._group_mode = False
        self._session = None
        self._delayed_progress: Optional[Dict[str, Any]] = None
        self._hb_count = 0
        self.stats = {"leases_run": 0, "completions": 0, "kills": 0,
                      "preemptions": 0, "leases_lost": 0,
                      "heartbeats_sent": 0, "heartbeats_dropped": 0,
                      "heartbeats_delayed": 0, "rpc_retries": 0,
                      "checkpoints_recovered": 0,
                      "checkpoints_discarded": 0,
                      "corpus_published": 0, "corpus_resent": 0,
                      "corpus_seeded": 0,
                      "leases_prefetched": 0, "grouped_leases": 0,
                      "acquire_s": 0.0, "sweep_s": 0.0}

    @staticmethod
    def _wall() -> float:
        # Phase-timing telemetry only (bench.py fleet_sweep breakdown);
        # never feeds a lease or sim decision.
        from time import perf_counter
        return perf_counter()  # detlint: allow[DET001]

    # -- preemption ------------------------------------------------------
    def request_preemption(self) -> None:
        """Ask the worker to stop at the next heartbeat, checkpoint, and
        release its leases (the SIGTERM handler's body; also callable
        directly, which is how the inline chaos harness models
        preemption)."""
        self._preempt_requested = True

    def install_sigterm_handler(self) -> None:
        """Route SIGTERM to :meth:`request_preemption` — for worker
        processes under a preempting scheduler (k8s, borg, spot VMs).
        Must run on the main thread of the worker process."""
        import signal

        signal.signal(signal.SIGTERM,
                      lambda _sig, _frm: self.request_preemption())

    def restart(self) -> None:
        """Revive after a kill/preemption (the fabric's restart path).
        All lease state was lost with the 'process'; the engine and its
        jit caches survive because inline workers share the host
        process — a real restart would recompile, changing nothing
        about results. The sweep session's standing batch was already
        invalidated when the dying sweep unwound."""
        self.dead = False
        self.preempted = False
        self._preempt_requested = False
        self._lease = None
        self._held = []
        self._group_mode = False
        self._delayed_progress = None

    # -- telemetry -------------------------------------------------------
    def emit(self, event: str, **fields) -> None:
        if self._emit is None:
            return
        rec = {"schema": "madsim.fleet.telemetry/1", "event": event,
               "t": self.clock.now(), "worker": self.worker_id}
        rec.update(fields)
        self._emit(rec)

    # -- RPC helpers (all retried with deterministic backoff) ------------
    def _call(self, method: str, **kw):
        def on_retry(attempt, delay, exc):
            self.stats["rpc_retries"] += 1
            self.emit("rpc_retry", method=method, attempt=attempt,
                      delay=round(float(delay), 3), error=str(exc))

        return call_with_retry(
            lambda: self.transport.call(method, self.worker_id, **kw),
            self.retry, self.clock, tag=f"{self.worker_id}:{method}",
            on_retry=on_retry)

    # -- the persistent sweep session ------------------------------------
    def session(self):
        """The worker's persistent :class:`SweepSession` (created on
        first use, held across leases — the point of the thing)."""
        if self._session is None:
            from ..parallel.sweep import SweepSession

            kw = {k: self.sweep_kwargs[k]
                  for k in SweepSession.GROUPABLE_KW
                  if k in self.sweep_kwargs}
            self._session = SweepSession(engine=self.engine,
                                         mesh=self.mesh, **kw)
        return self._session

    def _groupable(self, leases: List[Dict[str, Any]]) -> bool:
        """May these leases advance as ONE grouped device batch?
        Checkpointing, corpus exchange, and any sweep mode outside the
        session's grouped whitelist keep their per-lease machinery —
        those leases run solo, sequentially, within the quantum."""
        from ..parallel.sweep import SweepSession

        if len(leases) < 2 or self.checkpoint_dir is not None:
            return False
        if any(l.get("exchange_epoch") is not None for l in leases):
            return False
        return all(k in SweepSession.GROUPABLE_KW
                   for k in self.sweep_kwargs)

    # -- the scheduling quantum ------------------------------------------
    def run_once(self) -> bool:
        """Acquire + run + report up to ``1 + prefetch`` leases. Returns
        True if any work happened (False: idle — nothing pending, or
        acquire failed and will be retried next round)."""
        if self.dead:
            return False
        want = 1 + self.prefetch
        t0 = self._wall()
        try:
            if want == 1:
                lease = self._call("acquire")
                leases = [] if lease is None else [lease]
            else:
                resp = self._call("acquire", count=want)
                leases = list(resp.get("leases") or [])
        except RetryExhausted as exc:
            self.emit("acquire_abandoned", error=str(exc))
            return False
        finally:
            self.stats["acquire_s"] += self._wall() - t0
        if not leases:
            return False
        self.stats["leases_run"] += len(leases)
        self.stats["leases_prefetched"] += len(leases) - 1
        self._held = list(leases)
        try:
            if self._groupable(leases):
                self._run_group_quantum(leases)
            else:
                self._run_solo_quantum(leases)
        except WorkerKilled:
            self.dead = True
            self.died_at = self.clock.now()
            self.stats["kills"] += 1
            for lease in self._held:
                self.emit("worker_killed", lease_id=lease["lease_id"],
                          range_id=lease["range_id"])
                self._maybe_tear_checkpoint(lease)
            return True
        except LeasePreempted:
            for lease in self._held:
                ck = None
                if self._lease is not None and \
                        lease["lease_id"] == self._lease["lease_id"]:
                    ck = self._lease_checkpoint(lease)
                    ck = ck if ck and os.path.exists(ck) else None
                try:
                    self._call("release", lease_id=lease["lease_id"],
                               checkpoint=ck)
                except RetryExhausted:
                    pass  # expiry re-queues the range; ck rides the table
                self.emit("worker_preempted", lease_id=lease["lease_id"],
                          range_id=lease["range_id"], checkpoint=ck)
            self.dead = True
            self.preempted = True
            self.died_at = self.clock.now()
            self.stats["preemptions"] += 1
            return True
        finally:
            self._lease = None
            self._held = []
            self._group_mode = False
        return True

    def _run_group_quantum(self, leases: List[Dict[str, Any]]) -> None:
        """All held leases through ONE SweepSession.run_group batch,
        then one batched completion turn."""
        parts = []
        for lease in leases:
            lo, hi = lease["lo"], lease["hi"]
            faults = self.faults
            if faults is not None and np.asarray(faults).ndim == 3:
                faults = np.asarray(faults)[lo:hi]
            parts.append({"seeds": self.seeds[lo:hi], "faults": faults})
        self._group_mode = True
        self._lease = leases[0]
        self._hb_count = 0
        self.stats["grouped_leases"] += len(leases)
        t0 = self._wall()
        try:
            results = self.session().run_group(parts,
                                               observe=self._heartbeat)
        except LeaseLost:
            # Every lease in the group was declared lost mid-flight
            # (each already accounted by the heartbeat path): abandon
            # the batch; re-execution elsewhere reproduces the results.
            return
        finally:
            self.stats["sweep_s"] += self._wall() - t0
        self._lease = None
        # Complete EVERY range we computed — including any lease lost
        # mid-group: determinism makes a late completion a harmless
        # first-or-crosschecked duplicate, and it may beat the re-issue.
        msgs = [{"method": "complete", "lease_id": l["lease_id"],
                 "range_id": l["range_id"], "result": r}
                for l, r in zip(leases, results)]
        try:
            resps = self._call("batch", msgs=msgs)
            self.stats["completions"] += len(resps)
        except RetryExhausted as exc:
            for lease in leases:
                self.emit("complete_abandoned",
                          lease_id=lease["lease_id"],
                          range_id=lease["range_id"], error=str(exc))

    def _run_solo_quantum(self, leases: List[Dict[str, Any]]) -> None:
        """Each held lease through the full per-lease sweep (checkpoint
        / exchange / search machinery intact), sequentially."""
        for lease in leases:
            if not any(l["lease_id"] == lease["lease_id"]
                       for l in self._held):
                continue  # declared lost by an earlier heartbeat
            self._lease = lease
            t0 = self._wall()
            try:
                result = self._run_lease(lease)
            except LeaseLost:
                self.stats["leases_lost"] += 1
                self.emit("lease_lost", lease_id=lease["lease_id"],
                          range_id=lease["range_id"])
                self._drop_held(lease["lease_id"])
                self._lease = None
                continue
            finally:
                # NB: self._lease stays set on kill/preempt unwind —
                # run_once's handlers need to know WHICH lease was
                # running (its checkpoint rides the release).
                self.stats["sweep_s"] += self._wall() - t0
            self._lease = None
            self._report_lease(lease, result)
            self._drop_held(lease["lease_id"])

    def _drop_held(self, lease_id: int) -> None:
        self._held = [l for l in self._held
                      if l["lease_id"] != lease_id]

    # -- reporting (publish + complete, one coalesced turn) --------------
    def _report_lease(self, lease, result) -> None:
        """Report one solo lease: the corpus publish (exchange leases)
        and the completion ride ONE batched RPC turn — ordered publish
        first so the exchange barrier lifts with the quantum, with the
        coordinator's complete-time backstop unchanged behind it. A
        torn publish falls back to the solo re-send loop."""
        corpus = None
        msgs = []
        if lease.get("exchange_epoch") is not None and \
                getattr(result, "search", None) is not None:
            from .exchange import corpus_payload

            corpus = self._result_corpus(result)
            msgs.append({"method": "publish",
                         "range_id": lease["range_id"],
                         "snapshot": corpus_payload(corpus)})
        msgs.append({"method": "complete", "lease_id": lease["lease_id"],
                     "range_id": lease["range_id"], "result": result})
        try:
            resps = self._call("batch", msgs=msgs)
        except RetryExhausted as exc:
            # Abandon: the lease expires, the range re-issues, and the
            # re-execution (or our own retry on a later lease of the
            # same range) reproduces the identical result.
            self.emit("complete_abandoned", lease_id=lease["lease_id"],
                      range_id=lease["range_id"], error=str(exc))
            return
        if corpus is not None:
            presp = resps[0]
            if presp.get("torn"):
                self.stats["corpus_resent"] += 1
                self._publish_corpus(lease, corpus, first_attempt=1)
            else:
                self.stats["corpus_published"] += 1
                self.emit("corpus_published", range_id=lease["range_id"],
                          epoch=lease.get("exchange_epoch"),
                          duplicate=bool(presp.get("duplicate")),
                          resent=0)
        self.stats["completions"] += 1

    # -- lease execution -------------------------------------------------
    def _lease_checkpoint(self, lease) -> Optional[str]:
        if self.checkpoint_dir is None:
            return None
        return os.path.join(self.checkpoint_dir,
                            f"range_{lease['range_id']:05d}.npz")

    def _maybe_tear_checkpoint(self, lease) -> None:
        """Chaos follow-up to a kill: tear the dead worker's lease
        checkpoint, simulating a crash that corrupted the file (the
        pre-fsync failure mode) so the next holder exercises the
        corrupt-checkpoint recovery path."""
        if self.chaos is None or \
                not self.chaos.config.tear_checkpoint_on_kill:
            return
        ck = self._lease_checkpoint(lease)
        if ck and os.path.exists(ck):
            from .chaos import tear_file

            tear_file(ck)
            self.emit("checkpoint_torn", range_id=lease["range_id"],
                      path=ck)

    def _result_corpus(self, result):
        """The finished range's corpus snapshot (deterministic host
        data — every serialization of it is bitwise identical)."""
        from ..search.corpus import HostCorpus

        rep = result.search
        return HostCorpus(sched=rep.corpus_sched, sig=rep.corpus_sig,
                          score=rep.corpus_score,
                          filled=rep.corpus_filled,
                          entry=rep.corpus_entry,
                          depth=rep.corpus_depth)

    def _publish_corpus(self, lease, corpus, first_attempt: int = 0) -> None:
        """Solo re-send loop for a corpus publish whose coalesced first
        attempt came back TORN (payload failed the coordinator's
        checksum — chaos, or a real transport tearing bytes): re-send a
        fresh serialization; the dedupe layer absorbs any accidental
        double delivery."""
        from .exchange import corpus_payload

        for attempt in range(first_attempt, 4):
            try:
                resp = self._call("publish", range_id=lease["range_id"],
                                  snapshot=corpus_payload(corpus))
            except RetryExhausted as exc:
                # Abandon: the coordinator backstops from the completion
                # payload (or the range re-runs after expiry).
                self.emit("publish_abandoned",
                          range_id=lease["range_id"], error=str(exc))
                return
            if not resp.get("torn"):
                self.stats["corpus_published"] += 1
                self.emit("corpus_published", range_id=lease["range_id"],
                          epoch=lease.get("exchange_epoch"),
                          duplicate=bool(resp.get("duplicate")),
                          resent=attempt)
                return
            self.stats["corpus_resent"] += 1
        self.emit("publish_abandoned", range_id=lease["range_id"],
                  error="torn on every attempt")

    def _run_lease(self, lease) -> Any:
        lo, hi = lease["lo"], lease["hi"]
        seeds = self.seeds[lo:hi]
        faults = self.faults
        if faults is not None and np.asarray(faults).ndim == 3:
            faults = np.asarray(faults)[lo:hi]
        kwargs = dict(self.sweep_kwargs)
        if kwargs.get("search") is not None:
            # Lineage entry-id base (obs/lineage.py): this range's
            # corpus inserts are recorded under globally-unique entry
            # ids lo + position + 1, so the fleet-merged report
            # resolves cross-range ancestry — a pure id shift,
            # chaos-invariant like every other per-range input.
            kwargs["search_lin_base"] = lo
        if lease.get("exchange_gen0"):
            # Epoch stream offset: this range's sweep mutates on a
            # fresh generation-key family (exchange.GEN_STRIDE) so a
            # seeded epoch explores NEW children instead of redrawing
            # the mutations its seed corpus's epoch already tried.
            kwargs["search_gen0"] = lease["exchange_gen0"]
        if lease.get("corpus") is not None:
            # Exchange seeding: the lease carries the merged previous-
            # epoch corpus; verify the checksum (a torn broadcast must
            # not silently skew the hunt) and install it as the sweep's
            # seed corpus. Deterministic per range — a re-issued lease
            # carries the identical payload.
            from .exchange import payload_corpus

            kwargs["search_corpus"] = payload_corpus(lease["corpus"])
            self.stats["corpus_seeded"] += 1
        ck = self._lease_checkpoint(lease)
        if ck is not None:
            # resume=True: if a previous holder (crashed or preempted)
            # left a checkpoint at this range's path, continue from it
            # bit-exactly; otherwise start fresh and write our own.
            kwargs.update(checkpoint_path=lease.get("checkpoint") or ck,
                          checkpoint_every_chunks=self.checkpoint_every_chunks,
                          resume=True)
            if lease.get("checkpoint") and os.path.exists(lease["checkpoint"]):
                self.stats["checkpoints_recovered"] += 1
                self.emit("lease_resumed", range_id=lease["range_id"],
                          checkpoint=lease["checkpoint"])
        self._hb_count = 0
        run = lambda: self.session().run(  # noqa: E731
            seeds, faults=faults, observe=self._heartbeat, **kwargs)
        try:
            return run()
        except CheckpointError as exc:
            # Torn/corrupt resume artifact: discard and re-run fresh —
            # the loader's message names the path and this exact
            # recovery option. Deterministic re-execution means the
            # retry costs time, never correctness.
            self.stats["checkpoints_discarded"] += 1
            path = kwargs.get("checkpoint_path", ck)
            self.emit("checkpoint_corrupt", range_id=lease["range_id"],
                      path=path, error=str(exc).splitlines()[0])
            if path and os.path.exists(path):
                os.remove(path)
            return run()

    # -- the heartbeat boundary ------------------------------------------
    def _heartbeat(self, record: Dict[str, Any]) -> None:
        """sweep(observe=...) callback: one call per host scalar read.
        This is the fabric's preemption point — chaos and SIGTERM land
        here, between supersteps, where the sweep's exception path
        flushes the checkpoint writer before unwinding. One beat covers
        EVERY held lease (the running one and any prefetched behind it):
        liveness is a worker property, so the coalesced extension is the
        semantics, not an approximation."""
        if record.get("event") == "summary":
            return  # final sweep record, not a liveness beat
        if record.get("schema") not in (None, "madsim.sweep.telemetry/1"):
            # Search-telemetry records (obs/lineage.py) ride the same
            # observe sink but are refill-grain accounting, not scalar-
            # read beats: counting them would shift the heartbeat
            # numbering chaos kill/preempt schedules key on.
            return
        self._hb_count += 1
        self.clock.advance(1)
        action = (self.chaos.heartbeat_action(self.worker_id)
                  if self.chaos is not None else "ok")
        if action == KILL:
            raise WorkerKilled(self.worker_id)
        if action == PREEMPT or self._preempt_requested:
            raise LeasePreempted(self.worker_id)
        progress = {"seeds_done": record.get("seeds_done"),
                    "chunks": record.get("chunks"),
                    "n_active": record.get("n_active")}
        if action == DROP:
            self.stats["heartbeats_dropped"] += 1
            self.emit("heartbeat_dropped", lease_id=self._lease["lease_id"])
            return
        if action == DELAY:
            # Deferred, not lost: delivered before the NEXT beat — the
            # lease sees a late extension instead of a gap.
            self.stats["heartbeats_delayed"] += 1
            self._delayed_progress = progress
            return
        if self._delayed_progress is not None:
            self._send_heartbeat(self._delayed_progress)
            self._delayed_progress = None
        self._send_heartbeat(progress)

    def _send_heartbeat(self, progress: Dict[str, Any]) -> None:
        held = self._held if self._held else (
            [self._lease] if self._lease is not None else [])
        if not held:
            return
        ids = [l["lease_id"] for l in held]
        kw = ({"lease_id": ids[0]} if len(ids) == 1
              else {"lease_ids": ids})
        try:
            resp = self._call("heartbeat", progress=progress, **kw)
        except RetryExhausted:
            # Transport down: keep sweeping — the lease may expire, in
            # which case a later beat (or the completion) learns it.
            return
        self.stats["heartbeats_sent"] += 1
        lost = resp.get("lost")
        if lost is None:
            lost = [] if resp.get("ok") else ids
        if not lost:
            return
        lost = set(lost)
        running_id = (self._lease["lease_id"]
                      if self._lease is not None else None)
        for lease in list(self._held):
            if lease["lease_id"] not in lost:
                continue
            if lease["lease_id"] == running_id and not self._group_mode:
                continue  # raised below — the solo queue accounts it
            self.stats["leases_lost"] += 1
            self.emit("lease_lost", lease_id=lease["lease_id"],
                      range_id=lease["range_id"])
            self._drop_held(lease["lease_id"])
        if running_id in lost and not self._group_mode:
            raise LeaseLost(running_id)
        if self._group_mode and not self._held:
            # Every lease of the group is gone: abandon the batch.
            raise LeaseLost(tuple(sorted(lost)))
