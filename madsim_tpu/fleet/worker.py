"""The fleet worker: acquire a lease, sweep it, heartbeat, report.

A worker is a thin loop around PR 4's pipelined ``sweep()``: one lease =
one sweep over the leased seed slice, run to completion with the same
engine, mesh, and sweep knobs every other worker uses (that uniformity
is what the merge layer's bitwise contract rides on). Heartbeats piggy-
back on the sweep's own telemetry cadence — the ``observe=`` callback
fires once per host scalar read, so lease liveness costs ZERO extra
device syncs — and the heartbeat boundary doubles as the fabric's
preemption point: chaos kills, SIGTERM preemption, and lease-lost
aborts all land there, between supersteps, where the sweep's own
exception path already flushes the async checkpoint writer.

Failure handling per the ISSUE contract:

- **kill** (crash): the sweep aborts mid-flight, nothing is released;
  the lease expires at the coordinator and re-issues. If the dead
  worker had checkpointed, the re-issued lease carries the path and the
  next holder resumes bit-exactly (crash recovery == resume).
- **SIGTERM preemption**: ``request_preemption()`` (wired to the signal
  by :func:`install_sigterm_handler`) makes the next heartbeat raise;
  the worker releases the lease WITH its checkpoint and exits its
  quantum cleanly — resume on restart, per the satellite.
- **corrupt checkpoint** (torn file from a crashed writer): the
  hardened loader (engine/checkpoint.py) raises ``CheckpointError``;
  the worker deletes the file and re-runs the range fresh — losing only
  time, never correctness, because re-execution is deterministic.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..engine.checkpoint import CheckpointError
from .chaos import DELAY, DROP, KILL, PREEMPT
from .rpc import RetryExhausted, RetryPolicy, call_with_retry


class WorkerKilled(BaseException):
    """Chaos crash: aborts the in-flight sweep at a heartbeat boundary.
    BaseException so no recovery handler inside the sweep path can
    accidentally swallow the 'crash'. (Python ``finally`` blocks still
    run — so an async checkpoint writer flushes its last COMPLETED
    snapshot, equivalent to dying just after a finished write; the
    torn-file crash is injected separately via
    ``ChaosConfig.tear_checkpoint_on_kill``.)"""


class LeasePreempted(Exception):
    """SIGTERM-style preemption: stop at the next heartbeat, release the
    lease with the checkpoint, survive."""


class LeaseLost(Exception):
    """The coordinator declared this lease expired/superseded: abandon
    the range (someone else owns it now; determinism makes any late
    completion of ours a harmless crosschecked duplicate)."""


class Worker:
    """One fleet worker. ``run_once()`` is the scheduling quantum the
    fabric drives: acquire one lease, sweep it, report it.

    ``sweep_kwargs`` are the uniform per-lease sweep knobs
    (chunk_steps, superstep_max, recycle/batch_worlds, ...);
    ``checkpoint_dir`` enables per-lease checkpointing (preemption
    survival + crash recovery); ``checkpoint_every_chunks`` its cadence.
    """

    def __init__(self, worker_id: str, engine, seeds, transport, clock,
                 faults: Optional[np.ndarray] = None, mesh=None,
                 retry: Optional[RetryPolicy] = None,
                 chaos=None, emit=None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every_chunks: int = 4,
                 sweep_kwargs: Optional[Dict[str, Any]] = None):
        self.worker_id = worker_id
        self.engine = engine
        self.seeds = np.asarray(seeds, np.uint64)
        self.faults = faults
        self.mesh = mesh
        self.transport = transport
        self.clock = clock
        self.retry = retry or RetryPolicy()
        self.chaos = chaos
        self._emit = emit
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every_chunks = checkpoint_every_chunks
        self.sweep_kwargs = dict(sweep_kwargs or {})
        self.dead = False
        self.died_at: float = 0.0
        self.preempted = False
        self._preempt_requested = False
        self._lease: Optional[Dict[str, Any]] = None
        self._delayed_progress: Optional[Dict[str, Any]] = None
        self._hb_count = 0
        self.stats = {"leases_run": 0, "completions": 0, "kills": 0,
                      "preemptions": 0, "leases_lost": 0,
                      "heartbeats_sent": 0, "heartbeats_dropped": 0,
                      "heartbeats_delayed": 0, "rpc_retries": 0,
                      "checkpoints_recovered": 0,
                      "checkpoints_discarded": 0,
                      "corpus_published": 0, "corpus_resent": 0,
                      "corpus_seeded": 0}

    # -- preemption ------------------------------------------------------
    def request_preemption(self) -> None:
        """Ask the worker to stop at the next heartbeat, checkpoint, and
        release its lease (the SIGTERM handler's body; also callable
        directly, which is how the inline chaos harness models
        preemption)."""
        self._preempt_requested = True

    def install_sigterm_handler(self) -> None:
        """Route SIGTERM to :meth:`request_preemption` — for worker
        processes under a preempting scheduler (k8s, borg, spot VMs).
        Must run on the main thread of the worker process."""
        import signal

        signal.signal(signal.SIGTERM,
                      lambda _sig, _frm: self.request_preemption())

    def restart(self) -> None:
        """Revive after a kill/preemption (the fabric's restart path).
        All lease state was lost with the 'process'; the engine and its
        jit caches survive because inline workers share the host
        process — a real restart would recompile, changing nothing
        about results."""
        self.dead = False
        self.preempted = False
        self._preempt_requested = False
        self._lease = None
        self._delayed_progress = None

    # -- telemetry -------------------------------------------------------
    def emit(self, event: str, **fields) -> None:
        if self._emit is None:
            return
        rec = {"schema": "madsim.fleet.telemetry/1", "event": event,
               "t": self.clock.now(), "worker": self.worker_id}
        rec.update(fields)
        self._emit(rec)

    # -- RPC helpers (all retried with deterministic backoff) ------------
    def _call(self, method: str, **kw):
        def on_retry(attempt, delay, exc):
            self.stats["rpc_retries"] += 1
            self.emit("rpc_retry", method=method, attempt=attempt,
                      delay=round(float(delay), 3), error=str(exc))

        return call_with_retry(
            lambda: self.transport.call(method, self.worker_id, **kw),
            self.retry, self.clock, tag=f"{self.worker_id}:{method}",
            on_retry=on_retry)

    # -- the scheduling quantum ------------------------------------------
    def run_once(self) -> bool:
        """Acquire + run + report ONE lease. Returns True if any work
        happened (False: idle — nothing pending, or acquire failed and
        will be retried next round)."""
        if self.dead:
            return False
        try:
            lease = self._call("acquire")
        except RetryExhausted as exc:
            self.emit("acquire_abandoned", error=str(exc))
            return False
        if lease is None:
            return False
        self.stats["leases_run"] += 1
        self._lease = lease
        try:
            result = self._run_lease(lease)
        except WorkerKilled:
            self.dead = True
            self.died_at = self.clock.now()
            self.stats["kills"] += 1
            self.emit("worker_killed", lease_id=lease["lease_id"],
                      range_id=lease["range_id"])
            self._maybe_tear_checkpoint(lease)
            return True
        except LeasePreempted:
            ck = self._lease_checkpoint(lease)
            ck = ck if ck and os.path.exists(ck) else None
            try:
                self._call("release", lease_id=lease["lease_id"],
                           checkpoint=ck)
            except RetryExhausted:
                pass  # expiry will re-queue the range; ck rides the table
            self.dead = True
            self.preempted = True
            self.died_at = self.clock.now()
            self.stats["preemptions"] += 1
            self.emit("worker_preempted", lease_id=lease["lease_id"],
                      range_id=lease["range_id"], checkpoint=ck)
            return True
        except LeaseLost:
            self.stats["leases_lost"] += 1
            self.emit("lease_lost", lease_id=lease["lease_id"],
                      range_id=lease["range_id"])
            return True
        finally:
            self._lease = None
        if lease.get("exchange_epoch") is not None and \
                getattr(result, "search", None) is not None:
            # Publish the range's final corpus BEFORE the completion so
            # the exchange barrier can lift as soon as the epoch's last
            # quantum finishes; a lost publish is backstopped by the
            # coordinator at complete (same dedupe path), so neither RPC
            # alone is load-bearing.
            self._publish_corpus(lease, result)
        try:
            self._call("complete", lease_id=lease["lease_id"],
                       range_id=lease["range_id"], result=result)
            self.stats["completions"] += 1
        except RetryExhausted as exc:
            # Abandon: the lease expires, the range re-issues, and the
            # re-execution (or our own retry on a later lease of the
            # same range) reproduces the identical result.
            self.emit("complete_abandoned", lease_id=lease["lease_id"],
                      range_id=lease["range_id"], error=str(exc))
        return True

    # -- lease execution -------------------------------------------------
    def _lease_checkpoint(self, lease) -> Optional[str]:
        if self.checkpoint_dir is None:
            return None
        return os.path.join(self.checkpoint_dir,
                            f"range_{lease['range_id']:05d}.npz")

    def _maybe_tear_checkpoint(self, lease) -> None:
        """Chaos follow-up to a kill: tear the dead worker's lease
        checkpoint, simulating a crash that corrupted the file (the
        pre-fsync failure mode) so the next holder exercises the
        corrupt-checkpoint recovery path."""
        if self.chaos is None or \
                not self.chaos.config.tear_checkpoint_on_kill:
            return
        ck = self._lease_checkpoint(lease)
        if ck and os.path.exists(ck):
            from .chaos import tear_file

            tear_file(ck)
            self.emit("checkpoint_torn", range_id=lease["range_id"],
                      path=ck)

    def _publish_corpus(self, lease, result) -> None:
        """Send the finished range's corpus snapshot to the coordinator.

        Retries ride the normal RPC backoff; a TORN response (payload
        failed the coordinator's checksum — chaos, or a real transport
        tearing bytes) re-sends a fresh serialization: the snapshot is
        deterministic host data, so a re-send is bitwise identical and
        the dedupe layer absorbs any accidental double delivery."""
        from ..search.corpus import HostCorpus
        from .exchange import corpus_payload

        rep = result.search
        corpus = HostCorpus(sched=rep.corpus_sched, sig=rep.corpus_sig,
                            score=rep.corpus_score,
                            filled=rep.corpus_filled,
                            entry=rep.corpus_entry,
                            depth=rep.corpus_depth)
        for attempt in range(4):
            try:
                resp = self._call("publish", range_id=lease["range_id"],
                                  snapshot=corpus_payload(corpus))
            except RetryExhausted as exc:
                # Abandon: the coordinator backstops from the completion
                # payload (or the range re-runs after expiry).
                self.emit("publish_abandoned",
                          range_id=lease["range_id"], error=str(exc))
                return
            if not resp.get("torn"):
                self.stats["corpus_published"] += 1
                self.emit("corpus_published", range_id=lease["range_id"],
                          epoch=lease.get("exchange_epoch"),
                          duplicate=bool(resp.get("duplicate")),
                          resent=attempt)
                return
            self.stats["corpus_resent"] += 1
        self.emit("publish_abandoned", range_id=lease["range_id"],
                  error="torn on every attempt")

    def _run_lease(self, lease) -> Any:
        from ..parallel.sweep import sweep

        lo, hi = lease["lo"], lease["hi"]
        seeds = self.seeds[lo:hi]
        faults = self.faults
        if faults is not None and np.asarray(faults).ndim == 3:
            faults = np.asarray(faults)[lo:hi]
        kwargs = dict(self.sweep_kwargs)
        if kwargs.get("search") is not None:
            # Lineage entry-id base (obs/lineage.py): this range's
            # corpus inserts are recorded under globally-unique entry
            # ids lo + position + 1, so the fleet-merged report
            # resolves cross-range ancestry — a pure id shift,
            # chaos-invariant like every other per-range input.
            kwargs["search_lin_base"] = lo
        if lease.get("exchange_gen0"):
            # Epoch stream offset: this range's sweep mutates on a
            # fresh generation-key family (exchange.GEN_STRIDE) so a
            # seeded epoch explores NEW children instead of redrawing
            # the mutations its seed corpus's epoch already tried.
            kwargs["search_gen0"] = lease["exchange_gen0"]
        if lease.get("corpus") is not None:
            # Exchange seeding: the lease carries the merged previous-
            # epoch corpus; verify the checksum (a torn broadcast must
            # not silently skew the hunt) and install it as the sweep's
            # seed corpus. Deterministic per range — a re-issued lease
            # carries the identical payload.
            from .exchange import payload_corpus

            kwargs["search_corpus"] = payload_corpus(lease["corpus"])
            self.stats["corpus_seeded"] += 1
        ck = self._lease_checkpoint(lease)
        if ck is not None:
            # resume=True: if a previous holder (crashed or preempted)
            # left a checkpoint at this range's path, continue from it
            # bit-exactly; otherwise start fresh and write our own.
            kwargs.update(checkpoint_path=lease.get("checkpoint") or ck,
                          checkpoint_every_chunks=self.checkpoint_every_chunks,
                          resume=True)
            if lease.get("checkpoint") and os.path.exists(lease["checkpoint"]):
                self.stats["checkpoints_recovered"] += 1
                self.emit("lease_resumed", range_id=lease["range_id"],
                          checkpoint=lease["checkpoint"])
        self._hb_count = 0
        run = lambda: sweep(  # noqa: E731
            None, self.engine.cfg, seeds, faults=faults, engine=self.engine,
            mesh=self.mesh, observe=self._heartbeat, **kwargs)
        try:
            return run()
        except CheckpointError as exc:
            # Torn/corrupt resume artifact: discard and re-run fresh —
            # the loader's message names the path and this exact
            # recovery option. Deterministic re-execution means the
            # retry costs time, never correctness.
            self.stats["checkpoints_discarded"] += 1
            path = kwargs.get("checkpoint_path", ck)
            self.emit("checkpoint_corrupt", range_id=lease["range_id"],
                      path=path, error=str(exc).splitlines()[0])
            if path and os.path.exists(path):
                os.remove(path)
            return run()

    # -- the heartbeat boundary ------------------------------------------
    def _heartbeat(self, record: Dict[str, Any]) -> None:
        """sweep(observe=...) callback: one call per host scalar read.
        This is the fabric's preemption point — chaos and SIGTERM land
        here, between supersteps, where the sweep's exception path
        flushes the checkpoint writer before unwinding."""
        if record.get("event") == "summary":
            return  # final sweep record, not a liveness beat
        if record.get("schema") not in (None, "madsim.sweep.telemetry/1"):
            # Search-telemetry records (obs/lineage.py) ride the same
            # observe sink but are refill-grain accounting, not scalar-
            # read beats: counting them would shift the heartbeat
            # numbering chaos kill/preempt schedules key on.
            return
        self._hb_count += 1
        self.clock.advance(1)
        action = (self.chaos.heartbeat_action(self.worker_id)
                  if self.chaos is not None else "ok")
        if action == KILL:
            raise WorkerKilled(self.worker_id)
        if action == PREEMPT or self._preempt_requested:
            raise LeasePreempted(self.worker_id)
        progress = {"seeds_done": record.get("seeds_done"),
                    "chunks": record.get("chunks"),
                    "n_active": record.get("n_active")}
        if action == DROP:
            self.stats["heartbeats_dropped"] += 1
            self.emit("heartbeat_dropped", lease_id=self._lease["lease_id"])
            return
        if action == DELAY:
            # Deferred, not lost: delivered before the NEXT beat — the
            # lease sees a late extension instead of a gap.
            self.stats["heartbeats_delayed"] += 1
            self._delayed_progress = progress
            return
        if self._delayed_progress is not None:
            self._send_heartbeat(self._delayed_progress)
            self._delayed_progress = None
        self._send_heartbeat(progress)

    def _send_heartbeat(self, progress: Dict[str, Any]) -> None:
        try:
            resp = self._call("heartbeat",
                              lease_id=self._lease["lease_id"],
                              progress=progress)
        except RetryExhausted:
            # Transport down: keep sweeping — the lease may expire, in
            # which case a later beat (or the completion) learns it.
            return
        self.stats["heartbeats_sent"] += 1
        if not resp.get("ok"):
            raise LeaseLost(self._lease["lease_id"])
