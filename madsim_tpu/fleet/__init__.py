"""Resilient fleet sweep fabric: leased seed ranges + crash-identical
recovery (docs/fleet.md).

The step from "one process's device set" (parallel/sweep.py) toward the
ROADMAP's always-on, millions-of-seeds/s hunting service: a coordinator
splits the seed vector into contiguous ranges and leases them (with
expiry) to workers; each worker runs its leased slice through the
pipelined device sweep and heartbeats progress; expired or released
leases re-issue to surviving workers. Because every range sweep is
bit-deterministic from its seeds, failure recovery is *replay*: a
crashed worker's range re-executes identically elsewhere, a preempted
worker's checkpoint resumes bit-exactly, and a double-completed range
is resolved by asserting bitwise equality — redundancy becomes a free
cross-execution determinism check instead of a conflict.

The contract (tier-1 chaos matrix, tests/test_fleet.py + ``make
chaos``): a fleet sweep under injected worker kills, lease expiries,
duplicate completions, SIGTERM preemptions, and torn checkpoints
returns seed ids, bug flags, per-seed observations/metrics, and a
coverage ledger bitwise identical to a crash-free fleet AND to a
single-host ``sweep()`` over the same seeds.

Entry point: :func:`fleet_sweep` (inline deterministic fabric by
default; ``spawn="process"`` for real OS workers with pipes+signals).
"""
from .chaos import ChaosConfig, ChaosPolicy
from .coordinator import Coordinator, FLEET_SCHEMA
from .exchange import (
    EXCHANGE_SCHEMA,
    CorpusExchange,
    ExchangeConfig,
    TornPayloadError,
)
from .fabric import FleetStalledError, LocalFabric, fleet_sweep
from .lease import Lease, LeaseTable, SeedRange, split_ranges
from .merge import (
    FleetIntegrityError,
    contract_mismatches,
    merge_range_results,
)
from .rpc import (
    MAX_CONTROL_RPCS_PER_LEASE,
    InlineTransport,
    RealClock,
    RetryExhausted,
    RetryPolicy,
    RpcError,
    VirtualClock,
    call_with_retry,
)
from .worker import LeaseLost, LeasePreempted, Worker, WorkerKilled

__all__ = [
    "ChaosConfig", "ChaosPolicy", "Coordinator", "CorpusExchange",
    "EXCHANGE_SCHEMA", "ExchangeConfig", "FLEET_SCHEMA",
    "FleetIntegrityError", "FleetStalledError", "InlineTransport",
    "TornPayloadError",
    "Lease", "LeaseLost", "LeasePreempted", "LeaseTable", "LocalFabric",
    "MAX_CONTROL_RPCS_PER_LEASE",
    "RealClock", "RetryExhausted", "RetryPolicy", "RpcError",
    "SeedRange", "VirtualClock", "Worker", "WorkerKilled",
    "call_with_retry", "contract_mismatches", "fleet_sweep",
    "merge_range_results", "split_ranges",
]
