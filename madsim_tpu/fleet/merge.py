"""Merge per-range SweepResults into one fleet SweepResult.

The crash-identical contract lives here. Per-range sweeps are bit-
deterministic functions of (seeds, config, faults) — worlds are
position-independent and each range runs to retirement — so the
CONTRACT fields of the merged result (seed ids, per-seed observations
incl. the ``m_*`` metrics frames, bug flags, and the coverage ledger's
hits/first-seen) depend only on the *set* of completed ranges, never on
which worker ran a range, how many times it ran, whether it resumed
from a preemption checkpoint, or in what order completions arrived.
That is what makes the three-way tier-1 equality possible: chaotic
fleet == clean fleet == single-host ``sweep()`` (ISSUE 7 acceptance).

Orchestration fields (``n_active_history``, ``loop_stats``,
``novelty_curve``, ``world_utilization``) are *fabric telemetry*: they
describe how this particular fleet execution unfolded and legitimately
differ run to run. They are merged best-effort (range-major order,
chunk indices re-based) and excluded from the crosscheck.

The same contract powers duplicate resolution: a double-reported range
(lease expired but the old holder finished anyway; a network-duplicated
completion) is resolved by asserting the two payloads bitwise equal on
the contract fields — redundancy becomes a free cross-execution
determinism check, and any mismatch is a loud
:class:`FleetIntegrityError`, never a silent pick-one.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..parallel.sweep import SweepResult
from .lease import SeedRange


class FleetIntegrityError(RuntimeError):
    """Two executions of the same seed range disagreed bitwise — the
    determinism contract is broken (nondeterministic actor/engine code,
    mixed engine versions in one fleet, or corrupted transport)."""


def contract_mismatches(a: SweepResult, b: SweepResult) -> List[str]:
    """Field names where two results for the SAME range disagree on the
    contract surface (empty list = bitwise interchangeable)."""
    bad: List[str] = []
    if not np.array_equal(a.seeds, b.seeds):
        bad.append("seeds")
    if not np.array_equal(a.bug, b.bug):
        bad.append("bug")
    if set(a.observations) != set(b.observations):
        bad.append("observations.keys")
    else:
        bad.extend(f"observations.{k}" for k in sorted(a.observations)
                   if not np.array_equal(a.observations[k],
                                         b.observations[k]))
    if (a.coverage is None) != (b.coverage is None):
        bad.append("coverage")
    elif a.coverage is not None:
        if not np.array_equal(a.coverage.hits, b.coverage.hits):
            bad.append("coverage.hits")
        if not np.array_equal(a.coverage.first_seen_seed,
                              b.coverage.first_seen_seed):
            bad.append("coverage.first_seen_seed")
    if a.faults_sha256 != b.faults_sha256:
        bad.append("faults_sha256")
    sa = getattr(a, "search", None)
    sb = getattr(b, "search", None)
    if (sa is None) != (sb is None):
        bad.append("search")
    elif sa is not None:
        # Guided sweeps: the materialized per-seed schedules and the
        # final corpus are contract surface too — two executions of one
        # range must evolve identical corpora and run identical
        # children (docs/search.md determinism contract).
        if not np.array_equal(sa.schedules, sb.schedules):
            bad.append("search.schedules")
        for f in ("corpus_sched", "corpus_sig", "corpus_score",
                  "corpus_filled", "corpus_entry", "corpus_depth"):
            if not np.array_equal(getattr(sa, f), getattr(sb, f)):
                bad.append(f"search.{f}")
        la = getattr(sa, "lineage", None)
        lb = getattr(sb, "lineage", None)
        if (la is None) != (lb is None):
            bad.append("search.lineage")
        elif la is not None:
            # The provenance lanes (obs/lineage.py) are contract
            # surface: ancestry attribution must not depend on which
            # worker ran the range.
            for f in ("parent1", "parent2", "ops", "depth"):
                if not np.array_equal(getattr(la, f), getattr(lb, f)):
                    bad.append(f"search.lineage.{f}")
    return bad


def crosscheck_duplicate(range_id: int, first: SweepResult,
                         second: SweepResult) -> None:
    """Raise FleetIntegrityError unless the double-reported range's two
    executions agree bitwise on the contract fields."""
    bad = contract_mismatches(first, second)
    if bad:
        raise FleetIntegrityError(
            f"duplicate completion of range {range_id} disagrees with the "
            f"accepted result on: {', '.join(bad)} — two executions of "
            "the same seeds must be bitwise identical; this fleet is "
            "mixing engine versions or running nondeterministic code")


def _merge_coverage(ranges: Sequence[SeedRange],
                    parts: Dict[int, SweepResult]):
    """Fold per-range ledgers into the global ledger.

    ``hits`` are counts and ``first_seen`` minima (obs/coverage.py's
    order-invariance contract), and every range folds each of its seeds
    exactly once — so sum-of-hits and min-of-(first_seen + range.lo)
    reproduce the single-host ledger bit for bit. Returns the merged
    SweepCoverage, or None when the sweeps ran metrics-off.
    """
    from ..obs.coverage import SweepCoverage

    first_part = parts[ranges[0].range_id]
    if first_part.coverage is None:
        return None
    k = first_part.coverage.n_buckets
    hits = np.zeros(k, np.int64)
    first_seen = np.full(k, np.iinfo(np.int64).max, np.int64)
    novelty: List[int] = []
    for r in ranges:
        cov = parts[r.range_id].coverage
        if cov is None or cov.n_buckets != k:
            raise FleetIntegrityError(
                f"range {r.range_id} reported an incompatible coverage "
                f"ledger (buckets: {None if cov is None else cov.n_buckets}"
                f" vs {k}) — all workers must run the same engine config")
        hits += np.asarray(cov.hits, np.int64)
        fs = np.asarray(cov.first_seen_seed, np.int64)
        seen = fs >= 0
        # Range-local seed positions re-base to global by +lo; the
        # global first_seen is the min over ranges of the re-based ids.
        first_seen = np.where(seen, np.minimum(first_seen, fs + r.lo),
                              first_seen)
        novelty.append(int(np.count_nonzero(hits)))
    first_seen = np.where(first_seen == np.iinfo(np.int64).max,
                          np.int64(-1), first_seen)
    return SweepCoverage(
        n_buckets=k, hits=hits, first_seen_seed=first_seen,
        # Fleet novelty is sampled at RANGE grain (cumulative distinct
        # after merging each range in range-id order) — fabric
        # telemetry, deterministic for a given range split but not the
        # single-host per-chunk curve.
        novelty_curve=np.asarray(novelty, np.int64))


def merge_range_results(seeds: np.ndarray, ranges: Sequence[SeedRange],
                        parts: Dict[int, SweepResult], n_devices: int,
                        fleet_stats: Optional[Dict[str, Any]] = None
                        ) -> SweepResult:
    """Assemble the fleet SweepResult from one completed result per range.

    Requires every range completed exactly once in ``parts`` (the
    coordinator resolves duplicates before this point). Contract fields
    scatter per-seed into global position; telemetry fields concatenate
    in range-id order with chunk indices re-based.
    """
    ranges = sorted(ranges, key=lambda r: r.range_id)
    missing = [r.range_id for r in ranges if r.range_id not in parts]
    if missing:
        raise ValueError(f"cannot merge: ranges {missing} not completed")
    n = int(np.asarray(seeds).shape[0])
    if ranges[-1].hi != n or ranges[0].lo != 0:
        raise ValueError("ranges do not tile the seed vector")

    first = parts[ranges[0].range_id]
    obs: Dict[str, np.ndarray] = {}
    for key, proto in first.observations.items():
        proto = np.asarray(proto)
        obs[key] = np.zeros((n,) + proto.shape[1:], proto.dtype)
    steps_run = 0
    hist: List[np.ndarray] = []
    hist_chunks: List[np.ndarray] = []
    chunk_base = 0
    util_num = 0.0
    util_den = 0
    faults_sha = first.faults_sha256
    for r in ranges:
        p = parts[r.range_id]
        if p.faults_sha256 != faults_sha:
            raise FleetIntegrityError(
                f"range {r.range_id} swept a different fault schedule "
                f"({p.faults_sha256} vs {faults_sha})")
        for key in obs:
            obs[key][r.lo:r.hi] = np.asarray(p.observations[key])[:r.n_seeds]
        steps_run += p.steps_run
        hist.append(np.asarray(p.n_active_history, np.int64))
        hist_chunks.append(np.asarray(p.n_active_chunks, np.int64)
                           + chunk_base)
        chunk_base += int(p.loop_stats.get("chunks", 0))
        # Utilization weighted by issued steps (steps_run ~ chunk count;
        # an estimate — the exact issued-slot-step sums stay per range).
        util_num += p.world_utilization * max(p.steps_run, 1)
        util_den += max(p.steps_run, 1)

    loop_stats: Dict[str, Any] = {
        "fleet": dict(fleet_stats or {}),
        "ranges": {r.range_id: parts[r.range_id].loop_stats
                   for r in ranges},
    }
    return SweepResult(
        seeds=np.asarray(seeds),
        bug=obs["bug"],
        observations=obs,
        steps_run=steps_run,
        n_devices=n_devices,
        n_active_history=(np.concatenate(hist) if hist
                          else np.zeros(0, np.int64)),
        world_utilization=(util_num / util_den if util_den else 0.0),
        n_active_chunks=(np.concatenate(hist_chunks) if hist_chunks
                         else np.zeros(0, np.int64)),
        loop_stats=loop_stats,
        faults_sha256=faults_sha,
        coverage=_merge_coverage(ranges, parts),
    )
