"""Fleet RPC plumbing: clocks, retry-with-backoff, inline transport.

Everything in this module is HOST-side orchestration of the fabric — it
never feeds a simulation decision (the merge contract makes the final
``SweepResult`` independent of any timing here), but the fabric itself
must still be *testable deterministically*: the chaos matrix asserts a
crashed fleet's result bitwise against a crash-free one, and flaky
orchestration would make those tests flaky. Hence two clocks behind one
interface (a virtual tick clock for the inline fabric, the monotonic
clock for real processes) and backoff jitter drawn from splitmix64 —
a counter-based generator like the engine's Threefry, so a retry
schedule is a pure function of (seed, call site, attempt).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


class RpcError(RuntimeError):
    """Transient transport failure: the call may be retried."""


class RetryExhausted(RpcError):
    """All retry attempts failed; the caller must abandon the operation
    (for a worker: drop the lease and let expiry re-issue it — the
    deterministic replay makes abandonment safe)."""


# -- clocks ------------------------------------------------------------------

class VirtualClock:
    """Integer fabric ticks for the inline (deterministic) fabric.

    Ticks advance on worker heartbeats and fabric scheduling rounds —
    never from the wall — so lease expiry, backoff, and restart timing
    are replayable facts of the schedule, not of host load.
    """

    def __init__(self) -> None:
        self._t = 0

    def now(self) -> float:
        return float(self._t)

    def advance(self, n: int = 1) -> None:
        self._t += int(n)

    def sleep(self, dt: float) -> None:
        # Sleeping IS advancing: a backoff of d ticks moves the fabric
        # forward, which is what lets a retry loop outlive a lease TTL
        # in tests exactly as it would on the wall clock.
        self._t += max(1, int(-(-dt // 1)))


class RealClock:
    """Monotonic wall time for multiprocess/production fabrics.

    The fabric is host-side orchestration beside the device sweep, like
    the observatory and the async checkpoint writer: its clock reads are
    sanctioned here, at one site, and never reach simulation code — the
    merged result is bitwise independent of them (tier-1 chaos matrix).
    """

    def now(self) -> float:
        import time as _walltime

        return _walltime.monotonic()  # detlint: allow[DET001]

    def advance(self, n: int = 1) -> None:
        pass  # the wall advances itself

    def sleep(self, dt: float) -> None:
        import time as _walltime

        _walltime.sleep(dt)  # detlint: allow[DET001]


# -- deterministic jitter ----------------------------------------------------

_MASK64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    """One splitmix64 step: the fabric's counter-based hash/PRNG.

    Pure integer math (no `random`, no OS entropy), so every jitter and
    chaos decision is a function of its inputs alone — the same property
    the engine gets from Threefry, at host-bookkeeping price.
    """
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return (x ^ (x >> 31)) & _MASK64


def unit_hash(*parts: object) -> float:
    """Deterministic uniform in [0, 1) from arbitrary hashable parts.

    Strings fold in via their UTF-8 bytes (``hash()`` is per-process
    salted — DET006's lesson applies to the fabric too).
    """
    acc = 0x243F6A8885A308D3
    for p in parts:
        if isinstance(p, str):
            for b in p.encode():
                acc = splitmix64(acc ^ b)
        else:
            acc = splitmix64(acc ^ (int(p) & _MASK64))
    return splitmix64(acc) / float(1 << 64)


# -- retry with backoff ------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    Delays are in CLOCK units: fabric ticks under the inline fabric
    (keep ``base_delay`` at 1.0 so a retry visibly advances the fabric),
    seconds under real processes. ``jitter`` is the uniform fraction
    added on top of the exponential term — drawn via splitmix64 from
    (seed, tag, attempt), so two runs of the same fabric schedule
    identical retries.
    """

    max_attempts: int = 5
    base_delay: float = 1.0
    max_delay: float = 16.0
    jitter: float = 0.5
    seed: int = 0

    def delay(self, tag: str, attempt: int) -> float:
        exp = min(self.base_delay * (2 ** attempt), self.max_delay)
        return exp * (1.0 + self.jitter * unit_hash(self.seed, tag, attempt))


def call_with_retry(fn: Callable[[], Any], policy: RetryPolicy, clock,
                    tag: str,
                    on_retry: Optional[Callable[[int, float, BaseException],
                                                None]] = None) -> Any:
    """Run ``fn`` retrying RpcError with backoff; other exceptions pass
    through untouched (they are bugs, not weather). ``on_retry`` sees
    (attempt, delay, error) before each sleep — the fleet telemetry
    hook."""
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except RpcError as exc:
            last = exc
            if attempt + 1 >= policy.max_attempts:
                break
            d = policy.delay(tag, attempt)
            if on_retry is not None:
                on_retry(attempt, d, exc)
            clock.sleep(d)
    raise RetryExhausted(
        f"{tag}: {policy.max_attempts} attempts failed; last error: {last}")


# -- inline transport --------------------------------------------------------

#: Counted-discipline bound (tests/test_fleet.py): non-heartbeat control
#: turns (acquire / batch / release / publish-resend / ...) a fleet run
#: may spend per issued lease. The coalesced control plane holds the
#: real number well under this — one acquire turn and one batched
#: publish+complete turn cover a whole prefetched quantum — but idle
#: acquire polls and chaos-forced re-sends ride the same budget, hence
#: the slack. This is the "RPC count per lease drops to a small
#: constant" gate, as a named constant.
MAX_CONTROL_RPCS_PER_LEASE = 3


class InlineTransport:
    """Worker→coordinator calls as plain method dispatch, with the chaos
    policy interposed exactly where a network would sit.

    The RPC surface is the coordinator's ``rpc_*`` methods. Chaos can
    fail a call before it reaches the coordinator (the worker retries
    with backoff — ISSUE's "retry-with-backoff on all coordinator
    RPCs") and can DUPLICATE a completion after it succeeds (the
    at-least-once delivery failure mode the merge layer's bitwise
    crosscheck exists for).
    """

    def __init__(self, coordinator, chaos=None):
        self.coordinator = coordinator
        self.chaos = chaos
        self.calls: Dict[str, int] = {}
        self.injected_failures = 0
        self.injected_duplicates = 0
        self.injected_torn_publishes = 0

    def call(self, method: str, worker_id: str, **kw):
        self.calls[method] = self.calls.get(method, 0) + 1
        if method == "batch":
            return self._call_batch(worker_id, kw["msgs"])
        if self.chaos is not None and self.chaos.rpc_fail(method, worker_id):
            self.injected_failures += 1
            raise RpcError(
                f"injected transport failure: {method} from {worker_id}")
        return self._deliver(method, worker_id, kw)

    def _call_batch(self, worker_id: str, msgs):
        """One batched control turn: several logical messages, one
        transport round trip (the coalesced control plane). Chaos
        interposition stays per LOGICAL message — each message draws
        its rpc_fail / tear-publish / duplicate-completion decisions
        under its own method name, so chaos schedules keyed on logical
        traffic are invariant to the coalescing — but failure is
        atomic: every message's fail draw lands BEFORE any delivery,
        and one failure fails the whole turn (the worker's retry
        re-sends all of it; the publish dedupe and completion
        crosscheck absorb the replays)."""
        prepared = []
        failed = None
        for m in msgs:
            m = dict(m)
            lm = m.pop("method")
            if self.chaos is not None and self.chaos.rpc_fail(lm, worker_id):
                self.injected_failures += 1
                if failed is None:
                    failed = lm
            prepared.append((lm, m))
        if failed is not None:
            raise RpcError(f"injected transport failure: {failed} "
                           f"(batched) from {worker_id}")
        return [self._deliver(lm, worker_id, m) for lm, m in prepared]

    def _deliver(self, method: str, worker_id: str, kw: Dict[str, Any]):
        """Deliver one logical message (tear/duplicate chaos included)."""
        if (method == "publish" and self.chaos is not None
                and self.chaos.tear_publish(worker_id)):
            # Tear the snapshot IN FLIGHT (flip one byte of the payload)
            # so the coordinator's checksum rejects it — the torn-
            # publish failure mode of a real network, exercised
            # deterministically. The worker re-sends a fresh (clean)
            # serialization.
            import numpy as _np

            self.injected_torn_publishes += 1
            kw = dict(kw)
            snap = dict(kw["snapshot"])
            sched = _np.array(snap["sched"], copy=True)
            sched.flat[0] ^= 1
            snap["sched"] = sched
            kw["snapshot"] = snap
        fn = getattr(self.coordinator, f"rpc_{method}")
        out = fn(worker_id=worker_id, **kw)
        if (method == "complete" and self.chaos is not None
                and self.chaos.duplicate_completion(worker_id)):
            # At-least-once delivery: the network "retransmits" an
            # already-delivered completion. The coordinator must resolve
            # it as a bitwise-checked duplicate, not double-merge it.
            self.injected_duplicates += 1
            fn(worker_id=worker_id, **kw)
        return out
