"""Seed-range leases: the unit of work distribution in the fleet fabric.

The coordinator splits the seed vector into contiguous ranges and hands
them to workers as *leases with expiry*: a worker must heartbeat a lease
to keep it, and a lease whose expiry passes (worker crashed, heartbeats
dropped, host preempted without a release) silently returns to the
pending queue for re-issue to a surviving worker. Because every range's
sweep is bit-deterministic from its seeds (PAPER.md; the engine's core
contract), re-issuing a lease whose original holder is secretly still
running is *harmless*: whichever completion arrives second is resolved
by asserting bitwise equality against the first (fleet/merge.py), which
turns accidental redundancy into a free cross-execution determinism
check — the FoundationDB move of making recovery a replay, not a repair.

Time here is the *fabric clock* (fleet/rpc.py): integer ticks under the
deterministic inline fabric, monotonic seconds under real processes.
Nothing in this module reads a clock itself — callers pass ``now`` — so
the lease state machine is a pure, directly testable object.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


class LeaseError(RuntimeError):
    """Protocol violation at the lease table (not a transport failure)."""


@dataclasses.dataclass(frozen=True)
class SeedRange:
    """A contiguous slice [lo, hi) of the fleet's global seed vector.

    ``lo``/``hi`` are *positions* in the seed vector (the same ids the
    sweep's slot→seed index and the coverage ledger's ``first_seen_seed``
    use), not seed values — so range-local results re-base into the
    global result by adding ``lo``.
    """

    range_id: int
    lo: int
    hi: int

    @property
    def n_seeds(self) -> int:
        return self.hi - self.lo


@dataclasses.dataclass
class Lease:
    """One issued lease: a range, its current holder, and its deadline.

    ``generation`` counts issues of the range (0 = first issue); a
    heartbeat or completion carrying a stale generation belongs to a
    holder the table already declared dead — it is refused (heartbeat)
    or resolved as a duplicate (completion), never allowed to extend a
    lease it no longer owns. ``checkpoint`` is the resume artifact a
    preempted holder released (or a crashed holder left on shared
    storage): it rides the lease so the NEXT holder continues from it
    instead of replaying the range from step zero.
    """

    lease_id: int
    range: SeedRange
    worker_id: str
    generation: int
    issued_at: float
    expires_at: float
    checkpoint: Optional[str] = None
    progress: Dict[str, object] = dataclasses.field(default_factory=dict)
    # Liveness bookkeeping for stall diagnostics (FleetStalledError
    # names the holder and its last beat): count + clock time of the
    # most recent accepted heartbeat (-1.0 = never beat).
    heartbeats: int = 0
    last_heartbeat: float = -1.0
    # Acquire-ahead marker: True when this lease was issued beyond the
    # first slot of a multi-lease acquire (lease prefetch) — the holder
    # is NOT running it yet, it is queued behind the holder's running
    # lease. Stall diagnostics must say so, or a prefetched lease reads
    # as a hung sweep.
    prefetched: bool = False


def split_ranges(n_seeds: int, range_size: int) -> List[SeedRange]:
    """Cut the seed vector into contiguous ranges of ``range_size``.

    The split depends ONLY on (n_seeds, range_size) — never on worker
    count, chaos, or timing — so the set of per-range sweeps (and
    therefore the merged result) is the same for every fabric shape.
    """
    if range_size < 1:
        raise ValueError("range_size must be >= 1")
    return [SeedRange(i, lo, min(lo + range_size, n_seeds))
            for i, lo in enumerate(range(0, n_seeds, range_size))]


class LeaseTable:
    """The coordinator's lease bookkeeping: pending queue + live leases.

    Deterministic by construction: ranges issue in range-id order, an
    expired range re-queues at the back, and every mutation is driven by
    an explicit ``now`` from the caller. The table never touches results
    — completion bookkeeping lives in the coordinator, which also owns
    the duplicate crosscheck.
    """

    def __init__(self, ranges: List[SeedRange], ttl: float):
        if ttl <= 0:
            raise ValueError("lease ttl must be > 0")
        self.ttl = ttl
        self._ranges = {r.range_id: r for r in ranges}
        self._pending: List[int] = [r.range_id for r in ranges]
        self._live: Dict[int, Lease] = {}          # lease_id -> Lease
        self._by_range: Dict[int, int] = {}        # range_id -> lease_id
        self._generation: Dict[int, int] = {r.range_id: -1 for r in ranges}
        self._checkpoint: Dict[int, str] = {}      # range_id -> resume path
        self._next_lease_id = 0
        self._done: Dict[int, bool] = {r.range_id: False for r in ranges}

    # -- queries ---------------------------------------------------------
    @property
    def n_pending(self) -> int:
        return len(self._pending)

    @property
    def n_live(self) -> int:
        return len(self._live)

    def outstanding(self) -> List[int]:
        """Range ids not yet completed (pending or leased)."""
        return [rid for rid, done in self._done.items() if not done]

    def live_leases(self) -> List[Lease]:
        return list(self._live.values())

    # -- mutations (all take explicit ``now``) ---------------------------
    def expire(self, now: float) -> List[Lease]:
        """Reap leases whose deadline passed; their ranges re-queue.

        Returns the reaped leases so the coordinator can emit telemetry
        (lease_expired + re-lease records) — the table itself stays
        silent.
        """
        reaped = []
        for lease_id in sorted(self._live):
            lease = self._live[lease_id]
            if lease.expires_at <= now:
                reaped.append(lease)
        for lease in reaped:
            del self._live[lease.lease_id]
            del self._by_range[lease.range.range_id]
            if lease.checkpoint is not None:
                self._checkpoint[lease.range.range_id] = lease.checkpoint
            if not self._done[lease.range.range_id]:
                self._pending.append(lease.range.range_id)
        return reaped

    def issue(self, worker_id: str, now: float,
              eligible=None) -> Optional[Lease]:
        """Issue the next pending range to ``worker_id`` (None if all
        ranges are leased or done). ``eligible`` (optional predicate on
        range ids) gates which pending ranges may issue — the corpus
        exchange's epoch barrier (fleet/exchange.py) holds back ranges
        whose seed corpus has not merged yet; the FIRST eligible pending
        range issues, preserving range-id-major order within an epoch."""
        if not self._pending:
            return None
        pos = 0
        if eligible is not None:
            pos = next((i for i, rid in enumerate(self._pending)
                        if eligible(rid)), None)
            if pos is None:
                return None
        rid = self._pending.pop(pos)
        self._generation[rid] += 1
        lease = Lease(
            lease_id=self._next_lease_id,
            range=self._ranges[rid],
            worker_id=worker_id,
            generation=self._generation[rid],
            issued_at=now,
            expires_at=now + self.ttl,
            checkpoint=self._checkpoint.get(rid),
        )
        self._next_lease_id += 1
        self._live[lease.lease_id] = lease
        self._by_range[rid] = lease.lease_id
        return lease

    def heartbeat(self, lease_id: int, worker_id: str, now: float,
                  progress: Optional[Dict[str, object]] = None) -> bool:
        """Extend a lease's deadline. False = the lease is lost (expired
        and reaped, superseded by a re-issue, or never existed) — the
        caller must stop working on it."""
        lease = self._live.get(lease_id)
        if lease is None or lease.worker_id != worker_id:
            return False
        lease.expires_at = now + self.ttl
        lease.heartbeats += 1
        lease.last_heartbeat = now
        if progress:
            lease.progress.update(progress)
        return True

    def release(self, lease_id: int, worker_id: str,
                checkpoint: Optional[str] = None) -> bool:
        """Voluntary give-back (SIGTERM preemption): the range re-queues
        immediately — no expiry wait — carrying ``checkpoint`` so the
        next holder resumes instead of replaying."""
        lease = self._live.get(lease_id)
        if lease is None or lease.worker_id != worker_id:
            return False
        del self._live[lease_id]
        del self._by_range[lease.range.range_id]
        if checkpoint is not None:
            self._checkpoint[lease.range.range_id] = checkpoint
        if not self._done[lease.range.range_id]:
            self._pending.append(lease.range.range_id)
        return True

    def complete(self, range_id: int,
                 lease_id: Optional[int] = None) -> Tuple[bool, bool]:
        """Mark a range done. Returns ``(first, was_live)``: ``first`` is
        False for a duplicate completion (range already done — the
        coordinator crosschecks the payloads), ``was_live`` True when a
        live lease was retired by this completion.

        Completions are accepted even from expired/superseded leases:
        the data is valid regardless of who computed it — determinism is
        the authenticator, and the crosscheck enforces it.
        """
        first = not self._done[range_id]
        self._done[range_id] = True
        was_live = False
        live_id = self._by_range.get(range_id)
        if live_id is not None:
            # Any completion retires the range's live lease — including a
            # completion from the ORIGINAL holder of a re-issued range
            # (the new holder's eventual completion resolves as a
            # crosschecked duplicate).
            del self._live[live_id]
            del self._by_range[range_id]
            was_live = True
        if first and range_id in self._pending:
            # Completed by a holder the table had given up on while the
            # range sat re-queued: drop the stale queue entry so nobody
            # re-runs work that is already done.
            self._pending.remove(range_id)
        self._checkpoint.pop(range_id, None)
        return first, was_live

    def checkpoint_for(self, range_id: int) -> Optional[str]:
        return self._checkpoint.get(range_id)

    def lease_for_range(self, range_id: int) -> Optional[Lease]:
        """The live lease currently holding ``range_id`` (None when the
        range is pending or done) — stall diagnostics."""
        lease_id = self._by_range.get(range_id)
        return None if lease_id is None else self._live.get(lease_id)
