"""The fleet coordinator: lease issue/expiry/re-issue, completion dedup,
and per-worker fabric telemetry.

One coordinator owns one hunt: the global seed vector, the range split,
the lease table, and the accumulating per-range results. Its public
surface is the four ``rpc_*`` methods workers reach through a transport
(inline dispatch or a process pipe) — everything else is local state.
The coordinator never touches a device: results arrive as host-side
``SweepResult`` payloads, and the only "validation" it ever performs is
the one determinism makes possible — bitwise equality of independent
executions (fleet/merge.py).

Telemetry: every protocol event emits one
``madsim.fleet.telemetry/1`` record into the same observe-sink shape
the sweep observatory uses (callable or JSONL path; docs/fleet.md lists
the event vocabulary), so ``python -m madsim_tpu.obs watch`` machinery
and operators get per-worker lease/retry/re-lease visibility without a
second pipeline.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..parallel.sweep import SweepResult
from .lease import LeaseTable, SeedRange, split_ranges
from .merge import crosscheck_duplicate, merge_range_results

FLEET_SCHEMA = "madsim.fleet.telemetry/1"


class Coordinator:
    """Lease-table owner + result accumulator for one fleet sweep.

    ``clock`` follows fleet/rpc.py (virtual ticks inline, monotonic
    seconds under processes); ``lease_ttl`` is in clock units. ``emit``
    is an optional telemetry callable (one dict per protocol event).
    """

    def __init__(self, seeds, range_size: int, lease_ttl: float, clock,
                 emit=None, n_devices: int = 1, exchange=None):
        self.seeds = np.asarray(seeds, np.uint64)
        self.ranges: List[SeedRange] = split_ranges(
            self.seeds.shape[0], range_size)
        self.table = LeaseTable(self.ranges, ttl=lease_ttl)
        self.clock = clock
        self.n_devices = n_devices
        self._emit = emit
        # Cross-range corpus exchange (fleet/exchange.py CorpusExchange,
        # or None): gates lease issue on the epoch barrier, delivers
        # seed corpora with leases, and accepts/dedupes snapshot
        # publishes.
        self.exchange = exchange
        self.results: Dict[int, SweepResult] = {}
        # worker_id -> why that worker's last acquire-ahead stopped
        # short of its requested count (exchange epoch barrier) — the
        # stall report's "prefetch blocked" line.
        self._prefetch_blocked: Dict[str, str] = {}
        self.merge_s = 0.0
        self.stats: Dict[str, int] = {
            "ranges": len(self.ranges),
            "leases_issued": 0,
            "leases_reissued": 0,
            "leases_expired": 0,
            "leases_released": 0,
            "heartbeats": 0,
            "heartbeats_lost": 0,
            "completions": 0,
            "duplicate_completions": 0,
            "duplicates_crosschecked": 0,
        }

    # -- telemetry -------------------------------------------------------
    def emit(self, event: str, **fields) -> None:
        if self._emit is None:
            return
        rec = {"schema": FLEET_SCHEMA, "event": event,
               "t": self.clock.now()}
        rec.update(fields)
        self._emit(rec)

    # -- the RPC surface -------------------------------------------------
    def rpc_acquire(self, worker_id: str, count: int = 1
                    ) -> Optional[Dict[str, Any]]:
        """Hand the next pending range(s) to ``worker_id``.

        ``count=1`` (the legacy wire shape): one lease dict, or None —
        nothing pending (all ranges leased out or done, or every pending
        range held back by the exchange's epoch barrier; idle and retry).

        ``count>1`` is the acquire-ahead path (lease prefetch): up to
        ``count`` leases issue in ONE control turn, returned as
        ``{"leases": [...]}``; every lease beyond the first is marked
        ``prefetched``. The exchange epoch barrier is enforced at
        INSTALL time — issuing stops at the first ineligible range, so a
        prefetched lease's seed corpus is always its epoch's final
        merged corpus, exactly as if it had been acquired after the
        barrier lifted; the barrier reason is remembered per worker for
        ``stall_report()``.

        Under an exchange each lease additionally carries the range's
        deterministic seed corpus (the merged previous-epoch corpus;
        None for epoch 0) — a re-issued lease for a killed worker's
        range gets the SAME corpus its first holder did, which is the
        bounded-loss contract."""
        self._reap()
        eligible = (self.exchange.eligible
                    if self.exchange is not None else None)
        now = self.clock.now()
        out_leases: List[Dict[str, Any]] = []
        for i in range(max(1, int(count))):
            lease = self.table.issue(worker_id, now, eligible=eligible)
            if lease is None:
                break
            lease.prefetched = i > 0
            self.stats["leases_issued"] += 1
            if lease.generation > 0:
                self.stats["leases_reissued"] += 1
            self.emit("lease_issued", worker=worker_id,
                      lease_id=lease.lease_id,
                      range_id=lease.range.range_id,
                      lo=lease.range.lo, hi=lease.range.hi,
                      generation=lease.generation,
                      reissued=lease.generation > 0,
                      prefetched=lease.prefetched,
                      resume_checkpoint=lease.checkpoint)
            out = {
                "lease_id": lease.lease_id,
                "range_id": lease.range.range_id,
                "lo": lease.range.lo,
                "hi": lease.range.hi,
                "generation": lease.generation,
                "expires_at": lease.expires_at,
                "checkpoint": lease.checkpoint,
                "prefetched": lease.prefetched,
            }
            if self.exchange is not None:
                rid = lease.range.range_id
                out["exchange_epoch"] = self.exchange.epoch_of(rid)
                out["exchange_gen0"] = self.exchange.gen0_of(rid)
                out["corpus"] = self.exchange.seed_payload(rid,
                                                          worker=worker_id)
            out_leases.append(out)
        # Remember why the acquire-ahead stopped short (stall_report's
        # "barrier reason" line): only meaningful under an exchange —
        # a plain fleet's short acquire just means the queue ran dry.
        self._prefetch_blocked.pop(worker_id, None)
        if (len(out_leases) < max(1, int(count))
                and self.exchange is not None):
            for rid in sorted(self.table.outstanding()):
                if self.table.lease_for_range(rid) is not None:
                    continue
                reason = self.exchange.blocked_reason(rid)
                if reason:
                    self._prefetch_blocked[worker_id] = (
                        f"range {rid}: {reason}")
                    break
        if count == 1:
            return out_leases[0] if out_leases else None
        return {"leases": out_leases}

    def rpc_heartbeat(self, worker_id: str, lease_id: Optional[int] = None,
                      progress: Optional[Dict[str, Any]] = None,
                      lease_ids: Optional[List[int]] = None
                      ) -> Dict[str, Any]:
        """Extend lease(s). One beat covers every lease the worker holds
        (``lease_ids`` — the coalesced control plane; ``lease_id`` is
        the legacy single-lease wire shape). ``ok`` is the conjunction;
        ``lost`` names the leases that are LOST (expired and possibly
        re-issued): the worker must abandon those ranges — the fabric
        guarantees someone (re-)runs them, and if the worker's own run
        completes anyway the dedup layer absorbs it."""
        self._reap()
        ids = list(lease_ids) if lease_ids is not None else [lease_id]
        now = self.clock.now()
        lost = [i for i in ids
                if not self.table.heartbeat(i, worker_id, now, progress)]
        ok = not lost
        self.stats["heartbeats" if ok else "heartbeats_lost"] += 1
        self.emit("heartbeat", worker=worker_id, lease_id=ids[0],
                  ok=ok, leases=len(ids), **(progress or {}))
        return {"ok": ok, "lost": lost}

    def rpc_batch(self, worker_id: str, msgs: List[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
        """Server side of one coalesced control turn (the process
        transport delivers batches whole; the inline transport unpacks
        them itself so chaos can interpose per logical message).
        Messages dispatch in order — publish-before-complete keeps the
        exchange backstop semantics."""
        out = []
        for m in msgs:
            m = dict(m)
            method = m.pop("method")
            out.append(getattr(self, f"rpc_{method}")(
                worker_id=worker_id, **m))
        return out

    def rpc_release(self, worker_id: str, lease_id: int,
                    checkpoint: Optional[str] = None) -> Dict[str, Any]:
        """SIGTERM-preemption give-back: the range re-queues immediately,
        carrying the released checkpoint so its next holder resumes
        bit-exactly instead of replaying from step zero."""
        ok = self.table.release(lease_id, worker_id, checkpoint)
        if ok:
            self.stats["leases_released"] += 1
        self.emit("lease_released", worker=worker_id, lease_id=lease_id,
                  ok=ok, checkpoint=checkpoint)
        return {"ok": ok}

    def rpc_complete(self, worker_id: str, lease_id: int, range_id: int,
                     result: SweepResult) -> Dict[str, Any]:
        """Accept a range result. Duplicates (an expired lease's two
        holders both finishing, retransmitted completions) resolve by
        bitwise crosscheck against
        the accepted result — a mismatch raises FleetIntegrityError
        rather than silently picking a winner."""
        self._reap()
        first, _was_live = self.table.complete(range_id, lease_id)
        if first:
            self.results[range_id] = result
            self.stats["completions"] += 1
            if self.exchange is not None and \
                    not self.exchange.has(range_id):
                # Backstop publish: a worker that completed but whose
                # explicit publish was lost (crash between the two
                # RPCs, retry exhaustion) must not stall the epoch
                # barrier — the completion payload carries the same
                # final corpus, so the coordinator publishes it through
                # the identical dedupe/crosscheck path.
                self._publish_from_result(worker_id, range_id, result)
        else:
            self.stats["duplicate_completions"] += 1
            crosscheck_duplicate(range_id, self.results[range_id], result)
            self.stats["duplicates_crosschecked"] += 1
        self.emit("completion", worker=worker_id, lease_id=lease_id,
                  range_id=range_id, duplicate=not first,
                  crosschecked=not first,
                  n_seeds=int(np.asarray(result.seeds).shape[0]),
                  failing=len(result.failing_seeds))
        return {"accepted": True, "duplicate": not first}

    def rpc_publish(self, worker_id: str, range_id: int,
                    snapshot: Any) -> Dict[str, Any]:
        """Accept a range's corpus snapshot (cross-range exchange,
        fleet/exchange.py). Torn payloads are discarded and re-requested
        (``torn=True`` tells the sender to re-send); duplicates resolve
        by bitwise crosscheck — mismatch raises FleetIntegrityError."""
        if self.exchange is None:
            return {"accepted": False, "torn": False, "disabled": True}
        return self.exchange.publish(range_id, snapshot, worker=worker_id)

    def _publish_from_result(self, worker_id: str, range_id: int,
                             result: SweepResult) -> None:
        from ..search.corpus import HostCorpus
        from .exchange import corpus_payload

        rep = getattr(result, "search", None)
        if rep is None:
            return
        payload = corpus_payload(HostCorpus(
            sched=rep.corpus_sched, sig=rep.corpus_sig,
            score=rep.corpus_score, filled=rep.corpus_filled,
            entry=rep.corpus_entry, depth=rep.corpus_depth))
        self.exchange.publish(range_id, payload, worker=worker_id)

    def rpc_poll_done(self, worker_id: str) -> Dict[str, Any]:
        """Is the hunt over? Idle workers (acquire returned None because
        every pending range is leased to someone else) poll this to
        decide between waiting for a possible re-issue and exiting."""
        del worker_id
        return {"done": self.done()}

    # -- scheduler-side --------------------------------------------------
    def _reap(self) -> None:
        for lease in self.table.expire(self.clock.now()):
            self.stats["leases_expired"] += 1
            self.emit("lease_expired", worker=lease.worker_id,
                      lease_id=lease.lease_id,
                      range_id=lease.range.range_id,
                      generation=lease.generation,
                      had_checkpoint=lease.checkpoint is not None)

    def tick(self) -> None:
        """One scheduling round: reap expired leases even when no RPC
        arrives (a fleet whose only live worker is mid-sweep must still
        notice a dead peer's lease)."""
        self._reap()

    def done(self) -> bool:
        return len(self.results) == len(self.ranges)

    def stall_report(self) -> str:
        """One line per outstanding range, naming the holder, its lease
        generation, last accepted heartbeat, and deadline — or why a
        pending range cannot issue (exchange barrier). This is what
        FleetStalledError carries instead of a bare range count, so the
        post-mortem starts at the sick range, not at a grep."""
        now = self.clock.now()
        # A worker's RUNNING lease is its lowest live lease id; anything
        # above it marked prefetched is queued behind that run.
        running: Dict[str, int] = {}
        for lease in self.table.live_leases():
            cur = running.get(lease.worker_id)
            if cur is None or lease.lease_id < cur:
                running[lease.worker_id] = lease.lease_id
        lines: List[str] = []
        for rid in sorted(self.table.outstanding()):
            lease = self.table.lease_for_range(rid)
            if lease is not None:
                beat = ("never" if lease.last_heartbeat < 0
                        else f"t={lease.last_heartbeat:g}")
                role = ""
                if lease.prefetched and \
                        running.get(lease.worker_id) != lease.lease_id:
                    role = (f", prefetched behind lease "
                            f"{running[lease.worker_id]}")
                lines.append(
                    f"range {rid}: held by {lease.worker_id} (lease "
                    f"{lease.lease_id}, generation {lease.generation}, "
                    f"heartbeats {lease.heartbeats}, last heartbeat "
                    f"{beat}, expires t={lease.expires_at:g}{role})")
                continue
            blocked = (self.exchange.blocked_reason(rid)
                       if self.exchange is not None else None)
            lines.append(f"range {rid}: pending"
                         + (f", {blocked}" if blocked else " re-issue"))
        for wid in sorted(self._prefetch_blocked):
            lines.append(f"worker {wid}: prefetch blocked at epoch "
                         f"barrier ({self._prefetch_blocked[wid]})")
        return (f"outstanding ranges at t={now:g}:\n  "
                + "\n  ".join(lines)) if lines else "no outstanding ranges"

    def finalize(self, fleet_stats: Optional[Dict[str, Any]] = None
                 ) -> SweepResult:
        """Merge all range results into the fleet SweepResult and emit
        the summary telemetry record. Under an exchange the result also
        carries the fleet-level ``search`` report: the final merged
        corpus plus the per-seed materialized schedules."""
        import time as _walltime

        stats = dict(self.stats)
        if self.exchange is not None:
            stats.update(self.exchange.stats)
        stats.update(fleet_stats or {})
        t0 = _walltime.perf_counter()  # detlint: allow[DET001] reason=merge-phase wall timing for the fabric cost breakdown; never feeds a sim decision
        result = merge_range_results(self.seeds, self.ranges, self.results,
                                     self.n_devices, fleet_stats=stats)
        self.merge_s = _walltime.perf_counter() - t0  # detlint: allow[DET001] reason=merge-phase wall timing for the fabric cost breakdown; never feeds a sim decision
        stats["merge_s"] = round(self.merge_s, 6)
        result.loop_stats["fleet"]["merge_s"] = stats["merge_s"]
        if self.exchange is not None:
            result.search = self.exchange.fleet_report(
                int(self.seeds.shape[0]), self.ranges, self.results)
        self.emit("fleet_summary", seeds_total=int(self.seeds.shape[0]),
                  failing=len(result.failing_seeds), **stats)
        return result
