"""The fleet's failure machinery: a chaos harness for the fabric itself.

The simulator injects faults into the *simulated* cluster; this module
injects faults into the *fleet that runs the simulator* — worker kills
and restarts, dropped/delayed heartbeats, duplicated completions,
SIGTERM-style preemptions, torn checkpoint files, transient RPC
failures. The resilience contract under test (tests/test_fleet.py,
``make chaos``): a sweep that survives any mix of these produces a
``SweepResult`` bitwise identical to one that never saw them.

Every decision is deterministic: rate-based decisions hash
(seed, worker, event counter) through splitmix64, and explicit
``*_at`` schedules fire on exact per-worker heartbeat counts — so a
failing chaos combination replays exactly from its ChaosConfig, the
same way a failing seed replays through MADSIM_TEST_SEED.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from .rpc import unit_hash

# Heartbeat-time actions a chaos policy can order (worker.py executes
# them at the heartbeat boundary — the fabric's preemption point).
OK = "ok"
DROP = "drop"          # heartbeat lost in flight (expiry pressure)
DELAY = "delay"        # heartbeat deferred to the next beat
KILL = "kill"          # worker dies NOW: no release, no checkpoint flush
PREEMPT = "preempt"    # SIGTERM: checkpoint + lease release, then exit


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Declarative failure mix. All rates are per-event probabilities
    decided by deterministic hash; all ``*_at`` entries are
    ``(worker_id, nth_heartbeat)`` pairs (1-based, per worker, counted
    across that worker's whole life — kills don't reset the count).

    ``restart_after``: fabric ticks a dead worker stays down before the
    scheduler revives it (< 0 = never — the fleet must finish on the
    survivors). ``max_kills_per_worker`` bounds rate-based kills so a
    hostile rate cannot livelock the fleet; explicit ``kill_at`` entries
    are exempt (you asked for exactly those).
    ``tear_checkpoint_on_kill`` truncates the dead worker's in-progress
    lease checkpoint — the torn-file crash the hardened loader
    (engine/checkpoint.py) must refuse cleanly and the worker must
    recover from by discarding and re-running.
    """

    seed: int = 0
    kill_at: Tuple[Tuple[str, int], ...] = ()
    preempt_at: Tuple[Tuple[str, int], ...] = ()
    kill_rate: float = 0.0
    preempt_rate: float = 0.0
    drop_heartbeat_rate: float = 0.0
    delay_heartbeat_rate: float = 0.0
    drop_rpc_rate: float = 0.0
    duplicate_completion_rate: float = 0.0
    duplicate_all_completions: bool = False
    tear_checkpoint_on_kill: bool = False
    # Corpus-exchange publish tearing (fleet/exchange.py): flip a byte
    # of the snapshot in flight so the coordinator's checksum rejects it
    # and the worker must re-send. ``tear_publish_at`` entries are
    # (worker_id, nth-publish-attempt) pairs (1-based, per worker);
    # ``tear_publish_rate`` rolls per attempt — re-sends re-roll, so
    # convergence is guaranteed for rates < 1.
    tear_publish_at: Tuple[Tuple[str, int], ...] = ()
    tear_publish_rate: float = 0.0
    restart_after: int = 2
    max_kills_per_worker: int = 2


class ChaosPolicy:
    """Stateful executor of a ChaosConfig: per-worker event counters +
    the deterministic decisions derived from them."""

    def __init__(self, config: Optional[ChaosConfig] = None):
        self.config = config or ChaosConfig()
        self._beats: Dict[str, int] = {}
        self._kills: Dict[str, int] = {}
        self._rpc_seq: Dict[str, int] = {}
        self._kill_at = set(self.config.kill_at)
        self._preempt_at = set(self.config.preempt_at)

    # -- heartbeat-boundary decisions -----------------------------------
    def heartbeat_action(self, worker_id: str) -> str:
        """One action per heartbeat, evaluated most-destructive first so
        an explicit kill schedule cannot be shadowed by a drop roll."""
        c = self.config
        n = self._beats.get(worker_id, 0) + 1
        self._beats[worker_id] = n
        if (worker_id, n) in self._kill_at:
            self._kills[worker_id] = self._kills.get(worker_id, 0) + 1
            return KILL
        if (worker_id, n) in self._preempt_at:
            return PREEMPT
        budget = self._kills.get(worker_id, 0) < c.max_kills_per_worker
        if c.kill_rate > 0 and budget and \
                unit_hash(c.seed, worker_id, n, "kill") < c.kill_rate:
            self._kills[worker_id] = self._kills.get(worker_id, 0) + 1
            return KILL
        if c.preempt_rate > 0 and \
                unit_hash(c.seed, worker_id, n, "preempt") < c.preempt_rate:
            return PREEMPT
        if c.drop_heartbeat_rate > 0 and \
                unit_hash(c.seed, worker_id, n, "drop") < c.drop_heartbeat_rate:
            return DROP
        if c.delay_heartbeat_rate > 0 and \
                unit_hash(c.seed, worker_id, n, "delay") < c.delay_heartbeat_rate:
            return DELAY
        return OK

    # -- transport decisions --------------------------------------------
    def rpc_fail(self, method: str, worker_id: str) -> bool:
        """Fail this RPC attempt? Each attempt re-rolls on its own
        (worker, method, sequence) counter, so bursts of consecutive
        failures are possible — deliberately: retry exhaustion makes the
        worker ABANDON the operation, and the fabric's expiry + re-issue
        + duplicate-crosscheck machinery is what must (and does)
        converge the fleet anyway."""
        c = self.config
        if c.drop_rpc_rate <= 0:
            return False
        key = f"{worker_id}:{method}"
        seq = self._rpc_seq.get(key, 0)
        self._rpc_seq[key] = seq + 1
        return unit_hash(c.seed, worker_id, method, seq, "rpc") \
            < c.drop_rpc_rate

    def tear_publish(self, worker_id: str) -> bool:
        """Corrupt this corpus publish in flight? Counted per worker
        publish ATTEMPT, so an explicit ``tear_publish_at`` entry tears
        exactly once and the re-send goes through clean."""
        c = self.config
        if not c.tear_publish_at and c.tear_publish_rate <= 0:
            return False
        key = f"{worker_id}:pub"
        n = self._rpc_seq.get(key, 0) + 1
        self._rpc_seq[key] = n
        if (worker_id, n) in set(c.tear_publish_at):
            return True
        return c.tear_publish_rate > 0 and \
            unit_hash(c.seed, worker_id, n, "tearpub") < c.tear_publish_rate

    def duplicate_completion(self, worker_id: str) -> bool:
        c = self.config
        if c.duplicate_all_completions:
            return True
        if c.duplicate_completion_rate <= 0:
            return False
        key = f"{worker_id}:dup"
        seq = self._rpc_seq.get(key, 0)
        self._rpc_seq[key] = seq + 1
        return unit_hash(c.seed, worker_id, seq, "dup") \
            < c.duplicate_completion_rate

    # -- scheduler decisions --------------------------------------------
    def restart_due(self, died_at: float, now: float) -> bool:
        return (self.config.restart_after >= 0
                and now - died_at >= self.config.restart_after)

    @property
    def restarts_enabled(self) -> bool:
        return self.config.restart_after >= 0


def tear_file(path: str, keep_bytes: int = 128) -> None:
    """Truncate ``path`` to its first ``keep_bytes`` bytes — the torn
    npz a crash between write and publish would have left before the
    fsync fix, kept as an injectable fault so the corrupt-checkpoint
    recovery path stays exercised forever."""
    import os

    if not os.path.exists(path):
        return
    with open(path, "rb+") as f:
        f.truncate(min(keep_bytes, os.path.getsize(path)))
