"""Multiprocess fleet: real OS worker processes, pipes, and signals.

The deployment-shaped leg of the fabric: the coordinator stays in the
parent, each worker is a spawned process with its own JAX runtime and
engine, RPCs ride ``multiprocessing`` pipes, and preemption is a real
``SIGTERM`` handled by the worker's signal handler (checkpoint + lease
release + clean exit). The protocol objects are the SAME classes the
inline fabric runs — only the transport, the clock, and the scheduler
change — so the bitwise result contract carries over unchanged while
schedules become as nondeterministic as the OS makes them.

Scope: the CPU-mesh proof (``make chaos`` runs a small kill/SIGTERM
matrix here; tests mark it slow) and the template for a real deployment
where "pipe" becomes "TCP" and "spawn" becomes "your cluster
scheduler". Worker crash-kill is a parent-side SIGKILL; recovery is the
lease TTL doing its job.
"""
from __future__ import annotations

import os
import sys
from typing import Any, Dict, Optional

import numpy as np

from .rpc import RealClock, RetryPolicy, RpcError


def _wire_safe(kw: Dict[str, Any]) -> Dict[str, Any]:
    """Strip the process-local triage context from any SweepResult
    payload before it crosses the pipe: ``triage_ctx`` holds the live
    engine (jit closures — unpicklable by design), and the coordinator
    side never uses it (merged fleet results are 'reconstructed' and
    carry None there anyway)."""
    import dataclasses as _dc

    def scrub(v):
        if getattr(v, "triage_ctx", None) is not None:
            return _dc.replace(v, triage_ctx=None)
        return v

    out = {k: scrub(v) for k, v in kw.items()}
    if isinstance(out.get("msgs"), list):
        out["msgs"] = [{k: scrub(v) for k, v in m.items()}
                       for m in out["msgs"]]
    return out


class PipeTransport:
    """Worker-side transport: one request/response per call over the
    process's pipe to the coordinator."""

    def __init__(self, conn):
        self.conn = conn

    def call(self, method: str, worker_id: str, **kw):
        try:
            self.conn.send({"method": method, "worker_id": worker_id,
                            "kw": _wire_safe(kw)})
            resp = self.conn.recv()
        except (EOFError, OSError, BrokenPipeError) as exc:
            raise RpcError(f"coordinator pipe failed: {exc}") from exc
        if "err" in resp:
            raise RpcError(resp["err"])
        return resp["ok"]


def _worker_main(conn, worker_id: str, actor, cfg, seeds, faults,
                 checkpoint_dir: Optional[str],
                 checkpoint_every_chunks: int,
                 sweep_kwargs: Dict[str, Any]) -> None:
    """Entry point of a spawned worker process."""
    # Spawned fresh: the parent's test/CI environment (JAX_PLATFORMS,
    # XLA device-count flags) rides the inherited env vars; the engine
    # and all jit caches are rebuilt here, as on any real fleet host.
    # The persistent compilation cache (MADSIM_COMPILE_CACHE, set by the
    # parent when a checkpoint dir exists) turns that rebuild into a
    # disk load after the first worker compiles — without it, N workers
    # compile the identical sweep program N times.
    from ..parallel.compile_cache import enable_from_env

    enable_from_env()
    from ..engine.core import DeviceEngine
    from .worker import Worker

    eng = DeviceEngine(actor, cfg)
    clock = RealClock()
    transport = PipeTransport(conn)
    w = Worker(worker_id, eng, np.asarray(seeds, np.uint64), transport,
               clock, faults=faults,
               retry=RetryPolicy(base_delay=0.05, max_delay=1.0),
               checkpoint_dir=checkpoint_dir,
               checkpoint_every_chunks=checkpoint_every_chunks,
               sweep_kwargs=sweep_kwargs)
    w.install_sigterm_handler()
    while True:
        try:
            did = w.run_once()
        except RpcError:
            break  # parent gone: nothing to report to
        if w.dead:
            break  # preempted (SIGTERM): lease released, exit cleanly
        if not did:
            try:
                if transport.call("poll_done", worker_id)["done"]:
                    break
            except RpcError:
                break
            clock.sleep(0.05)
    conn.close()
    sys.exit(0)


def process_fleet_sweep(actor, cfg, seeds, *, n_workers: int,
                        range_size: int,
                        faults: Optional[np.ndarray] = None,
                        lease_ttl: float = 5.0,
                        observe: Any = None,
                        checkpoint_dir: Optional[str] = None,
                        checkpoint_every_chunks: int = 4,
                        retry: Optional[RetryPolicy] = None,
                        kill_after_heartbeats: Optional[Dict[str, int]] = None,
                        preempt_after_heartbeats: Optional[Dict[str, int]]
                        = None,
                        max_restarts_per_worker: int = 1,
                        serve_timeout_s: float = 60.0,
                        **sweep_kwargs):
    """Run a fleet sweep with real worker processes (``spawn="process"``).

    ``kill_after_heartbeats`` / ``preempt_after_heartbeats`` map worker
    ids to a heartbeat count after which the parent SIGKILLs /
    SIGTERMs that worker — the process-mode chaos hooks (the inline
    fabric's richer ChaosConfig needs deterministic scheduling this
    mode deliberately gives up). A killed worker respawns up to
    ``max_restarts_per_worker`` times; its lease recovers via TTL
    expiry either way. ``lease_ttl`` is in SECONDS here.
    """
    import multiprocessing as mp
    import signal

    from ..obs import observatory as _obsy
    from .coordinator import Coordinator

    seeds = np.asarray(seeds, np.uint64)
    clock = RealClock()
    emit, close = _obsy.make_observer(observe)
    coordinator = Coordinator(seeds, range_size=range_size,
                              lease_ttl=lease_ttl, clock=clock, emit=emit,
                              n_devices=1)
    if checkpoint_dir is not None:
        os.makedirs(checkpoint_dir, exist_ok=True)
        # Workers inherit env on spawn: point their persistent XLA
        # cache at the durable workdir so respawns (and workers 2..N)
        # load executables instead of recompiling them. An explicit
        # MADSIM_COMPILE_CACHE in the environment wins.
        from ..parallel.compile_cache import ENV_VAR

        os.environ.setdefault(
            ENV_VAR, os.path.join(checkpoint_dir, "xla_cache"))
    del retry  # worker-side policy is fixed in _worker_main

    ctx = mp.get_context("spawn")
    conns: Dict[Any, str] = {}
    procs: Dict[str, Any] = {}
    hb_served: Dict[str, int] = {}
    restarts: Dict[str, int] = {}

    def spawn_worker(wid: str) -> None:
        parent_conn, child_conn = ctx.Pipe()
        # The fleet's multiprocess leg IS real concurrency, host-side by
        # design; sim determinism is preserved by the merge layer's
        # bitwise contract, not by the scheduler.
        p = ctx.Process(target=_worker_main,
                        args=(child_conn, wid, actor, cfg, seeds, faults,
                              checkpoint_dir, checkpoint_every_chunks,
                              sweep_kwargs),
                        daemon=True)
        p.start()
        child_conn.close()
        conns[parent_conn] = wid
        procs[wid] = p
        coordinator.emit("worker_spawned", worker=wid, pid=p.pid)

    for i in range(n_workers):
        wid = f"w{i}"
        spawn_worker(wid)
        hb_served[wid] = 0
        restarts[wid] = 0

    from multiprocessing.connection import wait as conn_wait

    t0 = clock.now()
    try:
        while not coordinator.done():
            if clock.now() - t0 > serve_timeout_s:
                raise TimeoutError(
                    f"process fleet did not converge in {serve_timeout_s}s; "
                    f"stats: {coordinator.stats}")
            coordinator.tick()
            # Reap dead processes; their leases recover via TTL.
            for wid, p in list(procs.items()):
                if p.exitcode is not None and p.exitcode != 0 and \
                        restarts[wid] < max_restarts_per_worker:
                    restarts[wid] += 1
                    coordinator.emit("worker_restarted", worker=wid,
                                     exitcode=p.exitcode)
                    spawn_worker(wid)
            ready = conn_wait(list(conns), timeout=0.05)
            for conn in ready:
                wid = conns[conn]
                try:
                    req = conn.recv()
                except (EOFError, OSError):
                    del conns[conn]
                    continue
                method, kw = req["method"], req["kw"]
                try:
                    out = getattr(coordinator, f"rpc_{method}")(
                        worker_id=req["worker_id"], **kw)
                    conn.send({"ok": out})
                except Exception as exc:  # noqa: BLE001 — to the worker
                    conn.send({"err": f"{type(exc).__name__}: {exc}"})
                if method == "heartbeat":
                    hb_served[wid] = hb_served.get(wid, 0) + 1
                    n = hb_served[wid]
                    if (kill_after_heartbeats or {}).get(wid) == n:
                        os.kill(procs[wid].pid, signal.SIGKILL)
                        coordinator.emit("worker_killed", worker=wid,
                                         via="SIGKILL")
                    elif (preempt_after_heartbeats or {}).get(wid) == n:
                        os.kill(procs[wid].pid, signal.SIGTERM)
                        coordinator.emit("worker_preempt_signaled",
                                         worker=wid, via="SIGTERM")
        stats = {"n_workers": n_workers, "spawn": "process",
                 "restarts": dict(restarts)}
        return coordinator.finalize(fleet_stats=stats)
    finally:
        for p in procs.values():
            if p.exitcode is None:
                p.terminate()
        for p in procs.values():
            p.join(timeout=5.0)
        if close is not None:
            close()
