"""Cross-range corpus exchange: fleet-wide guided search (docs/fleet.md).

Under ``fleet_sweep(search=...)`` each leased seed range evolves its
parent corpus independently — deterministic, but partition-dependent,
and a killed worker forfeits every novel schedule its range discovered.
This module makes the fleet SHARE search progress without giving up one
bit of the chaos contract, by making every exchange decision a pure
function of the range partition and an exchange cadence — never of
scheduling, timing, or failures:

- **Epochs are structural.** Ranges partition into exchange epochs by
  range id (``epoch(r) = r // every``), so which ranges belong to an
  epoch is decided by ``split_ranges`` alone. The epoch BOUNDARY is
  keyed to completed lease quanta: epoch ``e`` ranges become issueable
  only once every epoch ``e-1`` range has published its corpus snapshot
  — never a wall clock.
- **Seeding is deterministic.** A lease for an epoch-``e`` range runs
  its guided sweep from the merged corpus of epoch ``e-1`` (the
  template-seeded corpus for epoch 0), delivered with the lease and
  installed at the sweep's first refill boundary via
  ``sweep(search_corpus=...)`` — a host→device transfer at sweep start,
  zero new mid-loop device syncs. A re-issued lease for a killed
  worker's range seeds from the SAME merged epoch, which is what bounds
  corpus loss to one exchange epoch instead of the whole range.
- **The merge is the device fold's host twin.** Snapshots fold in
  range-id order through :func:`madsim_tpu.search.corpus.merge_corpus`
  — the sequential worst-first insertion of ``harvest_fold``, bit for
  bit (parity tier-1-gated) — so the merged corpus of an epoch is a
  deterministic fold over (previous epoch's corpus, snapshots in
  range-id order), no matter who computed which snapshot or when.
- **Redundancy is an integrity check.** Duplicate publishes (restarted
  workers, re-leased ranges, at-least-once transports) dedupe by range
  id with a bitwise crosscheck — a mismatch raises
  :class:`~madsim_tpu.fleet.merge.FleetIntegrityError`, never a silent
  pick-one. Torn publishes fail the payload checksum, are discarded,
  and the worker re-sends.
- **The merged corpus is durable.** Accepted snapshots persist to
  ``state_path`` (fsync-before-rename, the engine/checkpoint.py
  discipline); a restarted coordinator reloads them and re-derives
  every merged epoch bit-exactly (the merge is a deterministic fold, so
  persistence of the inputs is persistence of the outputs).

Telemetry: every exchange event emits one ``madsim.fleet.exchange/1``
record (publish/merge/broadcast, with epoch, ranges merged, corpus
inserted, bytes) into the same observe sink as the sweep and fleet
schemas; ``python -m madsim_tpu.obs watch`` renders all three
interleaved.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
import tempfile
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..search.corpus import HostCorpus, host_corpus_init, merge_corpus
from .lease import SeedRange
from .merge import FleetIntegrityError

EXCHANGE_SCHEMA = "madsim.fleet.exchange/1"

# Generation stride between exchange epochs: epoch-``e`` ranges run
# their sweeps with ``search_gen0 = e * GEN_STRIDE``, so each epoch
# draws a FRESH family of mutation streams (children are keyed by
# (search seed, slot id, generation) — without the shift, every range
# would redraw the same mutations its parents' epoch already tried and
# the chained evolution would stall). Epoch 0 stays at 0: its ranges
# are bitwise identical to a non-exchanged fleet's. The stride bounds
# generations per range at 65536 — far above any real refill count.
GEN_STRIDE = 1 << 16

# The exchanged arrays, in canonical wire order (dtype-pinned so the
# checksum is computed over identical bytes on both ends). The
# ``entry``/``depth`` lineage lanes (obs/lineage.py) ride the wire
# verbatim — merged entries keep their origin-range identity, which is
# what lets the fleet-merged report attribute finds across ranges.
_WIRE = (("sched", np.int32), ("sig", np.uint32), ("score", np.int32),
         ("filled", np.bool_), ("entry", np.int32), ("depth", np.int32))


@dataclasses.dataclass(frozen=True)
class ExchangeConfig:
    """Knobs of the cross-range corpus exchange.

    ``every`` is the exchange cadence: ranges per epoch (``None`` →
    one epoch per worker-round, i.e. ``n_workers`` at ``fleet_sweep``
    time — epoch peers run in parallel, the barrier sits between
    rounds). A cadence >= the range count means a single epoch: every
    range seeds from the template and the exchange machinery is bitwise
    invisible (tested). ``state_path`` persists accepted snapshots for
    coordinator crash→resume; ``None`` with a fleet ``checkpoint_dir``
    defaults to ``<checkpoint_dir>/exchange_state.npz``.
    """

    every: Optional[int] = None
    state_path: Optional[str] = None

    def __post_init__(self):
        if self.every is not None and self.every < 1:
            raise ValueError("ExchangeConfig.every must be >= 1")


def corpus_payload(corpus: HostCorpus) -> Dict[str, Any]:
    """Serialize a corpus snapshot for the RPC wire: the four arrays
    (dtype-pinned) plus a sha256 over their canonical bytes — the
    torn-publish detector."""
    out: Dict[str, Any] = {}
    h = hashlib.sha256()
    for name, dt in _WIRE:
        arr = np.ascontiguousarray(np.asarray(getattr(corpus, name), dt))
        out[name] = arr
        h.update(arr.tobytes())
    out["sha256"] = h.hexdigest()
    return out


def payload_bytes(payload: Dict[str, Any]) -> int:
    """Wire size of a snapshot payload (telemetry)."""
    return int(sum(np.asarray(payload[name]).nbytes for name, _ in _WIRE))


class TornPayloadError(ValueError):
    """A corpus payload failed validation (missing/mis-shaped arrays or
    checksum mismatch): the transfer tore in flight. Recoverable — the
    receiver discards it and the sender re-sends."""


def payload_corpus(payload: Any, corpus_k: Optional[int] = None,
                   f_rows: Optional[int] = None) -> HostCorpus:
    """Validate + deserialize a snapshot payload; raises
    :class:`TornPayloadError` on any malformation, so a torn publish is
    discarded at the boundary instead of corrupting the merge fold."""
    if not isinstance(payload, dict):
        raise TornPayloadError(
            f"corpus payload must be a dict, got {type(payload).__name__}")
    arrs = {}
    h = hashlib.sha256()
    for name, dt in _WIRE:
        if name not in payload:
            raise TornPayloadError(f"corpus payload missing {name!r}")
        arr = np.ascontiguousarray(np.asarray(payload[name], dt))
        arrs[name] = arr
        h.update(arr.tobytes())
    sched, sig = arrs["sched"], arrs["sig"]
    if sched.ndim != 3 or sched.shape[-1] != 4:
        raise TornPayloadError(
            f"corpus sched must be (K, F, 4), got {sched.shape}")
    k = sched.shape[0]
    if corpus_k is not None and k != corpus_k:
        raise TornPayloadError(
            f"corpus payload holds {k} entries but SearchConfig.corpus "
            f"is {corpus_k} — all workers must run one SearchConfig")
    if f_rows is not None and sched.shape[1] != f_rows:
        raise TornPayloadError(
            f"corpus schedules carry {sched.shape[1]} rows but the fleet "
            f"template has {f_rows}")
    for name in ("sig", "score", "filled", "entry", "depth"):
        if arrs[name].shape != (k,):
            raise TornPayloadError(
                f"corpus {name} must be ({k},), got {arrs[name].shape}")
    if payload.get("sha256") != h.hexdigest():
        raise TornPayloadError(
            "corpus payload checksum mismatch (torn publish): "
            f"recorded {str(payload.get('sha256'))[:16]}..., recomputed "
            f"{h.hexdigest()[:16]}...")
    return HostCorpus(sched=sched, sig=sig, score=arrs["score"],
                      filled=arrs["filled"], entry=arrs["entry"],
                      depth=arrs["depth"])


def _snapshots_equal(a: HostCorpus, b: HostCorpus) -> List[str]:
    """Field names where two snapshots of the SAME range disagree
    bitwise (empty = interchangeable) — the dedupe crosscheck."""
    return [name for name, dt in _WIRE
            if not np.array_equal(np.asarray(getattr(a, name), dt),
                                  np.asarray(getattr(b, name), dt))]


class CorpusExchange:
    """Coordinator-side exchange state: published snapshots, the epoch
    barrier, and the merged-corpus chain.

    Pure host bookkeeping, deterministic by construction: its outputs
    (eligibility, seed corpora, merged epochs) depend only on WHICH
    ranges have published — never on order of arrival, duplicates, or
    the clock — which is what lets the chaos matrix hold bitwise.
    """

    def __init__(self, ranges: Sequence[SeedRange], every: int,
                 template: np.ndarray, corpus_k: int, min_novelty: int,
                 emit=None, clock=None, state_path: Optional[str] = None):
        if every < 1:
            raise ValueError("exchange cadence (every) must be >= 1")
        self.range_ids = sorted(r.range_id for r in ranges)
        if self.range_ids != list(range(len(self.range_ids))):
            raise ValueError("exchange needs the contiguous range ids of "
                             "split_ranges")
        self.every = int(every)
        self.n_ranges = len(self.range_ids)
        self.n_epochs = -(-self.n_ranges // self.every)
        self.template = np.asarray(template, np.int32)
        self.corpus_k = int(corpus_k)
        self.min_novelty = int(min_novelty)
        self.state_path = state_path
        self._emit = emit
        self._clock = clock
        self.base = host_corpus_init(self.corpus_k, self.template)
        self._published: Dict[int, HostCorpus] = {}
        self._merged: Dict[int, HostCorpus] = {}
        self.stats: Dict[str, int] = {
            "exchange_epochs": self.n_epochs,
            "publishes": 0,
            "publishes_duplicate": 0,
            "publishes_torn": 0,
            "epochs_merged": 0,
            "merge_inserts": 0,
            "broadcast_bytes": 0,
            "publish_bytes": 0,
        }

    # -- telemetry -------------------------------------------------------
    def emit(self, event: str, **fields) -> None:
        if self._emit is None:
            return
        rec = {"schema": EXCHANGE_SCHEMA, "event": event,
               "t": self._clock.now() if self._clock is not None else 0.0}
        rec.update(fields)
        self._emit(rec)

    # -- the epoch partition (pure functions of the range split) ---------
    def epoch_of(self, range_id: int) -> int:
        return range_id // self.every

    def gen0_of(self, range_id: int) -> int:
        """The sweep's ``search_gen0`` for this range: the epoch stream
        offset (:data:`GEN_STRIDE`), a pure function of the range id —
        a re-issued lease draws the identical streams."""
        return self.epoch_of(range_id) * GEN_STRIDE

    def epoch_ranges(self, epoch: int) -> List[int]:
        return [rid for rid in self.range_ids if self.epoch_of(rid) == epoch]

    def merged_through(self) -> int:
        """Number of consecutively merged epochs from 0 — the exchange
        frontier. Epoch ``e`` ranges are issueable iff ``e <= frontier``."""
        e = 0
        while e in self._merged:
            e += 1
        return e

    def eligible(self, range_id: int) -> bool:
        """May this range be leased yet? Its epoch's seed corpus must
        exist — i.e. every earlier epoch has merged. (The barrier that
        keys epoch boundaries to completed lease quanta.)"""
        return self.epoch_of(range_id) <= self.merged_through()

    def blocked_reason(self, range_id: int) -> Optional[str]:
        """Human diagnosis for a pending-but-ineligible range (the
        FleetStalledError detail)."""
        e = self.epoch_of(range_id)
        if e <= self.merged_through():
            return None
        waiting = [rid for rid in self.epoch_ranges(self.merged_through())
                   if rid not in self._published]
        return (f"blocked at exchange barrier: epoch {e} awaits the "
                f"merge of epoch {self.merged_through()} "
                f"(unpublished ranges: {waiting})")

    # -- seeding ---------------------------------------------------------
    def seed_corpus(self, range_id: int) -> Optional[HostCorpus]:
        """The corpus an epoch-``e`` range's sweep starts from: the
        merged epoch ``e-1`` corpus, or ``None`` for epoch 0 (the sweep
        falls back to its own template-seeded ``corpus_init`` — the
        exact non-exchanged behavior)."""
        e = self.epoch_of(range_id)
        if e == 0:
            return None
        merged = self._merged.get(e - 1)
        if merged is None:
            raise FleetIntegrityError(
                f"range {range_id} (epoch {e}) was leased before epoch "
                f"{e - 1} merged — the exchange barrier was bypassed")
        return merged

    def seed_payload(self, range_id: int, worker: str = "?"
                     ) -> Optional[Dict[str, Any]]:
        """Wire payload of :meth:`seed_corpus` (+ broadcast telemetry)."""
        corpus = self.seed_corpus(range_id)
        if corpus is None:
            return None
        payload = corpus_payload(corpus)
        n = payload_bytes(payload)
        self.stats["broadcast_bytes"] += n
        self.emit("broadcast", worker=worker, range_id=range_id,
                  epoch=self.epoch_of(range_id),
                  from_epoch=self.epoch_of(range_id) - 1, bytes=n)
        return payload

    # -- publish / dedupe / merge ----------------------------------------
    def has(self, range_id: int) -> bool:
        return range_id in self._published

    def publish(self, range_id: int, payload: Any,
                worker: str = "?") -> Dict[str, Any]:
        """Accept one range's corpus snapshot.

        Torn payloads (checksum/shape failures) are discarded with
        ``{"accepted": False, "torn": True}`` — the sender re-sends.
        Duplicates (same range published again — a restarted worker, a
        re-leased range's second holder, a retransmission) crosscheck
        bitwise against the accepted snapshot: equal → absorbed,
        different → :class:`FleetIntegrityError` (the determinism
        contract is broken; never silently pick one).
        """
        if range_id not in set(self.range_ids):
            raise FleetIntegrityError(
                f"publish for unknown range {range_id} "
                f"(fleet has ranges {self.range_ids[:4]}...)")
        try:
            corpus = payload_corpus(payload, corpus_k=self.corpus_k,
                                    f_rows=self.template.shape[0])
        except TornPayloadError as exc:
            self.stats["publishes_torn"] += 1
            self.emit("publish_torn", worker=worker, range_id=range_id,
                      epoch=self.epoch_of(range_id), error=str(exc))
            return {"accepted": False, "torn": True, "error": str(exc)}
        if range_id in self._published:
            bad = _snapshots_equal(self._published[range_id], corpus)
            if bad:
                raise FleetIntegrityError(
                    f"duplicate corpus publish for range {range_id} "
                    f"(epoch {self.epoch_of(range_id)}) disagrees with "
                    f"the accepted snapshot on: {', '.join(bad)} — two "
                    "executions of one range must be bitwise identical; "
                    "this fleet is mixing engine/search versions or "
                    "running nondeterministic code")
            self.stats["publishes_duplicate"] += 1
            self.emit("publish", worker=worker, range_id=range_id,
                      epoch=self.epoch_of(range_id), duplicate=True,
                      bytes=payload_bytes(payload))
            return {"accepted": True, "torn": False, "duplicate": True}
        self._published[range_id] = corpus
        self.stats["publishes"] += 1
        self.stats["publish_bytes"] += payload_bytes(payload)
        self.emit("publish", worker=worker, range_id=range_id,
                  epoch=self.epoch_of(range_id), duplicate=False,
                  bytes=payload_bytes(payload),
                  corpus_size=int(np.asarray(corpus.filled).sum()))
        self._try_merge()
        if self.state_path is not None:
            self._save(self.state_path)
        return {"accepted": True, "torn": False, "duplicate": False}

    def _try_merge(self) -> None:
        """Merge every epoch whose ranges have all published, in epoch
        order — a fold whose inputs (snapshots, order) are independent
        of scheduling, so the chain is reproducible from the published
        set alone."""
        e = self.merged_through()
        while e < self.n_epochs:
            rids = self.epoch_ranges(e)
            if not all(rid in self._published for rid in rids):
                return
            acc = self.base if e == 0 else self._merged[e - 1]
            inserts = 0
            for rid in rids:                 # range-id order: the contract
                acc, n = merge_corpus(acc, self._published[rid],
                                      self.min_novelty)
                inserts += n
            self._merged[e] = acc
            self.stats["epochs_merged"] += 1
            self.stats["merge_inserts"] += inserts
            self.emit("merge", epoch=e, ranges_merged=len(rids),
                      corpus_inserted=inserts,
                      corpus_size=int(np.asarray(acc.filled).sum()),
                      corpus_gen=e + 1,
                      epochs_merged=self.stats["epochs_merged"])
            e += 1

    def merged_epoch(self, epoch: int) -> HostCorpus:
        if epoch not in self._merged:
            raise FleetIntegrityError(
                f"exchange epoch {epoch} has not merged "
                f"(frontier: {self.merged_through()})")
        return self._merged[epoch]

    # -- the fleet-level search report -----------------------------------
    def fleet_report(self, n_seeds: int, ranges: Sequence[SeedRange],
                     parts: Dict[int, Any]):
        """Assemble the merged ``SweepResult.search``: the final merged
        corpus (the last epoch's fold) plus the per-seed materialized
        schedules — and the per-seed lineage lanes + summed operator
        outcome table (obs/lineage.py) — scattered from the per-range
        reports. Each range wrote its lineage entry ids at base
        ``range.lo``, so the concatenated per-seed arrays resolve
        cross-range ancestry at ``entry_base=0`` with plain arithmetic.
        """
        from ..obs.lineage import SearchLineage, merge_operator_stats
        from ..search import SearchReport

        final = self.merged_epoch(self.n_epochs - 1)
        f = self.template.shape[0]
        sched = np.full((n_seeds, f, 4), -1, np.int32)
        sched[:, :, 1:] = 0                  # canonical DISABLED_ROW pad
        lin_arrays = {
            "parent1": np.full((n_seeds,), -1, np.int32),
            "parent2": np.full((n_seeds,), -1, np.int32),
            "ops": np.zeros((n_seeds,), np.int32),
            "depth": np.zeros((n_seeds,), np.int32),
        }
        op_parts = []
        lineage_all = True
        generations = inserted = 0
        for r in sorted(ranges, key=lambda r: r.range_id):
            rep = getattr(parts[r.range_id], "search", None)
            if rep is None:
                raise FleetIntegrityError(
                    f"range {r.range_id} completed without a search "
                    "report under an exchanged fleet — all workers must "
                    "run search=")
            sched[r.lo:r.hi] = np.asarray(rep.schedules,
                                          np.int32)[:r.n_seeds]
            generations += int(rep.generations)
            inserted += int(rep.inserted)
            lin = getattr(rep, "lineage", None)
            if lin is None:
                lineage_all = False
            else:
                for name in lin_arrays:
                    lin_arrays[name][r.lo:r.hi] = np.asarray(
                        getattr(lin, name), np.int32)[:r.n_seeds]
                op_parts.append(rep.operator_stats or {})
        filled = np.asarray(final.filled, bool)
        lineage = (SearchLineage(entry_base=0, **lin_arrays)
                   if lineage_all else None)
        return SearchReport(
            generations=generations, inserted=inserted,
            corpus_size=int(filled.sum()), corpus_capacity=int(self.corpus_k),
            corpus_sched=np.asarray(final.sched, np.int32),
            corpus_sig=np.asarray(final.sig, np.uint32),
            corpus_score=np.asarray(final.score, np.int32),
            corpus_filled=filled, schedules=sched,
            corpus_entry=np.asarray(final.entry, np.int32),
            corpus_depth=np.asarray(final.depth, np.int32),
            lineage=lineage,
            operator_stats=(merge_operator_stats(op_parts)
                            if lineage_all and op_parts else None))

    # -- persistence (the coordinator's crash→resume aux channel) --------
    def _save(self, path: str) -> None:
        """Persist accepted snapshots atomically (tmp + fsync + rename,
        the engine/checkpoint.py discipline). Merged epochs are NOT
        stored: the merge is a deterministic fold of the stored inputs,
        so a resume re-derives them bit-exactly — persistence of the
        inputs IS persistence of the outputs."""
        arrays: Dict[str, np.ndarray] = {
            "meta": np.array([self.n_ranges, self.every, self.corpus_k,
                              self.min_novelty], np.int64),
            "template": self.template,
            "published": np.array(sorted(self._published), np.int64),
        }
        for rid, c in self._published.items():
            for name, dt in _WIRE:
                arrays[f"r{rid}_{name}"] = np.asarray(getattr(c, name), dt)
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".exchange.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def resume(self, path: str) -> int:
        """Reload accepted snapshots from :meth:`_save` output and
        re-derive the merged-epoch chain. Returns the number of
        snapshots restored. A mismatched fleet shape (different range
        count, cadence, corpus size, novelty bar, or template) raises
        :class:`FleetIntegrityError` — resuming an exchange under a
        different partition would seed ranges with corpora they never
        would have seen."""
        with np.load(path, allow_pickle=False) as z:
            meta = np.asarray(z["meta"], np.int64)
            want = np.array([self.n_ranges, self.every, self.corpus_k,
                             self.min_novelty], np.int64)
            if meta.shape != want.shape or not np.array_equal(meta, want):
                raise FleetIntegrityError(
                    f"exchange state {path!r} was written by a different "
                    f"fleet shape (n_ranges/every/corpus/min_novelty "
                    f"{meta.tolist()} vs {want.tolist()}): results are "
                    "deterministic per partitioning + cadence — resume "
                    "with the original settings or delete the state file")
            if not np.array_equal(np.asarray(z["template"], np.int32),
                                  self.template):
                raise FleetIntegrityError(
                    f"exchange state {path!r} holds a different fault "
                    "template — this state belongs to another hunt")
            for rid in np.asarray(z["published"], np.int64).tolist():
                self._published[int(rid)] = HostCorpus(
                    **{name: np.asarray(z[f"r{rid}_{name}"], dt)
                       for name, dt in _WIRE})
        restored = len(self._published)
        self._try_merge()
        self.emit("resume", snapshots=restored,
                  epochs_merged=self.merged_through())
        return restored


__all__ = [
    "EXCHANGE_SCHEMA", "GEN_STRIDE", "CorpusExchange", "ExchangeConfig",
    "TornPayloadError", "corpus_payload", "payload_bytes",
    "payload_corpus",
]
