"""Array-level ops shared by host and device engines (threefry RNG, event
queues, pallas kernels)."""
