"""Counter-based RNG core: Threefry-2x32, 20 rounds (Random123).

This replaces the reference's stateful SmallRng (`madsim/src/sim/rand.rs:63-108`)
with a *counter-based* generator addressed by ``(key, counter)``. Counter-based
is the design decision that makes the batched TPU backend possible: every random
decision in a simulation is a pure function of ``(seed, stream, draw_index)``,
so the host engine (numpy, one seed at a time) and the device engine (JAX,
thousands of seeds vmapped) draw bit-identical values with no shared mutable
state and no draw-order dependence.

Two implementations with bit-exact agreement (tested against each other and
against Random123 known-answer vectors):

- :func:`threefry2x32_np` — numpy uint32, used by the host runtime's GlobalRng.
- :func:`threefry2x32_jax` — jax uint32, traced into the device engine step.
"""
from __future__ import annotations

import numpy as np

_M32 = np.uint32(0xFFFFFFFF)
# Threefry-2x32 rotation constants (Random123).
_ROTATIONS = (13, 15, 26, 6, 17, 29, 16, 24)
_PARITY = 0x1BD11BDA


def _rotl_np(x: np.ndarray, r: int) -> np.ndarray:
    r = np.uint32(r)
    return (x << r) | (x >> np.uint32(32 - int(r)))


def threefry2x32_np(k0, k1, c0, c1):
    """Threefry-2x32 (20 rounds) on numpy uint32 arrays or scalars.

    Returns a pair of uint32 arrays with the same shape as the inputs.
    """
    with np.errstate(over="ignore"):
        k0 = np.asarray(k0, dtype=np.uint32)
        k1 = np.asarray(k1, dtype=np.uint32)
        x0 = np.asarray(c0, dtype=np.uint32) + k0
        x1 = np.asarray(c1, dtype=np.uint32) + k1
        ks2 = k0 ^ k1 ^ np.uint32(_PARITY)
        ks = (k0, k1, ks2)
        for i in range(5):
            for r in range(4):
                x0 = x0 + x1
                x1 = _rotl_np(x1, _ROTATIONS[4 * (i % 2) + r])
                x1 = x1 ^ x0
            x0 = x0 + ks[(i + 1) % 3]
            x1 = x1 + ks[(i + 2) % 3] + np.uint32(i + 1)
        return x0, x1


def threefry2x32_jax(k0, k1, c0, c1):
    """Threefry-2x32 (20 rounds) on jax uint32 arrays. Bit-exact vs numpy."""
    import jax.numpy as jnp

    k0 = jnp.asarray(k0, dtype=jnp.uint32)
    k1 = jnp.asarray(k1, dtype=jnp.uint32)
    x0 = jnp.asarray(c0, dtype=jnp.uint32) + k0
    x1 = jnp.asarray(c1, dtype=jnp.uint32) + k1
    ks2 = k0 ^ k1 ^ jnp.uint32(_PARITY)
    ks = (k0, k1, ks2)

    def rotl(x, r):
        return (x << r) | (x >> (32 - r))

    for i in range(5):
        for r in range(4):
            x0 = x0 + x1
            x1 = rotl(x1, _ROTATIONS[4 * (i % 2) + r])
            x1 = x1 ^ x0
        x0 = x0 + ks[(i + 1) % 3]
        x1 = x1 + ks[(i + 2) % 3] + jnp.uint32(i + 1)
    return x0, x1


# ---------------------------------------------------------------------------
# Stream derivation.
#
# A simulation seed (u64) is split into a 2x32 key. Named streams (scheduler,
# network, time-base, user, per-purpose device streams) are derived by
# encrypting the stream id under the seed key, giving independent counter
# spaces per purpose. Draw i of stream s under seed k is
#   threefry(derive(k, s), (lo(i), hi(i)))
# — a pure function, identical on host and device.
# ---------------------------------------------------------------------------

_M = 0xFFFFFFFF


def threefry2x32_scalar(k0: int, k1: int, c0: int, c1: int):
    """Threefry-2x32 (20 rounds) on plain Python ints — bit-exact with the
    numpy/jax versions, and much faster than numpy for one block at a time
    (the host engine's draw pattern). The C++ native core (native/
    madsim_core.cpp) supersedes this when built."""
    x0 = (c0 + k0) & _M
    x1 = (c1 + k1) & _M
    ks = (k0, k1, k0 ^ k1 ^ _PARITY)
    for i in range(5):
        for r in range(4):
            x0 = (x0 + x1) & _M
            rot = _ROTATIONS[4 * (i % 2) + r]
            x1 = ((x1 << rot) & _M) | (x1 >> (32 - rot))
            x1 ^= x0
        x0 = (x0 + ks[(i + 1) % 3]) & _M
        x1 = (x1 + ks[(i + 2) % 3] + i + 1) & _M
    return x0, x1


def seed_to_key(seed: int):
    """Split a u64 seed into a (k0, k1) uint32 pair."""
    seed &= (1 << 64) - 1
    return np.uint32(seed & 0xFFFFFFFF), np.uint32(seed >> 32)


def derive_stream_np(k0, k1, stream: int):
    """Derive an independent (k0, k1) key for a named stream id (u64)."""
    stream &= (1 << 64) - 1
    return threefry2x32_np(k0, k1, np.uint32(stream & 0xFFFFFFFF), np.uint32(stream >> 32))


def derive_stream_jax(k0, k1, stream):
    """JAX version of :func:`derive_stream_np` (stream may be a traced u32 pair)."""
    import jax.numpy as jnp

    stream_lo = jnp.asarray(stream, dtype=jnp.uint32)
    return threefry2x32_jax(k0, k1, stream_lo, jnp.zeros_like(stream_lo))


def draw_np(k0, k1, counter: int):
    """Draw block `counter` (u64) of the stream keyed by (k0, k1) → 2 uint32."""
    counter &= (1 << 64) - 1
    return threefry2x32_np(k0, k1, np.uint32(counter & 0xFFFFFFFF), np.uint32(counter >> 32))
