"""obs CLI: ``python -m madsim_tpu.obs replay|watch ...``.

``replay`` re-runs a failing seed and exports its timeline — the device
analog of re-running a reference test with ``MADSIM_TEST_SEED`` pinned
and ``MADSIM_LOG`` on, except the whole recipe can ride in a repro
bundle. ``watch`` tails or summarizes a live sweep telemetry stream
(``sweep(observe="tele.jsonl")``, obs/observatory.py), optionally
refreshing a Prometheus text snapshot:

    python -m madsim_tpu.obs watch /tmp/tele.jsonl            # summary
    python -m madsim_tpu.obs watch /tmp/tele.jsonl --follow \\
        --prom /var/lib/node_exporter/madsim.prom

Replay usage:

    # a seed from SweepResult.failing_seeds, explicit config
    python -m madsim_tpu.obs replay --seed 17234 --actor raft \\
        --actor-config '{"n": 3, "buggy_double_vote": true}' \\
        --out trace.json

    # a bundle written by a failing sweep/@test (obs/bundle.py)
    python -m madsim_tpu.obs replay --bundle repro.json --out trace.json

Device bundles re-trace the seed through the same actor/config/schedule
and write Chrome trace-event JSON (``--format text`` for a terminal
rendering); host-test bundles re-import the recorded test entry point
and re-run it under the bundle's pinned ``MADSIM_TEST_*`` environment.
Exit codes: 0 = replay ran (and reproduced the recorded failure, when
one was recorded), 1 = a recorded failure did NOT reproduce, 2 = usage.
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional

from .bundle import load_bundle
from .timeline import dump_chrome, render_text, trace_to_chrome


def _actor_registry() -> Dict[str, tuple]:
    # One shared family table (engine/families.py): the replay CLI,
    # triage's bundle naming, and the all-families conformance test all
    # read the same registry, so a new family — hand-written or
    # actorc-compiled — registers once and replays/triages/validates
    # everywhere.
    from ..engine.families import actor_families

    return {name: (fam.actor_cls, fam.config_cls)
            for name, fam in actor_families().items()}


def _replay_device(seed: int, actor_name: str, actor_config: Dict[str, Any],
                   engine_config: Dict[str, Any], faults,
                   max_steps: int, out: Optional[str], fmt: str,
                   expect_bug: Optional[bool]) -> int:
    import numpy as np

    from ..engine import DeviceEngine, EngineConfig

    registry = _actor_registry()
    if actor_name not in registry:
        print(f"obs replay: unknown actor {actor_name!r} "
              f"(known: {sorted(registry)})", file=sys.stderr)
        return 2
    actor_cls, acfg_cls = registry[actor_name]
    acfg = acfg_cls(**(actor_config or {}))
    actor = actor_cls(acfg)
    cfg = EngineConfig(**(engine_config or {"n_nodes": acfg.n}))
    frows = None if faults is None else np.asarray(faults, np.int32)
    eng = DeviceEngine(actor, cfg)
    trace = eng.trace(int(seed), max_steps=max_steps, faults=frows)
    bug_seen = any(e.get("bug_raised") for e in trace)
    if fmt == "text":
        text = render_text(trace)
        if out:
            with open(out, "w", encoding="utf-8") as f:
                f.write(text + "\n")
        else:
            print(text)
    else:
        doc = trace_to_chrome(trace, seed=int(seed),
                              label=f"{actor_name} seed {seed}")
        if out:
            dump_chrome(doc, out)
        else:
            json.dump(doc, sys.stdout, indent=1)
            print()
    print(f"obs replay: seed {seed} ({actor_name}): {len(trace)} events, "
          f"invariant {'RAISED' if bug_seen else 'held'}"
          + (f", wrote {out}" if out else ""), file=sys.stderr)
    if expect_bug and not bug_seen:
        print("obs replay: bundle recorded a failure but the invariant "
              "held on replay — config/schedule drift?", file=sys.stderr)
        return 1
    return 0


def _crosscheck_blackbox(bundle: Dict[str, Any]) -> int:
    """``replay --crosscheck``: verify the bundle's recorded flight-
    recorder ring is BITWISE the suffix of a freshly replayed
    ``trace()`` (obs/blackbox.py ``ring_matches_trace``).

    The ``madsim.blackbox/1`` block is self-contained: it carries the
    schedule rows the ring was RECORDED under and the world's final
    step count, so the crosscheck replays exactly the recorded window —
    independent of the bundle's top-level (possibly minimized) schedule.
    Determinism makes this a free cross-execution check, the fleet-merge
    crosscheck's single-world analog. Exit 0 = bitwise match, 1 =
    ring/replay divergence, 2 = no block / unknown actor.
    """
    import numpy as np

    from ..engine import DeviceEngine, EngineConfig
    from .blackbox import SCHEMA, ring_matches_trace

    block = (bundle.get("extra") or {}).get("blackbox")
    if not block:
        print("obs replay: --crosscheck needs a bundle carrying a "
              f"{SCHEMA} block (written by a blackbox-on sweep/triage "
              "— EngineConfig(blackbox=K))", file=sys.stderr)
        return 2
    if block.get("schema") != SCHEMA:
        print(f"obs replay: unknown blackbox block schema "
              f"{block.get('schema')!r} (this build reads {SCHEMA})",
              file=sys.stderr)
        return 2
    registry = _actor_registry()
    actor_name = bundle.get("actor")
    if actor_name not in registry:
        print(f"obs replay: unknown actor {actor_name!r} "
              f"(known: {sorted(registry)})", file=sys.stderr)
        return 2
    actor_cls, acfg_cls = registry[actor_name]
    actor = actor_cls(acfg_cls(**(bundle.get("actor_config") or {})))
    acfg_n = getattr(actor, "n", None)
    cfg = EngineConfig(**(bundle.get("engine_config")
                          or {"n_nodes": acfg_n}))
    frows = block.get("faults")
    frows = None if frows is None else np.asarray(frows, np.int32)
    eng = DeviceEngine(actor, cfg)
    steps = int(block.get("steps") or bundle.get("max_steps", 2_000))
    trace = eng.trace(int(block["seed"]), max_steps=steps, faults=frows)
    err = ring_matches_trace(block.get("events") or [], trace,
                             total=block.get("n_total"))
    if err:
        print(f"obs replay --crosscheck: RING/REPLAY DIVERGENCE: {err}",
              file=sys.stderr)
        return 1
    print(f"obs replay --crosscheck: seed {block['seed']}: recorded ring "
          f"({block.get('n_records')} events, K={block.get('k')}) is "
          f"bitwise the suffix of the replayed trace ({len(trace)} "
          "events)", file=sys.stderr)
    return 0


def _load_test_module(mod_name: str, test_file: Optional[str]):
    """Import the bundle's test module by name, falling back to loading
    its recorded source file — a test defined in a directly-run script
    records module ``__main__``, which only the file path can resolve."""
    if mod_name != "__main__":
        try:
            return importlib.import_module(mod_name)
        except ImportError:
            if not test_file:
                raise
    if not test_file:
        raise ImportError(
            f"bundle test module {mod_name!r} is not importable and no "
            "test_file was recorded")
    spec = importlib.util.spec_from_file_location("_madsim_repro_target",
                                                  test_file)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _replay_host_test(bundle: Dict[str, Any]) -> int:
    test_id = bundle.get("test")
    if not test_id or ":" not in test_id:
        print("obs replay: host_test bundle has no importable test id "
              f"({test_id!r})", file=sys.stderr)
        return 2
    mod_name, qualname = test_id.split(":", 1)
    # The bundle's env block IS the repro recipe — apply it verbatim
    # (this process exists only to replay; no restore needed).
    for k, v in (bundle.get("env") or {}).items():
        os.environ[k] = str(v)
    mod = _load_test_module(mod_name, bundle.get("test_file"))
    fn = mod
    for part in qualname.split("."):
        fn = getattr(fn, part)
    recorded = bundle.get("error")
    try:
        fn()
    except BaseException as exc:  # noqa: BLE001 — the failure is the point
        got = f"{type(exc).__name__}: {exc}"
        if recorded is None or got.split(":")[0] == recorded.split(":")[0]:
            print(f"obs replay: reproduced {got!r} "
                  f"(bundle recorded {recorded!r})", file=sys.stderr)
            return 0
        print(f"obs replay: raised {got!r} but the bundle recorded "
              f"{recorded!r}", file=sys.stderr)
        return 1
    if recorded is None:
        print("obs replay: test passed (no error was recorded)",
              file=sys.stderr)
        return 0
    print(f"obs replay: test PASSED but the bundle recorded {recorded!r} "
          "— failure did not reproduce", file=sys.stderr)
    return 1


def _lineage_cmd(path: str, out=None) -> int:
    """``obs lineage <bundle|telemetry.jsonl>``: render a guided find's
    ancestry tree + the hunt's per-operator outcome table
    (obs/lineage.py; schema ``madsim.search.lineage/1``). Accepts a
    repro bundle carrying a ``lineage`` block (triage/corpus.py) or a
    sweep telemetry JSONL whose summary record carries ``search.finds``.
    Exit 0 = rendered, 2 = the file holds no lineage."""
    from .lineage import render_operator_table, render_tree

    out = out or sys.stdout
    if not os.path.exists(path):
        print(f"obs lineage: no such file: {path}", file=sys.stderr)
        return 2
    blocks: List[Dict[str, Any]] = []
    stats = None
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        # A repro bundle (obs/bundle.py) with a lineage block.
        block = doc.get("lineage")
        if block:
            blocks = [block]
            stats = block.get("operator_stats")
    else:
        # A telemetry JSONL stream: the sweep summary record carries
        # search.finds (+ operator_stats inside each block).
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            srch = rec.get("search") if isinstance(rec, dict) else None
            if isinstance(srch, dict) and srch.get("finds"):
                blocks = list(srch["finds"])
                stats = (blocks[0].get("operator_stats")
                         or srch.get("operator_stats"))
    if not blocks:
        print(f"obs lineage: no lineage block in {path} — is it a "
              "guided-hunt bundle (triage over a search= sweep with "
              "SearchConfig(lineage=True)) or its telemetry stream?",
              file=sys.stderr)
        return 2
    for block in blocks:
        print(f"find: seed {block.get('seed')} (depth "
              f"{block.get('depth')}, operators: "
              f"{', '.join(block.get('operators_applied') or []) or 'none'})",
              file=out)
        print(render_tree(block.get("chain") or []), file=out)
        if stats is None:
            stats = block.get("operator_stats")
    if stats:
        print(render_operator_table(stats), file=out)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="madsim_tpu.obs",
        description="observability tools: replay failing seeds, export "
                    "timelines (docs/observability.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("replay", help="replay a seed / repro bundle and "
                                       "export its timeline")
    rp.add_argument("--bundle", help="repro bundle JSON (obs/bundle.py)")
    rp.add_argument("--seed", type=int, help="seed to replay (without a "
                                             "bundle)")
    rp.add_argument("--actor", help="actor family: raft | pb | tpc")
    rp.add_argument("--actor-config", default=None,
                    help="JSON dict of actor-config overrides")
    rp.add_argument("--engine-config", default=None,
                    help="JSON dict of EngineConfig fields (n_nodes, ...)")
    rp.add_argument("--faults", default=None,
                    help="JSON (F, 4) fault rows [time_us, op, a, b]")
    rp.add_argument("--max-steps", type=int, default=None)
    rp.add_argument("--out", default=None, help="output file (default: "
                                                "stdout)")
    rp.add_argument("--format", choices=("chrome", "text"), default="chrome")
    rp.add_argument("--crosscheck", action="store_true",
                    help="after the replay, verify the bundle's recorded "
                         "flight-recorder ring (madsim.blackbox/1 block) "
                         "is bitwise the suffix of the replayed trace")
    wp = sub.add_parser("watch", help="tail/summarize a sweep telemetry "
                                      "JSONL stream (sweep(observe=...))")
    wp.add_argument("file", help="telemetry JSONL written by "
                                 "sweep(observe=<path>)")
    wp.add_argument("--follow", action="store_true",
                    help="tail the stream until its summary record lands")
    wp.add_argument("--interval", type=float, default=1.0,
                    help="poll interval in seconds (--follow)")
    wp.add_argument("--prom", default=None,
                    help="also write a Prometheus text snapshot of the "
                         "latest record to this path (atomic rewrite)")
    lp = sub.add_parser("lineage", help="render a guided find's ancestry "
                                        "tree + operator outcome table "
                                        "(docs/search.md)")
    lp.add_argument("file", help="repro bundle with a lineage block, or "
                                 "a sweep telemetry JSONL")
    args = ap.parse_args(argv)

    if args.cmd == "lineage":
        return _lineage_cmd(args.file)
    if args.cmd == "watch":
        from .observatory import watch

        return watch(args.file, follow=args.follow, prom=args.prom,
                     interval=args.interval)
    if args.bundle:
        bundle = load_bundle(args.bundle)
        if bundle["kind"] == "host_test":
            if args.crosscheck:
                print("obs replay: --crosscheck applies to device_sweep "
                      "bundles (host tests carry no flight recorder)",
                      file=sys.stderr)
                return 2
            return _replay_host_test(bundle)
        rc = _replay_device(
            seed=bundle["seed"], actor_name=bundle["actor"],
            actor_config=bundle.get("actor_config") or {},
            engine_config=bundle.get("engine_config") or {},
            faults=bundle.get("faults"),
            max_steps=args.max_steps or int(bundle.get("max_steps", 2_000)),
            out=args.out, fmt=args.format,
            expect_bug=bundle.get("error") is not None)
        if rc != 0 or not args.crosscheck:
            return rc
        return _crosscheck_blackbox(bundle)
    if args.crosscheck:
        ap.error("--crosscheck needs --bundle (the recorded ring rides "
                 "the bundle's madsim.blackbox/1 block)")
    if args.seed is None or not args.actor:
        ap.error("replay needs --bundle, or --seed and --actor")
    return _replay_device(
        seed=args.seed, actor_name=args.actor,
        actor_config=json.loads(args.actor_config) if args.actor_config
        else {},
        engine_config=json.loads(args.engine_config) if args.engine_config
        else None,
        faults=json.loads(args.faults) if args.faults else None,
        max_steps=args.max_steps or 2_000, out=args.out, fmt=args.format,
        expect_bug=None)
