"""``python -m madsim_tpu.obs`` — the observability CLI (obs/cli.py)."""
import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
