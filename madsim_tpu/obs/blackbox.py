"""The flight recorder: a per-world ring of the last K step events.

A hunt at W=2048 worlds surfaces counters, coverage signatures and fault
schedules — but the actual event sequence of a failing world was only
reconstructable by a separate single-world host replay through
``DeviceEngine.trace()``. ``BlackboxRing`` closes that gap in situ
(PRISM's point, PAPERS.md): with ``EngineConfig(blackbox=K)`` every
world carries a ring buffer of its last K *recorded* step events inside
``WorldState.blackbox``, written by the core step program and riding the
existing retirement machinery — permuted by the compactor, selected by
the refill, checkpointed with the state, and pulled ONLY on the sweep's
existing retirement fetch and final pull (zero new mid-loop syncs,
counted by the ``_fetch`` seam in tests/test_fused.py).

The ring records exactly the steps ``trace()`` records — valid
processed events (``found & active & in_time``, including popped-and-
dropped stale/dead events and fault injections) plus the ``invariant``
marker for a bug that rises on a step that processed no event. Because
both live worlds and the trace scan freeze/skip identically, the
recorded step indices of one world are **consecutive from step 0**, so
``pos`` (total records written) alone reconstructs every absolute step
index and the decoded ring is — by determinism — bitwise the suffix of
a fresh ``trace()`` of the same seed/schedule. ``ring_matches_trace``
is that crosscheck (the ``obs replay --crosscheck`` CLI leg and the
fleet-merge-style free cross-execution check).

Packing (engine/lanes.py, the PR 10 discipline): kind/src/dst/flags
ride the i8 code lane, the wrapped step index rides the i16 slot lane,
and the full-width virtual time splits across two payload-lane words
(``lanes.split_wide`` — the net-config precedent), so K=64 costs
~644 B/world against the packed budget's slack (the ledgered
``engine.run_blackbox`` row in analysis/budgets.json).

Like obs/metrics.py, this module imports nothing from
:mod:`madsim_tpu.engine` (the engine imports *it*); the fault-op name
table lives here and ``trace()`` shares it, so ring decode and trace
decode cannot drift apart.
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

# Bundle-block schema id (docs/observability.md bundle schema table).
SCHEMA = "madsim.blackbox/1"

# Observation-dict prefix for ring fields (DeviceEngine.observe adds one
# ``bb_<field>`` entry per ring field when the recorder is on).
OBS_PREFIX = "bb_"

# Flag bits of the per-record ``flags`` lane. TIMER/FAULT mirror the
# queue's event flags; STALE/DEAD are the two popped-but-dropped causes
# (mutually exclusive, STALE wins — the step's own precedence); RAISE
# marks the step the bug flag first rose; MARKER marks the synthetic
# ``invariant`` entry for a raise on a step that processed no event.
BB_TIMER = 1
BB_FAULT = 2
BB_DROP_STALE = 4
BB_DROP_DEAD = 8
BB_RAISE = 16
BB_MARKER = 32

# Fault-op names, by op code (engine/core.py FAULT_KILL..FAULT_RESUME).
# Shared by ``DeviceEngine.trace()`` and :func:`decode_ring` so the two
# decoders name events identically — the crosscheck depends on it.
FAULT_NAMES = {0: "kill", 1: "restart", 2: "clog_node", 3: "unclog_node",
               4: "clog_link", 5: "unclog_link", 6: "set_latency",
               7: "set_loss", 8: "pause", 9: "resume"}


class BlackboxRing(NamedTuple):
    """Per-world event ring (leading world axis when batched).

    ``pos`` is the total records ever written (int32); record ``r``
    lives at slot ``r % K``, so the ring holds records
    ``pos - min(pos, K) .. pos - 1`` and — because recorded steps are
    consecutive from 0 (module docstring) — record ``r`` IS step ``r``.
    All lanes are write-only within the step: nothing ever reads them
    for a simulation decision (the metrics bitwise-invisibility
    contract, tier-1-gated in tests/test_obs.py).
    """

    pos: jnp.ndarray       # int32 scalar — records written (ring cursor)
    step_lo: jnp.ndarray   # (K,) slot lane — step index, wrapped
    t_lo: jnp.ndarray      # (K,) payload lane — event t_us low half
    t_hi: jnp.ndarray      # (K,) payload lane — event t_us high half
    kind: jnp.ndarray      # (K,) code lane — event kind / fault op
    src: jnp.ndarray       # (K,) code lane — source node (-1 marker)
    dst: jnp.ndarray       # (K,) code lane — destination node (-1 marker)
    flags: jnp.ndarray     # (K,) code lane — BB_* bits

    @staticmethod
    def zeros(k: int, lanes) -> "BlackboxRing":
        """A fresh (single-world) ring of depth ``k`` on the config's
        lane dtypes (``lanes`` is an engine/lanes.py ``Lanes``)."""
        return BlackboxRing(
            pos=jnp.int32(0),
            step_lo=jnp.zeros((k,), lanes.slot),
            t_lo=jnp.zeros((k,), lanes.payload),
            t_hi=jnp.zeros((k,), lanes.payload),
            kind=jnp.zeros((k,), lanes.code),
            src=jnp.zeros((k,), lanes.code),
            dst=jnp.zeros((k,), lanes.code),
            flags=jnp.zeros((k,), lanes.code),
        )


RING_FIELDS = BlackboxRing._fields


def rings_from_observations(obs: Dict[str, np.ndarray]
                            ) -> Optional[Dict[str, np.ndarray]]:
    """Extract the per-seed ring arrays from an observation dict (the
    ``bb_``-prefixed entries ``DeviceEngine.observe`` adds), or ``None``
    when the sweep ran blackbox-off."""
    per_seed = {k[len(OBS_PREFIX):]: np.asarray(v)
                for k, v in obs.items() if k.startswith(OBS_PREFIX)}
    return per_seed or None


def ring_depth(obs: Dict[str, np.ndarray]) -> Optional[int]:
    """The recorder depth K of a sweep's observations, or ``None`` when
    it ran blackbox-off (summary/banner self-description)."""
    v = obs.get(OBS_PREFIX + "step_lo")
    return None if v is None else int(np.asarray(v).shape[-1])


def _join_t(lo: int, hi: int) -> int:
    """Reassemble the split virtual time (lanes.join_wide, on host)."""
    return int(np.int32((int(lo) & 0xFFFF) | (int(hi) << 16)))


def decode_ring(ring: Dict[str, np.ndarray], *,
                kind_names: Optional[List[str]] = None
                ) -> List[Dict[str, Any]]:
    """Decode ONE world's ring into trace-shaped event records.

    ``ring`` is a single seed's row of :func:`rings_from_observations`
    (scalar ``pos``, (K,) lanes). Entries mirror ``trace()``'s exactly
    — ``step``/``t_us``/``kind``/``timer``/``src``/``dst`` plus the
    optional ``dropped``/``bug_raised`` keys and the synthetic
    ``invariant`` marker — except ``payload`` (not recorded) and the
    extra ``drop_cause`` ("stale"/"dead") the trace does not carry;
    :func:`ring_matches_trace` projects both sides accordingly. Oldest
    record first. Raises ``ValueError`` when a record's wrapped step
    index contradicts its reconstructed absolute step — a torn ring,
    which determinism says cannot happen.
    """
    pos = int(np.asarray(ring["pos"]))
    step_lo = np.asarray(ring["step_lo"])
    k = int(step_lo.shape[-1])
    n = min(pos, k)
    t_lo, t_hi = np.asarray(ring["t_lo"]), np.asarray(ring["t_hi"])
    kind, flags = np.asarray(ring["kind"]), np.asarray(ring["flags"])
    src, dst = np.asarray(ring["src"]), np.asarray(ring["dst"])
    out: List[Dict[str, Any]] = []
    for j in range(n):
        step = pos - n + j          # record r IS step r (module docstring)
        idx = step % k
        expect = np.asarray(step).astype(step_lo.dtype)
        if int(step_lo[idx]) != int(expect):
            raise ValueError(
                f"blackbox ring is torn: slot {idx} records wrapped step "
                f"{int(step_lo[idx])} but reconstruction expects step "
                f"{step} (pos={pos}, k={k})")
        fl = int(flags[idx])
        t = _join_t(int(t_lo[idx]), int(t_hi[idx]))
        if fl & BB_MARKER:
            out.append({"step": step, "t_us": t, "kind": "invariant",
                        "timer": False, "src": -1, "dst": -1,
                        "bug_raised": True})
            continue
        kd = int(kind[idx])
        if fl & BB_FAULT:
            name = f"fault:{FAULT_NAMES.get(kd, kd)}"
        elif kind_names is not None and 0 <= kd < len(kind_names):
            name = kind_names[kd]
        else:
            name = str(kd)
        entry: Dict[str, Any] = {
            "step": step, "t_us": t, "kind": name,
            "timer": bool(fl & BB_TIMER),
            "src": int(src[idx]), "dst": int(dst[idx]),
        }
        if fl & (BB_DROP_STALE | BB_DROP_DEAD):
            entry["dropped"] = True
            entry["drop_cause"] = "stale" if fl & BB_DROP_STALE else "dead"
        if fl & BB_RAISE:
            entry["bug_raised"] = True
        out.append(entry)
    return out


def _project_trace(trace: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """``trace()`` entries → ring-comparable records: drop the payload
    (not recorded) and the host-only ``truncated`` end marker."""
    out = []
    for e in trace:
        if e.get("kind") == "truncated":
            continue
        out.append({k: v for k, v in e.items() if k != "payload"})
    return out


def ring_matches_trace(entries: List[Dict[str, Any]],
                       trace: List[Dict[str, Any]], *,
                       total: Optional[int] = None) -> Optional[str]:
    """Verify a decoded ring is BITWISE the suffix of a replayed trace.

    ``entries`` from :func:`decode_ring` (or a bundle's ``events``),
    ``trace`` from ``DeviceEngine.trace()`` of the same seed/schedule
    with ``max_steps`` covering the recorded run. ``total`` (the ring's
    ``pos``) additionally pins the replay's total recorded-event count —
    a ring that wrapped must still agree with the trace about how many
    events ever happened. Returns ``None`` on an exact match, else a
    human mismatch description (the crosscheck's failure message).
    """
    ref = _project_trace(trace)
    got = [{k: v for k, v in e.items() if k != "drop_cause"}
           for e in entries]
    if total is not None and len(ref) != int(total):
        return (f"replayed trace recorded {len(ref)} events but the ring "
                f"wrote {int(total)} in total — schedule/config drift?")
    if len(got) > len(ref):
        return (f"ring holds {len(got)} events but the replayed trace "
                f"has only {len(ref)}")
    tail = ref[len(ref) - len(got):] if got else []
    for i, (g, r) in enumerate(zip(got, tail)):
        if g != r:
            return (f"ring event {i} (step {g.get('step')}) diverges from "
                    f"the replayed trace: ring {g!r} != trace {r!r}")
    return None


def blackbox_block(entries: List[Dict[str, Any]], *, seed: int, k: int,
                   pos: int, steps: int,
                   faults: Optional[Any] = None) -> Dict[str, Any]:
    """The ``madsim.blackbox/1`` bundle block for one world's ring.

    Self-contained for the CLI crosscheck: ``faults`` are the rows the
    ring was RECORDED under (for a triaged class representative, the
    original hunt schedule — the minimized schedule rides the bundle's
    top level and replays separately) and ``steps`` is the world's final
    step counter, so ``trace(seed, max_steps=steps, faults=faults)``
    re-executes exactly the recorded window.
    """
    rows = None if faults is None \
        else np.asarray(faults, np.int32).tolist()
    return {
        "schema": SCHEMA,
        "seed": int(seed),
        "k": int(k),
        "n_records": len(entries),
        "n_total": int(pos),
        "steps": int(steps),
        "faults": rows,
        "events": entries,
    }
