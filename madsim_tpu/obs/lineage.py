# tracelint: hot-loop
"""The evolution observatory: device-resident search lineage + operator
outcome accounting (docs/search.md "Reading the lineage").

The guided search (search/, docs/search.md) evolves fault schedules on
device, but a ``SearchReport`` alone only says *what* was found — not
which parents and operators produced a find, or which mutation operators
are earning their keep. This module adds that accounting with the PR 5/6
house pattern: **write-only device lanes** carried beside the state,
folded inside the programs the sweep already dispatches, synced to the
host only on the cadence it already pays (retire pulls + the final
fetch), and bitwise invisible to the simulation itself
(``SearchConfig(lineage=False)`` compiles every lane out; lineage-on ≡
lineage-off is tier-1-gated).

Three pieces:

- **Provenance lanes** (:class:`LineageLanes`): every installed child
  carries its two splice-parent corpus **entry ids**, an
  applied-operator bitmask (one bit per operator class — the masks
  already computed inside ``mutate.make_children``, exposed rather than
  recomputed), and its ancestry depth. Entry ids are *globally unique by
  construction*: a corpus entry inserted from the world at seed position
  ``i`` gets entry id ``lin_base + i + 1`` (``0`` is the seeded
  template, ``-1`` means "no parent"), where ``lin_base`` is the
  sweep's seed-position base (a fleet range passes its ``lo``), so a
  fleet-merged report resolves parents across ranges with plain
  arithmetic.
- **The operator outcome table** (:class:`OperatorTable`): per operator
  bit, how many installed children carried it (``produced``), how many
  retiring carriers cleared the novelty bar (``novel``), survived into
  the corpus (``survived``), and found a bug (``bug``) — accumulated
  inside the jitted ``search.generate`` program, pulled with the retire
  ``_fetch`` the loop already pays. This is the measurement ROADMAP
  item 2 names as the prerequisite for AFL-style operator credit
  assignment.
- **Host-side reconstruction**: :func:`ancestry` chases parent entry
  ids through the per-seed lanes back to the generation-0 template;
  :func:`render_tree` prints the chain; :func:`lineage_block` packages
  a find's derivation as the ``madsim.search.lineage/1`` bundle block
  the triage bundles carry and ``python -m madsim_tpu.obs lineage``
  renders.

Dtype discipline (docs/perf.md "Roofline round 2"): the operator
bitmask lane is packed ``int8`` (5 bits used) and every read widens
through ``engine/lanes.widen`` — the one sanctioned narrow→wide site
(tracelint TRC005); entry ids and depths are unbounded counters and
stay wide ``int32`` per the :class:`~madsim_tpu.engine.lanes.Lanes`
category rules.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from ..engine.lanes import narrow, widen

# Operator classes, in bit order. Bit i of a child's ops mask is set iff
# operator i touched at least one of its rows (the masks are the
# intermediates of mutate.make_children, exposed — never recomputed).
OP_NAMES = ("splice", "disable", "time_jitter", "node_rotate", "op_flip")
N_OPS = len(OP_NAMES)
OP_SPLICE, OP_DISABLE, OP_TIME, OP_NODE, OP_FLIP = range(N_OPS)

# Outcome rows of the operator table, in array order.
OUTCOME_NAMES = ("produced", "novel", "survived", "bug")

# Entry-id vocabulary: TEMPLATE_ENTRY is the seeded template's corpus
# entry; NO_PARENT marks a generation-0 world (it IS the template run,
# not a mutation of it).
TEMPLATE_ENTRY = 0
NO_PARENT = -1

LINEAGE_SCHEMA = "madsim.search.lineage/1"
SEARCH_TELEMETRY_SCHEMA = "madsim.search.telemetry/1"


# ---------------------------------------------------------------------------
# Device lanes
# ---------------------------------------------------------------------------

class LineageLanes(NamedTuple):
    """Per-slot provenance lanes, carried beside ``slot_sched`` through
    the guided sweep (permuted/split by the same compactor dispatch,
    harvested at retire, refilled with each installed child's lanes).

    ``p1``/``p2`` are corpus ENTRY ids (the tournament winners the
    child was spliced from; ``p2`` is recorded even when no row spliced
    — the selection happened), ``ops`` the packed applied-operator
    bitmask, ``depth`` the ancestry depth (template = 0, child = 1 +
    max(parent depths)).
    """

    p1: jnp.ndarray     # (W,) i32 parent-1 corpus entry id (-1 = none)
    p2: jnp.ndarray     # (W,) i32 parent-2 (splice) corpus entry id
    ops: jnp.ndarray    # (W,) i8 packed operator bitmask (widen on read)
    depth: jnp.ndarray  # (W,) i32 ancestry depth (template = 0)


def lanes_origin(w: int) -> LineageLanes:
    """Generation-0 lanes: the initial batch runs the template itself —
    no parents, no operators, depth 0 (host arrays; the sweep shards
    them)."""
    return LineageLanes(
        p1=jnp.full((w,), NO_PARENT, jnp.int32),
        p2=jnp.full((w,), NO_PARENT, jnp.int32),
        ops=jnp.zeros((w,), jnp.int8),
        depth=jnp.zeros((w,), jnp.int32),
    )


def lanes_buffer(n_ids: int) -> LineageLanes:
    """Device-resident PER-SEED lane buffer for the fused sweep.

    One row per seed id plus one trailing dump row (index ``n_ids``)
    that masked in-loop scatters target — the same dump-row idiom as
    the coverage fold. Defaults equal :func:`lanes_origin`'s, so a seed
    the hunt never admitted (or whose slot died on a dry cursor) reads
    back exactly like a generation-0 template world — the value the
    host-side merge in parallel/sweep.py assigns in the unfused paths.
    """
    return lanes_origin(n_ids + 1)


def pack_ops(bits) -> jnp.ndarray:
    """Fold per-operator bool masks ``bits[i]`` (each ``(W,)``) into the
    packed i8 bitmask lane, through the sanctioned saturating
    ``lanes.narrow`` write boundary (values fit 5 bits)."""
    m = jnp.zeros(jnp.shape(bits[0]), jnp.int32)
    for i, b in enumerate(bits):
        m = m | (b.astype(jnp.int32) << i)
    return narrow(m, jnp.int8)


def ops_bits(ops: jnp.ndarray) -> jnp.ndarray:
    """Unpack the i8 ops lane to a ``(..., N_OPS)`` bool matrix — the
    ONE widen site of the lane (tracelint TRC005)."""
    wide = widen(ops)
    return (wide[..., None] >> jnp.arange(N_OPS, dtype=jnp.int32)) & 1 > 0


class OperatorTable(NamedTuple):
    """Per-operator outcome counters, device-resident (mesh-replicated
    like the coverage ledger). All rows i32 — counters stay wide.

    The fourth outcome (``bug``) is deliberately NOT a device row: a
    find can halt the sweep (``stop_on_first_bug``) or sit live at exit,
    in which case it never crosses a harvest edge — so bug credit is
    folded HOST-side from the per-seed lanes the final fetch already
    carries (:func:`host_credit` over ``obs['bug']``), which counts
    every find exactly once."""

    produced: jnp.ndarray   # (N_OPS,) children installed carrying the op
    novel: jnp.ndarray      # (N_OPS,) retiring carriers >= min_novelty
    survived: jnp.ndarray   # (N_OPS,) retiring carriers inserted


def table_zeros() -> OperatorTable:
    z = jnp.zeros((N_OPS,), jnp.int32)
    return OperatorTable(produced=z, novel=z, survived=z)


def credit(counter: jnp.ndarray, obits: jnp.ndarray,
           mask: jnp.ndarray) -> jnp.ndarray:
    """Add each masked world's operator bits into a per-op counter row:
    ``counter[o] += sum_w mask[w] & obits[w, o]`` (dtype-pinned — a bare
    sum would widen under the x64 flag, tracelint TRC003)."""
    add = jnp.sum(obits & mask[..., None], axis=0, dtype=jnp.int32)
    return counter + add


# ---------------------------------------------------------------------------
# Host twin of the outcome crediting (parity-gated, PR 9 FNV-twin style)
# ---------------------------------------------------------------------------

def host_ops_bits(ops: np.ndarray) -> np.ndarray:
    """Numpy twin of :func:`ops_bits` over an i8/i32 mask vector."""
    wide = np.asarray(ops, np.int32)
    return (wide[..., None] >> np.arange(N_OPS, dtype=np.int32)) & 1 > 0


def host_credit(counter: np.ndarray, ops: np.ndarray,
                mask: np.ndarray) -> np.ndarray:
    """Numpy twin of :func:`credit` — the fold the tier-1 parity test
    holds against the device accumulation."""
    obits = host_ops_bits(ops)
    add = np.sum(obits & np.asarray(mask, bool)[..., None], axis=0,
                 dtype=np.int32)
    return np.asarray(counter, np.int32) + add


# ---------------------------------------------------------------------------
# Host-side lineage reconstruction
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SearchLineage:
    """Per-seed provenance of one guided sweep
    (``SweepResult.search.lineage``).

    Arrays are indexed by seed POSITION (row ``i`` of the sweep's seed
    vector). ``entry_base`` is the sweep's entry-id base: the world at
    position ``i`` — if its schedule survived into the corpus — holds
    entry id ``entry_base + i + 1``, so ``resolve(e) = e - 1 -
    entry_base`` maps a parent entry id back to a seed position. A
    fleet-merged lineage concatenates ranges into global positions with
    ``entry_base = 0`` (each range wrote ids at base ``range.lo``), so
    cross-range ancestry resolves with the same arithmetic.
    """

    parent1: np.ndarray   # (n,) i32 corpus entry id (-1 = generation 0)
    parent2: np.ndarray   # (n,) i32 splice-parent entry id
    ops: np.ndarray       # (n,) i32 applied-operator bitmask
    depth: np.ndarray     # (n,) i32 ancestry depth (template = 0)
    entry_base: int = 0

    def resolve(self, entry: int) -> Optional[int]:
        """Seed position holding ``entry``, or None for the template /
        an entry outside this report (an exchange-seeded parent from
        another range, visible only in the fleet-merged report)."""
        if entry <= TEMPLATE_ENTRY:
            return None
        pos = int(entry) - 1 - self.entry_base
        return pos if 0 <= pos < self.parent1.shape[0] else None

    @property
    def max_depth(self) -> int:
        return int(self.depth.max()) if self.depth.size else 0

    def to_json(self) -> Dict[str, Any]:
        return {"n_seeds": int(self.parent1.shape[0]),
                "entry_base": int(self.entry_base),
                "max_depth": self.max_depth}


def op_names(mask: int) -> List[str]:
    """Operator names set in a packed bitmask, in bit order."""
    return [name for i, name in enumerate(OP_NAMES) if (int(mask) >> i) & 1]


def ancestry(lin: SearchLineage, pos: int,
             seeds: Optional[np.ndarray] = None,
             max_depth: int = 10_000) -> List[Dict[str, Any]]:
    """The ancestry chain of the world at seed position ``pos``: a list
    of nodes from the find itself back to the generation-0 template,
    following the primary (``parent1``) line and recording the splice
    parent of every hop.

    Each node: ``{"pos", "seed", "entry", "depth", "ops", "parent1",
    "parent2", "kind"}`` with ``kind`` one of ``"world"`` /
    ``"template"`` / ``"external"`` (an exchange-seeded parent whose
    origin range is outside this report). Chains are finite by
    construction — parents always retired strictly earlier — but
    ``max_depth`` bounds a corrupted report.
    """
    chain: List[Dict[str, Any]] = []
    cur: Optional[int] = int(pos)
    hops = 0
    while cur is not None and hops < max_depth:
        hops += 1
        e1, e2 = int(lin.parent1[cur]), int(lin.parent2[cur])
        chain.append({
            "pos": cur,
            "seed": int(np.asarray(seeds)[cur]) if seeds is not None
            else cur,
            "entry": int(lin.entry_base) + cur + 1,
            "depth": int(lin.depth[cur]),
            "ops": op_names(int(lin.ops[cur])),
            "parent1": e1,
            "parent2": e2,
            "kind": "world",
        })
        if e1 == NO_PARENT:
            # Generation 0: this world ran the template itself.
            chain.append({"entry": NO_PARENT, "kind": "template",
                          "depth": 0, "ops": [], "parent1": NO_PARENT,
                          "parent2": NO_PARENT})
            return chain
        if e1 == TEMPLATE_ENTRY:
            chain.append({"entry": TEMPLATE_ENTRY, "kind": "template",
                          "depth": 0, "ops": [], "parent1": NO_PARENT,
                          "parent2": NO_PARENT})
            return chain
        nxt = lin.resolve(e1)
        if nxt is None:
            chain.append({"entry": e1, "kind": "external", "depth": -1,
                          "ops": [], "parent1": NO_PARENT,
                          "parent2": NO_PARENT})
            return chain
        cur = nxt
    return chain


def render_tree(chain: List[Dict[str, Any]]) -> str:
    """Terminal rendering of an ancestry chain (find first, template
    last) — the ``obs lineage`` CLI body."""
    lines: List[str] = []
    for i, node in enumerate(chain):
        pad = "" if i == 0 else "  " * (i - 1) + "└─ "
        if node["kind"] == "template":
            lines.append(f"{pad}template (entry {TEMPLATE_ENTRY}, "
                         "generation 0)")
            continue
        if node["kind"] == "external":
            lines.append(f"{pad}external entry {node['entry']} "
                         "(exchange-seeded; resolve in the fleet-merged "
                         "report)")
            continue
        if node["parent1"] == NO_PARENT:
            # Generation-0 world: it RAN the template (no mutation).
            lines.append(f"{pad}seed {node['seed']} (entry "
                         f"{node['entry']}, depth 0) ran the template")
            continue
        ops = "+".join(node["ops"]) if node["ops"] else "no-op-copy"
        splice = (f"  [x entry {node['parent2']}]"
                  if "splice" in node["ops"] else "")
        lines.append(f"{pad}seed {node['seed']} (entry {node['entry']}, "
                     f"depth {node['depth']}) via {ops}{splice}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Operator stats (host dicts of the device OperatorTable)
# ---------------------------------------------------------------------------

def operator_stats(produced, novel, survived, bug) -> Dict[str, Dict[str, int]]:
    """Host dict of the pulled outcome table: one row per operator,
    ``{produced, novel, survived, bug, survival_pct}``."""
    out: Dict[str, Dict[str, int]] = {}
    produced = np.asarray(produced, np.int64)
    novel = np.asarray(novel, np.int64)
    survived = np.asarray(survived, np.int64)
    bug = np.asarray(bug, np.int64)
    for i, name in enumerate(OP_NAMES):
        p = int(produced[i])
        out[name] = {
            "produced": p,
            "novel": int(novel[i]),
            "survived": int(survived[i]),
            "bug": int(bug[i]),
            # Corpus-survival rate per installed carrier — the credit
            # signal a future operator scheduler would feed on.
            "survival_pct": round(100.0 * int(survived[i]) / p, 2)
            if p else 0.0,
        }
    return out


def merge_operator_stats(parts: List[Dict[str, Dict[str, int]]]
                         ) -> Dict[str, Dict[str, int]]:
    """Sum per-range operator tables into the fleet table (counts add;
    the rate recomputes)."""
    acc = {name: {k: 0 for k in OUTCOME_NAMES} for name in OP_NAMES}
    for part in parts:
        for name in OP_NAMES:
            row = part.get(name, {})
            for k in OUTCOME_NAMES:
                acc[name][k] += int(row.get(k, 0))
    for name in OP_NAMES:
        p = acc[name]["produced"]
        acc[name]["survival_pct"] = (round(
            100.0 * acc[name]["survived"] / p, 2) if p else 0.0)
    return acc


def top_operator(stats: Optional[Dict[str, Dict[str, int]]],
                 by: str = "survived") -> Optional[str]:
    """The operator with the highest ``by`` count (ties to bit order);
    None when the table is empty/absent or all-zero."""
    if not stats:
        return None
    best, best_v = None, 0
    for name in OP_NAMES:
        v = int(stats.get(name, {}).get(by, 0))
        if v > best_v:
            best, best_v = name, v
    return best


def render_operator_table(stats: Dict[str, Dict[str, int]]) -> str:
    """Fixed-width terminal table of the per-operator outcome counts."""
    head = (f"{'operator':<12} {'produced':>9} {'novel':>7} "
            f"{'survived':>9} {'bug':>5} {'surv%':>7}")
    lines = [head, "-" * len(head)]
    for name in OP_NAMES:
        row = stats.get(name, {})
        lines.append(
            f"{name:<12} {row.get('produced', 0):>9} "
            f"{row.get('novel', 0):>7} {row.get('survived', 0):>9} "
            f"{row.get('bug', 0):>5} {row.get('survival_pct', 0.0):>7}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The madsim.search.lineage/1 bundle block
# ---------------------------------------------------------------------------

def lineage_block(lin: SearchLineage, pos: int,
                  seeds: Optional[np.ndarray] = None,
                  stats: Optional[Dict[str, Dict[str, int]]] = None
                  ) -> Dict[str, Any]:
    """The provenance block a triage bundle carries for a guided find:
    the find's full ancestry chain plus the sweep's operator outcome
    table — a minimized repro that documents its own derivation
    (schema ``madsim.search.lineage/1``)."""
    chain = ancestry(lin, pos, seeds=seeds)
    applied = sorted({op for node in chain for op in node.get("ops", [])})
    return {
        "schema": LINEAGE_SCHEMA,
        "seed": chain[0].get("seed") if chain else None,
        "depth": chain[0].get("depth", 0) if chain else 0,
        "operators_applied": applied,
        "chain": chain,
        "operator_stats": stats,
    }
