"""Timeline export: device/host traces → Chrome trace-event JSON or text.

Converts the ordered event list of ``DeviceEngine.trace()`` (one dict per
processed step — see engine/core.py) and host ``Runtime`` poll traces
(``task.trace`` ``(task_id, elapsed_ns)`` tuples) into the Chrome
trace-event format, loadable in ``chrome://tracing`` / Perfetto, plus a
human text renderer for terminals.

Every timestamp is **virtual time** (the simulation's microsecond clock),
never the wall clock — two replays of one seed produce byte-identical
timelines, which is the property that makes a timeline a repro artifact
rather than a log. detlint enforces this statically: wall-clock reads
(including decode-path calls like ``time.ctime``/``time.localtime``) are
DET001 findings, and ``madsim_tpu/obs`` carries no allowlist entries.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# Chrome trace-event phase codes used here: M = metadata, i = instant.
# (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
_SCOPE_THREAD = "t"


def _category(entry: Dict[str, Any]) -> str:
    kind = str(entry.get("kind", ""))
    if kind.startswith("fault:"):
        return "fault"
    if kind in ("invariant", "truncated"):
        return kind
    if entry.get("dropped"):
        return "drop"
    return "timer" if entry.get("timer") else "msg"


def trace_to_chrome(trace: Sequence[Dict[str, Any]], *,
                    seed: Optional[int] = None,
                    label: Optional[str] = None) -> Dict[str, Any]:
    """Render a ``DeviceEngine.trace()`` event list as a Chrome
    trace-event document (a plain dict; ``json.dump`` it).

    Layout: one process (the world), one thread lane per destination
    node (faults land on their target node's lane; engine-level markers
    — invariant raise, truncation — on lane -1). Events are instants at
    their virtual-time microsecond; an entry carrying ``bug_raised``
    additionally emits an ``invariant:raise`` instant immediately after
    it, so under ``stop_on_bug`` (the default) the raise is the
    document's final event — the acceptance contract the repro CLI
    checks.
    """
    pid = 0
    name = label or (f"madsim seed {seed}" if seed is not None else "madsim")
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": name},
    }]
    for e in trace:
        kind = str(e.get("kind", "?"))
        cat = _category(e)
        if cat in ("invariant", "truncated"):
            tid = -1
        else:
            tid = int(e.get("dst", -1))
        ev: Dict[str, Any] = {
            "name": kind, "cat": cat, "ph": "i", "s": _SCOPE_THREAD,
            "ts": float(e.get("t_us", 0)), "pid": pid, "tid": tid,
            "args": {k: v for k, v in e.items()
                     if k in ("step", "src", "dst", "timer", "payload",
                              "dropped", "drop_cause", "bug_seen")},
        }
        events.append(ev)
        if e.get("bug_raised") and kind != "invariant":
            events.append({
                "name": "invariant:raise", "cat": "invariant", "ph": "i",
                "s": _SCOPE_THREAD, "ts": float(e.get("t_us", 0)),
                "pid": pid, "tid": -1, "args": {"step": e.get("step")},
            })
        elif kind == "invariant":
            # The no-event raise marker IS the raise; normalize its name
            # so consumers match one event name either way.
            ev["name"] = "invariant:raise"
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"generator": "madsim_tpu.obs.timeline",
                      "clock": "virtual_us",
                      **({"seed": int(seed)} if seed is not None else {})},
    }


def ring_to_chrome(entries: Sequence[Dict[str, Any]], *,
                   seed: Optional[int] = None,
                   label: Optional[str] = None,
                   k: Optional[int] = None) -> Dict[str, Any]:
    """Render a decoded flight-recorder ring (obs/blackbox.py
    ``decode_ring`` / ``SweepResult.blackbox(seed)`` / a bundle's
    ``madsim.blackbox/1`` ``events``) as a Chrome trace document.

    Ring entries are trace-shaped, so the layout is exactly
    :func:`trace_to_chrome`'s — one thread lane per destination node,
    instants at virtual-time microseconds, the ``invariant:raise``
    instant at the bug — plus the recorder provenance in ``otherData``
    (``source: "blackbox"`` and the ring depth ``k``), so a timeline
    reconstructed from K in-situ records is never mistaken for a full
    replay trace (docs/observability.md "reading a black-box timeline").
    """
    name = label or (f"madsim blackbox seed {seed}" if seed is not None
                     else "madsim blackbox")
    doc = trace_to_chrome(entries, seed=seed, label=name)
    doc["otherData"]["source"] = "blackbox"
    if k is not None:
        doc["otherData"]["blackbox_k"] = int(k)
    return doc


def polls_to_chrome(polls: Iterable[Tuple[int, int]], *,
                    seed: Optional[int] = None,
                    label: Optional[str] = None) -> Dict[str, Any]:
    """Render a host-engine poll trace (``Runtime``'s ``task.trace`` /
    ``bridge.sweep_traced`` entries: ``(task_id, elapsed_ns)`` per poll)
    as a Chrome trace document — one thread lane per task, one instant
    per poll, timestamped in virtual microseconds."""
    pid = 0
    name = label or (f"madsim seed {seed}" if seed is not None else "madsim")
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": name},
    }]
    for i, (task_id, elapsed_ns) in enumerate(polls):
        events.append({
            "name": "poll", "cat": "poll", "ph": "i", "s": _SCOPE_THREAD,
            "ts": elapsed_ns / 1_000.0, "pid": pid, "tid": int(task_id),
            "args": {"poll": i},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"generator": "madsim_tpu.obs.timeline",
                      "clock": "virtual_us",
                      **({"seed": int(seed)} if seed is not None else {})},
    }


def render_text(trace: Sequence[Dict[str, Any]]) -> str:
    """Human-readable timeline: one line per processed event, virtual
    time left-aligned, with drop/bug annotations."""
    lines: List[str] = []
    for e in trace:
        kind = str(e.get("kind", "?"))
        if kind == "truncated":
            lines.append(f"{e.get('t_us', 0):>12,} µs  -- trace truncated at "
                         f"step {e.get('step')} (world still active"
                         f"{'' if e.get('bug_seen') else ', bug never seen'})")
            continue
        src, dst = e.get("src", -1), e.get("dst", -1)
        route = f"{src}->{dst}" if src >= 0 else f"->{dst}" if dst >= 0 else ""
        flags = []
        if e.get("timer"):
            flags.append("timer")
        if e.get("dropped"):
            flags.append("DROPPED")
        note = f" [{','.join(flags)}]" if flags else ""
        payload = e.get("payload") or []
        pay = f" {payload}" if any(payload) else ""
        lines.append(f"{e.get('t_us', 0):>12,} µs  step {e.get('step'):>6}  "
                     f"{route:<7} {kind}{note}{pay}")
        if e.get("bug_raised"):
            lines.append(f"{e.get('t_us', 0):>12,} µs  "
                         f"*** INVARIANT VIOLATION RAISED HERE ***")
    return "\n".join(lines)


def dump_chrome(doc: Dict[str, Any], path: str) -> None:
    """Write a trace document to ``path`` as JSON."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
