"""The sweep observatory: live telemetry, Prometheus snapshots, profiler
hooks, and the ``watch`` CLI.

The sweep loop (parallel/sweep.py) learns a handful of scalars per
superstep anyway — occupancy, bug flag, chunk count, the coverage
ledger's distinct count. This module turns that already-fetched stream
into operator-facing telemetry **without adding a single device→host
sync** (the counted-``_fetch`` tier-1 test covers an ``observe=``-on
sweep): a callback or JSONL emitter per host read, a Prometheus
text-format snapshot writer, and ``python -m madsim_tpu.obs watch`` to
tail or summarize the stream.

Everything here is *host-side* observation of the orchestration loop —
wall-clock reads and ``jax.profiler`` captures are exactly the calls
detlint forbids in simulation code (DET001 / DET007), so this module is
their one sanctioned home and carries the inline pragmas. Nothing in it
feeds a simulation decision: telemetry-on sweeps are bitwise identical
to telemetry-off (tier-1, tests/test_observatory.py).

Record schema (``madsim.sweep.telemetry/1``): progress records carry
``elapsed_s`` (monotonic seconds since loop start — never a wall-clock
date), ``chunks``, ``steps``, ``batch_worlds``, ``n_active``,
``occupancy``, ``seeds_total`` / ``seeds_admitted`` / ``seeds_done``,
``seeds_per_s``, ``world_utilization`` (running lower bound),
``dispatch_depth``, ``bug_seen``, ``eta_s`` (None while the rate is
still 0), and — when the engine runs metrics — ``coverage_distinct`` /
``coverage_buckets``. The final record has ``event: "summary"`` with
``loop_stats`` and the coverage ledger rollup.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Any, Callable, List, Optional, Tuple

# Every duration in the telemetry schema is MONOTONIC seconds (the
# sweep's ``_clk`` = time.perf_counter, docs/perf.md "Telemetry units"),
# never a wall-clock date: two runs of one seed must render identical
# *virtual* timelines, and host clocks must never leak into them.
_SCHEMA = "madsim.sweep.telemetry/1"

# The summary record alone is versioned /2 since the whole-hunt fused
# sweep: it carries ``seeds_per_dispatch`` and ``epochs_on_device`` as
# TOP-LEVEL numerics (the Prometheus renderer exports only top-level
# fields). Additive — every /1 consumer reads a /2 summary unchanged;
# progress records stay /1 (docs/observability.md "Schema history").
_SCHEMA_V2 = "madsim.sweep.telemetry/2"

# The fleet fabric (madsim_tpu.fleet, docs/fleet.md) emits its protocol
# events — lease_issued/expired/released, heartbeats, rpc_retry,
# completions (with duplicate-crosscheck flags), worker
# kill/restart/preemption — into the SAME observe sink as one-line
# records under this schema, so one JSONL stream carries both the
# sweep's progress and the fabric's lease churn and ``watch`` can
# summarize either.
_FLEET_SCHEMA = "madsim.fleet.telemetry/1"

# The cross-range corpus exchange (fleet/exchange.py, docs/fleet.md
# "Corpus exchange") rides the same sink with its own schema: publish
# (range/epoch/bytes, duplicate + torn flags), merge (epoch, ranges
# merged, corpus inserted/size), broadcast (seed corpus delivered with
# a lease), resume (coordinator crash→resume snapshot count).
_EXCHANGE_SCHEMA = "madsim.fleet.exchange/1"

# The evolution observatory (obs/lineage.py, docs/search.md "Reading
# the lineage"): guided sweeps emit one record per refill — corpus
# size/insert pressure, per-refill novelty, and the per-operator
# produced/novel/survived scalars — built from values the retire pull
# already fetched (zero extra device syncs, counted tier-1).
_SEARCH_SCHEMA = "madsim.search.telemetry/1"

# Schema → short key, for the per-schema Prometheus counters and the
# snapshot's namespacing.
_SCHEMA_KEYS = {
    _SCHEMA: "sweep",
    _SCHEMA_V2: "sweep",
    _FLEET_SCHEMA: "fleet",
    _EXCHANGE_SCHEMA: "exchange",
    _SEARCH_SCHEMA: "search",
}


class JsonlEmitter:
    """Append one JSON line per telemetry record; flush per line so a
    killed sweep leaves a readable stream (and ``watch --follow`` sees
    records as they land)."""

    def __init__(self, path: str):
        self.path = os.fspath(path)
        self._f = open(self.path, "w", encoding="utf-8")

    def emit(self, record: dict) -> None:
        if self._f is None:
            return
        json.dump(record, self._f, separators=(",", ":"))
        self._f.write("\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def make_observer(observe: Any
                  ) -> Tuple[Optional[Callable[[dict], None]],
                             Optional[Callable[[], None]]]:
    """Normalize ``sweep(observe=...)`` into ``(emit, close)``.

    ``None`` → no-op; a callable is used as-is (no close); a path string
    becomes a :class:`JsonlEmitter` stream the ``watch`` CLI consumes.
    """
    if observe is None:
        return None, None
    if callable(observe):
        return observe, None
    if isinstance(observe, (str, os.PathLike)):
        em = JsonlEmitter(observe)
        return em.emit, em.close
    raise TypeError(
        f"observe must be a callable or a JSONL file path, got "
        f"{type(observe).__name__}")


# ---------------------------------------------------------------------------
# Prometheus text-format snapshots
# ---------------------------------------------------------------------------

def prometheus_text(record: dict, prefix: str = "madsim_sweep") -> str:
    """Render one telemetry record's numeric fields as Prometheus text
    exposition gauges (booleans as 0/1; nested/None/str fields skipped).
    """
    lines: List[str] = []
    for k in sorted(record):
        v = record[k]
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            continue
        name = f"{prefix}_{k}"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {v}")
    return "\n".join(lines) + "\n"


def _atomic_write(text: str, path: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)


def write_prometheus(record: dict, path: str,
                     prefix: str = "madsim_sweep") -> None:
    """Atomically (tmp+rename) write a Prometheus snapshot of one record
    — the node-exporter-textfile-collector handoff shape, so a scraper
    never reads a half-written file."""
    _atomic_write(prometheus_text(record, prefix=prefix), path)


def _prom_name(s: str) -> str:
    """Sanitize an event/schema key into a metric-name fragment."""
    return "".join(c if c.isalnum() else "_" for c in str(s))


def prometheus_snapshot(records: List[dict]) -> str:
    """Whole-stream Prometheus snapshot: per-schema record counters,
    per-event fleet/exchange counters, and the latest sweep + search
    records' gauges.

    A stream from a fleet interleaves four schemas; rendering only the
    newest record used to let a fleet/exchange record carry no sweep
    gauges at all (and fleet activity never surfaced as metrics). The
    snapshot keeps the newest record of EACH numeric schema as gauges
    (``madsim_sweep_*`` / ``madsim_search_*``) and counts every record
    and fleet/exchange event (``madsim_records_<schema>``,
    ``madsim_fleet_events_<event>``, ``madsim_exchange_events_<event>``)
    so node-exporter dashboards see fleet + search activity, not just
    sweep progress.
    """
    parts: List[str] = []
    counts: dict = {}
    events: dict = {}
    latest: dict = {}
    for r in records:
        key = _SCHEMA_KEYS.get(r.get("schema"), "other")
        counts[key] = counts.get(key, 0) + 1
        if key in ("sweep", "search"):
            latest[key] = r
        if key in ("fleet", "exchange") and r.get("event"):
            name = f"madsim_{key}_events_{_prom_name(r['event'])}"
            events[name] = events.get(name, 0) + 1
    for key in sorted(counts):
        name = f"madsim_records_{_prom_name(key)}"
        parts.append(f"# TYPE {name} counter\n{name} {counts[key]}")
    for name in sorted(events):
        parts.append(f"# TYPE {name} counter\n{name} {events[name]}")
    out = "\n".join(parts) + ("\n" if parts else "")
    if "sweep" in latest:
        out += prometheus_text(latest["sweep"], prefix="madsim_sweep")
    if "search" in latest:
        out += prometheus_text(latest["search"], prefix="madsim_search")
    return out


def write_prometheus_snapshot(records: List[dict], path: str) -> None:
    """Atomic write of :func:`prometheus_snapshot` (tmp+rename)."""
    _atomic_write(prometheus_snapshot(records), path)


# ---------------------------------------------------------------------------
# Profiler capture window
# ---------------------------------------------------------------------------

class ProfilerWindow:
    """Wrap a window of sweep dispatches in ``jax.profiler`` capture.

    ``window=(start, stop)`` counts loop dispatches: the capture starts
    right before dispatch ``start`` and stops at the first blocking
    scalar read at/after dispatch ``stop`` (so the device execution of
    every in-window dispatch has completed inside the capture), or at
    loop end. The device timeline lands under ``trace_dir`` — beside the
    *virtual-time* timelines of obs/timeline.py, this is the sanctioned
    wall-clock view of the same sweep. With ``trace_dir=None`` every
    method is a no-op. Capture failures (profiler backends vary) are
    recorded on ``self.error`` and never propagate into the sweep.
    """

    def __init__(self, trace_dir: Optional[str],
                 window: Tuple[int, int] = (0, 4)):
        self.trace_dir = os.fspath(trace_dir) if trace_dir else None
        start, stop = int(window[0]), int(window[1])
        if self.trace_dir is not None and not 0 <= start < stop:
            raise ValueError(
                f"profile_window must be (start, stop) dispatch indices "
                f"with 0 <= start < stop; got {window!r}")
        self.start, self.stop = start, stop
        self.error: Optional[str] = None
        self._dispatches = 0
        self._reads = 0
        self._active = False
        self._done = self.trace_dir is None

    def before_dispatch(self) -> None:
        if not self._done and not self._active \
                and self._dispatches >= self.start:
            try:
                import jax

                os.makedirs(self.trace_dir, exist_ok=True)
                # detlint: allow[DET007] reason=the sanctioned sweep(profile_dir=) capture site; host-side observation only
                jax.profiler.start_trace(self.trace_dir)
                self._active = True
            except Exception as exc:  # pragma: no cover — backend-specific
                self.error = f"{type(exc).__name__}: {exc}"
                self._done = True
        self._dispatches += 1

    def annotate(self, label: str):
        """Context manager naming the enclosed dispatch on the captured
        timeline; a null context while no capture is active."""
        if self._active:
            try:
                import jax

                # detlint: allow[DET007] reason=names the dispatch on the sanctioned capture timeline
                return jax.profiler.TraceAnnotation(label)
            except Exception:  # pragma: no cover — backend-specific
                pass
        import contextlib

        return contextlib.nullcontext()

    def after_read(self) -> None:
        """One blocking scalar read happened: device work up to the read
        superstep is complete. Stop once the window is covered."""
        self._reads += 1
        if self._active and self._reads >= self.stop:
            self.close()

    def close(self) -> None:
        """Idempotent; also the error-path stop (sweep's finally)."""
        if self._active:
            try:
                import jax

                # detlint: allow[DET007] reason=closes the sanctioned capture window (also the error-path stop)
                jax.profiler.stop_trace()
            except Exception as exc:  # pragma: no cover — backend-specific
                self.error = f"{type(exc).__name__}: {exc}"
            self._active = False
        self._done = True


# ---------------------------------------------------------------------------
# `python -m madsim_tpu.obs watch` — tail/summarize a telemetry stream
# ---------------------------------------------------------------------------

def _load_records(path: str) -> List[dict]:
    out: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # half-written tail of a live stream
    return out


def render_progress(rec: dict) -> str:
    """One terminal line per progress record."""
    occ = rec.get("occupancy")
    cov = rec.get("coverage_distinct")
    eta = rec.get("eta_s")
    bits = [
        f"t={rec.get('elapsed_s', 0):8.2f}s",
        f"chunks={rec.get('chunks', 0):<5}",
        f"active={rec.get('n_active', 0)}/{rec.get('batch_worlds', 0)}"
        + (f" ({occ:.0%})" if isinstance(occ, (int, float)) else ""),
        f"seeds {rec.get('seeds_done', 0)}/{rec.get('seeds_total', 0)}"
        f" @ {rec.get('seeds_per_s', 0)}/s",
    ]
    if cov is not None:
        bits.append(f"behaviors={cov}")
    bits.append("eta=" + (f"{eta:.1f}s" if isinstance(eta, (int, float))
                          else "?"))
    if rec.get("bug_seen"):
        bits.append("BUG")
    return "  ".join(bits)


def render_fleet_event(rec: dict) -> str:
    """One terminal line per fleet-fabric record (lease churn, worker
    life cycle, retries) — keyed by worker so an operator can eyeball a
    sick host in the stream."""
    bits = [f"t={rec.get('t', 0):>6}", f"[{rec.get('worker', '?')}]",
            rec.get("event", "?")]
    for k in ("range_id", "lease_id", "generation", "reissued",
              "duplicate", "crosschecked", "attempt", "exitcode"):
        if k in rec and rec[k] not in (None, False):
            bits.append(f"{k}={rec[k]}")
    if rec.get("error"):
        bits.append(f"error={rec['error']}")
    return "  ".join(str(b) for b in bits)


def render_exchange_event(rec: dict) -> str:
    """One terminal line per corpus-exchange record — epochs, ranges
    merged, corpus growth, bytes on the wire — so an operator can watch
    the fleet's shared search progress next to its lease churn."""
    bits = [f"t={rec.get('t', 0):>6}", "[exchange]", rec.get("event", "?")]
    for k in ("epoch", "from_epoch", "range_id", "worker",
              "ranges_merged", "corpus_inserted", "corpus_size",
              "corpus_gen", "epochs_merged", "bytes", "snapshots"):
        if k in rec and rec[k] is not None:
            bits.append(f"{k}={rec[k]}")
    for k in ("duplicate", "torn"):
        if rec.get(k):
            bits.append(k.upper())
    if rec.get("error"):
        bits.append(f"error={rec['error']}")
    return "  ".join(str(b) for b in bits)


def render_exchange_summary(exchange: List[dict]) -> List[str]:
    """Aggregate line for the exchange records in a stream: epochs
    merged, corpus inserts, publish/broadcast traffic."""
    if not exchange:
        return []
    merges = [r for r in exchange if r.get("event") == "merge"]
    pubs = [r for r in exchange if r.get("event") == "publish"]
    line = (f"exchange: {len(merges)} epoch(s) merged, "
            f"{sum(r.get('corpus_inserted', 0) for r in merges)} corpus "
            f"insert(s), {len(pubs)} publish(es) "
            f"({sum(r.get('bytes', 0) for r in pubs)} B published)")
    dup = sum(1 for r in pubs if r.get("duplicate"))
    torn = sum(1 for r in exchange if r.get("event") == "publish_torn")
    if dup or torn:
        line += (f" [{dup} duplicate(s) crosschecked, {torn} torn "
                 "publish(es) discarded]")
    if merges:
        last = merges[-1]
        line += (f"; merged corpus: {last.get('corpus_size', '?')} "
                 f"entries after epoch {last.get('epoch', '?')}")
    return [line]


def render_search_event(rec: dict) -> str:
    """One terminal line per search-telemetry record (obs/lineage.py):
    refill-grain corpus growth and the per-operator survival scalars, so
    an operator can watch which mutation operators are earning their
    keep while the hunt runs."""
    bits = [f"t={rec.get('elapsed_s', 0):8.2f}s", "[search]",
            rec.get("event", "?"),
            f"gen={rec.get('generation', '?')}",
            f"corpus={rec.get('corpus_size', '?')}",
            f"inserted={rec.get('corpus_inserted', '?')}"]
    if rec.get("refill_novel") is not None:
        bits.append(f"novel+={rec['refill_novel']}")
    if rec.get("refill_inserted") is not None:
        bits.append(f"ins+={rec['refill_inserted']}")
    if rec.get("epochs_on_device") is not None:
        # Fused-hunt cadence: refills run on device, so each record is
        # a per-MEGA-DISPATCH rollup — render that explicitly so an
        # operator reading a sparse stream knows the hunt is not stuck.
        bits.append(f"epochs_on_device={rec['epochs_on_device']} "
                    "(per-mega-dispatch rollup)")
    surv = [(k[len("op_survived_"):], v) for k, v in rec.items()
            if k.startswith("op_survived_") and v]
    if surv:
        bits.append("survived[" + " ".join(f"{k}={v}"
                                           for k, v in sorted(surv)) + "]")
    return "  ".join(str(b) for b in bits)


def render_search_summary(search: List[dict]) -> List[str]:
    """Aggregate line for the search records in a stream: generations,
    corpus growth, and the top surviving operator."""
    if not search:
        return []
    last = search[-1]
    fused = last.get("epochs_on_device") is not None
    line = (f"search: {len(search)} "
            f"{'mega-dispatch rollup(s)' if fused else 'refill(s)'}, "
            f"generation {last.get('generation', '?')}, corpus "
            f"{last.get('corpus_size', '?')} "
            f"({last.get('corpus_inserted', '?')} inserted)")
    if fused:
        line += (f"; fused=true — {last['epochs_on_device']} refill "
                 "epoch(s) ran on device between pulls")
    surv = [(k[len("op_survived_"):], v) for k, v in last.items()
            if k.startswith("op_survived_")]
    if surv:
        top = max(surv, key=lambda kv: kv[1])
        if top[1]:
            line += f"; top operator {top[0]} ({top[1]} survived)"
    return [line]


def render_fleet_summary(fleet: List[dict]) -> List[str]:
    """Aggregate lines for the fleet records in a stream: event counts
    plus the resilience headline (expiries, re-leases, crosschecked
    duplicates)."""
    if not fleet:
        return []
    counts: dict = {}
    for r in fleet:
        counts[r.get("event", "?")] = counts.get(r.get("event", "?"), 0) + 1
    lines = ["fleet: " + ", ".join(f"{k}={v}"
                                   for k, v in sorted(counts.items()))]
    summary = next((r for r in fleet if r.get("event") == "fleet_summary"),
                   None)
    if summary is not None:
        lines.append(
            f"fleet summary: {summary.get('completions', '?')} ranges "
            f"completed ({summary.get('leases_expired', 0)} leases "
            f"expired, {summary.get('leases_reissued', 0)} re-issued, "
            f"{summary.get('duplicates_crosschecked', 0)} duplicate "
            "completions crosschecked bitwise)")
    return lines


def render_summary(records: List[dict]) -> str:
    """Human summary of a whole stream (the non-follow ``watch`` mode)."""
    if not records:
        return "watch: empty telemetry stream"
    fleet = [r for r in records if r.get("schema") == _FLEET_SCHEMA]
    exchange = [r for r in records if r.get("schema") == _EXCHANGE_SCHEMA]
    search = [r for r in records if r.get("schema") == _SEARCH_SCHEMA]
    records = [r for r in records
               if r.get("schema") not in (_FLEET_SCHEMA, _EXCHANGE_SCHEMA,
                                          _SEARCH_SCHEMA)]
    progress = [r for r in records if r.get("event") != "summary"]
    summary = next((r for r in records if r.get("event") == "summary"),
                   None)
    lines: List[str] = render_fleet_summary(fleet)
    lines.extend(render_exchange_summary(exchange))
    lines.extend(render_search_summary(search))
    if progress:
        lines.append(f"{len(progress)} progress records; last:")
        lines.append("  " + render_progress(progress[-1]))
        covs = [r["coverage_distinct"] for r in progress
                if "coverage_distinct" in r]
        if covs:
            lines.append(
                f"novelty curve: {covs[0]} -> {covs[-1]} distinct "
                f"behaviors over {len(covs)} reads"
                + (" (still growing at exit — the hunt had not "
                   "saturated)" if len(covs) >= 2 and covs[-1] > covs[-2]
                   else ""))
    if summary is not None:
        ls = summary.get("loop_stats") or {}
        lines.append(
            f"final: {summary.get('failing_seeds', '?')} failing of "
            f"{summary.get('seeds_total', '?')} seeds in "
            f"{summary.get('elapsed_s', '?')}s "
            f"(utilization {summary.get('world_utilization', '?')}, "
            f"{ls.get('chunks', '?')} chunks / "
            f"{ls.get('dispatches', '?')} dispatches)")
        if "seeds_per_dispatch" in summary:
            # /2 summaries: the dispatch-economics gauges, top-level.
            fused = " (fused hunt)" if ls.get("fused") else ""
            lines.append(
                f"dispatch economics: {summary['seeds_per_dispatch']} "
                f"seeds/dispatch, {summary.get('epochs_on_device', 0)} "
                f"refill epochs on device{fused}")
        cov = summary.get("coverage")
        if cov:
            lines.append(
                f"coverage: {cov.get('distinct_behaviors')} distinct "
                f"behaviors in {cov.get('n_buckets')} buckets "
                f"({cov.get('worlds_folded')} worlds folded, novelty "
                f"{cov.get('novelty_first')}->{cov.get('novelty_last')})")
        srch = summary.get("search")
        if srch:
            line = (f"search: corpus {srch.get('corpus_size')}/"
                    f"{srch.get('corpus_capacity')} after "
                    f"{srch.get('generations')} generation(s), "
                    f"{srch.get('inserted')} inserted")
            ops = srch.get("operator_stats") or {}
            best = max(ops.items(),
                       key=lambda kv: kv[1].get("survived", 0),
                       default=None)
            if best and best[1].get("survived", 0):
                line += (f"; top operator {best[0]} "
                         f"({best[1]['survived']} survived, "
                         f"{best[1].get('survival_pct', 0)}% of "
                         f"{best[1].get('produced', 0)} produced)")
            lines.append(line)
    elif not fleet and not exchange:
        lines.append("no summary record yet (sweep still running?)")
    return "\n".join(lines)


def watch(path: str, follow: bool = False, prom: Optional[str] = None,
          interval: float = 1.0, out=None) -> int:
    """The ``watch`` subcommand body. Summarizes the stream (default) or
    tails it (``follow=True``) until the summary record arrives; with
    ``prom`` set, each new record refreshes a Prometheus snapshot file.
    """
    out = out or sys.stdout
    if not os.path.exists(path):
        print(f"watch: no such file: {path}", file=sys.stderr)
        return 2
    if not follow:
        records = _load_records(path)
        print(render_summary(records), file=out)
        if prom and records:
            write_prometheus_snapshot(records, prom)
        return 0
    # Follow mode: host-side tail of a host-side stream — the one place
    # a real sleep belongs (this process never runs simulation code).
    import time as _walltime

    seen = 0
    done = False
    while not done:
        records = _load_records(path)
        for i, rec in enumerate(records[seen:], start=seen):
            if rec.get("event") == "summary" \
                    and rec.get("schema") != _SEARCH_SCHEMA:
                print(render_summary(records), file=out)
                done = True
            elif rec.get("schema") == _SEARCH_SCHEMA:
                print(render_search_event(rec), file=out)
            elif rec.get("schema") == _EXCHANGE_SCHEMA:
                print(render_exchange_event(rec), file=out)
            elif rec.get("schema") == _FLEET_SCHEMA:
                print(render_fleet_event(rec), file=out)
            else:
                print(render_progress(rec), file=out)
            if prom:
                # Snapshot over everything seen so far: a fleet or
                # search record must ADD counters, never clobber the
                # sweep gauges (the per-schema counter satellite).
                write_prometheus_snapshot(records[:i + 1], prom)
        seen = len(records)
        if not done:
            _walltime.sleep(interval)  # detlint: allow[DET001]
    return 0
