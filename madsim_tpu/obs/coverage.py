"""Device-resident behavior-coverage ledger: the sweep's novelty signal.

A FoundationDB-style always-on hunt (PAPER.md) is only as good as its
ability to answer "are we still finding *new behaviors*?" while it runs.
This module turns the :class:`~madsim_tpu.obs.metrics.MetricsBlock`
histograms PR 5 already accumulates per world into exactly that signal,
with the DrJAX MapReduce-primitive shape (PAPERS.md): a *map* over
retiring worlds (hash each world's histograms into a behavior signature)
and an on-device *reduce* (psum/pmin of a fixed-size bucket sketch over
the mesh), so the hunt's coverage accounting costs **zero host pulls**
inside the sweep's superstep loop.

The signature is deliberately coarse — AFL-style: every histogram count
is first quantized to its power-of-two bucket (``bit_length``), then the
bucketed columns are FNV-1a-folded into one u32 per world. Two worlds
that delivered "about the same mix" of event kinds, drop causes, and
fault injections therefore share a signature; a world that took a new
qualitative path (a drop cause never seen, a fault survived differently,
an order-of-magnitude shift in an event kind) lands in a fresh bucket.
Exact counts would make every seed "novel" and the signal useless.

The ledger itself is ``K`` buckets carried as mesh-replicated device
arrays (``hits`` — worlds folded per bucket; ``first_seen`` — the lowest
seed id folded into the bucket). Folds happen at **retire time**: the
chunk/superstep bodies (engine/core.py ``_superstep_impl``,
parallel/sweep.py runners) detect the worlds whose ``active`` flag fell
during the chunk and scatter their signatures in, which gives each world
exactly one fold with no extra bookkeeping state — and makes the fold
sequence (and so the per-chunk ``novelty_curve``) identical between the
serial and pipelined orchestration loops, because both execute the same
chunk bodies in the same order (the bitwise contract of docs/perf.md
"Pipelined orchestration").

Order-invariance contract: ``hits`` (a count per bucket) and
``first_seen`` (a *minimum* seed id per bucket, not a temporal first)
do not depend on fold order, only on the folded set — which is what
lets a checkpoint→resume sweep reproduce them bit-identically (the
resume pre-pass folds the already-retired worlds it finds in the
checkpoint; tests/test_obs.py). Only ``novelty_curve`` is per-call (it
is the *history* of this run's chunks).

Like :class:`MetricsBlock` itself, everything here is read-only over the
simulation state: no RNG draw, queue lane, or actor input ever depends
on the ledger, so coverage-on sweeps walk bit-identical trajectories to
coverage-off (tier-1, tests/test_obs.py).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

# Default sketch width (buckets). 256 is far above the distinct-behavior
# counts observed on the in-repo actor families (tens), so hash
# collisions stay rare while the whole ledger is ~2 KB of device memory
# and one ~2 KB pull at sweep end.
DEFAULT_BUCKETS = 256

# FNV-1a 32-bit constants (the signature hash).
_FNV_SEED = 0x811C9DC5
_FNV_PRIME = 0x01000193

# Sentinel for "no seed folded into this bucket yet" inside device math
# (host-facing arrays use -1).
_NO_SEED = np.int32(2**31 - 1)


def _bit_length_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Per-element ``int.bit_length`` of a non-negative int array, as u32.

    The AFL-style count quantizer: 0→0, 1→1, 2..3→2, 4..7→3, ... Exact
    integer math (no float log), so signatures are bit-stable across
    backends.
    """
    x = x.astype(jnp.uint32)
    n = jnp.zeros(x.shape, jnp.uint32)
    for s in (16, 8, 4, 2, 1):  # static unroll: 5 shift/compare rounds
        hi = x >> s
        move = hi > 0
        n = n + jnp.where(move, jnp.uint32(s), jnp.uint32(0))
        x = jnp.where(move, hi, x)
    return n + (x > 0).astype(jnp.uint32)


def behavior_signature(mb) -> jnp.ndarray:
    """u32 behavior signature per world from a (batched) MetricsBlock.

    Hashes the per-event-kind histogram, the fault-injection histogram,
    and the drop-cause counters — each bucketed to its power of two —
    in a fixed column order with FNV-1a. Works on a single block or a
    batch (leading world axis); traceable under jit/vmap/shard_map.
    """
    cols = [mb.kind_hist[..., j] for j in range(mb.kind_hist.shape[-1])]
    cols += [mb.fault_hist[..., j] for j in range(mb.fault_hist.shape[-1])]
    cols += [mb.drop_loss, mb.drop_stale, mb.drop_dead,
             mb.drop_out_of_time, mb.drop_overflow, mb.drop_inf]
    h = jnp.full(jnp.shape(cols[0]), _FNV_SEED, jnp.uint32)
    for c in cols:
        h = (h ^ _bit_length_u32(c)) * jnp.uint32(_FNV_PRIME)
    return h


def ledger_zeros(n_buckets: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """A fresh (hits, first_seen) ledger pair (mesh-replicated shapes)."""
    return (jnp.zeros((n_buckets,), jnp.int32),
            jnp.full((n_buckets,), -1, jnp.int32))


def fold_retired(hits, first_seen, mb, fold_mask, idx,
                 reduce_sum, reduce_min):
    """Fold the masked worlds' behavior signatures into the ledger.

    ``mb`` is the batched MetricsBlock, ``fold_mask`` a (W,) bool of
    worlds to fold (the caller computes "retired during this chunk, real
    seed id"), ``idx`` the (W,) slot→seed-id vector. ``reduce_sum`` /
    ``reduce_min`` reduce a replicated array over the mesh axes (psum /
    pmin inside a shard_mapped sweep; identity under plain use). Masked
    scatters go to a dump row, so the fold costs no branches.
    """
    k = hits.shape[0]
    sig = behavior_signature(mb)
    bucket = (sig % jnp.uint32(k)).astype(jnp.int32)
    slot = jnp.where(fold_mask, bucket, k)  # dump row for masked-out worlds
    add = jnp.zeros((k + 1,), jnp.int32).at[slot].add(1)[:k]
    add = reduce_sum(add)
    cand = jnp.full((k + 1,), _NO_SEED, jnp.int32).at[slot].min(
        idx.astype(jnp.int32))[:k]
    cand = reduce_min(cand)
    # first_seen is a MINIMUM seed id, not a temporal first: fold-order
    # invariant, so pipeline reordering and checkpoint/resume cannot
    # perturb it.
    best = jnp.minimum(jnp.where(first_seen >= 0, first_seen, _NO_SEED),
                       cand)
    first_seen = jnp.where(best < _NO_SEED, best, jnp.int32(-1))
    return hits + add, first_seen


def fold_retired_local(hits, first_seen, mb, fold_mask, idx):
    """:func:`fold_retired` for programs that see the FULL world axis.

    The in-loop variant the fused whole-hunt superstep uses
    (parallel/sweep.py): that program is a plain ``jit`` partitioned by
    GSPMD rather than a ``shard_map`` body, so its scatters already
    cover every world and the mesh reducers collapse to identity.
    Integer adds and minima are reduction-order invariant, so the
    resulting ledger is bitwise equal to the shard_mapped fold's.
    """
    ident = lambda x: x
    return fold_retired(hits, first_seen, mb, fold_mask, idx,
                        reduce_sum=ident, reduce_min=ident)


def distinct_count(hits: jnp.ndarray) -> jnp.ndarray:
    """Number of non-empty buckets — the ``distinct_behaviors`` scalar.
    (dtype-pinned sum: a bare jnp.sum widens to i64 under the x64 flag,
    which would break the i32 novelty-history carry — tracelint TRC003.)
    """
    return jnp.sum(hits > 0, dtype=jnp.int32)


@dataclasses.dataclass
class SweepCoverage:
    """Host-side coverage ledger of one sweep (``SweepResult.coverage``).

    ``novelty_curve[i]`` is the cumulative distinct-behavior count after
    the chunk ``SweepResult.n_active_chunks[i]`` (entrywise aligned with
    ``n_active_history`` — the same cadence, the same skew notes).
    Monotone non-decreasing by construction; deterministic across the
    pipelined/serial loops for the same seed set. ``distinct_behaviors``
    additionally includes the end-of-sweep fold of worlds still live at
    exit (a truncated world's partial histograms are a behavior too), so
    it is ``>= novelty_curve[-1]``.
    """

    n_buckets: int
    hits: np.ndarray             # (K,) worlds folded per bucket
    first_seen_seed: np.ndarray  # (K,) lowest seed id in bucket; -1 empty
    novelty_curve: np.ndarray    # cumulative distinct per executed chunk

    @property
    def distinct_behaviors(self) -> int:
        return int(np.count_nonzero(self.hits))

    @property
    def new_behaviors_per_chunk(self) -> np.ndarray:
        """The novelty curve's derivative: fresh buckets per chunk entry."""
        c = np.asarray(self.novelty_curve, np.int64)
        return np.diff(c, prepend=0)

    def to_json(self) -> Dict[str, object]:
        """Compact JSON-safe record (bench_results.json ``coverage``)."""
        curve = [int(x) for x in self.novelty_curve]
        return {
            "n_buckets": int(self.n_buckets),
            "distinct_behaviors": self.distinct_behaviors,
            "worlds_folded": int(self.hits.sum()),
            "novelty_first": curve[0] if curve else 0,
            "novelty_last": curve[-1] if curve else 0,
            "novelty_chunks": len(curve),
        }


def coverage_of_counters(counters: Dict[str, np.ndarray],
                         n_buckets: int = DEFAULT_BUCKETS
                         ) -> Dict[str, object]:
    """Host-side ledger over a dict of per-slot counter vectors.

    The bridge analog of the device fold: the kernel's ``BridgeMetrics``
    block is pulled once at sweep end (per *slot*, cumulative across
    recycled seeds — see bridge/kernel.py), and the same
    bucketize-then-FNV sketch runs in numpy over its columns. Column
    order is the sorted key order, so the sketch is stable across runs.
    """
    keys = sorted(counters)
    if not keys:
        return {"n_buckets": n_buckets, "distinct_behaviors": 0,
                "worlds_folded": 0}
    w = np.asarray(counters[keys[0]]).shape[0]
    h = np.full((w,), _FNV_SEED, np.uint32)
    for k in keys:
        col = np.asarray(counters[k], np.uint64)
        bl = np.zeros((w,), np.uint32)
        nz = col > 0
        # np bit_length via log2 on exact-integer u64 range would lose
        # precision; use the binary count loop like the device side.
        x = col.copy()
        for s in (32, 16, 8, 4, 2, 1):
            hi = x >> np.uint64(s)
            move = hi > 0
            bl[move] += np.uint32(s)
            x[move] = hi[move]
        bl += nz.astype(np.uint32)
        h = (h ^ bl) * np.uint32(_FNV_PRIME)
    buckets = h % np.uint32(n_buckets)
    hits = np.bincount(buckets, minlength=n_buckets)
    return {
        "n_buckets": int(n_buckets),
        "distinct_behaviors": int(np.count_nonzero(hits)),
        "worlds_folded": int(w),
    }


def coverage_from_device(n_buckets: int, hits, first_seen,
                         novelty: Optional[list]) -> SweepCoverage:
    """Assemble the host dataclass from the pulled ledger arrays."""
    return SweepCoverage(
        n_buckets=int(n_buckets),
        hits=np.asarray(hits, np.int64),
        first_seen_seed=np.asarray(first_seen, np.int64),
        novelty_curve=np.asarray(novelty or [], np.int64),
    )
