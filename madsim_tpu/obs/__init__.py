"""Observability for madsim_tpu sweeps: the FoundationDB-style triad.

madsim's whole value is that a failure is a *seed you can replay*
(`madsim/src/sim/runtime/builder.rs:118-136`, the repro banner at
`runtime/mod.rs:192-199`). This package closes the gap between "seed
17234 failed" and knowing *what the fleet did on the way there*, with
the three-layer shape simulation-testing systems converge on (PAPERS.md,
FoundationDB lineage):

1. **Cheap always-on counters** (:mod:`.metrics`): an opt-in
   ``MetricsBlock`` pytree carried alongside ``WorldState``
   (``EngineConfig(metrics=True)``), accumulating per-world simulation
   counters entirely on device — sends, deliveries, drops by cause,
   timer fires, fault injections by kind, per-event-kind histograms,
   virtual time. The load-bearing contract is **bitwise invisibility**:
   metrics never feed step math, so a metrics-on sweep is bit-identical
   to metrics-off (tier-1, tests/test_obs.py) and metrics-off compiles
   the exact pre-existing program (the PR 3 op budget is untouched).
2. **Deep on-demand traces** (:mod:`.timeline`): ``EngineCore.trace()``
   output (and host ``Runtime`` poll traces) rendered as Chrome
   trace-event / Perfetto JSON or human-readable text. Timestamps are
   *virtual time* — never the wall clock (detlint-gated).
3. **One-file repros** (:mod:`.bundle`): a failing run writes a JSON
   artifact (seed, config + hash, fault schedule, backend/batch knobs)
   that ``python -m madsim_tpu.obs replay`` re-runs verbatim. Bundles
   emitted by the failure-triage pipeline (:mod:`madsim_tpu.triage`,
   docs/triage.md) carry the MINIMIZED fault schedule plus a
   ``minimization`` provenance block (rounds, candidates, original→final
   row counts, weakenings) — the replay contract is unchanged.

Since the sweep observatory landed, the triad has a live fourth leg
(docs/observability.md "The sweep observatory"): a behavior-coverage
ledger folded on device at retire time (:mod:`.coverage` —
``SweepResult.coverage`` with the per-chunk ``novelty_curve``), a
telemetry stream piggybacking the loop's existing scalar fetch
(:mod:`.observatory` — ``sweep(observe=...)``, Prometheus snapshots,
``jax.profiler`` capture windows), and the matching ``watch`` CLI.

CLI: ``python -m madsim_tpu.obs replay --seed N --actor raft ...``,
``replay --bundle repro.json``, or ``watch telemetry.jsonl [--follow]``.
See docs/observability.md.
"""
from .blackbox import (
    BlackboxRing,
    blackbox_block,
    decode_ring,
    ring_matches_trace,
    rings_from_observations,
)
from .bundle import load_bundle, write_sweep_bundle, write_test_bundle
from .coverage import (
    DEFAULT_BUCKETS,
    SweepCoverage,
    behavior_signature,
    coverage_of_counters,
)
from .metrics import (
    BLOCK_FIELDS,
    NUM_FAULT_KINDS,
    MetricsBlock,
    aggregate_metrics,
    metrics_from_observations,
)
from .observatory import (
    JsonlEmitter,
    ProfilerWindow,
    make_observer,
    prometheus_text,
    write_prometheus,
)
from .timeline import polls_to_chrome, render_text, ring_to_chrome, \
    trace_to_chrome

__all__ = [
    "BlackboxRing", "blackbox_block", "decode_ring",
    "ring_matches_trace", "rings_from_observations", "ring_to_chrome",
    "MetricsBlock", "NUM_FAULT_KINDS", "BLOCK_FIELDS",
    "aggregate_metrics", "metrics_from_observations",
    "SweepCoverage", "DEFAULT_BUCKETS", "behavior_signature",
    "coverage_of_counters",
    "JsonlEmitter", "ProfilerWindow", "make_observer",
    "prometheus_text", "write_prometheus",
    "trace_to_chrome", "polls_to_chrome", "render_text",
    "write_sweep_bundle", "write_test_bundle", "load_bundle",
]
