"""Device-resident sweep metrics: the always-on counter layer.

``MetricsBlock`` is a per-world pytree of int32 counters that rides in
``WorldState.metrics`` when ``EngineConfig(metrics=True)`` — a *separate
leaf* the step updates but never reads for simulation decisions, so:

- **bitwise invisibility**: a metrics-on run walks the bit-identical
  trajectory of a metrics-off run (no RNG draw, queue write, or actor
  input ever depends on a counter) — tier-1-gated for raft/pb/tpc across
  plain/recycled/pipelined sweeps in tests/test_obs.py;
- **zero cost when off**: with ``metrics=False`` the field is ``None``
  (an empty pytree subtree), the update code is not even traced, and the
  compiled step is the exact pre-existing program — the PR 3 per-step
  op budget in tests/test_queue_insert.py holds unchanged.

The counters survive world recycling for free: they live in the world
slot, the sweep's slot→seed index attributes them per seed at
retirement, and ``SweepResult.metrics`` reports per-seed frames plus the
fleet aggregate (``bench.py`` records the latter under
``configs.*.sim_metrics``). The bridge kernel carries the analogous
block for host-workload sweeps (``bridge/kernel.py`` ``BridgeMetrics``).

This module deliberately imports nothing from :mod:`madsim_tpu.engine`
(the engine imports *it*); the fault-kind count mirrors the
``FAULT_KILL..FAULT_RESUME`` op range in engine/core.py and is asserted
against it in tests/test_obs.py.

Packed-lane interplay (engine/lanes.py, docs/perf.md "Roofline
round 2"): the counters stay **int32 in both dtype profiles** — they
are unbounded counts (the registry's wide ``counter`` category), not
value-bounded lanes — while the narrow code lanes feed them only
through the engine's widened in-flight values (``ev.kind`` is i32 by
the time it indexes ``kind_hist``/``fault_hist``). That keeps the
``m_*`` observations bit-identical between ``packed=True`` and the i32
reference profile, which the packed crosscheck matrix in
tests/test_obs.py relies on.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

# Width of the fault-injection histogram: one bin per FAULT_* op
# (engine/core.py FAULT_KILL=0 .. FAULT_RESUME=9).
NUM_FAULT_KINDS = 10

# Observation-dict prefix for metrics fields (DeviceEngine.observe adds
# one ``m_<field>`` entry per block field when metrics are on).
OBS_PREFIX = "m_"


class MetricsBlock(NamedTuple):
    """Per-world simulation counters (leading world axis when batched).

    Counter semantics (all int32; increments are masked on the world's
    pre-step ``active`` flag, so a frozen world's block never moves):

    - ``msgs_sent``: non-timer outbox rows a live handler offered to the
      network (send *attempts*, before loss/clog).
    - ``msgs_delivered`` / ``timer_fires``: events actually handled by
      the actor, split message vs (generation-valid) timer.
    - ``drop_loss``: sends dropped at send time — Bernoulli loss or a
      clogged node/link (`net/network.rs:249-257` sampling point).
    - ``drop_stale`` / ``drop_dead``: popped events discarded because
      the timer's node generation changed (kill/restart) or the
      destination was dead at delivery time.
    - ``drop_out_of_time``: events popped at/past ``t_limit_us``.
    - ``enqueued``: events inserted into the queue (actor sends, timer
      arms, fault rows); ``drop_overflow`` counts inserts refused by a
      full queue, ``drop_inf`` deadline-saturated events dropped at
      push (queue.py INF_TIME contract).
    - ``vtime_us``: virtual microseconds this world advanced (the sum
      of per-step clock deltas; equals the world's final clock).
    - ``fault_hist``: (NUM_FAULT_KINDS,) injections applied, by op.
    - ``kind_hist``: (num_kinds,) delivered events by actor event kind
      (the actor's ``kind_names`` order).
    """

    msgs_sent: jnp.ndarray
    msgs_delivered: jnp.ndarray
    timer_fires: jnp.ndarray
    drop_loss: jnp.ndarray
    drop_stale: jnp.ndarray
    drop_dead: jnp.ndarray
    drop_out_of_time: jnp.ndarray
    enqueued: jnp.ndarray
    drop_overflow: jnp.ndarray
    drop_inf: jnp.ndarray
    vtime_us: jnp.ndarray
    fault_hist: jnp.ndarray   # (NUM_FAULT_KINDS,)
    kind_hist: jnp.ndarray    # (num_kinds,)

    @staticmethod
    def zeros(num_kinds: int) -> "MetricsBlock":
        """A fresh (single-world) block for an actor with ``num_kinds``
        event kinds."""
        z = jnp.int32(0)
        return MetricsBlock(
            msgs_sent=z, msgs_delivered=z, timer_fires=z, drop_loss=z,
            drop_stale=z, drop_dead=z, drop_out_of_time=z, enqueued=z,
            drop_overflow=z, drop_inf=z, vtime_us=z,
            fault_hist=jnp.zeros((NUM_FAULT_KINDS,), jnp.int32),
            kind_hist=jnp.zeros((num_kinds,), jnp.int32),
        )


BLOCK_FIELDS = MetricsBlock._fields


def metrics_from_observations(obs: Dict[str, np.ndarray]
                              ) -> Optional[Dict[str, np.ndarray]]:
    """Extract the per-seed metrics frame from an observation dict
    (the ``m_``-prefixed entries ``DeviceEngine.observe`` adds), or
    ``None`` when the sweep ran metrics-off."""
    per_seed = {k[len(OBS_PREFIX):]: np.asarray(v)
                for k, v in obs.items() if k.startswith(OBS_PREFIX)}
    return per_seed or None


def aggregate_metrics(per_seed: Dict[str, np.ndarray]) -> Dict[str, object]:
    """Fleet-aggregate frame: counters sum over the seed axis; histograms
    stay per-bin lists. JSON-serializable (bench.py ``sim_metrics``)."""
    out: Dict[str, object] = {}
    for k, v in per_seed.items():
        s = np.asarray(v).sum(axis=0)
        out[k] = int(s) if np.ndim(s) == 0 else [int(x) for x in s]
    return out
