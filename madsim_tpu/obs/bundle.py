"""Repro bundles: one JSON file that replays a failure verbatim.

The reference's repro story is a two-line banner (seed + config hash,
`runtime/mod.rs:192-199`) the user must combine with the right binary,
env vars and schedule by hand. A *bundle* captures the whole recipe —
seed, engine/actor config (with a stable hash), fault schedule,
backend/batch knobs, the recorded error — so
``python -m madsim_tpu.obs replay --bundle repro.json`` reproduces the
failure with no archaeology:

- ``kind="device_sweep"``: a failing seed from a device-engine sweep
  (``SweepResult.failing_seeds``); replay re-traces the seed through the
  same actor/config/schedule and exports the timeline.
- ``kind="host_test"``: a failing ``@madsim_tpu.test``; replay
  re-imports the test entry point and re-runs it under the bundle's
  pinned ``MADSIM_TEST_*`` environment, expecting the same error.

``testing.Builder`` writes a host_test bundle automatically on failure
when ``MADSIM_REPRO_DIR`` is set (the banner says where it landed).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Dict, Optional

BUNDLE_VERSION = 1


def _as_plain(obj: Any) -> Any:
    """Config objects → JSON-plain dicts (dataclasses pass through
    ``asdict``; dicts/lists/scalars unchanged)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    return obj


def config_digest(obj: Any) -> str:
    """Stable 16-hex fingerprint of a config dict/dataclass — the
    device-engine analog of ``Config.hash()`` (`config.rs:27-31`)."""
    canon = json.dumps(_as_plain(obj), sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def _write(bundle: Dict[str, Any], path: str, stem: str) -> str:
    if os.path.isdir(path):
        path = os.path.join(path, f"{stem}-{bundle['config_hash']}.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(bundle, f, indent=2, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


def write_sweep_bundle(path: str, *, seed: int, actor: str,
                       actor_config: Any, engine_config: Any,
                       faults: Optional[Any] = None,
                       max_steps: int = 2_000,
                       error: Optional[str] = None,
                       trace_path: Optional[str] = None,
                       minimization: Optional[Dict[str, Any]] = None,
                       lineage: Optional[Dict[str, Any]] = None,
                       extra: Optional[Dict[str, Any]] = None) -> str:
    """Write a device-sweep repro bundle; returns the file path.

    ``path`` may be a directory (a ``repro-seed<seed>-<hash>.json`` name
    is chosen inside it). ``actor`` is a replay-registry name
    (``raft``/``pb``/``tpc``/``pair_restart`` — obs/cli.py); configs are
    the dataclass instances (or plain dicts) the sweep ran with;
    ``faults`` the schedule rows for THIS seed ((F, 4), or None).

    ``minimization`` (triage/minimize.py ``MinimizeResult.provenance()``)
    records how the recorded schedule was shrunk from the one the hunt
    actually swept — rounds, candidates evaluated, original→final row
    counts, weakenings applied (schema
    ``madsim.triage.minimization/1``, docs/triage.md). When present,
    ``faults`` should be the MINIMIZED rows: replay then reproduces the
    failure from the minimal schedule, which is the point.

    ``lineage`` (obs/lineage.py ``lineage_block``, schema
    ``madsim.search.lineage/1``) records a GUIDED find's derivation:
    the ancestry chain from the failing world back to the generation-0
    template — which corpus parents it was spliced from, which mutation
    operators touched it — plus the hunt's per-operator outcome table.
    Rendered by ``python -m madsim_tpu.obs lineage <bundle>``.
    """
    import numpy as np

    acfg = _as_plain(actor_config)
    ecfg = _as_plain(engine_config)
    frows = None if faults is None else np.asarray(faults, np.int32).tolist()
    fault_sha = hashlib.sha256(
        json.dumps(frows).encode()).hexdigest()[:16] if frows else None
    bundle = {
        "version": BUNDLE_VERSION,
        "kind": "device_sweep",
        "seed": int(seed),
        "actor": actor,
        "actor_config": acfg,
        "engine_config": ecfg,
        "config_hash": config_digest({"actor": actor, "actor_config": acfg,
                                      "engine_config": ecfg}),
        "faults": frows,
        "faults_sha256": fault_sha,
        "max_steps": int(max_steps),
        "error": error,
        "trace_path": trace_path,
        "minimization": minimization,
        "lineage": lineage,
        "extra": dict(extra or {}),
    }
    return _write(bundle, path, f"repro-seed{int(seed)}")


def write_test_bundle(path: str, *, seed: int, test_id: Optional[str],
                      test_file: Optional[str] = None,
                      backend: str = "host", batch: Optional[int] = None,
                      config: Optional[Any] = None,
                      config_path: Optional[str] = None,
                      time_limit: Optional[float] = None,
                      error: Optional[str] = None,
                      minimization: Optional[Dict[str, Any]] = None,
                      extra: Optional[Dict[str, Any]] = None) -> str:
    """Write a host-test repro bundle (a failing ``@madsim_tpu.test``);
    returns the file path. ``test_id`` is ``module:qualname`` of the
    decorated test so replay can re-import it (``test_file`` is the
    source-path fallback for tests whose module is not importable by
    name — scripts run as ``__main__``); the ``env`` block is the exact
    ``MADSIM_TEST_*`` environment that reproduces the failure —
    including the backend/batch knobs a bridge-backend failure needs.
    ``minimization`` (testing.py ``MADSIM_MINIMIZE=1``) records the
    fault-model knob minimization: which non-default config rows the
    failure actually needs, with the minimized config dict inside.
    """
    cfg_dict = None
    cfg_hash = None
    if config is not None:
        cfg_dict = config.to_dict() if hasattr(config, "to_dict") \
            else _as_plain(config)
        cfg_hash = config.hash() if hasattr(config, "hash") \
            else config_digest(cfg_dict)
    env = {"MADSIM_TEST_SEED": str(int(seed)), "MADSIM_TEST_NUM": "1",
           "MADSIM_TEST_BACKEND": backend}
    if batch is not None:
        env["MADSIM_TEST_BATCH"] = str(int(batch))
    if config_path:
        env["MADSIM_TEST_CONFIG"] = config_path
    if time_limit is not None:
        env["MADSIM_TEST_TIME_LIMIT"] = str(time_limit)
    bundle = {
        "version": BUNDLE_VERSION,
        "kind": "host_test",
        "seed": int(seed),
        "test": test_id,
        "test_file": test_file,
        "backend": backend,
        "batch": batch,
        "config": cfg_dict,
        "config_hash": cfg_hash or config_digest({"test": test_id,
                                                  "backend": backend}),
        "env": env,
        "error": error,
        "minimization": minimization,
        "extra": dict(extra or {}),
    }
    return _write(bundle, path, f"repro-seed{int(seed)}")


def load_bundle(path: str) -> Dict[str, Any]:
    """Read and validate a bundle written by the writers above."""
    with open(path, encoding="utf-8") as f:
        bundle = json.load(f)
    if bundle.get("version") != BUNDLE_VERSION:
        raise ValueError(
            f"unsupported bundle version {bundle.get('version')!r} "
            f"(this build reads version {BUNDLE_VERSION})")
    if bundle.get("kind") not in ("device_sweep", "host_test"):
        raise ValueError(f"unknown bundle kind {bundle.get('kind')!r}")
    return bundle
