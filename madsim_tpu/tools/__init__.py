"""Build-time tooling (codegen). Import side-effect free."""
