"""Loader for the native (C++) host-engine core.

Builds ``native/madsim_core.cpp`` as a CPython extension module on first use
(one translation unit, no dependencies beyond Python.h — sub-second with the
system g++), caches the .so next to this package, and imports it. The C API
is used rather than ctypes: per-call ctypes marshalling (~µs) costs more
than the kernels themselves.

Everything here is optional: when the toolchain or build is unavailable
(``MADSIM_NATIVE=0`` also forces this) the host engine uses its pure-Python
implementations with identical bit-exact behavior — the native core is an
accelerator, never a semantic fork (tested in tests/test_native.py).
"""
from __future__ import annotations

import importlib.machinery
import importlib.util
import logging
import os
import subprocess
import sysconfig
from pathlib import Path
from typing import Optional

log = logging.getLogger("madsim_tpu.native")

_MOD = None
_TRIED = False

_SRC = Path(__file__).resolve().parent.parent.parent / "native" / "madsim_core.cpp"
_SO = Path(__file__).resolve().parent / "_core.so"


def _build() -> bool:
    if not _SRC.exists():
        return False
    include = sysconfig.get_paths()["include"]
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           f"-I{include}", "-o", str(_SO), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as exc:
        detail = getattr(exc, "stderr", b"")
        log.info("native core build failed (%s %s); using pure-Python host core",
                 exc, detail[-400:] if detail else "")
        return False


def get_lib():
    """The native extension module, building it on first call; None if absent."""
    global _MOD, _TRIED
    if _MOD is not None or _TRIED:
        return _MOD
    _TRIED = True
    if os.environ.get("MADSIM_NATIVE", "1") in ("0", "false", "no"):
        return None
    try:
        if not _SO.exists() or (_SRC.exists()
                                and _SRC.stat().st_mtime > _SO.stat().st_mtime):
            if not _build():
                return None
        loader = importlib.machinery.ExtensionFileLoader(
            "madsim_tpu.native._core", str(_SO))
        spec = importlib.util.spec_from_loader(loader.name, loader)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        _MOD = mod
    except (OSError, ImportError) as exc:
        log.info("native core unavailable (%s); using pure-Python host core", exc)
        _MOD = None
    return _MOD


def available() -> bool:
    return get_lib() is not None


class NativeTimerHeap:
    """Thin wrapper over the extension module's capsule-based timer heap."""

    __slots__ = ("_core", "_heap")

    def __init__(self, core):
        self._core = core
        self._heap = core.heap_new()

    def push(self, deadline_ns: int, seq: int) -> None:
        self._core.heap_push(self._heap, deadline_ns, seq)

    def cancel(self, seq: int) -> None:
        self._core.heap_cancel(self._heap, seq)

    def peek(self) -> Optional[int]:
        return self._core.heap_peek(self._heap)

    def pop_due(self, now_ns: int) -> Optional[int]:
        return self._core.heap_pop_due(self._heap, now_ns)

    def __len__(self) -> int:
        return self._core.heap_len(self._heap)
