"""Real-TCP twins of the sim TcpListener/TcpStream.

The madsim-tokio model for ``net``: outside a simulation the TCP types are
the real thing (`madsim-tokio/src/lib.rs:32-38` re-exports tokio::net) —
here the same bind/accept/connect/read/write_all surface runs over asyncio
streams, so byte-stream code written against :mod:`madsim_tpu.net.tcp`
deploys unchanged with ``MADSIM_BACKEND=real``.
"""
from __future__ import annotations

import asyncio
from typing import Optional, Tuple

from ..net.addr import Addr, AddrLike
from ..net.network import ConnectionReset
from .net import real_lookup


class RealTcpListener:
    def __init__(self):
        self._server: Optional[asyncio.base_events.Server] = None
        self._queue: "asyncio.Queue" = None
        self._addr: Optional[Addr] = None

    @staticmethod
    async def bind(addr: AddrLike) -> "RealTcpListener":
        host, port = await real_lookup(addr)
        lst = RealTcpListener()
        lst._queue = asyncio.Queue()

        async def on_accept(reader, writer):
            await lst._queue.put((reader, writer))

        lst._server = await asyncio.start_server(on_accept, host, port)
        ip, bound_port = lst._server.sockets[0].getsockname()[:2]
        lst._addr = (ip, bound_port)
        return lst

    def local_addr(self) -> Addr:
        return self._addr

    async def accept(self) -> Tuple["RealTcpStream", Addr]:
        if self._server is None:
            raise ConnectionReset("listener closed")
        item = await self._queue.get()
        if item is None:
            # close() sentinel: re-enqueue so every pending/later accept
            # unwinds too (the sim twin's ChannelClosed contract).
            self._queue.put_nowait(None)
            raise ConnectionReset("listener closed")
        reader, writer = item
        peer = writer.get_extra_info("peername")[:2]
        local = writer.get_extra_info("sockname")[:2]
        return RealTcpStream(reader, writer, tuple(local), tuple(peer)), \
            tuple(peer)

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
            # Wake blocked accepts (matching the sim listener, whose close
            # fails the accept with ConnectionReset).
            self._queue.put_nowait(None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class RealTcpStream:
    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, local: Addr, peer: Addr):
        self._reader = reader
        self._writer = writer
        self._local = local
        self._peer = peer
        self._write_buf = bytearray()

    @staticmethod
    async def connect(addr: AddrLike) -> "RealTcpStream":
        host, port = await real_lookup(addr)
        reader, writer = await asyncio.open_connection(host, port)
        local = tuple(writer.get_extra_info("sockname")[:2])
        return RealTcpStream(reader, writer, local, (host, port))

    def local_addr(self) -> Addr:
        return self._local

    def peer_addr(self) -> Addr:
        return self._peer

    # -- reading (sim TcpStream surface) -----------------------------------
    async def read(self, max_bytes: int = 65536) -> bytes:
        try:
            return await self._reader.read(max_bytes)
        except (ConnectionError, OSError) as exc:
            raise ConnectionReset(str(exc)) from exc

    async def read_exact(self, n: int) -> bytes:
        try:
            return await self._reader.readexactly(n)
        except asyncio.IncompleteReadError as exc:
            raise ConnectionReset("unexpected EOF") from exc
        except (ConnectionError, OSError) as exc:
            raise ConnectionReset(str(exc)) from exc

    # -- writing -----------------------------------------------------------
    def write(self, data: bytes) -> None:
        self._write_buf.extend(data)

    async def flush(self) -> None:
        if self._write_buf:
            payload, self._write_buf = bytes(self._write_buf), bytearray()
            try:
                self._writer.write(payload)
                await self._writer.drain()
            except (ConnectionError, OSError) as exc:
                raise ConnectionReset(str(exc)) from exc

    async def write_all(self, data: bytes) -> None:
        self.write(data)
        await self.flush()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
