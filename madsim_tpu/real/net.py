"""Real-transport Endpoint: the tag-matching API over framed TCP.

The production twin of :class:`madsim_tpu.net.endpoint.Endpoint`, modeled on
the reference's std backend (`madsim/src/std/net/tcp.rs:20-324`):

- ``bind`` opens a real TCP listener;
- the *connecting* side sends one handshake frame carrying its own
  listener address, so the acceptor can key the connection by the peer's
  canonical endpoint address (`tcp.rs:79-103`);
- each message is one length-delimited frame ``[len u32][tag u64][fmt u8]
  [payload]`` (big-endian), where fmt 0 = raw bytes, fmt 1 = pickled
  Python object, and fmt 2 = pickle-5 stream with an out-of-band buffer
  table — the analog of the std RPC layer's bincode serialization
  (`std/net/rpc.rs:118-190`); sim mode needs no fmt byte because payloads
  never leave the process;
- received frames land in the same pending-receivers-first tag
  :class:`Mailbox` discipline as the sim endpoint (`tcp.rs:264-302`).

Connections are created lazily on first send and cached per peer
(`tcp.rs:160-183`); a closed connection evicts its cache entry so the next
send reconnects.

The byte path is built for throughput (the reference measures exactly this
with criterion, `madsim/benches/rpc.rs:28-54`): senders emit the header and
payload as separate write buffers (no whole-frame copy), large ``bytes``
inside pickled containers travel as out-of-band pickle-5 buffers (no copy
into the pickle stream), and the receive side is an
:class:`asyncio.BufferedProtocol` whose ``get_buffer`` hands the kernel the
frame section's own buffer for bulk payloads — one copy from socket to
payload storage, with no StreamReader buffer shuffling in between.
"""
from __future__ import annotations

import asyncio
import collections
import os
import pickle
import socket as _socket
import struct
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..net.addr import (Addr, AddrLike, AddrParseError, format_addr,
                        lookup_host, parse_addr)
from ..net.network import BrokenPipe, ConnectionReset, NetworkError


async def real_lookup(addr: AddrLike) -> Addr:
    """Resolve an address for the real backend, including DNS hostnames.

    The sim parser only accepts numeric IPs (no DNS inside a simulation);
    production addresses are names, so fall back to getaddrinfo — the
    `std/net/addr` path resolving through tokio's lookup_host.
    """
    try:
        return (await lookup_host(addr))[0]
    except AddrParseError:
        if isinstance(addr, tuple):
            host, port = addr
        else:
            host, _, port = str(addr).rpartition(":")
        infos = await asyncio.get_running_loop().getaddrinfo(
            host, int(port), type=_socket.SOCK_STREAM)
        if not infos:
            raise OSError(f"cannot resolve {addr!r}") from None
        ip, rport = infos[0][4][:2]
        return (ip, rport)

_HDR = struct.Struct(">I")        # frame length
_TAGFMT = struct.Struct(">QB")    # tag u64 + fmt u8
_OOB_HEAD = struct.Struct(">II")  # buffer count + pickle stream length
FMT_BYTES = 0
FMT_PICKLE = 1
FMT_PICKLE_OOB = 2                # pickle-5 stream + out-of-band buffer table
# Shared-memory bulk leg (MADSIM_REAL_TRANSPORT=shm): control frames ride
# the ordered socket stream; bulk payload bytes live in a per-connection
# ring arena. The analog of the reference's zero-copy transports behind
# the same Endpoint API (`std/net/ucx.rs`, `std/net/erpc.rs`); see
# docs/transports.md for the measured envelope and design limits.
FMT_SHM_HELLO = 3                 # body: the sender's arena segment name
FMT_SHM_ACK = 4                   # body: u64 cumulative consumed cursor
FMT_SHM_REF = 5                   # body: [logical off u64][len u64][fmt u8]
_SHM_REF = struct.Struct(">QQB")
_SHM_ACK = struct.Struct(">Q")
_SHM_MIN = 1 << 15                # payloads >= 32 KiB take the arena path
_MAX_FRAME = 1 << 30
_FRAME_HEAD = _HDR.size + _TAGFMT.size
# Frames whose raw payload (or any hoisted bytes inside a pickled
# container) reaches this size skip the in-band pickle copy and are
# received directly into their own buffer (the zero-copy bulk path).
_OOB_MIN = 1 << 12
_SCRATCH = 1 << 16                # receive scratch for small frame sections
_QUEUE_MAX = 64                   # channel-mode frames parked before pausing
_HS_MAX = 4096                    # handshake size bound


class _Message:
    __slots__ = ("tag", "data", "from_addr")

    def __init__(self, tag: int, data: Any, from_addr: Addr):
        self.tag = tag
        self.data = data
        self.from_addr = from_addr


class _Mailbox:
    """Tag-matched mailbox over asyncio futures (same discipline as the sim
    endpoint's: deliver tries pending receivers first, else buffers)."""

    __slots__ = ("registered", "msgs", "closed")

    def __init__(self):
        self.registered: List[Tuple[int, asyncio.Future]] = []
        self.msgs: List[_Message] = []
        self.closed = False

    def deliver(self, msg: _Message) -> None:
        for i, (tag, fut) in enumerate(self.registered):
            if tag == msg.tag and not fut.done():
                del self.registered[i]
                fut.set_result(msg)
                return
        self.registered = [(t, f) for (t, f) in self.registered if not f.done()]
        self.msgs.append(msg)

    def recv(self, tag: int) -> "asyncio.Future[_Message]":
        fut = asyncio.get_running_loop().create_future()
        if self.closed:
            fut.set_exception(BrokenPipe("endpoint closed"))
            return fut
        for i, msg in enumerate(self.msgs):
            if msg.tag == tag:
                del self.msgs[i]
                fut.set_result(msg)
                return fut
        self.registered.append((tag, fut))
        return fut

    def unregister(self, fut: asyncio.Future) -> None:
        self.registered = [(t, f) for (t, f) in self.registered if f is not fut]

    def requeue_front(self, msg: _Message) -> None:
        self.msgs.insert(0, msg)

    def close(self) -> None:
        self.closed = True
        for _, fut in self.registered:
            if not fut.done():
                fut.set_exception(BrokenPipe("endpoint closed"))
        self.registered.clear()


# ---------------------------------------------------------------------------
# Frame encoding
# ---------------------------------------------------------------------------

def _hoist(obj: Any, sink: list, depth: int = 2) -> Any:
    """Replace large immutable ``bytes`` inside (nested) tuples/lists with
    :class:`pickle.PickleBuffer` so they serialize out-of-band — no copy
    into the pickle stream. Only exact tuples/lists are walked (a subclass
    may have invariants) and only immutable bytes are hoisted (the
    transport may hold the view past return, so writable buffers keep the
    in-band copy). ``sink`` records whether anything was hoisted."""
    t = type(obj)
    if t is bytes and len(obj) >= _OOB_MIN:
        sink.append(obj)
        return pickle.PickleBuffer(obj)
    if depth and (t is tuple or t is list):
        out = [_hoist(v, sink, depth - 1) for v in obj]
        if any(a is not b for a, b in zip(out, obj)):
            return t(out)
    return obj


def _encode_frames(tag: int, data: Any) -> List[Any]:
    """Encode one message as a list of write buffers (header first).

    Large payloads stay as views over the caller's bytes — the copy into
    one contiguous frame was the round-3 large-payload bottleneck."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        if not isinstance(data, bytes):
            data = bytes(data)  # writable: snapshot before the socket sees it
        head = _TAGFMT.pack(tag, FMT_BYTES)
        if len(data) < _OOB_MIN:
            return [_HDR.pack(len(head) + len(data)) + head + data]
        return [_HDR.pack(len(head) + len(data)) + head, data]
    sink: list = []
    hoisted = _hoist(data, sink)
    if not sink:
        body = _TAGFMT.pack(tag, FMT_PICKLE) + pickle.dumps(data)
        return [_HDR.pack(len(body)) + body]
    bufs: List[pickle.PickleBuffer] = []
    stream = pickle.dumps(hoisted, protocol=5, buffer_callback=bufs.append)
    raws = [b.raw() for b in bufs]
    table = struct.pack(f">II{len(raws)}I", len(raws), len(stream),
                        *[r.nbytes for r in raws])
    n = _TAGFMT.size + len(table) + len(stream) + sum(r.nbytes for r in raws)
    return [_HDR.pack(n) + _TAGFMT.pack(tag, FMT_PICKLE_OOB) + table + stream,
            *raws]


def _encode_frames_for(proto: Optional["_FrameProtocol"], tag: int,
                       data: Any) -> List[Any]:
    """Per-connection encoder: on shm-enabled connections, payloads >=
    _SHM_MIN are copied once into the connection's ring arena and the wire
    carries a tiny (offset, length, fmt) reference; everything else (and
    any arena-full condition) takes the plain inline path — the fallback
    keeps the stream correct under any backpressure."""
    if proto is None or not proto.shm_enabled:
        return _encode_frames(tag, data)

    # The one-time HELLO (arena name + logical ring size) must precede
    # whatever this call emits — INCLUDING an inline fallback, or a later
    # in-range bulk send would emit a REF the receiver cannot resolve.
    hello: List[Any] = []

    def arena():
        if proto.shm_tx is None:
            proto.shm_tx = _ShmArena(_shm_arena_size())
            text = f"{proto.shm_tx.name}:{proto.shm_tx.size}".encode()
            hello.append(_HDR.pack(_TAGFMT.size + len(text))
                         + _TAGFMT.pack(0, FMT_SHM_HELLO) + text)
        return proto.shm_tx

    def ref_frame(off: int, n: int, ofmt: int) -> List[Any]:
        body = _SHM_REF.pack(off, n, ofmt)
        return hello + [_HDR.pack(_TAGFMT.size + len(body))
                        + _TAGFMT.pack(tag, FMT_SHM_REF) + body]

    if isinstance(data, (bytes, bytearray, memoryview)):
        raw = data if isinstance(data, (bytes, bytearray)) else bytes(data)
        if len(raw) >= _SHM_MIN:
            slot = arena().alloc(len(raw))
            if slot is not None:
                off, dst = slot
                dst[:] = raw
                del dst
                return ref_frame(off, len(raw), FMT_BYTES)
        return hello + _encode_frames(tag, data)

    sink: list = []
    hoisted = _hoist(data, sink)
    if not sink:
        blob = pickle.dumps(data)
        if len(blob) >= _SHM_MIN:
            slot = arena().alloc(len(blob))
            if slot is not None:
                off, dst = slot
                dst[:] = blob
                del dst
                return ref_frame(off, len(blob), FMT_PICKLE)
        return hello + _encode_frames(tag, data)
    bufs: List[pickle.PickleBuffer] = []
    stream = pickle.dumps(hoisted, protocol=5, buffer_callback=bufs.append)
    raws = [b.raw() for b in bufs]
    table = struct.pack(f">II{len(raws)}I", len(raws), len(stream),
                        *[r.nbytes for r in raws])
    total = len(table) + len(stream) + sum(r.nbytes for r in raws)
    if total >= _SHM_MIN:
        slot = arena().alloc(total)
        if slot is not None:
            off, dst = slot
            pos = 0
            for part in (table, stream, *raws):
                n = len(part) if not isinstance(part, memoryview) \
                    else part.nbytes
                dst[pos:pos + n] = part
                pos += n
            del dst
            return ref_frame(off, total, FMT_PICKLE_OOB)
    n = _TAGFMT.size + total
    return hello + [
        _HDR.pack(n) + _TAGFMT.pack(tag, FMT_PICKLE_OOB) + table + stream,
        *raws]


def _write_frames(transport: asyncio.Transport, frames: List[Any]) -> None:
    if len(frames) == 1:
        transport.write(frames[0])
    else:
        # Header + payload views; the transport scatter-gathers. Joining
        # here would reintroduce the full-frame copy.
        for f in frames:
            transport.write(f)


class _FrameError(Exception):
    """Malformed frame: the byte stream is desynced beyond recovery."""


# ---------------------------------------------------------------------------
# The connection protocol
# ---------------------------------------------------------------------------

# Parser phases. Handshake (server-accepted connections only) → frame head
# → payload sections. OOB frames read their pickle stream and each
# out-of-band buffer into separate buffers, so the buffers emerge as the
# exact ``bytes`` objects pickle splices back into the decoded message.
_PH_HS_HEAD = 0
_PH_HS_BODY = 1
_PH_HEAD = 2
_PH_BODY = 3
_PH_OOB_HEAD = 4
_PH_OOB_TABLE = 5
_PH_OOB_STREAM = 6
_PH_OOB_BUF = 7
_BULK_PHASES = (_PH_BODY, _PH_OOB_BUF, _PH_OOB_STREAM)

_EOFMARK = object()   # parsed-stream terminator (EOF / connection lost)


def _shm_arena_size() -> int:
    return int(os.environ.get("MADSIM_SHM_ARENA", str(32 << 20)))


class _ShmArena:
    """Sender-side bulk ring: one shared-memory segment per connection
    direction, bump-allocated with logical (monotone u64) cursors. The
    receiver acks the logical end of each consumed block over the socket
    stream; blocks are never overwritten before their ack. A full arena
    is not an error — the caller falls back to the inline socket path."""

    __slots__ = ("size", "seg", "head", "tail")

    def __init__(self, size: int):
        from multiprocessing import shared_memory

        self.size = size
        self.seg = shared_memory.SharedMemory(create=True, size=size)
        self.head = 0  # logical write cursor
        self.tail = 0  # logical acked cursor

    @property
    def name(self) -> str:
        return self.seg.name

    def alloc(self, n: int):
        """Reserve n contiguous bytes → (logical_off, memoryview) or None.

        Blocks never wrap: if the physical tail fragment is too small the
        cursor pads past it (the pad is freed by any later ack)."""
        if n > self.size:
            return None
        head = self.head
        phys = head % self.size
        if phys + n > self.size:
            head += self.size - phys  # pad to the segment start
            phys = 0
        if head + n - self.tail > self.size:
            return None  # would overwrite un-acked bytes
        self.head = head + n
        return head, self.seg.buf[phys:phys + n]

    def ack(self, cursor: int) -> None:
        if cursor > self.tail:
            self.tail = cursor

    def close(self) -> None:
        try:
            self.seg.close()
        except (OSError, BufferError):
            pass
        try:
            self.seg.unlink()
        except (OSError, FileNotFoundError):
            pass


def _decode_oob_body(mv) -> Any:
    """Decode a contiguous FMT_PICKLE_OOB body ([table][stream][buffers])
    — the arena path's one-shot twin of the incremental wire parser."""
    nbufs, slen = _OOB_HEAD.unpack_from(mv)
    lens = struct.unpack_from(f">{nbufs}I", mv, _OOB_HEAD.size)
    off = _OOB_HEAD.size + 4 * nbufs
    stream = bytes(mv[off:off + slen])
    off += slen
    bufs = []
    for n in lens:
        bufs.append(bytes(mv[off:off + n]))
        off += n
    return pickle.loads(stream, buffers=bufs)


class _FrameProtocol(asyncio.BufferedProtocol):
    """One per connection: incremental frame parser + write flow control.

    Frames are surfaced either by push (``sink`` set → endpoint mailbox)
    or pull (``next_frame`` with a bounded parking queue and transport
    read-pause — the channel mode). ``expect_handshake`` makes the first
    bytes a ``[len u32][text]`` handshake line, reported via
    ``on_handshake`` (the server side's routing hook)."""

    def __init__(self, expect_handshake: bool = False,
                 on_handshake: Optional[Callable[["_FrameProtocol", str], None]] = None,
                 peer: Optional[Addr] = None):
        self.transport: Optional[asyncio.Transport] = None
        self.peer = peer
        self.sink: Optional[Callable[[int, Any, Addr], None]] = None
        self.on_lost: Optional[Callable[["_FrameProtocol"], None]] = None
        self._on_handshake = on_handshake
        self._queue: Deque[Any] = collections.deque()
        self._waiter: Optional[asyncio.Future] = None
        self._paused_reading = False
        self._closed = False          # connection_lost seen (or torn down)
        self._eof = False             # orderly EOF from the peer
        # -- write flow control (FlowControlMixin analog) --
        self._send_paused = False
        self._drain_waiters: List[asyncio.Future] = []
        # -- parse state --
        self._scratch = bytearray(_SCRATCH)
        self._scratch_mv = memoryview(self._scratch)
        self._direct = False
        self._phase = _PH_HS_HEAD if expect_handshake else _PH_HEAD
        self._target = bytearray(4 if expect_handshake else _FRAME_HEAD)
        self._fill = 0
        self._tag = 0
        self._fmt = 0
        self._lens: Tuple[int, ...] = ()
        self._stream: Optional[bytearray] = None
        self._bufs: List[bytearray] = []
        # -- shared-memory bulk leg (ShmEndpoint connections) --
        self.shm_enabled = False
        self.shm_tx: Optional[_ShmArena] = None   # our outgoing arena
        self.shm_rx = None                        # peer's attached segment
        self._shm_rx_size = 0                     # peer's LOGICAL ring size
        self._write_shut = False                  # write_eof sent (half-close)

    # -- transport callbacks ----------------------------------------------
    def connection_made(self, transport) -> None:
        self.transport = transport
        # A 1 MiB payload should not bounce the writer on the default
        # 64 KiB high-water mark several times per frame.
        transport.set_write_buffer_limits(high=1 << 21)
        # Default kernel socket buffers (~208 KiB) force a 1 MiB frame
        # through many partial send/recv cycles; size them to a frame.
        sock = transport.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, 1 << 22)
                sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 1 << 22)
            except OSError:
                pass

    def connection_lost(self, exc) -> None:
        self._closed = True
        if self.shm_tx is not None:
            self.shm_tx.close()
            self.shm_tx = None
        if self.shm_rx is not None:
            try:
                self.shm_rx.close()
            except (OSError, BufferError):
                pass
            self.shm_rx = None
        self._emit_eof()
        for w in self._drain_waiters:
            if not w.done():
                w.set_exception(ConnectionReset("connection lost"))
        self._drain_waiters.clear()
        if self.on_lost is not None:
            self.on_lost(self)

    def eof_received(self) -> bool:
        self._eof = True
        self._emit_eof()
        if self.sink is not None:
            # Mailbox-mode connection: peer EOF means the peer endpoint is
            # gone — tear down now so the cached sender is evicted and the
            # next send reconnects (`tcp.rs:144-150`).
            self._closed = True
            if self.on_lost is not None:
                self.on_lost(self)
            return False  # close the transport
        return True  # channel: keep the write direction open (half-close)

    def pause_writing(self) -> None:
        self._send_paused = True

    def resume_writing(self) -> None:
        self._send_paused = False
        for w in self._drain_waiters:
            if not w.done():
                w.set_result(None)
        self._drain_waiters.clear()

    async def drain(self) -> None:
        if self._closed:
            raise ConnectionReset("connection lost")
        if not self._send_paused:
            return
        fut = asyncio.get_running_loop().create_future()
        self._drain_waiters.append(fut)
        await fut

    # -- receive path ------------------------------------------------------
    def get_buffer(self, sizehint: int):
        if self._direct:
            return memoryview(self._target)[self._fill:]
        return self._scratch_mv

    def buffer_updated(self, nbytes: int) -> None:
        if self._direct:
            self._fill += nbytes
            if self._fill == len(self._target):
                self._direct = False
                try:
                    self._advance_sections()
                except _FrameError:
                    self._protocol_error()
            return
        data = self._scratch_mv[:nbytes]
        off = 0
        try:
            while off < nbytes and not self._closed:
                take = min(len(self._target) - self._fill, nbytes - off)
                self._target[self._fill:self._fill + take] = data[off:off + take]
                self._fill += take
                off += take
                if self._fill == len(self._target):
                    self._advance_sections()
        except _FrameError:
            self._protocol_error()
            return
        # Scratch fully consumed: a large in-flight section can now take
        # socket reads directly into its own buffer.
        if (not self._closed and self._phase in _BULK_PHASES
                and len(self._target) - self._fill >= _OOB_MIN):
            self._direct = True

    def _protocol_error(self) -> None:
        self._closed = True
        self._emit_eof()
        if self.transport is not None:
            self.transport.close()

    def _advance_sections(self) -> None:
        """Complete the filled section, then any zero-size sections it
        begins: those are already "full" with no bytes to arrive, so waiting
        for the next read would stall a complete message in the parser
        (e.g. a frame whose last out-of-band buffer is 0 bytes)."""
        self._section_done()
        while not self._closed and self._fill == len(self._target):
            self._section_done()

    def _section_done(self) -> None:
        phase = self._phase
        if phase == _PH_HS_HEAD:
            (n,) = _HDR.unpack_from(self._target)
            if not 0 < n <= _HS_MAX:
                raise _FrameError("bad handshake")
            self._begin(_PH_HS_BODY, n)
            return
        if phase == _PH_HS_BODY:
            try:
                text = bytes(self._target).decode()
            except UnicodeDecodeError:
                raise _FrameError("bad handshake") from None
            self._begin(_PH_HEAD, _FRAME_HEAD)
            if self._on_handshake is not None:
                self._on_handshake(self, text)
            return
        if phase == _PH_HEAD:
            (n,) = _HDR.unpack_from(self._target)
            tag, fmt = _TAGFMT.unpack_from(self._target, _HDR.size)
            if n < _TAGFMT.size or n > _MAX_FRAME:
                raise _FrameError(f"bad frame length {n}")
            self._tag, self._fmt = tag, fmt
            body = n - _TAGFMT.size
            if fmt == FMT_PICKLE_OOB:
                if body < _OOB_HEAD.size:
                    raise _FrameError("truncated buffer table")
                self._lens = (body,)  # remaining frame bytes, re-split below
                self._begin(_PH_OOB_HEAD, _OOB_HEAD.size)
            elif body == 0:
                self._emit(tag, b"" if fmt == FMT_BYTES else None)
                self._begin(_PH_HEAD, _FRAME_HEAD)
            else:
                self._begin(_PH_BODY, body)
        elif phase == _PH_BODY:
            target = self._target
            if self._fmt == FMT_PICKLE:
                self._emit(self._tag, pickle.loads(target))
            elif self._fmt == FMT_SHM_HELLO:
                from multiprocessing import shared_memory

                name, _, size = bytes(target).decode().rpartition(":")
                # The LOGICAL ring size travels in the hello: the mapped
                # segment may be page-rounded, and both sides must wrap
                # cursors at the same modulus.
                self.shm_rx = shared_memory.SharedMemory(name=name)
                self._shm_rx_size = int(size)
            elif self._fmt == FMT_SHM_ACK:
                (cursor,) = _SHM_ACK.unpack_from(target)
                if self.shm_tx is not None:
                    self.shm_tx.ack(cursor)
            elif self._fmt == FMT_SHM_REF:
                self._emit_shm_ref(target)
            else:
                self._emit(self._tag, bytes(target))
            self._begin(_PH_HEAD, _FRAME_HEAD)
        elif phase == _PH_OOB_HEAD:
            nbufs, slen = _OOB_HEAD.unpack_from(self._target)
            rest = self._lens[0] - _OOB_HEAD.size
            if nbufs == 0 or 4 * nbufs + slen > rest:
                raise _FrameError(f"bad buffer table ({nbufs} buffers)")
            self._lens = (rest, slen)
            self._begin(_PH_OOB_TABLE, 4 * nbufs)
        elif phase == _PH_OOB_TABLE:
            nbufs = len(self._target) // 4
            rest, slen = self._lens
            lens = struct.unpack(f">{nbufs}I", self._target)
            # Zero-length entries are legitimate: pickle's buffer_callback
            # collects every out-of-band PickleBuffer the payload emits
            # (an empty numpy array yields a 0-byte one). _advance_sections
            # finalizes zero-size sections eagerly so a frame ending on one
            # cannot stall complete in the parser.
            if 4 * nbufs + slen + sum(lens) != rest:
                raise _FrameError("frame length / buffer table mismatch")
            self._lens = lens
            self._bufs = []
            self._begin(_PH_OOB_STREAM, slen)
        elif phase == _PH_OOB_STREAM:
            self._stream = self._target
            self._begin(_PH_OOB_BUF, self._lens[0])
        else:  # _PH_OOB_BUF
            self._bufs.append(self._target)
            if len(self._bufs) < len(self._lens):
                self._begin(_PH_OOB_BUF, self._lens[len(self._bufs)])
            else:
                data = pickle.loads(self._stream,
                                    buffers=[bytes(b) for b in self._bufs])
                self._stream = None
                self._bufs = []
                self._emit(self._tag, data)
                self._begin(_PH_HEAD, _FRAME_HEAD)

    def _begin(self, phase: int, size: int) -> None:
        self._phase = phase
        self._target = bytearray(size)
        self._fill = 0

    def _emit_shm_ref(self, body) -> None:
        """A bulk message whose bytes live in the peer's arena: copy out,
        decode by the original fmt, ack the logical cursor so the sender
        can reuse the space."""
        off, n, ofmt = _SHM_REF.unpack_from(body)
        if self.shm_rx is None:
            raise _FrameError("shm ref before hello")
        size = self._shm_rx_size
        phys = off % size
        if n > size or phys + n > size:
            raise _FrameError("shm ref out of bounds")
        view = self.shm_rx.buf[phys:phys + n]
        if ofmt == FMT_BYTES:
            data = bytes(view)
        elif ofmt == FMT_PICKLE:
            data = pickle.loads(view)
        elif ofmt == FMT_PICKLE_OOB:
            data = _decode_oob_body(view)
        else:
            raise _FrameError(f"bad shm inner fmt {ofmt}")
        del view
        # Ack AFTER the copy-out: the sender may reuse the block the
        # moment this cursor lands. Written directly on the transport —
        # frames are written without awaits in between, so an ack can
        # never interleave mid-frame. A half-closed write side
        # (_write_shut: write_eof sent) cannot ack; the peer's ring then
        # fills and degrades to the inline path, which stays correct.
        if self.transport is not None and not self._closed \
                and not self._write_shut:
            ack = _SHM_ACK.pack(off + n)
            self.transport.write(
                _HDR.pack(_TAGFMT.size + len(ack))
                + _TAGFMT.pack(0, FMT_SHM_ACK) + ack)
        self._emit(self._tag, data)

    # -- frame consumers ---------------------------------------------------
    def _emit(self, tag: int, data: Any) -> None:
        if self.sink is not None:
            self.sink(tag, data, self.peer)
            return
        self._queue.append((tag, data))
        self._wake()
        if (len(self._queue) > _QUEUE_MAX and not self._paused_reading
                and self.transport is not None):
            self._paused_reading = True
            try:
                self.transport.pause_reading()
            except RuntimeError:
                self._paused_reading = False

    def _emit_eof(self) -> None:
        self._queue.append(_EOFMARK)
        self._wake()

    def _wake(self) -> None:
        if self._waiter is not None and not self._waiter.done():
            self._waiter.set_result(None)
            self._waiter = None

    def set_sink(self, sink: Callable[[int, Any, Addr], None]) -> None:
        """Switch to push mode, draining anything parked in the queue."""
        while self._queue:
            item = self._queue.popleft()
            if item is not _EOFMARK:
                sink(item[0], item[1], self.peer)
        self.sink = sink
        self._resume()

    async def next_frame(self):
        """Pull mode: the next (tag, data), or ``_EOFMARK`` at EOF."""
        while not self._queue:
            if self._closed or self._eof:
                return _EOFMARK
            if self._waiter is None or self._waiter.done():
                self._waiter = asyncio.get_running_loop().create_future()
            await self._waiter
        item = self._queue.popleft()
        if item is _EOFMARK:
            self._queue.appendleft(item)  # EOF is sticky
            return _EOFMARK
        if len(self._queue) <= _QUEUE_MAX // 2:
            self._resume()
        return item

    def _resume(self) -> None:
        if self._paused_reading and self.transport is not None:
            self._paused_reading = False
            try:
                self.transport.resume_reading()
            except RuntimeError:
                pass

    def close(self) -> None:
        self._closed = True
        if self.transport is not None:
            self.transport.close()


class _Conn:
    __slots__ = ("transport", "proto", "lock")

    def __init__(self, transport: asyncio.Transport, proto: _FrameProtocol):
        self.transport = transport
        self.proto = proto
        self.lock = asyncio.Lock()  # frames must not interleave


class RealChannelSender:
    """Sending half of a real ``connect1`` channel (one dedicated framed
    connection). ``close()`` shuts down the write direction only, so the
    peer's receiver sees EOF while this side can keep reading — matching
    the sim channel halves' independent-close semantics."""

    __slots__ = ("_transport", "_proto", "_lock")

    def __init__(self, transport: asyncio.Transport, proto: _FrameProtocol):
        self._transport = transport
        self._proto = proto
        self._lock = asyncio.Lock()

    async def send(self, payload) -> None:
        try:
            async with self._lock:
                # Checked under the lock (a sender queued behind the lock
                # must re-observe transport state). is_closing() covers the
                # window between transport.close() and connection_lost
                # delivery: a write there is silently dropped while drain()
                # reports success, violating the sim's closed-send
                # semantics (ConnectionReset).
                if self._proto._closed or self._transport.is_closing():
                    raise ConnectionReset("connection reset")
                _write_frames(self._transport,
                              _encode_frames_for(self._proto, 0, payload))
                await self._proto.drain()
        except (ConnectionError, OSError, RuntimeError):
            # RuntimeError: write after write_eof/close — the sim raises
            # ConnectionReset for sends on a closed channel; match it.
            raise ConnectionReset("connection reset") from None

    def close(self) -> None:
        try:
            if self._transport.can_write_eof():
                self._proto._write_shut = True
                self._transport.write_eof()
            else:
                self._transport.close()
        except (ConnectionError, OSError, RuntimeError):
            pass


class RealChannelReceiver:
    """Receiving half of a real ``connect1`` channel: reads frames on
    demand; EOF or a broken socket surfaces like the sim's closed
    channel."""

    __slots__ = ("_proto",)

    def __init__(self, proto: _FrameProtocol):
        self._proto = proto

    async def recv(self):
        item = await self._proto.next_frame()
        if item is _EOFMARK:
            raise ConnectionReset("connection reset")
        return item[1]

    async def recv_or_eof(self):
        """Like recv but returns None at EOF (for stream adapters)."""
        item = await self._proto.next_frame()
        return None if item is _EOFMARK else item[1]

    def close(self) -> None:
        self._proto.close()  # tears down the whole connection


_CLOSED = object()  # accept1 wake-up sentinel after endpoint close


class RealEndpoint:
    """Bindable, tag-matching endpoint over real TCP."""

    def __init__(self):
        self._server: Optional[asyncio.base_events.Server] = None
        self._addr: Optional[Addr] = None
        self._bound_wildcard = False
        self._conns: Dict[Addr, "asyncio.Future[_Conn]"] = {}
        self._mailbox = _Mailbox()
        self._protos: List[_FrameProtocol] = []
        self._peer: Optional[Addr] = None
        self._closed = False
        # Inbound connect1 channels park here until accept1 takes them.
        self._chan_queue: "asyncio.Queue" = asyncio.Queue()

    # -- constructors ------------------------------------------------------
    @classmethod
    async def bind(cls, addr: AddrLike) -> "RealEndpoint":
        host, port = await real_lookup(addr)
        ep = cls()
        await ep._listen(host, port)
        return ep

    @classmethod
    async def connect(cls, addr: AddrLike) -> "RealEndpoint":
        peer = await real_lookup(addr)
        ep = await cls.bind("0.0.0.0:0")
        ep._peer = peer
        return ep

    # -- transport hooks (overridden by alternative wire transports) -------
    def _server_proto(self) -> _FrameProtocol:
        proto = _FrameProtocol(expect_handshake=True,
                               on_handshake=self._route_inbound)
        self._track(proto)
        return proto

    async def _listen(self, host: str, port: int) -> None:
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(self._server_proto, host, port)
        sock = self._server.sockets[0]
        ip, bound_port = sock.getsockname()[:2]
        # A wildcard bind IP is not a routable peer-facing address:
        # local_addr() reports loopback (usable in-process), and each
        # outgoing handshake advertises that connection's interface IP.
        self._bound_wildcard = ip in ("0.0.0.0", "::")
        self._addr = ("127.0.0.1" if self._bound_wildcard else ip, bound_port)

    async def _dial(self, dst: Addr,
                    peer: Optional[Addr] = None
                    ) -> Tuple[asyncio.Transport, _FrameProtocol]:
        loop = asyncio.get_running_loop()
        transport, proto = await loop.create_connection(
            lambda: _FrameProtocol(peer=peer if peer is not None else dst),
            dst[0], dst[1])
        self._track(proto)
        return transport, proto

    def _advertised_addr(self, transport: asyncio.Transport) -> str:
        # Advertise the address the peer can reach our listener at. For a
        # wildcard bind the bound IP is not routable, so use this
        # connection's local interface IP — loopback for loopback peers,
        # the NIC address cross-host.
        adv_ip = self._addr[0]
        if self._bound_wildcard:
            adv_ip = transport.get_extra_info("sockname")[0]
        return format_addr((adv_ip, self._addr[1]))

    def _track(self, proto: _FrameProtocol) -> None:
        self._protos.append(proto)
        if len(self._protos) > 32:
            self._protos = [p for p in self._protos if not p._closed]

    def _untrack(self, proto: _FrameProtocol) -> None:
        self._protos = [p for p in self._protos if p is not proto]

    # -- introspection -----------------------------------------------------
    def local_addr(self) -> Addr:
        return self._addr

    def peer_addr(self) -> Addr:
        if self._peer is None:
            raise NetworkError("not connected")
        return self._peer

    # -- connection management --------------------------------------------
    def _route_inbound(self, proto: _FrameProtocol, text: str) -> None:
        """Handshake received on a server-accepted connection: key it by
        the peer's canonical listener address (`tcp.rs:87-96`), or park it
        as a connect1 channel when marked ``chan:``."""
        try:
            is_chan = text.startswith("chan:")
            peer = parse_addr(text[5:] if is_chan else text)
        except (AddrParseError, ValueError):
            # ValueError: parse_addr raises it bare for a non-numeric port.
            proto.close()
            return
        proto.peer = peer
        if self._closed:
            proto.close()
            return
        if is_chan:
            self._untrack(proto)  # channels outlive the endpoint (sim parity)
            self._chan_queue.put_nowait(
                (RealChannelSender(proto.transport, proto),
                 RealChannelReceiver(proto), peer))
            return
        proto.on_lost = lambda p: self._evict(peer, p)
        prev = self._conns.get(peer)
        if prev is not None and not prev.done():
            # Simultaneous connect: our own outbound connect to this peer
            # is mid-handshake. Don't displace its pending future (waiters
            # already hold it — overwriting would split senders across two
            # sockets and orphan one); this inbound socket still feeds the
            # mailbox so the peer's traffic is received.
            proto.set_sink(self._deliver)
            return
        fut = asyncio.get_running_loop().create_future()
        fut.set_result(_Conn(proto.transport, proto))
        self._conns[peer] = fut
        if prev is not None and prev.done() and prev.exception() is None:
            # A stale duplicate connection loses to the fresh one
            # (`tcp.rs:99-101` warns on duplicates); close it so its fd
            # doesn't leak.
            prev.result().proto.close()
        proto.set_sink(self._deliver)

    def _deliver(self, tag: int, data: Any, peer: Addr) -> None:
        self._mailbox.deliver(_Message(tag, data, peer))

    def _evict(self, peer: Addr, proto: _FrameProtocol) -> None:
        # Closed by remote: drop the cached sender so later sends
        # reconnect (`tcp.rs:144-150`) — but only if the cache still
        # points at THIS connection; a newer one must not be evicted
        # by a stale teardown.
        cached = self._conns.get(peer)
        if (cached is not None and cached.done()
                and cached.exception() is None
                and cached.result().proto is proto):
            self._conns.pop(peer, None)

    async def _get_or_connect(self, dst: Addr) -> _Conn:
        fut = self._conns.get(dst)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._conns[dst] = fut
            try:
                transport, proto = await self._dial(dst)
            except BaseException as exc:
                # Cancellation (or any failure) must not leave a forever-
                # pending future cached: later senders would await it and
                # hang. Evict and fail it before propagating.
                if self._conns.get(dst) is fut:
                    self._conns.pop(dst, None)
                if not fut.done():
                    fut.set_exception(
                        exc if isinstance(exc, (ConnectionError, OSError))
                        else BrokenPipe(f"connect cancelled: {exc!r}"))
                    fut.exception()  # mark retrieved: no waiter may exist
                raise
            try:
                # Handshake: advertise our listener's canonical address.
                text = self._advertised_addr(transport).encode()
                transport.write(_HDR.pack(len(text)) + text)
                proto.set_sink(self._deliver)
                proto.on_lost = lambda p: self._evict(dst, p)
                if proto._closed:
                    raise BrokenPipe("connection lost during handshake")
                fut.set_result(_Conn(transport, proto))
            except BaseException as exc:
                if self._conns.get(dst) is fut:
                    self._conns.pop(dst, None)
                if not fut.done():
                    fut.set_exception(
                        exc if isinstance(exc, (ConnectionError, OSError))
                        else BrokenPipe(f"handshake failed: {exc!r}"))
                    fut.exception()  # mark retrieved: no waiter may exist
                proto.close()
                raise
        return await asyncio.shield(fut)

    # -- datagram path -----------------------------------------------------
    async def send_to(self, dst: AddrLike, tag: int, data: Any) -> None:
        await self.send_to_raw(await real_lookup(dst), tag, data)

    async def send_to_raw(self, dst: Addr, tag: int, data: Any) -> None:
        if self._closed:
            raise BrokenPipe("endpoint closed")
        conn = await self._get_or_connect(dst)
        async with conn.lock:
            # Checked under the lock: a sender queued behind an in-flight
            # send must re-observe the transport state, and is_closing()
            # covers the window between a fatal close and connection_lost
            # where writes are silently discarded while _closed is False.
            if conn.proto._closed or conn.transport.is_closing():
                raise ConnectionReset("connection reset")
            # Encoded under the lock: the shm leg's encoder allocates from
            # the connection's arena and may prepend its one-time HELLO
            # frame, which must hit the wire before any REF that uses it
            # (a no-op for tcp/uds connections).
            _write_frames(conn.transport,
                          _encode_frames_for(conn.proto, tag, data))
            await conn.proto.drain()

    async def recv_from(self, tag: int) -> Tuple[Any, Addr]:
        return await self.recv_from_raw(tag)

    async def recv_from_raw(self, tag: int,
                            timeout: Optional[float] = None) -> Tuple[Any, Addr]:
        fut = self._mailbox.recv(tag)
        try:
            if timeout is not None:
                msg = await asyncio.wait_for(asyncio.shield(fut), timeout)
            else:
                msg = await fut
        except asyncio.TimeoutError:
            if fut.done() and fut.exception() is None:
                self._mailbox.requeue_front(fut.result())
            else:
                fut.cancel()
                self._mailbox.unregister(fut)
            raise TimeoutError() from None
        except asyncio.CancelledError:
            if fut.done() and fut.exception() is None:
                self._mailbox.requeue_front(fut.result())
            else:
                self._mailbox.unregister(fut)
            raise
        return msg.data, msg.from_addr

    # -- connection-oriented path (sim connect1/accept1 twins) -------------
    async def connect1(self, addr: AddrLike):
        """Open a dedicated ordered duplex channel to a peer's endpoint
        (the sim ``connect1`` twin): returns (sender, receiver)."""
        dst = await real_lookup(addr)
        transport, proto = await self._dial(dst)
        try:
            text = f"chan:{self._advertised_addr(transport)}".encode()
            transport.write(_HDR.pack(len(text)) + text)
        except (ConnectionError, OSError):
            proto.close()
            raise ConnectionReset("connection reset") from None
        self._untrack(proto)  # channels outlive the endpoint (sim parity)
        return RealChannelSender(transport, proto), RealChannelReceiver(proto)

    async def accept1(self):
        """Await an inbound channel: returns (sender, receiver, peer).
        Raises :class:`ConnectionReset` once the endpoint closes — the
        sim accept1's closed-endpoint behavior."""
        if self._closed:
            raise ConnectionReset("endpoint closed")
        item = await self._chan_queue.get()
        if item is _CLOSED:
            self._chan_queue.put_nowait(_CLOSED)  # wake further waiters
            raise ConnectionReset("endpoint closed")
        return item

    async def send(self, tag: int, data: Any) -> None:
        await self.send_to(self.peer_addr(), tag, data)

    async def recv(self, tag: int) -> Any:
        peer = self.peer_addr()
        data, from_addr = await self.recv_from(tag)
        if from_addr != peer:
            raise NetworkError("received a message not from the connected address")
        return data

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._server is not None:
            self._server.close()
        for fut in self._conns.values():
            if fut.done() and fut.exception() is None:
                fut.result().proto.close()
        self._conns.clear()
        for proto in self._protos:
            proto.close()
        self._mailbox.close()
        # Tear down parked inbound channels and wake accept1 waiters.
        while not self._chan_queue.empty():
            item = self._chan_queue.get_nowait()
            if item is not _CLOSED:
                item[1].close()
        self._chan_queue.put_nowait(_CLOSED)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class UdsEndpoint(RealEndpoint):
    """The same framed tag protocol over Unix-domain sockets.

    The analog of the reference's feature-selected alternative wire
    transports behind one Endpoint API (UCX `std/net/ucx.rs`, eRPC
    `std/net/erpc.rs`, chosen by Cargo feature): here the transport is
    chosen by ``MADSIM_REAL_TRANSPORT=uds``, for same-host deployments
    that want filesystem-scoped addressing and permissions instead of the
    shared TCP port namespace (latency is comparable to loopback TCP —
    bench.py measures both). Addresses stay virtual
    ``(ip, port)`` pairs — each maps to one socket file under
    ``MADSIM_UDS_DIR`` (default ``$TMPDIR/madsim-uds-<uid>``) so
    application code is transport-agnostic, like the reference keeping
    ``SocketAddr`` across its UCX/eRPC backends.
    """

    def __init__(self):
        super().__init__()
        self._path: Optional[str] = None
        self._lock_fd: Optional[int] = None

    @staticmethod
    def _dir() -> str:
        import tempfile

        d = os.environ.get("MADSIM_UDS_DIR") or os.path.join(
            tempfile.gettempdir(), f"madsim-uds-{os.getuid()}")
        os.makedirs(d, exist_ok=True)
        return d

    @classmethod
    def _path_for(cls, ip: str, port: int) -> str:
        return os.path.join(cls._dir(), f"{ip}_{port}.sock")

    async def _listen(self, host: str, port: int) -> None:
        import errno
        import fcntl

        loop = asyncio.get_running_loop()
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
        ephemeral = port == 0
        for _attempt in range(32):
            if ephemeral:
                port = 49152 + int.from_bytes(os.urandom(2), "little") % 16384
            path = self._path_for(host, port)
            # Address ownership is an flock on a sidecar file, held for the
            # listener's lifetime: the kernel drops it when the owner dies,
            # so "lock held" IS the liveness test — no probe-connect, and
            # no window where two binders both decide a socket file is
            # stale and unlink each other's fresh listener.
            # Lock files are deliberately never unlinked (removing one can
            # race a new binder that already open()ed it, splitting the
            # lock across two inodes); they are zero-byte and bounded by
            # the port range.
            lock_fd = os.open(path + ".lock", os.O_CREAT | os.O_RDWR, 0o600)
            try:
                fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as exc:
                os.close(lock_fd)
                if exc.errno not in (errno.EAGAIN, errno.EWOULDBLOCK,
                                     errno.EACCES):
                    raise  # e.g. ENOLCK (no-flock fs): report faithfully
                if ephemeral:
                    continue  # a live listener owns this draw: redraw
                raise OSError(errno.EADDRINUSE,
                              f"address {host}:{port} already in use (uds)")
            try:
                if os.path.exists(path):
                    os.unlink(path)  # stale socket of a dead owner
                self._server = await loop.create_unix_server(
                    self._server_proto, path)
            except BaseException:
                os.close(lock_fd)  # releases the flock
                raise
            self._lock_fd = lock_fd
            self._path = path
            self._addr = (host, port)
            self._bound_wildcard = False
            return
        raise OSError("could not find a free ephemeral uds address")

    async def _dial(self, dst: Addr, peer: Optional[Addr] = None):
        loop = asyncio.get_running_loop()
        transport, proto = await loop.create_unix_connection(
            lambda: _FrameProtocol(peer=peer if peer is not None else dst),
            self._path_for(dst[0], dst[1]))
        self._track(proto)
        return transport, proto

    def _advertised_addr(self, transport) -> str:
        return format_addr(self._addr)

    def close(self) -> None:
        was_closed = self._closed
        super().close()
        if not was_closed and self._path is not None:
            try:
                os.unlink(self._path)
            except OSError:
                pass
        if not was_closed and self._lock_fd is not None:
            os.close(self._lock_fd)  # releases the address flock
            self._lock_fd = None


class ShmEndpoint(UdsEndpoint):
    """Shared-memory bulk transport: UDS control plane + per-connection
    ring arenas for payloads >= 32 KiB.

    The third real-transport leg (the stand-in for the reference's
    UCX/eRPC features, `std/net/ucx.rs` / `std/net/erpc.rs`): message
    framing, ordering, connection lifecycle, and small messages ride the
    battle-tested UDS stream unchanged; bulk payload bytes are written
    once into a sender-owned shared-memory ring and the wire carries a
    17-byte (offset, length, fmt) reference, eliminating both kernel
    socket copies and send-buffer chunking for large frames. Receivers
    ack consumed cursors on the reverse stream; a full ring falls back to
    the inline path, so throughput degrades instead of deadlocking.

    Measured envelope and the latency rationale (why small-message RPC
    keeps the socket path) live in docs/transports.md.
    """

    def _server_proto(self) -> _FrameProtocol:
        proto = super()._server_proto()
        proto.shm_enabled = True
        return proto

    async def _dial(self, dst: Addr, peer: Optional[Addr] = None):
        transport, proto = await super()._dial(dst, peer)
        proto.shm_enabled = True
        return transport, proto


def real_endpoint_class() -> type:
    """The Endpoint implementation selected by ``MADSIM_REAL_TRANSPORT``
    (``tcp`` default; ``uds``/``unix`` for same-host Unix sockets;
    ``shm`` for UDS control + shared-memory bulk rings) — the env-var
    analog of the reference's transport feature flags."""
    t = os.environ.get("MADSIM_REAL_TRANSPORT", "tcp").lower()
    if t == "tcp":
        return RealEndpoint
    if t in ("uds", "unix"):
        return UdsEndpoint
    if t == "shm":
        return ShmEndpoint
    raise ValueError(f"unknown MADSIM_REAL_TRANSPORT {t!r} "
                     "(expected 'tcp', 'uds', or 'shm')")


# The backend-generic RPC layer rides on the endpoint surface
# (`std/net/rpc.rs` analog); attach the same ergonomic methods the sim
# endpoint carries. Done here so sim-only runs never import this module.
from ..net import rpc as _rpc  # noqa: E402

# (Transport subclasses like UdsEndpoint inherit these.)
RealEndpoint.call = _rpc.call  # type: ignore[attr-defined]
RealEndpoint.call_with_data = _rpc.call_with_data  # type: ignore[attr-defined]
RealEndpoint.add_rpc_handler = _rpc.add_rpc_handler  # type: ignore[attr-defined]
RealEndpoint.add_rpc_handler_with_data = _rpc.add_rpc_handler_with_data  # type: ignore[attr-defined]
