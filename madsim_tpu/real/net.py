"""Real-transport Endpoint: the tag-matching API over framed TCP.

The production twin of :class:`madsim_tpu.net.endpoint.Endpoint`, modeled on
the reference's std backend (`madsim/src/std/net/tcp.rs:20-324`):

- ``bind`` opens a real TCP listener (asyncio);
- the *connecting* side sends one handshake frame carrying its own
  listener address, so the acceptor can key the connection by the peer's
  canonical endpoint address (`tcp.rs:79-103`);
- each message is one length-delimited frame ``[len u32][tag u64][fmt u8]
  [payload]`` (big-endian), where fmt 0 = raw bytes and fmt 1 = pickled
  Python object — the analog of the std RPC layer's bincode serialization
  (`std/net/rpc.rs:118-190`); sim mode needs no fmt byte because payloads
  never leave the process;
- received frames land in the same pending-receivers-first tag
  :class:`Mailbox` discipline as the sim endpoint (`tcp.rs:264-302`).

Connections are created lazily on first send and cached per peer
(`tcp.rs:160-183`); a closed connection evicts its cache entry so the next
send reconnects.
"""
from __future__ import annotations

import asyncio
import os
import pickle
import socket as _socket
import struct
from typing import Any, Dict, List, Optional, Tuple

from ..net.addr import Addr, AddrLike, AddrParseError, lookup_host
from ..net.network import BrokenPipe, ConnectionReset, NetworkError


async def real_lookup(addr: AddrLike) -> Addr:
    """Resolve an address for the real backend, including DNS hostnames.

    The sim parser only accepts numeric IPs (no DNS inside a simulation);
    production addresses are names, so fall back to getaddrinfo — the
    `std/net/addr` path resolving through tokio's lookup_host.
    """
    try:
        return (await lookup_host(addr))[0]
    except AddrParseError:
        if isinstance(addr, tuple):
            host, port = addr
        else:
            host, _, port = str(addr).rpartition(":")
        infos = await asyncio.get_running_loop().getaddrinfo(
            host, int(port), type=_socket.SOCK_STREAM)
        if not infos:
            raise OSError(f"cannot resolve {addr!r}") from None
        ip, rport = infos[0][4][:2]
        return (ip, rport)

_HDR = struct.Struct(">I")        # frame length
_TAGFMT = struct.Struct(">QB")    # tag u64 + fmt u8
FMT_BYTES = 0
FMT_PICKLE = 1
_MAX_FRAME = 1 << 30


class _Message:
    __slots__ = ("tag", "data", "from_addr")

    def __init__(self, tag: int, data: Any, from_addr: Addr):
        self.tag = tag
        self.data = data
        self.from_addr = from_addr


class _Mailbox:
    """Tag-matched mailbox over asyncio futures (same discipline as the sim
    endpoint's: deliver tries pending receivers first, else buffers)."""

    __slots__ = ("registered", "msgs", "closed")

    def __init__(self):
        self.registered: List[Tuple[int, asyncio.Future]] = []
        self.msgs: List[_Message] = []
        self.closed = False

    def deliver(self, msg: _Message) -> None:
        for i, (tag, fut) in enumerate(self.registered):
            if tag == msg.tag and not fut.done():
                del self.registered[i]
                fut.set_result(msg)
                return
        self.registered = [(t, f) for (t, f) in self.registered if not f.done()]
        self.msgs.append(msg)

    def recv(self, tag: int) -> "asyncio.Future[_Message]":
        fut = asyncio.get_running_loop().create_future()
        if self.closed:
            fut.set_exception(BrokenPipe("endpoint closed"))
            return fut
        for i, msg in enumerate(self.msgs):
            if msg.tag == tag:
                del self.msgs[i]
                fut.set_result(msg)
                return fut
        self.registered.append((tag, fut))
        return fut

    def unregister(self, fut: asyncio.Future) -> None:
        self.registered = [(t, f) for (t, f) in self.registered if f is not fut]

    def requeue_front(self, msg: _Message) -> None:
        self.msgs.insert(0, msg)

    def close(self) -> None:
        self.closed = True
        for _, fut in self.registered:
            if not fut.done():
                fut.set_exception(BrokenPipe("endpoint closed"))
        self.registered.clear()


class _Conn:
    __slots__ = ("writer", "lock")

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.lock = asyncio.Lock()  # frames must not interleave


def _encode(tag: int, data: Any) -> bytes:
    if isinstance(data, (bytes, bytearray, memoryview)):
        fmt, payload = FMT_BYTES, bytes(data)
    else:
        fmt, payload = FMT_PICKLE, pickle.dumps(data)
    body = _TAGFMT.pack(tag, fmt) + payload
    return _HDR.pack(len(body)) + body


class _FrameError(Exception):
    """Malformed frame: the byte stream is desynced beyond recovery."""


async def _read_frame(reader: asyncio.StreamReader):
    """The ONE frame decoder (endpoint reader loop and channel receivers
    share it): one framed message → (tag, data); None at orderly EOF or a
    broken socket; :class:`_FrameError` on a malformed length."""
    try:
        hdr = await reader.readexactly(_HDR.size)
        (n,) = _HDR.unpack(hdr)
        if n < _TAGFMT.size or n > _MAX_FRAME:
            raise _FrameError(f"bad frame length {n}")
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
    tag, fmt = _TAGFMT.unpack_from(body)
    payload = body[_TAGFMT.size:]
    return tag, (pickle.loads(payload) if fmt == FMT_PICKLE else payload)


class RealChannelSender:
    """Sending half of a real ``connect1`` channel (one dedicated framed
    connection). ``close()`` shuts down the write direction only, so the
    peer's receiver sees EOF while this side can keep reading — matching
    the sim channel halves' independent-close semantics."""

    __slots__ = ("_writer", "_lock")

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._lock = asyncio.Lock()

    async def send(self, payload) -> None:
        try:
            async with self._lock:
                self._writer.write(_encode(0, payload))
                await self._writer.drain()
        except (ConnectionError, OSError, RuntimeError):
            # RuntimeError: write after write_eof/close — the sim raises
            # ConnectionReset for sends on a closed channel; match it.
            raise ConnectionReset("connection reset") from None

    def close(self) -> None:
        try:
            if self._writer.can_write_eof():
                self._writer.write_eof()
            else:
                self._writer.close()
        except (ConnectionError, OSError, RuntimeError):
            pass


class RealChannelReceiver:
    """Receiving half of a real ``connect1`` channel: reads frames on
    demand; EOF or a broken socket surfaces like the sim's closed
    channel."""

    __slots__ = ("_reader", "_writer")

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer

    async def recv(self):
        msg = await self._recv_raw()
        if msg is _EOF:
            raise ConnectionReset("connection reset")
        return msg

    async def recv_or_eof(self):
        """Like recv but returns None at EOF (for stream adapters)."""
        msg = await self._recv_raw()
        return None if msg is _EOF else msg

    async def _recv_raw(self):
        try:
            frame = await _read_frame(self._reader)
        except _FrameError:
            # Desynced stream: tear the connection down (a plain EOF must
            # NOT close — the peer may have half-closed and still expect
            # our replies).
            self._writer.close()
            return _EOF
        return _EOF if frame is None else frame[1]

    def close(self) -> None:
        self._writer.close()  # tears down the whole connection


class _EofType:
    pass


_EOF = _EofType()
_CLOSED = object()  # accept1 wake-up sentinel after endpoint close


class RealEndpoint:
    """Bindable, tag-matching endpoint over real TCP."""

    def __init__(self):
        self._server: Optional[asyncio.base_events.Server] = None
        self._addr: Optional[Addr] = None
        self._bound_wildcard = False
        self._conns: Dict[Addr, "asyncio.Future[_Conn]"] = {}
        self._mailbox = _Mailbox()
        self._tasks: List[asyncio.Task] = []
        self._peer: Optional[Addr] = None
        self._closed = False
        # Inbound connect1 channels park here until accept1 takes them.
        self._chan_queue: "asyncio.Queue" = asyncio.Queue()

    # -- constructors ------------------------------------------------------
    @classmethod
    async def bind(cls, addr: AddrLike) -> "RealEndpoint":
        host, port = await real_lookup(addr)
        ep = cls()
        await ep._listen(host, port)
        return ep

    @classmethod
    async def connect(cls, addr: AddrLike) -> "RealEndpoint":
        peer = await real_lookup(addr)
        ep = await cls.bind("0.0.0.0:0")
        ep._peer = peer
        return ep

    # -- transport hooks (overridden by alternative wire transports) -------
    async def _listen(self, host: str, port: int) -> None:
        self._server = await asyncio.start_server(self._on_accept, host, port)
        sock = self._server.sockets[0]
        ip, bound_port = sock.getsockname()[:2]
        # A wildcard bind IP is not a routable peer-facing address:
        # local_addr() reports loopback (usable in-process), and each
        # outgoing handshake advertises that connection's interface IP.
        self._bound_wildcard = ip in ("0.0.0.0", "::")
        self._addr = ("127.0.0.1" if self._bound_wildcard else ip, bound_port)

    async def _dial(self, dst: Addr):
        return await asyncio.open_connection(dst[0], dst[1])

    def _advertised_addr(self, writer: asyncio.StreamWriter) -> str:
        # Advertise the address the peer can reach our listener at. For a
        # wildcard bind the bound IP is not routable, so use this
        # connection's local interface IP — loopback for loopback peers,
        # the NIC address cross-host.
        adv_ip = self._addr[0]
        if self._bound_wildcard:
            adv_ip = writer.get_extra_info("sockname")[0]
        return f"{adv_ip}:{self._addr[1]}"

    # -- introspection -----------------------------------------------------
    def local_addr(self) -> Addr:
        return self._addr

    def peer_addr(self) -> Addr:
        if self._peer is None:
            raise NetworkError("not connected")
        return self._peer

    # -- connection management --------------------------------------------
    async def _on_accept(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            # Handshake: the connector's listener address (`tcp.rs:87-96`),
            # or "chan:<addr>" marking a dedicated connect1 channel.
            hdr = await reader.readexactly(_HDR.size)
            (n,) = _HDR.unpack(hdr)
            if n > 4096:
                raise NetworkError("bad handshake")
            text = (await reader.readexactly(n)).decode()
            is_chan = text.startswith("chan:")
            peer = (await lookup_host(text[5:] if is_chan else text))[0]
        except (asyncio.IncompleteReadError, UnicodeDecodeError,
                NetworkError, ValueError):
            writer.close()
            return
        if is_chan:
            self._chan_queue.put_nowait(
                (RealChannelSender(writer),
                 RealChannelReceiver(reader, writer), peer))
            return
        prev = self._conns.get(peer)
        if prev is not None and not prev.done():
            # Simultaneous connect: our own outbound connect to this peer
            # is mid-handshake. Don't displace its pending future (waiters
            # already hold it — overwriting would split senders across two
            # sockets and orphan one); this inbound socket still gets a
            # reader so the peer's traffic is received.
            self._spawn_reader(reader, writer, peer)
            return
        fut = asyncio.get_running_loop().create_future()
        fut.set_result(_Conn(writer))
        self._conns[peer] = fut
        if prev is not None and prev.done() and prev.exception() is None:
            # A stale duplicate connection loses to the fresh one
            # (`tcp.rs:99-101` warns on duplicates); close it so its fd
            # doesn't leak.
            prev.result().writer.close()
        self._spawn_reader(reader, writer, peer)

    def _spawn_reader(self, reader, writer, peer: Addr) -> None:
        task = asyncio.get_running_loop().create_task(
            self._reader_loop(reader, writer, peer))
        self._tasks.append(task)
        self._tasks = [t for t in self._tasks if not t.done()]

    async def _reader_loop(self, reader, writer, peer: Addr) -> None:
        try:
            while True:
                try:
                    frame = await _read_frame(reader)
                except _FrameError:
                    break
                if frame is None:
                    break
                self._mailbox.deliver(_Message(frame[0], frame[1], peer))
        finally:
            # Closed by remote: drop the cached sender so later sends
            # reconnect (`tcp.rs:144-150`) — but only if the cache still
            # points at THIS connection; a newer one must not be evicted
            # by a stale teardown.
            cached = self._conns.get(peer)
            if (cached is not None and cached.done()
                    and cached.exception() is None
                    and cached.result().writer is writer):
                self._conns.pop(peer, None)
            writer.close()

    async def _get_or_connect(self, dst: Addr) -> _Conn:
        fut = self._conns.get(dst)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._conns[dst] = fut
            try:
                reader, writer = await self._dial(dst)
            except BaseException as exc:
                # Cancellation (or any failure) must not leave a forever-
                # pending future cached: later senders would await it and
                # hang. Evict and fail it before propagating.
                if self._conns.get(dst) is fut:
                    self._conns.pop(dst, None)
                if not fut.done():
                    fut.set_exception(
                        exc if isinstance(exc, (ConnectionError, OSError))
                        else BrokenPipe(f"connect cancelled: {exc!r}"))
                    fut.exception()  # mark retrieved: no waiter may exist
                raise
            try:
                # Handshake: advertise our listener's canonical address.
                text = self._advertised_addr(writer).encode()
                writer.write(_HDR.pack(len(text)) + text)
                await writer.drain()
                self._spawn_reader(reader, writer, dst)
                fut.set_result(_Conn(writer))
            except BaseException as exc:
                if self._conns.get(dst) is fut:
                    self._conns.pop(dst, None)
                if not fut.done():
                    fut.set_exception(
                        exc if isinstance(exc, (ConnectionError, OSError))
                        else BrokenPipe(f"handshake failed: {exc!r}"))
                    fut.exception()  # mark retrieved: no waiter may exist
                writer.close()
                raise
        return await asyncio.shield(fut)

    # -- datagram path -----------------------------------------------------
    async def send_to(self, dst: AddrLike, tag: int, data: Any) -> None:
        await self.send_to_raw(await real_lookup(dst), tag, data)

    async def send_to_raw(self, dst: Addr, tag: int, data: Any) -> None:
        if self._closed:
            raise BrokenPipe("endpoint closed")
        frame = _encode(tag, data)
        conn = await self._get_or_connect(dst)
        async with conn.lock:
            conn.writer.write(frame)
            await conn.writer.drain()

    async def recv_from(self, tag: int) -> Tuple[Any, Addr]:
        return await self.recv_from_raw(tag)

    async def recv_from_raw(self, tag: int,
                            timeout: Optional[float] = None) -> Tuple[Any, Addr]:
        fut = self._mailbox.recv(tag)
        try:
            if timeout is not None:
                msg = await asyncio.wait_for(asyncio.shield(fut), timeout)
            else:
                msg = await fut
        except asyncio.TimeoutError:
            if fut.done() and fut.exception() is None:
                self._mailbox.requeue_front(fut.result())
            else:
                fut.cancel()
                self._mailbox.unregister(fut)
            raise TimeoutError() from None
        except asyncio.CancelledError:
            if fut.done() and fut.exception() is None:
                self._mailbox.requeue_front(fut.result())
            else:
                self._mailbox.unregister(fut)
            raise
        return msg.data, msg.from_addr

    # -- connection-oriented path (sim connect1/accept1 twins) -------------
    async def connect1(self, addr: AddrLike):
        """Open a dedicated ordered duplex channel to a peer's endpoint
        (the sim ``connect1`` twin): returns (sender, receiver)."""
        dst = await real_lookup(addr)
        reader, writer = await self._dial(dst)
        try:
            text = f"chan:{self._advertised_addr(writer)}".encode()
            writer.write(_HDR.pack(len(text)) + text)
            await writer.drain()
        except (ConnectionError, OSError):
            writer.close()
            raise ConnectionReset("connection reset") from None
        return RealChannelSender(writer), RealChannelReceiver(reader, writer)

    async def accept1(self):
        """Await an inbound channel: returns (sender, receiver, peer).
        Raises :class:`ConnectionReset` once the endpoint closes — the
        sim accept1's closed-endpoint behavior."""
        if self._closed:
            raise ConnectionReset("endpoint closed")
        item = await self._chan_queue.get()
        if item is _CLOSED:
            self._chan_queue.put_nowait(_CLOSED)  # wake further waiters
            raise ConnectionReset("endpoint closed")
        return item

    async def send(self, tag: int, data: Any) -> None:
        await self.send_to(self.peer_addr(), tag, data)

    async def recv(self, tag: int) -> Any:
        peer = self.peer_addr()
        data, from_addr = await self.recv_from(tag)
        if from_addr != peer:
            raise NetworkError("received a message not from the connected address")
        return data

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._server is not None:
            self._server.close()
        for fut in self._conns.values():
            if fut.done() and fut.exception() is None:
                fut.result().writer.close()
        self._conns.clear()
        for t in self._tasks:
            t.cancel()
        self._mailbox.close()
        # Tear down parked inbound channels and wake accept1 waiters.
        while not self._chan_queue.empty():
            item = self._chan_queue.get_nowait()
            if item is not _CLOSED:
                item[1].close()
        self._chan_queue.put_nowait(_CLOSED)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class UdsEndpoint(RealEndpoint):
    """The same framed tag protocol over Unix-domain sockets.

    The analog of the reference's feature-selected alternative wire
    transports behind one Endpoint API (UCX `std/net/ucx.rs`, eRPC
    `std/net/erpc.rs`, chosen by Cargo feature): here the transport is
    chosen by ``MADSIM_REAL_TRANSPORT=uds``, for same-host deployments
    that want filesystem-scoped addressing and permissions instead of the
    shared TCP port namespace (latency is comparable to loopback TCP —
    bench.py measures both). Addresses stay virtual
    ``(ip, port)`` pairs — each maps to one socket file under
    ``MADSIM_UDS_DIR`` (default ``$TMPDIR/madsim-uds-<uid>``) so
    application code is transport-agnostic, like the reference keeping
    ``SocketAddr`` across its UCX/eRPC backends.
    """

    def __init__(self):
        super().__init__()
        self._path: Optional[str] = None
        self._lock_fd: Optional[int] = None

    @staticmethod
    def _dir() -> str:
        import tempfile

        d = os.environ.get("MADSIM_UDS_DIR") or os.path.join(
            tempfile.gettempdir(), f"madsim-uds-{os.getuid()}")
        os.makedirs(d, exist_ok=True)
        return d

    @classmethod
    def _path_for(cls, ip: str, port: int) -> str:
        return os.path.join(cls._dir(), f"{ip}_{port}.sock")

    async def _listen(self, host: str, port: int) -> None:
        import errno
        import fcntl

        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
        ephemeral = port == 0
        for _attempt in range(32):
            if ephemeral:
                port = 49152 + int.from_bytes(os.urandom(2), "little") % 16384
            path = self._path_for(host, port)
            # Address ownership is an flock on a sidecar file, held for the
            # listener's lifetime: the kernel drops it when the owner dies,
            # so "lock held" IS the liveness test — no probe-connect, and
            # no window where two binders both decide a socket file is
            # stale and unlink each other's fresh listener.
            # Lock files are deliberately never unlinked (removing one can
            # race a new binder that already open()ed it, splitting the
            # lock across two inodes); they are zero-byte and bounded by
            # the port range.
            lock_fd = os.open(path + ".lock", os.O_CREAT | os.O_RDWR, 0o600)
            try:
                fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as exc:
                os.close(lock_fd)
                if exc.errno not in (errno.EAGAIN, errno.EWOULDBLOCK,
                                     errno.EACCES):
                    raise  # e.g. ENOLCK (no-flock fs): report faithfully
                if ephemeral:
                    continue  # a live listener owns this draw: redraw
                raise OSError(errno.EADDRINUSE,
                              f"address {host}:{port} already in use (uds)")
            try:
                if os.path.exists(path):
                    os.unlink(path)  # stale socket of a dead owner
                self._server = await asyncio.start_unix_server(
                    self._on_accept, path)
            except BaseException:
                os.close(lock_fd)  # releases the flock
                raise
            self._lock_fd = lock_fd
            self._path = path
            self._addr = (host, port)
            self._bound_wildcard = False
            return
        raise OSError("could not find a free ephemeral uds address")

    async def _dial(self, dst: Addr):
        return await asyncio.open_unix_connection(self._path_for(dst[0], dst[1]))

    def _advertised_addr(self, writer: asyncio.StreamWriter) -> str:
        return f"{self._addr[0]}:{self._addr[1]}"

    def close(self) -> None:
        was_closed = self._closed
        super().close()
        if not was_closed and self._path is not None:
            try:
                os.unlink(self._path)
            except OSError:
                pass
        if not was_closed and self._lock_fd is not None:
            os.close(self._lock_fd)  # releases the address flock
            self._lock_fd = None


def real_endpoint_class() -> type:
    """The Endpoint implementation selected by ``MADSIM_REAL_TRANSPORT``
    (``tcp`` default; ``uds``/``unix`` for same-host Unix sockets) — the
    env-var analog of the reference's transport feature flags."""
    t = os.environ.get("MADSIM_REAL_TRANSPORT", "tcp").lower()
    if t == "tcp":
        return RealEndpoint
    if t in ("uds", "unix"):
        return UdsEndpoint
    raise ValueError(f"unknown MADSIM_REAL_TRANSPORT {t!r} "
                     "(expected 'tcp' or 'uds')")


# The backend-generic RPC layer rides on the endpoint surface
# (`std/net/rpc.rs` analog); attach the same ergonomic methods the sim
# endpoint carries. Done here so sim-only runs never import this module.
from ..net import rpc as _rpc  # noqa: E402

# (Transport subclasses like UdsEndpoint inherit these.)
RealEndpoint.call = _rpc.call  # type: ignore[attr-defined]
RealEndpoint.call_with_data = _rpc.call_with_data  # type: ignore[attr-defined]
RealEndpoint.add_rpc_handler = _rpc.add_rpc_handler  # type: ignore[attr-defined]
RealEndpoint.add_rpc_handler_with_data = _rpc.add_rpc_handler_with_data  # type: ignore[attr-defined]
