"""Real-filesystem backend for the positional-I/O File API.

The production twin of :mod:`madsim_tpu.fs` (`madsim/src/std/fs.rs` analog:
the same create/open/read_at/write_all_at/set_len/sync_all surface over the
real disk). I/O runs on worker threads via ``asyncio.to_thread`` — the
tokio::fs model — so the event loop never blocks on disk.
"""
from __future__ import annotations

import asyncio
import os


class Metadata:
    __slots__ = ("len",)

    def __init__(self, length: int):
        self.len = length


class RealFile:
    """Positional-I/O handle over a real OS file."""

    def __init__(self, fd: int, path: str):
        self._fd = fd
        self.path = path
        self._closed = False

    # -- constructors ------------------------------------------------------
    @staticmethod
    async def create(path: str) -> "RealFile":
        fd = await asyncio.to_thread(
            os.open, str(path), os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
        return RealFile(fd, str(path))

    @staticmethod
    async def open(path: str) -> "RealFile":
        fd = await asyncio.to_thread(os.open, str(path), os.O_RDWR)
        return RealFile(fd, str(path))

    @staticmethod
    async def open_or_create(path: str) -> "RealFile":
        fd = await asyncio.to_thread(
            os.open, str(path), os.O_RDWR | os.O_CREAT, 0o644)
        return RealFile(fd, str(path))

    # -- I/O ---------------------------------------------------------------
    async def read_at(self, offset: int, length: int) -> bytes:
        return await asyncio.to_thread(os.pread, self._fd, length, offset)

    async def read_all(self) -> bytes:
        def _read():
            size = os.fstat(self._fd).st_size
            return os.pread(self._fd, size, 0)

        return await asyncio.to_thread(_read)

    async def write_all_at(self, data: bytes, offset: int) -> None:
        def _write():
            view = memoryview(bytes(data))
            pos = offset
            while view:
                n = os.pwrite(self._fd, view, pos)
                view = view[n:]
                pos += n

        await asyncio.to_thread(_write)

    async def set_len(self, length: int) -> None:
        await asyncio.to_thread(os.ftruncate, self._fd, length)

    async def sync_all(self) -> None:
        await asyncio.to_thread(os.fsync, self._fd)

    async def metadata(self) -> Metadata:
        st = await asyncio.to_thread(os.fstat, self._fd)
        return Metadata(st.st_size)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            os.close(self._fd)

    def __del__(self):
        try:
            self.close()
        except OSError:
            pass


async def read(path: str) -> bytes:
    f = await RealFile.open(path)
    try:
        return await f.read_all()
    finally:
        f.close()


async def write(path: str, data: bytes) -> None:
    f = await RealFile.create(path)
    try:
        await f.write_all_at(bytes(data), 0)
    finally:
        f.close()


async def metadata(path: str) -> Metadata:
    st = await asyncio.to_thread(os.stat, str(path))
    return Metadata(st.st_size)


async def remove_file(path: str) -> None:
    await asyncio.to_thread(os.remove, str(path))
