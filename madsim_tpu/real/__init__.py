"""Production ("real") execution backend.

The twin of the simulation that the reference keeps under
``madsim/src/std/`` (`std/mod.rs:1-7`): when code written against the
madsim_tpu facades runs outside a simulation with ``MADSIM_BACKEND=real``,
the facades delegate here — real asyncio tasks and sleeps, the OS clock,
OS entropy, real files, and a tag-matching Endpoint over framed TCP
(`std/net/tcp.rs:20-324` analog in :mod:`madsim_tpu.real.net`).

Nothing in this package is deterministic — that is the point: the same
application binary that was exhaustively seed-swept in simulation runs
here against the real world.
"""
from __future__ import annotations

import os
import random as _pyrandom
from typing import Any, List, Sequence


class RealRng:
    """OS-entropy-seeded RNG with the GlobalRng call surface.

    The real-mode analog of the reference re-exporting the real ``rand``
    crate outside sim (`madsim/src/std/mod.rs:5`): same method names as
    :class:`madsim_tpu.core.rng.GlobalRng`, nondeterministic values.
    """

    def __init__(self):
        self._rng = _pyrandom.Random(int.from_bytes(os.urandom(16), "little"))

    # -- GlobalRng surface -------------------------------------------------
    def next_u64(self) -> int:
        return self._rng.getrandbits(64)

    def random(self) -> float:
        return self._rng.random()

    def gen_range(self, low: int, high: int) -> int:
        if high <= low:
            raise ValueError("empty range")
        return self._rng.randrange(low, high)

    def gen_range_f64(self, low: float, high: float) -> float:
        return self._rng.uniform(low, high)

    def gen_bool(self, p: float) -> bool:
        return self._rng.random() < p

    def shuffle(self, seq: List[Any]) -> None:
        self._rng.shuffle(seq)

    def choice(self, seq: Sequence[Any]) -> Any:
        return self._rng.choice(seq)

    def gen_bytes(self, n: int) -> bytes:
        return os.urandom(n)


_thread_rng: RealRng = RealRng()


def thread_rng() -> RealRng:
    return _thread_rng
