"""Two-phase commit on the HOST engine: the device TPC actor's twin.

Same protocol and same injected bug as :mod:`madsim_tpu.engine.tpc_actor`,
written as ordinary Python coroutines against the framework API (Endpoint
RPC, timers, seeded randomness) — the second workload family with
implementations on BOTH engines, so host↔device cross-validation
(bug-rate comparison, tests/test_crossvalidation.py) does not rest on the
Raft pair alone.

Node 0 coordinates; participants vote yes/no (no with probability
``no_vote_p``, drawn from the world's seeded RNG), abort unilaterally on a
no-vote, and apply the coordinator's decision. The coordinator commits iff
every vote arrived yes within the timeout; on timeout it aborts — unless
``buggy_presumed_commit``, which presumes commit and violates atomicity
whenever a no-vote (or a PREPARE) was lost to the network.

The invariant is checked at apply time by a world-global
:class:`TPCChecker`: any transaction recorded both COMMIT and ABORT raises
:class:`TPCInvariantViolation`, failing the simulation like the device
engine's bug flag fails the world.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import madsim_tpu as ms
from madsim_tpu import rand, task, time
from madsim_tpu.net import Endpoint
from madsim_tpu.net import rpc as msrpc

COMMIT, ABORT = 1, 2


class TPCInvariantViolation(AssertionError):
    """Atomicity broken: a txn committed at one node, aborted at another."""


@dataclass
class Prepare:
    txn: int


@dataclass
class Decide:
    txn: int
    decision: int


class TPCChecker:
    """Apply-time atomicity record across every node of one world."""

    def __init__(self):
        self.applied: Dict[int, Dict[int, int]] = {}  # txn -> node -> outcome

    def record(self, node: int, txn: int, decision: int) -> None:
        per = self.applied.setdefault(txn, {})
        per[node] = decision
        outcomes = set(per.values())
        if COMMIT in outcomes and ABORT in outcomes:
            raise TPCInvariantViolation(
                f"txn {txn} committed at "
                f"{[n for n, d in per.items() if d == COMMIT]} but aborted "
                f"at {[n for n, d in per.items() if d == ABORT]}")


class Participant:
    """Votes on PREPARE (once, idempotently) and applies DECIDE."""

    def __init__(self, idx: int, checker: TPCChecker, no_vote_p: float):
        self.idx = idx
        self.checker = checker
        self.no_vote_p = no_vote_p
        self.votes: Dict[int, bool] = {}
        self.applied: Dict[int, int] = {}

    async def serve(self, addr) -> None:
        ep = await Endpoint.bind(addr)

        async def on_prepare(req: Prepare) -> bool:
            if req.txn not in self.votes:
                vote_no = rand.thread_rng().gen_bool(self.no_vote_p)
                self.votes[req.txn] = not vote_no
                if vote_no:
                    # Unilateral abort: no lock is held for a rejected txn.
                    self.applied[req.txn] = ABORT
                    self.checker.record(self.idx, req.txn, ABORT)
            return self.votes[req.txn]

        async def on_decide(req: Decide) -> bool:
            if req.txn not in self.applied:
                self.applied[req.txn] = req.decision
                self.checker.record(self.idx, req.txn, req.decision)
            return True

        msrpc.add_rpc_handler(ep, Prepare, on_prepare)
        msrpc.add_rpc_handler(ep, Decide, on_decide)
        await time.sleep(3600.0)


class Coordinator:
    """Runs one 2PC round per scheduled transaction."""

    def __init__(self, checker: TPCChecker, participants: List[str],
                 vote_timeout: float, buggy_presumed_commit: bool):
        self.checker = checker
        self.participants = participants
        self.vote_timeout = vote_timeout
        self.buggy = buggy_presumed_commit
        self.decided: Dict[int, int] = {}

    async def run_txn(self, ep: Endpoint, txn: int) -> int:
        async def ask(addr) -> Optional[bool]:
            try:
                return await msrpc.call(ep, addr, Prepare(txn),
                                        timeout=self.vote_timeout)
            except TimeoutError:
                return None  # lost PREPARE or lost vote

        votes = [await h for h in
                 [task.spawn(ask(a)) for a in self.participants]]
        if all(v is True for v in votes):
            decision = COMMIT
        elif any(v is False for v in votes):
            decision = ABORT
        else:
            # Stragglers only: the timeout decision — the bug switch.
            decision = COMMIT if self.buggy else ABORT
        self.decided[txn] = decision
        # The coordinator applies its own decision too (its durable log).
        self.checker.record(0, txn, decision)
        for addr in self.participants:
            try:
                await msrpc.call(ep, addr, Decide(txn, decision),
                                 timeout=self.vote_timeout)
            except TimeoutError:
                pass  # lost DECIDE: that participant stays blocked
        return decision


async def run_tpc_world(n: int = 4, n_txns: int = 6, no_vote_p: float = 0.125,
                        vote_timeout: float = 0.06,
                        txn_interval: float = 0.12,
                        buggy_presumed_commit: bool = False) -> Dict[str, int]:
    """Build an n-node world, run the txn schedule, return outcome counts.

    Raises :class:`TPCInvariantViolation` when atomicity breaks (buggy
    mode under packet loss). Mirrors the device actor's shape: same vote
    probability, timeout-vs-interval ratio, and decision rules.
    """
    h = ms.Handle.current()
    checker = TPCChecker()
    addrs = [f"10.0.0.{i + 2}:400{i}" for i in range(n - 1)]
    for i, addr in enumerate(addrs):
        part = Participant(i + 1, checker, no_vote_p)

        def init(p=part, a=addr):
            async def body():
                await p.serve(a)
            return body

        h.create_node(name=f"part{i + 1}", ip=f"10.0.0.{i + 2}", init=init())

    coord = Coordinator(checker, addrs, vote_timeout, buggy_presumed_commit)
    done = ms.sync.SimFuture()

    async def coord_body():
        await time.sleep(0.05)  # participants bind
        ep = await Endpoint.bind("10.0.0.1:4100")
        for t in range(n_txns):
            await coord.run_txn(ep, t)
            await time.sleep(txn_interval)
        done.set_result(True)

    h.create_node(name="coord", ip="10.0.0.1", init=lambda: coord_body())
    await time.timeout(120.0, done)
    outcomes = list(coord.decided.values())
    return {"commits": outcomes.count(COMMIT),
            "aborts": outcomes.count(ABORT)}
