"""Reference workloads built on the framework (the MadRaft analog and the
benchmark payloads from BASELINE.md)."""
