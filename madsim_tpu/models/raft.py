"""A MadRaft-equivalent: Raft consensus written against the framework API.

The reference ecosystem's flagship workload is MadRaft (an external repo built
on madsim; referenced at `README.md` of the reference). This module plays the
same role for madsim_tpu: leader election + log replication + crash-safe
persistence (via the simulated fs) + invariant checking, exercising endpoints,
RPC, timers, node kill/restart, and partitions. It is the payload for the
BASELINE.md benchmark configs (3-node election, 5-node replication sweeps).

This is the *host-engine* implementation (arbitrary Python, one seed per run).
The batched device engine has its own pure-JAX Raft actor in
``madsim_tpu.engine.raft_actor`` for the vmapped seed sweeps.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import madsim_tpu as ms
from madsim_tpu import fs, rand, task, time
from madsim_tpu.net import Endpoint
from madsim_tpu.net import rpc as msrpc

# ---------------------------------------------------------------------------
# Messages (in-sim these cross the network as objects, zero serialization)
# ---------------------------------------------------------------------------


@dataclass
class RequestVote:
    term: int
    candidate: int
    last_log_index: int
    last_log_term: int


@dataclass
class VoteReply:
    term: int
    granted: bool


@dataclass
class AppendEntries:
    term: int
    leader: int
    prev_index: int
    prev_term: int
    entries: List[Tuple[int, Any]]  # (term, command)
    leader_commit: int


@dataclass
class AppendReply:
    term: int
    success: bool
    match_index: int


FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftInvariantViolation(AssertionError):
    """Election safety / log matching violated — the 'bug flag' of the sim."""


class InvariantChecker:
    """Cross-node white-box checker (a simulation superpower: all nodes are
    in-process, so safety properties are asserted globally and instantly)."""

    def __init__(self):
        self.leaders_by_term: Dict[int, int] = {}
        self.committed: List[Tuple[int, Any]] = []  # longest committed prefix

    def on_become_leader(self, node: int, term: int) -> None:
        prev = self.leaders_by_term.setdefault(term, node)
        if prev != node:
            raise RaftInvariantViolation(
                f"election safety violated: term {term} has leaders {prev} and {node}"
            )

    def on_commit(self, node: int, log: List[Tuple[int, Any]], commit_index: int) -> None:
        prefix = log[:commit_index]
        n = min(len(prefix), len(self.committed))
        if prefix[:n] != self.committed[:n]:
            raise RaftInvariantViolation(
                f"log matching violated at node {node}: committed prefixes diverge"
            )
        if len(prefix) > len(self.committed):
            self.committed = list(prefix)


@dataclass
class RaftOptions:
    election_timeout: Tuple[float, float] = (0.15, 0.30)  # seconds, randomized
    heartbeat_interval: float = 0.05
    rpc_timeout: float = 0.10
    port: int = 7000
    persist: bool = True  # durable term/vote/log via the simulated fs
    # Injected bug (same switch as engine/raft_actor.py RaftDeviceConfig):
    # grant votes ignoring the one-vote-per-term rule, so seed sweeps have a
    # real election-safety violation to find. Used by the host↔device
    # cross-validation benchmark (bench.py time-to-first-bug).
    buggy_double_vote: bool = False


class RaftServer:
    """One Raft peer. Runs as a node's init task; survives crash-restart by
    reloading persistent state from the simulated disk."""

    def __init__(self, me: int, peers: List[str], checker: InvariantChecker,
                 opts: RaftOptions):
        self.me = me
        self.peers = peers  # ip strings, index == node index
        self.checker = checker
        self.opts = opts
        # Persistent state
        self.term = 0
        self.voted_for: Optional[int] = None
        self.log: List[Tuple[int, Any]] = []  # 1-based indexing helpers below
        # Volatile
        self.role = FOLLOWER
        self.commit_index = 0
        self.last_applied = 0
        self.applied: List[Any] = []
        self.leader_hint: Optional[int] = None
        self._last_heartbeat = 0.0
        self._ep: Optional[Endpoint] = None
        self._node: Optional[ms.NodeHandle] = None  # set in serve()
        # Leader volatile
        self.next_index: Dict[int, int] = {}
        self.match_index: Dict[int, int] = {}

    # -- log helpers (1-based) ---------------------------------------------
    def last_log_index(self) -> int:
        return len(self.log)

    def log_term(self, index: int) -> int:
        if index == 0:
            return 0
        return self.log[index - 1][0]

    # -- persistence --------------------------------------------------------
    async def _persist(self) -> None:
        if not self.opts.persist:
            return
        import pickle

        blob = pickle.dumps((self.term, self.voted_for, self.log))
        f = await fs.File.open_or_create("/raft-state")
        await f.set_len(0)
        await f.write_all_at(blob, 0)
        await f.sync_all()

    async def _restore(self) -> None:
        if not self.opts.persist:
            return
        import pickle

        try:
            blob = await fs.read("/raft-state")
        except FileNotFoundError:
            return
        if blob:
            self.term, self.voted_for, self.log = pickle.loads(blob)

    # -- role transitions ----------------------------------------------------
    async def _become_follower(self, term: int) -> None:
        self.role = FOLLOWER
        if term > self.term:
            self.term = term
            self.voted_for = None
            await self._persist()

    async def _become_leader(self) -> None:
        self.role = LEADER
        self.checker.on_become_leader(self.me, self.term)
        n = self.last_log_index() + 1
        self.next_index = {i: n for i in range(len(self.peers))}
        self.match_index = {i: 0 for i in range(len(self.peers))}
        self.match_index[self.me] = self.last_log_index()
        task.spawn(self._heartbeat_loop(self.term))

    # -- main ---------------------------------------------------------------
    async def serve(self) -> None:
        self._node = task.current_node()
        await self._restore()
        self._ep = await Endpoint.bind((self.peers[self.me], self.opts.port))
        msrpc.add_rpc_handler(self._ep, RequestVote, self._on_request_vote)
        msrpc.add_rpc_handler(self._ep, AppendEntries, self._on_append_entries)
        self._last_heartbeat = time.monotonic()
        await self._election_loop()

    async def _election_loop(self) -> None:
        while True:
            timeout = rand.thread_rng().gen_range_f64(*self.opts.election_timeout)
            await time.sleep(timeout)
            if self.role == LEADER:
                continue
            if time.monotonic() - self._last_heartbeat < timeout:
                continue
            await self._start_election()

    async def _start_election(self) -> None:
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.me
        await self._persist()
        term = self.term
        votes = [self.me]
        won = ms.sync.Event()

        async def ask(peer: int):
            req = RequestVote(term, self.me, self.last_log_index(),
                              self.log_term(self.last_log_index()))
            try:
                reply = await msrpc.call(self._ep, (self.peers[peer], self.opts.port),
                                         req, timeout=self.opts.rpc_timeout)
            except (TimeoutError, OSError):
                return
            if reply.term > self.term:
                await self._become_follower(reply.term)
                return
            if self.role == CANDIDATE and self.term == term and reply.granted:
                votes.append(peer)
                if len(votes) > len(self.peers) // 2:
                    won.set()

        for peer in range(len(self.peers)):
            if peer != self.me:
                task.spawn(ask(peer))
        try:
            await time.timeout(self.opts.election_timeout[0], won.wait())
        except TimeoutError:
            return  # election failed; loop will retry with a new timeout
        if self.role == CANDIDATE and self.term == term:
            await self._become_leader()

    async def _heartbeat_loop(self, term: int) -> None:
        while self.role == LEADER and self.term == term:
            for peer in range(len(self.peers)):
                if peer != self.me:
                    task.spawn(self._replicate_to(peer, term))
            await time.sleep(self.opts.heartbeat_interval)

    async def _replicate_to(self, peer: int, term: int) -> None:
        if self.role != LEADER or self.term != term:
            return
        next_i = self.next_index[peer]
        prev_index = next_i - 1
        entries = list(self.log[next_i - 1:])
        req = AppendEntries(term, self.me, prev_index, self.log_term(prev_index),
                            entries, self.commit_index)
        try:
            reply = await msrpc.call(self._ep, (self.peers[peer], self.opts.port),
                                     req, timeout=self.opts.rpc_timeout)
        except (TimeoutError, OSError):
            return
        if reply.term > self.term:
            await self._become_follower(reply.term)
            return
        if self.role != LEADER or self.term != term:
            return
        if reply.success:
            self.match_index[peer] = max(self.match_index[peer], reply.match_index)
            self.next_index[peer] = self.match_index[peer] + 1
            self._advance_commit()
        else:
            self.next_index[peer] = max(1, self.next_index[peer] - 1)

    def _advance_commit(self) -> None:
        for n in range(self.last_log_index(), self.commit_index, -1):
            if self.log_term(n) != self.term:
                continue
            count = sum(1 for i in range(len(self.peers)) if self.match_index.get(i, 0) >= n)
            if count > len(self.peers) // 2:
                self.commit_index = n
                self._apply()
                break

    def _apply(self) -> None:
        self.checker.on_commit(self.me, self.log, self.commit_index)
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            self.applied.append(self.log[self.last_applied - 1][1])

    # -- RPC handlers --------------------------------------------------------
    async def _on_request_vote(self, req: RequestVote) -> VoteReply:
        if req.term > self.term:
            await self._become_follower(req.term)
        if req.term < self.term:
            return VoteReply(self.term, False)
        up_to_date = (req.last_log_term, req.last_log_index) >= (
            self.log_term(self.last_log_index()), self.last_log_index())
        can_vote = (True if self.opts.buggy_double_vote
                    else self.voted_for in (None, req.candidate))
        if up_to_date and can_vote:
            self.voted_for = req.candidate
            await self._persist()
            self._last_heartbeat = time.monotonic()
            return VoteReply(self.term, True)
        return VoteReply(self.term, False)

    async def _on_append_entries(self, req: AppendEntries) -> AppendReply:
        if req.term > self.term or (req.term == self.term and self.role == CANDIDATE):
            await self._become_follower(req.term)
        if req.term < self.term:
            return AppendReply(self.term, False, 0)
        self._last_heartbeat = time.monotonic()
        self.leader_hint = req.leader
        if req.prev_index > self.last_log_index() or \
                self.log_term(req.prev_index) != req.prev_term:
            return AppendReply(self.term, False, 0)
        # Append / overwrite conflicting suffix
        changed = False
        for k, entry in enumerate(req.entries):
            idx = req.prev_index + 1 + k
            if idx <= self.last_log_index():
                if self.log[idx - 1] != entry:
                    del self.log[idx - 1:]
                    self.log.append(entry)
                    changed = True
            else:
                self.log.append(entry)
                changed = True
        if changed:
            await self._persist()
        if req.leader_commit > self.commit_index:
            self.commit_index = min(req.leader_commit, self.last_log_index())
            self._apply()
        return AppendReply(self.term, True, req.prev_index + len(req.entries))

    # -- client interface ----------------------------------------------------
    def start(self, command: Any) -> Optional[Tuple[int, int]]:
        """Leader-side propose: append to local log → (index, term), or None
        if this server is not the leader."""
        if self.role != LEADER:
            return None
        self.log.append((self.term, command))
        self.match_index[self.me] = self.last_log_index()
        # Spawn on *this server's* node: persistence must hit this node's
        # disk and replication tasks must die with this node, even when
        # start() is called from a client/supervisor task elsewhere.
        self._node.spawn(self._persist())
        term = self.term
        for peer in range(len(self.peers)):
            if peer != self.me:
                self._node.spawn(self._replicate_to(peer, term))
        return self.last_log_index(), self.term


class RaftCluster:
    """N Raft peers as simulated nodes, plus chaos/observation helpers."""

    def __init__(self, n: int, opts: Optional[RaftOptions] = None,
                 ip_prefix: str = "10.0.1."):
        self.n = n
        self.opts = opts or RaftOptions()
        self.checker = InvariantChecker()
        self.ips = [f"{ip_prefix}{i + 1}" for i in range(n)]
        self.servers: Dict[int, RaftServer] = {}
        self.nodes: List[ms.NodeHandle] = []
        handle = ms.Handle.current()
        for i in range(n):
            self.nodes.append(handle.create_node(
                name=f"raft-{i}", ip=self.ips[i], init=self._make_init(i)))

    def _make_init(self, i: int):
        async def init():
            server = RaftServer(i, self.ips, self.checker, self.opts)
            self.servers[i] = server
            await server.serve()

        return init

    # -- observation --------------------------------------------------------
    def leader(self) -> Optional[int]:
        leaders = [i for i, s in self.servers.items()
                   if s.role == LEADER and not self._is_killed(i)]
        if not leaders:
            return None
        # Highest term wins (stale leaders may linger across partitions).
        return max(leaders, key=lambda i: self.servers[i].term)

    def _is_killed(self, i: int) -> bool:
        return not self.nodes[i].is_alive()

    async def wait_for_leader(self, timeout: float = 10.0) -> int:
        async def waiter():
            while True:
                lead = self.leader()
                if lead is not None:
                    return lead
                await time.sleep(0.01)

        return await time.timeout(timeout, waiter())

    async def propose(self, command: Any, timeout: float = 10.0) -> Tuple[int, int]:
        """Find the leader, propose, and wait for commit."""

        async def attempt():
            while True:
                lead = self.leader()
                if lead is None:
                    await time.sleep(0.02)
                    continue
                # kill()/restart() pop the server entry; a concurrent kill
                # during any of the sleeps below must read as "leadership
                # lost: retry", never KeyError.
                server = self.servers.get(lead)
                if server is None:
                    await time.sleep(0.02)
                    continue
                started = server.start(command)
                if started is None:
                    await time.sleep(0.02)
                    continue
                index, term = started
                while True:
                    server = self.servers.get(lead)
                    if server is None:
                        break  # leader killed mid-commit: retry from scratch
                    if server.commit_index >= index and \
                            server.last_log_index() >= index and \
                            server.log_term(index) == term:
                        return index, term
                    if server.role != LEADER or server.term != term or self._is_killed(lead):
                        break  # leadership lost: retry from scratch
                    await time.sleep(0.01)

        return await time.timeout(timeout, attempt())

    # -- chaos --------------------------------------------------------------
    def kill(self, i: int) -> None:
        ms.Handle.current().kill(self.nodes[i])
        # Drop the orphaned server object immediately: observers must not act
        # on it between kill and the respawned init re-registering.
        self.servers.pop(i, None)

    def restart(self, i: int) -> None:
        ms.Handle.current().restart(self.nodes[i])
        # The replacement registers itself when the init task runs; until
        # then no server for i must be visible to leader()/propose().
        self.servers.pop(i, None)

    def partition(self, group_a: List[int], group_b: List[int]) -> None:
        from madsim_tpu.net import NetSim

        sim = ms.simulator(NetSim)
        for a in group_a:
            for b in group_b:
                sim.disconnect2(self.nodes[a].id, self.nodes[b].id)

    def heal(self) -> None:
        from madsim_tpu.net import NetSim

        sim = ms.simulator(NetSim)
        for a in range(self.n):
            for b in range(self.n):
                if a != b:
                    sim.connect2(self.nodes[a].id, self.nodes[b].id)
