"""Drop-in ``grpc.aio`` surface over the simulated network.

The madsim-tonic model (`madsim-tonic/src/lib.rs:1-8`): *outside* a
simulation the real grpc package is untouched; *inside* one, the patched
``grpc.aio.server()`` / ``grpc.aio.insecure_channel()`` return sim
implementations speaking grpc_sim's boxed-message protocol — so unmodified
code written against grpcio's async API (including protoc/grpcio-generated
stubs, which only consume this surface) runs deterministically in-sim.

What generated code needs, and what is provided here:

- client side: ``channel.unary_unary/unary_stream/stream_unary/
  stream_stream(path, request_serializer=..., response_deserializer=...)``
  multicallables (+ async context manager on the channel);
- server side: ``server.add_generic_rpc_handlers(...)`` (the object built
  by ``grpc.method_handlers_generic_handler``), grpcio>=1.60's
  ``add_registered_method_handlers``, ``add_insecure_port``, ``start``,
  ``wait_for_termination``, ``stop``;
- errors: sim failures raise a ``grpc.RpcError`` subclass with
  ``code()``/``details()`` so unmodified ``except grpc.RpcError`` handlers
  work.

Serializers are honored when present — messages cross the simulated wire
as real serialized bytes (protobuf or otherwise), exercising the app's
codec exactly as the real transport would (`madsim-tonic`'s BoxMessage
skips this; bytes are the stronger fidelity choice for Python where the
serializer is first-class).
"""
from __future__ import annotations

import contextlib
from typing import Any, AsyncIterator, Callable, Dict, Optional, Tuple

import grpc as _grpc

from .. import task as _task
from .. import time as _vtime
from ..core import context as _context
from ..core.futures import Cancelled, ChannelClosed
from ..net import Endpoint
from ..net.addr import AddrLike, lookup_host
from ..net.netsim import BrokenPipe, ConnectionRefused, ConnectionReset
from . import grpc_sim
from .grpc_sim import _END, _pump, _request_stream

_KINDS = {
    (False, False): "unary_unary",
    (False, True): "unary_stream",
    (True, False): "stream_unary",
    (True, True): "stream_stream",
}


class SimAioRpcError(_grpc.RpcError):
    """In-sim RPC failure, catchable as grpc.RpcError by unmodified code."""

    def __init__(self, code: _grpc.StatusCode, details: str = ""):
        super().__init__(f"{code.name}: {details}")
        self._code = code
        self._details = details

    def code(self) -> _grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details


def _to_grpc_code(code) -> _grpc.StatusCode:
    return getattr(_grpc.StatusCode, code.name, _grpc.StatusCode.UNKNOWN)


def _raise_status(status: grpc_sim.Status) -> None:
    raise SimAioRpcError(_to_grpc_code(status.code), status.details)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

async def _as_aiter(result):
    """Adapt a streaming handler's return into an async iterator.

    An async-generator method yields directly; a plain coroutine (e.g. an
    unoverridden protoc-style Servicer base method, which raises
    NotImplementedError when awaited) is awaited first — so unimplemented
    streaming methods surface UNIMPLEMENTED, not a TypeError→INTERNAL."""
    if hasattr(result, "__aiter__"):
        async for item in result:
            yield item
        return
    awaited = await result
    if awaited is None:
        return
    async for item in awaited:
        yield item


class _HandlerCallDetails:
    __slots__ = ("method", "invocation_metadata")

    def __init__(self, method: str):
        self.method = method
        self.invocation_metadata = ()


class SimAioServer:
    """grpc.aio.Server-shaped server over the sim endpoint transport."""

    def __init__(self):
        self._generic_handlers = []
        self._registered: Dict[str, Any] = {}
        self._ports = []
        self._ep: Optional[Endpoint] = None
        self._accept_task = None
        self._stopped = None
        self._in_flight: list = []  # live _handle_conn tasks (for drain)

    # -- registration (both grpcio generated-code generations) -------------
    def add_generic_rpc_handlers(self, handlers) -> None:
        self._generic_handlers.extend(handlers)

    def add_registered_method_handlers(self, service_name: str,
                                       method_handlers: Dict[str, Any]) -> None:
        for method, handler in method_handlers.items():
            self._registered[f"/{service_name}/{method}"] = handler

    def add_insecure_port(self, address: str) -> int:
        port = int(str(address).rsplit(":", 1)[1])
        if port == 0:
            # Ephemeral ports can't be returned from this sync call in-sim
            # (binding is async); simulations own their address space, so a
            # fixed virtual port is the idiom. Fail loudly over misrouting.
            raise ValueError(
                "in-sim grpc server cannot bind port 0; pick a fixed "
                "virtual port (the simulation owns the address space)")
        if self._ports:
            raise ValueError("in-sim grpc server supports a single port")
        self._ports.append(address)
        return port

    def add_secure_port(self, address: str, credentials=None) -> int:
        # TLS has no meaning in-sim (`madsim-tonic` accepts and ignores it).
        return self.add_insecure_port(address)

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> None:
        if not self._ports:
            raise RuntimeError("add_insecure_port before start")
        from .. import sync as _sync

        self._stopped = _sync.Event()
        self._ep = await Endpoint.bind(self._ports[0])
        self._accept_task = _task.spawn(self._accept_loop())

    async def wait_for_termination(self, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            await self._stopped.wait()
            return True
        try:
            await _vtime.timeout(timeout, self._stopped.wait())
            return True
        except TimeoutError:
            return False

    async def stop(self, grace: Optional[float] = None) -> None:
        """Stop accepting, then drain in-flight RPCs for up to ``grace``
        seconds before tearing the transport down (the grpc.aio contract;
        grace=None waits for all in-flight calls)."""
        if self._accept_task is not None:
            self._accept_task.abort()
        live = [t for t in self._in_flight if not t.is_finished()]
        if live:
            async def drain():
                for t in live:
                    try:
                        await t
                    except (Cancelled, ChannelClosed):
                        pass

            if grace is None:
                await drain()
            else:
                try:
                    await _vtime.timeout(grace, drain())
                except TimeoutError:
                    for t in live:
                        t.abort()
        self._in_flight.clear()
        if self._ep is not None:
            self._ep.close()
        if self._stopped is not None:
            self._stopped.set()

    # -- dispatch -----------------------------------------------------------
    def _resolve(self, path: str):
        handler = self._registered.get(path)
        if handler is None:
            details = _HandlerCallDetails(path)
            for gh in self._generic_handlers:
                handler = gh.service(details)
                if handler is not None:
                    break
        return handler

    async def _accept_loop(self) -> None:
        while True:
            try:
                tx, rx, src = await self._ep.accept1()
            except (ConnectionReset, ChannelClosed):
                return
            self._in_flight.append(_task.spawn(self._handle_conn(tx, rx, src)))
            if len(self._in_flight) > 64:  # prune completed handlers
                self._in_flight = [t for t in self._in_flight
                                   if not t.is_finished()]

    async def _handle_conn(self, tx, rx, src) -> None:
        try:
            path, first = await rx.recv()
        except (ChannelClosed, BrokenPipe, ConnectionReset):
            return
        ctx = grpc_sim.ServicerContext(src)
        try:
            handler = self._resolve(path)
            if handler is None:
                raise grpc_sim.Status(grpc_sim.StatusCode.UNIMPLEMENTED,
                                      f"unknown path {path}")
            deser = handler.request_deserializer or (lambda b: b)
            ser = handler.response_serializer or (lambda m: m)
            kind = _KINDS[(handler.request_streaming,
                           handler.response_streaming)]
            fn = getattr(handler, kind)

            async def req_iter():
                async for raw in _request_stream(rx):
                    yield deser(raw)

            if kind == "unary_unary":
                rsp = await fn(deser(first), ctx)
                await self._finish_unary(tx, ctx, ser, rsp)
            elif kind == "unary_stream":
                async for rsp in _as_aiter(fn(deser(first), ctx)):
                    await tx.send(("ok", ser(rsp)))
                await self._finish_stream(tx, ctx)
            elif kind == "stream_unary":
                rsp = await fn(req_iter(), ctx)
                await self._finish_unary(tx, ctx, ser, rsp)
            else:  # stream_stream
                async for rsp in _as_aiter(fn(req_iter(), ctx)):
                    await tx.send(("ok", ser(rsp)))
                await self._finish_stream(tx, ctx)
        except grpc_sim.Status as status:
            await grpc_sim._try_send(tx, ("err", status))
        except NotImplementedError as exc:
            # protoc-generated Servicer bases raise this after
            # context.set_code(UNIMPLEMENTED); real grpcio surfaces the
            # context code, so mirror that here.
            status = ctx.trailing_status() or grpc_sim.Status(
                grpc_sim.StatusCode.UNIMPLEMENTED, str(exc))
            await grpc_sim._try_send(tx, ("err", status))
        except (ChannelClosed, BrokenPipe, ConnectionReset, Cancelled):
            pass
        except Exception as exc:  # noqa: BLE001 — surface as INTERNAL
            await grpc_sim._try_send(
                tx, ("err", grpc_sim.Status(grpc_sim.StatusCode.INTERNAL,
                                            repr(exc))))
        finally:
            tx.close()

    @staticmethod
    async def _finish_unary(tx, ctx, ser, rsp) -> None:
        status = ctx.trailing_status()
        if status is not None:
            await grpc_sim._try_send(tx, ("err", status))
        else:
            await tx.send(("ok", ser(rsp)))

    @staticmethod
    async def _finish_stream(tx, ctx) -> None:
        status = ctx.trailing_status()
        if status is not None:
            await grpc_sim._try_send(tx, ("err", status))
        else:
            await tx.send(_END)


# ---------------------------------------------------------------------------
# Channel + multicallables
# ---------------------------------------------------------------------------

class _MultiCallable:
    def __init__(self, channel: "SimAioChannel", path: str,
                 request_serializer, response_deserializer,
                 req_streaming: bool, rsp_streaming: bool):
        self._channel = channel
        self._path = path
        self._ser = request_serializer or (lambda m: m)
        self._deser = response_deserializer or (lambda b: b)
        self._req_streaming = req_streaming
        self._rsp_streaming = rsp_streaming

    def __call__(self, request=None, *, timeout: Optional[float] = None,
                 metadata=None, credentials=None, wait_for_ready=None,
                 compression=None):
        if self._rsp_streaming:
            return self._stream_call(request, timeout)
        return self._unary_call(request, timeout)

    async def _open(self, request):
        ch = self._channel
        # Lazy endpoint bind: generated stubs construct multicallables
        # synchronously in Stub.__init__, before any loop exists.
        await ch._ensure()
        try:
            tx, rx = await ch._ep.connect1(ch._target)
            if self._req_streaming:
                await tx.send((self._path, None))
            else:
                await tx.send((self._path, self._ser(request)))
        except (BrokenPipe, ConnectionRefused, ConnectionReset,
                ChannelClosed) as exc:
            raise SimAioRpcError(_grpc.StatusCode.UNAVAILABLE,
                                 f"connect: {exc}") from exc
        return tx, rx

    async def _serialized(self, request_iterator):
        async for req in request_iterator:
            yield self._ser(req)

    def _spawn_pump(self, tx, requests):
        """Spawn the request pump with exception containment: an app-level
        error in the caller's request iterator must propagate to the stub
        caller, not crash the whole simulation via an uncaught-task path."""
        box: list = []

        async def run():
            try:
                await _pump(tx, requests)
            except Cancelled:
                raise
            except Exception as exc:  # noqa: BLE001 — rethrown to caller
                box.append(exc)
                tx.close()  # unblock the server / our recv

        return _task.spawn(run()), box

    async def _unary_call(self, request, timeout):
        async def _go():
            tx, rx = await self._open(request)
            pump, box = None, []
            try:
                if self._req_streaming:
                    # Concurrent pump: the server may respond (or error)
                    # after consuming only part of the request stream, and
                    # the iterator may be gated on application progress.
                    pump, box = self._spawn_pump(tx, self._serialized(request))
                try:
                    return self._deser(self._unwrap(await self._recv(rx)))
                except SimAioRpcError:
                    if box:
                        raise box[0] from None
                    raise
            finally:
                if pump is not None:
                    pump.abort()
                tx.close()

        if timeout is None:
            return await _go()
        try:
            return await _vtime.timeout(timeout, _go())
        except TimeoutError:
            raise SimAioRpcError(_grpc.StatusCode.DEADLINE_EXCEEDED,
                                 f"{self._path}") from None

    async def _stream_call(self, request, timeout) -> AsyncIterator[Any]:
        # Per-message deadline is not simulated; stream calls ignore timeout
        # (matching madsim-tonic, which ignores transport knobs wholesale).
        tx, rx = await self._open(request)
        pump, box = None, []
        if self._req_streaming:
            pump, box = self._spawn_pump(tx, self._serialized(request))
        try:
            while True:
                try:
                    frame = await rx.recv()
                except (ChannelClosed, BrokenPipe, ConnectionReset) as exc:
                    if box:
                        raise box[0] from None  # the app's iterator error
                    # Connection lost before the _END frame: real grpc.aio
                    # raises UNAVAILABLE; a silent clean EOF would hand
                    # unmodified code truncated streams.
                    raise SimAioRpcError(_grpc.StatusCode.UNAVAILABLE,
                                         f"stream broken: {exc}") from exc
                if frame == _END:
                    return
                yield self._deser(self._unwrap(frame))
        finally:
            if pump is not None:
                pump.abort()
            tx.close()

    async def _recv(self, rx):
        try:
            return await rx.recv()
        except (ChannelClosed, BrokenPipe, ConnectionReset) as exc:
            raise SimAioRpcError(_grpc.StatusCode.UNAVAILABLE,
                                 f"recv: {exc}") from exc

    @staticmethod
    def _unwrap(frame):
        kind, value = frame
        if kind == "ok":
            return value
        if kind == "err":
            _raise_status(value)
        raise SimAioRpcError(_grpc.StatusCode.INTERNAL,
                             f"unexpected frame {kind!r}")


class SimAioChannel:
    """grpc.aio.Channel-shaped client over the sim endpoint transport."""

    def __init__(self, target: str):
        self._target_str = target
        self._target = None
        self._ep: Optional[Endpoint] = None
        self._ensuring = None

    async def _ensure(self) -> None:
        # Single-flight: concurrent first RPCs (gather of stub calls) must
        # not each bind an endpoint and leak the loser's port.
        from ..core.futures import SimFuture

        if self._ep is not None:
            return
        if self._ensuring is not None:
            await self._ensuring
            return
        self._ensuring = SimFuture()
        try:
            # Resolve first: a bad target must not leak a bound endpoint
            # on every retry.
            self._target = (await lookup_host(self._target_str))[0]
            self._ep = await Endpoint.bind("0.0.0.0:0")
            self._ensuring.set_result(None)
        except BaseException as exc:
            self._ensuring.set_exception(exc)
            self._ensuring = None
            raise

    def _mc(self, path, req_ser, rsp_deser, req_s, rsp_s) -> _MultiCallable:
        return _MultiCallable(self, path, req_ser, rsp_deser, req_s, rsp_s)

    def unary_unary(self, path, request_serializer=None,
                    response_deserializer=None, **_kw):
        return self._mc(path, request_serializer, response_deserializer,
                        False, False)

    def unary_stream(self, path, request_serializer=None,
                     response_deserializer=None, **_kw):
        return self._mc(path, request_serializer, response_deserializer,
                        False, True)

    def stream_unary(self, path, request_serializer=None,
                     response_deserializer=None, **_kw):
        return self._mc(path, request_serializer, response_deserializer,
                        True, False)

    def stream_stream(self, path, request_serializer=None,
                      response_deserializer=None, **_kw):
        return self._mc(path, request_serializer, response_deserializer,
                        True, True)

    async def channel_ready(self) -> None:
        await self._ensure()

    async def close(self, grace: Optional[float] = None) -> None:
        if self._ep is not None:
            self._ep.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()
        return False


# ---------------------------------------------------------------------------
# The import hook: patch grpc.aio with in-sim passthrough wrappers
# ---------------------------------------------------------------------------

def _in_sim() -> bool:
    return _context.try_current_handle() is not None


_PATCHES = None


def install() -> None:
    """Patch ``grpc.aio.server``/``insecure_channel`` so unmodified grpcio
    client/server code runs in-sim; outside a simulation the real grpc
    implementations are called unchanged (`madsim-tonic/src/lib.rs:1-8`)."""
    global _PATCHES
    if _PATCHES is not None:
        return
    aio = _grpc.aio
    saved = {"server": aio.server, "insecure_channel": aio.insecure_channel,
             "secure_channel": aio.secure_channel}

    def server(*args, **kwargs):
        return SimAioServer() if _in_sim() else saved["server"](*args, **kwargs)

    def insecure_channel(target, *args, **kwargs):
        if _in_sim():
            return SimAioChannel(target)
        return saved["insecure_channel"](target, *args, **kwargs)

    def secure_channel(target, credentials, *args, **kwargs):
        if _in_sim():
            return SimAioChannel(target)  # TLS ignored in-sim
        return saved["secure_channel"](target, credentials, *args, **kwargs)

    aio.server = server
    aio.insecure_channel = insecure_channel
    aio.secure_channel = secure_channel
    _PATCHES = saved


def uninstall() -> None:
    global _PATCHES
    if _PATCHES is None:
        return
    for name, orig in _PATCHES.items():
        setattr(_grpc.aio, name, orig)
    _PATCHES = None


@contextlib.contextmanager
def patched():
    """``with grpc_aio.patched():`` — install() for the block's duration."""
    was_installed = _PATCHES is not None
    install()
    try:
        yield
    finally:
        if not was_installed:
            uninstall()
