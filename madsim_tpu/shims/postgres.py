"""PostgreSQL v3 wire-protocol client over the simulated network.

The madsim-tokio-postgres analog (SURVEY §2.15): the reference vendors the
real tokio-postgres client and runs its unchanged protocol machinery over the
simulated TcpStream, proving the shim strategy scales to a real protocol.
This module does the Python equivalent: a faithful implementation of the
PostgreSQL frontend/backend protocol (startup, simple-query flow, AND the
extended-query flow — Parse/Bind/Describe/Execute/Close/Sync with
ParseComplete/BindComplete/ParameterDescription/NoData/PortalSuspended
framing, per
https://www.postgresql.org/docs/current/protocol-message-formats.html —
matching what the vendored reference client exercises in prepare.rs /
transaction.rs / codec.rs) speaking through
:class:`madsim_tpu.net.TcpStream`, so every byte crosses the simulated
network with latency/loss/partition semantics.

Transactions follow the backend contract: ReadyForQuery carries the
transaction status byte (I idle / T in-transaction / E failed), errors
inside a transaction poison it (further statements fail with sqlstate
25P02) until ROLLBACK, and extended-protocol errors skip to Sync.

Where the reference needs a live out-of-process PostgreSQL server (its test
suite is excluded from CI for exactly that reason, reference `Makefile:12-16`),
the simulation can host the server *inside the world*: :class:`SimPostgresServer`
is a protocol-correct backend with a toy table engine, so client↔server runs
under seed sweeps, clock skew, and fault injection like any other workload.
"""
from __future__ import annotations

import re
import struct
from typing import Dict, List, Optional, Tuple

from .. import task as _task
from ..net.netsim import BrokenPipe, ConnectionReset
from ..net.tcp import TcpListener, TcpStream

PROTOCOL_VERSION = 196608  # 3.0


class PostgresError(Exception):
    """Server-reported error (ErrorResponse 'E')."""

    def __init__(self, severity: str, code: str, message: str):
        super().__init__(f"{severity} {code}: {message}")
        self.severity = severity
        self.code = code
        self.message = message


# ---------------------------------------------------------------------------
# Wire helpers
# ---------------------------------------------------------------------------

def _msg(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\0"


async def _read_message(stream: TcpStream) -> Tuple[bytes, bytes]:
    """Read one typed backend/frontend message → (type, payload)."""
    head = await stream.read_exact(5)
    mtype = head[:1]
    (length,) = struct.unpack("!I", head[1:5])
    payload = await stream.read_exact(length - 4) if length > 4 else b""
    return mtype, payload


def _split_cstrs(buf: bytes) -> List[str]:
    return [p.decode() for p in buf.split(b"\0")[:-1]]


def _parse_error(payload: bytes, default_severity: str = "ERROR",
                 default_message: str = "unknown") -> PostgresError:
    """Decode an ErrorResponse payload's field list into a PostgresError."""
    fields = dict((c[0], c[1:]) for c in _split_cstrs(payload) if c)
    return PostgresError(fields.get("S", default_severity),
                         fields.get("C", "XX000"),
                         fields.get("M", default_message))


# ---------------------------------------------------------------------------
# COPY text-format codec (protocol "COPY file formats", text mode): rows are
# newline-terminated, columns tab-separated, NULL is \N, and backslash, tab,
# newline, and carriage return are backslash-escaped in data.
# ---------------------------------------------------------------------------

def copy_encode_row(values: List[Optional[str]]) -> bytes:
    cols = []
    for v in values:
        if v is None:
            cols.append("\\N")
        else:
            cols.append(str(v).replace("\\", "\\\\").replace("\t", "\\t")
                        .replace("\n", "\\n").replace("\r", "\\r"))
    return ("\t".join(cols) + "\n").encode()


def _copy_unescape(field: str) -> Optional[str]:
    if field == "\\N":
        return None
    out: List[str] = []
    i, n = 0, len(field)
    while i < n:
        c = field[i]
        if c == "\\" and i + 1 < n:
            nxt = field[i + 1]
            out.append({"t": "\t", "n": "\n", "r": "\r"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def copy_decode(data: bytes) -> List[List[Optional[str]]]:
    text = data.decode()
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()  # fragment after the final row terminator, not a row
    rows: List[List[Optional[str]]] = []
    for line in lines:
        if line == "\\.":  # end-of-data marker terminates the stream
            break
        rows.append([_copy_unescape(f) for f in line.split("\t")])
    return rows


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

class Row(tuple):
    """A result row; column access by index or, via .get, by name."""

    def __new__(cls, values, columns):
        row = super().__new__(cls, values)
        row._columns = columns
        return row

    def get(self, name: str):
        return self[self._columns.index(name)]


class PreparedStatement:
    """A server-side prepared statement (Parse'd and Describe'd)."""

    __slots__ = ("name", "sql", "columns", "n_params")

    def __init__(self, name: str, sql: str, columns: List[str], n_params: int):
        self.name = name
        self.sql = sql
        self.columns = columns  # [] for statements returning no rows
        self.n_params = n_params


class CopyInWriter:
    """Sink side of ``COPY ... FROM STDIN`` (reference copy_in.rs analog:
    the CopyInSink the vendored client returns). Stream raw text-format
    bytes with :meth:`write`, or rows with :meth:`write_row`; then
    :meth:`finish` (→ rows copied) or :meth:`fail` to abort."""

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._done = False

    async def write(self, data: bytes) -> None:
        if self._done:
            raise PostgresError("ERROR", "08P01",
                                "COPY-in already finished on this writer")
        await self._conn._stream.write_all(_msg(b"d", data))

    async def write_row(self, values: List[Optional[str]]) -> None:
        await self.write(copy_encode_row(values))

    async def finish(self) -> int:
        """CopyDone; returns the server-reported copied-row count."""
        self._done = True
        await self._conn._stream.write_all(_msg(b"c", b""))
        await self._conn._read_until_ready()
        tag = self._conn._last_tag
        return int(tag.rsplit(" ", 1)[1]) if tag.startswith("COPY ") else 0

    async def fail(self, message: str = "aborted") -> None:
        """CopyFail: the server discards the data and reports 57014."""
        self._done = True
        await self._conn._stream.write_all(_msg(b"f", _cstr(message)))
        try:
            await self._conn._read_until_ready()
        except PostgresError:
            pass  # the expected "COPY from stdin failed" error


class Transaction:
    """``async with conn.transaction():`` — BEGIN, then COMMIT on clean
    exit / ROLLBACK on exception (reference transaction.rs semantics)."""

    def __init__(self, conn: "Connection"):
        self._conn = conn

    async def __aenter__(self) -> "Connection":
        await self._conn.execute("BEGIN")
        return self._conn

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            await self._conn.execute("COMMIT")
        else:
            try:
                await self._conn.execute("ROLLBACK")
            except (PostgresError, BrokenPipe, ConnectionReset):
                pass  # the original exception matters more
        return False


class Connection:
    """A connected PostgreSQL session (simple + extended query protocol)."""

    def __init__(self, stream: TcpStream, parameters: Dict[str, str]):
        self._stream = stream
        self.parameters = parameters  # ParameterStatus values from startup
        self._closed = False
        self.txn_status = "I"  # ReadyForQuery status: I / T / E
        self._stmt_counter = 0  # deterministic auto-generated stmt names
        self._last_tag = ""  # most recent CommandComplete tag

    # -- shared response pump ---------------------------------------------
    async def _read_until_ready(self) -> Tuple[List[Row], List[str], int]:
        """Consume messages until ReadyForQuery; raise the first error."""
        columns: List[str] = []
        rows: List[Row] = []
        n_params = 0
        error: Optional[PostgresError] = None
        while True:
            mtype, payload = await _read_message(self._stream)
            if mtype == b"T":  # RowDescription
                (nfields,) = struct.unpack("!H", payload[:2])
                off = 2
                columns = []
                for _ in range(nfields):
                    end = payload.index(b"\0", off)
                    columns.append(payload[off:end].decode())
                    off = end + 1 + 18  # fixed per-field descriptor tail
            elif mtype == b"D":  # DataRow
                (ncols,) = struct.unpack("!H", payload[:2])
                off = 2
                values = []
                for _ in range(ncols):
                    (vlen,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if vlen < 0:
                        values.append(None)
                    else:
                        values.append(payload[off:off + vlen].decode())
                        off += vlen
                rows.append(Row(values, columns))
            elif mtype == b"t":  # ParameterDescription
                (n_params,) = struct.unpack("!H", payload[:2])
            elif mtype == b"C":  # CommandComplete — keep the tag ("COPY 3")
                self._last_tag = payload.rstrip(b"\0").decode()
            elif mtype in (b"1", b"2", b"3", b"n", b"s", b"I"):
                # ParseComplete / BindComplete / CloseComplete / NoData /
                # PortalSuspended / EmptyQuery
                pass
            elif mtype == b"E":  # ErrorResponse
                error = _parse_error(payload)
            elif mtype == b"Z":  # ReadyForQuery — end of the response cycle
                self.txn_status = payload[:1].decode() or "I"
                break
            elif mtype in (b"S", b"N"):  # ParameterStatus / NoticeResponse
                continue
            else:
                raise PostgresError("FATAL", "08P01",
                                    f"unexpected message {mtype!r}")
        if error is not None:
            raise error
        return rows, columns, n_params

    # -- simple query protocol --------------------------------------------
    async def query(self, sql: str) -> List[Row]:
        """Run one simple query; returns data rows (empty for commands)."""
        await self._stream.write_all(_msg(b"Q", _cstr(sql)))
        rows, _cols, _np = await self._read_until_ready()
        return rows

    async def execute(self, sql: str) -> None:
        await self.query(sql)

    # -- extended query protocol (prepare.rs / codec.rs analog) -----------
    async def prepare(self, sql: str, name: Optional[str] = None) -> PreparedStatement:
        """Parse + Describe a statement with $1..$n placeholders."""
        if name is None:
            # Deterministic per-connection naming: statement names go over
            # the wire, so id()/hash()-derived names would leak process-
            # level nondeterminism into byte-level traces.
            self._stmt_counter += 1
            name = f"s{self._stmt_counter}"
        stmt = name
        parse = _cstr(stmt) + _cstr(sql) + struct.pack("!H", 0)
        describe = b"S" + _cstr(stmt)
        await self._stream.write_all(
            _msg(b"P", parse) + _msg(b"D", describe) + _msg(b"S", b""))
        _rows, columns, n_params = await self._read_until_ready()
        return PreparedStatement(stmt, sql, columns, n_params)

    async def query_prepared(self, stmt: "PreparedStatement | str",
                             params: List[Optional[str]] = ()) -> List[Row]:
        """Bind + Execute a prepared statement on the unnamed portal."""
        name = stmt.name if isinstance(stmt, PreparedStatement) else stmt
        bind = _cstr("") + _cstr(name) + struct.pack("!H", 0)  # text format
        bind += struct.pack("!H", len(params))
        for p in params:
            if p is None:
                bind += struct.pack("!i", -1)
            else:
                raw = str(p).encode()
                bind += struct.pack("!i", len(raw)) + raw
        bind += struct.pack("!H", 0)  # result formats: all text
        execute = _cstr("") + struct.pack("!i", 0)  # no row limit
        await self._stream.write_all(
            _msg(b"B", bind) + _msg(b"E", execute) + _msg(b"S", b""))
        rows, _cols, _np = await self._read_until_ready()
        return rows

    async def execute_prepared(self, stmt: "PreparedStatement | str",
                               params: List[Optional[str]] = ()) -> None:
        await self.query_prepared(stmt, params)

    async def close_statement(self, stmt: "PreparedStatement | str") -> None:
        name = stmt.name if isinstance(stmt, PreparedStatement) else stmt
        await self._stream.write_all(
            _msg(b"C", b"S" + _cstr(name)) + _msg(b"S", b""))
        await self._read_until_ready()

    # -- COPY sub-protocol (copy_in.rs / copy_out.rs analog) ---------------
    async def copy_in(self, sql: str) -> CopyInWriter:
        """Start ``COPY table [(cols)] FROM STDIN``; returns the sink."""
        await self._stream.write_all(_msg(b"Q", _cstr(sql)))
        error: Optional[PostgresError] = None
        while True:
            mtype, payload = await _read_message(self._stream)
            if mtype == b"G":  # CopyInResponse — ready for CopyData
                return CopyInWriter(self)
            if mtype == b"E":
                error = _parse_error(payload)
            elif mtype == b"Z":
                self.txn_status = payload[:1].decode() or "I"
                raise error if error is not None else PostgresError(
                    "ERROR", "08P01", "server did not enter COPY-in mode")
            elif mtype in (b"S", b"N", b"C"):
                continue
            else:
                raise PostgresError("FATAL", "08P01",
                                    f"unexpected message {mtype!r} in COPY")

    async def copy_out(self, sql: str) -> List[List[Optional[str]]]:
        """Run ``COPY table [(cols)] TO STDOUT``; returns decoded rows."""
        await self._stream.write_all(_msg(b"Q", _cstr(sql)))
        data = bytearray()
        error: Optional[PostgresError] = None
        while True:
            mtype, payload = await _read_message(self._stream)
            if mtype == b"H":  # CopyOutResponse
                continue
            if mtype == b"d":  # CopyData
                data += payload
            elif mtype == b"c":  # CopyDone
                continue
            elif mtype == b"C":
                self._last_tag = payload.rstrip(b"\0").decode()
            elif mtype == b"E":
                error = _parse_error(payload)
            elif mtype == b"Z":
                self.txn_status = payload[:1].decode() or "I"
                if error is not None:
                    raise error
                try:
                    return copy_decode(bytes(data))
                except UnicodeDecodeError as exc:
                    raise PostgresError(
                        "ERROR", "22P04",
                        f"invalid COPY data from server: {exc}") from exc
            elif mtype in (b"S", b"N"):
                continue
            else:
                raise PostgresError("FATAL", "08P01",
                                    f"unexpected message {mtype!r} in COPY")

    # -- transactions ------------------------------------------------------
    def transaction(self) -> Transaction:
        return Transaction(self)

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                await self._stream.write_all(_msg(b"X", b""))
            except (BrokenPipe, ConnectionReset):
                pass
            self._stream.close()


async def connect(host: str, port: int = 5432, user: str = "postgres",
                  database: str = "postgres") -> Connection:
    """Open a connection: TCP connect + startup handshake."""
    stream = await TcpStream.connect((host, port))
    try:
        params = _cstr("user") + _cstr(user) + _cstr("database") + _cstr(database) + b"\0"
        startup = struct.pack("!II", len(params) + 8, PROTOCOL_VERSION) + params
        await stream.write_all(startup)
        parameters: Dict[str, str] = {}
        while True:
            mtype, payload = await _read_message(stream)
            if mtype == b"R":
                (auth,) = struct.unpack("!I", payload[:4])
                if auth != 0:
                    raise PostgresError("FATAL", "28000",
                                        f"unsupported auth method {auth}")
            elif mtype == b"S":
                key, value = _split_cstrs(payload)[:2]
                parameters[key] = value
            elif mtype == b"K":  # BackendKeyData
                pass
            elif mtype == b"E":
                raise _parse_error(payload, "FATAL", "startup failed")
            elif mtype == b"Z":
                return Connection(stream, parameters)
            else:
                raise PostgresError("FATAL", "08P01",
                                    f"unexpected startup message {mtype!r}")
    except BaseException:
        # Failed handshakes must not leak simulated connections (retry loops
        # in fault-injection workloads would accumulate them).
        stream.close()
        raise


# ---------------------------------------------------------------------------
# In-sim server (protocol-correct backend, toy table engine)
# ---------------------------------------------------------------------------

_CREATE = re.compile(r"^\s*CREATE\s+TABLE\s+(\w+)\s*\(([^)]*)\)\s*;?\s*$", re.I)
_INSERT = re.compile(r"^\s*INSERT\s+INTO\s+(\w+)\s+VALUES\s*\((.*)\)\s*;?\s*$", re.I)
# WHERE accepts a ''-escaped string literal or NULL (never-matching, SQL
# three-valued-logic rule for `= NULL`).
_WHERE = r"(?:\s+WHERE\s+(\w+)\s*=\s*(?:'((?:[^']|'')*)'|(NULL)))?"
_SELECT = re.compile(r"^\s*SELECT\s+(.+?)\s+FROM\s+(\w+)" + _WHERE
                     + r"\s*;?\s*$", re.I)
_DELETE = re.compile(r"^\s*DELETE\s+FROM\s+(\w+)" + _WHERE + r"\s*;?\s*$",
                     re.I)
_COPY_FROM = re.compile(
    r"^\s*COPY\s+(\w+)\s*(?:\(([^)]*)\))?\s+FROM\s+STDIN\s*;?\s*$", re.I)
_COPY_TO = re.compile(
    r"^\s*COPY\s+(\w+)\s*(?:\(([^)]*)\))?\s+TO\s+STDOUT\s*;?\s*$", re.I)
_BEGIN = re.compile(r"^\s*(BEGIN|START\s+TRANSACTION)\s*;?\s*$", re.I)
_COMMIT = re.compile(r"^\s*(COMMIT|END)\s*;?\s*$", re.I)
_ROLLBACK = re.compile(r"^\s*ROLLBACK\s*;?\s*$", re.I)
_PARAM = re.compile(r"\$(\d+)")


def _parse_values(s: str) -> Optional[List[Optional[str]]]:
    """Parse a VALUES list: ''-escaped string literals, NULL, bare tokens.
    Quote-aware (commas inside strings are data). None on syntax error."""
    out: List[Optional[str]] = []
    i, n = 0, len(s)
    while True:
        while i < n and s[i].isspace():
            i += 1
        if i < n and s[i] == "'":
            i += 1
            buf: List[str] = []
            closed = False
            while i < n:
                if s[i] == "'":
                    if i + 1 < n and s[i + 1] == "'":
                        buf.append("'")
                        i += 2
                        continue
                    i += 1
                    closed = True
                    break
                buf.append(s[i])
                i += 1
            if not closed:
                return None
            out.append("".join(buf))
        else:
            j = i
            while j < n and s[j] != ",":
                j += 1
            tok = s[i:j].strip()
            if not tok:
                return None
            out.append(None if tok.upper() == "NULL" else tok)
            i = j
        while i < n and s[i].isspace():
            i += 1
        if i >= n:
            return out
        if s[i] != ",":
            return None
        i += 1


class _Session:
    """Per-connection state: prepared statements, portals, transaction.

    Transactions use an undo log (inverse operation per mutation) rather
    than a whole-database snapshot: ROLLBACK reverts only this session's
    writes, so commits from concurrent sessions survive, and BEGIN is O(1)
    instead of a full deepcopy."""

    __slots__ = ("statements", "portals", "txn", "undo")

    def __init__(self):
        self.statements: Dict[str, str] = {}          # name -> SQL
        self.portals: Dict[str, str] = {}             # portal -> bound SQL
        self.txn = "I"                                # I / T / E
        self.undo: List = []                          # inverse ops, in order


class SimPostgresServer:
    """A wire-protocol-correct PostgreSQL backend living inside the world."""

    def __init__(self):
        self.tables: Dict[str, Tuple[List[str], List[List[str]]]] = {}
        # Tables created inside a still-open transaction: invisible to
        # every other session until commit (postgres DDL transactionality),
        # which also makes CREATE's rollback-drop safe — no other session
        # can have written rows into a pending table.
        self.pending_tables: Dict[str, "_Session"] = {}
        self._listener: Optional[TcpListener] = None

    def _visible(self, name: str, sess: Optional["_Session"]) -> bool:
        if name not in self.tables:
            return False
        owner = self.pending_tables.get(name)
        return owner is None or owner is sess

    async def serve(self, addr) -> None:
        self._listener = await TcpListener.bind(addr)
        while True:
            try:
                stream, _src = await self._listener.accept()
            except ConnectionReset:
                return
            _task.spawn(self._session(stream))

    def close(self) -> None:
        if self._listener is not None:
            self._listener.close()

    # ------------------------------------------------------------------
    async def _session(self, stream: TcpStream) -> None:
        sess = _Session()
        try:
            head = await stream.read_exact(8)
            (length, version) = struct.unpack("!II", head)
            body = await stream.read_exact(length - 8) if length > 8 else b""
            if version != PROTOCOL_VERSION:
                await stream.write_all(self._error("FATAL", "0A000",
                                                   f"unsupported protocol {version}"))
                return
            kv = _split_cstrs(body)
            params = dict(zip(kv[::2], kv[1::2]))
            out = _msg(b"R", struct.pack("!I", 0))                     # AuthenticationOk
            out += _msg(b"S", _cstr("server_version") + _cstr("15.0-sim"))
            out += _msg(b"S", _cstr("session_user") + _cstr(params.get("user", "")))
            out += _msg(b"Z", b"I")                                    # ReadyForQuery
            await stream.write_all(out)
            skip_to_sync = False
            while True:
                mtype, payload = await _read_message(stream)
                if mtype == b"X":
                    return
                if skip_to_sync and mtype != b"S":
                    # Extended-protocol error: discard until Sync
                    # (protocol-flow rule for the extended query cycle).
                    continue
                if mtype == b"Q":
                    sql = payload.rstrip(b"\0").decode()
                    if _COPY_FROM.match(sql) or _COPY_TO.match(sql):
                        await self._copy_session(stream, sess, sql)
                        continue
                    await stream.write_all(self._run_txn(sql, sess)
                                           + _msg(b"Z", sess.txn.encode()))
                elif mtype == b"P":    # Parse
                    out, skip_to_sync = self._on_parse(payload, sess)
                    await stream.write_all(out)
                elif mtype == b"D":    # Describe
                    out, skip_to_sync = self._on_describe(payload, sess)
                    await stream.write_all(out)
                elif mtype == b"B":    # Bind
                    out, skip_to_sync = self._on_bind(payload, sess)
                    await stream.write_all(out)
                elif mtype == b"E":    # Execute
                    out, skip_to_sync = self._on_execute(payload, sess)
                    await stream.write_all(out)
                elif mtype == b"C":    # Close statement/portal
                    kind, name = payload[:1], payload[1:].rstrip(b"\0").decode()
                    (sess.statements if kind == b"S" else sess.portals).pop(name, None)
                    await stream.write_all(_msg(b"3", b""))
                elif mtype == b"S":    # Sync
                    skip_to_sync = False
                    await stream.write_all(_msg(b"Z", sess.txn.encode()))
                elif mtype == b"H":    # Flush — writes are unbuffered here
                    continue
                else:
                    await stream.write_all(self._error("ERROR", "0A000",
                                                       f"unsupported message {mtype!r}")
                                           + _msg(b"Z", sess.txn.encode()))
        except (ConnectionReset, BrokenPipe):
            return  # client vanished (crash / partition): session ends
        finally:
            # Session over (Terminate, reset, or crash): an open
            # transaction rolls back — uncommitted writes must never
            # outlive their connection (postgres disconnect semantics).
            if sess.txn != "I":
                self._rollback(sess)
            stream.close()

    # -- COPY sub-protocol ----------------------------------------------
    async def _copy_session(self, stream: TcpStream, sess: _Session,
                            sql: str) -> None:
        """One simple-protocol COPY cycle: ``COPY t [(cols)] FROM STDIN``
        (CopyInResponse → CopyData* → CopyDone/CopyFail) or
        ``COPY t [(cols)] TO STDOUT`` (CopyOutResponse → CopyData* →
        CopyDone). Errors poison an open transaction like any statement;
        COPY FROM inside a transaction appends an undo entry so ROLLBACK
        removes the copied rows."""
        def fail(out: bytes) -> bytes:
            if sess.txn == "T":
                sess.txn = "E"
            return out + _msg(b"Z", sess.txn.encode())

        m_in = _COPY_FROM.match(sql)
        m = m_in or _COPY_TO.match(sql)
        name = m.group(1).lower()
        if sess.txn == "E":
            await stream.write_all(self._error(
                "ERROR", "25P02", "current transaction is aborted, commands "
                "ignored until end of transaction block") + _msg(b"Z", b"E"))
            return
        if not self._visible(name, sess):
            await stream.write_all(fail(self._error(
                "ERROR", "42P01", f'no table "{name}"')))
            return
        cols, data = self.tables[name]
        want = ([c.strip().lower() for c in m.group(2).split(",")]
                if m.group(2) else list(cols))
        bad = [c for c in want if c not in cols]
        if bad:
            await stream.write_all(fail(self._error(
                "ERROR", "42703", f'no column "{bad[0]}"')))
            return
        # Copy{In,Out}Response: int8 overall format (0 = text), int16 column
        # count, int16 per-column format codes.
        fmt = struct.pack("!BH", 0, len(want)) + b"\0\0" * len(want)

        if m_in is None:  # COPY ... TO STDOUT
            idx = [cols.index(c) for c in want]
            out = _msg(b"H", fmt)
            for row in data:
                out += _msg(b"d", copy_encode_row([row[i] for i in idx]))
            out += (_msg(b"c", b"") + self._complete(f"COPY {len(data)}")
                    + _msg(b"Z", sess.txn.encode()))
            await stream.write_all(out)
            return

        # COPY ... FROM STDIN
        await stream.write_all(_msg(b"G", fmt))
        buf = bytearray()
        while True:
            mtype, payload = await _read_message(stream)
            if mtype == b"d":
                buf += payload
            elif mtype == b"c":
                break
            elif mtype == b"f":
                msg = payload.rstrip(b"\0").decode()
                await stream.write_all(fail(self._error(
                    "ERROR", "57014", f"COPY from stdin failed: {msg}")))
                return
            elif mtype == b"H":
                continue
            elif mtype == b"X":
                # Terminate mid-COPY: treat as a vanished client so the
                # session's finally block rolls back the open transaction.
                raise BrokenPipe("client terminated during COPY")
            else:
                # Real postgres discards the rest of the copy stream before
                # reporting the error, so the request/response cycle stays in
                # sync; drain to CopyDone/CopyFail (EOF propagates) first.
                while True:
                    drained, _ = await _read_message(stream)
                    if drained in (b"c", b"f"):
                        break
                    if drained == b"X":
                        raise BrokenPipe("client terminated during COPY")
                await stream.write_all(fail(self._error(
                    "ERROR", "08P01",
                    f"unexpected message {mtype!r} during COPY")))
                return
        try:
            rows = copy_decode(bytes(buf))
        except UnicodeDecodeError:
            await stream.write_all(fail(self._error(
                "ERROR", "22P04", "invalid COPY data")))
            return
        added: List[List[Optional[str]]] = []
        for r in rows:
            if len(r) != len(want):
                await stream.write_all(fail(self._error(
                    "ERROR", "22P04",
                    f"row has {len(r)} columns, expected {len(want)}")))
                return
            full: List[Optional[str]] = [None] * len(cols)
            for c, v in zip(want, r):
                full[cols.index(c)] = v
            added.append(full)
        data.extend(added)
        if sess.txn == "T" and added:
            def _undo_copy(data=data, added=added):
                for row in added:
                    for i in range(len(data) - 1, -1, -1):
                        if data[i] is row:
                            del data[i]
                            break

            sess.undo.append(_undo_copy)
        await stream.write_all(self._complete(f"COPY {len(added)}")
                               + _msg(b"Z", sess.txn.encode()))

    # -- extended-protocol handlers -------------------------------------
    def _on_parse(self, payload: bytes, sess: _Session) -> Tuple[bytes, bool]:
        end = payload.index(b"\0")
        name = payload[:end].decode()
        end2 = payload.index(b"\0", end + 1)
        sql = payload[end + 1:end2].decode()
        sess.statements[name] = sql
        return _msg(b"1", b""), False

    def _on_describe(self, payload: bytes, sess: _Session) -> Tuple[bytes, bool]:
        kind, name = payload[:1], payload[1:].rstrip(b"\0").decode()
        sql = (sess.statements if kind == b"S" else sess.portals).get(name)
        if sql is None:
            return (self._error("ERROR", "26000",
                                f'unknown statement "{name}"'), True)
        n_params = max((int(m) for m in _PARAM.findall(sql)), default=0)
        out = b""
        if kind == b"S":
            out += _msg(b"t", struct.pack("!H", n_params)
                        + struct.pack("!I", 25) * n_params)
        # Row-shape probe: substitute placeholders with dummy literals so
        # the statement patterns match parameterized SQL.
        probe = _PARAM.sub("''", sql)
        if m := _SELECT.match(probe):
            want = m.group(1)
            tname = m.group(2).lower()
            table = self.tables.get(tname) if self._visible(tname, sess) else None
            cols = ([c.strip().lower() for c in want.split(",")]
                    if want.strip() != "*" else
                    (table[0] if table else []))
            out += self._rowdesc(cols)
        elif probe.strip().rstrip(";").lower() in ("select now()",
                                                   "select current_timestamp"):
            out += self._rowdesc(["now"])
        else:
            out += _msg(b"n", b"")  # NoData
        return out, False

    def _on_bind(self, payload: bytes, sess: _Session) -> Tuple[bytes, bool]:
        off = payload.index(b"\0")
        portal = payload[:off].decode()
        end = payload.index(b"\0", off + 1)
        stmt = payload[off + 1:end].decode()
        off = end + 1
        (nfmt,) = struct.unpack_from("!H", payload, off)
        off += 2 + 2 * nfmt
        (nparams,) = struct.unpack_from("!H", payload, off)
        off += 2
        values: List[Optional[str]] = []
        for _ in range(nparams):
            (vlen,) = struct.unpack_from("!i", payload, off)
            off += 4
            if vlen < 0:
                values.append(None)
            else:
                values.append(payload[off:off + vlen].decode())
                off += vlen
        sql = sess.statements.get(stmt)
        if sql is None:
            return (self._error("ERROR", "26000",
                                f'unknown statement "{stmt}"'), True)
        indices = [int(m) for m in _PARAM.findall(sql)]
        if any(i < 1 for i in indices):
            bad = min(indices)
            return (self._error("ERROR", "42P02",
                                f"there is no parameter ${bad}"), True)
        n_params = max(indices, default=0)
        if len(values) != n_params:
            return (self._error("ERROR", "08P01",
                                f"bind supplies {len(values)} parameters, "
                                f"statement needs {n_params}"), True)

        def subst(m: "re.Match[str]") -> str:
            v = values[int(m.group(1)) - 1]
            return "NULL" if v is None else "'" + v.replace("'", "''") + "'"

        sess.portals[portal] = _PARAM.sub(subst, sql)
        return _msg(b"2", b""), False

    def _on_execute(self, payload: bytes, sess: _Session) -> Tuple[bytes, bool]:
        portal = payload[:payload.index(b"\0")].decode()
        sql = sess.portals.get(portal)
        if sql is None:
            return (self._error("ERROR", "34000",
                                f'unknown portal "{portal}"'), True)
        out = self._run_txn(sql, sess)
        # An error inside the extended flow skips to Sync.
        return out, out[:1] == b"E"

    # -- transaction wrapper --------------------------------------------
    def _run_txn(self, sql: str, sess: _Session) -> bytes:
        if _BEGIN.match(sql):
            if sess.txn == "I":
                sess.undo = []
                sess.txn = "T"
                return self._complete("BEGIN")
            return self._notice() + self._complete("BEGIN")  # nested: no-op
        if _COMMIT.match(sql):
            if sess.txn == "E":
                # COMMIT of a failed transaction rolls back (postgres rule).
                self._rollback(sess)
                return self._complete("ROLLBACK")
            self._publish_pending(sess)
            sess.txn, sess.undo = "I", []
            return self._complete("COMMIT")
        if _ROLLBACK.match(sql):
            self._rollback(sess)
            return self._complete("ROLLBACK")
        if sess.txn == "E":
            return self._error("ERROR", "25P02",
                               "current transaction is aborted, commands "
                               "ignored until end of transaction block")
        out = self._run(sql, sess)
        if out[:1] == b"E" and sess.txn == "T":
            sess.txn = "E"  # poison the transaction
        return out

    def _rollback(self, sess: _Session) -> None:
        for inverse in reversed(sess.undo):
            inverse()
        self._publish_pending(sess)
        sess.txn, sess.undo = "I", []

    def _publish_pending(self, sess: _Session) -> None:
        """End-of-transaction: this session's pending DDL becomes globally
        visible (commit) or is gone already (rollback ran the drop)."""
        self.pending_tables = {n: s for n, s in self.pending_tables.items()
                               if s is not sess}

    # -- toy engine ----------------------------------------------------
    def _run(self, sql: str, sess: Optional[_Session] = None) -> bytes:
        """Execute one statement for ``sess``; mutations append their
        inverse to the session's undo log when its transaction is open,
        and pending (uncommitted-DDL) tables of other sessions are
        invisible."""
        undo = sess.undo if sess is not None and sess.txn == "T" else None
        if sql.strip().rstrip(";").lower() in ("select now()", "select current_timestamp"):
            # Server-side wall-clock read: observes this node's simulated
            # system time *including injected clock skew*
            # (Handle.set_clock_skew) — the observation surface for the
            # clock-skew chaos config (BASELINE config 4).
            from .. import time as simtime

            return self._rowset(["now"], [[repr(simtime.system_time())]])
        if m := _CREATE.match(sql):
            name, cols = m.group(1).lower(), [c.strip().split()[0].lower()
                                             for c in m.group(2).split(",")]
            if name in self.tables:
                return self._error("ERROR", "42P07", f'table "{name}" exists')
            self.tables[name] = (cols, [])
            if undo is not None:
                # Transactional DDL: invisible to other sessions until
                # commit, so the rollback-drop can never destroy another
                # session's committed rows.
                self.pending_tables[name] = sess

                def _undo_create(name=name):
                    self.tables.pop(name, None)
                    self.pending_tables.pop(name, None)

                undo.append(_undo_create)
            return self._complete("CREATE TABLE")
        if m := _INSERT.match(sql):
            name = m.group(1).lower()
            if not self._visible(name, sess):
                return self._error("ERROR", "42P01", f'no table "{name}"')
            cols, data = self.tables[name]
            values = _parse_values(m.group(2))
            if values is None:
                return self._error("ERROR", "42601",
                                   f"bad VALUES list: {m.group(2)[:40]!r}")
            if len(values) != len(cols):
                return self._error("ERROR", "42601",
                                   f"expected {len(cols)} values")
            data.append(values)
            if undo is not None:
                def _undo_insert(data=data, row=values):
                    for i in range(len(data) - 1, -1, -1):
                        if data[i] is row:
                            del data[i]
                            return

                undo.append(_undo_insert)
            return self._complete("INSERT 0 1")
        if m := _SELECT.match(sql):
            want, name = m.group(1), m.group(2).lower()
            if not self._visible(name, sess):
                return self._error("ERROR", "42P01", f'no table "{name}"')
            cols, data = self.tables[name]
            out_cols = cols if want.strip() == "*" else \
                [c.strip().lower() for c in want.split(",")]
            for c in out_cols:
                if c not in cols:
                    return self._error("ERROR", "42703", f'no column "{c}"')
            rows = self._filter(cols, data, m.group(3), m.group(4), m.group(5))
            proj = [[row[cols.index(c)] for c in out_cols] for row in rows]
            return self._rowset(out_cols, proj)
        if m := _DELETE.match(sql):
            name = m.group(1).lower()
            if not self._visible(name, sess):
                return self._error("ERROR", "42P01", f'no table "{name}"')
            cols, data = self.tables[name]
            drop = self._filter(cols, data, m.group(2), m.group(3), m.group(4))
            # Mutate the row list in place: other sessions (and their undo
            # closures) hold references to it.
            data[:] = [r for r in data if r not in drop]
            if undo is not None and drop:
                undo.append(lambda data=data, rows=drop: data.extend(rows))
            return self._complete(f"DELETE {len(drop)}")
        return self._error("ERROR", "42601", f"syntax error: {sql[:40]!r}")

    @staticmethod
    def _filter(cols, data, where_col, where_val, where_null):
        if where_col is None:
            return list(data)
        if where_null is not None:
            return []  # `col = NULL` matches nothing (three-valued logic)
        idx = cols.index(where_col.lower()) if where_col.lower() in cols else None
        if idx is None:
            return []
        val = where_val.replace("''", "'")
        return [r for r in data if r[idx] == val]

    # -- response builders ---------------------------------------------
    @staticmethod
    def _rowdesc(columns: List[str]) -> bytes:
        desc = struct.pack("!H", len(columns))
        for col in columns:
            # name, table oid, attnum, type oid (25=text), typlen, typmod, fmt
            desc += _cstr(col) + struct.pack("!IHIhih", 0, 0, 25, -1, -1, 0)
        return _msg(b"T", desc)

    @staticmethod
    def _rowset(columns: List[str], rows: List[List[str]]) -> bytes:
        out = SimPostgresServer._rowdesc(columns)
        for row in rows:
            body = struct.pack("!H", len(row))
            for val in row:
                if val is None:
                    body += struct.pack("!i", -1)  # SQL NULL
                else:
                    raw = val.encode()
                    body += struct.pack("!i", len(raw)) + raw
            out += _msg(b"D", body)
        return out + SimPostgresServer._complete(f"SELECT {len(rows)}")

    @staticmethod
    def _complete(tag: str) -> bytes:
        return _msg(b"C", _cstr(tag))

    @staticmethod
    def _notice(message: str = "there is already a transaction in progress") -> bytes:
        body = (_cstr("SWARNING") + _cstr("VWARNING") + _cstr("C25001")
                + _cstr("M" + message) + b"\0")
        return _msg(b"N", body)

    @staticmethod
    def _error(severity: str, code: str, message: str) -> bytes:
        # Standard error fields: S localized severity, V non-localized
        # severity, C sqlstate, M message (protocol error-fields table).
        body = (_cstr("S" + severity) + _cstr("V" + severity)
                + _cstr("C" + code) + _cstr("M" + message) + b"\0")
        return _msg(b"E", body)
