"""PostgreSQL v3 wire-protocol client over the simulated network.

The madsim-tokio-postgres analog (SURVEY §2.15): the reference vendors the
real tokio-postgres client and runs its unchanged protocol machinery over the
simulated TcpStream, proving the shim strategy scales to a real protocol.
This module does the Python equivalent: a faithful implementation of the
PostgreSQL frontend/backend protocol (startup, simple-query flow,
RowDescription/DataRow/CommandComplete/ErrorResponse/ReadyForQuery framing —
https://www.postgresql.org/docs/current/protocol-message-formats.html)
speaking through :class:`madsim_tpu.net.TcpStream`, so every byte crosses the
simulated network with latency/loss/partition semantics.

Where the reference needs a live out-of-process PostgreSQL server (its test
suite is excluded from CI for exactly that reason, reference `Makefile:12-16`),
the simulation can host the server *inside the world*: :class:`SimPostgresServer`
is a protocol-correct backend with a toy table engine, so client↔server runs
under seed sweeps, clock skew, and fault injection like any other workload.
"""
from __future__ import annotations

import re
import struct
from typing import Dict, List, Optional, Tuple

from .. import task as _task
from ..net.netsim import BrokenPipe, ConnectionReset
from ..net.tcp import TcpListener, TcpStream

PROTOCOL_VERSION = 196608  # 3.0


class PostgresError(Exception):
    """Server-reported error (ErrorResponse 'E')."""

    def __init__(self, severity: str, code: str, message: str):
        super().__init__(f"{severity} {code}: {message}")
        self.severity = severity
        self.code = code
        self.message = message


# ---------------------------------------------------------------------------
# Wire helpers
# ---------------------------------------------------------------------------

def _msg(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\0"


async def _read_message(stream: TcpStream) -> Tuple[bytes, bytes]:
    """Read one typed backend/frontend message → (type, payload)."""
    head = await stream.read_exact(5)
    mtype = head[:1]
    (length,) = struct.unpack("!I", head[1:5])
    payload = await stream.read_exact(length - 4) if length > 4 else b""
    return mtype, payload


def _split_cstrs(buf: bytes) -> List[str]:
    return [p.decode() for p in buf.split(b"\0")[:-1]]


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

class Row(tuple):
    """A result row; column access by index or, via .get, by name."""

    def __new__(cls, values, columns):
        row = super().__new__(cls, values)
        row._columns = columns
        return row

    def get(self, name: str):
        return self[self._columns.index(name)]


class Connection:
    """A connected PostgreSQL session (simple-query protocol)."""

    def __init__(self, stream: TcpStream, parameters: Dict[str, str]):
        self._stream = stream
        self.parameters = parameters  # ParameterStatus values from startup
        self._closed = False

    async def query(self, sql: str) -> List[Row]:
        """Run one simple query; returns data rows (empty for commands)."""
        await self._stream.write_all(_msg(b"Q", _cstr(sql)))
        columns: List[str] = []
        rows: List[Row] = []
        error: Optional[PostgresError] = None
        while True:
            mtype, payload = await _read_message(self._stream)
            if mtype == b"T":  # RowDescription
                (nfields,) = struct.unpack("!H", payload[:2])
                off = 2
                columns = []
                for _ in range(nfields):
                    end = payload.index(b"\0", off)
                    columns.append(payload[off:end].decode())
                    off = end + 1 + 18  # fixed per-field descriptor tail
            elif mtype == b"D":  # DataRow
                (ncols,) = struct.unpack("!H", payload[:2])
                off = 2
                values = []
                for _ in range(ncols):
                    (vlen,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if vlen < 0:
                        values.append(None)
                    else:
                        values.append(payload[off:off + vlen].decode())
                        off += vlen
                rows.append(Row(values, columns))
            elif mtype == b"C":  # CommandComplete
                pass
            elif mtype == b"E":  # ErrorResponse
                fields = dict((chunk[0], chunk[1:]) for chunk in
                              _split_cstrs(payload) if chunk)
                error = PostgresError(fields.get("S", "ERROR"),
                                      fields.get("C", "XX000"),
                                      fields.get("M", "unknown"))
            elif mtype == b"Z":  # ReadyForQuery — end of the response cycle
                break
            elif mtype in (b"S", b"N"):  # ParameterStatus / NoticeResponse
                continue
            else:
                raise PostgresError("FATAL", "08P01",
                                    f"unexpected message {mtype!r}")
        if error is not None:
            raise error
        return rows

    async def execute(self, sql: str) -> None:
        await self.query(sql)

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                await self._stream.write_all(_msg(b"X", b""))
            except (BrokenPipe, ConnectionReset):
                pass
            self._stream.close()


async def connect(host: str, port: int = 5432, user: str = "postgres",
                  database: str = "postgres") -> Connection:
    """Open a connection: TCP connect + startup handshake."""
    stream = await TcpStream.connect((host, port))
    try:
        params = _cstr("user") + _cstr(user) + _cstr("database") + _cstr(database) + b"\0"
        startup = struct.pack("!II", len(params) + 8, PROTOCOL_VERSION) + params
        await stream.write_all(startup)
        parameters: Dict[str, str] = {}
        while True:
            mtype, payload = await _read_message(stream)
            if mtype == b"R":
                (auth,) = struct.unpack("!I", payload[:4])
                if auth != 0:
                    raise PostgresError("FATAL", "28000",
                                        f"unsupported auth method {auth}")
            elif mtype == b"S":
                key, value = _split_cstrs(payload)[:2]
                parameters[key] = value
            elif mtype == b"K":  # BackendKeyData
                pass
            elif mtype == b"E":
                fields = dict((c[0], c[1:]) for c in _split_cstrs(payload) if c)
                raise PostgresError(fields.get("S", "FATAL"),
                                    fields.get("C", "XX000"),
                                    fields.get("M", "startup failed"))
            elif mtype == b"Z":
                return Connection(stream, parameters)
            else:
                raise PostgresError("FATAL", "08P01",
                                    f"unexpected startup message {mtype!r}")
    except BaseException:
        # Failed handshakes must not leak simulated connections (retry loops
        # in fault-injection workloads would accumulate them).
        stream.close()
        raise


# ---------------------------------------------------------------------------
# In-sim server (protocol-correct backend, toy table engine)
# ---------------------------------------------------------------------------

_CREATE = re.compile(r"^\s*CREATE\s+TABLE\s+(\w+)\s*\(([^)]*)\)\s*;?\s*$", re.I)
_INSERT = re.compile(r"^\s*INSERT\s+INTO\s+(\w+)\s+VALUES\s*\((.*)\)\s*;?\s*$", re.I)
_SELECT = re.compile(r"^\s*SELECT\s+(.+?)\s+FROM\s+(\w+)"
                     r"(?:\s+WHERE\s+(\w+)\s*=\s*'([^']*)')?\s*;?\s*$", re.I)
_DELETE = re.compile(r"^\s*DELETE\s+FROM\s+(\w+)"
                     r"(?:\s+WHERE\s+(\w+)\s*=\s*'([^']*)')?\s*;?\s*$", re.I)


class SimPostgresServer:
    """A wire-protocol-correct PostgreSQL backend living inside the world."""

    def __init__(self):
        self.tables: Dict[str, Tuple[List[str], List[List[str]]]] = {}
        self._listener: Optional[TcpListener] = None

    async def serve(self, addr) -> None:
        self._listener = await TcpListener.bind(addr)
        while True:
            try:
                stream, _src = await self._listener.accept()
            except ConnectionReset:
                return
            _task.spawn(self._session(stream))

    def close(self) -> None:
        if self._listener is not None:
            self._listener.close()

    # ------------------------------------------------------------------
    async def _session(self, stream: TcpStream) -> None:
        try:
            head = await stream.read_exact(8)
            (length, version) = struct.unpack("!II", head)
            body = await stream.read_exact(length - 8) if length > 8 else b""
            if version != PROTOCOL_VERSION:
                await stream.write_all(self._error("FATAL", "0A000",
                                                   f"unsupported protocol {version}"))
                return
            kv = _split_cstrs(body)
            params = dict(zip(kv[::2], kv[1::2]))
            out = _msg(b"R", struct.pack("!I", 0))                     # AuthenticationOk
            out += _msg(b"S", _cstr("server_version") + _cstr("15.0-sim"))
            out += _msg(b"S", _cstr("session_user") + _cstr(params.get("user", "")))
            out += _msg(b"Z", b"I")                                    # ReadyForQuery
            await stream.write_all(out)
            while True:
                mtype, payload = await _read_message(stream)
                if mtype == b"X":
                    return
                if mtype != b"Q":
                    await stream.write_all(self._error("ERROR", "0A000",
                                                       f"unsupported message {mtype!r}")
                                           + _msg(b"Z", b"I"))
                    continue
                sql = payload.rstrip(b"\0").decode()
                await stream.write_all(self._run(sql) + _msg(b"Z", b"I"))
        except (ConnectionReset, BrokenPipe):
            return  # client vanished (crash / partition): session ends
        finally:
            stream.close()

    # -- toy engine ----------------------------------------------------
    def _run(self, sql: str) -> bytes:
        if sql.strip().rstrip(";").lower() in ("select now()", "select current_timestamp"):
            # Server-side wall-clock read: observes this node's simulated
            # system time *including injected clock skew*
            # (Handle.set_clock_skew) — the observation surface for the
            # clock-skew chaos config (BASELINE config 4).
            from .. import time as simtime

            return self._rowset(["now"], [[repr(simtime.system_time())]])
        if m := _CREATE.match(sql):
            name, cols = m.group(1).lower(), [c.strip().split()[0].lower()
                                             for c in m.group(2).split(",")]
            if name in self.tables:
                return self._error("ERROR", "42P07", f'table "{name}" exists')
            self.tables[name] = (cols, [])
            return self._complete("CREATE TABLE")
        if m := _INSERT.match(sql):
            name = m.group(1).lower()
            if name not in self.tables:
                return self._error("ERROR", "42P01", f'no table "{name}"')
            cols, data = self.tables[name]
            values = [v.strip().strip("'") for v in m.group(2).split(",")]
            if len(values) != len(cols):
                return self._error("ERROR", "42601",
                                   f"expected {len(cols)} values")
            data.append(values)
            return self._complete("INSERT 0 1")
        if m := _SELECT.match(sql):
            want, name = m.group(1), m.group(2).lower()
            if name not in self.tables:
                return self._error("ERROR", "42P01", f'no table "{name}"')
            cols, data = self.tables[name]
            out_cols = cols if want.strip() == "*" else \
                [c.strip().lower() for c in want.split(",")]
            for c in out_cols:
                if c not in cols:
                    return self._error("ERROR", "42703", f'no column "{c}"')
            rows = self._filter(cols, data, m.group(3), m.group(4))
            proj = [[row[cols.index(c)] for c in out_cols] for row in rows]
            return self._rowset(out_cols, proj)
        if m := _DELETE.match(sql):
            name = m.group(1).lower()
            if name not in self.tables:
                return self._error("ERROR", "42P01", f'no table "{name}"')
            cols, data = self.tables[name]
            keep = [r for r in data
                    if r not in self._filter(cols, data, m.group(2), m.group(3))]
            removed = len(data) - len(keep)
            self.tables[name] = (cols, keep)
            return self._complete(f"DELETE {removed}")
        return self._error("ERROR", "42601", f"syntax error: {sql[:40]!r}")

    @staticmethod
    def _filter(cols, data, where_col, where_val):
        if where_col is None:
            return list(data)
        idx = cols.index(where_col.lower()) if where_col.lower() in cols else None
        if idx is None:
            return []
        return [r for r in data if r[idx] == where_val]

    # -- response builders ---------------------------------------------
    @staticmethod
    def _rowset(columns: List[str], rows: List[List[str]]) -> bytes:
        desc = struct.pack("!H", len(columns))
        for col in columns:
            # name, table oid, attnum, type oid (25=text), typlen, typmod, fmt
            desc += _cstr(col) + struct.pack("!IHIhih", 0, 0, 25, -1, -1, 0)
        out = _msg(b"T", desc)
        for row in rows:
            body = struct.pack("!H", len(row))
            for val in row:
                raw = val.encode()
                body += struct.pack("!i", len(raw)) + raw
            out += _msg(b"D", body)
        return out + SimPostgresServer._complete(f"SELECT {len(rows)}")

    @staticmethod
    def _complete(tag: str) -> bytes:
        return _msg(b"C", _cstr(tag))

    @staticmethod
    def _error(severity: str, code: str, message: str) -> bytes:
        body = _cstr("S" + severity) + _cstr("C" + code) + _cstr("M" + message) + b"\0"
        return _msg(b"E", body)
