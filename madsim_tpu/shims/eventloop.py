"""Event-loop-level drop-in: asyncio's transport/protocol surface over sim TCP.

This is the deepest interception layer (VERDICT r4 item 2, the
tokio-postgres-class proof): unmodified third-party libraries that open
their *own* sockets through the running event loop — aiohttp, asyncpg-style
protocol clients — run inside the simulation with no source changes.
Reference analog: `madsim/src/sim/net/tcp/{listener,stream}.rs` presenting
the tokio TcpListener/TcpStream API so upstream tokio-postgres's
``socket.rs`` connects over the simulated network unchanged
(`madsim-tokio-postgres/src/socket.rs:6-13`).

What lives here:

- :class:`SimEventLoop` — the ``asyncio.AbstractEventLoop`` surface used by
  protocol libraries: ``create_connection`` / ``create_server`` /
  ``sock_connect`` / ``sock_sendall`` / ``sock_recv`` / ``getaddrinfo`` /
  ``call_soon`` / ``call_later`` / ``call_at`` / ``create_future`` /
  ``create_task`` / ``run_in_executor``, all mapped onto the deterministic
  executor, virtual time, and the simulated network. One instance per
  world (cached on the Handle) so identity checks (``loop is self._loop``)
  hold.
- :class:`SimTransport` — an ``asyncio.Transport`` over a sim
  :class:`~madsim_tpu.net.tcp.TcpStream`: sync ``write`` with a writer
  pump task, a reader pump feeding ``protocol.data_received``, EOF and
  reset mapped to ``eof_received`` / ``connection_lost``.
- :class:`SimServer` — the object ``create_server`` returns (``sockets``,
  ``close``, ``wait_closed``), with an in-sim accept loop.
- A socket *token* registry: modern clients (aiohttp via aiohappyeyeballs)
  create a real ``socket.socket``, call ``loop.sock_connect(sock, addr)``,
  then hand the sock to ``create_connection(sock=...)``. The real fd is
  never connected; it serves as the lookup key for the sim stream
  established by ``sock_connect`` (and as ``get_extra_info("socket")`` so
  ``tcp_nodelay``-style tuning finds a live fd to setsockopt on).

TLS is deliberately not simulated (``ssl=`` raises): in-sim traffic rides
the deterministic network, so tests speak plain protocols, exactly like
the reference's sim transports.
"""
from __future__ import annotations

import socket as _socket
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import task as _task
from ..core import context as _context
from ..core.futures import Cancelled, Channel, ChannelClosed, SimFuture
from ..core.timewheel import to_ns
from ..net.addr import parse_addr
from ..net.netsim import ConnectionReset
from ..net.tcp import TcpListener, TcpStream

import asyncio as _std_asyncio


class AsyncioFuture(SimFuture):
    """``loop.create_future()`` result: a SimFuture whose *cancellation
    family* is the stdlib's, so unmodified ``except asyncio.CancelledError``
    handlers around awaited futures keep working."""

    __slots__ = ()

    def cancel(self, msg: Optional[str] = None) -> bool:
        if self.done():
            return False
        self.set_exception(_std_asyncio.CancelledError()
                           if msg is None else
                           _std_asyncio.CancelledError(msg))
        return True

    def cancelled(self) -> bool:
        return self.done() and isinstance(self._exception,
                                          _std_asyncio.CancelledError)

    def exception(self):
        if not self.done():
            raise RuntimeError("future is not done")
        return self._exception

    def remove_done_callback(self, cb) -> int:
        n = len(self._callbacks)
        self._callbacks = [c for c in self._callbacks if c != cb]
        return n - len(self._callbacks)

    def get_loop(self):
        return get_sim_loop()


class _DeadTimerHandle:
    """Returned by loop timer calls after the world ended (GC-time
    cleanup); there is no timer to cancel."""

    __slots__ = ()

    def cancel(self) -> None:
        pass

    def cancelled(self) -> bool:
        return True

    def when(self) -> float:
        return 0.0


class SimTimerHandle:
    """``loop.call_later``/``call_at`` handle (asyncio.TimerHandle shape)."""

    __slots__ = ("_entry", "_when", "_cancelled")

    def __init__(self, entry, when: float):
        self._entry = entry
        self._when = when

        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True
        self._entry.cancel()

    def cancelled(self) -> bool:
        return self._cancelled

    def when(self) -> float:
        return self._when


class TaskView:
    """The object our patched ``asyncio.current_task()`` returns.

    Third-party timeout scopes (aiohttp's TimerContext, stdlib-style
    timeouts) need exactly the 3.11 cancellation-counting protocol on the
    current task: ``cancel()`` / ``uncancel()`` / ``cancelling()``.
    ``cancel`` delivers a *stdlib* CancelledError through the executor's
    interrupt machinery (thrown into the task at its current await), which
    every cancel-safe path in the framework already handles via
    CANCELLED_TYPES."""

    # __weakref__: libraries key WeakKeyDictionaries by the current task
    # (anyio's task-state registry, reached through httpx).
    __slots__ = ("_task", "_executor", "_cancelling", "__weakref__")

    # Stdlib-Task internals some libraries reach into (anyio reads
    # _must_cancel and _fut_waiter before delivering cancellation):
    # interrupts deliver at the next poll here, so there is never a
    # deferred cancel or a tracked waiter future.
    _must_cancel = False
    _fut_waiter = None

    def __init__(self, task, executor):
        self._task = task
        self._executor = executor
        self._cancelling = 0

    def cancel(self, msg: Optional[str] = None) -> bool:
        if self._task.done:
            return False
        self._cancelling += 1
        self._executor.interrupt(
            self._task,
            _std_asyncio.CancelledError() if msg is None
            else _std_asyncio.CancelledError(msg))
        return True

    def uncancel(self) -> int:
        if self._cancelling > 0:
            self._cancelling -= 1
        return self._cancelling

    def cancelling(self) -> int:
        return self._cancelling

    def done(self) -> bool:
        return self._task.done

    def cancelled(self) -> bool:
        return self._task.cancelled

    def get_name(self) -> str:
        return f"sim-task-{self._task.id}"

    def set_name(self, name: str) -> None:
        pass

    def get_coro(self):
        return self._task.coro


def current_task_view():
    """The TaskView for the currently running sim task (None outside)."""
    task = _context.try_current_task()
    if task is None:
        return None
    executor = _context.current_handle().task
    views = getattr(executor, "_asyncio_task_views", None)
    if views is None:
        views = executor._asyncio_task_views = {}
    view = views.get(task.id)
    if view is None:
        if len(views) > 256:  # prune finished tasks' views
            for tid in [t for t, v in views.items() if v._task.done]:
                del views[tid]
        view = views[task.id] = TaskView(task, executor)
    return view


# ---------------------------------------------------------------------------
# Transport
# ---------------------------------------------------------------------------

_EOF_SENTINEL = object()    # write_eof: half-close after flushing
_CLOSE_SENTINEL = object()  # close: flush, then tear down


class SimTransport:
    """asyncio.Transport over a sim TcpStream (write side pumped by a
    dedicated task so ``write`` stays synchronous, read side pumped into
    ``protocol.data_received``)."""

    def __init__(self, loop: "SimEventLoop", stream: TcpStream, protocol,
                 extra: Dict[str, Any]):
        self._loop = loop
        self._stream = stream
        self._protocol = protocol
        self._extra = extra
        self._wq = Channel()
        self._wbuf_size = 0
        self._closing = False
        self._lost = False
        self._read_gate: Optional[SimFuture] = None
        self._reader = None
        self._writer = None

    def start_pumps(self) -> None:
        """Spawn reader/writer tasks; call after protocol.connection_made
        (asyncio guarantees no data_received before connection_made)."""
        self._reader = _task.spawn(self._read_pump())
        self._writer = _task.spawn(self._write_pump())

    # -- asyncio.BaseTransport ---------------------------------------------
    def get_extra_info(self, name: str, default: Any = None) -> Any:
        return self._extra.get(name, default)

    def is_closing(self) -> bool:
        return self._closing

    def set_protocol(self, protocol) -> None:
        self._protocol = protocol

    def get_protocol(self):
        return self._protocol

    def close(self) -> None:
        """Flush buffered writes, then tear down (asyncio close contract:
        connection_lost(None) is delivered after pending data ships)."""
        if self._closing:
            return
        self._closing = True
        try:
            self._wq.send(_CLOSE_SENTINEL)
        except ChannelClosed:
            self._teardown(None)

    def abort(self) -> None:
        self._closing = True
        self._teardown(None)

    # -- asyncio.WriteTransport --------------------------------------------
    def write(self, data) -> None:
        if self._closing or self._lost or not data:
            return
        self._wbuf_size += len(data)
        try:
            self._wq.send(bytes(data))
        except ChannelClosed:
            pass

    def writelines(self, chunks) -> None:
        self.write(b"".join(bytes(c) for c in chunks))

    def can_write_eof(self) -> bool:
        return True

    def write_eof(self) -> None:
        if self._closing or self._lost:
            return
        try:
            self._wq.send(_EOF_SENTINEL)
        except ChannelClosed:
            pass

    def get_write_buffer_size(self) -> int:
        return self._wbuf_size

    def get_write_buffer_limits(self) -> Tuple[int, int]:
        return (0, 0)

    def set_write_buffer_limits(self, high: int = None, low: int = None) -> None:
        pass  # sim channels are unbounded; flow control is not simulated

    # -- asyncio.ReadTransport ---------------------------------------------
    def pause_reading(self) -> None:
        if self._read_gate is None and not self._lost:
            self._read_gate = SimFuture()

    def resume_reading(self) -> None:
        if self._read_gate is not None:
            gate, self._read_gate = self._read_gate, None
            gate.set_result(None)

    def is_reading(self) -> bool:
        return self._read_gate is None and not self._lost

    # -- pumps -------------------------------------------------------------
    async def _read_pump(self) -> None:
        try:
            while True:
                if self._read_gate is not None:
                    await self._read_gate
                data = await self._stream.read()
                if data == b"":
                    keep = False
                    if not self._lost and not self._closing:
                        keep = bool(self._protocol.eof_received())
                    if not keep:
                        self._teardown(None)
                    return
                if self._lost:
                    return
                self._protocol.data_received(data)
        except ConnectionReset as exc:
            self._teardown(ConnectionResetError(str(exc)))
        except Cancelled:
            raise

    async def _write_pump(self) -> None:
        try:
            while True:
                item = await self._wq.recv()
                if item is _EOF_SENTINEL:
                    self._stream._tx.close()
                    continue
                if item is _CLOSE_SENTINEL:
                    self._teardown(None)
                    return
                self._wbuf_size -= len(item)
                await self._stream._tx.send(item)
        except ChannelClosed:
            pass
        except ConnectionReset as exc:
            self._teardown(ConnectionResetError(str(exc)))

    def _teardown(self, exc: Optional[Exception]) -> None:
        if self._lost:
            return
        self._lost = True
        self._closing = True
        self._wq.close()
        self._stream.close()
        if self._reader is not None:
            self._reader.abort()
        if self._writer is not None:
            self._writer.abort()
        sock = self._extra.get("socket")
        if sock is not None:
            try:
                sock.close()  # the never-connected token fd
            except OSError:
                pass
        try:
            self._protocol.connection_lost(exc)
        except Exception:  # noqa: BLE001 — protocol bugs must not kill the sim
            pass


class SimDatagramTransport:
    """asyncio.DatagramTransport over a sim UdpSocket: sync ``sendto``
    through a sender pump, inbound datagrams pumped into
    ``protocol.datagram_received``."""

    def __init__(self, loop: "SimEventLoop", usock, protocol, peer):
        self._loop = loop
        self._usock = usock
        self._protocol = protocol
        self._peer = peer  # remote_addr-connected endpoints omit the dst
        self._sq = Channel()
        self._closing = False
        self._extra = {"sockname": usock.local_addr(),
                       "socket": _FakeServerSocket(usock.local_addr(), peer,
                                                   datagram=True)}
        if peer is not None:
            self._extra["peername"] = peer
        self._reader = None
        self._writer = None

    def start_pump(self) -> None:
        self._reader = _task.spawn(self._read_pump())
        self._writer = _task.spawn(self._write_pump())

    def get_extra_info(self, name: str, default: Any = None) -> Any:
        return self._extra.get(name, default)

    def is_closing(self) -> bool:
        return self._closing

    def set_protocol(self, protocol) -> None:
        self._protocol = protocol

    def get_protocol(self):
        return self._protocol

    def sendto(self, data, addr=None) -> None:
        # asyncio's contracts, enforced eagerly so errors surface at the
        # call site (not as a pump-task failure that would abort the sim):
        # a connected endpoint takes None (or its own peer); an
        # unconnected endpoint requires an address; the address must
        # parse.
        if addr is None:
            if self._peer is None:
                raise ValueError(
                    "sendto needs an address on an unconnected endpoint")
            dst = self._peer
        else:
            dst = parse_addr((str(addr[0]), int(addr[1])))
            if self._peer is not None and dst != self._peer:
                raise ValueError(
                    f"Invalid address: must be None or {self._peer}")
        if self._closing:
            return
        try:
            self._sq.send((bytes(data), dst))
        except ChannelClosed:
            pass

    def abort(self) -> None:
        self.close()

    def close(self) -> None:
        if self._closing:
            return
        self._closing = True
        self._sq.close()
        if self._reader is not None:
            self._reader.abort()
        self._usock.close()
        try:
            self._protocol.connection_lost(None)
        except Exception:  # noqa: BLE001 — protocol bugs stay contained
            pass

    async def _read_pump(self) -> None:
        try:
            while not self._closing:
                data, addr = await self._usock.recv_from()
                if self._peer is not None and addr != self._peer:
                    continue  # connected-UDP filter, like the kernel's
                self._protocol.datagram_received(data, addr)
        except (ConnectionReset, ChannelClosed, Cancelled):
            pass

    async def _write_pump(self) -> None:
        try:
            while True:
                data, dst = await self._sq.recv()
                try:
                    await self._usock.send_to(dst, data)
                except (ConnectionReset, OSError) as exc:
                    try:
                        self._protocol.error_received(exc)
                    except Exception:  # noqa: BLE001
                        pass
        except (ChannelClosed, Cancelled):
            pass


class _FakeServerSocket:
    """Stand-in for ``Server.sockets`` entries and for a connection's
    ``get_extra_info("socket")``: consumers inspect addresses (aiohttp's
    runner reads ``getsockname()``; anyio, reached through httpx, calls
    ``getpeername()``) or apply socket options, which are no-ops in-sim."""

    __slots__ = ("_addr", "_peer", "type", "proto")
    family = _socket.AF_INET

    def __init__(self, addr: Tuple[str, int], peer: Tuple[str, int] = None,
                 *, datagram: bool = False):
        self._addr = addr
        self._peer = peer
        self.type = _socket.SOCK_DGRAM if datagram else _socket.SOCK_STREAM
        self.proto = (_socket.IPPROTO_UDP if datagram
                      else _socket.IPPROTO_TCP)

    def getsockname(self):
        return self._addr

    def getpeername(self):
        if self._peer is None:
            raise OSError("not connected")
        return self._peer

    def fileno(self) -> int:
        return -1

    def setsockopt(self, *a, **kw) -> None:
        pass

    def getsockopt(self, *a, **kw) -> int:
        return 0

    def close(self) -> None:
        pass


class SimServer:
    """``loop.create_server`` result: in-sim accept loop feeding the
    protocol factory (asyncio.Server shape)."""

    def __init__(self, loop: "SimEventLoop", listener: TcpListener,
                 factory: Callable[[], Any]):
        self._loop = loop
        self._listener = listener
        self._factory = factory
        self.sockets: List[_FakeServerSocket] = [
            _FakeServerSocket(listener.local_addr())]
        self._closed = SimFuture()
        self._accept_task = _task.spawn(self._accept_loop())

    async def _accept_loop(self) -> None:
        try:
            while True:
                stream, peer = await self._listener.accept()
                protocol = self._factory()
                transport = SimTransport(
                    self._loop, stream, protocol,
                    {"peername": peer, "sockname": stream.local_addr(),
                     "socket": _FakeServerSocket(stream.local_addr(), peer)})
                try:
                    protocol.connection_made(transport)
                except Exception:  # noqa: BLE001 — drop the conn, not the server
                    transport.abort()
                    continue
                transport.start_pumps()
        except (ConnectionReset, ChannelClosed):
            pass  # listener closed
        finally:
            if not self._closed.done():
                self._closed.set_result(None)

    def close(self) -> None:
        self._listener.close()

    async def wait_closed(self) -> None:
        if self._loop._world_gone():
            return  # GC-time cleanup: nothing left to wait for
        await self._closed

    def is_serving(self) -> bool:
        return not self._closed.done()

    async def start_serving(self) -> None:
        pass  # always serving once created

    async def serve_forever(self) -> None:
        await SimFuture()  # parks forever; cancellation tears it down

    def get_loop(self) -> "SimEventLoop":
        return self._loop

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        self.close()
        await self.wait_closed()
        return False


# ---------------------------------------------------------------------------
# The loop
# ---------------------------------------------------------------------------

class SimEventLoop:
    """The running-event-loop surface, one per simulation world.

    Methods resolve the *current* handle at call time (timers, tasks, and
    connections land on whatever node's context is active), but the object
    itself is cached per Handle so library identity checks hold."""

    def __init__(self, handle):
        self._handle = handle
        # Real socket objects used as connect tokens → their sim streams.
        self._sock_streams: Dict[Any, TcpStream] = {}
        self._exception_handler: Optional[Callable] = None
        self.exceptions: List[dict] = []  # call_exception_handler records

    # -- time & scheduling --------------------------------------------------
    def time(self) -> float:
        return self._handle.time.now_ns() / 1e9

    def _world_gone(self) -> bool:
        """True when called after the loop's world ended (typically
        GC-time cleanup: a library's __del__/__aexit__ closing servers
        once block_on returned). Real asyncio raises 'Event loop is
        closed' there and the interpreter prints 'Exception ignored';
        the sim degrades silently instead — the world's state is gone,
        so the cleanup has nothing left to act on."""
        return _context.try_current_handle() is not self._handle

    def call_soon(self, callback, *args, context=None):
        return self.call_later(0, callback, *args)

    def call_soon_threadsafe(self, callback, *args, context=None):
        import threading

        # In-world (the executing thread, whichever OS thread that is —
        # each world runs on exactly one at a time): behaves as
        # call_soon. This is the common caller — defensive library code,
        # and in-sim "threads" (asyncio.to_thread / run_in_executor) are
        # deterministic tasks on the same thread.
        if _context.try_current_handle() is self._handle:
            return self.call_soon(callback, *args)
        running = self._handle.task.running_thread
        if running is not None and running != threading.get_ident():
            # A foreign OS thread racing a LIVE run cannot safely mutate
            # the timer heap — refuse loudly instead of corrupting it.
            raise RuntimeError(
                "call_soon_threadsafe from a foreign OS thread during a "
                "live simulation is not supported: real threads are "
                "outside the deterministic world (use asyncio.to_thread, "
                "which the sim runs as a deterministic task)")
        # Idle world (between block_on runs) or teardown: arm the timer
        # directly on the world's own heap — it fires when (and if) the
        # world next advances, like the pre-round-5 behavior.
        try:
            entry = self._handle.time.add_timer(0, lambda: callback(*args))
        except Exception:  # noqa: BLE001 — interpreter-teardown safety
            return _DeadTimerHandle()
        return SimTimerHandle(entry, self._handle.time.now_ns() / 1e9)

    def call_later(self, delay: float, callback, *args, context=None):
        if self._world_gone():
            return _DeadTimerHandle()
        entry = self._handle.time.add_timer(
            to_ns(max(0.0, delay)), lambda: callback(*args))
        return SimTimerHandle(entry, self.time() + delay)

    def call_at(self, when: float, callback, *args, context=None):
        if self._world_gone():
            return _DeadTimerHandle()
        entry = self._handle.time.add_timer_at(
            round(when * 1e9), lambda: callback(*args))
        return SimTimerHandle(entry, when)

    # -- futures & tasks ----------------------------------------------------
    def create_future(self) -> AsyncioFuture:
        return AsyncioFuture()

    def create_task(self, coro, *, name: str = None, context=None):
        from . import aio

        if self._world_gone():
            coro.close()
            dead = AsyncioFuture()
            dead.cancel()
            return aio.Task(None, dead)
        return aio.create_task(coro)

    def run_in_executor(self, executor, fn, *args):
        from . import aio

        async def _run():
            return await _task.spawn_blocking(lambda: fn(*args))

        return aio.create_task(_run())

    # -- name resolution ----------------------------------------------------
    async def getaddrinfo(self, host, port, *, family=0, type=0, proto=0,
                          flags=0):
        ip, port = parse_addr((str(host), int(port or 0)))
        fam = _socket.AF_INET6 if ":" in ip else _socket.AF_INET
        if family not in (0, fam):
            raise _socket.gaierror(
                _socket.EAI_NONAME, f"no address of family {family} for {host}")
        return [(fam, _socket.SOCK_STREAM, _socket.IPPROTO_TCP, "",
                 (ip, port))]

    async def getnameinfo(self, sockaddr, flags=0):
        return (sockaddr[0], str(sockaddr[1]))

    # -- raw-socket surface (token-keyed over sim streams) ------------------
    async def sock_connect(self, sock, address) -> None:
        """Associate a (never actually connected) real socket object with a
        sim stream to ``address``; the sock is the lookup token that
        ``create_connection(sock=...)`` and ``sock_sendall``/``sock_recv``
        use. This is the path aiohappyeyeballs-era clients take."""
        self._sweep_closed_socks()
        self._sock_streams[sock] = await TcpStream.connect(address)

    async def sock_sendall(self, sock, data) -> None:
        await self._sim_sock(sock).write_all(bytes(data))

    async def sock_recv(self, sock, nbytes: int) -> bytes:
        return await self._sim_sock(sock).read(nbytes)

    async def sock_recv_into(self, sock, buf) -> int:
        data = await self._sim_sock(sock).read(len(buf))
        buf[: len(data)] = data
        return len(data)

    def _sim_sock(self, sock) -> TcpStream:
        stream = self._sock_streams.get(sock)
        if stream is None:
            raise OSError(
                "socket is not connected through the sim loop "
                "(sock_connect was never called on it)")
        if sock.fileno() == -1:  # token fd closed: surface it like a dead fd
            self._sock_streams.pop(sock, None)
            stream.close()
            raise OSError("socket is closed")
        return stream

    def _sweep_closed_socks(self) -> None:
        """Close sim streams whose token fd was close()d by the caller.

        A real close() sends FIN from the kernel with no loop involvement;
        the sim analog cannot hook close(), so closed tokens are reaped at
        deterministic points (each sock_connect, and any sock_* touch of
        the closed sock) — the peer sees EOF then, not at GC time."""
        dead = [s for s in self._sock_streams if s.fileno() == -1]
        for s in dead:
            self._sock_streams.pop(s).close()

    # -- connections --------------------------------------------------------
    async def create_connection(self, protocol_factory, host=None, port=None,
                                *, sock=None, ssl=None, family=0, proto=0,
                                flags=0, local_addr=None, server_hostname=None,
                                happy_eyeballs_delay=None, interleave=None,
                                all_errors=False, ssl_handshake_timeout=None,
                                ssl_shutdown_timeout=None):
        if ssl:
            raise NotImplementedError(
                "TLS is not simulated; connect with plain protocols in-sim")
        if sock is not None:
            stream = self._sock_streams.pop(sock, None)
            if stream is None:
                raise OSError("sock was not connected through the sim loop")
        else:
            stream = await TcpStream.connect((host, port))
        protocol = protocol_factory()
        extra = {"peername": stream.peer_addr(),
                 "sockname": stream.local_addr()}
        if sock is not None:
            extra["socket"] = sock  # live fd for tcp_nodelay-style tuning
        else:
            # Libraries (anyio/httpx) read addresses off the socket object
            # itself; hand them an address-faithful stand-in.
            extra["socket"] = _FakeServerSocket(stream.local_addr(),
                                                stream.peer_addr())
        transport = SimTransport(self, stream, protocol, extra)
        protocol.connection_made(transport)
        transport.start_pumps()
        return transport, protocol

    async def create_server(self, protocol_factory, host=None, port=None,
                            *, sock=None, backlog=100, ssl=None, family=0,
                            flags=0, reuse_address=None, reuse_port=None,
                            keep_alive=None, ssl_handshake_timeout=None,
                            ssl_shutdown_timeout=None, start_serving=True):
        if ssl:
            raise NotImplementedError(
                "TLS is not simulated; serve plain protocols in-sim")
        if sock is not None:
            raise NotImplementedError(
                "create_server(sock=...) is not supported in-sim; pass "
                "host/port")
        if not isinstance(host, str):
            # asyncio accepts a sequence of hosts; sim worlds bind one.
            host = host[0] if host else "0.0.0.0"
        listener = await TcpListener.bind((host, port or 0))
        return SimServer(self, listener, protocol_factory)

    async def create_datagram_endpoint(self, protocol_factory,
                                       local_addr=None, remote_addr=None,
                                       *, family=0, proto=0, flags=0,
                                       sock=None, reuse_port=None,
                                       allow_broadcast=None):
        """asyncio.DatagramProtocol over the sim UDP facade: the loop
        surface DNS resolvers and UDP-protocol libraries use."""
        if sock is not None:
            raise NotImplementedError(
                "create_datagram_endpoint(sock=...) is not supported "
                "in-sim; pass local_addr/remote_addr")
        from ..net.udp import UdpSocket

        if local_addr is not None:
            usock = await UdpSocket.bind(local_addr)
        else:
            usock = await UdpSocket.bind("0.0.0.0:0")
        peer = None
        if remote_addr is not None:
            peer = parse_addr((str(remote_addr[0]), int(remote_addr[1])))
        protocol = protocol_factory()
        transport = SimDatagramTransport(self, usock, protocol, peer)
        protocol.connection_made(transport)
        transport.start_pump()
        return transport, protocol

    async def start_tls(self, *a, **kw):
        raise NotImplementedError("TLS is not simulated")

    # -- lifecycle / introspection -----------------------------------------
    def get_debug(self) -> bool:
        return False

    def set_debug(self, enabled: bool) -> None:
        pass

    def is_running(self) -> bool:
        return True

    def is_closed(self) -> bool:
        return False

    def close(self) -> None:
        raise RuntimeError("the sim event loop is owned by the Runtime")

    def stop(self) -> None:
        raise RuntimeError("the sim event loop is owned by the Runtime")

    def run_until_complete(self, *a):
        raise RuntimeError(
            "sim worlds are driven by Runtime.block_on, not loop.run_*")

    run_forever = run_until_complete

    async def shutdown_asyncgens(self) -> None:
        pass

    async def shutdown_default_executor(self, timeout=None) -> None:
        pass

    def add_signal_handler(self, sig, callback, *args):
        raise NotImplementedError("signals do not exist inside a simulation")

    def remove_signal_handler(self, sig) -> bool:
        return False

    def default_exception_handler(self, ctx: dict) -> None:
        import logging

        logging.getLogger("madsim_tpu.eventloop").warning(
            "%s", ctx.get("message", "Unhandled exception in event loop"))

    def set_exception_handler(self, handler) -> None:
        self._exception_handler = handler

    def get_exception_handler(self):
        return self._exception_handler

    def call_exception_handler(self, ctx: dict) -> None:
        self.exceptions.append(ctx)
        if self._exception_handler is not None:
            self._exception_handler(self, ctx)
        else:
            self.default_exception_handler(ctx)

    def get_task_factory(self):
        return None

    def set_task_factory(self, factory) -> None:
        pass


def get_sim_loop() -> SimEventLoop:
    """The current world's SimEventLoop (cached on the Handle so loop
    identity is stable across get_event_loop/get_running_loop calls)."""
    handle = _context.current_handle()
    loop = getattr(handle, "_sim_event_loop", None)
    if loop is None:
        loop = handle._sim_event_loop = SimEventLoop(handle)
    return loop
