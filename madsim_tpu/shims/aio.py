"""asyncio-shaped API over the simulation + interpreter-level patching.

The madsim-tokio analog (`madsim-tokio/src/lib.rs:32-52`): application code
written against asyncio's surface runs deterministically inside the
simulation. Two usage modes:

1. Import this module instead of asyncio (``from madsim_tpu.shims import
   aio as asyncio``): the shimmed subset keeps asyncio's names and
   semantics — ``sleep``, ``wait_for``, ``gather``, ``create_task``,
   ``Event``, ``Lock``, ``Semaphore``, ``Queue`` — on virtual time and the
   seeded scheduler.

2. ``with aio.patched():`` — monkeypatch the real ``asyncio`` module (plus
   ``time.time``/``monotonic``/``perf_counter``/``sleep``, ``random``'s
   global functions, and ``os.urandom``) so *unmodified* third-party async
   code runs in-sim. This is the Python-level analog of the reference's
   libc ``#[no_mangle]`` interception (`rand.rs:195-261`,
   `time/system_time.rs:4-97`): outside a simulation context every patched
   function falls through to the real implementation, exactly like the
   reference's ``dlsym(RTLD_NEXT)`` passthrough.

``patched()`` also swaps the *running event loop* surface: code that opens
its own sockets through ``loop.create_connection`` / ``create_server`` /
``sock_*`` (pip aiohttp, protocol-level DB clients) lands on the simulated
network — see :mod:`madsim_tpu.shims.eventloop` and tests/test_eventloop.py
(the tokio-postgres-class proof, `madsim-tokio-postgres/src/socket.rs:6-13`).

Not simulable at this level (documented gap, SURVEY §7): code that drives
its own event loop (``asyncio.run``/``loop.run_until_complete`` inside the
sim), raw selector registration (``loop.add_reader`` on real fds), and
threads.
"""
from __future__ import annotations

import builtins
import contextlib
from typing import Any, Awaitable, Callable, Coroutine, Iterable, List

from .. import sync as _sync
from .. import task as _task
from .. import time as _time
from ..core import context as _context
from ..core.futures import Cancelled, SimFuture

TimeoutError = builtins.TimeoutError  # asyncio.TimeoutError is this since 3.11
CancelledError = Cancelled
# In real mode awaits bridge through asyncio, whose CancelledError is the
# stdlib BaseException one — cancellation-aware except clauses must catch
# both families.
import asyncio as _stdlib_asyncio_early  # noqa: E402

CANCELLED_TYPES = (Cancelled, _stdlib_asyncio_early.CancelledError)

try:  # 3.11+: alias the builtin, so `aio.ExceptionGroup` works everywhere
    ExceptionGroup = ExceptionGroup
except NameError:  # 3.10: minimal stand-in so sim TaskGroups still report
    class ExceptionGroup(Exception):  # noqa: A001 — deliberate shadow
        def __init__(self, message, exceptions):
            super().__init__(message, exceptions)
            self.message = message
            self.exceptions = list(exceptions)


# ---------------------------------------------------------------------------
# Coroutine / task API
# ---------------------------------------------------------------------------

def sleep(delay: float, result: Any = None):
    """asyncio.sleep on virtual time."""

    async def _sleep():
        await _time.sleep(max(0.0, delay))
        return result

    return _sleep()


class Task:
    """asyncio.Task-flavored wrapper over a simulation JoinHandle."""

    # Stdlib-Task internals some libraries reach into (anyio checks both
    # before delivering cancellation). Sim interrupts deliver at the next
    # poll — there is never a deferred cancel or a tracked waiter future.
    _must_cancel = False
    _fut_waiter = None

    def __init__(self, handle: _task.JoinHandle, fut: SimFuture,
                 coro: Coroutine = None):
        self._handle = handle
        self._fut = fut
        self._coro = coro
        self._done_callbacks: List[tuple] = []  # (user cb, installed wrapper)

    def cancel(self, msg: "str | None" = None) -> bool:
        """Request cancellation (asyncio semantics): CancelledError is
        THROWN INTO the task at its current await, so the task can catch
        it, run cleanup, and even raise a different error — completion is
        observed by awaiting the task, not by cancel() returning. ``msg``
        is accepted for stdlib signature parity (anyio passes one)."""
        if self._fut.done():
            return False
        import inspect as _inspect

        handle = _context.try_current_handle()
        inner = getattr(self._handle, "_task", None)
        # ensure_future accepts non-coroutine awaitables (SimFuture etc.);
        # only real coroutines have inspectable start state.
        if self._coro is not None and _inspect.iscoroutine(self._coro) and \
                _inspect.getcoroutinestate(self._coro) == "CORO_CREATED":
            # Never started: nothing to unwind and the guard will die
            # before it can resolve the result future — close the wrapped
            # coroutine (no unawaited leak) and resolve here.
            try:
                self._coro.close()
            except (RuntimeError, ValueError):
                pass
            self._fut.set_exception(CancelledError())
        if handle is not None and inner is not None:
            handle.task.interrupt(inner, CancelledError())
        else:
            # No executor to deliver through (e.g. real backend): abort.
            self._handle.abort()
            if not self._fut.done():
                self._fut.set_exception(CancelledError())
        return True

    def done(self) -> bool:
        return self._fut.done()

    def cancelled(self) -> bool:
        return self._fut.done() and isinstance(self._fut._exception,
                                               CANCELLED_TYPES)

    def result(self) -> Any:
        if not self._fut.done():
            raise RuntimeError("task is not done")
        return self._fut.result()

    def exception(self):
        if not self._fut.done():
            raise RuntimeError("task is not done")
        return self._fut._exception

    # -- asyncio.Task surface used by third-party code under patched() ----
    def add_done_callback(self, cb: Callable[["Task"], None]) -> None:
        """asyncio semantics: the callback receives the *task* object."""
        def wrapper(_f, cb=cb):
            cb(self)

        self._done_callbacks.append((cb, wrapper))
        self._fut.add_done_callback(wrapper)

    def remove_done_callback(self, cb: Callable[["Task"], None]) -> int:
        removed = 0
        kept = []
        for user_cb, wrapper in self._done_callbacks:
            if user_cb == cb:
                removed += 1
                try:
                    self._fut._callbacks.remove(wrapper)
                except ValueError:
                    pass  # already fired
            else:
                kept.append((user_cb, wrapper))
        self._done_callbacks = kept
        return removed

    def get_name(self) -> str:
        return f"sim-task-{getattr(self._handle, 'id', '?')}"

    def set_name(self, name: str) -> None:
        pass

    def get_coro(self) -> Coroutine:
        return self._coro

    def get_loop(self):
        from .eventloop import get_sim_loop

        return get_sim_loop()

    def uncancel(self) -> int:
        return 0

    def cancelling(self) -> int:
        return 0

    def __await__(self):
        return self._fut.__await__()


def create_task(coro: Coroutine, *, name: str = None) -> Task:
    """Spawn on the current node's deterministic scheduler.

    Exceptions are contained in the Task (asyncio semantics) rather than
    aborting the whole simulation (the raw task.spawn semantics).
    """
    fut = SimFuture()

    async def _guard():
        try:
            fut.set_result(await coro)
        except GeneratorExit:
            raise  # task abort: let close() unwind; cancel() sets the future
        except CANCELLED_TYPES:
            if not fut.done():
                fut.set_exception(CancelledError())
        except BaseException as exc:  # noqa: BLE001 — contained, like asyncio
            if not fut.done():
                fut.set_exception(exc)

    return Task(_task.spawn(_guard()), fut, coro)


ensure_future = create_task


async def gather(*aws: Awaitable, return_exceptions: bool = False) -> List[Any]:
    tasks = [create_task(aw) if not isinstance(aw, Task) else aw for aw in aws]
    results: List[Any] = []
    first_exc = None
    for t in tasks:
        try:
            results.append(await t)
        except BaseException as exc:  # noqa: BLE001
            if return_exceptions:
                results.append(exc)
            elif first_exc is None:
                first_exc = exc
                results.append(None)
    if first_exc is not None and not return_exceptions:
        raise first_exc
    return results


async def wait_for(aw: Awaitable, timeout: float) -> Any:
    if timeout is None:
        return await aw
    return await _time.timeout(timeout, aw)


FIRST_COMPLETED = "FIRST_COMPLETED"
FIRST_EXCEPTION = "FIRST_EXCEPTION"
ALL_COMPLETED = "ALL_COMPLETED"


async def wait(aws, *, timeout: float = None, return_when: str = ALL_COMPLETED):
    """asyncio.wait over sim tasks → (done, pending). The select!/select
    building block (`madsim-tokio` passes tokio's through)."""
    tasks = [aw if isinstance(aw, Task) else create_task(aw) for aw in aws]
    gate = SimFuture()

    def arm(t: Task):
        def on_done(_f):
            if gate.done():
                return
            exc = t._fut._exception
            failed = exc is not None and not isinstance(exc, CANCELLED_TYPES)
            if return_when == FIRST_COMPLETED:
                gate.set_result(None)
            elif return_when == FIRST_EXCEPTION and failed:
                # Cancellations don't count (the asyncio contract).
                gate.set_result(None)
            elif all(x.done() for x in tasks):
                gate.set_result(None)

        t._fut.add_done_callback(on_done)

    for t in tasks:
        arm(t)
    if not tasks:
        return set(), set()
    try:
        if timeout is not None:
            await _time.timeout(timeout, gate)
        else:
            await gate
    except TimeoutError:
        pass
    done = {t for t in tasks if t.done()}
    return done, set(tasks) - done


def as_completed(aws, *, timeout: float = None):
    """asyncio.as_completed: yields awaitables in completion order; each
    resolves to the task's RESULT (raising its exception), and ``timeout``
    is one overall deadline across the whole iteration — both per the real
    asyncio contract, since install() patches this over asyncio."""
    tasks = [aw if isinstance(aw, Task) else create_task(aw) for aw in aws]
    ch = _sync.Channel()
    for t in tasks:
        t._fut.add_done_callback(lambda _f, t=t: ch.send(t))
    deadline_ns = (_time.monotonic_ns() + _time.to_ns(timeout)
                   if timeout is not None else None)

    async def _next():
        if deadline_ns is None:
            t = await ch.recv()
        else:
            remaining = (deadline_ns - _time.monotonic_ns()) / 1e9
            if remaining <= 0:
                raise TimeoutError()
            t = await _time.timeout(remaining, ch.recv())
        return t.result()

    return (_next() for _ in tasks)


async def shield(aw: Awaitable) -> Any:
    # Cancellation granularity in the sim is the task; a shielded await is
    # just the await (supervisor aborts drop whole tasks, not awaits).
    return await aw


class Timeout:
    """``async with asyncio.timeout(s):`` (3.11+) on virtual time.

    Real asyncio cancels the waiting TASK on expiry (never the awaited
    object — it may be shared) and converts the cancellation into
    TimeoutError at scope exit; same here via the executor's interrupt():
    the deadline timer throws CancelledError into the enclosing task's
    current await, which unwinds through the existing cancel-safe paths
    (mailbox requeue, channel restore, ...), and __aexit__ swallows that
    cancellation into TimeoutError.
    """

    def __init__(self, delay: "float | None", when: "float | None" = None):
        self._delay = delay    # relative seconds, or None = never expires
        self._when = when      # absolute loop-time deadline (timeout_at)
        self._expired = False
        self._timer = None

    async def __aenter__(self):
        if self._when is not None:
            self._delay = max(0.0, self._when - _time.monotonic())
        if self._delay is None:  # timeout(None) / reschedule(None): no deadline
            return self
        if self._when is None:
            # asyncio contract: when() is the absolute deadline once armed.
            self._when = _time.monotonic() + self._delay
        task = _context.current_task()
        executor = _context.current_handle().task

        def expire():
            self._expired = True
            executor.interrupt(task, CancelledError("timeout scope expired"))

        self._timer = _context.current_handle().time.add_timer(
            _time.to_ns(self._delay), expire)
        return self

    async def __aexit__(self, exc_type, exc, tb):
        if self._timer is not None:
            self._timer.cancel()
        if self._expired and (exc_type is None
                              or issubclass(exc_type, CANCELLED_TYPES)):
            raise TimeoutError() from None
        return False

    def expired(self) -> bool:
        return self._expired

    def when(self) -> "float | None":
        return self._when

    def reschedule(self, when: "float | None") -> None:
        # Supported only before __aenter__ arms the timer (the common
        # library pattern: construct, adjust, then enter). ``when`` fully
        # replaces the deadline: None disables it even for a scope
        # constructed with a relative delay.
        if self._timer is not None:
            raise RuntimeError("cannot reschedule an armed sim timeout")
        self._when = when
        self._delay = None


def timeout(delay: "float | None"):
    from ..core.backend import is_real

    if is_real():
        # Production backend: the real thing exists and is correct.
        import asyncio as _real_asyncio

        return _real_asyncio.timeout(delay)
    return Timeout(delay)


def timeout_at(when: "float | None"):
    """asyncio.timeout_at on the virtual clock (deadline in loop.time()
    terms, i.e. virtual monotonic seconds)."""
    from ..core.backend import is_real

    if is_real():
        import asyncio as _real_asyncio

        return _real_asyncio.timeout_at(when)
    if when is None:
        return Timeout(None)
    return Timeout(0.0, when=when)


class TaskGroup:
    """asyncio.TaskGroup (3.11+) over sim tasks, with the real contract:
    a body exception cancels all children immediately; a child failure
    cancels its siblings the moment it happens (not when its turn to be
    awaited comes — a hung earlier sibling cannot mask it); children may
    spawn further children mid-flight (a task handed the group can call
    create_task, and those are awaited/cancelled too); failures surface as
    an ExceptionGroup (combined with the body's exception if both fail)."""

    def __init__(self):
        self._tasks: List[Task] = []
        self._errors: List[BaseException] = []
        self._left = 0
        self._aborting = False
        self._exited = False
        self._in_body = False
        self._host_interrupted = False
        self._host = None
        self._gate: SimFuture = None

    async def __aenter__(self):
        # None on the real backend (no sim executor): the group still
        # works there via the SimFuture asyncio bridge, minus the
        # host-interrupt fast path.
        self._host = _context.try_current_task()
        self._in_body = True
        return self

    def create_task(self, coro: Coroutine, *, name: str = None) -> Task:
        if self._exited:
            # asyncio's contract: a finished group refuses new children
            # loudly instead of spawning an unwatched orphan.
            coro.close()
            raise RuntimeError("TaskGroup is finished")
        t = create_task(coro)
        self._tasks.append(t)
        self._left += 1
        # Done-callbacks attach at CREATE time, so late children (spawned
        # from inside running children) are tracked like any other.
        t._fut.add_done_callback(lambda _f, t=t: self._on_child_done(t))
        if self._aborting:
            t.cancel()
        return t

    def _on_child_done(self, t: Task) -> None:
        self._left -= 1
        child_exc = t._fut._exception
        if child_exc is not None and not isinstance(child_exc,
                                                    CANCELLED_TYPES):
            self._errors.append(child_exc)
            self._abort()
        if self._left == 0 and self._gate is not None and not self._gate.done():
            self._gate.set_result(None)

    def _abort(self) -> None:
        if self._aborting:
            # Idempotent: a second failing child must not throw extra
            # CancelledErrors into siblings already mid-cleanup (new tasks
            # created while aborting are cancelled by create_task).
            return
        self._aborting = True
        for t in self._tasks:
            t.cancel()
        if self._in_body and self._host is not None:
            # asyncio cancels the PARENT too: a child failure must tear
            # down `await serve_forever()` in the body, not hang behind it.
            self._host_interrupted = True
            _context.current_handle().task.interrupt(
                self._host, CancelledError("TaskGroup child failed"))

    async def __aexit__(self, exc_type, exc, tb):
        self._in_body = False
        if exc_type is not None and issubclass(exc_type, CANCELLED_TYPES) \
                and self._host_interrupted:
            # The body exited ON our own abort interrupt: the flag is
            # consumed here, so a later CancelledError at the gate is a
            # genuine external one.
            self._host_interrupted = False
        if exc_type is not None:
            self._abort()
        self._gate = SimFuture()
        if self._left == 0:
            self._gate.set_result(None)
        external_cancel: "BaseException | None" = None
        while True:
            try:
                await self._gate
                break
            except CANCELLED_TYPES as cancel_exc:
                if self._host_interrupted:
                    # Exactly one self-induced cancel may land late (our
                    # own abort interrupt raced the body's exit); absorb it.
                    self._host_interrupted = False
                    continue
                # EXTERNAL cancellation (supervisor / enclosing timeout):
                # abort the children and keep waiting for them.
                external_cancel = cancel_exc
                self._aborting = True
                for t in self._tasks:
                    t.cancel()
        self._exited = True
        if self._errors:
            # Child errors take precedence over a cancellation (asyncio:
            # the cancellation propagates only when there are no errors).
            group = list(self._errors)
            if exc is not None and not isinstance(exc, CANCELLED_TYPES):
                group.append(exc)  # both failed: neither may be lost
            raise ExceptionGroup("unhandled errors in a TaskGroup", group)
        if external_cancel is not None:
            # Preserve the cancellation family: real-mode asyncio
            # cancellation must stay convertible by asyncio.timeout.
            raise external_cancel
        return False  # the body's own exception propagates


def get_event_loop():
    """The current world's SimEventLoop: the full transport/protocol
    surface (create_connection/create_server/sock_*), cached per Handle so
    library identity checks (``loop is self._loop``) hold. See
    :mod:`madsim_tpu.shims.eventloop`."""
    from .eventloop import get_sim_loop

    return get_sim_loop()


get_running_loop = get_event_loop


def current_task(loop=None):
    """asyncio.current_task over the sim executor: a per-task view with the
    3.11 cancel/uncancel counting protocol (aiohttp's TimerContext relies
    on it to convert its own cancellation into TimeoutError)."""
    from .eventloop import current_task_view

    return current_task_view()


def all_tasks(loop=None):
    return set()  # introspection-only surface; not tracked in-sim


# ---------------------------------------------------------------------------
# Synchronization (asyncio surface over madsim_tpu.sync)
# ---------------------------------------------------------------------------

class Event(_sync.Event):
    def clear(self) -> None:
        self._set = False


Lock = _sync.Lock
Semaphore = _sync.Semaphore


class Condition:
    """asyncio.Condition over the sim scheduler."""

    def __init__(self, lock: Lock = None):
        self._lock = lock if lock is not None else Lock()
        self._waiters: List[SimFuture] = []

    async def __aenter__(self):
        await self._lock.acquire()
        return self

    async def __aexit__(self, *exc):
        self._lock.release()
        return False

    async def acquire(self) -> None:
        await self._lock.acquire()

    def release(self) -> None:
        self._lock.release()

    async def wait(self) -> bool:
        if not self._lock._locked:
            raise RuntimeError("cannot wait on un-acquired lock")
        fut = SimFuture()
        self._waiters.append(fut)
        self._lock.release()
        try:
            # Shared interrupt-safe protocol: a delivered notification is
            # handed to a live waiter; a pending one deregisters. The
            # handoff uses the internal path — the cancelled waiter does
            # not hold the lock here.
            await _sync._await_waiter(fut, self._waiters,
                                      lambda _f: self._notify(1))
        finally:
            await self._lock.acquire()
        return True

    async def wait_for(self, predicate) -> Any:
        while not (result := predicate()):
            await self.wait()
        return result

    def notify(self, n: int = 1) -> None:
        if not self._lock._locked:
            raise RuntimeError("cannot notify on un-acquired lock")
        self._notify(n)

    def _notify(self, n: int) -> None:
        woken = 0
        while self._waiters and woken < n:
            fut = self._waiters.pop(0)
            if not fut.done():
                fut.set_result(None)
                woken += 1

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


# The real asyncio exception classes, so unmodified `except asyncio.QueueEmpty`
# handlers keep working under patched().
import asyncio as _stdlib_asyncio  # noqa: E402

QueueEmpty = _stdlib_asyncio.QueueEmpty


class Queue(_sync.Queue):
    def get_nowait(self) -> Any:
        ok, item = self._ch.try_recv()
        if not ok:
            raise QueueEmpty()
        return item


# ---------------------------------------------------------------------------
# Interpreter-level patching (libc-interception analog)
# ---------------------------------------------------------------------------

def _in_sim() -> bool:
    return _context.try_current_handle() is not None


def _sim_rng():
    return _context.current_handle().rand


_PATCHES = None


def install() -> None:
    """Patch asyncio/time/random/os so unmodified code runs in-sim.

    Each wrapper falls through to the real function when called outside a
    simulation context (the dlsym(RTLD_NEXT) passthrough analog,
    `rand.rs:241-253`). Idempotent; undo with :func:`uninstall`.
    """
    global _PATCHES
    if _PATCHES is not None:
        return
    import asyncio as _aio
    import os as _os
    import random as _random
    import time as _walltime

    saved = {}

    def patch(mod, name, fn):
        # _MISSING: the stdlib lacks this name (3.11+ API on 3.10) and the
        # shim backfills it in-sim; uninstall() removes it again.
        saved[(mod, name)] = getattr(mod, name, _MISSING)
        setattr(mod, name, fn)

    def passthrough(orig, sim_fn):
        def wrapper(*a, **kw):
            if _in_sim():
                return sim_fn(*a, **kw)
            return orig(*a, **kw)

        wrapper.__name__ = getattr(orig, "__name__", "patched")
        return wrapper

    # -- asyncio ------------------------------------------------------------
    patch(_aio, "sleep", passthrough(_aio.sleep, sleep))
    patch(_aio, "wait_for", passthrough(_aio.wait_for, wait_for))
    patch(_aio, "gather", passthrough(_aio.gather, gather))
    patch(_aio, "shield", passthrough(_aio.shield, shield))
    patch(_aio, "get_event_loop", passthrough(_aio.get_event_loop, get_event_loop))
    patch(_aio, "get_running_loop", passthrough(_aio.get_running_loop, get_running_loop))

    def _sim_create_task(coro, **kw):
        return create_task(coro)

    patch(_aio, "create_task", passthrough(_aio.create_task, _sim_create_task))
    patch(_aio, "ensure_future", passthrough(_aio.ensure_future, _sim_create_task))

    # Direct asyncio.Task(...) construction (aiohttp's 3.12 eager-start
    # path) must yield a sim task in-sim, while staying a real *type*:
    # isinstance(x, asyncio.Task) and `class Mine(asyncio.Task)` keep
    # working under patched(). A metaclass dispatches only the patched
    # name's own constructor; subclasses construct normally. Eagerness is a
    # latency optimization, not semantics — the sim schedules the task
    # through the seeded ready queue like any other spawn.
    orig_task_cls = _aio.Task

    class _TaskDispatchMeta(type(orig_task_cls)):
        def __call__(cls, coro=None, **kw):
            if cls is task_patch_cls and _in_sim():
                return create_task(coro)
            return super().__call__(coro, **kw)

        def __instancecheck__(cls, obj):
            return isinstance(obj, (orig_task_cls, Task))

    class _TaskPatch(orig_task_cls, metaclass=_TaskDispatchMeta):
        pass

    task_patch_cls = _TaskPatch
    _TaskPatch.__name__ = orig_task_cls.__name__
    _TaskPatch.__qualname__ = orig_task_cls.__qualname__
    patch(_aio, "Task", _TaskPatch)

    async def _sim_to_thread(fn, /, *a, **kw):
        # In-sim "thread offload" runs the callable as a deterministic task
        # (madsim-tokio's spawn_blocking mapping); real threads inside a
        # simulation would reintroduce scheduling nondeterminism.
        from .. import task as _task_mod

        return await _task_mod.spawn_blocking(lambda: fn(*a, **kw))

    patch(_aio, "to_thread", passthrough(_aio.to_thread, _sim_to_thread))
    patch(_aio, "wait", passthrough(_aio.wait, wait))
    patch(_aio, "as_completed", passthrough(_aio.as_completed, as_completed))
    for name, sim_fn in (("timeout", timeout), ("timeout_at", timeout_at)):
        if hasattr(_aio, name):
            patch(_aio, name, passthrough(getattr(_aio, name), sim_fn))
        else:  # 3.10: no stdlib scope API — backfill it in-sim only
            patch(_aio, name, _sim_only(name, sim_fn))
    patch(_aio, "current_task", passthrough(_aio.current_task, current_task))
    patch(_aio, "all_tasks", passthrough(_aio.all_tasks, all_tasks))
    # Stdlib-internal call sites resolve these through asyncio.events
    # (``events.get_running_loop()``) and asyncio.tasks, not the package
    # namespace — patch those module attrs too. With both in place even
    # the STDLIB Timeout class (reached by libraries that bound
    # ``from asyncio import timeout`` before patching, e.g. websockets)
    # runs over the sim loop: it gets the SimEventLoop from
    # events.get_running_loop(), a TaskView (with the 3.11
    # cancel/uncancel counting) from tasks.current_task(), and arms its
    # deadline via loop.call_at on virtual time.
    patch(_aio.events, "get_running_loop",
          passthrough(_aio.events.get_running_loop, get_running_loop))
    patch(_aio.events, "get_event_loop",
          passthrough(_aio.events.get_event_loop, get_event_loop))
    patch(_aio.tasks, "current_task",
          passthrough(_aio.tasks.current_task, current_task))

    # anyio's asyncio backend binds these via `from asyncio import ...` at
    # module import; if it loaded BEFORE install(), its references bypass
    # the asyncio-module patches. Re-point the already-bound names — the
    # analog of the reference shipping patched ecosystem crates
    # (quanta/getrandom, reference README.md:36-52). A backend imported
    # later binds the patched names by itself.
    import sys as _sys

    anyio_backend = _sys.modules.get("anyio._backends._asyncio")
    if anyio_backend is not None:
        for name, sim_fn in [("current_task", current_task),
                             ("all_tasks", all_tasks),
                             ("get_running_loop", get_running_loop),
                             ("create_task", _sim_create_task),
                             ("sleep", sleep)]:
            orig = getattr(anyio_backend, name, None)
            if orig is not None:
                patch(anyio_backend, name, passthrough(orig, sim_fn))
    for name, cls in [("Event", Event), ("Lock", Lock),
                      ("Semaphore", Semaphore), ("Queue", Queue),
                      ("Condition", Condition), ("TaskGroup", TaskGroup)]:
        orig_cls = getattr(_aio, name, None)
        if orig_cls is not None:
            patch(_aio, name, _class_passthrough(orig_cls, cls))
        else:  # TaskGroup on 3.10: backfill the sim class in-sim only
            patch(_aio, name, _sim_only(name, cls))

    # -- time ---------------------------------------------------------------
    patch(_walltime, "time", passthrough(_walltime.time, _time.system_time))
    patch(_walltime, "time_ns", passthrough(_walltime.time_ns, _time.system_time_ns))
    patch(_walltime, "monotonic", passthrough(_walltime.monotonic, _time.monotonic))
    patch(_walltime, "monotonic_ns", passthrough(_walltime.monotonic_ns, _time.monotonic_ns))
    patch(_walltime, "perf_counter", passthrough(_walltime.perf_counter, _time.monotonic))

    def _sim_blocking_sleep(seconds):
        # A blocking sleep inside the single-threaded sim just advances the
        # virtual clock (due timers fire at the next scheduling point).
        _context.current_handle().time.advance(int(seconds * 1e9))

    patch(_walltime, "sleep", passthrough(_walltime.sleep, _sim_blocking_sleep))

    # -- host introspection (sched_getaffinity/sysconf interception analog,
    # `madsim/src/sim/task.rs:508-560`) -------------------------------------
    # Unmodified third-party code sizing thread pools (ThreadPoolExecutor's
    # default max_workers, loky, numexpr) must observe the NODE's configured
    # cores, same as madsim_tpu.task.available_parallelism(), not the host's.
    def _sim_cpu_count():
        return _context.current_task().node.cores

    patch(_os, "cpu_count", passthrough(_os.cpu_count, _sim_cpu_count))
    if hasattr(_os, "process_cpu_count"):  # 3.13+
        patch(_os, "process_cpu_count",
              passthrough(_os.process_cpu_count, _sim_cpu_count))
    if hasattr(_os, "sched_getaffinity"):  # POSIX
        patch(_os, "sched_getaffinity",
              passthrough(_os.sched_getaffinity,
                          lambda pid=0: set(range(_sim_cpu_count()))))

    # -- randomness (getrandom/getentropy interception analog) --------------
    patch(_os, "urandom", passthrough(_os.urandom, lambda n: _sim_rng().gen_bytes(n)))
    patch(_random, "random", passthrough(_random.random, lambda: _sim_rng().random()))
    patch(_random, "randint",
          passthrough(_random.randint, lambda a, b: _sim_rng().gen_range(a, b + 1)))
    def _sim_randrange(start, stop=None, step=1):
        if stop is None:
            start, stop = 0, start
        n_steps = (stop - start + step - 1) // step if step > 0 \
            else (stop - start + step + 1) // step
        if n_steps <= 0:
            raise ValueError("empty range for randrange()")
        return start + step * _sim_rng().gen_range(0, n_steps)

    patch(_random, "randrange", passthrough(_random.randrange, _sim_randrange))
    patch(_random, "choice", passthrough(_random.choice, lambda seq: _sim_rng().choice(seq)))
    patch(_random, "shuffle", passthrough(_random.shuffle, lambda seq: _sim_rng().shuffle(seq)))
    patch(_random, "uniform",
          passthrough(_random.uniform, lambda a, b: _sim_rng().gen_range_f64(a, b)))
    patch(_random, "getrandbits",
          passthrough(_random.getrandbits,
                      lambda k: int.from_bytes(_sim_rng().gen_bytes((k + 7) // 8),
                                               "little") >> ((8 - k % 8) % 8)))

    _PATCHES = saved


_MISSING = object()   # patch() marker: the name did not exist pre-install


def _sim_only(name, sim_obj):
    """Backfill a 3.11+ asyncio name absent from this interpreter: the sim
    implementation serves in-sim; outside a simulation the name keeps not
    existing (AttributeError), mirroring the unpatched interpreter."""

    def wrapper(*a, **kw):
        if _in_sim():
            return sim_obj(*a, **kw)
        raise AttributeError(
            f"module 'asyncio' has no attribute {name!r} on this Python "
            f"(3.11+ API; the madsim shim provides it inside a simulation "
            f"only)")

    wrapper.__name__ = name
    return wrapper


def _class_passthrough(orig_cls, sim_cls):
    """A callable standing in for a class: constructs the sim variant inside
    a simulation, the original outside."""

    def factory(*a, **kw):
        return sim_cls(*a, **kw) if _in_sim() else orig_cls(*a, **kw)

    factory.__name__ = orig_cls.__name__
    return factory


def uninstall() -> None:
    global _PATCHES
    if _PATCHES is None:
        return
    for (mod, name), orig in _PATCHES.items():
        if orig is _MISSING:
            delattr(mod, name)  # backfilled 3.11+ name: remove again
        else:
            setattr(mod, name, orig)
    _PATCHES = None


@contextlib.contextmanager
def patched():
    """``with aio.patched():`` — install() for the duration of the block."""
    was_installed = _PATCHES is not None
    install()
    try:
        yield
    finally:
        if not was_installed:
            uninstall()
