"""asyncio-shaped API over the simulation + interpreter-level patching.

The madsim-tokio analog (`madsim-tokio/src/lib.rs:32-52`): application code
written against asyncio's surface runs deterministically inside the
simulation. Two usage modes:

1. Import this module instead of asyncio (``from madsim_tpu.shims import
   aio as asyncio``): the shimmed subset keeps asyncio's names and
   semantics — ``sleep``, ``wait_for``, ``gather``, ``create_task``,
   ``Event``, ``Lock``, ``Semaphore``, ``Queue`` — on virtual time and the
   seeded scheduler.

2. ``with aio.patched():`` — monkeypatch the real ``asyncio`` module (plus
   ``time.time``/``monotonic``/``perf_counter``/``sleep``, ``random``'s
   global functions, and ``os.urandom``) so *unmodified* third-party async
   code runs in-sim. This is the Python-level analog of the reference's
   libc ``#[no_mangle]`` interception (`rand.rs:195-261`,
   `time/system_time.rs:4-97`): outside a simulation context every patched
   function falls through to the real implementation, exactly like the
   reference's ``dlsym(RTLD_NEXT)`` passthrough.

Not simulable at this level (documented gap, SURVEY §7): code that drives
its own event loop (``asyncio.run``/``loop.run_until_complete`` inside the
sim), raw selectors/sockets, and threads.
"""
from __future__ import annotations

import builtins
import contextlib
from typing import Any, Awaitable, Callable, Coroutine, Iterable, List

from .. import sync as _sync
from .. import task as _task
from .. import time as _time
from ..core import context as _context
from ..core.futures import Cancelled, SimFuture

TimeoutError = builtins.TimeoutError  # asyncio.TimeoutError is this since 3.11
CancelledError = Cancelled


# ---------------------------------------------------------------------------
# Coroutine / task API
# ---------------------------------------------------------------------------

def sleep(delay: float, result: Any = None):
    """asyncio.sleep on virtual time."""

    async def _sleep():
        await _time.sleep(max(0.0, delay))
        return result

    return _sleep()


class Task:
    """asyncio.Task-flavored wrapper over a simulation JoinHandle."""

    def __init__(self, handle: _task.JoinHandle, fut: SimFuture):
        self._handle = handle
        self._fut = fut

    def cancel(self) -> bool:
        if self._fut.done():
            return False
        self._handle.abort()
        if not self._fut.done():
            self._fut.set_exception(CancelledError())
        return True

    def done(self) -> bool:
        return self._fut.done()

    def cancelled(self) -> bool:
        return self._fut.done() and isinstance(self._fut._exception, Cancelled)

    def result(self) -> Any:
        if not self._fut.done():
            raise RuntimeError("task is not done")
        return self._fut.result()

    def exception(self):
        if not self._fut.done():
            raise RuntimeError("task is not done")
        return self._fut._exception

    def __await__(self):
        return self._fut.__await__()


def create_task(coro: Coroutine, *, name: str = None) -> Task:
    """Spawn on the current node's deterministic scheduler.

    Exceptions are contained in the Task (asyncio semantics) rather than
    aborting the whole simulation (the raw task.spawn semantics).
    """
    fut = SimFuture()

    async def _guard():
        try:
            fut.set_result(await coro)
        except GeneratorExit:
            raise  # task abort: let close() unwind; cancel() sets the future
        except Cancelled:
            if not fut.done():
                fut.set_exception(CancelledError())
        except BaseException as exc:  # noqa: BLE001 — contained, like asyncio
            if not fut.done():
                fut.set_exception(exc)

    return Task(_task.spawn(_guard()), fut)


ensure_future = create_task


async def gather(*aws: Awaitable, return_exceptions: bool = False) -> List[Any]:
    tasks = [create_task(aw) if not isinstance(aw, Task) else aw for aw in aws]
    results: List[Any] = []
    first_exc = None
    for t in tasks:
        try:
            results.append(await t)
        except BaseException as exc:  # noqa: BLE001
            if return_exceptions:
                results.append(exc)
            elif first_exc is None:
                first_exc = exc
                results.append(None)
    if first_exc is not None and not return_exceptions:
        raise first_exc
    return results


async def wait_for(aw: Awaitable, timeout: float) -> Any:
    if timeout is None:
        return await aw
    return await _time.timeout(timeout, aw)


async def shield(aw: Awaitable) -> Any:
    # Cancellation granularity in the sim is the task; a shielded await is
    # just the await (supervisor aborts drop whole tasks, not awaits).
    return await aw


def get_event_loop():
    """Minimal loop object for code that calls loop.time()/create_task()."""
    return _Loop()


get_running_loop = get_event_loop


class _Loop:
    def time(self) -> float:
        return _time.monotonic()

    def create_task(self, coro: Coroutine) -> Task:
        return create_task(coro)

    def call_later(self, delay: float, cb: Callable, *args):
        handle = _context.current_handle()
        return handle.time.add_timer(_time.to_ns(delay), lambda: cb(*args))


# ---------------------------------------------------------------------------
# Synchronization (asyncio surface over madsim_tpu.sync)
# ---------------------------------------------------------------------------

class Event(_sync.Event):
    def clear(self) -> None:
        self._set = False


Lock = _sync.Lock
Semaphore = _sync.Semaphore


# The real asyncio exception classes, so unmodified `except asyncio.QueueEmpty`
# handlers keep working under patched().
import asyncio as _stdlib_asyncio  # noqa: E402

QueueEmpty = _stdlib_asyncio.QueueEmpty


class Queue(_sync.Queue):
    def get_nowait(self) -> Any:
        ok, item = self._ch.try_recv()
        if not ok:
            raise QueueEmpty()
        return item


# ---------------------------------------------------------------------------
# Interpreter-level patching (libc-interception analog)
# ---------------------------------------------------------------------------

def _in_sim() -> bool:
    return _context.try_current_handle() is not None


def _sim_rng():
    return _context.current_handle().rand


_PATCHES = None


def install() -> None:
    """Patch asyncio/time/random/os so unmodified code runs in-sim.

    Each wrapper falls through to the real function when called outside a
    simulation context (the dlsym(RTLD_NEXT) passthrough analog,
    `rand.rs:241-253`). Idempotent; undo with :func:`uninstall`.
    """
    global _PATCHES
    if _PATCHES is not None:
        return
    import asyncio as _aio
    import os as _os
    import random as _random
    import time as _walltime

    saved = {}

    def patch(mod, name, fn):
        saved[(mod, name)] = getattr(mod, name)
        setattr(mod, name, fn)

    def passthrough(orig, sim_fn):
        def wrapper(*a, **kw):
            if _in_sim():
                return sim_fn(*a, **kw)
            return orig(*a, **kw)

        wrapper.__name__ = getattr(orig, "__name__", "patched")
        return wrapper

    # -- asyncio ------------------------------------------------------------
    patch(_aio, "sleep", passthrough(_aio.sleep, sleep))
    patch(_aio, "wait_for", passthrough(_aio.wait_for, wait_for))
    patch(_aio, "gather", passthrough(_aio.gather, gather))
    patch(_aio, "shield", passthrough(_aio.shield, shield))
    patch(_aio, "get_event_loop", passthrough(_aio.get_event_loop, get_event_loop))
    patch(_aio, "get_running_loop", passthrough(_aio.get_running_loop, get_running_loop))

    def _sim_create_task(coro, **kw):
        return create_task(coro)

    patch(_aio, "create_task", passthrough(_aio.create_task, _sim_create_task))
    patch(_aio, "ensure_future", passthrough(_aio.ensure_future, _sim_create_task))
    for name, cls in [("Event", Event), ("Lock", Lock),
                      ("Semaphore", Semaphore), ("Queue", Queue)]:
        orig_cls = getattr(_aio, name)
        patch(_aio, name, _class_passthrough(orig_cls, cls))

    # -- time ---------------------------------------------------------------
    patch(_walltime, "time", passthrough(_walltime.time, _time.system_time))
    patch(_walltime, "time_ns", passthrough(_walltime.time_ns, _time.system_time_ns))
    patch(_walltime, "monotonic", passthrough(_walltime.monotonic, _time.monotonic))
    patch(_walltime, "monotonic_ns", passthrough(_walltime.monotonic_ns, _time.monotonic_ns))
    patch(_walltime, "perf_counter", passthrough(_walltime.perf_counter, _time.monotonic))

    def _sim_blocking_sleep(seconds):
        # A blocking sleep inside the single-threaded sim just advances the
        # virtual clock (due timers fire at the next scheduling point).
        _context.current_handle().time.advance(int(seconds * 1e9))

    patch(_walltime, "sleep", passthrough(_walltime.sleep, _sim_blocking_sleep))

    # -- randomness (getrandom/getentropy interception analog) --------------
    patch(_os, "urandom", passthrough(_os.urandom, lambda n: _sim_rng().gen_bytes(n)))
    patch(_random, "random", passthrough(_random.random, lambda: _sim_rng().random()))
    patch(_random, "randint",
          passthrough(_random.randint, lambda a, b: _sim_rng().gen_range(a, b + 1)))
    def _sim_randrange(start, stop=None, step=1):
        if stop is None:
            start, stop = 0, start
        n_steps = (stop - start + step - 1) // step if step > 0 \
            else (stop - start + step + 1) // step
        if n_steps <= 0:
            raise ValueError("empty range for randrange()")
        return start + step * _sim_rng().gen_range(0, n_steps)

    patch(_random, "randrange", passthrough(_random.randrange, _sim_randrange))
    patch(_random, "choice", passthrough(_random.choice, lambda seq: _sim_rng().choice(seq)))
    patch(_random, "shuffle", passthrough(_random.shuffle, lambda seq: _sim_rng().shuffle(seq)))
    patch(_random, "uniform",
          passthrough(_random.uniform, lambda a, b: _sim_rng().gen_range_f64(a, b)))
    patch(_random, "getrandbits",
          passthrough(_random.getrandbits,
                      lambda k: int.from_bytes(_sim_rng().gen_bytes((k + 7) // 8),
                                               "little") >> ((8 - k % 8) % 8)))

    _PATCHES = saved


def _class_passthrough(orig_cls, sim_cls):
    """A callable standing in for a class: constructs the sim variant inside
    a simulation, the original outside."""

    def factory(*a, **kw):
        return sim_cls(*a, **kw) if _in_sim() else orig_cls(*a, **kw)

    factory.__name__ = orig_cls.__name__
    return factory


def uninstall() -> None:
    global _PATCHES
    if _PATCHES is None:
        return
    for (mod, name), orig in _PATCHES.items():
        setattr(mod, name, orig)
    _PATCHES = None


@contextlib.contextmanager
def patched():
    """``with aio.patched():`` — install() for the duration of the block."""
    was_installed = _PATCHES is not None
    install()
    try:
        yield
    finally:
        if not was_installed:
            uninstall()
