"""grpc.aio-shaped RPC over the simulated network — the madsim-tonic analog.

Reference semantics (`madsim-tonic/src/{client,transport/server}.rs`,
`madsim-tonic-build/src/server.rs:104-128`):

- each RPC is one ``connect1`` duplex channel; the first message carries
  ``(path, request)`` where a ``None`` request marks a client-streaming
  start (`client.rs:29-147`);
- the server accept-loop routes on ``"/package.Service/Method"`` to a
  service map, spawns a task per request, and streams back
  ``("ok", message)`` / ``("err", Status)`` frames, ``("end", None)``
  terminating a stream (`transport/server.rs:195-253`);
- messages cross the network as boxed Python objects — zero serialization,
  like tonic-sim's ``BoxMessage`` (`madsim-tonic/src/codec.rs:12-48`);
- all four streaming modes: unary, server-streaming, client-streaming, bidi.

Services are plain classes: set ``SERVICE_NAME`` and decorate handler
methods with :func:`unary` / :func:`server_streaming` /
:func:`client_streaming` / :func:`bidi`. Handlers get ``(request, context)``
where ``context.peer()`` is the caller address (the ``remote_addr``
smuggling of `madsim-tonic/src/sim.rs:36-50`, minus the transmute).
"""
from __future__ import annotations

import enum
import logging
from typing import Any, AsyncIterator, Callable, Dict, Optional, Tuple

from .. import task as _task
from ..core.futures import Cancelled, ChannelClosed
from ..net import Endpoint
from ..net.addr import Addr, AddrLike
from ..net.netsim import BrokenPipe, ConnectionRefused, ConnectionReset

log = logging.getLogger("madsim_tpu.grpc")

UNARY = "unary"
SERVER_STREAMING = "server_streaming"
CLIENT_STREAMING = "client_streaming"
BIDI = "bidi"

_END = ("end", None)


class StatusCode(enum.Enum):
    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    NOT_FOUND = 5
    UNIMPLEMENTED = 12
    INTERNAL = 13
    UNAVAILABLE = 14


class Status(Exception):
    """gRPC error status (tonic::Status analog)."""

    def __init__(self, code: StatusCode, details: str = ""):
        super().__init__(f"{code.name}: {details}")
        self.code = code
        self.details = details


def _method(kind: str):
    def deco(fn: Callable) -> Callable:
        fn._grpc_kind = kind
        return fn

    return deco


unary = _method(UNARY)
server_streaming = _method(SERVER_STREAMING)
client_streaming = _method(CLIENT_STREAMING)
bidi = _method(BIDI)


class ServicerContext:
    """Per-call context handed to handlers.

    Carries the grpcio ServicerContext error surface (set_code/set_details/
    abort) so handlers written for real grpc.aio — including protoc-style
    generated bases — behave identically in-sim."""

    def __init__(self, peer: Addr):
        self._peer = peer
        self._code = None
        self._details = ""

    def peer(self) -> str:
        return f"{self._peer[0]}:{self._peer[1]}"

    def set_code(self, code) -> None:
        self._code = code

    def set_details(self, details: str) -> None:
        self._details = details

    def abort(self, code, details: str = "") -> None:
        self.set_code(code)
        self.set_details(details)
        raise Status(_to_sim_code(code), details)

    def trailing_status(self) -> Optional["Status"]:
        """A non-OK status the handler set without raising, else None."""
        if self._code is None:
            return None
        sim_code = _to_sim_code(self._code)
        if sim_code == StatusCode.OK:
            return None
        return Status(sim_code, self._details)


def _to_sim_code(code) -> StatusCode:
    """Map a grpc.StatusCode (or sim StatusCode) by name; unknown → UNKNOWN."""
    name = getattr(code, "name", str(code))
    try:
        return StatusCode[name]
    except KeyError:
        return StatusCode.UNKNOWN


class Server:
    """Accept-loop server routing boxed messages to registered services."""

    def __init__(self):
        self._routes: Dict[str, Tuple[str, Callable]] = {}
        self._ep: Optional[Endpoint] = None
        self._accept_task = None

    def add_service(self, service: Any) -> "Server":
        name = getattr(service, "SERVICE_NAME", type(service).__name__)
        for attr in dir(service):
            fn = getattr(service, attr)
            kind = getattr(fn, "_grpc_kind", None)
            if kind is not None:
                self._routes[f"/{name}/{attr}"] = (kind, fn)
        return self

    async def serve(self, addr: AddrLike) -> None:
        """Bind and accept until the serving task is aborted / node killed."""
        self._ep = await Endpoint.bind(addr)
        while True:
            try:
                tx, rx, src = await self._ep.accept1()
            except (ConnectionReset, ChannelClosed):
                return
            _task.spawn(self._handle_conn(tx, rx, src))

    def start(self, addr: AddrLike):
        """Spawn serve() as a task; returns its JoinHandle."""
        self._accept_task = _task.spawn(self.serve(addr))
        return self._accept_task

    def close(self) -> None:
        if self._accept_task is not None:
            self._accept_task.abort()
        if self._ep is not None:
            self._ep.close()

    # ------------------------------------------------------------------
    async def _handle_conn(self, tx, rx, src: Addr) -> None:
        try:
            path, first = await rx.recv()
        except (ChannelClosed, BrokenPipe, ConnectionReset):
            return
        route = self._routes.get(path)
        ctx = ServicerContext(src)
        try:
            if route is None:
                raise Status(StatusCode.UNIMPLEMENTED, f"unknown path {path}")
            kind, fn = route
            if kind == UNARY:
                rsp = await fn(first, ctx)
                await tx.send(("ok", rsp))
            elif kind == SERVER_STREAMING:
                async for rsp in fn(first, ctx):
                    await tx.send(("ok", rsp))
                await tx.send(_END)
            elif kind == CLIENT_STREAMING:
                rsp = await fn(_request_stream(rx), ctx)
                await tx.send(("ok", rsp))
            else:  # BIDI
                async for rsp in fn(_request_stream(rx), ctx):
                    await tx.send(("ok", rsp))
                await tx.send(_END)
        except Status as status:
            await _try_send(tx, ("err", status))
        except (ChannelClosed, BrokenPipe, ConnectionReset, Cancelled):
            pass  # peer gone / node dying: nothing to report
        except Exception as exc:  # noqa: BLE001 — surface as INTERNAL
            log.warning("handler %s raised: %r", path, exc)
            await _try_send(tx, ("err", Status(StatusCode.INTERNAL, repr(exc))))
        finally:
            tx.close()


async def _try_send(tx, item) -> None:
    try:
        await tx.send(item)
    except (BrokenPipe, ConnectionReset, ChannelClosed):
        pass


async def _request_stream(rx) -> AsyncIterator[Any]:
    """Adapt the receive channel into the handler's request iterator.

    Requests arrive framed as ("req", message) so an arbitrary user payload
    can never collide with the ("end", None) terminator.
    """
    while True:
        try:
            frame = await rx.recv()
        except (ChannelClosed, BrokenPipe, ConnectionReset):
            return
        if frame == _END:
            return
        yield frame[1]


class Channel:
    """Client-side channel: one endpoint, one connect1 stream per RPC."""

    def __init__(self, ep: Endpoint, target: Addr):
        self._ep = ep
        self._target = target

    @staticmethod
    async def connect(target: AddrLike) -> "Channel":
        from ..net.addr import lookup_host

        ep = await Endpoint.bind("0.0.0.0:0")
        return Channel(ep, (await lookup_host(target))[0])

    def close(self) -> None:
        self._ep.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        self.close()
        return False

    # -- the four call shapes (client.rs:29-147) -----------------------------
    async def unary(self, path: str, request: Any) -> Any:
        tx, rx = await self._open(path, request)
        try:
            return _unwrap(await _recv_frame(rx))
        finally:
            tx.close()

    async def server_streaming(self, path: str, request: Any) -> AsyncIterator[Any]:
        tx, rx = await self._open(path, request)
        try:
            async for rsp in _response_stream(rx):
                yield rsp
        finally:
            tx.close()

    async def client_streaming(self, path: str, requests: AsyncIterator[Any]) -> Any:
        tx, rx = await self._open(path, None)
        await _pump(tx, requests)
        try:
            return _unwrap(await _recv_frame(rx))
        finally:
            tx.close()

    async def bidi(self, path: str, requests: AsyncIterator[Any]) -> AsyncIterator[Any]:
        tx, rx = await self._open(path, None)
        # Requests are pumped concurrently so both directions interleave
        # (the spawned request-sender of `codec.rs:12-48`).
        pump = _task.spawn(_pump(tx, requests))
        try:
            async for rsp in _response_stream(rx):
                yield rsp
        finally:
            pump.abort()
            tx.close()

    # ------------------------------------------------------------------
    async def _open(self, path: str, first: Any):
        try:
            tx, rx = await self._ep.connect1(self._target)
            await tx.send((path, first))
        except (BrokenPipe, ConnectionRefused, ConnectionReset, ChannelClosed) as exc:
            raise Status(StatusCode.UNAVAILABLE, f"connect: {exc}") from exc
        return tx, rx


async def _pump(tx, requests: AsyncIterator[Any]) -> None:
    try:
        async for req in requests:
            await tx.send(("req", req))
        await tx.send(_END)
    except (BrokenPipe, ConnectionReset, ChannelClosed):
        pass


async def _recv_frame(rx):
    try:
        return await rx.recv()
    except (ChannelClosed, BrokenPipe, ConnectionReset) as exc:
        raise Status(StatusCode.UNAVAILABLE, f"recv: {exc}") from exc


def _unwrap(frame) -> Any:
    kind, value = frame
    if kind == "ok":
        return value
    if kind == "err":
        raise value
    raise Status(StatusCode.INTERNAL, f"unexpected frame {kind!r}")


async def _response_stream(rx) -> AsyncIterator[Any]:
    while True:
        try:
            frame = await rx.recv()
        except (ChannelClosed, BrokenPipe, ConnectionReset):
            return  # server side closed after _END or died: end of stream
        if frame == _END:
            return
        yield _unwrap(frame)
