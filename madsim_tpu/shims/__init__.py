"""Drop-in ecosystem shims (SURVEY §2.12-2.15 analogs).

The reference makes real-world code simulable by shadowing its dependencies:
madsim-tokio re-exports tokio in production and maps onto the simulator under
``--cfg madsim`` (`madsim-tokio/src/lib.rs:1-7`); madsim-tonic reimplements
tonic's transport over simulated Endpoints (`madsim-tonic/src/lib.rs`); and
madsim-tokio-postgres proves a real wire-protocol client runs unchanged over
the simulated TCP stack.

The Python analogs:

- :mod:`.aio` — asyncio-shaped API over the simulation, plus interpreter-
  level patching of ``asyncio``/``time``/``random``/``os.urandom`` (the
  analog of the reference's libc interception, scoped per SURVEY §7).
- :mod:`.grpc_sim` — grpc.aio-shaped RPC (server/channel, 4 streaming modes,
  status codes) over Endpoint duplex channels with boxed messages.
- :mod:`.postgres` — a PostgreSQL v3 wire-protocol client (and an in-sim
  test server) over the simulated TcpStream.
"""
from . import aio, grpc_sim, postgres

__all__ = ["aio", "grpc_sim", "postgres"]
