"""Public deterministic-randomness API.

Reference: `madsim/src/sim/rand.rs:135-164` — ``thread_rng()``/``random()``
backed by the single seeded global RNG, so *every* random decision in the
simulated world comes from the seed.
"""
from __future__ import annotations

from .core import context
from .core.backend import is_real
from .core.rng import DeterminismError, GlobalRng  # noqa: F401 (re-export)

__all__ = ["thread_rng", "random", "gen_range", "gen_bool", "shuffle", "choice",
           "randbytes", "GlobalRng", "DeterminismError"]


def thread_rng() -> GlobalRng:
    """The current simulation's global RNG (real backend: OS entropy with
    the same call surface, `std/mod.rs:5` re-export analog)."""
    if is_real():
        from .real import thread_rng as real_thread_rng

        return real_thread_rng()
    return context.current_handle().rand


def random() -> float:
    return thread_rng().random()


def gen_range(low: int, high: int) -> int:
    return thread_rng().gen_range(low, high)


def gen_bool(p: float) -> bool:
    return thread_rng().gen_bool(p)


def shuffle(seq: list) -> None:
    thread_rng().shuffle(seq)


def choice(seq):
    return thread_rng().choice(seq)


def randbytes(n: int) -> bytes:
    """Deterministic replacement for os.urandom within a simulation
    (the analog of the libc getrandom/getentropy overrides,
    `rand.rs:195-261`)."""
    return thread_rng().gen_bytes(n)
