"""Synchronization primitives for simulation tasks.

The reference passes tokio::sync through unchanged (`madsim-tokio/src/lib.rs:
40-52`) because tokio's primitives are runtime-independent. Here the executor
is our own, so these are native implementations whose wakeups all route
through the deterministic scheduler.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, List, Optional

from .core.futures import Channel, ChannelClosed, SimFuture  # noqa: F401 (re-export)

__all__ = ["Event", "Barrier", "Lock", "Semaphore", "Notify", "Queue", "oneshot",
           "Channel", "ChannelClosed", "SimFuture"]


async def _await_waiter(fut: SimFuture, waiters, on_handoff) -> None:
    """Shared interrupt-safe wait protocol for handoff primitives: await a
    registered waiter future; on cancellation, either pass an already-
    delivered handoff onward (``on_handoff(fut)``) or deregister."""
    try:
        await fut
    except BaseException:
        if fut.done() and fut._exception is None:
            on_handoff(fut)
        else:
            try:
                waiters.remove(fut)
            except ValueError:
                pass
        raise


class Event:
    """One-way latch: wait() until set()."""

    def __init__(self):
        self._set = False
        self._waiters: List[SimFuture] = []

    def is_set(self) -> bool:
        return self._set

    def set(self) -> None:
        self._set = True
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.set_result(None)

    async def wait(self) -> None:
        if self._set:
            return
        fut = SimFuture()
        self._waiters.append(fut)
        await fut


class Barrier:
    """N-party barrier (tokio::sync::Barrier semantics, reusable)."""

    def __init__(self, parties: int):
        if parties < 1:
            raise ValueError("barrier needs at least 1 party")
        self._parties = parties
        self._arrived: List[SimFuture] = []

    async def wait(self) -> bool:
        """Returns True for the leader (last arriver) of each generation."""
        if len(self._arrived) + 1 == self._parties:
            arrived, self._arrived = self._arrived, []
            for fut in arrived:
                fut.set_result(None)
            return True
        fut = SimFuture()
        self._arrived.append(fut)
        await fut
        return False


class Lock:
    """Async mutex. Interrupt-safe: a waiter cancelled mid-acquire (task
    abort or an aio.timeout scope) unregisters itself, and if the lock was
    already handed to it, passes it on instead of leaking it."""

    def __init__(self):
        self._locked = False
        self._waiters: Deque[SimFuture] = deque()

    async def acquire(self) -> None:
        if not self._locked:
            self._locked = True
            return
        fut = SimFuture()
        self._waiters.append(fut)
        # On cancellation, a lock already handed to us passes onward.
        await _await_waiter(fut, self._waiters, lambda _f: self.release())

    def release(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)  # hand the lock to the next waiter
                return
        self._locked = False

    async def __aenter__(self):
        await self.acquire()
        return self

    async def __aexit__(self, *exc):
        self.release()
        return False


class Semaphore:
    def __init__(self, permits: int):
        self._permits = permits
        self._waiters: Deque[SimFuture] = deque()

    async def acquire(self) -> None:
        if self._permits > 0:
            self._permits -= 1
            return
        fut = SimFuture()
        self._waiters.append(fut)
        # On cancellation, a permit already handed to us is given back.
        await _await_waiter(fut, self._waiters, lambda _f: self.release())

    def release(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return
        self._permits += 1

    async def __aenter__(self):
        await self.acquire()
        return self

    async def __aexit__(self, *exc):
        self.release()
        return False


class Notify:
    """tokio::sync::Notify: notify_one stores a permit if nobody waits."""

    def __init__(self):
        self._permit = False
        self._waiters: Deque[SimFuture] = deque()

    def notify_one(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                # True marks a targeted (notify_one) wakeup: a cancelled
                # recipient must pass it on. notify_waiters wakeups are
                # broadcast (False) and mint no permit on cancellation.
                fut.set_result(True)
                return
        self._permit = True

    def notify_waiters(self) -> None:
        waiters, self._waiters = self._waiters, deque()
        for fut in waiters:
            if not fut.done():
                fut.set_result(False)

    async def notified(self) -> None:
        if self._permit:
            self._permit = False
            return
        fut = SimFuture()
        self._waiters.append(fut)
        await _await_waiter(
            fut, self._waiters,
            lambda f: self.notify_one() if f._result else None)


class Queue:
    """Unbounded async FIFO queue (asyncio.Queue-flavored surface)."""

    def __init__(self):
        self._ch = Channel()

    def put_nowait(self, item: Any) -> None:
        self._ch.send(item)

    async def put(self, item: Any) -> None:
        self._ch.send(item)

    async def get(self) -> Any:
        return await self._ch.recv()

    def qsize(self) -> int:
        return len(self._ch)

    def empty(self) -> bool:
        return len(self._ch) == 0

    def close(self) -> None:
        self._ch.close()


def oneshot() -> SimFuture:
    """A oneshot channel is just a future: sender calls set_result."""
    return SimFuture()
