"""Synchronization primitives for simulation tasks.

The reference passes tokio::sync through unchanged (`madsim-tokio/src/lib.rs:
40-52`) because tokio's primitives are runtime-independent. Here the executor
is our own, so these are native implementations whose wakeups all route
through the deterministic scheduler.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Deque, List, Optional

from .core.futures import Channel, ChannelClosed, SimFuture  # noqa: F401 (re-export)

__all__ = ["Event", "Barrier", "Lock", "RwLock", "Semaphore", "Notify",
           "Queue", "oneshot", "watch", "broadcast", "Lagged",
           "Channel", "ChannelClosed", "SimFuture"]


async def _await_waiter(fut: SimFuture, waiters, on_handoff) -> None:
    """Shared interrupt-safe wait protocol for handoff primitives: await a
    registered waiter future; on cancellation, either pass an already-
    delivered handoff onward (``on_handoff(fut)``) or deregister."""
    try:
        await fut
    except BaseException:
        if fut.done() and fut._exception is None:
            on_handoff(fut)
        else:
            try:
                waiters.remove(fut)
            except ValueError:
                pass
        raise


class Event:
    """One-way latch: wait() until set()."""

    def __init__(self):
        self._set = False
        self._waiters: List[SimFuture] = []

    def is_set(self) -> bool:
        return self._set

    def set(self) -> None:
        self._set = True
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            w.set_result(None)

    async def wait(self) -> None:
        if self._set:
            return
        fut = SimFuture()
        self._waiters.append(fut)
        await fut


class Barrier:
    """N-party barrier (tokio::sync::Barrier semantics, reusable)."""

    def __init__(self, parties: int):
        if parties < 1:
            raise ValueError("barrier needs at least 1 party")
        self._parties = parties
        self._arrived: List[SimFuture] = []

    async def wait(self) -> bool:
        """Returns True for the leader (last arriver) of each generation."""
        if len(self._arrived) + 1 == self._parties:
            arrived, self._arrived = self._arrived, []
            for fut in arrived:
                fut.set_result(None)
            return True
        fut = SimFuture()
        self._arrived.append(fut)
        await fut
        return False


class Lock:
    """Async mutex. Interrupt-safe: a waiter cancelled mid-acquire (task
    abort or an aio.timeout scope) unregisters itself, and if the lock was
    already handed to it, passes it on instead of leaking it."""

    def __init__(self):
        self._locked = False
        self._waiters: Deque[SimFuture] = deque()

    async def acquire(self) -> None:
        if not self._locked:
            self._locked = True
            return
        fut = SimFuture()
        self._waiters.append(fut)
        # On cancellation, a lock already handed to us passes onward.
        await _await_waiter(fut, self._waiters, lambda _f: self.release())

    def release(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)  # hand the lock to the next waiter
                return
        self._locked = False

    async def __aenter__(self):
        await self.acquire()
        return self

    async def __aexit__(self, *exc):
        self.release()
        return False


class Semaphore:
    def __init__(self, permits: int):
        self._permits = permits
        self._waiters: Deque[SimFuture] = deque()

    async def acquire(self) -> None:
        if self._permits > 0:
            self._permits -= 1
            return
        fut = SimFuture()
        self._waiters.append(fut)
        # On cancellation, a permit already handed to us is given back.
        await _await_waiter(fut, self._waiters, lambda _f: self.release())

    def release(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return
        self._permits += 1

    async def __aenter__(self):
        await self.acquire()
        return self

    async def __aexit__(self, *exc):
        self.release()
        return False


class Notify:
    """tokio::sync::Notify: notify_one stores a permit if nobody waits."""

    def __init__(self):
        self._permit = False
        self._waiters: Deque[SimFuture] = deque()

    def notify_one(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                # True marks a targeted (notify_one) wakeup: a cancelled
                # recipient must pass it on. notify_waiters wakeups are
                # broadcast (False) and mint no permit on cancellation.
                fut.set_result(True)
                return
        self._permit = True

    def notify_waiters(self) -> None:
        waiters, self._waiters = self._waiters, deque()
        for fut in waiters:
            if not fut.done():
                fut.set_result(False)

    async def notified(self) -> None:
        if self._permit:
            self._permit = False
            return
        fut = SimFuture()
        self._waiters.append(fut)
        await _await_waiter(
            fut, self._waiters,
            lambda f: self.notify_one() if f._result else None)


class Queue:
    """Unbounded async FIFO queue (asyncio.Queue-flavored surface)."""

    def __init__(self):
        self._ch = Channel()

    def put_nowait(self, item: Any) -> None:
        self._ch.send(item)

    async def put(self, item: Any) -> None:
        self._ch.send(item)

    async def get(self) -> Any:
        return await self._ch.recv()

    def qsize(self) -> int:
        return len(self._ch)

    def empty(self) -> bool:
        return len(self._ch) == 0

    def close(self) -> None:
        self._ch.close()


def oneshot() -> SimFuture:
    """A oneshot channel is just a future: sender calls set_result."""
    return SimFuture()


class RwLock:
    """Fair async reader-writer lock (tokio::sync::RwLock semantics: FIFO
    fairness — a queued writer blocks later readers, so writers never
    starve). ``async with rw.read(): ...`` / ``async with rw.write(): ...``.
    Interrupt-safe like :class:`Lock`: a cancelled waiter that was already
    handed the lock releases it onward."""

    def __init__(self):
        self._readers = 0
        self._writer = False
        self._waiters: Deque[tuple] = deque()  # ("r"|"w", SimFuture)

    # -- guards ------------------------------------------------------------
    class _Guard:
        __slots__ = ("_rw", "_kind")

        def __init__(self, rw: "RwLock", kind: str):
            self._rw = rw
            self._kind = kind

        async def __aenter__(self):
            await (self._rw.acquire_read() if self._kind == "r"
                   else self._rw.acquire_write())
            return self._rw

        async def __aexit__(self, *exc):
            (self._rw.release_read() if self._kind == "r"
             else self._rw.release_write())
            return False

    def read(self) -> "_Guard":
        return RwLock._Guard(self, "r")

    def write(self) -> "_Guard":
        return RwLock._Guard(self, "w")

    # -- core --------------------------------------------------------------
    async def acquire_read(self) -> None:
        # Fairness: a new reader queues behind ANY waiter (else a stream
        # of readers starves a queued writer forever).
        if not self._writer and not self._waiters:
            self._readers += 1
            return
        fut = SimFuture()
        self._waiters.append(("r", fut))
        await _await_waiter(
            fut, _RwWaiterView(self._waiters), lambda _f: self.release_read())

    async def acquire_write(self) -> None:
        if not self._writer and self._readers == 0 and not self._waiters:
            self._writer = True
            return
        fut = SimFuture()
        self._waiters.append(("w", fut))
        await _await_waiter(
            fut, _RwWaiterView(self._waiters), lambda _f: self.release_write())

    def release_read(self) -> None:
        self._readers -= 1
        if self._readers == 0:
            self._wake()

    def release_write(self) -> None:
        self._writer = False
        self._wake()

    def _wake(self) -> None:
        # Hand off in FIFO order: one writer, or every reader up to the
        # next queued writer. Counters are charged at handoff time so a
        # release racing the wakeup sees a consistent state.
        while self._waiters:
            kind, fut = self._waiters[0]
            if fut.done():
                self._waiters.popleft()
                continue
            if kind == "w":
                if self._readers == 0 and not self._writer:
                    self._waiters.popleft()
                    self._writer = True
                    fut.set_result(None)
                return
            if self._writer:
                return
            self._waiters.popleft()
            self._readers += 1
            fut.set_result(None)


class _RwWaiterView:
    """Adapter so _await_waiter's ``waiters.remove(fut)`` deregisters a
    (kind, fut) entry from the RwLock queue."""

    __slots__ = ("_q",)

    def __init__(self, q):
        self._q = q

    def remove(self, fut) -> None:
        for i, (_kind, f) in enumerate(self._q):
            if f is fut:
                del self._q[i]
                return
        raise ValueError


# ---------------------------------------------------------------------------
# watch channel (tokio::sync::watch): single slot, many observers
# ---------------------------------------------------------------------------

class _WatchShared:
    __slots__ = ("value", "version", "closed", "waiters")

    def __init__(self, value):
        self.value = value
        self.version = 0
        self.closed = False
        self.waiters: List[SimFuture] = []

    def wake_all(self) -> None:
        waiters, self.waiters = self.waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)


class WatchSender:
    def __init__(self, shared: _WatchShared):
        self._shared = shared

    def send(self, value) -> None:
        if self._shared.closed:
            raise ChannelClosed()
        self._shared.value = value
        self._shared.version += 1
        self._shared.wake_all()

    def borrow(self):
        return self._shared.value

    def close(self) -> None:
        self._shared.closed = True
        self._shared.wake_all()

    def subscribe(self) -> "WatchReceiver":
        return WatchReceiver(self._shared)


class WatchReceiver:
    """Observes the latest value; ``changed()`` waits for a version newer
    than the last one this receiver saw (intermediate values may be
    skipped — watch is last-write-wins, like the reference's)."""

    def __init__(self, shared: _WatchShared):
        self._shared = shared
        self._seen = shared.version

    def borrow(self):
        return self._shared.value

    def borrow_and_update(self):
        self._seen = self._shared.version
        return self._shared.value

    async def changed(self) -> None:
        while self._shared.version == self._seen:
            if self._shared.closed:
                raise ChannelClosed()
            fut = SimFuture()
            self._shared.waiters.append(fut)
            try:
                await fut
            except BaseException:
                if fut in self._shared.waiters:
                    self._shared.waiters.remove(fut)
                raise
        self._seen = self._shared.version

    def clone(self) -> "WatchReceiver":
        rx = WatchReceiver(self._shared)
        rx._seen = self._seen
        return rx


def watch(initial) -> tuple:
    """``tx, rx = watch(initial)`` — a single-value channel where every
    receiver sees the latest value and can await changes."""
    shared = _WatchShared(initial)
    return WatchSender(shared), WatchReceiver(shared)


# ---------------------------------------------------------------------------
# broadcast channel (tokio::sync::broadcast): ring buffer, lag detection
# ---------------------------------------------------------------------------

class Lagged(Exception):
    """A slow receiver was overrun; ``skipped`` messages were dropped."""

    def __init__(self, skipped: int):
        super().__init__(f"lagged: {skipped} messages skipped")
        self.skipped = skipped


class _BroadcastShared:
    __slots__ = ("buf", "head", "capacity", "closed", "waiters")

    def __init__(self, capacity: int):
        self.buf: Deque[Any] = deque()
        self.head = 0  # sequence number of the NEXT message to be sent
        self.capacity = capacity
        self.closed = False
        self.waiters: List[SimFuture] = []


class BroadcastSender:
    def __init__(self, shared: _BroadcastShared):
        self._shared = shared

    def send(self, value) -> None:
        sh = self._shared
        if sh.closed:
            raise ChannelClosed()
        sh.buf.append(value)
        if len(sh.buf) > sh.capacity:
            sh.buf.popleft()  # overrun the slowest receivers
        sh.head += 1
        waiters, sh.waiters = sh.waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    def close(self) -> None:
        self._shared.closed = True
        waiters, self._shared.waiters = self._shared.waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    def subscribe(self) -> "BroadcastReceiver":
        # A new receiver sees only messages sent after it subscribes.
        return BroadcastReceiver(self._shared, self._shared.head)


class BroadcastReceiver:
    def __init__(self, shared: _BroadcastShared, next_seq: int):
        self._shared = shared
        self._next = next_seq

    async def recv(self):
        sh = self._shared
        while True:
            oldest = sh.head - len(sh.buf)
            if self._next < oldest:
                skipped = oldest - self._next
                self._next = oldest
                raise Lagged(skipped)
            if self._next < sh.head:
                value = sh.buf[self._next - oldest]
                self._next += 1
                return value
            if sh.closed:
                raise ChannelClosed()
            fut = SimFuture()
            sh.waiters.append(fut)
            try:
                await fut
            except BaseException:
                if fut in sh.waiters:
                    sh.waiters.remove(fut)
                raise


def broadcast(capacity: int) -> BroadcastSender:
    """``tx = broadcast(16); rx = tx.subscribe()`` — multi-consumer fanout
    with bounded history; slow receivers observe :class:`Lagged`."""
    if capacity < 1:
        raise ValueError("broadcast capacity must be >= 1")
    return BroadcastSender(_BroadcastShared(capacity))
