"""Host↔device bridge: TPU acceleration for arbitrary host workloads.

``sweep(world_fn, seeds)`` runs any coroutine written against the
madsim_tpu host API across many seeds, with the decision kernel
(next-event selection, virtual clock, timer wheel, per-message
loss/latency sampling) batched on the device and task bodies on the
host — SURVEY §7 stage 4. Per seed, trajectories are bit-identical to
``Runtime.block_on`` (tests/test_bridge.py).
"""
from .kernel import BridgeKernel, HostBatch, StepOut  # noqa: F401
from .pool import BridgePoolError, sweep_pooled  # noqa: F401
from .runtime import (  # noqa: F401
    BridgeNetSim,
    BridgeRuntime,
    BridgeTime,
    Outcome,
    SliceDriver,
    sweep,
    sweep_traced,
)

__all__ = ["sweep", "sweep_traced", "sweep_pooled", "Outcome",
           "BridgeRuntime", "BridgeKernel", "BridgeNetSim", "BridgeTime",
           "BridgePoolError", "HostBatch", "StepOut", "SliceDriver"]
