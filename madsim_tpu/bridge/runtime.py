"""Host-side half of the host↔device bridge: lockstep sweep of W worlds.

``sweep(world_fn, seeds)`` runs one *unmodified* host-engine workload —
any coroutine written against the madsim_tpu API (Endpoint/RPC, gRPC
shims, sleep/timeout, kill/restart/clog chaos) — for many seeds at once:

- each seed gets a full host world (executor, nodes, coroutines, NetSim
  mailboxes) exactly like ``Runtime``; task bodies always run on host —
  the one thing that cannot be vectorized (SURVEY §7 "hard parts");
- the *decision kernel* — timer wheel, next-event selection, virtual
  clock advance, per-message loss/latency sampling — lives on the device
  as [W]-shaped arrays, advanced by one jitted XLA step per lockstep
  round (`bridge/kernel.py`).

Determinism contract: per seed, a bridge world walks the **bit-identical
trajectory** of a plain ``Runtime`` world (same poll sequence, same
virtual timestamps, same RNG streams) — the property tested in
tests/test_bridge.py. It holds because every framework draw is addressed
as (seed, purpose-stream, counter) (`core/rng.py`) and the device samples
the same counters with the same integer math.

Reference parity: this is the batched analog of the multi-seed test
driver (`madsim/src/sim/runtime/builder.rs:118-136`) — the reference
fans seeds out to OS threads; here the per-seed decision work fans into
one device batch while hosts bodies run under the GIL, and seed batches
shard across chips via the parallel/ meshes.
"""
from __future__ import annotations

import copy
import inspect
import os
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..core import context
from ..core.config import Config
from ..core.rng import GlobalRng, loss_threshold
from ..core.runtime import Runtime
from ..core.task import Deadlock, TimeLimitExceeded
from ..core.timewheel import NANOS_PER_SEC, TIMER_MAX_NS, TimeRuntime, to_ns
from ..net.addr import ip_is_loopback, unspecified_for
from ..net.netsim import NetSim
from ..net.network import LOCALHOST_V4
from .kernel import BridgeKernel, HostBatch, StepOut, bucket


class _TimerHandle:
    """Cancellation handle for a bridge timer (TimerEntry.cancel parity)."""

    __slots__ = ("_time", "seq")

    def __init__(self, time: "BridgeTime", seq: int):
        self._time = time
        self.seq = seq

    def cancel(self) -> None:
        self._time.cancel_seq(self.seq)


class _Send(NamedTuple):
    ctr: int       # NET-stream counter of the loss draw (latency = ctr+1)
    base_ns: int   # elapsed at the send
    slot: int      # delivery lane slot (-1 for count-only sends)
    seq: int
    thr: int       # loss threshold (u64, clamped)
    lossall: bool  # loss rate >= 1.0
    lat_lo: int
    lat_w: int
    live: bool     # has a destination socket


class BridgeTime(TimeRuntime):
    """TimeRuntime whose wheel lives on the device: ``add_timer_at`` and
    ``cancel`` record lane operations; the sweep driver ships them each
    lockstep round and dispatches the popped events. The clock is a local
    mirror (host advances it during polls; the driver overwrites it with
    the device's post-advance value)."""

    def __init__(self, rng: GlobalRng, cap: int):
        super().__init__(rng)
        self._native_heap = None  # the wheel is device-resident
        self.cap = cap
        self._free = list(range(cap - 1, -1, -1))
        # slot -> (deadline, seq) recorded but not yet shipped this round.
        self.pending_add: Dict[int, Tuple[int, int]] = {}
        self.cancels: List[int] = []          # device-resident cancels
        self.sends: List[_Send] = []
        self.send_cbs: List[Optional[Callable]] = []
        self.callbacks: Dict[int, Tuple[Callable, int]] = {}

    # -- the TimeRuntime surface ------------------------------------------
    def add_timer_at(self, deadline_ns: int, callback: Callable[[], None]):
        # Same clamp as the host wheel (timewheel.py): TIMER_MAX_NS is one
        # below the device kernel's empty-lane sentinel, so an over-range
        # timer stays visible to has_timer instead of reading as "no timer"
        # (which would report a spurious Deadlock the host never sees).
        deadline_ns = min(max(deadline_ns, self.elapsed_ns), TIMER_MAX_NS)
        seq = self._seq
        self._seq += 1
        slot = self._alloc()
        self.pending_add[slot] = (deadline_ns, seq)
        self.callbacks[seq] = (callback, slot)
        return _TimerHandle(self, seq)

    def cancel_seq(self, seq: int) -> None:
        ent = self.callbacks.pop(seq, None)
        if ent is None:
            return  # already fired or cancelled
        _cb, slot = ent
        pend = self.pending_add.get(slot)
        if pend is not None and pend[1] == seq:
            del self.pending_add[slot]  # never reached the device
        else:
            self.cancels.append(slot)
        self._free.append(slot)

    def next_deadline_ns(self):  # pragma: no cover — driver-owned
        raise NotImplementedError("bridge worlds are driven by sweep()")

    def advance_to_next_event(self):  # pragma: no cover — driver-owned
        raise NotImplementedError("bridge worlds are driven by sweep()")

    # -- bridge bookkeeping ------------------------------------------------
    def _alloc(self) -> int:
        try:
            return self._free.pop()
        except IndexError:
            raise RuntimeError(
                f"bridge timer capacity exceeded ({self.cap} concurrent "
                "timers in one world); raise sweep(cap=...)") from None

    def record_send(self, ctr: int, thr: int, lossall: bool, lat_lo: int,
                    lat_w: int, cb: Optional[Callable]) -> None:
        live = cb is not None
        if live:
            slot = self._alloc()
            seq = self._seq
            self._seq += 1
            self.callbacks[seq] = (cb, slot)
        else:
            slot, seq = -1, 0
        self.sends.append(_Send(ctr, self.elapsed_ns, slot, seq,
                                min(thr, (1 << 64) - 1), lossall,
                                lat_lo, lat_w, live))
        self.send_cbs.append(cb)

    def fire(self, seq: int) -> None:
        ent = self.callbacks.pop(seq, None)
        if ent is None:
            return
        cb, slot = ent
        self._free.append(slot)
        cb()

    def drop_send(self, send: _Send) -> None:
        """A live send the device declared lost: release its lane slot."""
        ent = self.callbacks.pop(send.seq, None)
        if ent is not None:
            self._free.append(ent[1])

    def take_round(self):
        adds = self.pending_add
        cancels = self.cancels
        sends = self.sends
        self.pending_add = {}
        self.cancels = []
        self.sends = []
        self.send_cbs = []
        return adds, cancels, sends


class BridgeNetSim(NetSim):
    """NetSim whose datagram sampling runs on the device.

    The send-side processing delay and the connection-oriented paths
    (connect1 relays, whose latency value is needed inline for their
    sleep) keep drawing host-side from the same NET cursor — counters
    stay aligned with pure-host mode either way, because both modes
    consume exactly the same blocks in the same order."""

    async def send(self, node_id, port, dst, protocol, msg) -> None:
        await self.rand_delay()
        net = self.network
        dst_node = net.resolve_dest_node(node_id, dst, protocol)
        if dst_node is None:
            return
        ctr = self.rand.reserve(2)  # loss @ctr, latency @ctr+1 — on device
        if net.link_clogged(node_id, dst_node):
            return  # draws consumed, like the host test_link
        sockets = net.nodes[dst_node].sockets
        socket = sockets.get((dst, protocol))
        if socket is None:
            socket = sockets.get(((unspecified_for(dst[0]), dst[1]), protocol))
        cfg = net.config
        lo_ns = to_ns(cfg.send_latency[0])
        width = max(to_ns(cfg.send_latency[1]), lo_ns + 1) - lo_ns
        p = cfg.packet_loss_rate
        if socket is None:
            cb = None  # loss draw still decides stat.msg_count
        else:
            src_ip = (LOCALHOST_V4 if ip_is_loopback(dst[0])
                      else net.nodes[node_id].ip)
            src = (src_ip, port)

            def cb(socket=socket, src=src, dst=dst, msg=msg):
                socket.deliver(src, dst, msg)

        self.time.record_send(ctr, loss_threshold(p), p >= 1.0,
                              lo_ns, width, cb)


class BridgeRuntime(Runtime):
    """Runtime wired for the bridge: device-backed time + NetSim."""

    def __init__(self, seed: int = 0, config: Optional[Config] = None,
                 cap: int = 128):
        self._cap = cap
        super().__init__(seed=seed, config=config)

    def _make_time(self) -> BridgeTime:
        return BridgeTime(self.rand, self._cap)

    def _default_simulators(self) -> tuple:
        from ..fs import FsSim

        return (BridgeNetSim, FsSim)

    def block_on(self, coro):  # pragma: no cover
        raise NotImplementedError("bridge worlds are driven by sweep()")


class Outcome(NamedTuple):
    """Per-seed sweep outcome: exactly what ``Runtime.block_on`` would
    have returned (value) or raised (error)."""

    seed: int
    value: Any
    error: Optional[BaseException]


class _World:
    __slots__ = ("idx", "slot", "rt", "root", "done", "stat")

    def __init__(self, idx: int, slot: int, rt: BridgeRuntime, root):
        self.idx = idx          # position in the seed list (outcome row)
        self.slot = slot        # kernel batch row currently hosting it
        self.rt = rt
        self.root = root
        self.done = False
        self.stat = rt.handle.sims.get(NetSim).network.stat


def sweep(world_fn: Callable, seeds, *, config: Optional[Config] = None,
          configs: Optional[List[Config]] = None, cap: int = 128,
          k_events: int = 4, time_limit: Optional[float] = None,
          trace: bool = False, device: Optional[str] = None,
          jobs: int = 1, batch: Optional[int] = None) -> List[Outcome]:
    """Sweep an unmodified host workload over many seeds with the device
    decision kernel (`builder.rs:118-136`, batched).

    ``world_fn`` is called once per seed (with the seed if it accepts an
    argument) and must return the root coroutine. ``configs`` gives each
    world its own Config — the (seeds × configs) sweep axis. With
    ``trace=True`` each world records (task_id, elapsed_ns) per poll for
    trajectory-equality checks.

    ``jobs`` runs the Python task bodies of the W live worlds across a
    pool of forked worker processes behind ONE shared decision kernel
    (`bridge/pool.py`, the MADSIM_TEST_JOBS analog of
    `builder.rs:55-107`; the reference forks OS threads, which a GIL
    rules out for Python task bodies). Each worker owns a contiguous
    slot slice of the batch and packs it directly into shared memory, so
    the parent's per-round work is O(1) in W. Per-seed trajectories stay
    bit-identical to ``jobs=1`` for every J (tests/test_bridge_pool.py).
    Task bodies are CPU-bound Python, so jobs only helps up to the
    machine's core count; jobs=0 picks ``os.cpu_count()``.

    ``batch`` bounds how many worlds are live at once (world recycling,
    the host-side analog of ``parallel.sweep(recycle=True)``): seeds
    stream through ``batch`` kernel slots, each finished world's slot
    re-keyed (`BridgeKernel.reset_slot`) for the next seed. Memory and
    per-round pack width stay O(batch) however long the seed list, and
    every seed's trajectory stays bit-identical to an unbatched run
    (tests/test_bridge.py). The bound is the whole pool's: with
    ``jobs>1`` the ``batch`` kernel slots are SHARED, sliced across the
    workers, so the process tree's total stays O(batch)."""
    if jobs == 0:
        # Host driver sizing its own fork pool — no simulation is live here.
        jobs = os.cpu_count() or 1  # detlint: allow[DET004]
    seeds = list(seeds)
    if jobs > 1 and len(seeds) > 1:
        from .pool import sweep_pooled

        outcomes, _ = sweep_pooled(world_fn, seeds, jobs=jobs, config=config,
                                   configs=configs, cap=cap,
                                   k_events=k_events, time_limit=time_limit,
                                   trace=trace, device=device, batch=batch)
        return outcomes
    outcomes, _ = _sweep_impl(world_fn, seeds, config=config,
                              configs=configs, cap=cap, k_events=k_events,
                              time_limit=time_limit, trace=trace,
                              device=device, batch=batch)
    return outcomes


def sweep_traced(world_fn, seeds, *, jobs: int = 1,
                 **kw) -> Tuple[List[Outcome], List[list]]:
    """sweep() + per-seed poll traces (testing hook)."""
    seeds = list(seeds)
    if jobs > 1 and len(seeds) > 1:
        from .pool import sweep_pooled

        return sweep_pooled(world_fn, seeds, jobs=jobs, trace=True, **kw)
    return _sweep_impl(world_fn, seeds, trace=True, **kw)


def sweep_profiled(world_fn, seeds, **kw) -> Tuple[List[Outcome], dict]:
    """sweep() + a per-phase wall-time breakdown of the lockstep loop.

    The profile dict (all times in seconds) answers "where does a round
    go": ``host_s`` (Python task bodies + root settling), ``pack_s``
    (building the padded numpy batch), ``dispatch_s`` (the jitted kernel
    step, including device sync), ``settle_s`` (send accounting, event
    dispatch, drain rounds). ``rounds``/``drain_rounds`` count kernel
    dispatches; ``events``/``sends``/``timers`` are totals across worlds.
    This is the measured artifact behind docs/bridge.md.

    Profiled sweeps additionally run the kernel with its device-resident
    observability block (``BridgeMetrics``) and report the fleet
    aggregate under ``sim_metrics`` — trajectories stay bit-identical to
    an unprofiled sweep (tests/test_obs.py).
    """
    profile: dict = {}
    outs, _ = _sweep_impl(world_fn, seeds, profile=profile, **kw)
    return outs, profile


class PackBufferCache:
    """Process-global LRU of preallocated round pack buffers.

    Round buffers are preallocated per (W, T, C, S) bucket and reused:
    fresh np.zeros for 18 arrays per round was a measured ~6% of sweep
    wall time at W=512. The cache is BOUNDED: a long recycled sweep (or
    a process re-sweeping many widths) walks many bucket shapes, and an
    unbounded dict pins every (W, T, C, S) combination it ever saw —
    least-recently-used shapes are dropped instead
    (tests/test_bridge_pool.py gates the bound).

    Buffers come back UNCLEARED: clearing is the packer's job
    (:meth:`SliceDriver.pack_into` masks-only-clears exactly the rows it
    owns), which is what lets pool workers share one (W, ...) batch
    region without any whole-array owner. Mutating a buffer after the
    kernel ``step()`` returns is safe: StepOut is materialized to numpy
    before step returns, so the device is done with the inputs.
    """

    def __init__(self, maxsize: int = 8):
        from collections import OrderedDict

        self.maxsize = maxsize
        self._bufs: "Dict[Tuple[int, int, int, int], list]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._bufs)

    def get(self, W: int, T: int, C: int, S: int) -> list:
        key = (W, T, C, S)
        buf = self._bufs.get(key)
        if buf is None:
            buf = [np.zeros((W, T), np.int32), np.zeros((W, T), np.int64),
                   np.zeros((W, T), np.int64), np.zeros((W, T), np.bool_),
                   np.zeros((W, C), np.int32), np.zeros((W, C), np.bool_),
                   np.zeros((W, S), np.uint64), np.zeros((W, S), np.int64),
                   np.zeros((W, S), np.int32), np.zeros((W, S), np.int64),
                   np.zeros((W, S), np.uint64), np.zeros((W, S), np.bool_),
                   np.zeros((W, S), np.int64), np.ones((W, S), np.int64),
                   np.zeros((W, S), np.bool_), np.zeros((W, S), np.bool_),
                   np.zeros((W,), np.int64), np.zeros((W,), np.bool_)]
            self._bufs[key] = buf
            while len(self._bufs) > self.maxsize:
                self._bufs.popitem(last=False)
        else:
            self._bufs.move_to_end(key)
        return buf


_PACK_BUFFERS = PackBufferCache()


class SliceDriver:
    """Host-side driving of a contiguous slice of bridge kernel slots.

    This is the slot-sliced seam the lockstep sweep is built from: the
    serial loop (`_sweep_impl`) drives ONE slice covering all W slots
    directly against the kernel; the forked worker pool
    (`bridge/pool.py`) gives each worker its own slice — worlds,
    ``Runtime`` object graphs, and seed sub-stream live only in that
    worker — and moves the kernel interactions to the parent. Every
    per-world decision here depends only on that world's own rows, which
    is what makes the per-seed trajectory independent of how slots are
    sliced (the ``jobs=J == jobs=1 == serial`` bitwise contract,
    tests/test_bridge_pool.py).

    ``slot_lo`` is the slice's first GLOBAL kernel row; all batch/StepOut
    indexing below is global (``slot_lo + local``). ``seeds`` is the
    slice's own seed stream, recycled through its ``n_slots`` slots.
    """

    def __init__(self, world_fn, seeds, *, slot_lo: int = 0,
                 n_slots: Optional[int] = None, config=None, configs=None,
                 cap: int = 128, time_limit=None, trace: bool = False,
                 profile: Optional[dict] = None):
        self.world_fn = world_fn
        self.seeds = [int(s) for s in seeds]
        n = len(self.seeds)
        self.slot_lo = slot_lo
        self.W = n if n_slots is None else n_slots
        self.wants_seed = len(inspect.signature(world_fn).parameters) >= 1
        self.config = config
        self.configs = configs
        self.cap = cap
        self.time_limit = time_limit
        self.trace = trace
        self.profile = profile
        self.outcomes: List[Optional[Outcome]] = [None] * n
        self.traces: List[list] = [[] for _ in range(n)]
        self.slots: List[Optional[_World]] = [None] * self.W
        self.free: List[int] = list(range(self.W - 1, -1, -1))  # slot 0 first
        self.pending: set = set()       # local slots holding a live world
        self.next_pos = 0               # next seed position to admit
        self.polls_done = 0             # poll_count of retired worlds
        self._rounds: Optional[list] = None
        self._woke: List[_World] = []

    # -- admission / retirement --------------------------------------------
    @property
    def live(self) -> int:
        return len(self.pending)

    @property
    def left(self) -> int:
        return len(self.seeds) - self.next_pos

    def live_slots(self) -> List[int]:
        """GLOBAL row indices of the slots holding a live world."""
        return [self.slot_lo + s for s in sorted(self.pending)]

    def finish(self, w: _World, value=None, error=None) -> None:
        self.outcomes[w.idx] = Outcome(self.seeds[w.idx], value, error)
        w.done = True
        self.pending.discard(w.slot)
        self.free.append(w.slot)
        self.polls_done += w.rt.task.poll_count

    def run_host(self, w: _World) -> None:
        """One host burst: run all ready tasks, then settle the root."""
        ex = w.rt.task
        with context.enter_handle(w.rt.handle):
            ex.run_all_ready()
        if ex._uncaught is not None:
            exc, ex._uncaught = ex._uncaught, None
            self.finish(w, error=exc)
        elif w.root.done:
            fut = w.root.join_future
            if fut._exception is not None:
                self.finish(w, error=fut._exception)
            else:
                self.finish(w, value=fut.result())

    def spawn(self, slot: int, pos: int) -> _World:
        if self.configs is not None:
            cfg = copy.deepcopy(self.configs[pos])
        else:
            cfg = (copy.deepcopy(self.config)
                   if self.config is not None else None)
        rt = BridgeRuntime(seed=self.seeds[pos], config=cfg, cap=self.cap)
        if self.time_limit is not None:
            rt.set_time_limit(self.time_limit)
        if self.trace:
            rt.task.trace = self.traces[pos]
        with context.enter_handle(rt.handle):
            coro = (self.world_fn(self.seeds[pos]) if self.wants_seed
                    else self.world_fn())
            root = rt.task.start_root(coro)
        w = _World(pos, slot, rt, root)
        self.slots[slot] = w
        self.pending.add(slot)
        return w

    def top_up(self) -> List[Tuple[int, int]]:
        """Admit seeds into free slots (runs between rounds only — a slot
        reset mid-round would let stale kernel rows fire into the fresh
        world's seq space). Returns the (GLOBAL slot, seed) pairs whose
        kernel rows must be re-keyed (`BridgeKernel.reset_slot`/
        `reset_slots`) before the next step — the caller owns the kernel
        (directly in the serial loop; via the pool parent otherwise)."""
        blocked: List[int] = []
        resets: List[Tuple[int, int]] = []
        while self.free and self.next_pos < len(self.seeds):
            slot = self.free.pop()
            old = self.slots[slot]
            if old is not None:
                t = old.rt.time
                if t.pending_add or t.sends or t.cancels:
                    # The retiring world's final host burst recorded
                    # activity that has not been shipped yet (its stats
                    # ride the next round's batch): recycle this slot one
                    # round later.
                    blocked.append(slot)
                    continue
                resets.append((self.slot_lo + slot,
                               self.seeds[self.next_pos]))
            w = self.spawn(slot, self.next_pos)
            self.next_pos += 1
            self.run_host(w)
        self.free.extend(blocked)
        return resets

    # -- the pack seam ------------------------------------------------------
    def take_rounds(self) -> Tuple[int, int, int]:
        """Collect each slot's recorded round activity; returns the raw
        (max timers, max cancels, max sends) widths of this slice — the
        caller buckets the GLOBAL max so every packer agrees on shape."""
        rounds = []
        t_n = c_n = s_n = 0
        for w in self.slots:
            adds, cancels, sends = w.rt.time.take_round()
            rounds.append((adds, cancels, sends))
            t_n = max(t_n, len(adds))
            c_n = max(c_n, len(cancels))
            s_n = max(s_n, len(sends))
        self._rounds = rounds
        if self.profile is not None:
            self.profile["timers"] += sum(len(r[0]) for r in rounds)
            self.profile["sends"] += sum(len(r[2]) for r in rounds)
        return t_n, c_n, s_n

    def pack_into(self, bufs: list) -> None:
        """Write this slice's rows of the padded (W, ...) round batch.

        Masks-only clears, restricted to the slice's own rows: every
        value lane sits behind a mask the kernel applies (stale values
        are jnp.where'd to the dump column), and the slices of a sweep
        partition the W rows, so the batch is fully initialized with no
        per-world work outside the owning slice/worker."""
        (t_slot, t_dl, t_seq, t_mask, c_slot, c_mask,
         s_ctr, s_base, s_slot, s_seq, s_thr, s_lossall,
         s_lat_lo, s_lat_w, s_mask, s_live, clock, advance) = bufs
        lo, hi = self.slot_lo, self.slot_lo + self.W
        t_mask[lo:hi] = False
        c_mask[lo:hi] = False
        s_lat_w[lo:hi] = 1   # divisor: must stay >= 1
        s_mask[lo:hi] = False
        s_live[lo:hi] = False
        for w, (adds, cancels, sends) in zip(self.slots, self._rounds):
            i = lo + w.slot
            clock[i] = w.rt.time.elapsed_ns
            advance[i] = not w.done
            for j, (slot, (dl, sq)) in enumerate(adds.items()):
                t_slot[i, j] = slot
                t_dl[i, j] = dl
                t_seq[i, j] = sq
                t_mask[i, j] = True
            for j, slot in enumerate(cancels):
                c_slot[i, j] = slot
                c_mask[i, j] = True
            for j, s in enumerate(sends):
                s_ctr[i, j] = s.ctr
                s_base[i, j] = s.base_ns
                s_slot[i, j] = max(s.slot, 0)
                s_seq[i, j] = s.seq
                s_thr[i, j] = s.thr
                s_lossall[i, j] = s.lossall
                s_lat_lo[i, j] = s.lat_lo
                s_lat_w[i, j] = s.lat_w
                s_mask[i, j] = True
                s_live[i, j] = s.live

    # -- the settle seam ----------------------------------------------------
    def settle(self, out) -> List[int]:
        """Settle sends, dispatch popped events, detect stops for this
        slice's rows of a StepOut-shaped result (numpy arrays — the
        kernel's own StepOut or the pool's shared-memory views). Returns
        the GLOBAL rows whose worlds finished during the settle."""
        newly_done: List[int] = []
        self._woke = []
        lo = self.slot_lo
        for w, (adds, cancels, sends) in zip(self.slots, self._rounds):
            i = lo + w.slot
            for j, s in enumerate(sends):
                if out.send_ok[i, j]:
                    w.stat.msg_count += 1
                elif s.live:
                    w.rt.time.drop_send(s)
            if w.done:
                continue
            w.rt.time.elapsed_ns = int(out.clock[i])
            if out.deadlock[i]:
                self.finish(w, error=Deadlock(
                    f"deadlock detected at t={w.rt.time.elapsed_ns / 1e9:.9f}s: "
                    "all tasks are blocked and no timers are pending"))
                newly_done.append(i)
                continue
            lim = w.rt.task.time_limit_ns
            if lim is not None and w.rt.time.elapsed_ns >= lim:
                self.finish(w, error=TimeLimitExceeded(
                    f"time limit ({lim / NANOS_PER_SEC}s) exceeded"))
                newly_done.append(i)
                continue
            fired = 0
            with context.enter_handle(w.rt.handle):
                for k in range(out.event_valid.shape[1]):
                    if not out.event_valid[i, k]:
                        break
                    w.rt.time.fire(int(out.event_seq[i, k]))
                    fired += 1
            if self.profile is not None:
                self.profile["events"] += fired
            if fired or out.more_due[i]:
                self._woke.append(w)
        return newly_done

    def any_pending_more(self, more: np.ndarray) -> bool:
        """Serial-loop drain predicate: any live world of this slice with
        >K events still due (``more`` is globally indexed)."""
        return bool(self.pending
                    and np.any(more[[self.slot_lo + s
                                     for s in self.pending]]))

    def drain_assert(self, more: np.ndarray) -> None:
        # Drain rounds carry no host batch: anything a fire() callback
        # recorded would silently miss its own due cluster and fire in
        # the wrong order vs the host heap. No framework callback does
        # that today — enforce it rather than assume it.
        for w in self.slots:
            if w.done or not more[self.slot_lo + w.slot]:
                continue
            t = w.rt.time
            assert not (t.pending_add or t.sends or t.cancels), (
                "bridge drain invariant violated: a fire() callback "
                "recorded timers/sends during event dispatch")

    def fire_drain(self, ev_valid: np.ndarray, ev_seq: np.ndarray,
                   more: np.ndarray) -> None:
        """Fire one drain round's popped events for the slice's rows
        flagged in ``more`` (the PREVIOUS round's more_due — which worlds
        this drain was dispatched for)."""
        for w in self.slots:
            i = self.slot_lo + w.slot
            if w.done or not more[i]:
                continue
            with context.enter_handle(w.rt.handle):
                for k in range(ev_valid.shape[1]):
                    if not ev_valid[i, k]:
                        break
                    w.rt.time.fire(int(ev_seq[i, k]))
                    if self.profile is not None:
                        self.profile["events"] += 1

    def run_woke(self) -> None:
        """Run the host bursts of the worlds the settled round woke."""
        for w in self._woke:
            if not w.done:
                self.run_host(w)
        self._woke = []

    def poll_total(self) -> int:
        return self.polls_done + sum(
            w.rt.task.poll_count for w in self.slots
            if w is not None and not w.done)


def _sweep_impl(world_fn, seeds, *, config=None, configs=None, cap=128,
                k_events=4, time_limit=None, trace=False, device=None,
                profile=None, batch=None):
    seeds = [int(s) for s in seeds]
    n = len(seeds)
    # World recycling: W kernel slots, n seeds streamed through them. A
    # finished world's slot is re-keyed for the next seed, so batch width
    # (and host memory) stays O(W) for arbitrarily long seed lists.
    W = n if batch is None else max(1, min(int(batch), n))
    drv = SliceDriver(world_fn, seeds, n_slots=W, config=config,
                      configs=configs, cap=cap, time_limit=time_limit,
                      trace=trace, profile=profile)

    # Profiled sweeps also carry the device-resident observability block
    # (BridgeMetrics): counters accumulate inside the jitted step and are
    # pulled ONCE at the end — bit-invisible to trajectories either way.
    kernel = BridgeKernel(seeds[:W], cap=cap, k_events=k_events,
                          device=device, metrics=profile is not None)

    if profile is not None:
        from time import perf_counter

        profile.update(rounds=0, drain_rounds=0, host_s=0.0, pack_s=0.0,
                       dispatch_s=0.0, settle_s=0.0, events=0, sends=0,
                       timers=0, polls=0)

        def _clk():
            # Wall-clock profiling of the sweep driver itself (host side).
            return perf_counter()  # detlint: allow[DET001]
    else:
        def _clk():
            return 0.0

    t0 = _clk()
    for slot, seed in drv.top_up():  # no resets on the initial fill
        kernel.reset_slot(slot, seed)
    if profile is not None:
        profile["host_s"] += _clk() - t0

    while drv.live or drv.left:
        # -- build the padded round batch ---------------------------------
        t0 = _clk()
        t_n, c_n, s_n = drv.take_rounds()
        T, C, S = bucket(t_n), bucket(c_n), bucket(s_n)
        bufs = _PACK_BUFFERS.get(W, T, C, S)
        drv.pack_into(bufs)
        if profile is not None:
            profile["pack_s"] += _clk() - t0
            profile["rounds"] += 1
        t0 = _clk()
        out = kernel.step(HostBatch(*bufs))
        if profile is not None:
            profile["dispatch_s"] += _clk() - t0

        # -- settle sends, dispatch events, detect stops ------------------
        t0 = _clk()
        drv.settle(out)

        # -- drain rounds: >K events due fire before any poll runs --------
        # Pop-only kernel + dispatch-ahead (docs/perf.md "Pipelined
        # orchestration"): a drain round's only input is the
        # device-resident kernel state, so round r+1 enters the device
        # queue BEFORE round r's popped events are unpacked and fired on
        # the host. The one speculative round at chain end finds nothing
        # due and pops nothing — a semantic no-op on the lanes.
        more = out.more_due
        inflight_drain = (kernel.drain() if drv.any_pending_more(more)
                          else None)
        while inflight_drain is not None:
            drv.drain_assert(more)
            if profile is not None:
                profile["drain_rounds"] += 1
            cur = inflight_drain
            # Dispatch-ahead: queue the next round before materializing
            # this one's events (the device pops while the host fires).
            inflight_drain = kernel.drain()
            drv.fire_drain(np.asarray(cur.event_valid),
                           np.asarray(cur.event_seq), more)
            more = np.asarray(cur.more_due)
            if not drv.any_pending_more(more):
                break  # the in-flight round is the no-op tail

        if profile is not None:
            profile["settle_s"] += _clk() - t0
        t0 = _clk()
        drv.run_woke()
        # Recycle freed slots for the next seeds in the stream.
        for slot, seed in drv.top_up():
            kernel.reset_slot(slot, seed)
        if profile is not None:
            profile["host_s"] += _clk() - t0
            profile["polls"] = drv.poll_total()

    if profile is not None:
        mb = kernel.metrics()
        if mb is not None:
            # Fleet aggregate of the kernel's per-slot counters
            # (docs/observability.md; bench.py records it under
            # configs.bridge_sweep.sim_metrics).
            profile["sim_metrics"] = {k: int(v.sum()) for k, v in mb.items()}
            # Behavior-coverage sketch over the same block: the host-side
            # twin of the device sweep's ledger (obs/coverage.py). Bridge
            # counters are per SLOT and cumulative across recycled seeds
            # (bridge/kernel.py BridgeMetrics), so this is per-slot
            # coverage — one fold of the block pulled above, no extra
            # device traffic.
            from ..obs.coverage import coverage_of_counters

            profile["coverage"] = coverage_of_counters(mb)
    return [o for o in drv.outcomes], drv.traces
