"""Host-side half of the host↔device bridge: lockstep sweep of W worlds.

``sweep(world_fn, seeds)`` runs one *unmodified* host-engine workload —
any coroutine written against the madsim_tpu API (Endpoint/RPC, gRPC
shims, sleep/timeout, kill/restart/clog chaos) — for many seeds at once:

- each seed gets a full host world (executor, nodes, coroutines, NetSim
  mailboxes) exactly like ``Runtime``; task bodies always run on host —
  the one thing that cannot be vectorized (SURVEY §7 "hard parts");
- the *decision kernel* — timer wheel, next-event selection, virtual
  clock advance, per-message loss/latency sampling — lives on the device
  as [W]-shaped arrays, advanced by one jitted XLA step per lockstep
  round (`bridge/kernel.py`).

Determinism contract: per seed, a bridge world walks the **bit-identical
trajectory** of a plain ``Runtime`` world (same poll sequence, same
virtual timestamps, same RNG streams) — the property tested in
tests/test_bridge.py. It holds because every framework draw is addressed
as (seed, purpose-stream, counter) (`core/rng.py`) and the device samples
the same counters with the same integer math.

Reference parity: this is the batched analog of the multi-seed test
driver (`madsim/src/sim/runtime/builder.rs:118-136`) — the reference
fans seeds out to OS threads; here the per-seed decision work fans into
one device batch while hosts bodies run under the GIL, and seed batches
shard across chips via the parallel/ meshes.
"""
from __future__ import annotations

import copy
import inspect
import os
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..core import context
from ..core.config import Config
from ..core.rng import GlobalRng, loss_threshold
from ..core.runtime import Runtime
from ..core.task import Deadlock, TimeLimitExceeded
from ..core.timewheel import NANOS_PER_SEC, TIMER_MAX_NS, TimeRuntime, to_ns
from ..net.addr import ip_is_loopback, unspecified_for
from ..net.netsim import NetSim
from ..net.network import LOCALHOST_V4
from .kernel import BridgeKernel, HostBatch, StepOut, bucket


class _TimerHandle:
    """Cancellation handle for a bridge timer (TimerEntry.cancel parity)."""

    __slots__ = ("_time", "seq")

    def __init__(self, time: "BridgeTime", seq: int):
        self._time = time
        self.seq = seq

    def cancel(self) -> None:
        self._time.cancel_seq(self.seq)


class _Send(NamedTuple):
    ctr: int       # NET-stream counter of the loss draw (latency = ctr+1)
    base_ns: int   # elapsed at the send
    slot: int      # delivery lane slot (-1 for count-only sends)
    seq: int
    thr: int       # loss threshold (u64, clamped)
    lossall: bool  # loss rate >= 1.0
    lat_lo: int
    lat_w: int
    live: bool     # has a destination socket


class BridgeTime(TimeRuntime):
    """TimeRuntime whose wheel lives on the device: ``add_timer_at`` and
    ``cancel`` record lane operations; the sweep driver ships them each
    lockstep round and dispatches the popped events. The clock is a local
    mirror (host advances it during polls; the driver overwrites it with
    the device's post-advance value)."""

    def __init__(self, rng: GlobalRng, cap: int):
        super().__init__(rng)
        self._native_heap = None  # the wheel is device-resident
        self.cap = cap
        self._free = list(range(cap - 1, -1, -1))
        # slot -> (deadline, seq) recorded but not yet shipped this round.
        self.pending_add: Dict[int, Tuple[int, int]] = {}
        self.cancels: List[int] = []          # device-resident cancels
        self.sends: List[_Send] = []
        self.send_cbs: List[Optional[Callable]] = []
        self.callbacks: Dict[int, Tuple[Callable, int]] = {}

    # -- the TimeRuntime surface ------------------------------------------
    def add_timer_at(self, deadline_ns: int, callback: Callable[[], None]):
        # Same clamp as the host wheel (timewheel.py): TIMER_MAX_NS is one
        # below the device kernel's empty-lane sentinel, so an over-range
        # timer stays visible to has_timer instead of reading as "no timer"
        # (which would report a spurious Deadlock the host never sees).
        deadline_ns = min(max(deadline_ns, self.elapsed_ns), TIMER_MAX_NS)
        seq = self._seq
        self._seq += 1
        slot = self._alloc()
        self.pending_add[slot] = (deadline_ns, seq)
        self.callbacks[seq] = (callback, slot)
        return _TimerHandle(self, seq)

    def cancel_seq(self, seq: int) -> None:
        ent = self.callbacks.pop(seq, None)
        if ent is None:
            return  # already fired or cancelled
        _cb, slot = ent
        pend = self.pending_add.get(slot)
        if pend is not None and pend[1] == seq:
            del self.pending_add[slot]  # never reached the device
        else:
            self.cancels.append(slot)
        self._free.append(slot)

    def next_deadline_ns(self):  # pragma: no cover — driver-owned
        raise NotImplementedError("bridge worlds are driven by sweep()")

    def advance_to_next_event(self):  # pragma: no cover — driver-owned
        raise NotImplementedError("bridge worlds are driven by sweep()")

    # -- bridge bookkeeping ------------------------------------------------
    def _alloc(self) -> int:
        try:
            return self._free.pop()
        except IndexError:
            raise RuntimeError(
                f"bridge timer capacity exceeded ({self.cap} concurrent "
                "timers in one world); raise sweep(cap=...)") from None

    def record_send(self, ctr: int, thr: int, lossall: bool, lat_lo: int,
                    lat_w: int, cb: Optional[Callable]) -> None:
        live = cb is not None
        if live:
            slot = self._alloc()
            seq = self._seq
            self._seq += 1
            self.callbacks[seq] = (cb, slot)
        else:
            slot, seq = -1, 0
        self.sends.append(_Send(ctr, self.elapsed_ns, slot, seq,
                                min(thr, (1 << 64) - 1), lossall,
                                lat_lo, lat_w, live))
        self.send_cbs.append(cb)

    def fire(self, seq: int) -> None:
        ent = self.callbacks.pop(seq, None)
        if ent is None:
            return
        cb, slot = ent
        self._free.append(slot)
        cb()

    def drop_send(self, send: _Send) -> None:
        """A live send the device declared lost: release its lane slot."""
        ent = self.callbacks.pop(send.seq, None)
        if ent is not None:
            self._free.append(ent[1])

    def take_round(self):
        adds = self.pending_add
        cancels = self.cancels
        sends = self.sends
        self.pending_add = {}
        self.cancels = []
        self.sends = []
        self.send_cbs = []
        return adds, cancels, sends


class BridgeNetSim(NetSim):
    """NetSim whose datagram sampling runs on the device.

    The send-side processing delay and the connection-oriented paths
    (connect1 relays, whose latency value is needed inline for their
    sleep) keep drawing host-side from the same NET cursor — counters
    stay aligned with pure-host mode either way, because both modes
    consume exactly the same blocks in the same order."""

    async def send(self, node_id, port, dst, protocol, msg) -> None:
        await self.rand_delay()
        net = self.network
        dst_node = net.resolve_dest_node(node_id, dst, protocol)
        if dst_node is None:
            return
        ctr = self.rand.reserve(2)  # loss @ctr, latency @ctr+1 — on device
        if net.link_clogged(node_id, dst_node):
            return  # draws consumed, like the host test_link
        sockets = net.nodes[dst_node].sockets
        socket = sockets.get((dst, protocol))
        if socket is None:
            socket = sockets.get(((unspecified_for(dst[0]), dst[1]), protocol))
        cfg = net.config
        lo_ns = to_ns(cfg.send_latency[0])
        width = max(to_ns(cfg.send_latency[1]), lo_ns + 1) - lo_ns
        p = cfg.packet_loss_rate
        if socket is None:
            cb = None  # loss draw still decides stat.msg_count
        else:
            src_ip = (LOCALHOST_V4 if ip_is_loopback(dst[0])
                      else net.nodes[node_id].ip)
            src = (src_ip, port)

            def cb(socket=socket, src=src, dst=dst, msg=msg):
                socket.deliver(src, dst, msg)

        self.time.record_send(ctr, loss_threshold(p), p >= 1.0,
                              lo_ns, width, cb)


class BridgeRuntime(Runtime):
    """Runtime wired for the bridge: device-backed time + NetSim."""

    def __init__(self, seed: int = 0, config: Optional[Config] = None,
                 cap: int = 128):
        self._cap = cap
        super().__init__(seed=seed, config=config)

    def _make_time(self) -> BridgeTime:
        return BridgeTime(self.rand, self._cap)

    def _default_simulators(self) -> tuple:
        from ..fs import FsSim

        return (BridgeNetSim, FsSim)

    def block_on(self, coro):  # pragma: no cover
        raise NotImplementedError("bridge worlds are driven by sweep()")


class Outcome(NamedTuple):
    """Per-seed sweep outcome: exactly what ``Runtime.block_on`` would
    have returned (value) or raised (error)."""

    seed: int
    value: Any
    error: Optional[BaseException]


class _World:
    __slots__ = ("idx", "slot", "rt", "root", "done", "stat")

    def __init__(self, idx: int, slot: int, rt: BridgeRuntime, root):
        self.idx = idx          # position in the seed list (outcome row)
        self.slot = slot        # kernel batch row currently hosting it
        self.rt = rt
        self.root = root
        self.done = False
        self.stat = rt.handle.sims.get(NetSim).network.stat


def sweep(world_fn: Callable, seeds, *, config: Optional[Config] = None,
          configs: Optional[List[Config]] = None, cap: int = 128,
          k_events: int = 4, time_limit: Optional[float] = None,
          trace: bool = False, device: Optional[str] = None,
          jobs: int = 1, batch: Optional[int] = None) -> List[Outcome]:
    """Sweep an unmodified host workload over many seeds with the device
    decision kernel (`builder.rs:118-136`, batched).

    ``world_fn`` is called once per seed (with the seed if it accepts an
    argument) and must return the root coroutine. ``configs`` gives each
    world its own Config — the (seeds × configs) sweep axis. With
    ``trace=True`` each world records (task_id, elapsed_ns) per poll for
    trajectory-equality checks.

    ``jobs`` shards seeds across forked worker processes, each running
    its own lockstep loop — the MADSIM_TEST_JOBS analog
    (`builder.rs:55-107`; the reference forks OS threads, which a GIL
    rules out for Python task bodies). Task bodies are CPU-bound Python,
    so jobs only helps up to the machine's core count; jobs=0 picks
    ``os.cpu_count()``.

    ``batch`` bounds how many worlds are live at once (world recycling,
    the host-side analog of ``parallel.sweep(recycle=True)``): seeds
    stream through ``batch`` kernel slots, each finished world's slot
    re-keyed (`BridgeKernel.reset_slot`) for the next seed. Memory and
    per-round pack width stay O(batch) however long the seed list, and
    every seed's trajectory stays bit-identical to an unbatched run
    (tests/test_bridge.py). The bound is per lockstep loop: with
    ``jobs>1`` each forked worker holds up to ``batch`` live worlds, so
    the process tree's total is O(jobs*batch). Default: all seeds at
    once."""
    if jobs == 0:
        # Host driver sizing its own fork pool — no simulation is live here.
        jobs = os.cpu_count() or 1  # detlint: allow[DET004]
    if jobs > 1 and len(seeds) > 1 and not _jax_initialized():
        # fork is only safe before this process touches a jax backend
        # (forked XLA clients deadlock); with jax already live, fall back
        # to the in-process loop.
        return _sweep_jobs(world_fn, seeds, jobs, config=config,
                           configs=configs, cap=cap, k_events=k_events,
                           time_limit=time_limit, device=device,
                           batch=batch)
    outcomes, _ = _sweep_impl(world_fn, seeds, config=config,
                              configs=configs, cap=cap, k_events=k_events,
                              time_limit=time_limit, trace=trace,
                              device=device, batch=batch)
    return outcomes


def _jax_initialized() -> bool:
    import sys

    xb = sys.modules.get("jax._src.xla_bridge")
    return bool(xb is not None and getattr(xb, "_backends", None))


def _sweep_jobs(world_fn, seeds, jobs, *, configs=None, **kw):
    """Fork one worker per seed shard; each runs its own kernel + loop.

    fork (not spawn) so ``world_fn`` closures carry over without
    pickling; outcomes return through pipes. Errors that cannot pickle
    are re-wrapped as RuntimeError with the original repr."""
    import pickle

    seeds = list(seeds)
    jobs = min(jobs, len(seeds))
    shards = [list(range(i, len(seeds), jobs)) for i in range(jobs)]
    pipes = []
    pids = []
    for shard in shards:
        r, w = os.pipe()
        pid = os.fork()
        if pid == 0:  # child
            os.close(r)
            try:
                sub_cfgs = ([configs[i] for i in shard]
                            if configs is not None else None)
                outs, _ = _sweep_impl(world_fn, [seeds[i] for i in shard],
                                      configs=sub_cfgs, **kw)
                payload = []
                for o in outs:
                    try:
                        pickle.dumps(o)
                        payload.append(o)
                    except Exception:
                        payload.append(Outcome(
                            o.seed, None,
                            RuntimeError(f"unpicklable outcome: {o!r}")))
                blob = pickle.dumps(payload)
            except BaseException as exc:  # noqa: BLE001
                blob = pickle.dumps(RuntimeError(
                    f"sweep worker failed: {exc!r}"))
            with os.fdopen(w, "wb") as f:
                f.write(blob)
            os._exit(0)
        os.close(w)
        pipes.append(r)
        pids.append(pid)
    outcomes: List[Optional[Outcome]] = [None] * len(seeds)
    for shard, r, pid in zip(shards, pipes, pids):
        with os.fdopen(r, "rb") as f:
            data = pickle.loads(f.read())
        os.waitpid(pid, 0)
        if isinstance(data, BaseException):
            raise data
        for idx, o in zip(shard, data):
            outcomes[idx] = o
    return outcomes


def sweep_traced(world_fn, seeds, **kw) -> Tuple[List[Outcome], List[list]]:
    """sweep() + per-seed poll traces (testing hook)."""
    return _sweep_impl(world_fn, seeds, trace=True, **kw)


def sweep_profiled(world_fn, seeds, **kw) -> Tuple[List[Outcome], dict]:
    """sweep() + a per-phase wall-time breakdown of the lockstep loop.

    The profile dict (all times in seconds) answers "where does a round
    go": ``host_s`` (Python task bodies + root settling), ``pack_s``
    (building the padded numpy batch), ``dispatch_s`` (the jitted kernel
    step, including device sync), ``settle_s`` (send accounting, event
    dispatch, drain rounds). ``rounds``/``drain_rounds`` count kernel
    dispatches; ``events``/``sends``/``timers`` are totals across worlds.
    This is the measured artifact behind docs/bridge.md.

    Profiled sweeps additionally run the kernel with its device-resident
    observability block (``BridgeMetrics``) and report the fleet
    aggregate under ``sim_metrics`` — trajectories stay bit-identical to
    an unprofiled sweep (tests/test_obs.py).
    """
    profile: dict = {}
    outs, _ = _sweep_impl(world_fn, seeds, profile=profile, **kw)
    return outs, profile


def _sweep_impl(world_fn, seeds, *, config=None, configs=None, cap=128,
                k_events=4, time_limit=None, trace=False, device=None,
                profile=None, batch=None):
    seeds = [int(s) for s in seeds]
    n = len(seeds)
    # World recycling: W kernel slots, n seeds streamed through them. A
    # finished world's slot is re-keyed for the next seed, so batch width
    # (and host memory) stays O(W) for arbitrarily long seed lists.
    W = n if batch is None else max(1, min(int(batch), n))
    wants_seed = len(inspect.signature(world_fn).parameters) >= 1
    outcomes: List[Optional[Outcome]] = [None] * n
    traces: List[list] = [[] for _ in range(n)]
    slots: List[Optional[_World]] = [None] * W
    free: List[int] = list(range(W - 1, -1, -1))  # pop() fills slot 0 first
    pending: set = set()            # slots holding a live world
    next_pos = 0                    # next seed position to admit
    polls_done = 0                  # poll_count of retired worlds

    # Profiled sweeps also carry the device-resident observability block
    # (BridgeMetrics): counters accumulate inside the jitted step and are
    # pulled ONCE at the end — bit-invisible to trajectories either way.
    kernel = BridgeKernel(seeds[:W], cap=cap, k_events=k_events,
                          device=device, metrics=profile is not None)

    def finish(w: _World, value=None, error=None):
        nonlocal polls_done
        outcomes[w.idx] = Outcome(seeds[w.idx], value, error)
        w.done = True
        pending.discard(w.slot)
        free.append(w.slot)
        polls_done += w.rt.task.poll_count

    def run_host(w: _World) -> None:
        """One host burst: run all ready tasks, then settle the root."""
        ex = w.rt.task
        with context.enter_handle(w.rt.handle):
            ex.run_all_ready()
        if ex._uncaught is not None:
            exc, ex._uncaught = ex._uncaught, None
            finish(w, error=exc)
        elif w.root.done:
            fut = w.root.join_future
            if fut._exception is not None:
                finish(w, error=fut._exception)
            else:
                finish(w, value=fut.result())

    def spawn(slot: int, pos: int) -> _World:
        if configs is not None:
            cfg = copy.deepcopy(configs[pos])
        else:
            cfg = copy.deepcopy(config) if config is not None else None
        rt = BridgeRuntime(seed=seeds[pos], config=cfg, cap=cap)
        if time_limit is not None:
            rt.set_time_limit(time_limit)
        if trace:
            rt.task.trace = traces[pos]
        with context.enter_handle(rt.handle):
            coro = world_fn(seeds[pos]) if wants_seed else world_fn()
            root = rt.task.start_root(coro)
        w = _World(pos, slot, rt, root)
        slots[slot] = w
        pending.add(slot)
        return w

    def top_up() -> None:
        """Admit seeds into free slots (runs between rounds only — a slot
        reset mid-round would let stale kernel rows fire into the fresh
        world's seq space)."""
        nonlocal next_pos
        blocked: List[int] = []
        while free and next_pos < n:
            slot = free.pop()
            old = slots[slot]
            if old is not None:
                t = old.rt.time
                if t.pending_add or t.sends or t.cancels:
                    # The retiring world's final host burst recorded
                    # activity that has not been shipped yet (its stats
                    # ride the next round's batch): recycle this slot one
                    # round later.
                    blocked.append(slot)
                    continue
                kernel.reset_slot(slot, seeds[next_pos])
            w = spawn(slot, next_pos)
            next_pos += 1
            run_host(w)
        free.extend(blocked)

    if profile is not None:
        from time import perf_counter

        profile.update(rounds=0, drain_rounds=0, host_s=0.0, pack_s=0.0,
                       dispatch_s=0.0, settle_s=0.0, events=0, sends=0,
                       timers=0, polls=0)

        def _clk():
            # Wall-clock profiling of the sweep driver itself (host side).
            return perf_counter()  # detlint: allow[DET001]
    else:
        def _clk():
            return 0.0

    t0 = _clk()
    top_up()
    if profile is not None:
        profile["host_s"] += _clk() - t0

    # Round buffers are preallocated per (T, C, S) bucket and reused:
    # fresh np.zeros for 18 arrays per round was a measured ~6% of sweep
    # wall time at W=512. Only the mask lanes (and the s_lat_w divisor)
    # need clearing on reuse — every value lane sits behind a mask the
    # kernel applies (stale values are jnp.where'd to the dump column).
    # Mutating after step() returns is safe: StepOut is materialized to
    # numpy before step returns, so the device is done with the inputs.
    buffers: Dict[Tuple[int, int, int], list] = {}

    def round_buffers(T, C, S):
        buf = buffers.get((T, C, S))
        if buf is None:
            buf = [np.zeros((W, T), np.int32), np.zeros((W, T), np.int64),
                   np.zeros((W, T), np.int64), np.zeros((W, T), np.bool_),
                   np.zeros((W, C), np.int32), np.zeros((W, C), np.bool_),
                   np.zeros((W, S), np.uint64), np.zeros((W, S), np.int64),
                   np.zeros((W, S), np.int32), np.zeros((W, S), np.int64),
                   np.zeros((W, S), np.uint64), np.zeros((W, S), np.bool_),
                   np.zeros((W, S), np.int64), np.ones((W, S), np.int64),
                   np.zeros((W, S), np.bool_), np.zeros((W, S), np.bool_),
                   np.zeros((W,), np.int64), np.zeros((W,), np.bool_)]
            buffers[(T, C, S)] = buf
        else:
            buf[3].fill(False)   # t_mask
            buf[5].fill(False)   # c_mask
            buf[13].fill(1)      # s_lat_w (divisor: must stay >= 1)
            buf[14].fill(False)  # s_mask
            buf[15].fill(False)  # s_live
        return buf

    while pending or next_pos < n:
        # -- build the padded round batch ---------------------------------
        t0 = _clk()
        rounds = []
        t_n = c_n = s_n = 0
        for w in slots:
            adds, cancels, sends = w.rt.time.take_round()
            rounds.append((adds, cancels, sends))
            t_n = max(t_n, len(adds))
            c_n = max(c_n, len(cancels))
            s_n = max(s_n, len(sends))
        T, C, S = bucket(t_n), bucket(c_n), bucket(s_n)
        (t_slot, t_dl, t_seq, t_mask, c_slot, c_mask,
         s_ctr, s_base, s_slot, s_seq, s_thr, s_lossall,
         s_lat_lo, s_lat_w, s_mask, s_live, clock, advance) = \
            round_buffers(T, C, S)
        for w, (adds, cancels, sends) in zip(slots, rounds):
            i = w.slot
            clock[i] = w.rt.time.elapsed_ns
            advance[i] = not w.done
            for j, (slot, (dl, sq)) in enumerate(adds.items()):
                t_slot[i, j] = slot
                t_dl[i, j] = dl
                t_seq[i, j] = sq
                t_mask[i, j] = True
            for j, slot in enumerate(cancels):
                c_slot[i, j] = slot
                c_mask[i, j] = True
            for j, s in enumerate(sends):
                s_ctr[i, j] = s.ctr
                s_base[i, j] = s.base_ns
                s_slot[i, j] = max(s.slot, 0)
                s_seq[i, j] = s.seq
                s_thr[i, j] = s.thr
                s_lossall[i, j] = s.lossall
                s_lat_lo[i, j] = s.lat_lo
                s_lat_w[i, j] = s.lat_w
                s_mask[i, j] = True
                s_live[i, j] = s.live

        if profile is not None:
            profile["pack_s"] += _clk() - t0
            profile["rounds"] += 1
            profile["timers"] += sum(len(r[0]) for r in rounds)
            profile["sends"] += sum(len(r[2]) for r in rounds)
        t0 = _clk()
        out = kernel.step(HostBatch(
            t_slot, t_dl, t_seq, t_mask, c_slot, c_mask,
            s_ctr, s_base, s_slot, s_seq, s_thr, s_lossall,
            s_lat_lo, s_lat_w, s_mask, s_live, clock, advance))
        if profile is not None:
            profile["dispatch_s"] += _clk() - t0

        # -- settle sends, dispatch events, detect stops ------------------
        t0 = _clk()
        woke: List[_World] = []
        for w, (adds, cancels, sends) in zip(slots, rounds):
            i = w.slot
            for j, s in enumerate(sends):
                if out.send_ok[i, j]:
                    w.stat.msg_count += 1
                elif s.live:
                    w.rt.time.drop_send(s)
            if w.done:
                continue
            w.rt.time.elapsed_ns = int(out.clock[i])
            if out.deadlock[i]:
                finish(w, error=Deadlock(
                    f"deadlock detected at t={w.rt.time.elapsed_ns / 1e9:.9f}s: "
                    "all tasks are blocked and no timers are pending"))
                continue
            lim = w.rt.task.time_limit_ns
            if lim is not None and w.rt.time.elapsed_ns >= lim:
                finish(w, error=TimeLimitExceeded(
                    f"time limit ({lim / NANOS_PER_SEC}s) exceeded"))
                continue
            fired = 0
            with context.enter_handle(w.rt.handle):
                for k in range(out.event_valid.shape[1]):
                    if not out.event_valid[i, k]:
                        break
                    w.rt.time.fire(int(out.event_seq[i, k]))
                    fired += 1
            if profile is not None:
                profile["events"] += fired
            if fired or out.more_due[i]:
                woke.append(w)

        # -- drain rounds: >K events due fire before any poll runs --------
        # Pop-only kernel + dispatch-ahead (docs/perf.md "Pipelined
        # orchestration"): a drain round's only input is the
        # device-resident kernel state, so round r+1 enters the device
        # queue BEFORE round r's popped events are unpacked and fired on
        # the host. The one speculative round at chain end finds nothing
        # due and pops nothing — a semantic no-op on the lanes.
        more = out.more_due
        inflight_drain = (kernel.drain()
                          if pending and np.any(more[list(pending)])
                          else None)
        while inflight_drain is not None:
            # Drain rounds carry no host batch: anything a fire() callback
            # recorded would silently miss its own due cluster and fire in
            # the wrong order vs the host heap. No framework callback does
            # that today — enforce it rather than assume it.
            for w in slots:
                if w.done or not more[w.slot]:
                    continue
                t = w.rt.time
                assert not (t.pending_add or t.sends or t.cancels), (
                    "bridge drain invariant violated: a fire() callback "
                    "recorded timers/sends during event dispatch")
            if profile is not None:
                profile["drain_rounds"] += 1
            cur = inflight_drain
            # Dispatch-ahead: queue the next round before materializing
            # this one's events (the device pops while the host fires).
            inflight_drain = kernel.drain()
            ev_valid = np.asarray(cur.event_valid)
            ev_seq = np.asarray(cur.event_seq)
            for w in slots:
                i = w.slot
                if w.done or not more[i]:
                    continue
                with context.enter_handle(w.rt.handle):
                    for k in range(ev_valid.shape[1]):
                        if not ev_valid[i, k]:
                            break
                        w.rt.time.fire(int(ev_seq[i, k]))
                        if profile is not None:
                            profile["events"] += 1
            more = np.asarray(cur.more_due)
            if not (pending and np.any(more[list(pending)])):
                break  # the in-flight round is the no-op tail

        if profile is not None:
            profile["settle_s"] += _clk() - t0
        t0 = _clk()
        for w in woke:
            if not w.done:
                run_host(w)
        top_up()  # recycle freed slots for the next seeds in the stream
        if profile is not None:
            profile["host_s"] += _clk() - t0
            profile["polls"] = polls_done + sum(
                w.rt.task.poll_count for w in slots if not w.done)

    if profile is not None:
        mb = kernel.metrics()
        if mb is not None:
            # Fleet aggregate of the kernel's per-slot counters
            # (docs/observability.md; bench.py records it under
            # configs.bridge_sweep.sim_metrics).
            profile["sim_metrics"] = {k: int(v.sum()) for k, v in mb.items()}
            # Behavior-coverage sketch over the same block: the host-side
            # twin of the device sweep's ledger (obs/coverage.py). Bridge
            # counters are per SLOT and cumulative across recycled seeds
            # (bridge/kernel.py BridgeMetrics), so this is per-slot
            # coverage — one fold of the block pulled above, no extra
            # device traffic.
            from ..obs.coverage import coverage_of_counters

            profile["coverage"] = coverage_of_counters(mb)
    return [o for o in outcomes], traces
